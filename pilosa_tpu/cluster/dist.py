"""Distributed query execution: fan-out over nodes, merge partials.

Reference: ``executor.go#mapReduce`` (SURVEY.md §4.2) — shards are
grouped by owning node; local shards execute on this node's TPU mesh as
one batched program, remote groups ship the sub-query as PQL text to
``POST /internal/query`` on the peer (the rebuild of
``InternalClient.QueryNode``), and partial results merge host-side.
Intra-node merging stays on-device; only the per-node partials (already
tiny: counts, id lists, pairs) merge here.

Key translation in cluster mode happens at the edge (this module):
inputs are translated before routing (so shard targets are known) via
the partition-owner nodes, outputs after merging — local executors run
with ``translate_output=False``.
"""

from __future__ import annotations

import os

import numpy as np

from pilosa_tpu import fault
from pilosa_tpu.exec import result_to_json
from pilosa_tpu.exec.executor import ExecutionError, WriteUnavailableError
from pilosa_tpu.pql import parse_cached
from pilosa_tpu.pql.ast import Call, Condition, Query

WRITE_CALLS = frozenset({"Set", "Clear", "ClearRow", "Store"})
# attrs are replicated everywhere (not sharded): broadcast writes
ATTR_CALLS = frozenset({"SetRowAttrs", "SetColumnAttrs"})

_MAX_U64 = (1 << 64) - 1


def _call_of(call: Call) -> Call:
    """Unwrap Options() to the effective call."""
    return call.children[0] if call.name == "Options" and call.children else call


def _transport_class(e: BaseException):
    """The transport-class failure behind a failed READ leg — the class
    that is safe and useful to retry on a replica — or None when the
    failure is fatal no matter which node answers: query errors (400),
    deadline expiry (``QueryTimeoutError`` — the budget is gone on
    every replica), and other HTTP statuses.  Counted as transport:

    - ``ClientError`` kinds ``unreachable``/``transport``/``timeout``
      (dead peer, connect refused/reset/timed out, TLS alert; a
      post-send timeout is retryable for READS because the internode
      query surface is idempotent by contract) — raw, or as the
      ``__cause__`` of ``internal_query``'s ``ExecutionError`` mapping;
    - a peer 503 (saturated or not-yet-clustered: route around it, as
      ``h_internal_query``'s shedding contract intends);
    - ``fault.FaultError`` (the ``dist.fanout`` ``error`` action — it
      stands in for a leg dying mid-flight).
    """
    from pilosa_tpu.api.client import ClientError
    from pilosa_tpu.exec.executor import QueryTimeoutError
    if isinstance(e, QueryTimeoutError):
        return None
    if isinstance(e, fault.FaultError):
        return e
    c = e if isinstance(e, ClientError) else None
    if c is None and isinstance(e, ExecutionError) \
            and isinstance(e.__cause__, ClientError):
        c = e.__cause__
    if c is None:
        return None
    if c.status == 503 or c.kind != "http":
        return c
    return None


def _nested_limit(call: Call, top: bool = True) -> bool:
    eff = _call_of(call) if top else call
    if eff.name == "Limit" and not top:
        return True
    if any(_nested_limit(c, False) for c in eff.children):
        return True
    return any(isinstance(v, Call) and _nested_limit(v, False)
               for v in eff.args.values())


def _strip_truncation(call: Call) -> Call:
    """Remove per-node truncation args (TopN n, Rows/GroupBy limit) from
    the fan-out sub-query — each node must return full partials or the
    merge is inexact (the reference needs a second query phase for the
    same reason, ``executeTopN`` SURVEY.md §4.3; here nodes return full
    count vectors instead)."""
    eff = _call_of(call)
    # GroupBy having= also strips: per-node partial counts/sums cannot
    # be thresholded locally; the filter applies to the global sums in
    # merge_results
    strip = {"TopN": ("n",), "Rows": ("limit",),
             "GroupBy": ("limit", "having"),
             "All": ("limit", "offset"), "Limit": ("limit", "offset")}
    keys = strip.get(eff.name) or ()
    extra = {}
    if eff.name == "TopN" and "tanimoto" in eff.args:
        # tanimoto is a RATIO: per-node thresholds don't merge.  Nodes
        # return intersection+row counts and |src| (``_rowCounts=1``);
        # the threshold applies on the global sums in merge_results.
        # Validate here — nodes never see the stripped arg, so the
        # single-node executor's range check would not run.
        thr = float(eff.args["tanimoto"])
        if not 0 < thr <= 100:
            raise ExecutionError("TopN: tanimoto must be in (0, 100]")
        keys = keys + ("tanimoto",)
        extra["_rowCounts"] = 1
    if extra or (keys and any(k in eff.args for k in keys)):
        eff = Call(eff.name,
                   {**{k: v for k, v in eff.args.items() if k not in keys},
                    **extra},
                   eff.children)
    if call.name == "Options":
        # the shards list was already resolved into per-node groups;
        # forwarding it would make each node re-apply the FULL list
        # over its replicas and additive merges would over-count
        args = {k: v for k, v in call.args.items() if k != "shards"}
        return Call("Options", args, [eff])
    return eff


class DistributedExecutor:
    """Same surface as :class:`pilosa_tpu.exec.Executor`.execute but
    JSON-valued, routing shards across the cluster."""

    def __init__(self, cluster):
        self.cluster = cluster  # Cluster (membership + clients + api)
        # the active request's tracer, visible to every nested _read /
        # _fanout_partials on this thread (the public surface threads
        # tracer only into execute_json)
        import threading
        self._tls = threading.local()

    # -- public -------------------------------------------------------------

    def execute_json(self, index: str, pql: str,
                     shards: list[int] | None = None, tracer=None,
                     deadline: float | None = None) -> list:
        """``deadline`` is checked between top-level calls, honored by
        the local partial execution inside each fan-out, and shipped to
        remote nodes as their remaining budget (re-anchored on the
        peer's monotonic clock; a peer's expiry comes back as 504 and
        re-raises as QueryTimeoutError here)."""
        import time as _time

        from contextlib import nullcontext

        from pilosa_tpu.exec.executor import QueryTimeoutError
        from pilosa_tpu.obs import LiteTracer
        query = parse_cached(pql)
        out = []
        calls = query.calls
        self._tls.tracer = tracer
        # lite-path queries build no spans, but a slow capture still
        # needs per-call attribution: record plain (name, seconds)
        # marks on the LiteTracer — the traced path gets the same data
        # from its cluster.* spans, so marking there would double it
        lite = isinstance(tracer, LiteTracer)
        try:
            i = 0
            while i < len(calls):
                if deadline is not None and _time.monotonic() > deadline:
                    raise QueryTimeoutError("query timeout exceeded")
                t_call = _time.perf_counter() if lite else 0.0
                call = calls[i]
                name = _call_of(call).name
                # consecutive plain reads fan out as ONE multi-call
                # query per node — a 32-Count batch costs (nodes-1)
                # RPCs, not 32*(nodes-1) (reference: executor.go runs
                # the whole query per shard in one mapReduce; per-call
                # fan-out was the r5 config12 finding, +80 ms/request
                # at 4 nodes)
                if self._batchable(call):
                    j = i
                    while j < len(calls) and self._batchable(calls[j]):
                        j += 1
                    batch = calls[i:j]
                    span = (nullcontext() if tracer is None
                            else tracer.span(
                                f"cluster.batch[{len(batch)}]",
                                index=index)
                            if len(batch) > 1
                            else tracer.span("cluster." + name,
                                             index=index))
                    with span:
                        if len(batch) == 1:
                            out.append(self._read(index, call, shards,
                                                  deadline=deadline))
                        else:
                            out.extend(self._read_group(
                                index, batch, shards, deadline=deadline))
                    if lite:
                        # mirror the traced path's span naming: a
                        # single-call batch is "cluster.<name>"
                        tracer.stage(
                            f"cluster.batch[{len(batch)}]"
                            if len(batch) > 1 else "cluster." + name,
                            _time.perf_counter() - t_call)
                    i = j
                    continue
                span = (tracer.span("cluster." + name, index=index)
                        if tracer is not None else nullcontext())
                with span:
                    if name in ATTR_CALLS:
                        out.append(self._attr_write(index, call))
                    elif name in WRITE_CALLS:
                        out.append(self._write(index, call))
                    elif name == "Percentile":
                        out.append(self._percentile(index, call, shards,
                                                    deadline=deadline))
                    else:
                        out.append(self._read(index, call, shards,
                                              deadline=deadline))
                if lite:
                    tracer.stage("cluster." + name,
                                 _time.perf_counter() - t_call)
                i += 1
        finally:
            self._tls.tracer = None
        return out

    @staticmethod
    def _batchable(call: Call) -> bool:
        """Reads with no shard override and no nested Limit share one
        fan-out; everything else keeps its own dispatch (writes for
        ordering, Options(shards)/nested-Limit for their rewrites)."""
        name = _call_of(call).name
        return (name not in WRITE_CALLS and name not in ATTR_CALLS
                and name != "Percentile" and call.name != "Options"
                and not _nested_limit(call))

    def _read_group(self, index: str, calls: list[Call],
                    shards: list[int] | None,
                    deadline: float | None = None) -> list:
        """Fan out several independent read calls as one query per node
        and merge each call's partials (the general-call sibling of
        ``_read_many``; local execution also engages the executor's
        whole-query count/aggregate fusion)."""
        calls = [self._translate_input(index, c) for c in calls]
        subs = [_strip_truncation(c) for c in calls]
        per_node = self._fanout_partials(index, subs, shards,
                                         deadline=deadline)
        out = []
        for k, call in enumerate(calls):
            eff = _call_of(call)
            merged = merge_results(eff, [pn[k] for pn in per_node])
            out.append(self._translate_output(index, eff, merged))
        return out

    # k-ary search fan-out width: one round ships K Counts per node in
    # ONE multi-call query (nodes fuse consecutive Counts into a single
    # program + read), so rounds = log_{K+1}(value range) instead of
    # log_2 — a 21-bit field resolves in ~6 fan-outs, not ~42
    PERCENTILE_FANOUT = 16

    def _percentile(self, index: str, call: Call, shards,
                    deadline: float | None = None):
        """Percentile cannot merge from per-node partials (a median of
        medians is not a median): run a k-ary search HERE with
        cluster-wide counts — each round one batched multi-Count
        fan-out over the normal query path."""
        import math
        # translate key inputs ONCE here — _read_many ships raw PQL to
        # peers without the per-call _read translation step
        call = self._translate_input(index, call)
        eff = _call_of(call)
        fname = eff.args.get("field") or eff.args.get("_field")
        nth = eff.args.get("nth")
        if fname is None or nth is None:
            raise ExecutionError("Percentile: field= and nth= required")
        nth = float(nth)
        if not 0 <= nth <= 100:
            raise ExecutionError("Percentile: nth must be in [0, 100]")
        idx = self.cluster.api.holder.index(index)
        field = idx.field(str(fname)) if idx else None
        if field is None:
            raise ExecutionError(f"field {fname!r} not found")
        base = field.options.base
        bound = (1 << field.options.bit_depth) - 1
        flt = eff.args.get("filter")
        children = [c for c in eff.children]

        def count_call(offset: int) -> Call:
            v = offset + base
            if field.options.type == "decimal":
                v = v / 10**field.options.scale
            row = Call("Row", {str(fname): Condition("<=", v)})
            tree = (Call("Intersect", {}, [row] + children +
                         ([flt] if isinstance(flt, Call) else []))
                    if (children or isinstance(flt, Call)) else row)
            return Call("Count", {}, [tree])

        def dist_counts(offsets: list[int]) -> list[int]:
            return self._read_many(index,
                                   [count_call(o) for o in offsets],
                                   shards, deadline=deadline)

        (total,) = dist_counts([bound])
        if total == 0:
            return {"value": 0, "count": 0}
        target = max(1, math.ceil(nth / 100.0 * total))
        k = self.PERCENTILE_FANOUT
        lo, hi = -bound, bound
        while lo < hi:
            if hi - lo <= k:
                cands = list(range(lo, hi))
            else:
                cands = sorted({lo + (hi - lo) * (j + 1) // (k + 1)
                                for j in range(k)})
            cnts = dist_counts(cands)
            prev = lo - 1
            nlo, nhi = None, hi
            for cand, c in zip(cands, cnts):
                if c >= target:
                    nlo, nhi = prev + 1, cand
                    break
                prev = cand
            if nlo is None:
                nlo = prev + 1
            lo, hi = nlo, nhi
        if lo > -bound:
            at, below = dist_counts([lo, lo - 1])
        else:
            (at,), below = dist_counts([lo]), 0
        return {"value": field.from_stored(lo + base), "count": at - below}

    def _fanout_partials(self, index: str, subs: list[Call], shards,
                         deadline: float | None = None) -> list[list]:
        """The one per-node fan-out: run ``subs`` locally over this
        node's shard group while peers execute the same multi-call
        query concurrently.  Returns one ``[per-call JSON partial]``
        list per participating node (the caller's merges are
        associative over disjoint shard sets, so a failed-over or
        hedged leg may legally come back as several entries).

        Availability (r11) — reads are idempotent by the internode
        contract, so a leg is never a single point of failure:

        - **replica failover**: a leg that dies with a transport-class
          error (:func:`_transport_class`) re-groups its shards by
          their next live replica — per shard, since replicas differ
          across partitions — and retries there, bounded by
          ``failover_max_depth`` hops and the query deadline.  Writes
          never take this path (``_write``/``_run_on`` keep their
          strict semantics).
        - **hedged requests**: when ``hedge_after`` > 0, a leg that
          exceeds it gets a duplicate issued to live replicas; the
          first complete answer wins and the loser is abandoned.  The
          winning subtree is grafted with a ``hedged`` trace tag.

        The pool is torn down on EVERY exit path with
        ``cancel_futures=True`` — failover and hedging multiply
        in-flight legs, and none may outlive the dispatch (queued legs
        are dropped; already-running stragglers finish into ignored
        futures and release their threads)."""
        import time as _time
        from concurrent.futures import (FIRST_COMPLETED,
                                        ThreadPoolExecutor, wait)

        from pilosa_tpu.exec.executor import QueryTimeoutError

        try:
            all_shards = (tuple(shards) if shards is not None
                          else self.cluster.index_shards(index,
                                                         strict=True))
        except RuntimeError as e:
            # an incomplete universe would silently undercount
            raise ExecutionError(str(e)) from e
        groups = self.cluster.group_shards_by_node(index, all_shards)
        pql = "\n".join(str(s) for s in subs)
        # span fan-in: capture the dispatching thread's open cluster.*
        # span HERE — remote legs run on pool threads where the
        # tracer's thread-local stack is empty — inject it as the
        # Traceparent every leg carries, and graft each peer's returned
        # subtree under it (to_json renders dict children verbatim)
        tracer = getattr(self._tls, "tracer", None)
        parent = tracer.current_span() if tracer is not None else None
        trace_headers = None
        if tracer is not None:
            # a LiteTracer has no open span but still injects its
            # trace IDENTITY (flags "00"): peers neither invent fresh
            # root spans nor churn their rings for a tree the
            # coordinator will never materialize
            trace_headers = {}
            tracer.inject(trace_headers, span=parent,
                          sampled=getattr(tracer, "sampled", True))
            if not trace_headers:
                trace_headers = None

        def remote(node_id, node_shards, tags=None):
            if fault.ACTIVE:
                # per-leg failpoint: `error` fails ONE node's share of
                # the fan-out (a remote leg dying mid-query), `delay`
                # models a straggler node without touching its process
                fault.fire("dist.fanout", peer=node_id, index=index)
            tr = ({"headers": trace_headers, **(tags or {})}
                  if trace_headers is not None else None)
            results = self.cluster.internal_query(
                node_id, index, pql, node_shards, deadline=deadline,
                trace=tr, map_unreachable=False)
            return results, tr

        def run_local(node_shards):
            # the local group executes on the DISPATCHING thread,
            # inside the open cluster.* span — its executor spans nest
            # there (also the failover target when a dead peer's shards
            # re-group onto this node)
            rs = self.cluster.api.executor.execute(
                index, Query(list(subs)), shards=list(node_shards),
                translate_output=False, deadline=deadline,
                tracer=tracer)
            return [result_to_json(r) for r in rs]

        def graft(tr) -> None:
            # graft on the DISPATCHING thread only, from collected
            # futures: a straggler leg abandoned by an earlier leg's
            # raise (or by losing its hedge race) must never mutate a
            # span tree that may already be closed, retained, and
            # served (its thread only ever touches its own `tr` dict)
            if tr is None or parent is None:
                return
            for sub in tr.get("profile") or []:
                tags = sub.setdefault("tags", {})
                for flag in ("retried", "hedged", "failover"):
                    # redelivered / hedge-winner / failed-over legs are
                    # visible in the profile: traces never lie under
                    # failure
                    if tr.get(flag):
                        tags[flag] = True
                parent.children.append(sub)

        cfg = self.cluster.cfg
        hedge_after = float(getattr(cfg, "hedge_after", 0.0) or 0.0)
        max_depth = int(getattr(cfg, "failover_max_depth", 2))
        stats = self.cluster.stats
        remote_items = [(n, s) for n, s in groups.items()
                        if n != self.cluster.node_id]
        per_node: list[list] = []
        pool = None

        def new_slot(node_id, node_shards, tried, depth, tags=None):
            return {"node": node_id, "shards": tuple(node_shards),
                    "primary": pool.submit(remote, node_id,
                                           tuple(node_shards), tags),
                    "tried": set(tried) | {node_id},
                    "depth": depth, "start": _time.monotonic(),
                    "hedge": None, "hedge_ok": [], "hedge_dead": False,
                    "settled": False}

        def settle(slot):
            slot["settled"] = True
            slots.remove(slot)

        def failover(slot, failed_node, err):
            """Re-group a transport-failed leg's shards onto their next
            live replicas (which may include THIS node) and retry."""
            stats.count("read_failover_total", 1, peer=failed_node)
            if deadline is not None and _time.monotonic() > deadline:
                raise QueryTimeoutError(
                    "query timeout exceeded during read failover") \
                    from err
            if slot["depth"] + 1 > max_depth:
                raise ExecutionError(
                    f"node {failed_node} unreachable and read failover "
                    f"exhausted after {max_depth} hops: {err}") from err
            try:
                regroups = self.cluster.group_shards_by_node(
                    index, slot["shards"], exclude=slot["tried"])
            except RuntimeError as e2:
                raise ExecutionError(
                    f"node {failed_node} unreachable: {err} (and no "
                    f"live replica remains: {e2})") from err
            for n2, s2 in regroups.items():
                if n2 == self.cluster.node_id:
                    per_node.append(run_local(s2))
                else:
                    slots.append(new_slot(n2, s2, slot["tried"],
                                          slot["depth"] + 1,
                                          tags={"failover": True}))

        def fire_hedges(now):
            for slot in slots:
                if (slot["hedge"] is not None or slot["primary"] is None
                        or now - slot["start"] < hedge_after):
                    continue
                slot["hedge"] = {}  # marks "hedge attempted" even if 0
                try:
                    # exclude every node that already failed this leg
                    # (tried includes the straggler): a failover leg
                    # must not hedge back onto the node that just died
                    regroups = self.cluster.group_shards_by_node(
                        index, slot["shards"], exclude=slot["tried"])
                except RuntimeError:
                    continue  # no live replica to hedge to
                if self.cluster.node_id in regroups:
                    # a self-targeted part would run synchronously on
                    # the dispatch thread and block the loop — let the
                    # straggler stand (failover still covers death)
                    continue
                stats.count("read_hedged_total", 1, peer=slot["node"])
                slot["hedge"] = {
                    pool.submit(remote, n2, s2, {"hedged": True}): n2
                    for n2, s2 in regroups.items()}

        slots: list[dict] = []
        try:
            if remote_items:
                # headroom beyond the original legs: failover and hedge
                # legs must not deadlock behind abandoned stragglers
                pool = ThreadPoolExecutor(
                    max_workers=2 * len(remote_items) + 2)
                for n, s in remote_items:
                    slots.append(new_slot(n, s, set(), 0))
            if self.cluster.node_id in groups:
                per_node.append(run_local(groups[self.cluster.node_id]))
            while slots:
                now = _time.monotonic()
                if hedge_after > 0:
                    fire_hedges(now)
                timeout = None
                if hedge_after > 0:
                    unhedged = [s["start"] + hedge_after for s in slots
                                if s["hedge"] is None
                                and s["primary"] is not None]
                    if unhedged:
                        timeout = max(0.0, min(unhedged) - now)
                futs = {}
                for slot in slots:
                    if slot["primary"] is not None:
                        futs[slot["primary"]] = slot
                    for hf in (slot["hedge"] or {}):
                        futs[hf] = slot
                done, _ = wait(list(futs), timeout=timeout,
                               return_when=FIRST_COMPLETED)
                for f in done:
                    slot = futs[f]
                    if slot["settled"]:
                        continue  # twin answered earlier this pass
                    is_hedge = bool(slot["hedge"]) and f in slot["hedge"]
                    try:
                        results, tr = f.result()
                    except Exception as e:  # noqa: BLE001 — classified
                        te = _transport_class(e)
                        if te is None:
                            if isinstance(e, QueryTimeoutError):
                                e.shards_outstanding = sum(
                                    len(s["shards"]) for s in slots
                                    if not s["settled"])
                            raise
                        if is_hedge:
                            failed = slot["hedge"].pop(f)
                            slot["tried"].add(failed)
                            if slot["primary"] is None:
                                # the primary already died; the hedge
                                # was the leg — fail over for real
                                settle(slot)
                                failover(slot, failed, te)
                            else:
                                # primary still in flight; the hedge
                                # set can no longer complete
                                slot["hedge_dead"] = True
                            continue
                        if slot["hedge"] and not slot["hedge_dead"]:
                            # primary died but a live hedge set covers
                            # the shards — let it race on
                            slot["primary"] = None
                            continue
                        settle(slot)
                        failover(slot, slot["node"], te)
                        continue
                    if is_hedge:
                        # pop FIRST: a completed future left in the
                        # hedge map would re-trigger wait() instantly
                        # and busy-spin the loop until the primary lands
                        node2 = slot["hedge"].pop(f)
                        if slot["hedge_dead"]:
                            continue  # abandoned set; primary decides
                        slot["hedge_ok"].append((results, tr, node2))
                        if slot["hedge"]:
                            continue  # parts still outstanding
                        # the full hedge set answered first: it wins;
                        # the primary straggler is abandoned (its
                        # result is never read or grafted)
                        settle(slot)
                        if slot["primary"] is not None:
                            slot["primary"].cancel()
                        for r2, t2, _n2 in slot["hedge_ok"]:
                            graft(t2)
                            per_node.append(r2)
                        continue
                    # primary answered: it wins; queued hedge parts are
                    # cancelled, running ones abandoned
                    settle(slot)
                    for hf in (slot["hedge"] or {}):
                        hf.cancel()
                    graft(tr)
                    per_node.append(results)
        finally:
            if pool is not None:
                pool.shutdown(wait=False, cancel_futures=True)
        return per_node

    def _read_many(self, index: str, calls: list[Call], shards,
                   deadline: float | None = None):
        """Fan out SEVERAL Count calls as one query per node (each node
        fuses the run into one program + read); returns merged ints."""
        per_node = self._fanout_partials(index, calls, shards,
                                         deadline=deadline)
        return [sum(node_counts[i] for node_counts in per_node)
                for i in range(len(calls))]

    def _resolve_nested_limits(self, index: str, call: Call, shards,
                               *, deadline: float | None = None) -> Call:
        """Rewrite non-top-level Limit subtrees into resolved ConstRow
        literals, bottom-up (inner Limits resolve first, so a Limit
        whose child contains another Limit also works)."""
        def resolve(node: Call) -> Call:
            kids = [resolve(c) for c in node.children]
            args = {k: (resolve(v) if isinstance(v, Call) else v)
                    for k, v in node.args.items()}
            node = Call(node.name, args, kids)
            if node.name == "Limit":
                cols = self._read(index, node, shards,
                                  deadline=deadline)
                return Call("ConstRow",
                            {"columns": (cols.get("columns")
                                         or cols.get("keys") or [])})
            return node

        eff = _call_of(call)
        # the top-level Limit itself stays (strip+merge handles it
        # exactly); only its/other calls' SUBTREES rewrite
        rebuilt = Call(eff.name,
                       {k: (resolve(v) if isinstance(v, Call) else v)
                        for k, v in eff.args.items()},
                       [resolve(c) for c in eff.children])
        if call.name == "Options" and call.children:
            return Call("Options", dict(call.args), [rebuilt])
        return rebuilt

    # -- reads --------------------------------------------------------------

    def _read(self, index: str, call: Call, shards: list[int] | None,
              deadline: float | None = None):
        if call.name == "Options" and call.args.get("shards") is not None:
            # apply the shard override BEFORE any rewrite that issues
            # its own distributed reads (Extract(Limit) / nested-Limit
            # resolution) — those must page over the restricted shard
            # set, exactly as the single-node executor scopes the tree
            shards = [int(s) for s in call.args["shards"]]
        if _nested_limit(call):
            # per-node Limit then merge is NOT global Limit: column
            # order crosses node boundaries.  Resolve EVERY nested
            # Limit subtree (Extract(Limit(...)) included) as its own
            # exact top-level distributed read (limit applied on the
            # globally merged ascending column list) and substitute the
            # result as a ConstRow literal — one extra fan-out round
            # per nested Limit, exactness preserved.
            call = self._resolve_nested_limits(index, call, shards,
                                               deadline=deadline)
        call = self._translate_input(index, call)
        if call.name == "Options" and call.args.get("shards") is not None:
            # Options(shards=[...]) overrides, as in single-node
            shards = [int(s) for s in call.args["shards"]]
        # remote groups fan out CONCURRENTLY (the reference runs one
        # goroutine per node, executor.go#mapReduce); the local group
        # executes on this thread while peers work
        per_node = self._fanout_partials(index, [_strip_truncation(call)],
                                         shards, deadline=deadline)
        merged = merge_results(_call_of(call),
                               [pn[0] for pn in per_node])
        return self._translate_output(index, _call_of(call), merged)

    # -- writes -------------------------------------------------------------

    def _write(self, index: str, call: Call):
        """Replicated write with durable hinted handoff (r13).

        Every write — strict (Clear/ClearRow/Store) or best-effort
        (Set) — keeps serving through a dead replica: the op applies
        on the write-reachable owners and is durably HINTED for the
        unreachable ones (appended to the crash-safe per-peer hint
        log; replayed in order on rejoin).  Owners known dead UP FRONT
        hint BEFORE the live applies run: a coordinator crash in
        between re-delivers (idempotently) rather than loses, and a
        torn hint append fails the op before anything mutated.  An
        owner that dies MID-APPLY necessarily hints after the
        surviving legs applied — a crash in that narrower window
        leaves an un-acked op partially applied with no hint, which
        AAE converges exactly like a pre-r13 best-effort miss (the
        at-least-once contract: un-acked ops may partially apply).

        Refusal (``WriteUnavailableError`` → 503 + Retry-After) is the
        bounded fallback, not the default: handoff disabled
        (``hint_max_age <= 0`` — the pre-r13 contract), a hinted
        peer's backlog past ``hint_max_age``, or no live replica left
        to apply the op right now."""
        from pilosa_tpu.engine.words import SHARD_WIDTH
        if (_call_of(call).name in ("Clear", "ClearRow", "Store")
                and self.cluster.state == "RESIZING"):
            # a clear routed to the OLD owners while their fragments
            # stream to the incoming topology would be resurrected the
            # moment the new placement activates; refuse loudly until
            # the resize lands (Set stays allowed — union-merge AAE
            # repairs additive divergence)
            raise ExecutionError(
                f"{_call_of(call).name} refused during cluster resize; "
                "retry when the cluster returns to NORMAL")
        # Set/Store create missing keys; Clear/ClearRow must not
        create = _call_of(call).name in ("Set", "Store")
        call = self._translate_input(index, call, create=create)
        eff = _call_of(call)
        hints = self.cluster.hints
        if eff.name in ("Set", "Clear"):
            shard = int(eff.args["_col"]) // SHARD_WIDTH
            owners = self.cluster.shard_owners(index, shard)
            if hints is None:
                # handoff disabled: the legacy contract — Set is
                # best-effort over reachable owners (AAE repairs a
                # dead replica on rejoin), Clear fail-fasts BEFORE any
                # replica applies (a copy missed by a down node would
                # be resurrected by union-merge AAE)
                if eff.name == "Clear":
                    dead = sorted(set(owners) - self._write_reachable())
                    if dead:
                        raise self._unavailable(eff.name, dead[0],
                                                "replica_down")
                results = self._run_on(index, call, owners, shards=None,
                                       best_effort=eff.name == "Set")
                return bool(results[0])
            targets, handed = self._split_write_targets(eff.name, owners)
            hinter = self._hinter(index, call, (shard,))
            for peer in handed:
                # hint FIRST (durable intent), then apply on the live
                # owners: a crash in between re-delivers — never loses
                hinter(peer)
            results = self._run_on(index, call, targets, shards=None,
                                   best_effort=eff.name == "Set",
                                   handoff=hinter)
            if not results:
                # every live target died mid-apply (each was hinted):
                # nothing applied NOW, the same state the up-front
                # split refuses as no_live_replica — acking would
                # claim otherwise.  The hints stay queued: the
                # un-acked op may still replay (at-least-once).
                raise self._unavailable(eff.name, targets[0],
                                        "no_live_replica")
            return bool(results[0])
        # ClearRow / Store touch every shard, and every REPLICA of each
        # shard must eventually apply them (a replica that missed a
        # clear would diverge and union-merge AAE would resurrect the
        # cleared bits cluster-wide).  The shard UNIVERSE itself must
        # be complete, or shards only the unreadable peer knows about
        # would miss the clear.  Down owners get the op hinted with
        # exactly their shard group; AAE defers those fragments until
        # the hints drain, so the ordering rule holds per shard.
        try:
            all_shards = self.cluster.index_shards(index, strict=True)
        except RuntimeError as e:
            raise ExecutionError(str(e)) from e
        groups: dict[str, list[int]] = {}
        for s in all_shards:
            for o in self.cluster.shard_owners(index, s):
                groups.setdefault(o, []).append(s)
        reachable = self._write_reachable()
        dead = sorted(set(groups) - reachable)
        if dead and hints is None:
            # legacy fail-fast BEFORE mutating anything: discovering a
            # dead owner mid-loop would leave the clear half-applied
            raise self._unavailable(eff.name, dead[0], "replica_down")
        if dead:
            # at least one REACHABLE owner per shard must apply the op
            # now — with every owner of a shard down there is no live
            # copy to serve reads from either, so refuse loudly
            for s in all_shards:
                owners_s = self.cluster.shard_owners(index, s)
                if not any(o in reachable for o in owners_s):
                    raise self._unavailable(eff.name, owners_s[0],
                                            "no_live_replica")
            for o in dead:
                if hints.overflowed(o):
                    raise self._unavailable(eff.name, o, "hint_overflow")
            for o in dead:
                self._hinter(index, call, groups[o])(o)
        live = {o: s for o, s in groups.items() if o not in dead}
        from concurrent.futures import ThreadPoolExecutor

        def leg(kv):
            o, shards_o = kv
            handoff = (self._hinter(index, call, shards_o)
                       if hints is not None else None)
            rs = self._run_on(index, call, [o], shards=tuple(shards_o),
                              handoff=handoff)
            # an answered leg may legitimately return a falsy result
            # (no bits changed), so "applied" is rs non-empty, not
            # rs[0] truthiness
            return o, (rs[0] if rs else False), bool(rs)

        with ThreadPoolExecutor(max_workers=len(live)) as pool:
            legs = list(pool.map(leg, live.items()))
        if hints is not None:
            # the up-front rule re-checked against what actually
            # happened: every shard needs at least one LIVE apply —
            # an owner that died mid-apply was hinted, and if it was
            # a shard's only reachable owner the op applied nowhere
            # live for that shard (ack would claim otherwise)
            applied_on = {o for o, _r, ok in legs if ok}
            for s in all_shards:
                owners_s = self.cluster.shard_owners(index, s)
                if not any(o in applied_on for o in owners_s):
                    raise self._unavailable(eff.name, owners_s[0],
                                            "no_live_replica")
        return any(bool(r) for _o, r, _ok in legs)

    @staticmethod
    def write_failure_class(e) -> str | None:
        """Classify a write leg's ClientError — the ONE copy of the
        rule the PQL write path (:meth:`_run_on`) and the bulk-import
        coordinator (``ingest.bulk``) share.  Only never-delivered
        failures mean ``"down"``: connection refused/reset, TLS
        handshake alerts ("transport" — the handshake precedes any
        request processing).  An answered 503 is an ALIVE peer that
        shed the request pre-execution (``"busy"``): it keeps serving
        reads, so hinting it would ack a strict op that a read on that
        replica then contradicts — busy legs never hand off.  None =
        propagate: a timeout is "state unknown" (the peer may still
        apply — a hinted replay could reorder behind a newer direct
        write), and any other 5xx from an alive peer is a real failed
        write, not AAE-repairable noise."""
        if e.status == 503 and "quarantined" in str(e):
            # a QUARANTINED-fragment refusal (r19; both shapes — the
            # internal-query shard gate and the fragment write gate's
            # storageFault — carry the word).  The busy-never-hints
            # rationale does not apply: a quarantined fragment serves
            # NO reads (routing skips it, peer legs 503 onto the
            # failover path), so a hinted strict op can never be
            # contradicted by a read on that replica — and repair +
            # ordered drain deliver it once the fragment is healthy.
            # Without this, one quarantined replica would refuse
            # strict writes for its shard cluster-wide for the whole
            # detect→repair window.
            return "down"
        if e.status == 503:
            return "busy"
        if e.status == 507:
            # the replica's DISK is out (r19 read-only degraded
            # serving): the node is alive, answered before mutating,
            # and will drain an ordered hint replay once its probe
            # restores healthy — exactly what handoff is for
            return "down"
        if e.status == 0 and e.kind != "timeout":
            return "down"
        return None

    def _write_reachable(self) -> set[str]:
        """The node set a write may target DIRECTLY: alive, breaker-
        closed, and — with handoff enabled — holding no pending hints.
        The breaker sees a dead peer within a few transport failures,
        seconds before the suspect horizon.  A peer with pending hints
        is not write-reachable even once alive again: new writes to it
        must append BEHIND the older hints (one ordered stream per
        peer) until the drain empties the log, or a replayed Clear
        could land after a newer direct Set and destroy it."""
        out = (set(self.cluster.alive_ids())
               - self.cluster.breakers.unhealthy_peers())
        hints = self.cluster.hints
        if hints is not None:
            out -= hints.pending_peers()
        return out

    def _split_write_targets(self, op: str, owners,
                             additive: bool | None = None
                             ) -> tuple[list[str], list[str]]:
        """(apply-now targets, hand-off peers) for one shard's owner
        set, refusing when the split cannot serve: no live replica at
        all, or a hand-off peer whose backlog overflowed
        ``hint_max_age`` (additive ops — Set, and r15 non-clearing
        bulk imports — fall back to the legacy best-effort miss there
        instead: AAE union-merge repairs additive divergence, so
        boundedness never costs them availability).  ``additive``
        defaults from the op name for the PQL write path."""
        hints = self.cluster.hints
        if additive is None:
            additive = op == "Set"
        reachable = self._write_reachable()
        targets = [o for o in owners if o in reachable]
        dead = [o for o in owners if o not in reachable]
        if not targets:
            raise self._unavailable(op, dead[0] if dead else None,
                                    "no_live_replica")
        handed = []
        for o in dead:
            if hints.overflowed(o):
                if additive:
                    self.cluster.stats.count("write_replicas_missed", 1)
                    self.cluster.logger.warning(
                        "%s not hinted for %s (backlog older than "
                        "hint_max_age=%gs); AAE repairs on rejoin",
                        op, o, hints.max_age)
                    continue
                raise self._unavailable(op, o, "hint_overflow")
            handed.append(o)
        return targets, handed

    def _unavailable(self, op: str, replica: str | None,
                     reason: str) -> WriteUnavailableError:
        """The structured refusal every write-unavailability path
        shares: the API edges map it to 503 + Retry-After with a body
        naming the down replica (mirrors the 504 timeout block)."""
        hints = self.cluster.hints
        if reason == "replica_down":
            msg = (f"replica {replica} unreachable for {op}: this op "
                   "requires every replica (a copy missed by a down "
                   "node would be resurrected by anti-entropy union "
                   "merge, and hinted handoff is disabled)")
        elif reason == "hint_overflow":
            msg = (f"replica {replica} unreachable for {op} and its "
                   f"hint backlog is older than hint_max_age="
                   f"{hints.max_age:g}s; refusing to diverge further "
                   "(drain or remove the node)")
        elif reason == "replica_busy":
            msg = (f"replica {replica} shed {op} (executor saturated): "
                   "the peer is alive and still serving reads, so "
                   "hinting would let this strict op ack while that "
                   "replica contradicts it — retry shortly")
        else:
            msg = (f"no live replica reachable for {op}"
                   + (f" (first unreachable: {replica})" if replica
                      else ""))
        retry = max(1.0, float(getattr(self.cluster.cfg,
                                       "heartbeat_interval", 1.0)))
        return WriteUnavailableError(msg, op=op, replica=replica,
                                     reason=reason, retry_after=retry)

    def _hint_record(self, index: str, call: Call, shards) -> dict:
        """One replayable hint: the already-translated PQL plus the
        routing facts (index/field/shards) AAE gating keys on, and a
        unique 128-bit op id the receiver dedups by."""
        eff = _call_of(call)
        return {"id": os.urandom(16).hex(), "index": index,
                "pql": str(call), "op": eff.name,
                "field": self._write_field(eff),
                "shards": (sorted(int(s) for s in shards)
                           if shards is not None else None)}

    def _hinter(self, index: str, call: Call, shards):
        """A hand-off callable for one op: durably hints ``call`` for
        a peer (used both pre-apply for known-dead owners and from
        ``_run_on`` when a target dies mid-apply)."""
        hints = self.cluster.hints

        def hand_off(node_id: str, err=None) -> None:
            hints.add(node_id, self._hint_record(index, call, shards))
            self.cluster.stats.count("hint_handoff_total", 1,
                                     peer=node_id)
            self.cluster.logger.info(
                "%s hinted for %s (replica down%s)",
                _call_of(call).name, node_id,
                f": {err}" if err is not None else "")

        return hand_off

    @staticmethod
    def _write_field(eff: Call) -> str | None:
        """The field a write call targets (the single non-reserved
        field arg — the same rule the translate walk uses), or None
        when indeterminable (gating then treats the hint as covering
        every field of the index: conservative, never unsound)."""
        from pilosa_tpu.exec.executor import reserved_for
        rk = reserved_for(eff.name)
        for k, v in eff.args.items():
            if (k in rk or k.startswith("_")
                    or isinstance(v, (Condition, Call))):
                continue
            return str(k)
        f = eff.args.get("_field")
        return str(f) if f is not None else None

    def _attr_write(self, index: str, call: Call):
        """SetRowAttrs/SetColumnAttrs apply on every member — attr
        stores are fully replicated.  Routed through the breaker-aware
        write-reachable set (r13 fix: this fanned out over
        ``alive_ids()`` ignoring breaker state, so a sick-but-not-yet-
        suspect peer ate a connect timeout on every attrs write);
        unreachable members are durably hinted when handoff is
        enabled, else left to attr AAE as before."""
        call = self._translate_input(index, call, create=True)
        hints = self.cluster.hints
        reachable = self._write_reachable()
        members = self.cluster.member_ids()
        targets = [n for n in members if n in reachable]
        rest = [n for n in members if n not in reachable]
        handoff = None
        if hints is not None:
            hinter = self._hinter(index, call, None)
            handoff = hinter
            for peer in rest:
                if not hints.overflowed(peer):
                    hinter(peer)
        elif rest:
            self.cluster.stats.count("write_replicas_missed", len(rest))
            self.cluster.logger.warning(
                "%s skipped %d unreachable member(s) %s (attr AAE "
                "repairs on rejoin)", _call_of(call).name, len(rest),
                rest)
        self._run_on(index, call, targets, shards=None, best_effort=True,
                     handoff=handoff)
        return None

    def _run_on(self, index: str, call: Call, node_ids, shards,
                best_effort: bool = False, handoff=None):
        """Execute one call on each named node (replica-synchronous for
        writes, replicas in parallel); returns the successful results,
        primary's first.

        ``best_effort``: an unreachable node (ClientError — dead or not
        yet past the suspect horizon) is skipped as long as at least
        one owner accepts; AAE repairs it on rejoin.  Execution errors
        (validation etc.) always propagate.  A socket TIMEOUT is not
        "unreachable": the peer saw the request and may still apply
        the write after we give up, so it propagates as a hard
        failure ("state unknown") on every path — skipping it would
        undercount a write that likely applied (ADVICE r4): a hinted
        replay of a maybe-applied op could land AFTER a newer direct
        write and reorder it, so only never-delivered failures hand
        off.

        ``handoff`` (r13): a callable ``(node_id, err)`` that durably
        hints the op for a target that died mid-apply (the "down"
        class only) — the failure is then handled, not raised, and the
        op keeps serving on the surviving results."""
        from pilosa_tpu.api.client import ClientError

        pql = str(call)

        def one(node_id):
            if node_id == self.cluster.node_id:
                rs = self.cluster.api.executor.execute(
                    index, Query([call]),
                    shards=list(shards) if shards else None,
                    translate_output=False)
                return result_to_json(rs[0])
            # map_unreachable=False: "down" classification below needs
            # the raw transport error; timeouts still arrive mapped as
            # ExecutionError("state unknown…") and propagate hard
            return self.cluster.internal_query(node_id, index, pql,
                                               shards,
                                               map_unreachable=False)[0]

        def guarded(node_id):
            try:
                return ("ok", one(node_id))
            except ClientError as e:
                tag = self.write_failure_class(e)
                if tag is None:
                    raise
                return (tag, (node_id, e))

        node_ids = list(node_ids)
        if len(node_ids) == 1:
            outs = [guarded(node_ids[0])]
        else:
            from concurrent.futures import ThreadPoolExecutor
            with ThreadPoolExecutor(max_workers=len(node_ids)) as pool:
                outs = list(pool.map(guarded, node_ids))
        oks = [r for tag, r in outs if tag == "ok"]
        downs = [r for tag, r in outs if tag == "down"]
        busys = [r for tag, r in outs if tag == "busy"]
        if downs and handoff is not None:
            # durable hinted handoff: targets that died mid-apply get
            # the op appended to their hint log (ordered replay on
            # rejoin) instead of failing or silently diverging
            for nid, err in downs:
                handoff(nid, err)
            downs = []
        if busys and not best_effort:
            # a saturated replica shed the op pre-execution: transient
            # unavailability, retryable — structured 503, never hinted
            nid, err = busys[0]
            raise self._unavailable(_call_of(call).name, nid,
                                    "replica_busy")
        downs += busys
        if downs and (not best_effort or not oks):
            nid, err = downs[0]
            raise ExecutionError(
                f"replica {nid} unreachable for {_call_of(call).name}: "
                f"{err}" + ("" if best_effort else
                            " (this op requires every replica: a copy "
                            "missed by a down node would be resurrected "
                            "by anti-entropy union merge)"))
        if downs:
            self.cluster.stats.count("write_replicas_missed", len(downs))
            self.cluster.logger.warning(
                "%s applied on %d/%d owners; missed %s (AAE repairs on "
                "rejoin)", _call_of(call).name, len(oks), len(node_ids),
                [nid for nid, _ in downs])
        return oks

    # -- key translation at the edge ---------------------------------------

    def _translate_input(self, index: str, call: Call,
                         create: bool = False) -> Call:
        """Replace string row/column keys with IDs (on a copy).  An
        unknown key on a read becomes ID 0 — key IDs start at 1, so the
        sub-row/column is empty, matching single-node semantics exactly
        (a missing key must not veto Not/Difference/Union siblings)."""
        idx = self.cluster.api.holder.index(index)
        if idx is None:
            raise ExecutionError(f"index {index!r} not found")

        def resolve(field: str | None, key: str) -> int:
            kid = self.cluster.translate_keys(index, field, [key],
                                              create=create)[0]
            return 0 if kid is None else kid

        def walk(c: Call) -> Call:
            new = Call(c.name, dict(c.args), [walk(ch) for ch in c.children])
            for k, v in list(new.args.items()):
                if isinstance(v, Call):
                    new.args[k] = walk(v)
            if isinstance(new.args.get("_col"), str):
                new.args["_col"] = resolve(None, new.args["_col"])
            if isinstance(new.args.get("_row"), str):
                fname = new.args.get("_field")
                f = idx.field(str(fname)) if fname else None
                if f is not None and f.options.keys:
                    new.args["_row"] = resolve(str(fname), new.args["_row"])
            if isinstance(new.args.get("column"), str):
                cid = self.cluster.translate_keys(
                    index, None, [new.args["column"]], create=False)[0]
                new.args["column"] = 0 if cid is None else cid
            # row key: the single non-reserved field arg (reservation
            # is per call — see executor.reserved_for).  Attr calls
            # never carry row keys in their kv args: an attr VALUE that
            # happens to share a keyed field's name must stay verbatim.
            if c.name in ("SetRowAttrs", "SetColumnAttrs"):
                return new
            from pilosa_tpu.exec.executor import reserved_for
            rk = reserved_for(c.name)
            for k, v in list(new.args.items()):
                if (k in rk or k.startswith("_")
                        or isinstance(v, (Condition, Call))):
                    continue
                field = idx.field(k)
                if field is not None and field.options.keys \
                        and isinstance(v, str):
                    new.args[k] = resolve(k, v)
            prev = new.args.get("previous")
            if isinstance(prev, str):
                fname = new.args.get("_field") or new.args.get("field")
                rid = self.cluster.translate_keys(
                    index, str(fname), [prev], create=False)[0]
                new.args["previous"] = rid if rid is not None else _MAX_U64
            return new

        return walk(call)

    def _translate_extract(self, index: str, idx, merged):
        """Edge translation for merged Extract results: column ids →
        keys (keyed index), keyed fields' row values → keys."""
        if idx.keys:
            ids = [c.pop("column") for c in merged["columns"]]
            for c, k in zip(merged["columns"],
                            self.cluster.keys_of(index, None, ids)):
                c["key"] = k
        for fi, spec in enumerate(merged.get("fields", [])):
            f = idx.field(spec["name"])
            if f is None or not f.options.keys:
                continue
            for c in merged["columns"]:
                v = c["rows"][fi]
                if isinstance(v, list):
                    c["rows"][fi] = self.cluster.keys_of(
                        index, spec["name"], v)
                elif v is not None and not isinstance(v, bool):
                    c["rows"][fi] = self.cluster.keys_of(
                        index, spec["name"], [v])[0]
        return merged

    def _translate_output(self, index: str, call: Call, merged):
        idx = self.cluster.api.holder.index(index)
        if merged is None or idx is None:
            return merged
        if isinstance(merged, dict) and call.name == "Extract" \
                and "columns" in merged:
            return self._translate_extract(index, idx, merged)
        if isinstance(merged, dict) and "columns" in merged and idx.keys:
            keys = self.cluster.keys_of(index, None, merged["columns"])
            out = {"keys": keys}
            if merged.get("rowAttrs"):  # carried through key translation
                out["rowAttrs"] = merged["rowAttrs"]
            if merged.get("attrs"):
                # column-attr maps re-key from column ids to column keys
                # (the id axis is gone from a keyed response)
                id_to_key = {str(c): k for c, k in
                             zip(merged["columns"], keys)}
                out["attrs"] = {id_to_key.get(i, i): a
                                for i, a in merged["attrs"].items()}
            return out
        fname = call.args.get("_field") or call.args.get("field")
        field = idx.field(str(fname)) if fname else None
        keyed_field = field is not None and field.options.keys
        if isinstance(merged, list) and keyed_field:  # TopN pairs
            ids = [p["id"] for p in merged]
            keys = self.cluster.keys_of(index, str(fname), ids)
            return [{"key": k, "count": p["count"]}
                    for k, p in zip(keys, merged)]
        if isinstance(merged, dict) and "rows" in merged and keyed_field:
            keys = self.cluster.keys_of(index, str(fname), merged["rows"])
            return {"keys": keys}
        if isinstance(merged, list) and call.name == "GroupBy":
            for g in merged:
                for fr in g["group"]:
                    f = idx.field(fr["field"])
                    if f is not None and f.options.keys and "rowID" in fr:
                        fr["rowKey"] = self.cluster.keys_of(
                            index, fr["field"], [fr.pop("rowID")])[0]
        return merged



# ---------------------------------------------------------------------------
# partial-result merging (reference: the reduce fns in executor.go)
# ---------------------------------------------------------------------------


def merge_results(call: Call, partials: list):
    if not partials:
        return None
    name = call.name
    if name == "Count":
        return sum(partials)
    if name in WRITE_CALLS or name == "IncludesColumn":
        return any(partials)
    if name in ("Row", "Range", "Intersect", "Union", "Difference", "Xor",
                "Not", "All", "Shift", "UnionRows", "ConstRow", "Limit"):
        cols = np.unique(np.concatenate(
            [np.asarray(p.get("columns", []), dtype=np.uint64)
             for p in partials]))
        if name in ("All", "Limit"):
            # paging applies to the MERGED list (per-node paging was
            # stripped from the fan-out)
            offset = int(call.args.get("offset", 0))
            limit = call.args.get("limit")
            end = None if limit is None else offset + int(limit)
            cols = cols[offset:end]
        out = {"columns": [int(c) for c in cols]}
        for p in partials:  # row attrs are replicated — any node's copy
            if p.get("rowAttrs"):
                out["rowAttrs"] = p["rowAttrs"]
                break
        # column attrs (Options columnAttrs=true): each node annotates
        # its own columns; the merged map is their union
        attr_maps = [p["attrs"] for p in partials if p.get("attrs")]
        if attr_maps:
            out["attrs"] = {k: v for m in attr_maps for k, v in m.items()}
        return out
    if name == "Extract":
        from pilosa_tpu.exec.executor import Executor
        fields = partials[0].get("fields", []) if partials else []
        cols = [c for p in partials for c in p.get("columns", [])]
        if len(cols) > Executor.MAX_EXTRACT_COLUMNS:
            # per-node caps pass individually; the merged result must
            # honor the same memory bound
            raise ExecutionError(
                f"Extract: {len(cols)} columns across the cluster; cap "
                f"is {Executor.MAX_EXTRACT_COLUMNS} — narrow the filter "
                "or use Limit as Extract's filter")
        cols.sort(key=lambda c: c.get("column", 0))
        return {"fields": fields, "columns": cols}
    if name == "TopN":
        counts: dict[int, int] = {}
        if partials and isinstance(partials[0], dict) and "pairs" in partials[0]:
            # tanimoto partials: sum intersection counts, row counts and
            # |src| across nodes, then threshold on the GLOBAL ratio
            row_counts: dict[int, int] = {}
            src = 0
            for p in partials:
                src += int(p.get("srcCount", 0))
                for pair in p["pairs"]:
                    i = pair["id"]
                    counts[i] = counts.get(i, 0) + pair["count"]
                    row_counts[i] = (row_counts.get(i, 0)
                                     + pair.get("rowCount", 0))
            thr = float(call.args.get("tanimoto", 0))
            pairs = sorted(
                ((i, c) for i, c in counts.items()
                 if c > 0 and 100.0 * c >= thr * (src + row_counts[i] - c)),
                key=lambda kv: (-kv[1], kv[0]))
        else:
            for p in partials:
                for pair in p:
                    counts[pair["id"]] = (counts.get(pair["id"], 0)
                                          + pair["count"])
            pairs = sorted(counts.items(), key=lambda kv: (-kv[1], kv[0]))
        n = call.args.get("n")
        if n is not None:
            pairs = pairs[: int(n)]
        return [{"id": i, "count": c} for i, c in pairs]
    if name == "Sum":
        return {"value": sum(p["value"] for p in partials),
                "count": sum(p["count"] for p in partials)}
    if name in ("Min", "Max"):
        live = [p for p in partials if p["count"] > 0]
        if not live:
            return {"value": 0, "count": 0}
        best = (min if name == "Min" else max)(p["value"] for p in live)
        return {"value": best,
                "count": sum(p["count"] for p in live
                             if p["value"] == best)}
    if name == "Distinct":
        vals = sorted({v for p in partials for v in p.get("values", [])})
        return {"values": vals}
    if name == "Rows":
        rows = np.unique(np.concatenate(
            [np.asarray(p.get("rows", []), dtype=np.uint64)
             for p in partials]))
        limit = call.args.get("limit")
        if limit is not None:
            rows = rows[: int(limit)]
        return {"rows": [int(r) for r in rows]}
    if name == "GroupBy":
        return _merge_groupby(call, partials)
    raise ExecutionError(f"cannot merge results for call {name!r}")


# safe margin for int64 aggregate accumulation across nodes: past this,
# fall back to exact Python big-int merging (matches the executor's
# Sum host-finish policy)
_AGG_I64_BOUND = 1 << 60


def _merge_groupby(call: Call, partials: list):
    """GroupBy partial merge, vectorized (reference: the per-group map
    merge in ``executor.go#executeGroupBy`` reduce fn).

    Fast path: all group members carry numeric rowIDs and aggregates fit
    int64 — key matrix ``np.unique(axis=0)`` + ``ufunc.at`` reductions,
    no per-group dict churn (the dict merge was ~40% of a 125k-group
    distributed GroupBy).  Keyed rows or big-int aggregates take the
    exact dict path.
    """
    agg_call = call.args.get("aggregate")
    agg_op = agg_call.name if isinstance(agg_call, Call) else None
    flat = [g for p in partials for g in p]
    if not flat:
        groups = []
    else:
        fast = all("rowID" in fr for g in flat for fr in g["group"])
        if fast:
            n_nodes = len(partials)
            fast = all(
                g.get("agg") is None
                or abs(g["agg"]) * n_nodes < _AGG_I64_BOUND
                for g in flat)
        groups = (_merge_groupby_fast(flat, agg_op) if fast
                  else _merge_groupby_dicts(flat, agg_op))
    having = call.args.get("having")
    if having is not None:
        from pilosa_tpu.exec.executor import Executor
        metric, cond = Executor.parse_having(having, agg_op)
        groups = [g for g in groups
                  if (g["count"] if metric == "count"
                      else g.get("agg")) is not None
                  and cond.matches(g["count"] if metric == "count"
                                   else g["agg"])]
    limit = call.args.get("limit")
    if limit is not None:
        groups = groups[: int(limit)]
    return groups


def _merge_groupby_fast(flat: list, agg_op):
    fields = [fr["field"] for fr in flat[0]["group"]]
    rows = np.array([[fr["rowID"] for fr in g["group"]] for g in flat],
                    np.uint64).reshape(len(flat), len(fields))
    counts = np.array([g["count"] for g in flat], np.int64)
    # np.unique(axis=0) sorts lexicographically by level — the same
    # rowID ordering the reference returns
    uniq, inv = np.unique(rows, axis=0, return_inverse=True)
    inv = inv.ravel()
    n = len(uniq)
    mcounts = np.zeros(n, np.int64)
    np.add.at(mcounts, inv, counts)
    agg_vals = [g.get("agg") for g in flat]
    maggs = amask = None
    if any(a is not None for a in agg_vals):
        present = np.array([a is not None for a in agg_vals], bool)
        vals = np.array([0 if a is None else a for a in agg_vals],
                        np.int64)
        amask = np.zeros(n, bool)
        amask[inv[present]] = True
        if agg_op == "Min":
            maggs = np.full(n, np.iinfo(np.int64).max)
            np.minimum.at(maggs, inv[present], vals[present])
        elif agg_op == "Max":
            maggs = np.full(n, np.iinfo(np.int64).min)
            np.maximum.at(maggs, inv[present], vals[present])
        else:
            maggs = np.zeros(n, np.int64)
            np.add.at(maggs, inv[present], vals[present])
    out = []
    key_rows = uniq.tolist()
    for i, (krow, count) in enumerate(zip(key_rows, mcounts.tolist())):
        g = {"group": [{"field": f, "rowID": r}
                       for f, r in zip(fields, krow)],
             "count": count}
        if maggs is not None and amask[i]:
            g["agg"] = int(maggs[i])
        out.append(g)
    return out


def _merge_groupby_dicts(flat: list, agg_op):
    """Exact fallback: keyed rows and/or arbitrary-precision aggs."""
    merged: dict[tuple, dict] = {}
    for g in flat:
        key = tuple((fr["field"], fr.get("rowID", fr.get("rowKey")))
                    for fr in g["group"])
        hit = merged.get(key)
        if hit is None:
            merged[key] = dict(g)
        else:
            hit["count"] += g["count"]
            if g.get("agg") is not None:
                if hit.get("agg") is None:
                    hit["agg"] = g["agg"]
                elif agg_op == "Min":
                    hit["agg"] = min(hit["agg"], g["agg"])
                elif agg_op == "Max":
                    hit["agg"] = max(hit["agg"], g["agg"])
                else:
                    hit["agg"] = hit["agg"] + g["agg"]
    return sorted(merged.values(),
                  key=lambda g: [fr.get("rowID", 0)
                                 for fr in g["group"]])
