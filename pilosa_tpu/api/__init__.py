"""API surface (L5 of SURVEY.md §2): programmatic façade, REST server,
HTTP client."""

from pilosa_tpu.api.api import API, ApiError, field_options_from_json
from pilosa_tpu.api.client import Client, ClientError
from pilosa_tpu.api.server import Server

__all__ = ["API", "ApiError", "Server", "Client", "ClientError",
           "field_options_from_json"]
