"""gRPC query/ingest surface (reference: the v2-era gRPC server,
``grpc.go`` + ``proto/`` — SURVEY.md §3.3).

Service ``pilosa_tpu.Pilosa`` with unary rpcs:

    Query(QueryRequest) -> QueryResponse
    Import(ImportRequest) -> ImportResponse
    ImportValue(ImportValueRequest) -> ImportResponse

Messages are the ones in ``api/internal.proto`` (QueryRequest.index
carries the index name — there is no URL path here), encoded by the
project's dependency-free codec (``api/proto.py``).  The server uses
grpcio's *generic method handlers* over raw bytes, so no
protoc/grpc_tools codegen exists at build or run time; any client
generated from internal.proto interoperates, and Python callers can use
``channel.unary_unary`` with the same codec (see tests/test_grpc.py).

Application errors arrive as ``QueryResponse.err`` /
``ImportResponse.err`` with gRPC status OK — a non-OK unary status
would drop the response message, and the err field is the schema's
error contract (matching the HTTP proto surface's decodable bodies).
"""

from __future__ import annotations

from pilosa_tpu.api import proto
from pilosa_tpu.api.api import API, ApiError

SERVICE = "pilosa_tpu.Pilosa"


class GrpcServer:
    def __init__(self, api: API, host: str = "127.0.0.1", port: int = 0,
                 max_workers: int = 8, credentials=None):
        import grpc
        from concurrent import futures

        self.api = api
        self._server = grpc.server(
            futures.ThreadPoolExecutor(max_workers=max_workers))
        rpcs = {
            "Query": grpc.unary_unary_rpc_method_handler(self._query),
            "Import": grpc.unary_unary_rpc_method_handler(self._import),
            "ImportValue": grpc.unary_unary_rpc_method_handler(
                self._import_value),
        }
        self._server.add_generic_rpc_handlers(
            (grpc.method_handlers_generic_handler(SERVICE, rpcs),))
        if credentials is not None:
            # same tls block as the REST surface (api.tls.
            # grpc_server_credentials)
            self.port = self._server.add_secure_port(
                f"{host}:{port}", credentials)
        else:
            self.port = self._server.add_insecure_port(f"{host}:{port}")

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "GrpcServer":
        self._server.start()
        return self

    def close(self, grace: float = 0.5) -> None:
        self._server.stop(grace)

    # -- rpcs (raw request bytes -> raw response bytes) ----------------------

    def _query(self, request: bytes, context) -> bytes:
        try:
            pql, shards, index = proto.decode_query_request_indexed(request)
        except ValueError as e:
            return proto.encode_query_response(err=f"bad request: {e}")
        if not index:
            return proto.encode_query_response(err="missing index")
        try:
            res = self.api.query(index, pql, shards=shards)
            return proto.encode_query_response(res["results"])
        except (ApiError, ValueError) as e:
            return proto.encode_query_response(err=str(e))

    def _import(self, request: bytes, context) -> bytes:
        try:
            b = proto.decode_import_request(request)
        except ValueError as e:
            return proto.encode_import_response(err=f"bad request: {e}")
        if not b["index"] or not b["field"]:
            return proto.encode_import_response(err="missing index/field")
        try:
            changed = self.api.import_bits(
                b["index"], b["field"], row_ids=b["row_ids"],
                col_ids=b["col_ids"], row_keys=b["row_keys"],
                col_keys=b["col_keys"], timestamps=b["timestamps"],
                clear=b["clear"])
            return proto.encode_import_response(changed)
        except ApiError as e:
            return proto.encode_import_response(err=str(e))

    def _import_value(self, request: bytes, context) -> bytes:
        try:
            b = proto.decode_import_value_request(request)
        except ValueError as e:
            return proto.encode_import_response(err=f"bad request: {e}")
        if not b["index"] or not b["field"]:
            return proto.encode_import_response(err="missing index/field")
        try:
            changed = self.api.import_values(
                b["index"], b["field"], col_ids=b["col_ids"],
                col_keys=b["col_keys"], values=b["values"])
            return proto.encode_import_response(changed)
        except ApiError as e:
            return proto.encode_import_response(err=str(e))
