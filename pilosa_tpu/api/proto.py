"""Protobuf wire codec for the query endpoint — zero dependencies.

Implements exactly the messages in ``internal.proto`` (this project's
own schema; the upstream ``internal/internal.proto`` was unavailable to
copy — see that file's header) with a hand-rolled proto3 wire format:
varints, zigzag sint64, 64-bit doubles, length-delimited submessages,
packed repeated scalars.  ~150 lines beats shipping generated code that
version-locks to a protoc/runtime pair (reference:
``http/handler.go`` content negotiation, SURVEY.md §3.3).

Encoding maps the JSON result shapes produced by
``pilosa_tpu.exec.result_to_json`` — the negotiation layer converts at
the HTTP edge, so executors/cluster merge logic stay JSON-native.
"""

from __future__ import annotations

import struct

# wire types
_VARINT, _I64, _LEN = 0, 1, 2

CONTENT_TYPE = "application/x-protobuf"


def _malformed_as_value_error(fn):
    """Decoders promise ValueError on ANY malformed input (the HTTP and
    gRPC layers translate that into decodable 400s / .err responses) —
    but the raw parsing raises struct.error on short fixed-width
    payloads, AttributeError on wire-type confusion (int where bytes
    expected), and UnicodeDecodeError on bad UTF-8."""
    import functools
    import struct as _struct

    @functools.wraps(fn)
    def wrapped(buf):
        try:
            return fn(buf)
        except (ValueError, _struct.error, AttributeError,
                UnicodeDecodeError, TypeError, IndexError) as e:
            if type(e) is ValueError:
                raise
            raise ValueError(f"proto: malformed message: {e}")
    return wrapped


# -- primitives --------------------------------------------------------------


def _varint(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _zigzag(n: int) -> int:
    return (n << 1) ^ (n >> 63) if n < 0 else n << 1


def _unzigzag(n: int) -> int:
    return (n >> 1) ^ -(n & 1)


def _tag(field: int, wire: int) -> bytes:
    return _varint(field << 3 | wire)


def _uint(field: int, n: int) -> bytes:
    return _tag(field, _VARINT) + _varint(int(n)) if n else b""


def _string(field: int, s: str) -> bytes:
    if not s:
        return b""
    raw = s.encode()
    return _tag(field, _LEN) + _varint(len(raw)) + raw


def _sub(field: int, raw: bytes) -> bytes:
    return _tag(field, _LEN) + _varint(len(raw)) + raw


def _vec_varints(values) -> bytes:
    """Packed varint body for a uint64 array, vectorized: per-value
    byte lengths by comparison ladder, then one numpy pass per varint
    byte position.  A Python per-int loop measured 7× slower than
    C-json on 100k-id import batches — the packed arrays ARE the wire,
    so this is the codec's hot path."""
    import numpy as _np
    try:
        v = _np.asarray(values, dtype=_np.uint64)
    except OverflowError as e:
        raise ValueError(f"proto: value out of uint64 range: {e}")
    lens = _np.ones(len(v), _np.int64)
    for g in range(1, 10):
        lens += (v >= (_np.uint64(1) << _np.uint64(7 * g)))
    offs = _np.cumsum(lens) - lens
    out = _np.zeros(int(lens.sum()), _np.uint8)
    for g in range(10):
        m = lens > g
        if not m.any():
            break
        byte = ((v[m] >> _np.uint64(7 * g))
                & _np.uint64(0x7F)).astype(_np.uint8)
        out[offs[m] + g] = byte | _np.where(lens[m] > g + 1, 0x80,
                                            0).astype(_np.uint8)
    return out.tobytes()


def _vec_zigzag(values):
    """int64 list/array -> zigzagged uint64 array (vectorized).
    Out-of-int64 inputs raise ValueError (not numpy's OverflowError),
    so callers' fall-back-to-JSON handling fires."""
    import numpy as _np
    try:
        v = _np.asarray(values, dtype=_np.int64)
    except OverflowError as e:
        raise ValueError(f"proto: value out of sint64 range: {e}")
    return ((v << 1) ^ (v >> 63)).view(_np.uint64)


def _packed(field: int, values, enc) -> bytes:
    if not len(values):
        return b""
    if enc is _varint:
        raw = _vec_varints(values)
    else:
        raw = b"".join(enc(int(v)) for v in values)
    return _tag(field, _LEN) + _varint(len(raw)) + raw


def _double(field: int, v: float) -> bytes:
    return _tag(field, _I64) + struct.pack("<d", v)


class _Reader:
    def __init__(self, buf: bytes):
        self.buf, self.pos = buf, 0

    def varint(self) -> int:
        n = shift = 0
        while True:
            if self.pos >= len(self.buf):
                raise ValueError("proto: truncated varint")
            b = self.buf[self.pos]
            self.pos += 1
            n |= (b & 0x7F) << shift
            if not b & 0x80:
                return n
            shift += 7
            if shift > 70:
                raise ValueError("proto: varint too long")

    def fields(self):
        """Yield (field, wire, value) — value is int for varint, bytes
        for length-delimited, 8 raw bytes for i64."""
        while self.pos < len(self.buf):
            key = self.varint()
            field, wire = key >> 3, key & 7
            if wire == _VARINT:
                yield field, wire, self.varint()
            elif wire == _LEN:
                n = self.varint()
                if self.pos + n > len(self.buf):
                    raise ValueError("proto: truncated field")
                yield field, wire, self.buf[self.pos:self.pos + n]
                self.pos += n
            elif wire == _I64:
                if self.pos + 8 > len(self.buf):
                    raise ValueError("proto: truncated i64")
                yield field, wire, self.buf[self.pos:self.pos + 8]
                self.pos += 8
            elif wire == 5:  # i32
                self.pos += 4
            else:
                raise ValueError(f"proto: unsupported wire type {wire}")


def _packed_uints(raw) -> list[int]:
    if isinstance(raw, int):  # unpacked single element
        return [raw]
    if not len(raw):
        return []
    # vectorized: varint boundaries are the bytes without the
    # continuation bit; one numpy pass per byte position reconstructs
    # every value (counterpart of _vec_varints)
    import numpy as _np
    buf = _np.frombuffer(raw, _np.uint8)
    ends = _np.nonzero((buf & 0x80) == 0)[0]
    if not len(ends) or int(ends[-1]) != len(buf) - 1:
        raise ValueError("proto: truncated packed varint")
    starts = _np.concatenate(([0], ends[:-1] + 1))
    lens = ends - starts + 1
    if int(lens.max()) > 10:
        raise ValueError("proto: varint too long")
    vals = _np.zeros(len(starts), _np.uint64)
    for g in range(int(lens.max())):
        m = lens > g
        vals[m] |= ((buf[starts[m] + g] & _np.uint8(0x7F))
                    .astype(_np.uint64) << _np.uint64(7 * g))
    return vals.tolist()


# -- QueryRequest ------------------------------------------------------------


@_malformed_as_value_error
def decode_query_request(buf: bytes) -> tuple[str, list[int] | None]:
    """-> (pql, shards or None)."""
    pql, shards = "", None
    for field, wire, val in _Reader(buf).fields():
        if field == 1 and wire == _LEN:
            pql = val.decode()
        elif field == 2:
            shards = (shards or []) + _packed_uints(val)
    return pql, shards


@_malformed_as_value_error
def decode_query_request_indexed(buf: bytes) \
        -> tuple[str, list[int] | None, str]:
    """-> (pql, shards or None, index) — the gRPC form, where no URL
    path carries the index name.  One pass over the buffer."""
    pql, index = "", ""
    shards = None
    for field, wire, val in _Reader(buf).fields():
        if field == 1 and wire == _LEN:
            pql = val.decode()
        elif field == 2:
            shards = (shards or []) + _packed_uints(val)
        elif field == 3 and wire == _LEN:
            index = val.decode()
    return pql, shards, index


def encode_query_request(pql: str, shards=None, index: str = "") -> bytes:
    out = _string(1, pql)
    if shards:
        out += _packed(2, shards, _varint)
    out += _string(3, index)
    return out


# -- Import requests ---------------------------------------------------------


def encode_import_request(*, index: str = "", field: str = "",
                          row_ids=None, col_ids=None, row_keys=None,
                          col_keys=None, timestamps=None,
                          clear: bool = False) -> bytes:
    """ImportRequest bytes.  ``timestamps`` must be homogeneous — all
    epoch ints or all ISO strings; a mixed list raises ValueError (the
    caller falls back to the JSON wire, which allows heterogeneity)."""
    out = _string(1, index) + _string(2, field)
    if row_ids is not None and len(row_ids):
        out += _packed(3, row_ids, _varint)
    if col_ids is not None and len(col_ids):
        out += _packed(4, col_ids, _varint)
    # empty strings are unrepresentable on this wire (zero-valued
    # fields elide — an empty key would silently vanish and desync the
    # parallel arrays): refuse so callers' JSON fallback fires
    for k in row_keys or []:
        if not k:
            raise ValueError("proto: empty row key")
        out += _string(5, k)
    for k in col_keys or []:
        if not k:
            raise ValueError("proto: empty column key")
        out += _string(6, k)
    if timestamps is not None and len(timestamps):
        if all(isinstance(t, int) for t in timestamps):
            out += _packed(7, _vec_zigzag([int(t) for t in timestamps]),
                           _varint)
        elif all(isinstance(t, str) for t in timestamps):
            for t in timestamps:
                if not t:
                    raise ValueError("proto: empty timestamp")
                out += _string(9, t)
        else:
            raise ValueError("proto: mixed timestamp types")
    if clear:
        out += _uint(8, 1)
    return out


@_malformed_as_value_error
def decode_import_request(buf: bytes) -> dict:
    """-> kwargs-shaped dict (row_ids/col_ids/row_keys/col_keys/
    timestamps/clear/index/field); absent lists are None."""
    index = field_name = ""
    row_ids: list | None = None
    col_ids: list | None = None
    row_keys: list | None = None
    col_keys: list | None = None
    ts: list | None = None
    clear = False
    for field, wire, val in _Reader(buf).fields():
        if field == 1:
            index = val.decode()
        elif field == 2:
            field_name = val.decode()
        elif field == 3:
            row_ids = (row_ids or []) + _packed_uints(val)
        elif field == 4:
            col_ids = (col_ids or []) + _packed_uints(val)
        elif field == 5:
            row_keys = row_keys if row_keys is not None else []
            row_keys.append(val.decode())
        elif field == 6:
            col_keys = col_keys if col_keys is not None else []
            col_keys.append(val.decode())
        elif field == 7:
            ts = (ts or []) + [_unzigzag(v) for v in _packed_uints(val)]
        elif field == 8:
            clear = bool(val)
        elif field == 9:
            ts = ts if ts is not None else []
            ts.append(val.decode())
    return {"index": index, "field": field_name, "row_ids": row_ids,
            "col_ids": col_ids, "row_keys": row_keys,
            "col_keys": col_keys, "timestamps": ts, "clear": clear}


def encode_import_value_request(*, index: str = "", field: str = "",
                                col_ids=None, col_keys=None,
                                values=None) -> bytes:
    out = _string(1, index) + _string(2, field)
    if col_ids is not None and len(col_ids):
        out += _packed(3, col_ids, _varint)
    for k in col_keys or []:
        if not k:  # see encode_import_request: empty strings elide
            raise ValueError("proto: empty column key")
        out += _string(4, k)
    vals = values if values is not None else []
    if len(vals):
        if all(isinstance(v, bool) for v in vals):
            raise ValueError("proto: bool import values")
        if all(isinstance(v, int) for v in vals):
            out += _packed(5, _vec_zigzag([int(v) for v in vals]), _varint)
        elif all(isinstance(v, (int, float)) for v in vals):
            # mixed ints encode as float64: refuse ints the double
            # can't carry exactly (|v| > 2^53) — silent rounding is
            # data corruption, the JSON fallback carries them intact
            for v in vals:
                if isinstance(v, int):
                    try:
                        exact = int(float(v)) == v
                    except OverflowError:
                        exact = False
                    if not exact:
                        raise ValueError(
                            f"proto: int {v} not exact in float64")
            raw = b"".join(struct.pack("<d", float(v)) for v in vals)
            out += _tag(6, _LEN) + _varint(len(raw)) + raw
        elif all(isinstance(v, str) for v in vals):
            for v in vals:
                if not v:
                    raise ValueError("proto: empty value string")
                out += _string(7, v)
        else:
            raise ValueError("proto: mixed import value types")
    return out


@_malformed_as_value_error
def decode_import_value_request(buf: bytes) -> dict:
    index = field_name = ""
    col_ids: list | None = None
    col_keys: list | None = None
    values: list | None = None
    for field, wire, val in _Reader(buf).fields():
        if field == 1:
            index = val.decode()
        elif field == 2:
            field_name = val.decode()
        elif field == 3:
            col_ids = (col_ids or []) + _packed_uints(val)
        elif field == 4:
            col_keys = col_keys if col_keys is not None else []
            col_keys.append(val.decode())
        elif field == 5:
            values = (values or []) + [_unzigzag(v)
                                       for v in _packed_uints(val)]
        elif field == 6:
            values = (values or []) + list(
                struct.unpack(f"<{len(val) // 8}d", val))
        elif field == 7:
            values = values if values is not None else []
            values.append(val.decode())
    return {"index": index, "field": field_name, "col_ids": col_ids,
            "col_keys": col_keys, "values": values}


def encode_import_response(changed: int = 0, err: str = "") -> bytes:
    out = b""
    if changed:
        out += _tag(1, _VARINT) + _varint(_zigzag(int(changed)))
    return out + _string(2, err)


@_malformed_as_value_error
def decode_import_response(buf: bytes) -> dict:
    changed, err = 0, ""
    for field, wire, val in _Reader(buf).fields():
        if field == 1:
            changed = _unzigzag(val)
        elif field == 2:
            err = val.decode()
    out = {"changed": changed}
    if err:
        out["error"] = err
    return out


# -- QueryResponse -----------------------------------------------------------

T_NIL, T_ROW, T_PAIRS, T_VALCOUNT, T_COUNT, T_BOOL, T_ROWIDS, \
    T_GROUPS, T_DISTINCT = range(9)


def _enc_valcount(v) -> bytes:
    out = b""
    val = v.get("value", 0)
    if isinstance(val, float):
        out += _double(3, val) + _uint(4, 1)
    else:
        out += _tag(1, _VARINT) + _varint(_zigzag(int(val)))
    out += _tag(2, _VARINT) + _varint(_zigzag(int(v.get("count", 0))))
    return out


def _enc_result(r) -> bytes:
    if r is None:
        return _uint(1, T_NIL)
    if isinstance(r, bool):
        return _uint(1, T_BOOL) + _uint(4, int(r))
    if isinstance(r, int):
        return _uint(1, T_COUNT) + _uint(3, r)
    if isinstance(r, list):  # TopN pairs or GroupBy groups
        if r and "group" in r[0]:
            out = _uint(1, T_GROUPS)
            for g in r:
                sub = b""
                for fr in g["group"]:
                    frb = _string(1, fr["field"])
                    if "rowKey" in fr:
                        frb += _string(3, fr["rowKey"])
                    else:
                        frb += _uint(2, fr.get("rowID", 0))
                    sub += _sub(1, frb)
                sub += _uint(2, g.get("count", 0))
                if g.get("agg") is not None:
                    sub += _tag(3, _VARINT) + _varint(_zigzag(int(g["agg"])))
                    sub += _uint(4, 1)
                out += _sub(9, sub)
            return out
        out = _uint(1, T_PAIRS)
        for p in r:
            sub = _uint(2, p.get("count", 0))
            if "key" in p:
                sub += _string(3, p["key"])
            else:
                sub += _uint(1, p.get("id", 0))
            out += _sub(5, sub)
        return out
    if isinstance(r, dict):
        if "fields" in r:  # Extract: tabular, no proto representation
            raise ValueError(
                "Extract results are not representable in the protobuf "
                "schema; request JSON")
        keyed = ("keys" in r and "rows" not in r
                 and "value" not in r and "values" not in r)
        if "columns" in r or keyed:
            sub = _packed(1, r.get("columns", []), _varint)
            for k in r.get("keys", []) or []:
                sub += _string(2, k)
            if keyed:  # explicit flag so {"keys": []} round-trips
                sub += _uint(3, 1)
            if r.get("rowAttrs") or r.get("attrs"):
                import json as _json
                if r.get("rowAttrs"):
                    sub += _string(4, _json.dumps(r["rowAttrs"]))
                if r.get("attrs"):
                    sub += _string(5, _json.dumps(r["attrs"]))
            return _uint(1, T_ROW) + _sub(2, sub)
        if "rows" in r:
            return _uint(1, T_ROWIDS) + _packed(7, r["rows"], _varint)
        if "value" in r:
            return _uint(1, T_VALCOUNT) + _sub(6, _enc_valcount(r))
        if "values" in r:
            out = _uint(1, T_DISTINCT)
            vals = r["values"]
            if any(isinstance(v, float) for v in vals):
                raw = b"".join(struct.pack("<d", float(v)) for v in vals)
                return out + (_tag(11, _LEN) + _varint(len(raw)) + raw
                              if raw else b"")
            return out + _packed(10, _vec_zigzag([int(v) for v in vals]),
                                 _varint)
    raise ValueError(f"proto: unencodable result {type(r)}")


def encode_query_response(results=None, err: str = "") -> bytes:
    out = _string(1, err)
    for r in results or []:
        out += _sub(2, _enc_result(r))
    return out


# -- response decode (client/test side) --------------------------------------


def _dec_valcount(raw: bytes) -> dict:
    out = {"value": 0, "count": 0}
    is_float, fval = False, 0.0
    for field, wire, val in _Reader(raw).fields():
        if field == 1:
            out["value"] = _unzigzag(val)
        elif field == 2:
            out["count"] = _unzigzag(val)
        elif field == 3:
            fval = struct.unpack("<d", val)[0]
        elif field == 4:
            is_float = bool(val)
    if is_float:
        out["value"] = fval
    return out


def _dec_result(raw: bytes):
    typ = 0
    row_cols, row_keys = [], []
    row_keyed = False
    row_attrs = None
    col_attrs = None
    n = 0
    changed = False
    pairs, groups, row_ids, values = [], [], [], []
    valcount = None
    for field, wire, val in _Reader(raw).fields():
        if field == 1:
            typ = val
        elif field == 2:  # Row
            for f2, w2, v2 in _Reader(val).fields():
                if f2 == 1:
                    row_cols += _packed_uints(v2)
                elif f2 == 2:
                    row_keys.append(v2.decode())
                elif f2 == 3:
                    row_keyed = bool(v2)
                elif f2 == 4:
                    import json as _json
                    row_attrs = _json.loads(v2.decode())
                elif f2 == 5:
                    import json as _json
                    col_attrs = _json.loads(v2.decode())
        elif field == 3:
            n = val
        elif field == 4:
            changed = bool(val)
        elif field == 5:  # Pair
            p = {}
            for f2, w2, v2 in _Reader(val).fields():
                if f2 == 1:
                    p["id"] = v2
                elif f2 == 2:
                    p["count"] = v2
                elif f2 == 3:
                    p["key"] = v2.decode()
            p.setdefault("count", 0)
            if "key" not in p:
                p.setdefault("id", 0)
            pairs.append(p)
        elif field == 6:
            valcount = _dec_valcount(val)
        elif field == 7:
            row_ids += _packed_uints(val)
        elif field == 9:  # GroupCount
            g = {"group": [], "count": 0}
            has_agg = False
            for f2, w2, v2 in _Reader(val).fields():
                if f2 == 1:
                    fr = {}
                    for f3, w3, v3 in _Reader(v2).fields():
                        if f3 == 1:
                            fr["field"] = v3.decode()
                        elif f3 == 2:
                            fr["rowID"] = v3
                        elif f3 == 3:
                            fr["rowKey"] = v3.decode()
                    if "rowKey" not in fr:
                        fr.setdefault("rowID", 0)
                    g["group"].append(fr)
                elif f2 == 2:
                    g["count"] = v2
                elif f2 == 3:
                    g["agg"] = _unzigzag(v2)
                elif f2 == 4:
                    has_agg = bool(v2)
            if not has_agg:
                g.pop("agg", None)
            groups.append(g)
        elif field == 10:
            values += [_unzigzag(v) for v in _packed_uints(val)]
        elif field == 11:
            values += list(struct.unpack(f"<{len(val) // 8}d", val))
    if typ == T_NIL:
        return None
    if typ == T_BOOL:
        return changed
    if typ == T_COUNT:
        return n
    if typ == T_ROW:
        out = ({"keys": row_keys} if row_keyed or row_keys
               else {"columns": row_cols})
        if row_attrs:
            out["rowAttrs"] = row_attrs
        if col_attrs:
            out["attrs"] = col_attrs
        return out
    if typ == T_PAIRS:
        return pairs
    if typ == T_VALCOUNT:
        return valcount or {"value": 0, "count": 0}
    if typ == T_ROWIDS:
        return {"rows": row_ids}
    if typ == T_GROUPS:
        return groups
    if typ == T_DISTINCT:
        return {"values": values}
    raise ValueError(f"proto: unknown result type {typ}")


@_malformed_as_value_error
def decode_query_response(buf: bytes) -> dict:
    err = ""
    results = []
    for field, wire, val in _Reader(buf).fields():
        if field == 1:
            err = val.decode()
        elif field == 2:
            results.append(_dec_result(val))
    out = {"results": results}
    if err:
        out["error"] = err
    return out
