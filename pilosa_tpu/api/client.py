"""HTTP client for the REST surface.

Reference: ``http/client.go`` (SURVEY.md §3.3) — the same client serves
external callers (CLI import/export/backup) and, in the cluster layer,
node-to-node calls (``InternalClient``).  stdlib urllib; no external
deps.
"""

from __future__ import annotations

import json
import urllib.error
import urllib.parse
import urllib.request


class ClientError(Exception):
    def __init__(self, msg: str, status: int = 0):
        super().__init__(msg)
        self.status = status


class Client:
    def __init__(self, host: str = "127.0.0.1", port: int = 10101,
                 timeout: float = 60.0, ssl_context=None):
        scheme = "https" if ssl_context is not None else "http"
        self.base = f"{scheme}://{host}:{port}"
        self.timeout = timeout
        self._ssl = ssl_context

    # -- transport ----------------------------------------------------------

    def _do(self, method: str, path: str, body: bytes | None = None,
            content_type: str = "application/json",
            headers: dict | None = None, _retried: bool = False):
        hdrs = dict(headers or {})
        if body:
            hdrs["Content-Type"] = content_type
        req = urllib.request.Request(
            self.base + path, data=body, method=method, headers=hdrs)
        try:
            with urllib.request.urlopen(req, timeout=self.timeout,
                                        context=self._ssl) as resp:
                data = resp.read()
                ctype = resp.headers.get("Content-Type", "")
        except ConnectionResetError:
            # transient under connection churn; one retry
            if _retried:
                raise ClientError(f"connection reset by {self.base}")
            return self._do(method, path, body, content_type, headers,
                            _retried=True)
        except urllib.error.HTTPError as e:
            detail = e.read().decode(errors="replace")
            try:
                detail = json.loads(detail).get("error", detail)
            except json.JSONDecodeError:
                pass
            raise ClientError(detail, e.code) from e
        except urllib.error.URLError as e:
            if isinstance(getattr(e, "reason", None), ConnectionResetError) \
                    and not _retried:
                return self._do(method, path, body, content_type, headers,
                                _retried=True)
            raise ClientError(f"cannot reach {self.base}: {e.reason}") from e
        except OSError as e:
            # TLS alerts (e.g. mTLS 'certificate required') can surface
            # as raw ssl.SSLError during getresponse(), outside
            # urllib's URLError wrapping — same contract: ClientError
            raise ClientError(f"transport error from {self.base}: {e}") \
                from e
        if ctype.startswith("application/json"):
            return json.loads(data)
        return data

    def _json(self, method: str, path: str, obj=None,
              headers: dict | None = None):
        body = json.dumps(obj).encode() if obj is not None else None
        return self._do(method, path, body, headers=headers)

    # -- api ----------------------------------------------------------------

    def query(self, index: str, pql: str, shards: list[int] | None = None):
        path = f"/index/{index}/query"
        if shards:
            path += "?shards=" + ",".join(str(s) for s in shards)
        return self._do("POST", path, pql.encode())["results"]

    def create_index(self, name: str, options: dict | None = None):
        return self._json("POST", f"/index/{name}",
                          {"options": options or {}})

    def delete_index(self, name: str):
        return self._json("DELETE", f"/index/{name}")

    def create_field(self, index: str, name: str,
                     options: dict | None = None):
        return self._json("POST", f"/index/{index}/field/{name}",
                          {"options": options or {}})

    def delete_field(self, index: str, name: str):
        return self._json("DELETE", f"/index/{index}/field/{name}")

    def import_bits(self, index: str, field: str, **body):
        """Bulk bit import; batches ride the protobuf wire when the
        codec accepts them (2.5× smaller, less CPU than JSON at 100k
        pairs — BASELINE.md r3), falling back to JSON otherwise
        (heterogeneous timestamp lists, out-of-range ints)."""
        from pilosa_tpu.api import proto
        try:
            raw = proto.encode_import_request(
                row_ids=body.get("rowIDs"), col_ids=body.get("columnIDs"),
                row_keys=body.get("rowKeys"),
                col_keys=body.get("columnKeys"),
                timestamps=body.get("timestamps"),
                clear=bool(body.get("clear", False)))
        except ValueError:
            return self._json(
                "POST", f"/index/{index}/field/{field}/import",
                body)["changed"]
        return self._do("POST", f"/index/{index}/field/{field}/import",
                        raw, content_type=proto.CONTENT_TYPE)["changed"]

    def import_values(self, index: str, field: str, **body):
        from pilosa_tpu.api import proto
        try:
            raw = proto.encode_import_value_request(
                col_ids=body.get("columnIDs"),
                col_keys=body.get("columnKeys"),
                values=body.get("values"))
        except ValueError:
            return self._json(
                "POST", f"/index/{index}/field/{field}/importValue",
                body)["changed"]
        return self._do("POST",
                        f"/index/{index}/field/{field}/importValue",
                        raw, content_type=proto.CONTENT_TYPE)["changed"]

    def import_roaring(self, index: str, field: str, shard: int, blob: bytes,
                       view: str = "standard"):
        path = (f"/index/{index}/field/{field}/import-roaring/{shard}"
                f"?view={urllib.parse.quote(view)}")
        return self._do("POST", path, blob,
                        content_type="application/octet-stream")["changed"]

    def export_csv(self, index: str, field: str) -> str:
        return self._do(
            "GET", f"/export?index={index}&field={field}").decode()

    def schema(self) -> list[dict]:
        return self._json("GET", "/schema")["indexes"]

    def status(self) -> dict:
        return self._json("GET", "/status")

    def info(self) -> dict:
        return self._json("GET", "/info")

    def version(self) -> str:
        return self._json("GET", "/version")["version"]

    def metrics_text(self) -> str:
        return self._do("GET", "/metrics").decode()
