"""HTTP client for the REST surface.

Reference: ``http/client.go`` (SURVEY.md §3.3) — the same client serves
external callers (CLI import/export/backup) and, in the cluster layer,
node-to-node calls (``InternalClient``).  stdlib urllib; no external
deps.

Retry policy (ADVICE r5): a failure in the SEND phase
(``CannotSendRequest`` — the request never left this process) always
retries once on a fresh connection.  A failure AFTER the request was
sent (``BadStatusLine`` / connection reset / broken pipe — the response
was lost, but the peer may already have processed the request) retries
only when the request is idempotent: safe methods (GET/HEAD/PUT/
DELETE), or POSTs on a client constructed with
``idempotent_posts=True`` — the cluster's internode client, whose
``/internal/*`` POST surface is idempotent by contract (see
:mod:`pilosa_tpu.cluster.internal`).  Default clients never auto-retry
a possibly-delivered POST: ``query`` can carry writes (``Set(...)``)
and imports are not exactly-once.
"""

from __future__ import annotations

import http.client
import json
import ssl
import threading
import urllib.parse

from pilosa_tpu import fault


class ClientError(Exception):
    """Transport or HTTP failure.

    ``kind`` distinguishes failure classes that demand different
    handling at write-replication time (ADVICE r4):

    - ``"http"``       — the peer answered with an error status
    - ``"unreachable"`` — connection refused/reset/DNS: the peer never
      saw the request, so a write definitely did NOT apply
    - ``"timeout"``    — the socket timed out AFTER the request was
      sent: the peer may still apply it → replica state is UNKNOWN,
      which is NOT the same as "down"
    - ``"transport"``  — other transport faults (TLS alerts, …)
    """

    def __init__(self, msg: str, status: int = 0, kind: str = "transport"):
        super().__init__(msg)
        self.status = status
        self.kind = kind if status == 0 else "http"


class Client:
    """Persistent-connection HTTP client.  Each request checks a
    keep-alive connection out of a small idle pool (concurrent callers
    each get their own; at most ``MAX_IDLE`` are kept) — the cluster
    fan-out previously paid a fresh TCP handshake per internode RPC
    (config12 r4 measured ~1.2 ms/node; connection reuse is the first
    lever the r4 verdict named)."""

    MAX_IDLE = 8

    # methods whose retry after a lost response cannot double-apply
    IDEMPOTENT_METHODS = frozenset({"GET", "HEAD", "PUT", "DELETE"})

    def __init__(self, host: str = "127.0.0.1", port: int = 10101,
                 timeout: float = 60.0, ssl_context=None,
                 idempotent_posts: bool = False):
        scheme = "https" if ssl_context is not None else "http"
        self.base = f"{scheme}://{host}:{port}"
        self.host, self.port = host, port
        self.timeout = timeout
        # True ONLY when every POST this client sends is idempotent
        # (the cluster's /internal/* contract) — enables the stale-
        # socket retry for POSTs whose response was lost after the
        # peer may have processed them (module docstring)
        self.idempotent_posts = idempotent_posts
        self._ssl = ssl_context
        self._idle: list[http.client.HTTPConnection] = []
        self._plock = threading.Lock()
        # per-thread flag: did the LAST completed request on this
        # thread go through a retry?  Read by the cluster fan-out so a
        # trace records that its remote leg was redelivered — traces
        # must not lie under failure (chaos scenario)
        self._tls = threading.local()

    # -- transport ----------------------------------------------------------

    def _checkout(self, timeout: float, fresh: bool = False):
        """An idle keep-alive connection, or a freshly-connected one.
        A pooled socket may be stale (server restarted / idle-closed),
        so ``_do`` retries stale errors once with ``fresh=True``, which
        bypasses and drains the pool — every idle socket predates the
        failure and is equally suspect."""
        if fresh:
            self.close()
        else:
            with self._plock:
                if self._idle:
                    conn = self._idle.pop()
                    if conn.sock is not None:
                        conn.sock.settimeout(timeout)
                    return conn
        cls = http.client.HTTPConnection
        kw = {}
        if self._ssl is not None:
            cls, kw = http.client.HTTPSConnection, {"context": self._ssl}
        conn = cls(self.host, self.port, timeout=timeout, **kw)
        try:
            conn.connect()
        except TimeoutError as e:
            # CONNECT timeout: not one byte of the request was sent, so
            # this is "unreachable" (a write definitely did not apply),
            # NOT the state-unknown "timeout" class — that kind is
            # reserved for sockets that time out AFTER the request left
            # (the peer may still be processing it)
            raise ClientError(f"cannot reach {self.base}: connect timed "
                              f"out: {e}", kind="unreachable") from e
        except OSError as e:
            # refused / DNS / TLS-handshake rejection: the request was
            # never delivered — a write definitely did not apply
            raise ClientError(f"cannot reach {self.base}: {e}",
                              kind="unreachable") from e
        # no Nagle: request writes on a kept-alive socket must not wait
        # out the server's delayed ACK (mirror of the server setting)
        import socket
        conn.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        return conn

    def _checkin(self, conn) -> None:
        with self._plock:
            if len(self._idle) < self.MAX_IDLE:
                self._idle.append(conn)
                return
        conn.close()

    def close(self) -> None:
        """Drop idle pooled connections (new requests reconnect)."""
        with self._plock:
            idle, self._idle = self._idle, []
        for conn in idle:
            conn.close()

    def _do(self, method: str, path: str, body: bytes | None = None,
            content_type: str = "application/json",
            headers: dict | None = None, _retried: bool = False,
            timeout: float | None = None):
        hdrs = dict(headers or {})
        if body:
            hdrs["Content-Type"] = content_type
        if not _retried:
            self._tls.retried = False
        if fault.ACTIVE:
            # failpoint BEFORE the socket: a partitioned peer is
            # indistinguishable from connection-refused (the request
            # was never delivered — kind="unreachable", exactly the
            # class write replication may safely skip best-effort)
            spec = fault.fire("client.send",
                              peer=f"{self.host}:{self.port}",
                              method=method, path=path)
            if spec is not None and spec["action"] == "partition":
                raise ClientError(
                    f"cannot reach {self.base}: injected partition",
                    kind="unreachable")
        t = self.timeout if timeout is None else timeout
        conn = self._checkout(t, fresh=_retried)
        try:
            conn.request(method, path, body=body, headers=hdrs)
            if fault.ACTIVE:
                # failpoint AFTER the request left: losing the response
                # here exercises the at-least-once retry contract — the
                # peer HAS processed the request (raised inside the try
                # so the reset takes the real lost-response path below)
                spec = fault.fire("client.recv",
                                  peer=f"{self.host}:{self.port}",
                                  method=method, path=path)
                if spec is not None and spec["action"] == "drop":
                    raise ConnectionResetError(
                        "injected response drop (request was sent)")
            resp = conn.getresponse()
            data = resp.read()
        except http.client.CannotSendRequest as e:
            # SEND-phase failure: the request never left this process —
            # always safe to retry once on a fresh connection
            conn.close()
            if not _retried:
                if hasattr(body, "seek"):
                    body.seek(0)  # streamed (file-object) bodies rewind
                self._tls.retried = True
                return self._do(method, path, body, content_type, headers,
                                _retried=True, timeout=timeout)
            raise ClientError(f"connection reset by {self.base}",
                              kind="unreachable") from e
        except (http.client.BadStatusLine, http.client.IncompleteRead,
                ConnectionResetError, BrokenPipeError) as e:
            # the response was lost AFTER the request was sent (a peer
            # dying mid-response-write surfaces as IncompleteRead, not
            # a reset): the peer may already have processed it, so an
            # automatic retry is at-least-once.  Retry only idempotent
            # requests (safe methods, or POSTs under the cluster's
            # idempotency contract) — a default client surfaces the
            # error and lets the caller decide (module docstring,
            # ADVICE r5)
            conn.close()
            idempotent = (method in self.IDEMPOTENT_METHODS
                          or self.idempotent_posts)
            if idempotent and not _retried:
                if hasattr(body, "seek"):
                    body.seek(0)  # streamed (file-object) bodies rewind
                self._tls.retried = True
                return self._do(method, path, body, content_type, headers,
                                _retried=True, timeout=timeout)
            raise ClientError(f"connection reset by {self.base}",
                              kind="unreachable") from e
        except TimeoutError as e:
            # read timeout after the request was sent (socket.timeout is
            # TimeoutError since 3.10): the peer may still apply a write
            conn.close()
            raise ClientError(
                f"request to {self.base} timed out", kind="timeout") from e
        except ssl.SSLError as e:
            # TLS alerts (e.g. mTLS 'certificate required') surfacing
            # mid-request, after the handshake
            conn.close()
            raise ClientError(f"transport error from {self.base}: {e}") \
                from e
        except OSError as e:
            conn.close()
            raise ClientError(f"cannot reach {self.base}: {e}",
                              kind="unreachable") from e
        status = resp.status
        ctype = resp.headers.get("Content-Type", "")
        if resp.will_close:
            conn.close()
        else:
            self._checkin(conn)
        if status >= 400:
            detail = data.decode(errors="replace")
            try:
                detail = json.loads(detail).get("error", detail)
            except json.JSONDecodeError:
                pass
            raise ClientError(detail, status)
        if ctype.startswith("application/json"):
            return json.loads(data)
        return data

    def last_retried(self) -> bool:
        """Whether the most recent ``_do`` on THIS thread retried (lost
        response redelivered / stale socket resent)."""
        return getattr(self._tls, "retried", False)

    # streamed-download read size: bounds peak memory per transfer (a
    # multi-GB fragment image never materializes as one bytes object)
    DOWNLOAD_CHUNK = 1 << 20

    def download(self, path: str, sink, chunk_size: int | None = None,
                 timeout: float | None = None,
                 _retried: bool = False) -> dict:
        """Stream a GET response body into ``sink`` (anything with
        ``write(bytes)``) in bounded chunks; returns the response
        headers as a plain dict (``Content-Length``,
        ``X-Content-SHA256``, …) so callers can verify digests they
        computed while writing.

        Retry contract: GET is idempotent, so a stale pooled socket
        retries once — but only while ZERO body bytes have reached the
        sink (a mid-body retry would duplicate the prefix; callers
        that want mid-body recovery restart the whole transfer, e.g.
        against another replica)."""
        chunk_size = chunk_size or self.DOWNLOAD_CHUNK
        t = self.timeout if timeout is None else timeout
        conn = self._checkout(t, fresh=_retried)
        wrote = 0
        try:
            conn.request("GET", path)
            resp = conn.getresponse()
            if resp.status >= 400:
                data = resp.read()
                if resp.will_close:
                    conn.close()
                else:
                    self._checkin(conn)
                detail = data.decode(errors="replace")
                try:
                    detail = json.loads(detail).get("error", detail)
                except json.JSONDecodeError:
                    pass
                raise ClientError(detail, resp.status)
            while True:
                chunk = resp.read(chunk_size)
                if not chunk:
                    break
                sink.write(chunk)
                wrote += len(chunk)
        except (http.client.CannotSendRequest, http.client.BadStatusLine,
                http.client.IncompleteRead, ConnectionResetError,
                BrokenPipeError) as e:
            conn.close()
            if not _retried and wrote == 0:
                return self.download(path, sink, chunk_size,
                                     timeout=timeout, _retried=True)
            raise ClientError(f"connection reset by {self.base}",
                              kind="unreachable") from e
        except TimeoutError as e:
            conn.close()
            raise ClientError(f"request to {self.base} timed out",
                              kind="timeout") from e
        except OSError as e:
            conn.close()
            raise ClientError(f"cannot reach {self.base}: {e}",
                              kind="unreachable") from e
        headers = dict(resp.headers.items())
        if resp.will_close:
            conn.close()
        else:
            self._checkin(conn)
        clen = headers.get("Content-Length")
        if clen is not None and int(clen) != wrote:
            raise ClientError(
                f"short read from {self.base}{path}: got {wrote} of "
                f"{clen} bytes", kind="transport")
        return headers

    def _json(self, method: str, path: str, obj=None,
              headers: dict | None = None):
        body = json.dumps(obj).encode() if obj is not None else None
        return self._do(method, path, body, headers=headers)

    # -- api ----------------------------------------------------------------

    def query(self, index: str, pql: str, shards: list[int] | None = None):
        path = f"/index/{index}/query"
        if shards:
            path += "?shards=" + ",".join(str(s) for s in shards)
        return self._do("POST", path, pql.encode())["results"]

    def create_index(self, name: str, options: dict | None = None):
        return self._json("POST", f"/index/{name}",
                          {"options": options or {}})

    def delete_index(self, name: str):
        return self._json("DELETE", f"/index/{name}")

    def create_field(self, index: str, name: str,
                     options: dict | None = None):
        getattr(self, "_field_type_cache", {}).pop((index, name), None)
        return self._json("POST", f"/index/{index}/field/{name}",
                          {"options": options or {}})

    def delete_field(self, index: str, name: str):
        getattr(self, "_field_type_cache", {}).pop((index, name), None)
        return self._json("DELETE", f"/index/{index}/field/{name}")

    # auto-roaring import: ID-form batches whose pairs concentrate per
    # shard serialize client-side and ride the ImportRoaring fast path
    # (~120× the per-pair path per bit — BASELINE.md r4); scattered
    # batches keep the pair wire, where per-shard HTTP round trips
    # would dominate
    ROARING_MIN_PER_SHARD = 4096

    def import_bits(self, index: str, field: str, **body):
        """Bulk bit import; dense ID-form batches ride the roaring
        bulk path (see ROARING_MIN_PER_SHARD), other batches the
        protobuf wire when the codec accepts them (2.5× smaller, less
        CPU than JSON at 100k pairs — BASELINE.md r3), falling back to
        JSON otherwise (heterogeneous timestamp lists, out-of-range
        ints)."""
        from pilosa_tpu.api import proto

        if (body.get("rowIDs") is not None
                and body.get("columnIDs") is not None
                and not body.get("rowKeys")
                and not body.get("columnKeys")
                and body.get("timestamps") is None
                and not body.get("clear", False)):
            out = self._try_import_roaring(index, field, body["rowIDs"],
                                           body["columnIDs"])
            if out is not None:
                return out
        try:
            raw = proto.encode_import_request(
                row_ids=body.get("rowIDs"), col_ids=body.get("columnIDs"),
                row_keys=body.get("rowKeys"),
                col_keys=body.get("columnKeys"),
                timestamps=body.get("timestamps"),
                clear=bool(body.get("clear", False)))
        except ValueError:
            return self._json(
                "POST", f"/index/{index}/field/{field}/import",
                body)["changed"]
        return self._do("POST", f"/index/{index}/field/{field}/import",
                        raw, content_type=proto.CONTENT_TYPE)["changed"]

    def import_values(self, index: str, field: str, **body):
        from pilosa_tpu.api import proto
        try:
            raw = proto.encode_import_value_request(
                col_ids=body.get("columnIDs"),
                col_keys=body.get("columnKeys"),
                values=body.get("values"))
        except ValueError:
            return self._json(
                "POST", f"/index/{index}/field/{field}/importValue",
                body)["changed"]
        return self._do("POST",
                        f"/index/{index}/field/{field}/importValue",
                        raw, content_type=proto.CONTENT_TYPE)["changed"]

    def _try_import_roaring(self, index: str, field: str, row_ids,
                            col_ids) -> int | None:
        """Serialize an ID-form batch into per-shard roaring blobs and
        import each — or return None to fall through to the pair wire:
        when the batch is too scattered (per-shard HTTP round trips
        would cost more than the wire saves), when ids don't fit
        uint64, or when the target is not a set/time field (raw
        fragment unions skip mutex/bool/BSI semantics — the server
        rejects those too).

        Unlike the single-request pair/proto wire, this path commits
        one request PER SHARD: a failure partway leaves earlier shards
        applied.  The raised ClientError carries the bits already
        committed as ``partial_changed`` — set-bit imports are
        idempotent, so retrying the whole batch is always safe."""
        import numpy as np

        from pilosa_tpu.engine.words import SHARD_WIDTH
        from pilosa_tpu.store import roaring

        if self._field_type(index, field) not in ("set", "time"):
            return None
        try:
            rows = np.asarray(row_ids, dtype=np.uint64)
            cols = np.asarray(col_ids, dtype=np.uint64)
        except (OverflowError, ValueError, TypeError):
            return None  # out-of-range ids: the JSON fallback's case
        if len(rows) != len(cols) or len(rows) == 0:
            return None
        shard_of = cols // np.uint64(SHARD_WIDTH)
        shards = np.unique(shard_of)
        if len(rows) < self.ROARING_MIN_PER_SHARD * len(shards):
            return None
        positions = rows * np.uint64(SHARD_WIDTH) \
            + (cols % np.uint64(SHARD_WIDTH))
        # one sort, then boundary slices — a per-shard boolean mask
        # would rescan the whole batch n_shards times
        order = np.argsort(shard_of, kind="stable")
        positions = positions[order]
        bounds = np.searchsorted(shard_of[order], shards)
        bounds = np.append(bounds, len(positions))
        changed = 0
        for i, s in enumerate(shards):
            blob = roaring.serialize(positions[bounds[i]:bounds[i + 1]])
            try:
                changed += self.import_roaring(index, field, int(s), blob)
            except ClientError as e:
                if e.status == 400 and i == 0:
                    # stale cached field type (field recreated with a
                    # different type): the server's type check fires
                    # before anything imports — refresh and fall back
                    self._field_type_cache.pop((index, field), None)
                    return None
                e.partial_changed = changed  # earlier shards committed
                raise
        return changed

    def _field_type(self, index: str, field: str) -> str | None:
        """Field type from the server schema, cached per (index,
        field).  Transient transport failures are NOT cached (a single
        connection blip must not pin this client to the slow pair wire
        for its lifetime); create/delete_field invalidate."""
        cache = getattr(self, "_field_type_cache", None)
        if cache is None:
            cache = self._field_type_cache = {}
        key = (index, field)
        if key not in cache:
            try:
                info = self._json("GET", f"/index/{index}/field/{field}")
                cache[key] = info.get("options", {}).get("type")
            except ClientError as e:
                if not 400 <= e.status < 500:
                    return None  # transport/5xx: don't cache
                cache[key] = None
        return cache[key]

    def import_roaring(self, index: str, field: str, shard: int, blob: bytes,
                       view: str = "standard"):
        path = (f"/index/{index}/field/{field}/import-roaring/{shard}"
                f"?view={urllib.parse.quote(view)}")
        return self._do("POST", path, blob,
                        content_type="application/octet-stream")["changed"]

    def export_csv(self, index: str, field: str) -> str:
        return self._do(
            "GET", f"/export?index={index}&field={field}").decode()

    def schema(self) -> list[dict]:
        return self._json("GET", "/schema")["indexes"]

    def status(self) -> dict:
        return self._json("GET", "/status")

    def write_health(self) -> dict:
        """The ``writeHealth`` block of ``/status`` (hinted-handoff
        backlog/age/per-peer drains) — what an operator or harness
        polls to watch a rejoined node's hint drain complete."""
        return self._json("GET", "/status").get("writeHealth", {})

    def info(self) -> dict:
        return self._json("GET", "/info")

    def version(self) -> str:
        return self._json("GET", "/version")["version"]

    def metrics_text(self, openmetrics: bool = False) -> str:
        """/metrics exposition text; ``openmetrics`` negotiates the
        OpenMetrics format (the only one that carries exemplars)."""
        headers = ({"Accept": "application/openmetrics-text"}
                   if openmetrics else None)
        return self._do("GET", "/metrics", headers=headers).decode()
