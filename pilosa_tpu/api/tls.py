"""TLS context construction for the REST surface, internode calls, and
gRPC.

Reference: upstream ``server/config.go``'s ``tls`` section (SURVEY.md
§3.3) — server certificate/key, optional CA, optional client-cert
verification for mutual TLS between nodes.  The same node certificate
serves both roles: presented as a server cert to inbound connections
and as a client cert on internode calls (mTLS when
``tls_enable_client_auth`` is on).

Plaintext stays the default; every surface switches together off one
config block so a cluster is either TLS end to end or not at all.
"""

from __future__ import annotations

import ssl
from dataclasses import dataclass


@dataclass(frozen=True)
class TLSConfig:
    """Resolved tls block (paths already expanded by config.load)."""

    certificate: str = ""        # PEM server/client cert path
    key: str = ""                # PEM private key path
    ca_certificate: str = ""     # PEM CA bundle for verifying peers
    skip_verify: bool = False    # client side: accept any server cert
    enable_client_auth: bool = False  # server side: require client certs

    @property
    def enabled(self) -> bool:
        return bool(self.certificate)

    def validate(self) -> None:
        if self.certificate and not self.key:
            raise ValueError("tls: certificate set but key missing")
        if self.enable_client_auth and not self.ca_certificate:
            raise ValueError(
                "tls: enable_client_auth requires ca_certificate")


def server_context(tls: TLSConfig) -> ssl.SSLContext | None:
    """SSLContext for inbound HTTP connections, or None when TLS is
    off.  ``enable_client_auth`` turns on mutual TLS: clients must
    present a certificate signed by ``ca_certificate``."""
    if not tls.enabled:
        return None
    tls.validate()
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
    ctx.load_cert_chain(tls.certificate, tls.key)
    if tls.enable_client_auth:
        ctx.load_verify_locations(tls.ca_certificate)
        ctx.verify_mode = ssl.CERT_REQUIRED
    return ctx


def client_context(tls: TLSConfig) -> ssl.SSLContext | None:
    """SSLContext for outbound calls (internode fan-out, CLI client),
    or None when TLS is off.  Presents the node certificate when one is
    configured so mTLS clusters authenticate both ways."""
    if not (tls.enabled or tls.ca_certificate or tls.skip_verify):
        return None
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
    if tls.skip_verify:
        ctx.check_hostname = False
        ctx.verify_mode = ssl.CERT_NONE
    elif tls.ca_certificate:
        ctx.load_verify_locations(tls.ca_certificate)
    else:
        ctx.load_default_certs()
    if tls.certificate:
        tls.validate()
        ctx.load_cert_chain(tls.certificate, tls.key)
    return ctx


def grpc_server_credentials(tls: TLSConfig):
    """``grpc.ssl_server_credentials`` built from the same block, or
    None when TLS is off."""
    if not tls.enabled:
        return None
    tls.validate()
    import grpc

    with open(tls.key, "rb") as f:
        key = f.read()
    with open(tls.certificate, "rb") as f:
        cert = f.read()
    ca = None
    if tls.ca_certificate:
        with open(tls.ca_certificate, "rb") as f:
            ca = f.read()
    return grpc.ssl_server_credentials(
        ((key, cert),), root_certificates=ca,
        require_client_auth=tls.enable_client_auth)
