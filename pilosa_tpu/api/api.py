"""Programmatic API façade.

Reference: ``api.go`` (SURVEY.md §3.3) — the validation + orchestration
layer used by both the HTTP handler and (upstream v2) gRPC: index/field
CRUD, query execution, bulk import routing, schema and status
introspection.  Both the REST server (:mod:`pilosa_tpu.api.server`) and
the CLI drive this class; it owns nothing itself — holder for storage,
executor for queries.
"""

from __future__ import annotations

import io
import logging
import os
import random
import time as _time
from datetime import datetime

import numpy as np

from pilosa_tpu.engine.words import SHARD_WIDTH
from pilosa_tpu.exec import Executor, result_to_json
from pilosa_tpu.exec.executor import (ExecutionError,
                                      ExecutorSaturatedError,
                                      PipelineStalledError,
                                      QueryTimeoutError,
                                      WriteUnavailableError)
from pilosa_tpu.pql.parser import ParseError
from pilosa_tpu.store import FieldOptions, Holder
from pilosa_tpu.store.field import BSI_TYPES
from pilosa_tpu.store.health import StorageFaultError
from pilosa_tpu.store.view import VIEW_STANDARD
from pilosa_tpu.tenancy import TenantThrottledError


class ApiError(Exception):
    def __init__(self, msg: str, status: int = 400,
                 retry_after: float | None = None,
                 extra: dict | None = None):
        super().__init__(msg)
        self.status = status
        # seconds for a Retry-After response header (load shedding:
        # a 503 should tell the client when to come back)
        self.retry_after = retry_after
        # structured fields merged into the JSON error body next to
        # "error" (e.g. the 504 timeout block: elapsed, deadline,
        # shards outstanding)
        self.extra = extra

    @classmethod
    def timeout(cls, exc, elapsed: float,
                deadline: float | None) -> "ApiError":
        """The deadline-exceeded contract, shared by the public and
        ``/internal/query`` edges: HTTP 504 with a structured body —
        how long the query ran, what the budget was, how many shards
        never answered."""
        return cls(str(exc), 504, extra={"timeout": {
            "elapsedSeconds": round(elapsed, 6),
            "deadlineSeconds": deadline or None,
            "shardsOutstanding": getattr(exc, "shards_outstanding",
                                         None),
            # r18: when the deadline expired while blocked on the
            # dispatch pipeline, name the stage (queued/dispatch/
            # readback) so a wedged caller's 504 says WHAT stalled
            "stage": getattr(exc, "stage", None)}})

    @classmethod
    def pipeline_stall(cls, exc) -> "ApiError":
        """The quarantined-window contract (r18), shared by the public
        and ``/internal/query`` edges: HTTP 500 with a structured
        ``pipelineStall`` body naming the stalled stage and how long
        the watchdog let it age — a sick device costs the wedged
        caller a loud, attributable error, never a hung thread."""
        return cls(str(exc), 500, extra={"pipelineStall": {
            "stage": getattr(exc, "stage", None),
            "elapsedSeconds": round(getattr(exc, "elapsed", 0.0), 3)}})

    @classmethod
    def write_unavailable(cls, exc) -> "ApiError":
        """The write-unavailability contract (r13), shared by the
        public and ``/internal/query`` edges: HTTP 503 + Retry-After
        with a structured body naming the op, the down replica, and
        why hinted handoff could not cover it (``replica_down`` —
        handoff disabled, ``hint_overflow`` — backlog older than
        hint_max_age, ``no_live_replica``, ``replica_busy`` — an
        alive replica shed the op).  Mirrors the 504 timeout
        block: unavailability is never a generic 400/500.  (The r19
        disk-full refusal has its own 507 shape — see
        :meth:`storage_fault`.)"""
        return cls(str(exc), 503,
                   retry_after=getattr(exc, "retry_after", 1.0),
                   extra={"writeUnavailable": {
                       "op": exc.op, "replica": exc.replica,
                       "reason": exc.reason}})

    @classmethod
    def tenant_throttled(cls, exc) -> "ApiError":
        """A per-tenant QoS shed (r17 tenancy): the tenant exceeded
        ITS qps/slot quota — same 503 + Retry-After contract as
        executor saturation, but with a structured
        ``tenantThrottled{tenant, quota, kind}`` body so the client
        can tell its own quota from server overload."""
        return cls(str(exc), 503,
                   retry_after=getattr(exc, "retry_after", 1.0),
                   extra={"tenantThrottled": {
                       "tenant": exc.tenant, "quota": exc.quota,
                       "kind": exc.kind}})

    @classmethod
    def storage_fault(cls, exc) -> "ApiError":
        """The storage-integrity contract (r19), applied by the
        request dispatcher to ANY surface a
        :class:`~pilosa_tpu.store.health.StorageFaultError` escapes
        from: ``disk_full`` answers a 507-style structured
        ``writeUnavailable{reason: "disk_full"}`` (the node is
        READ-ONLY; reads keep serving; peers hint the missed copies),
        anything else (quarantined corrupt/io_error fragment) answers
        503 with a structured ``storageFault{path, kind}`` naming the
        sick fragment — storage unavailability is never a generic
        500."""
        kind = getattr(exc, "kind", "unknown")
        retry = getattr(exc, "retry_after", 1.0)
        if kind == "disk_full":
            return cls(str(exc), 507, retry_after=retry,
                       extra={"writeUnavailable": {
                           "op": None, "replica": None,
                           "reason": "disk_full"}})
        return cls(str(exc), 503, retry_after=retry,
                   extra={"storageFault": {
                       "path": getattr(exc, "path", None),
                       "kind": kind}})


def field_options_from_json(o: dict) -> FieldOptions:
    """REST field-options body -> FieldOptions (reference:
    ``http/handler.go`` postFieldRequest decoding)."""
    return FieldOptions(
        type=o.get("type", "set"), keys=o.get("keys", False),
        cache_type=o.get("cacheType", "ranked"),
        cache_size=o.get("cacheSize", 50000),
        time_quantum=o.get("timeQuantum", ""),
        min=o.get("min"), max=o.get("max"), base=o.get("base", 0),
        bit_depth=o.get("bitDepth", 0), scale=o.get("scale", 0),
        epoch=o.get("epoch", ""), time_unit=o.get("timeUnit", "s"),
    )


class API:
    # span trees are materialized only for queries that can be
    # retained: sampled, profiled, or slow-HUNTED — an operator who
    # sets slow_query_threshold at/under this floor is asking for full
    # trees on (nearly) every query and gets them; above it, slow
    # captures carry the root + per-stage breakdown instead (the lite
    # path never builds the tree, which is what restored the r05
    # product/raw ratio)
    SLOW_TRACE_FLOOR = 0.05

    def __init__(self, holder: Holder, executor: Executor | None = None,
                 cluster=None, query_timeout: float = 0.0,
                 trace_sample_rate: float = 0.01,
                 slow_query_threshold: float = 1.0):
        from pilosa_tpu.obs import SlowQueryLog
        self.holder = holder
        self.executor = executor or Executor(holder)
        self.cluster = cluster  # set by the cluster layer when distributed
        self.query_timeout = query_timeout  # seconds; 0 = unlimited
        # always-on sampled tracing: this fraction of ordinary queries
        # is retained in the finished-trace ring without the caller
        # asking (profile=true and slow queries always retain)
        self.trace_sample_rate = min(max(float(trace_sample_rate), 0.0), 1.0)
        # queries slower than this (seconds) are captured — PQL, index,
        # shards, duration, full span tree — in the bounded ring behind
        # GET /debug/slow; 0 disables
        self.slow_query_threshold = float(slow_query_threshold)
        self.slow_log = SlowQueryLog()

    # -- schema -------------------------------------------------------------

    def create_index(self, name: str, options: dict | None = None):
        options = options or {}
        try:
            idx = self.holder.create_index(
                name, keys=options.get("keys", False),
                track_existence=options.get("trackExistence", True))
        except ValueError as e:
            raise ApiError(str(e), 409 if "exists" in str(e) else 400)
        if self.cluster is not None:
            self.cluster.broadcast_schema()
        return idx

    def delete_index(self, name: str, direct: bool = False) -> None:
        try:
            self.holder.delete_index(name)
        except KeyError:
            raise ApiError(f"index {name!r} not found", 404)
        self.executor.planes.invalidate(name)
        self.executor.invalidate_plans(name)
        # the index dir (incl. _keys/) is gone; cached logs must go too
        self.executor.translate.drop(name)
        if self.cluster is not None and not direct:
            self.cluster.broadcast_delete(name, None)

    def create_field(self, index: str, name: str, options: dict | None = None):
        idx = self._index(index)
        try:
            f = idx.create_field(
                name, field_options_from_json(options or {}))
        except ValueError as e:
            raise ApiError(str(e), 409 if "exists" in str(e) else 400)
        if self.cluster is not None:
            self.cluster.broadcast_schema()
        return f

    def delete_field(self, index: str, name: str,
                     direct: bool = False) -> None:
        idx = self._index(index)
        try:
            idx.delete_field(name)
        except KeyError:
            raise ApiError(f"field {name!r} not found", 404)
        self.executor.planes.invalidate(index)
        self.executor.invalidate_plans(index)
        # field delete leaves <index>/_keys/<field>.keys behind: remove
        # it so a recreated field starts with fresh key state
        self.executor.translate.drop(index, name, remove_files=True)
        if self.cluster is not None and not direct:
            self.cluster.broadcast_delete(index, name)

    def schema(self) -> list[dict]:
        return self.holder.schema()

    def apply_schema(self, schema: list[dict]) -> None:
        self.holder.apply_schema(schema)

    # -- query --------------------------------------------------------------

    def query(self, index: str, pql: str,
              shards: list[int] | None = None,
              profile: bool = False,
              timeout: float | None = None) -> dict:
        """``profile=True`` attaches the per-call span tree to the
        response (reference: query ``profile`` option, SURVEY.md §6).
        ``timeout`` (seconds) bounds execution — the deadline analogue
        of upstream's request-context cancellation; expiry answers
        HTTP 504 with a structured ``timeout`` body (elapsed, deadline,
        shards outstanding).  The server's ``query_timeout`` config is a CAP, not
        just a default: per-request values clamp to it (otherwise any
        caller could disable the operator's protection with
        ?timeout=0).

        Tracing identity is always on — every REST response carries
        ``X-Pilosa-Trace-Id`` — but the retention decision is made
        BEFORE any span materializes (r12 hot-path fix; this ordering
        is what keeps the product path at the raw-kernel ceiling):

        - sampled (``trace_sample_rate``), profiled, or slow-HUNTED
          (``slow_query_threshold`` at/under :data:`SLOW_TRACE_FLOOR`)
          queries run under a per-request tracer with a node-tagged
          ``query`` root and the full span tree, RETAINED in the
          process ring (``/internal/traces?trace_id=``);
        - every other query runs under a :class:`LiteTracer`: a trace
          id and per-stage marks, zero span objects — if such a query
          still comes in over ``slow_query_threshold`` it lands in
          ``/debug/slow`` with a root + ``stage.*`` breakdown (its
          PQL, shards and duration intact; full executor trees need
          sampling/profile/floor)."""
        from pilosa_tpu.obs import GLOBAL_TRACER, LiteTracer, Tracer
        from pilosa_tpu.obs.tracing import set_current_trace_id
        self._index(index)
        cap = self.query_timeout
        if timeout is None or timeout == 0:
            timeout = cap
        elif cap:
            timeout = min(timeout, cap)
        deadline = (_time.monotonic() + timeout) if timeout else None
        sampled = (self.trace_sample_rate > 0
                   and random.random() < self.trace_sample_rate)
        # the materialization decision, ahead of ANY span allocation
        trace = (profile or sampled
                 or 0 < self.slow_query_threshold <= self.SLOW_TRACE_FLOOR)
        stats = self.executor.stats
        if not trace:
            tracer = LiteTracer()
            # publish the id as this thread's ACTIVE trace id so log
            # lines emitted while serving join the query's exemplar
            # (one thread-local write — the lite path stays lite)
            set_current_trace_id(tracer.trace_id)
            t0 = _time.perf_counter()
            try:
                out, err = self._run_query(index, pql, shards, tracer,
                                           deadline, timeout, t0)
            finally:
                set_current_trace_id(None)
            duration = _time.perf_counter() - t0
            if (self.slow_query_threshold > 0
                    and duration >= self.slow_query_threshold):
                # slow capture on the lite path: root + stage.*
                # children reconstructed from the timer marks (rare by
                # construction — the threshold is above the floor)
                node = (self.cluster.node_id if self.cluster is not None
                        else "local")
                root = tracer.slow_root("query", duration, index=index,
                                        node=node, liteTrace=True)
                if err is not None:
                    root.tags["error"] = str(err)
                stats.count("slow_query_total", 1)
                self.slow_log.record(self._slow_entry(
                    index, pql, shards, duration, root, err))
                GLOBAL_TRACER.record(root)
                self._log_slow(index, pql, duration, tracer.trace_id)
            if err is not None:
                raise err
            out["traceId"] = tracer.trace_id
            return out
        tracer = Tracer()
        # the fan-out propagates this as the traceparent flags
        # segment: sampled/profiled queries send "01" (peers build +
        # ship their subtree AND keep a ring copy); slow-hunted
        # queries send "02" (build + ship — a slow capture needs the
        # subtrees — but do NOT churn peer rings at serving rate);
        # lite-path queries send "00" and peers skip trees entirely
        tracer.sampled = sampled or profile
        node = (self.cluster.node_id if self.cluster is not None
                else "local")
        t0 = _time.perf_counter()
        with tracer.span("query", index=index, node=node) as root:
            set_current_trace_id(root.trace_id)
            try:
                out, err = self._run_query(index, pql, shards, tracer,
                                           deadline, timeout, t0)
            finally:
                set_current_trace_id(None)
            if err is not None:
                root.tags["error"] = str(err)
        duration = _time.perf_counter() - t0
        # device-time join (r19): the cost ledger's measured device
        # seconds charged to THIS trace land on the profiled query's
        # root — the span tree then shows how much of the wall was
        # device work versus queueing/host time
        dev_s = self.executor.ledger.trace_seconds(root.trace_id)
        if dev_s is not None and dev_s > 0:
            root.tags["deviceSeconds"] = round(dev_s, 6)
            if duration > 0:
                root.tags["deviceShare"] = round(
                    min(1.0, dev_s / duration), 4)
        slow = (self.slow_query_threshold > 0
                and duration >= self.slow_query_threshold)
        if sampled:
            stats.count("trace_sampled_total", 1)
        if slow:
            stats.count("slow_query_total", 1)
            self.slow_log.record(self._slow_entry(
                index, pql, shards, duration, root, err))
            self._log_slow(index, pql, duration, root.trace_id)
        if sampled or slow or profile:
            # publish into the process ring so the trace id resolves
            # via GET /internal/traces?trace_id= after the request
            GLOBAL_TRACER.record(root)
        if err is not None:
            raise err
        out["traceId"] = root.trace_id
        if profile:
            out["profile"] = [s.to_json() for s in tracer.finished()]
        return out

    def _run_query(self, index: str, pql: str, shards, tracer,
                   deadline, timeout, t0) -> tuple[dict, ApiError | None]:
        """Execute + error-classify (shared by the lite and traced
        paths): returns (response dict, ApiError-or-None) — the caller
        owns raise/capture ordering."""
        try:
            if self.cluster is not None:
                return {"results": self.cluster.dist.execute_json(
                    index, pql, shards=shards, tracer=tracer,
                    deadline=deadline)}, None
            results = self.executor.execute(index, pql, shards=shards,
                                            tracer=tracer,
                                            deadline=deadline)
            return {"results": [result_to_json(r) for r in results]}, None
        except QueryTimeoutError as e:
            # a deadline-exceeded query is its own failure class —
            # never a generic 500, and distinct from client errors
            return {}, ApiError.timeout(e, _time.perf_counter() - t0,
                                        timeout)
        except PipelineStalledError as e:
            # a quarantined dispatch-pipeline window (r18): server-side
            # unavailability with a structured body naming the stalled
            # stage — distinct from client errors AND from timeouts
            # (the caller's own budget may not have expired yet)
            return {}, ApiError.pipeline_stall(e)
        except ExecutorSaturatedError as e:
            # admission shedding (VERDICT advice #6): a saturated
            # executor is overload, not a client mistake — 503 with a
            # Retry-After hint, never a generic 500/400
            return {}, ApiError(str(e), 503, retry_after=e.retry_after)
        except WriteUnavailableError as e:
            # a replica-down write refusal (handoff disabled/overflow/
            # no live replica) is unavailability, not a client error:
            # 503 + Retry-After with the structured writeUnavailable
            # body naming the down replica (r13)
            return {}, ApiError.write_unavailable(e)
        except StorageFaultError as e:
            # the storage layer refused (node read-only on disk-full,
            # or the target fragment quarantined): structured 507/503,
            # never a generic 500 (r19)
            return {}, ApiError.storage_fault(e)
        except TenantThrottledError as e:
            # the tenant's OWN quota shed this query (r17): 503 +
            # Retry-After with the structured tenantThrottled body —
            # never the generic 400 below (it is not a client mistake)
            # and never confusable with whole-server saturation
            return {}, ApiError.tenant_throttled(e)
        except (ParseError, ExecutionError) as e:
            return {}, ApiError(str(e), 400)

    def _slow_entry(self, index: str, pql: str, shards, duration: float,
                    root, err) -> dict:
        return {
            "ts": _time.time(), "index": index,
            "pql": pql if len(pql) <= 4096 else pql[:4096] + "…",
            "shards": list(shards) if shards is not None else None,
            "durationMs": round(duration * 1e3, 3),
            "traceId": root.trace_id,
            # which path answered (r19 satellite): fused /
            # op-at-a-time fallback / paged / row-directory oracle /
            # degraded governor — the first triage question for any
            # slow entry is "was this even on the fast path"
            "path": self.executor.serving_path(),
            "error": str(err) if err is not None else None,
            "profile": root.to_json()}

    def _log_slow(self, index: str, pql: str, duration: float,
                  trace_id: str) -> None:
        """One WARNING log line per slow-query capture, carrying the
        query's trace id as a record attribute (the JSON formatter
        emits it as ``traceId``): the correlated-logs leg of the
        observability pane — a p99 bucket's exemplar, the retained
        trace at ``/internal/traces?trace_id=``, and this line join on
        one id."""
        logging.getLogger("pilosa_tpu.api").warning(
            "slow query %.3fs index=%s pql=%s",
            duration, index, pql if len(pql) <= 200 else pql[:200] + "…",
            extra={"traceId": trace_id})

    # -- imports ------------------------------------------------------------

    def import_bits(self, index: str, field: str, *,
                    row_ids=None, col_ids=None, row_keys=None, col_keys=None,
                    timestamps=None, clear: bool = False,
                    direct: bool = False, op_id: str | None = None) -> int:
        """Bulk bit import (reference: ``API.Import``): ID or key form;
        timestamps are epoch-seconds or ISO strings.  In cluster mode
        batches route through the breaker-aware bulk-import coordinator
        (:class:`pilosa_tpu.ingest.BulkImporter` — hinted handoff and
        op-id dedup cover bulk ops, r15); ``direct`` marks an
        already-routed forwarded batch, ``op_id`` its dedup identity
        (a re-delivered batch is a no-op).  Local applies are
        oplog-batched: one fsync-coalesced append per batch per
        fragment, counted on ``ingest_bits_total`` and timed on
        ``import_batch_seconds``."""
        t0 = _time.perf_counter()
        idx = self._index(index)
        f = idx.field(field)
        if f is None:
            raise ApiError(f"field {field!r} not found", 404)
        rows = self._translate_rows(idx, f, row_ids, row_keys, direct)
        cols = self._translate_cols(idx, col_ids, col_keys, direct)
        if len(rows) != len(cols):
            raise ApiError("rows and columns length mismatch")
        stats = self.executor.stats
        if self.cluster is not None and not direct:
            changed = self._bulk().import_bits(index, field, rows, cols,
                                               timestamps, clear)
            stats.observe("import_batch_seconds",
                          _time.perf_counter() - t0)
            return changed
        if (op_id is not None and self.cluster is not None
                and op_id in self.cluster.applied_ops):
            return 0  # duplicate delivery (retry / replayed hint)
        ts = self._parse_timestamps(timestamps, len(cols))
        from pilosa_tpu.store.oplog import SyncBatch
        sb = SyncBatch()
        if clear:
            changed = f.clear_import(rows, cols, sync_batch=sb)
        else:
            changed = f.import_bits(rows, cols, ts, sync_batch=sb)
            idx.note_columns(cols)
        sb.flush()
        if op_id is not None and self.cluster is not None:
            self.cluster.applied_ops.add(op_id)
        if changed:
            stats.count("ingest_bits_total", changed)
        stats.observe("import_batch_seconds", _time.perf_counter() - t0)
        return changed

    def _bulk(self):
        """The cluster bulk-import coordinator (lazy: the cluster is
        attached after construction)."""
        bulk = getattr(self, "_bulk_importer", None)
        if bulk is None or bulk.cluster is not self.cluster:
            from pilosa_tpu.ingest import BulkImporter
            bulk = self._bulk_importer = BulkImporter(self, self.cluster)
        return bulk

    def import_values(self, index: str, field: str, *,
                      col_ids=None, col_keys=None, values=None,
                      direct: bool = False) -> int:
        idx = self._index(index)
        f = idx.field(field)
        if f is None:
            raise ApiError(f"field {field!r} not found", 404)
        if f.options.type not in BSI_TYPES:
            raise ApiError(f"field {field!r} is not an int field")
        cols = self._translate_cols(idx, col_ids, col_keys, direct)
        if values is None or len(values) != len(cols):
            raise ApiError("columns and values length mismatch")
        if self.cluster is not None and not direct:
            return self._route_import_values(index, field, cols, values)
        try:
            changed = f.import_values(cols, values)
        except ValueError as e:
            raise ApiError(str(e))
        idx.note_columns(cols)
        return changed

    def _route_to_owners(self, index: str, shard: int, local_fn,
                         remote_fn) -> int:
        """Apply a write on every replica owner of a shard; returns the
        primary's changed count (reference: ``API.Import`` routing to
        shard-owning nodes, SURVEY.md §4.5).  ``local_fn()`` applies
        locally; ``remote_fn(client)`` forwards with the direct flag."""
        primary_changed = None
        for owner in self.cluster.shard_owners(index, shard):
            if owner == self.cluster.node_id:
                got = local_fn()
            else:
                got = remote_fn(self.cluster._client(owner))
            if primary_changed is None:
                primary_changed = got
        return primary_changed or 0

    @staticmethod
    def _proto_or_json_forward(path: str, encode, json_body):
        """Forwarded import batches ride the protobuf wire (packed
        varint id arrays, SURVEY.md §3.3 internal proto), encoded
        LAZILY on the first remote owner — all-local routing
        (single-node clusters, owner-local shards) must not pay the
        encode.  Inputs the codec refuses (heterogeneous timestamps,
        out-of-int64 values: ValueError) fall back to JSON, which
        allows them."""
        from pilosa_tpu.api import proto
        cache: list = []

        def remote(client):
            if not cache:
                try:
                    cache.append((encode(), True))
                except ValueError:
                    cache.append((None, False))
            body, is_proto = cache[0]
            if is_proto:
                return client._do(
                    "POST", path, body, content_type=proto.CONTENT_TYPE,
                    headers={"X-Pilosa-Direct": "1"})["changed"]
            return client._json("POST", path, json_body(),
                                headers={"X-Pilosa-Direct": "1"})["changed"]
        return remote

    def _route_import_values(self, index: str, field: str, cols,
                             values) -> int:
        from pilosa_tpu.api import proto
        shards = cols // np.uint64(SHARD_WIDTH)
        changed = 0
        for shard in np.unique(shards):
            m = shards == shard
            sub_cols = [int(c) for c in cols[m]]
            sub_vals = [values[i] for i in np.nonzero(m)[0]]
            remote = self._proto_or_json_forward(
                f"/index/{index}/field/{field}/importValue",
                lambda: proto.encode_import_value_request(
                    col_ids=sub_cols, values=sub_vals),
                lambda: {"columnIDs": sub_cols, "values": sub_vals})
            changed += self._route_to_owners(
                index, int(shard),
                lambda: self.import_values(
                    index, field, col_ids=sub_cols, values=sub_vals,
                    direct=True),
                remote)
        return changed

    def import_roaring(self, index: str, field: str, shard: int, blob: bytes,
                       view: str = VIEW_STANDARD, clear: bool = False,
                       direct: bool = False,
                       op_id: str | None = None) -> int:
        """Pre-encoded roaring import — the bulk-loader fast path
        (reference: ``API.ImportRoaring``, SURVEY.md §4.5).  Cluster
        routing, op-id dedup and fsync coalescing mirror
        :meth:`import_bits` (r15)."""
        t0 = _time.perf_counter()
        idx = self._index(index)
        f = idx.field(field)
        if f is None:
            raise ApiError(f"field {field!r} not found", 404)
        if f.options.type not in ("set", "time"):
            # raw fragment unions skip field-type semantics (mutex
            # last-write-wins, bool row validation, BSI encoding) —
            # same restriction as upstream API.ImportRoaring
            raise ApiError(
                "import-roaring supports set/time fields, not "
                f"{f.options.type!r}; use the pair import", 400)
        stats = self.executor.stats
        if self.cluster is not None and not direct:
            changed = self._bulk().import_roaring(index, field, shard,
                                                  blob, view, clear)
            stats.observe("import_batch_seconds",
                          _time.perf_counter() - t0)
            return changed
        if (op_id is not None and self.cluster is not None
                and op_id in self.cluster.applied_ops):
            return 0  # duplicate delivery (retry / replayed hint)
        from pilosa_tpu.store.oplog import SyncBatch
        sb = SyncBatch()
        frag = f.view(view, create=True).fragment(shard, create=True)
        try:
            changed = f_changed = frag.import_roaring(blob, clear=clear,
                                                      sync_batch=sb)
        except ValueError as e:
            raise ApiError(f"bad roaring payload: {e}")
        if f_changed and idx.track_existence and not clear:
            from pilosa_tpu.store import roaring as rc
            positions = rc.deserialize(blob)
            cols = (np.unique(positions % np.uint64(SHARD_WIDTH))
                    + np.uint64(shard * SHARD_WIDTH))
            idx.note_columns(cols)
        sb.flush()
        if op_id is not None and self.cluster is not None:
            self.cluster.applied_ops.add(op_id)
        if changed:
            stats.count("ingest_bits_total", changed)
        stats.observe("import_batch_seconds", _time.perf_counter() - t0)
        return changed

    # -- export -------------------------------------------------------------

    def export_csv(self, index: str, field: str) -> str:
        """CSV of (row,col) pairs (reference: ``API.ExportCSV``), keys
        translated when the index/field is keyed."""
        idx = self._index(index)
        f = idx.field(field)
        if f is None:
            raise ApiError(f"field {field!r} not found", 404)
        out = io.StringIO()
        col_log = (self.executor.translate.columns(index)
                   if idx.keys else None)

        def col_repr(c: int):
            return col_log.key_of(int(c)) if col_log else int(c)

        if f.options.type in BSI_TYPES:
            # BSI export: one "column,value" line per non-null column
            # (reference: ExportCSV over int fields)
            from pilosa_tpu.engine.bsi import (EXISTS_ROW, OFFSET_ROW,
                                               SIGN_ROW)
            view = f.bsi_view()
            if view is not None:
                for shard in sorted(view.fragments):
                    frag = view.fragment(shard)
                    exists = frag.row(EXISTS_ROW).columns()
                    if len(exists) == 0:
                        continue
                    vals = np.zeros(len(exists), dtype=np.int64)
                    for b in range(f.options.bit_depth):
                        hit = np.isin(exists,
                                      frag.row(OFFSET_ROW + b).columns())
                        vals[hit] += 1 << b
                    neg = np.isin(exists, frag.row(SIGN_ROW).columns())
                    vals[neg] = -vals[neg]
                    vals += f.options.base
                    base_col = np.uint64(shard * SHARD_WIDTH)
                    for c, v in zip(exists, vals):
                        out.write(f"{col_repr(int(c) + int(base_col))},"
                                  f"{f.from_stored(int(v))}\n")
            return out.getvalue()

        row_log = (self.executor.translate.rows(index, field)
                   if f.options.keys else None)
        view = f.standard_view()
        if view is not None:
            for shard in sorted(view.fragments):
                frag = view.fragment(shard)
                for r in frag.row_ids():
                    cols = frag.row(r).columns().astype(np.uint64) + \
                        np.uint64(shard * SHARD_WIDTH)
                    rkey = row_log.key_of(r) if row_log else r
                    for c in cols:
                        out.write(f"{rkey},{col_repr(int(c))}\n")
        return out.getvalue()

    # -- backup / restore ---------------------------------------------------

    def backup_tar(self) -> bytes:
        """Consistent tar of the data dir (reference: ``ctl/backup``):
        snapshot every open fragment so snapshots subsume op-logs, then
        tar snapshot + meta + key files."""
        import tarfile
        for idx in self.holder.indexes.values():
            for f in idx.fields.values():
                for v in f.views.values():
                    for frag in v.fragments.values():
                        if frag.op_n > 0:
                            frag.snapshot()
        buf = io.BytesIO()
        with tarfile.open(fileobj=buf, mode="w") as tar:
            tar.add(self.holder.path, arcname="data",
                    filter=lambda ti: None if ti.name.endswith(".oplog")
                    else ti)
        return buf.getvalue()

    def restore_tar(self, blob: bytes) -> None:
        """Restore a backup tar into the data dir and reopen the holder.
        Refuses when indexes already exist (as upstream restore does)."""
        import tarfile
        if self.holder.indexes:
            raise ApiError("restore requires an empty holder", 409)
        buf = io.BytesIO(blob)
        with tarfile.open(fileobj=buf) as tar:
            for member in tar.getmembers():
                name = member.name
                if not name.startswith("data/") and name != "data":
                    raise ApiError(f"unexpected tar entry {name!r}")
            import tempfile
            with tempfile.TemporaryDirectory() as tmp:
                tar.extractall(tmp, filter="data")
                import shutil
                src = f"{tmp}/data"
                for entry in sorted(os.listdir(src)):
                    shutil.move(f"{src}/{entry}",
                                f"{self.holder.path}/{entry}")
        self.holder.close()
        self.holder.open()
        self.executor.planes.invalidate()
        self.executor.invalidate_plans()
        self.executor.translate.close()

    # -- introspection ------------------------------------------------------

    def storage_stats(self) -> dict:
        """Aggregate storage footprint: fragment count, op-log bytes
        (un-compacted write-ahead growth) and snapshot bytes.  Cheap
        (stat calls only); the ``/metrics`` gauges and the ``/status``
        storage block both read this."""
        frags = oplog = snap = 0
        for idx in list(self.holder.indexes.values()):
            for f in list(idx.fields.values()):
                for v in list(f.views.values()):
                    for frag in list(v.fragments.values()):
                        frags += 1
                        try:
                            oplog += os.path.getsize(frag._oplog.path)
                        except OSError:
                            pass
                        try:
                            snap += os.path.getsize(frag.path)
                        except OSError:
                            pass
        return {"fragmentCount": frags, "oplogBytes": oplog,
                "snapshotBytes": snap}

    def status(self) -> dict:
        import jax
        devices = [{"id": d.id, "platform": d.platform, "kind": d.device_kind}
                   for d in jax.devices()]
        state = "NORMAL"
        nodes = [{"id": "local", "uri": "", "state": state, "isPrimary": True}]
        cluster_health = None
        write_health = None
        if self.cluster is not None:
            nodes = self.cluster.nodes_status()
            state = self.cluster.state
            # serving-through-failure visibility: per-peer last-seen
            # age, suspect verdict, breaker state
            cluster_health = self.cluster.health_payload()
            # writes-through-failure visibility (r13): hint backlog,
            # oldest age vs the hint_max_age bound, per-peer drains
            write_health = self.cluster.write_health_payload()
        ex = self.executor
        snap_counters = ex.stats.snapshot()["counters"]
        shed = snap_counters.get("query_shed_total", {})
        pc = ex.planes.stats()
        delta = pc.get("delta", {})
        ingested = snap_counters.get("ingest_bits_total", {})
        # storage-integrity pane (r19): disk governor state, the
        # quarantine registry, scrub progress, last replica repair
        storage_health = None
        sh = getattr(self.holder, "storage_health", None)
        if sh is not None:
            storage_health = sh.payload()
            scrubber = getattr(self, "scrubber", None)
            if scrubber is not None:
                storage_health["scrub"] = scrubber.payload()
        return {"state": state, "nodes": nodes,
                **({"storageHealth": storage_health}
                   if storage_health is not None else {}),
                # ingest visibility (r15): device delta overlays
                # (fill %, compaction backlog + last duration) and
                # bulk-import volume — the mixed read/write serving
                # pane (bench/config26)
                "ingest": {
                    "deltaFillRatio": delta.get("deltaFillRatio", 0.0),
                    "deltaCells": delta.get("deltaCells", 0),
                    "deltaCap": delta.get("deltaCap", 0),
                    "deltaOverlayBits": delta.get("deltaOverlayBits", 0),
                    "absorbs": delta.get("absorbs", 0),
                    "compactions": delta.get("compactions", 0),
                    "pendingCompactions": delta.get(
                        "pendingCompactions", 0),
                    "lastCompactionSeconds": delta.get(
                        "lastCompactionSeconds", 0.0),
                    "importedBits": int(sum(ingested.values())),
                    "importBatch": ex.stats.histogram_summary(
                        "import_batch_seconds")},
                # self-healing pipeline visibility (r18): governor
                # state (healthy/degraded/probing), watchdog knob,
                # quarantine counts — the serving-through-a-sick-device
                # pane (bench/config28)
                "deviceHealth": ex.device_health(),
                # mesh serving (ISSUE 16): device count, shard axis,
                # per-device resident plane bytes, padded shards —
                # only present when a placement is wired
                **({"mesh": mesh_block}
                   if (mesh_block := ex.mesh_status()) is not None
                   else {}),
                **({"clusterHealth": cluster_health}
                   if cluster_health is not None else {}),
                **({"writeHealth": write_health}
                   if write_health is not None else {}),
                "localShardCount": sum(len(i.available_shards())
                                       for i in self.holder.indexes.values()),
                "devices": devices,
                # admission/shedding visibility: current slot occupancy,
                # the cap, total sheds, and the queue-wait distribution
                "admission": {
                    "slotsInUse": ex.slots_in_use,
                    "maxConcurrent": ex.max_concurrent,
                    "shedTotal": int(sum(shed.values())),
                    "queueWait": ex.stats.histogram_summary(
                        "query_queue_wait_seconds")},
                # on-disk footprint: what backup archives and the
                # snapshot queue compacts (oplogBytes growth = log
                # compaction falling behind), plus the plane-build
                # pipeline's health (r10): cold-build volume, failures
                # (a wedged background build is otherwise invisible),
                # and the dense-sidecar warm cache's hit ratio
                "storage": {
                    **self.storage_stats(),
                    "planeBuild": {
                        k: pc[k]
                        for k in ("builds", "buildSeconds", "buildBytes",
                                  "buildFailures", "warmHits",
                                  "warmMisses", "meshed")}},
                # slow-query visibility: ring totals + the configured
                # threshold (full records behind GET /debug/slow)
                "slowQueries": {
                    **self.slow_log.summary(),
                    "thresholdSeconds": self.slow_query_threshold},
                # HBM working set (reference: /status occupancy; the
                # device plane cache is the resident working set here)
                "planeCache": pc,
                # multi-tenant economy (r17): paging state, per-tenant
                # residency/hit-ratio/page-ins/sheds, QoS quotas,
                # eviction reasons
                "tenancy": ex.tenancy_status(),
                # device-cost ledger (r19): measured device seconds /
                # bytes scanned attributed per tenant, per query
                # shape, per plane (top-K + other), compile totals
                "costs": ex.cost_status(),
                # time-view planes (r23): which time fields serve range
                # queries from a resident bucketed plane (device speed)
                # vs the span-union fallback
                "timeViews": ex.time_status(),
                # per-stage overhead attribution (parse/plan/admit/
                # dispatch/read/assemble) — the diagnostics dump behind
                # bench/config18's concurrency-gap breakdown
                "queryStages": self.executor.stats.histogram_summary(
                    "query_stage_seconds")}

    def info(self) -> dict:
        import os
        return {"shardWidth": SHARD_WIDTH,
                "cpuPhysicalCores": os.cpu_count(),
                "memory": _total_memory_bytes()}

    # -- internal -----------------------------------------------------------

    def _index(self, name: str):
        idx = self.holder.index(name)
        if idx is None:
            raise ApiError(f"index {name!r} not found", 404)
        return idx

    def _translate_rows(self, idx, f, row_ids, row_keys,
                        direct: bool = False) -> np.ndarray:
        if row_keys is not None:
            if not f.options.keys:
                raise ApiError(f"field {f.name!r} is not keyed")
            if self.cluster is not None:
                ids = self.cluster.translate_keys(idx.name, f.name,
                                                  list(row_keys), create=True)
                return np.array(ids, dtype=np.uint64)
            log = self.executor.translate.rows(idx.name, f.name)
            return np.array(log.translate(list(row_keys), create=True),
                            dtype=np.uint64)
        if row_ids is None:
            raise ApiError("missing rowIDs/rowKeys")
        if f.options.keys and not direct:
            # forwarded cluster batches (direct) are pre-translated IDs
            raise ApiError(f"field {f.name!r} is keyed; use rowKeys")
        return np.asarray(row_ids, dtype=np.uint64)

    def _translate_cols(self, idx, col_ids, col_keys,
                        direct: bool = False) -> np.ndarray:
        if col_keys is not None:
            if not idx.keys:
                raise ApiError(f"index {idx.name!r} is not keyed")
            if self.cluster is not None:
                ids = self.cluster.translate_keys(idx.name, None,
                                                  list(col_keys), create=True)
                return np.array(ids, dtype=np.uint64)
            log = self.executor.translate.columns(idx.name)
            return np.array(log.translate(list(col_keys), create=True),
                            dtype=np.uint64)
        if col_ids is None:
            raise ApiError("missing columnIDs/columnKeys")
        if idx.keys and not direct:
            raise ApiError(f"index {idx.name!r} is keyed; use columnKeys")
        return np.asarray(col_ids, dtype=np.uint64)

    @staticmethod
    def _parse_timestamps(timestamps, n: int):
        if timestamps is None:
            return None
        out = []
        for t in timestamps:
            if t in (None, 0, ""):
                out.append(None)
            elif isinstance(t, str):
                from pilosa_tpu.store.timeq import parse_pql_time
                out.append(parse_pql_time(t))
            else:
                out.append(datetime.utcfromtimestamp(int(t)))
        return out


def _total_memory_bytes() -> int:
    try:
        with open("/proc/meminfo") as f:
            for line in f:
                if line.startswith("MemTotal:"):
                    return int(line.split()[1]) * 1024
    except OSError:
        pass
    return 0
