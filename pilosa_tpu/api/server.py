"""REST server over the API façade.

Reference: ``http/handler.go`` (SURVEY.md §3.3).  Routes (same
surface; query and import endpoints content-negotiate JSON or
``application/x-protobuf`` per ``api/internal.proto``):

    POST   /index/{i}/query                     PQL body -> {"results": [...]}
    POST   /index/{i}                           create index
    DELETE /index/{i}
    POST   /index/{i}/field/{f}                 create field
    DELETE /index/{i}/field/{f}
    POST   /index/{i}/field/{f}/import          bulk bits (JSON|proto)
    POST   /index/{i}/field/{f}/importValue     bulk values (JSON|proto)
    POST   /index/{i}/field/{f}/import-roaring/{shard}   binary roaring
    GET    /export?index=i&field=f              CSV
    GET    /schema | /status | /info | /version | /metrics
    GET    /metrics/cluster | /status/cluster   fleet fan-in (one scrape
                                                sees every live node)
    POST   /internal/*                          node-to-node (cluster layer)

Implementation is stdlib ``ThreadingHTTPServer`` — the control plane is
host-side Python; all data-plane math stays on device.
"""

from __future__ import annotations

import json
import re
import threading
import time
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from pilosa_tpu import __version__, fault
from pilosa_tpu.api.api import API, ApiError
from pilosa_tpu.store.health import StorageFaultError as _StorageFaultError


def parse_timeout_param(raw: str) -> float:
    """Validate a ``?timeout=`` value (public and internal handlers
    share one rule set): NaN would poison every deadline comparison
    into False (silently unlimited); negatives are nonsense — 400 on
    both.  0 falls back to the server's query-timeout cap (unlimited
    only when no cap is configured) — API.query clamps every request
    to the cap by design."""
    import math
    try:
        timeout = float(raw)
    except ValueError:
        timeout = None
    if timeout is None or not math.isfinite(timeout) or timeout < 0:
        raise ApiError(f"bad timeout param {raw!r}")
    return timeout


# /debug/profile capture bounds: a capture shorter than the profiler's
# startup cost is noise; one longer than a minute holds the device
# profiler (and the handler thread) hostage
PROFILE_SECONDS_MIN = 0.1
PROFILE_SECONDS_MAX = 60.0


def clamp_profile_seconds(seconds: float) -> float:
    """Clamp a ``?seconds=`` jax-profiler capture window to
    [PROFILE_SECONDS_MIN, PROFILE_SECONDS_MAX]."""
    return min(max(seconds, PROFILE_SECONDS_MIN), PROFILE_SECONDS_MAX)


class Router:
    def __init__(self):
        self.routes: list[tuple[str, re.Pattern, object]] = []

    def add(self, method: str, pattern: str, fn) -> None:
        # '{name}' segments -> named groups
        regex = re.sub(r"\{(\w+)\}", r"(?P<\1>[^/]+)", pattern)
        self.routes.append((method, re.compile("^" + regex + "$"), fn))

    def match(self, method: str, path: str):
        for m, rx, fn in self.routes:
            if m != method:
                continue
            hit = rx.match(path)
            if hit:
                return fn, hit.groupdict()
        return None, None


class Handler(BaseHTTPRequestHandler):
    """One instance per request; server state lives on ``self.server``."""

    protocol_version = "HTTP/1.1"
    server_version = "pilosa-tpu/" + __version__
    # socket read timeout (StreamRequestHandler applies it per
    # connection): reclaims handler threads from clients that stall
    # mid-handshake or idle forever without closing
    timeout = 120
    # TCP_NODELAY on every accepted connection (StreamRequestHandler
    # applies it in setup()): with keep-alive clients the response's
    # small writes otherwise collide with Nagle + the peer's delayed
    # ACK — a measured ~40 ms stall per RPC on loopback (one-shot
    # connections never showed it because close() flushes immediately)
    disable_nagle_algorithm = True

    # -- plumbing ------------------------------------------------------------

    def log_message(self, fmt, *args):  # route through our logger
        logger = getattr(self.server, "logger", None)
        if logger is not None:
            logger.debug("http: " + fmt % args)

    def _body(self) -> bytes:
        # read-once, cached: _dispatch drains the body for EVERY
        # request — a handler that replies without reading it would
        # otherwise leave the bytes in the keep-alive stream, where
        # they prefix the NEXT request's method line (seen in r5 as
        # 501 "Unsupported method ('{}GET')" corrupting the peer's
        # shard-universe fetch; one-shot connections masked the class)
        if not hasattr(self, "_body_cache"):
            n = int(self.headers.get("Content-Length") or 0)
            self._body_cache = self.rfile.read(n) if n else b""
        return self._body_cache

    def _json_body(self) -> dict:
        raw = self._body()
        if not raw:
            return {}
        try:
            return json.loads(raw)
        except json.JSONDecodeError as e:
            raise ApiError(f"invalid JSON body: {e}")

    def _reply(self, obj, status: int = 200,
               content_type: str = "application/json",
               headers: dict | None = None) -> None:
        if getattr(self, "_fault_drop_response", False):
            # drop-response failpoint: the handler RAN (state mutated,
            # side effects happened) but the peer never hears back —
            # its retry is a duplicate delivery.  Severing the
            # connection makes the client see a reset, not a timeout.
            self.close_connection = True
            return
        data = (obj if isinstance(obj, bytes)
                else json.dumps(obj).encode())
        self.send_response(status)
        self.send_header("Content-Type", content_type)
        for k, v in (headers or {}).items():
            self.send_header(k, v)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def _dispatch(self, method: str) -> None:
        parsed = urllib.parse.urlparse(self.path)
        self.query = urllib.parse.parse_qs(parsed.query)
        # one handler instance serves every request on a keep-alive
        # connection: reset, then always drain (see _body)
        self.__dict__.pop("_body_cache", None)
        if "chunked" in (self.headers.get("Transfer-Encoding")
                         or "").lower():
            # the drain below only understands Content-Length; an
            # undrained chunked payload would corrupt the keep-alive
            # stream, so refuse and drop the connection
            self.close_connection = True
            self._reply({"error": "chunked transfer encoding not "
                                  "supported; send Content-Length"}, 411)
            return
        self._body()
        fn, params = self.server.router.match(method, parsed.path)
        srv = self.server
        self._fault_drop_response = False
        if fault.ACTIVE and fn is not None:
            spec = fault.fire("server.response", method=method,
                              path=parsed.path)
            if spec is not None and spec["action"] == "drop_response":
                self._fault_drop_response = True
        t0 = time.perf_counter()
        code = 200
        try:
            if fn is None:
                code = 404
                self._reply({"error": f"no route {method} {parsed.path}"}, 404)
                return
            fn(self, **params)
        except (ApiError, _StorageFaultError) as e:
            if isinstance(e, _StorageFaultError):
                # storage-integrity refusal (r19) escaping ANY handler
                # — import endpoints, hint replay, fragment merge,
                # internal query: map it once to the structured
                # 507/503 shape instead of a generic 500, then share
                # the ApiError reply path
                e = ApiError.storage_fault(e)
            code = e.status
            hdrs = None
            if e.retry_after is not None:
                # 503 shedding: tell well-behaved clients when to come
                # back instead of letting them hammer the queue
                hdrs = {"Retry-After": str(max(1, int(e.retry_after)))}
            # structured error fields (e.g. the 504 timeout block) ride
            # the body next to "error"
            self._reply({"error": str(e), **(e.extra or {})}, e.status,
                        headers=hdrs)
        except BrokenPipeError:
            code = 499
        except Exception as e:  # noqa: BLE001 — server must not die
            code = 500
            if getattr(srv, "logger", None):
                srv.logger.exception("http 500: %s %s", method, parsed.path)
            try:
                self._reply({"error": f"internal error: {e}"}, 500)
            except BrokenPipeError:
                pass
        finally:
            stats = getattr(srv, "stats", None)
            if stats is not None:
                stats.count("http_requests_total", 1,
                            method=method, status=str(code))
                stats.observe("http_request_seconds",
                              time.perf_counter() - t0, method=method)

    def do_GET(self):
        self._dispatch("GET")

    def do_POST(self):
        self._dispatch("POST")

    def do_DELETE(self):
        self._dispatch("DELETE")

    # -- handlers -------------------------------------------------------------

    def h_query(self, index: str) -> None:
        # content negotiation (reference: http/handler.go JSON/protobuf):
        # Content-Type picks the request decoding, Accept the response
        from pilosa_tpu.api import proto
        body = self._body()
        want_proto = proto.CONTENT_TYPE in (self.headers.get("Accept") or "")
        if proto.CONTENT_TYPE in (self.headers.get("Content-Type") or ""):
            try:
                pql, shards = proto.decode_query_request(body)
            except ValueError as e:
                raise ApiError(f"bad protobuf request: {e}")
        else:
            pql = body.decode()
            shards = None
        if "shards" in self.query:
            try:
                shards = [int(s) for s in
                          self.query["shards"][0].split(",") if s]
            except ValueError:
                raise ApiError(f"bad shards param "
                               f"{self.query['shards'][0]!r}")
        profile = "profile" in self.query
        timeout = None
        if "timeout" in self.query:
            timeout = parse_timeout_param(self.query["timeout"][0])
        if not want_proto:
            out = self.server.api.query(index, pql, shards=shards,
                                        profile=profile,
                                        timeout=timeout)
            # the per-request trace identity rides a header, not the
            # body (resolvable via /internal/traces?trace_id=)
            tid = out.pop("traceId", None)
            self._reply(out, headers={"X-Pilosa-Trace-Id": tid}
                        if tid else None)
            return
        if profile:
            # QueryResponse has no profile field; fail loudly rather
            # than silently dropping the span tree the caller asked for
            # (pinned by tests/test_proto.py; documented in the README
            # observability runbook — use the JSON surface to profile)
            raise ApiError("?profile is not supported with "
                           "application/x-protobuf responses")
        # errors keep the proto body (so the caller can decode them) but
        # carry the same HTTP status the JSON surface would — status-code
        # behavior must not diverge by content type
        status = 200
        trace_id = None
        try:
            res = self.server.api.query(index, pql, shards=shards,
                                        timeout=timeout)
        except ApiError as e:
            raw = proto.encode_query_response(err=str(e))
            status = e.status
        else:
            trace_id = res.pop("traceId", None)
            try:
                raw = proto.encode_query_response(res["results"])
            except ValueError as e:  # result shape has no proto encoding
                # a client error (asked for proto on an Extract), and
                # answered IN proto so the caller can decode it
                raw = proto.encode_query_response(err=str(e))
                status = 400
        self._reply(raw, status=status, content_type=proto.CONTENT_TYPE,
                    headers={"X-Pilosa-Trace-Id": trace_id}
                    if trace_id else None)

    def h_create_index(self, index: str) -> None:
        body = self._json_body()
        self.server.api.create_index(index, body.get("options"))
        self._reply({"success": True})

    def h_delete_index(self, index: str) -> None:
        self.server.api.delete_index(index)
        self._reply({"success": True})

    def h_create_field(self, index: str, field: str) -> None:
        body = self._json_body()
        self.server.api.create_field(index, field, body.get("options"))
        self._reply({"success": True})

    def h_delete_field(self, index: str, field: str) -> None:
        self.server.api.delete_field(index, field)
        self._reply({"success": True})

    @property
    def _direct(self) -> bool:
        """Forwarded-batch marker: skip cluster re-routing."""
        return self.headers.get("X-Pilosa-Direct") == "1"

    @property
    def _op_id(self) -> str | None:
        """Bulk-op dedup identity (r15): forwarded import batches
        carry it so duplicate delivery — internode retries, replayed
        hints — is a no-op against the durable IdWindow."""
        return self.headers.get("X-Pilosa-Op-Id") or None

    def h_import(self, index: str, field: str) -> None:
        # content negotiation like the query endpoint: protobuf bodies
        # carry 100k-batch id arrays at a fraction of the JSON
        # encode/decode cost (reference: internal/internal.proto
        # ImportRequest on the import + internal wire)
        from pilosa_tpu.api import proto
        if proto.CONTENT_TYPE in (self.headers.get("Content-Type") or ""):
            try:
                b = proto.decode_import_request(self._body())
            except ValueError as e:
                raise ApiError(f"bad protobuf import: {e}")
            kw = dict(row_ids=b["row_ids"], col_ids=b["col_ids"],
                      row_keys=b["row_keys"], col_keys=b["col_keys"],
                      timestamps=b["timestamps"],
                      clear=b["clear"] or "clear" in self.query)
        else:
            b = self._json_body()
            kw = dict(row_ids=b.get("rowIDs"), col_ids=b.get("columnIDs"),
                      row_keys=b.get("rowKeys"),
                      col_keys=b.get("columnKeys"),
                      timestamps=b.get("timestamps"),
                      clear=b.get("clear", False) or "clear" in self.query)
        changed = self.server.api.import_bits(index, field,
                                              direct=self._direct,
                                              op_id=self._op_id, **kw)
        self._reply_import(changed)

    def h_import_value(self, index: str, field: str) -> None:
        from pilosa_tpu.api import proto
        if proto.CONTENT_TYPE in (self.headers.get("Content-Type") or ""):
            try:
                b = proto.decode_import_value_request(self._body())
            except ValueError as e:
                raise ApiError(f"bad protobuf import: {e}")
            kw = dict(col_ids=b["col_ids"], col_keys=b["col_keys"],
                      values=b["values"])
        else:
            b = self._json_body()
            kw = dict(col_ids=b.get("columnIDs"),
                      col_keys=b.get("columnKeys"), values=b.get("values"))
        changed = self.server.api.import_values(index, field,
                                                direct=self._direct, **kw)
        self._reply_import(changed)

    def _reply_import(self, changed: int) -> None:
        from pilosa_tpu.api import proto
        if proto.CONTENT_TYPE in (self.headers.get("Accept") or ""):
            self._reply(proto.encode_import_response(changed),
                        content_type=proto.CONTENT_TYPE)
        else:
            self._reply({"changed": changed})

    def h_import_roaring(self, index: str, field: str, shard: str) -> None:
        view = self.query.get("view", ["standard"])[0]
        clear = "clear" in self.query
        changed = self.server.api.import_roaring(
            index, field, int(shard), self._body(), view=view, clear=clear,
            direct=self._direct, op_id=self._op_id)
        self._reply({"changed": changed})

    def h_export(self) -> None:
        index = self.query.get("index", [None])[0]
        field = self.query.get("field", [None])[0]
        if not index or not field:
            raise ApiError("export requires ?index= and ?field=")
        csv = self.server.api.export_csv(index, field)
        self._reply(csv.encode(), content_type="text/csv")

    def h_schema(self) -> None:
        self._reply({"indexes": self.server.api.schema()})

    def h_get_index(self, index: str) -> None:
        for spec in self.server.api.schema():
            if spec["name"] == index:
                self._reply(spec)
                return
        raise ApiError(f"index {index!r} not found", 404)

    def h_get_field(self, index: str, field: str) -> None:
        for spec in self.server.api.schema():
            if spec["name"] == index:
                for f in spec["fields"]:
                    if f["name"] == field:
                        self._reply(f)
                        return
                raise ApiError(f"field {field!r} not found", 404)
        raise ApiError(f"index {index!r} not found", 404)

    def h_status(self) -> None:
        self._reply(self.server.api.status())

    def h_info(self) -> None:
        self._reply(self.server.api.info())

    def h_version(self) -> None:
        self._reply({"version": __version__})

    def _refresh_scrape_gauges(self) -> None:
        """Refresh point-in-time gauges at scrape time — shared by
        ``/metrics``, ``/internal/metrics/snapshot`` (each node
        refreshes before answering the cluster fan-in) and
        ``/metrics/cluster``."""
        stats = getattr(self.server, "stats", None)
        if stats is None:
            return
        # device working-set gauges
        ex = self.server.api.executor
        pc = ex.planes.stats()
        stats.gauge("plane_cache_bytes", pc["bytes"])
        stats.gauge("plane_cache_budget_bytes", pc["budgetBytes"])
        stats.gauge("plane_cache_entries", pc["entries"])
        stats.gauge("plane_cache_incremental_refreshes",
                    pc["incrementalRefreshes"])
        # HBM residency (r14): what eviction can and cannot reclaim
        # right now, plus how often the serving path finds its plane
        # already resident
        stats.gauge("plane_cache_pinned_entries", pc["pinnedEntries"])
        stats.gauge("plane_lease_count", pc["leases"])
        stats.gauge("plane_cache_hit_ratio", pc["hitRatio"])
        # ingest overlays (r15): set bits pending in device delta
        # overlays — base⊕delta serving depth before compaction folds
        stats.gauge("delta_overlay_bits",
                    pc.get("delta", {}).get("deltaOverlayBits", 0))
        # serving-spine gauges (r6): plan-cache occupancy and the
        # batcher's current adaptive window
        stats.gauge("plan_cache_entries", len(ex._plans))
        stats.gauge("fused_program_count", ex.fused.program_count)
        if ex.batcher is not None:
            stats.gauge("count_batcher_window_seconds",
                        ex.batcher.current_window)
        # self-healing pipeline (r18): governor state at scrape time
        # (0 healthy, 1 degraded, 2 probing) — transitions also set
        # this gauge the moment they happen
        stats.gauge("device_health_state",
                    ex.device_health()["stateCode"])
        # admission / shedding visibility (VERDICT advice #6): how
        # full the executor is right now, next to the shed counter
        # and queue-wait histogram fire() maintains
        stats.gauge("query_slots_in_use", ex.slots_in_use)
        stats.gauge("query_slots_max", ex.max_concurrent)
        # storage growth visibility (r8): op-log bytes are what the
        # snapshot queue + backup are supposed to bound — an
        # operator watching oplog_bytes climb knows compaction has
        # fallen behind before recovery time blows up
        st = self.server.api.storage_stats()
        stats.gauge("oplog_bytes", st["oplogBytes"])
        stats.gauge("fragment_count", st["fragmentCount"])
        stats.gauge("snapshot_bytes", st["snapshotBytes"])
        # storage integrity (r19): governor state + quarantine depth
        # at scrape time (transitions also set both the moment they
        # happen — this keeps a restarted scraper consistent)
        sh = getattr(self.server.api.holder, "storage_health", None)
        if sh is not None:
            pay = sh.payload()
            stats.gauge("disk_health_state", pay["stateCode"])
            stats.gauge("storage_fragment_quarantined",
                        len(pay["quarantined"]))

    # scrapers negotiating this media type get OpenMetrics output —
    # the only exposition format in which exemplars are legal (a
    # 0.0.4 parser rejects the `# {...}` suffix and fails the scrape)
    OPENMETRICS_TYPE = "application/openmetrics-text"

    def h_metrics(self) -> None:
        stats = getattr(self.server, "stats", None)
        self._refresh_scrape_gauges()
        om = self.OPENMETRICS_TYPE in (self.headers.get("Accept") or "")
        text = (stats.prometheus_text(openmetrics=om)
                if stats is not None else "")
        self._reply(text.encode(),
                    content_type=(self.OPENMETRICS_TYPE
                                  + "; version=1.0.0; charset=utf-8"
                                  if om else "text/plain; version=0.0.4"))

    def h_metrics_snapshot(self) -> None:
        """Node-to-node leg of the cluster metrics fan-in: the whole
        registry (counters, gauges, histograms with raw bucket counts)
        as JSON, gauges refreshed exactly like a direct scrape."""
        stats = getattr(self.server, "stats", None)
        self._refresh_scrape_gauges()
        cluster = self.server.api.cluster
        self._reply({
            "node": cluster.node_id if cluster is not None else "local",
            "snapshot": (stats.full_snapshot() if stats is not None
                         else {"counters": {}, "gauges": {},
                               "histograms": {}})})

    def h_metrics_cluster(self) -> None:
        """One Prometheus document for the fleet: fan out to live
        peers (breaker-aware), merge with the local registry, answer
        partial + ``cluster_metrics_node_up 0`` rows for unreachable
        nodes — a dead peer degrades the scrape, never fails it."""
        from pilosa_tpu.obs.metrics import render_cluster_metrics
        stats = getattr(self.server, "stats", None)
        self._refresh_scrape_gauges()
        local = (stats.full_snapshot() if stats is not None
                 else {"counters": {}, "gauges": {}, "histograms": {}})
        cluster = self.server.api.cluster
        if cluster is None:
            snaps, stale = {"local": local}, []
        else:
            snaps, stale = cluster.metrics_snapshots()
            snaps[cluster.node_id] = local
        # staleNodes ride a header too (the document's node_up 0 rows
        # carry the same fact inside the Prometheus text)
        self._reply(render_cluster_metrics(snaps, stale).encode(),
                    content_type="text/plain; version=0.0.4",
                    headers=({"X-Pilosa-Stale-Nodes": ",".join(stale)}
                             if stale else None))

    def h_status_cluster(self) -> None:
        """Every node's ``/status`` in one document, keyed by node id,
        with a ``staleNodes`` list for peers that could not answer
        (same partial-result contract as ``/metrics/cluster``)."""
        local = self.server.api.status()
        cluster = self.server.api.cluster
        if cluster is None:
            self._reply({"nodes": {"local": local}, "staleNodes": []})
            return
        snaps, stale = cluster.status_snapshots()
        snaps[cluster.node_id] = local
        self._reply({"nodes": snaps, "staleNodes": stale,
                     "coordinator": cluster.coordinator_id()})

    # -- fault injection (live control surface) -----------------------------

    def h_fault_list(self) -> None:
        self._reply({"faults": fault.list_faults(),
                     "triggered": [{"site": s, "action": a, "count": n}
                                   for (s, a), n
                                   in sorted(fault.triggered_total()
                                             .items())]})

    def h_fault_set(self) -> None:
        """Arm a failpoint on this node:
        ``{"site": ..., "action": ..., "nth"|"prob"|"seed"|"times"|
        "match"|"args": ...}`` — same spec shape as ``PILOSA_FAULTS``."""
        b = self._json_body()
        if not b.get("site") or not b.get("action"):
            raise ApiError("fault spec requires site and action")
        try:
            spec = fault.set_fault(
                b["site"], b["action"], nth=b.get("nth"),
                prob=b.get("prob"), seed=b.get("seed"),
                times=b.get("times"), match=b.get("match"),
                args=b.get("args"))
        except ValueError as e:
            raise ApiError(str(e))
        logger = getattr(self.server, "logger", None)
        if logger is not None:
            logger.warning("fault armed via /internal/fault: %s", spec)
        self._reply({"armed": spec})

    def h_fault_clear(self) -> None:
        """Disarm ``{"site": ...}`` (or every failpoint with no body)."""
        b = self._json_body()
        self._reply({"cleared": fault.clear(b.get("site"))})

    def h_backup(self) -> None:
        """Tar the whole data dir (reference: ``pilosa backup`` tars over
        HTTP; SURVEY.md §6 checkpoint/resume).  Fragments snapshot first
        so the tar is self-consistent."""
        self._reply(self.server.api.backup_tar(),
                    content_type="application/x-tar")

    def h_restore(self) -> None:
        self.server.api.restore_tar(self._body())
        self._reply({"success": True})

    def h_traces(self) -> None:
        """Recent retained traces (sampled / slow / profiled queries,
        plus this node's continuation spans of distributed queries);
        ``?trace_id=`` narrows to one trace."""
        from pilosa_tpu.obs import GLOBAL_TRACER
        spans = GLOBAL_TRACER.finished()
        want = self.query.get("trace_id", [None])[0]
        if want:
            spans = [s for s in spans if s.trace_id == want]
        self._reply({"traces": [s.to_json() for s in spans]})

    def h_debug_slow(self) -> None:
        """The slow-query ring: queries over ``slow_query_threshold``
        with PQL, shards, duration and the full span tree."""
        api = self.server.api
        self._reply({"thresholdSeconds": api.slow_query_threshold,
                     **api.slow_log.summary(),
                     "slow": api.slow_log.entries()})

    def h_debug_flight(self) -> None:
        """The dispatch flight recorder (r19): the last N lifecycle
        events (enqueue/dispatch/readback/deliver, governor moves,
        watchdog trips, quarantines, evictions, page-ins, compiles)
        straight from the in-memory ring — no dump file needed.
        ``?limit=`` trims to the newest N events; ``?cluster=1`` fans
        in every peer's ring (same partial-result contract as
        ``/status/cluster``)."""
        ex = getattr(self.server.api, "executor", None)
        flight = getattr(ex, "flight", None)
        raw = self.query.get("limit", ["0"])[0]
        try:
            limit = int(raw)
        except ValueError:
            raise ApiError(f"bad limit param {raw!r}")
        local = (flight.snapshot(limit=limit or None)
                 if flight is not None
                 else {"events": [], "lastSeq": 0, "capacity": 0,
                       "dumps": []})
        cluster = self.server.api.cluster
        if self.query.get("cluster", ["0"])[0] not in ("1", "true"):
            self._reply(local)
            return
        if cluster is None:
            self._reply({"nodes": {"local": local}, "staleNodes": []})
            return
        snaps, stale = cluster.flight_snapshots(limit=limit)
        snaps[cluster.node_id] = local
        self._reply({"nodes": snaps, "staleNodes": stale})

    def h_debug_threads(self) -> None:
        """Python stack dump of every thread — the rebuild's
        /debug/pprof (reference mounts net/http/pprof; SURVEY.md §6)."""
        import sys
        import threading
        import traceback
        names = {t.ident: t.name for t in threading.enumerate()}
        out = []
        for ident, frame in sys._current_frames().items():
            out.append(f"Thread {names.get(ident, '?')} ({ident}):")
            out.extend(line.rstrip()
                       for line in traceback.format_stack(frame))
            out.append("")
        self._reply("\n".join(out).encode(), content_type="text/plain")

    def h_debug_profile(self) -> None:
        """Capture a jax device profile for ?seconds= (default 3,
        clamped — see :func:`clamp_profile_seconds`) into ?dir=
        (default under the data dir) — TensorBoard-readable
        (SURVEY.md §6: expose jax.profiler traces)."""
        import time as _time

        import jax
        raw = self.query.get("seconds", ["3"])[0]
        try:
            seconds = float(raw)
        except ValueError:
            # a garbage ?seconds= is a client mistake, not a 500
            raise ApiError(f"bad seconds param {raw!r}")
        seconds = clamp_profile_seconds(seconds)
        out_dir = self.query.get("dir", [None])[0] or \
            self.server.api.holder.path + "/_profiles"
        jax.profiler.start_trace(out_dir)
        _time.sleep(seconds)
        jax.profiler.stop_trace()
        self._reply({"traceDir": out_dir, "seconds": seconds})


def build_router() -> Router:
    r = Router()
    r.add("POST", "/index/{index}/query", Handler.h_query)
    r.add("POST", "/index/{index}/field/{field}/import", Handler.h_import)
    r.add("POST", "/index/{index}/field/{field}/importValue",
          Handler.h_import_value)
    r.add("POST", "/index/{index}/field/{field}/import-roaring/{shard}",
          Handler.h_import_roaring)
    r.add("POST", "/index/{index}/field/{field}", Handler.h_create_field)
    r.add("DELETE", "/index/{index}/field/{field}", Handler.h_delete_field)
    r.add("POST", "/index/{index}", Handler.h_create_index)
    r.add("DELETE", "/index/{index}", Handler.h_delete_index)
    r.add("GET", "/index/{index}/field/{field}", Handler.h_get_field)
    r.add("GET", "/index/{index}", Handler.h_get_index)
    r.add("GET", "/export", Handler.h_export)
    r.add("GET", "/schema", Handler.h_schema)
    r.add("GET", "/status", Handler.h_status)
    r.add("GET", "/info", Handler.h_info)
    r.add("GET", "/version", Handler.h_version)
    r.add("GET", "/metrics", Handler.h_metrics)
    # cluster observability pane (r14): one scrape sees the fleet
    r.add("GET", "/metrics/cluster", Handler.h_metrics_cluster)
    r.add("GET", "/status/cluster", Handler.h_status_cluster)
    r.add("GET", "/internal/metrics/snapshot", Handler.h_metrics_snapshot)
    r.add("GET", "/internal/fault", Handler.h_fault_list)
    r.add("POST", "/internal/fault", Handler.h_fault_set)
    r.add("POST", "/internal/fault/clear", Handler.h_fault_clear)
    r.add("GET", "/internal/backup", Handler.h_backup)
    r.add("POST", "/internal/restore", Handler.h_restore)
    r.add("GET", "/internal/traces", Handler.h_traces)
    r.add("GET", "/debug/slow", Handler.h_debug_slow)
    r.add("GET", "/debug/flight", Handler.h_debug_flight)
    r.add("GET", "/debug/threads", Handler.h_debug_threads)
    r.add("POST", "/debug/profile", Handler.h_debug_profile)
    # node-to-node surface (deferred import: cluster depends on this
    # module for Handler/Router; a build without the cluster package
    # still serves single-node)
    try:
        from pilosa_tpu.cluster.internal import register_internal_routes
    except ImportError:
        pass
    else:
        register_internal_routes(r)
    # backup/restore surface (same deferred-import contract)
    try:
        from pilosa_tpu.backup.endpoints import register_backup_routes
    except ImportError:
        pass
    else:
        register_backup_routes(r)
    return r


class _HTTPServer(ThreadingHTTPServer):
    """Tracks live connections so ``close`` can sever them: with
    HTTP/1.1 keep-alive clients, ``shutdown()`` only stops the accept
    loop — handler threads parked on persistent connections would keep
    answering (a "closed" node would still heartbeat as alive)."""

    def __init__(self, *a, **kw):
        super().__init__(*a, **kw)
        self._conns: set = set()
        self._conns_lock = threading.Lock()

    def process_request(self, request, client_address):
        with self._conns_lock:
            self._conns.add(request)
        super().process_request(request, client_address)

    def shutdown_request(self, request):
        with self._conns_lock:
            self._conns.discard(request)
        super().shutdown_request(request)

    def close_all_connections(self) -> None:
        import socket as _socket
        with self._conns_lock:
            conns = list(self._conns)
        for s in conns:
            try:
                s.shutdown(_socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                s.close()
            except OSError:
                pass

    def handle_error(self, request, client_address):
        # failed TLS handshakes (plaintext probes, port scanners) and
        # client disconnects are per-connection noise, not server
        # errors — log at debug instead of dumping tracebacks
        import sys
        exc = sys.exc_info()[1]
        if isinstance(exc, OSError):  # incl. ssl.SSLError, disconnects
            logger = getattr(self, "logger", None)
            if logger is not None:
                logger.debug("http connection error from %s: %r",
                             client_address, exc)
            return
        super().handle_error(request, client_address)


class Server:
    """HTTP server wrapper: ``serve_forever`` on a background thread
    (reference: ``server.go#Server.Open`` / handler listen-serve)."""

    def __init__(self, api: API, host: str = "127.0.0.1", port: int = 10101,
                 stats=None, logger=None, ssl_context=None):
        _HTTPServer.request_queue_size = 64  # concurrent clients
        self.httpd = _HTTPServer((host, port), Handler)
        if ssl_context is not None:
            # TLS terminates here (reference: server/config.go tls
            # section).  do_handshake_on_connect=False: the handshake
            # runs in the per-connection handler thread on first read,
            # NOT in the accept loop — a client that connects and never
            # sends a ClientHello would otherwise wedge accept() and
            # with it the whole HTTP surface (and this node's liveness)
            self.httpd.socket = ssl_context.wrap_socket(
                self.httpd.socket, server_side=True,
                do_handshake_on_connect=False)
        self.httpd.api = api
        self.httpd.router = build_router()
        self.httpd.stats = stats
        self.httpd.logger = logger
        if stats is not None:
            # fault triggers surface as fault_triggered_total on THIS
            # registry's /metrics (process-global sink: one serving
            # server per process in production)
            fault.set_stats(stats)
        self._thread: threading.Thread | None = None

    @property
    def address(self) -> tuple[str, int]:
        return self.httpd.server_address[:2]

    def start(self) -> "Server":
        self._thread = threading.Thread(target=self.httpd.serve_forever,
                                        name="pilosa-tpu-http", daemon=True)
        self._thread.start()
        return self

    def serve_forever(self) -> None:
        self.httpd.serve_forever()

    def close(self) -> None:
        self.httpd.shutdown()
        self.httpd.server_close()
        self.httpd.close_all_connections()
        if self._thread is not None:
            self._thread.join(timeout=5)
