"""PQL recursive-descent parser.

Reference: ``pql/pql.peg`` grammar + generated parser (SURVEY.md §3.2).
Grammar (informally):

    query      = call*
    call       = IDENT '(' [arg (',' arg)*] ')'
    arg        = call                       # child
               | IDENT '=' value            # keyword arg
               | IDENT CMP value            # condition: amount > 5
               | value CMP IDENT CMP value  # between: 5 < amount < 10
               | value                      # positional (rewritten, see below)
    value      = INT | FLOAT | STRING | TIMESTAMP | list | true|false|null
               | IDENT                      # bareword == string

Positional rewrites mirror what the upstream grammar does so the executor
sees a uniform ``Args`` map (``pql/ast.go``):

    Set(10, f=1, 2017-01-01T00:00)   → _col=10, f=1, _timestamp=...
    Clear(10, f=1)                   → _col=10
    TopN(f, n=5) / Rows(f)           → _field="f"
    SetRowAttrs(f, 10, x=1)          → _field="f", _row=10
    SetColumnAttrs(10, x=1)          → _col=10

Bare timestamps anywhere map to ``_timestamp``; bareword identifiers in
positional position map to ``_field``.
"""

from __future__ import annotations

import functools as _functools

from typing import Any

from pilosa_tpu.pql import lexer as lx
from pilosa_tpu.pql.ast import Call, Condition, Query

# calls whose non-timestamp, non-bareword positional scalars fill these keys
_POSITIONAL_SLOTS: dict[str, list[str]] = {
    "Set": ["_col"],
    "Clear": ["_col"],
    "SetColumnAttrs": ["_col"],
    "SetRowAttrs": ["_row"],  # _field consumed by the bareword rule
}

_CMP_FLIP = {"<": ">", "<=": ">=", ">": "<", ">=": "<=", "==": "==", "!=": "!="}


class ParseError(Exception):
    def __init__(self, msg: str, pos: int):
        super().__init__(f"{msg} (at offset {pos})")
        self.pos = pos


class _Parser:
    def __init__(self, src: str):
        try:
            self.toks = lx.tokenize(src)
        except lx.LexError as e:
            raise ParseError(str(e), 0) from e
        self.i = 0

    # -- token helpers ---------------------------------------------------
    def peek(self, ahead: int = 0) -> lx.Token:
        j = min(self.i + ahead, len(self.toks) - 1)
        return self.toks[j]

    def next(self) -> lx.Token:
        t = self.toks[self.i]
        if t.kind != lx.EOF:
            self.i += 1
        return t

    def expect(self, kind: str) -> lx.Token:
        t = self.next()
        if t.kind != kind:
            raise ParseError(f"expected {kind}, got {t.kind} {t.value!r}", t.pos)
        return t

    # -- grammar ---------------------------------------------------------
    def query(self) -> Query:
        calls = []
        while self.peek().kind != lx.EOF:
            calls.append(self.call())
        if not calls:
            raise ParseError("empty query", 0)
        return Query(calls)

    def call(self) -> Call:
        name_tok = self.expect(lx.IDENT)
        self.expect(lx.LPAREN)
        call = Call(str(name_tok.value))
        positional_used = 0
        if self.peek().kind != lx.RPAREN:
            while True:
                self._arg(call, positional_used)
                positional_used = sum(
                    1 for k in _POSITIONAL_SLOTS.get(call.name, [])
                    if k in call.args
                )
                if self.peek().kind == lx.COMMA:
                    self.next()
                    continue
                break
        self.expect(lx.RPAREN)
        return call

    def _arg(self, call: Call, positional_used: int) -> None:
        t = self.peek()
        if t.kind == lx.IDENT:
            nxt = self.peek(1)
            if nxt.kind == lx.LPAREN:
                call.children.append(self.call())
                return
            if nxt.kind == lx.ASSIGN:
                key = str(self.next().value)
                self.next()  # '='
                if key in call.args:
                    raise ParseError(f"duplicate arg {key!r}", t.pos)
                call.args[key] = self.value()
                return
            if nxt.kind == lx.CMP:
                # condition with field on the left: amount > 5
                field = str(self.next().value)
                op = str(self.next().value)
                val = self.value()
                self._set_cond(call, field, Condition(op, val), t.pos)
                return
            # bareword positional → _field (TopN(f), Rows(f), SetRowAttrs(f,...))
            word = str(self.next().value)
            if word in ("true", "false", "null"):
                raise ParseError(f"unexpected positional literal {word!r}", t.pos)
            if "_field" in call.args:
                raise ParseError(f"unexpected bareword {word!r}", t.pos)
            call.args["_field"] = word
            return

        if t.kind in (lx.INT, lx.FLOAT) and self.peek(1).kind == lx.CMP:
            # between: 5 < amount < 10  (lo CMP field CMP hi)
            lo = self.next().value
            lo_op = str(self.expect(lx.CMP).value)
            field = str(self.expect(lx.IDENT).value)
            if self.peek().kind == lx.CMP:
                hi_op = str(self.next().value)
                hi = self.value()
                if lo_op not in ("<", "<=") or hi_op not in ("<", "<="):
                    raise ParseError(
                        f"between bounds must use < or <=, got {lo_op} {hi_op}",
                        t.pos)
                op = ("<" if lo_op == "<" else "<=") + ">" + \
                    ("<" if hi_op == "<" else "<=")
                self._set_cond(call, field, Condition(op, [lo, hi]), t.pos)
            else:
                # value on the left only: 5 < amount  ≡  amount > 5
                self._set_cond(
                    call, field, Condition(_CMP_FLIP[lo_op], lo), t.pos)
            return

        if t.kind == lx.TIMESTAMP:
            self.next()
            if "_timestamp" not in call.args:
                call.args["_timestamp"] = str(t.value)
                return
            # second bare timestamp: legacy Range(f=1, from, to) form
            if "_timestamp2" not in call.args:
                call.args["_timestamp2"] = str(t.value)
                return
            raise ParseError("too many timestamp args", t.pos)

        # positional scalar → per-call slot (_col / _row)
        val = self.value()
        slots = _POSITIONAL_SLOTS.get(call.name, [])
        if positional_used >= len(slots):
            raise ParseError(
                f"{call.name}: unexpected positional argument {val!r}", t.pos)
        call.args[slots[positional_used]] = val

    def _set_cond(self, call: Call, field: str, cond: Condition, pos: int) -> None:
        if field in call.args:
            raise ParseError(f"duplicate condition on field {field!r}", pos)
        call.args[field] = cond

    def value(self) -> Any:
        # call-valued args: GroupBy(Rows(a), filter=Row(x=1))
        if self.peek().kind == lx.IDENT and self.peek(1).kind == lx.LPAREN:
            return self.call()
        t = self.next()
        if t.kind == lx.INT or t.kind == lx.FLOAT or t.kind == lx.STRING:
            return t.value
        if t.kind == lx.TIMESTAMP:
            return str(t.value)
        if t.kind == lx.IDENT:
            if t.value == "true":
                return True
            if t.value == "false":
                return False
            if t.value == "null":
                return None
            return str(t.value)  # bareword value == string (field=amount)
        if t.kind == lx.LBRACK:
            items = []
            if self.peek().kind != lx.RBRACK:
                while True:
                    items.append(self.value())
                    if self.peek().kind == lx.COMMA:
                        self.next()
                        continue
                    break
            self.expect(lx.RBRACK)
            return items
        raise ParseError(f"expected value, got {t.kind} {t.value!r}", t.pos)


def parse(src: str) -> Query:
    """Parse a PQL string into a :class:`Query` (reference:
    ``pql.ParseString``)."""
    return _Parser(src).query()


@_functools.lru_cache(maxsize=512)
def parse_cached(src: str) -> Query:
    """Bounded memoized :func:`parse` for the serving hot path: repeated
    query shapes skip the parser entirely.  Callers must treat the
    returned AST as IMMUTABLE — every consumer that rewrites calls
    (cluster fan-out translation, Limit/Extract rewriting) copies first
    (``dist.py#_translate_input`` walk)."""
    return parse(src)
