"""PQL lexer.

Reference: token layer of the PEG grammar ``pql/pql.peg`` (SURVEY.md
§3.2).  Token set: identifiers (call + field + option names; dashes
allowed as in upstream field names), integers, floats, quoted strings,
bare timestamps (``2017-01-02T03:04``), punctuation, and the six
comparison operators used by BSI conditions.
"""

from __future__ import annotations

import re
from dataclasses import dataclass

# token kinds
IDENT = "IDENT"
INT = "INT"
FLOAT = "FLOAT"
STRING = "STRING"
TIMESTAMP = "TIMESTAMP"
LPAREN, RPAREN = "(", ")"
LBRACK, RBRACK = "[", "]"
COMMA, ASSIGN = ",", "="
CMP = "CMP"  # value: one of == != < <= > >=
EOF = "EOF"

_TIMESTAMP_RE = re.compile(
    r"\d{4}-\d{2}-\d{2}(?:T\d{2}:\d{2}(?::\d{2})?)?"
)
_NUM_RE = re.compile(r"-?\d+(\.\d+)?")
_IDENT_RE = re.compile(r"[A-Za-z_][A-Za-z0-9_-]*")
_WS_RE = re.compile(r"\s+")


class LexError(Exception):
    pass


@dataclass(frozen=True)
class Token:
    kind: str
    value: object
    pos: int

    def __repr__(self) -> str:
        return f"Token({self.kind}, {self.value!r}@{self.pos})"


def tokenize(src: str) -> list[Token]:
    toks: list[Token] = []
    i, n = 0, len(src)
    while i < n:
        m = _WS_RE.match(src, i)
        if m:
            i = m.end()
            continue
        c = src[i]
        if c in "()[],=<>!":
            # multi-char operators first
            two = src[i:i + 2]
            if two in ("==", "!=", "<=", ">="):
                toks.append(Token(CMP, two, i))
                i += 2
                continue
            if c in "<>":
                toks.append(Token(CMP, c, i))
                i += 1
                continue
            if c == "!":
                raise LexError(f"unexpected '!' at {i} (did you mean '!=')")
            if c == "=":
                toks.append(Token(ASSIGN, "=", i))
            else:
                toks.append(Token(c, c, i))
            i += 1
            continue
        if c in "'\"":
            j = i + 1
            buf = []
            while j < n and src[j] != c:
                if src[j] == "\\" and j + 1 < n:
                    buf.append(src[j + 1])
                    j += 2
                else:
                    buf.append(src[j])
                    j += 1
            if j >= n:
                raise LexError(f"unterminated string starting at {i}")
            toks.append(Token(STRING, "".join(buf), i))
            i = j + 1
            continue
        if c.isdigit() or (c == "-" and i + 1 < n and src[i + 1].isdigit()):
            # timestamp wins over int at digit positions: 2017-01-02T03:04
            m = _TIMESTAMP_RE.match(src, i)
            if m and c != "-" and "-" in m.group(0):
                toks.append(Token(TIMESTAMP, m.group(0), i))
                i = m.end()
                continue
            m = _NUM_RE.match(src, i)
            text = m.group(0)
            if "." in text:
                toks.append(Token(FLOAT, float(text), i))
            else:
                toks.append(Token(INT, int(text), i))
            i = m.end()
            continue
        m = _IDENT_RE.match(src, i)
        if m:
            toks.append(Token(IDENT, m.group(0), i))
            i = m.end()
            continue
        raise LexError(f"unexpected character {c!r} at {i}")
    toks.append(Token(EOF, None, n))
    return toks
