"""PQL AST node types.

Reference: ``pql/ast.go`` — ``pql.Query`` (list of calls), ``pql.Call``
(name, Args map, Children), ``pql.Condition`` (Op + Value) (SURVEY.md
§3.2).  Conventions kept from upstream:

- positional scalar args are stored under reserved keys the way the
  upstream grammar rewrites them (``Set(10, f=1)`` → ``_col=10``,
  trailing timestamp → ``_timestamp``, ``TopN(f, n=5)`` → ``_field=f``),
  so the executor sees one uniform Args map;
- a BSI condition arg (``Row(amount > 5)``) is stored as
  ``args[field] = Condition(op, value)``.
"""

from __future__ import annotations

from dataclasses import dataclass, field as dc_field
from typing import Any

# Condition ops.  Six scalar comparisons plus the four "between" variants
# the upstream grammar distinguishes (BETWEEN_LT_LT etc. in pql/token.go):
# the op string spells the two bounds' strictness, value is [lo, hi].
SCALAR_OPS = ("==", "!=", "<", "<=", ">", ">=")
BETWEEN_OPS = ("<><", "<=><", "<><=", "<=><=")  # lo(op)x(op)hi: <>< means lo<x<hi


def between_cmp_ops(op: str) -> tuple[str, str]:
    """One between op's (lo, hi) comparison keys against the stored
    values — ``<><`` is (gt, lt), ``<=><=`` is (ge, le).  THE source
    of truth for between-bound strictness: every executor lowering
    (eager, plan-spec, tree extras, the r20 bsirange family) maps
    through here."""
    return ("gt" if op.startswith("<>") else "ge",
            "lt" if op.endswith("><") else "le")

# the n-ary boolean-algebra calls and their canonical word-wise op
# tokens (reference: executeIntersect/executeUnion/... dispatch in
# executor.go).  This mapping is THE source of truth for operator
# semantics: the executor's eager path, both fused planners and the
# whole-tree compiler (exec/tree.py) all fold through it — `Not` is
# not listed because it is unary and lowers to `andnot(exists, x)`.
BOOL_CALLS = {"Union": "or", "Intersect": "and",
              "Difference": "andnot", "Xor": "xor"}


@dataclass(frozen=True)
class Condition:
    """A comparison against a BSI field: op + predicate value(s)."""

    op: str
    value: Any  # int | float | [lo, hi] for between ops

    def __post_init__(self):
        if self.op not in SCALAR_OPS + BETWEEN_OPS:
            raise ValueError(f"unknown condition op {self.op!r}")

    def __str__(self) -> str:
        if self.op in BETWEEN_OPS:
            lo_op = "<" if self.op.startswith("<>") else "<="
            hi_op = "<" if self.op.endswith("><") else "<="
            return f"{self.value[0]} {lo_op} x {hi_op} {self.value[1]}"
        return f"x {self.op} {self.value}"

    def matches(self, val) -> bool:
        """Host-side scalar evaluation (GroupBy ``having=`` filtering)."""
        if self.op in BETWEEN_OPS:
            lo, hi = self.value
            lo_ok = val > lo if self.op.startswith("<>") else val >= lo
            hi_ok = val < hi if self.op.endswith("><") else val <= hi
            return bool(lo_ok and hi_ok)
        v = self.value
        return bool({"==": val == v, "!=": val != v, "<": val < v,
                     "<=": val <= v, ">": val > v, ">=": val >= v}[self.op])

    def matches_array(self, vals):
        """Vectorized :meth:`matches` over a numpy array -> bool mask
        (the GroupBy having filter runs once per result block, not once
        per group)."""
        import numpy as np
        v = np.asarray(vals)
        if self.op in BETWEEN_OPS:
            lo, hi = self.value
            lo_ok = v > lo if self.op.startswith("<>") else v >= lo
            hi_ok = v < hi if self.op.endswith("><") else v <= hi
            return lo_ok & hi_ok
        c = self.value
        return {"==": v == c, "!=": v != c, "<": v < c,
                "<=": v <= c, ">": v > c, ">=": v >= c}[self.op]


@dataclass
class Call:
    """One PQL call: ``Name(child, ..., key=value, ...)``."""

    name: str
    args: dict[str, Any] = dc_field(default_factory=dict)
    children: list["Call"] = dc_field(default_factory=list)

    def field_arg(self, reserved: frozenset[str]) -> tuple[str, Any] | None:
        """The single (field, value) arg that is not a reserved option key —
        upstream resolves ``Row(f=1)``'s field name the same way at
        execution time, not parse time."""
        hits = [(k, v) for k, v in self.args.items()
                if k not in reserved and not k.startswith("_")]
        if not hits:
            return None
        if len(hits) > 1:
            raise ValueError(
                f"{self.name}: ambiguous field args {[k for k, _ in hits]}")
        return hits[0]

    def __str__(self) -> str:
        """Valid, re-parseable PQL (used to ship sub-queries to peer
        nodes — reference: ``InternalClient.QueryNode`` carrying the
        sub-AST, SURVEY.md §4.2).  ``parse(str(call))`` must equal
        ``call``."""
        parts: list[str] = []
        if "_field" in self.args:
            parts.append(str(self.args["_field"]))  # bareword field
        for slot in ("_col", "_row"):
            if slot in self.args:
                parts.append(_literal(self.args[slot]))
        parts += [str(c) for c in self.children]
        for k, v in self.args.items():
            if k in ("_field", "_col", "_row", "_timestamp", "_timestamp2"):
                continue
            if isinstance(v, Condition):
                parts.append(_condition_pql(k, v))
            else:
                parts.append(f"{k}={_literal(v)}")
        if "_timestamp" in self.args:
            parts.append(str(self.args["_timestamp"]))  # bare timestamp
        if "_timestamp2" in self.args:
            parts.append(str(self.args["_timestamp2"]))
        return f"{self.name}({', '.join(parts)})"


def _literal(v) -> str:
    """One PQL literal, re-parseable."""
    if isinstance(v, Call):
        return str(v)
    if isinstance(v, bool):
        return "true" if v else "false"
    if v is None:
        return "null"
    if isinstance(v, str):
        escaped = v.replace("\\", "\\\\").replace('"', '\\"')
        return f'"{escaped}"'
    if isinstance(v, list):
        return "[" + ", ".join(_literal(x) for x in v) + "]"
    return str(v)


def _condition_pql(field: str, c: Condition) -> str:
    if c.op in BETWEEN_OPS:
        lo_op = "<" if c.op.startswith("<>") else "<="
        hi_op = "<" if c.op.endswith("><") else "<="
        return (f"{_literal(c.value[0])} {lo_op} {field} "
                f"{hi_op} {_literal(c.value[1])}")
    return f"{field} {c.op} {_literal(c.value)}"


@dataclass
class Query:
    """A parsed PQL string: one or more top-level calls."""

    calls: list[Call]

    def __str__(self) -> str:
        return "\n".join(str(c) for c in self.calls)
