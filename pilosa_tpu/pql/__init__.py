"""PQL front end: lexer, recursive-descent parser, AST.

Reference: the ``pql/`` package (PEG grammar ``pql/pql.peg`` + generated
parser + ``pql/ast.go``; SURVEY.md §3.2).  The language is small, so a
hand-rolled recursive-descent parser replaces the PEG machinery; the AST
(`Call` with name, keyword args, children) is semantically identical to
upstream ``*pql.Call``.
"""

from pilosa_tpu.pql.ast import Call, Condition, Query
from pilosa_tpu.pql.parser import ParseError, parse, parse_cached

__all__ = ["Call", "Condition", "Query", "ParseError", "parse",
           "parse_cached"]
