"""Device mesh placement: the shard axis over TPU chips.

Reference: the reference distributes shards to nodes and merges partial
results over HTTP (``executor.go#mapReduce``, ``cluster.go#shardNodes``;
SURVEY.md §3.5).  The TPU rebuild replaces that with data placement: the
shard axis of every plane is sharded over a ``jax.sharding.Mesh``, the
same jitted query program runs on every chip against its resident
shards, and cross-shard reductions (``sum`` for counts, ``top_k`` after
a shard-axis sum) compile to XLA collectives over ICI — no host merge.

``MeshPlacement`` is the pluggable placement for
:class:`pilosa_tpu.exec.planes.PlaneCache`: it pads shard lists to the
mesh size and device_puts host arrays with a shard-axis
``NamedSharding``.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from pilosa_tpu.exec.planes import PAD_SHARD

SHARD_AXIS = "shard"


class MeshPlacement:
    """Places plane arrays (axis 0 = shards) across a device mesh."""

    def __init__(self, devices: list | None = None, axis: str = SHARD_AXIS):
        if devices is None:
            devices = jax.devices()
        self.axis = axis
        self.mesh = Mesh(np.array(devices), (axis,))
        self.n_devices = len(devices)

    def pad_shards(self, shards: tuple[int, ...]) -> tuple[int, ...]:
        """Pad a shard list to a multiple of the device count with
        PAD_SHARD sentinels (all-zero planes) so the shard axis divides
        evenly across the mesh."""
        rem = len(shards) % self.n_devices
        if rem:
            shards = shards + (PAD_SHARD,) * (self.n_devices - rem)
        return shards

    def sharding(self, ndim: int) -> NamedSharding:
        return NamedSharding(self.mesh, P(self.axis, *([None] * (ndim - 1))))

    def place(self, host_array: np.ndarray) -> jax.Array:
        return jax.device_put(host_array, self.sharding(host_array.ndim))


def local_placement() -> MeshPlacement | None:
    """Mesh over all local devices, or None for a single device (plain
    ``device_put`` placement is then used)."""
    devs = jax.devices()
    return MeshPlacement(devs) if len(devs) > 1 else None
