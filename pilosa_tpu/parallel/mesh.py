"""Device mesh placement: the shard axis over TPU chips.

Reference: the reference distributes shards to nodes and merges partial
results over HTTP (``executor.go#mapReduce``, ``cluster.go#shardNodes``;
SURVEY.md §3.5).  The TPU rebuild replaces that with data placement: the
shard axis of every plane is sharded over a ``jax.sharding.Mesh``, the
same jitted query program runs on every chip against its resident
shards, and cross-shard reductions (``sum`` for counts, ``top_k`` after
a shard-axis sum) compile to XLA collectives over ICI — no host merge.

``MeshPlacement`` is the pluggable placement for
:class:`pilosa_tpu.exec.planes.PlaneCache`: it pads shard lists to the
mesh size and device_puts host arrays with a shard-axis
``NamedSharding``.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from pilosa_tpu.exec.planes import PAD_SHARD

SHARD_AXIS = "shard"


class MeshPlacement:
    """Places plane arrays (axis 0 = shards) across a device mesh."""

    def __init__(self, devices: list | None = None, axis: str = SHARD_AXIS):
        if devices is None:
            devices = jax.devices()
        self.axis = axis
        self.mesh = Mesh(np.array(devices), (axis,))
        self.n_devices = len(devices)

    def pad_shards(self, shards: tuple[int, ...]) -> tuple[int, ...]:
        """Pad a shard list to a multiple of the device count with
        PAD_SHARD sentinels (all-zero planes) so the shard axis divides
        evenly across the mesh."""
        rem = len(shards) % self.n_devices
        if rem:
            shards = shards + (PAD_SHARD,) * (self.n_devices - rem)
        return shards

    def sharding(self, ndim: int) -> NamedSharding:
        return NamedSharding(self.mesh, P(self.axis, *([None] * (ndim - 1))))

    def place(self, host_array: np.ndarray) -> jax.Array:
        return jax.device_put(host_array, self.sharding(host_array.ndim))

    def replicated_sharding(self) -> NamedSharding:
        return NamedSharding(self.mesh, P())

    def replicate(self, host_array) -> jax.Array:
        """device_put with a fully-replicated sharding — overlay arrays
        (delta rows / BSI word-columns) stay one copy per chip so
        base⊕delta compiles into a single GSPMD program with the
        sharded base plane."""
        return jax.device_put(host_array, self.replicated_sharding())

    @property
    def key(self) -> tuple:
        """Hashable placement identity for program-cache / batch-group
        keys: same mesh topology ⇒ same compiled programs."""
        return ("mesh1d", self.axis, self.n_devices)


WORDS_AXIS = "words"


class MeshPlacement2D:
    """2D mesh ``(shard, words)``: shards across one axis AND each
    shard's packed-word axis split across the other — the rebuild's
    context-parallel analogue (SURVEY.md §3.5/§6: "split one shard's
    word axis across chips with partial popcounts psum-reduced").  Used
    when row-count × shard-width exceeds per-chip HBM: a row's 32768
    words live on ``words_size`` chips, counts reduce over both axes.

    Drop-in for :class:`MeshPlacement` in the executor/PlaneCache: the
    same eager kernels run under GSPMD with reductions compiling to
    collectives over both mesh axes.
    """

    def __init__(self, devices: list | None = None, shard_size: int = 1,
                 words_size: int = 2, shard_axis: str = SHARD_AXIS,
                 words_axis: str = WORDS_AXIS):
        if devices is None:
            devices = jax.devices()
        if shard_size * words_size != len(devices):
            raise ValueError(
                f"mesh {shard_size}x{words_size} needs "
                f"{shard_size * words_size} devices, have {len(devices)}")
        self.shard_axis, self.words_axis = shard_axis, words_axis
        self.mesh = Mesh(
            np.array(devices).reshape(shard_size, words_size),
            (shard_axis, words_axis))
        self.n_devices = shard_size  # shard-axis width (for pad_shards)
        self.words_size = words_size

    def pad_shards(self, shards: tuple[int, ...]) -> tuple[int, ...]:
        rem = len(shards) % self.n_devices
        if rem:
            shards = shards + (PAD_SHARD,) * (self.n_devices - rem)
        return shards

    def sharding(self, ndim: int) -> NamedSharding:
        if ndim == 1:
            return NamedSharding(self.mesh, P(self.words_axis))
        return NamedSharding(
            self.mesh,
            P(self.shard_axis, *([None] * (ndim - 2)), self.words_axis))

    def place(self, host_array: np.ndarray) -> jax.Array:
        return jax.device_put(host_array, self.sharding(host_array.ndim))

    def replicated_sharding(self) -> NamedSharding:
        return NamedSharding(self.mesh, P())

    def replicate(self, host_array) -> jax.Array:
        return jax.device_put(host_array, self.replicated_sharding())

    @property
    def key(self) -> tuple:
        return ("mesh2d", self.shard_axis, self.words_axis,
                self.n_devices, self.words_size)


def local_placement() -> MeshPlacement | None:
    """Mesh over all local devices, or None for a single device (plain
    ``device_put`` placement is then used)."""
    devs = jax.devices()
    return MeshPlacement(devs) if len(devs) > 1 else None
