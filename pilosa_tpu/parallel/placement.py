"""Shard → partition → node placement (host-level distribution).

Reference: ``cluster.go`` (SURVEY.md §3.3) — shards hash to one of 256
partitions via jump-consistent-hash of (index, shard); a partition maps
to ``replicaN`` nodes.  The TPU rebuild keeps this exact scheme for the
*host* layer (which host owns a shard's fragment files and feeds it to
its chips); within one host, shards map onto the chip mesh by position
(``MeshPlacement``).

Jump hash per Lamping & Veach, "A Fast, Minimal Memory, Consistent Hash
Algorithm" — the algorithm upstream uses.
"""

from __future__ import annotations

from pilosa_tpu.store.translate import PARTITION_N, fnv1a64


def jump_hash(key: int, n_buckets: int) -> int:
    """Jump consistent hash: uint64 key -> bucket in [0, n_buckets)."""
    if n_buckets <= 0:
        raise ValueError("n_buckets must be positive")
    b, j = -1, 0
    key &= 0xFFFFFFFFFFFFFFFF
    while j < n_buckets:
        b = j
        key = (key * 2862933555777941757 + 1) & 0xFFFFFFFFFFFFFFFF
        j = int((b + 1) * (float(1 << 31) / float((key >> 33) + 1)))
    return b


def shard_partition(index: str, shard: int, n_partitions: int = PARTITION_N) -> int:
    """Partition of (index, shard) — reference: ``Cluster.partition``:
    fnv hash of index name + big-endian shard, jump-hashed."""
    h = fnv1a64(index.encode() + shard.to_bytes(8, "big"))
    return jump_hash(h, n_partitions)


def partition_nodes(partition: int, node_ids: list[str],
                    replica_n: int = 1) -> list[str]:
    """The replica_n nodes owning a partition: jump-hash picks the
    primary among sorted node IDs; replicas follow in ring order
    (reference: ``Cluster.partitionNodes``)."""
    if not node_ids:
        return []
    nodes = sorted(node_ids)
    k = min(replica_n, len(nodes))
    start = jump_hash(partition, len(nodes))
    return [nodes[(start + i) % len(nodes)] for i in range(k)]


def shard_nodes(index: str, shard: int, node_ids: list[str],
                replica_n: int = 1) -> list[str]:
    """Owning nodes of a shard (primary first) — reference:
    ``Cluster.shardNodes``."""
    return partition_nodes(shard_partition(index, shard), node_ids, replica_n)
