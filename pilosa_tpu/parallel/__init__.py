"""Distribution over the TPU mesh (L3 of SURVEY.md §2): shard-axis
placement, SPMD query programs with ICI collectives, and host-level
jump-hash shard placement."""

from pilosa_tpu.parallel.mesh import (MeshPlacement, MeshPlacement2D,
                                      local_placement)
from pilosa_tpu.parallel.placement import (jump_hash, partition_nodes,
                                           shard_nodes, shard_partition)

__all__ = [
    "MeshPlacement", "MeshPlacement2D", "local_placement", "jump_hash",
    "shard_partition", "partition_nodes", "shard_nodes",
]
