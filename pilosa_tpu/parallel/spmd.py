"""Compiled SPMD query programs over the shard mesh.

One jitted function per query shape (SURVEY.md §8): inputs are plane
arrays whose leading axis is sharded over the mesh
(:class:`~pilosa_tpu.parallel.mesh.MeshPlacement`); cross-shard
reductions inside ``jit`` compile to XLA all-reduces over ICI.  The
``shard_map`` variants make the collective explicit (``psum`` over the
shard axis) — the compiled-in replacement for the reference's
coordinator-side HTTP merge (``executor.go#mapReduce`` reducers,
SURVEY.md §3.6).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

try:  # jax >= 0.8
    from jax import shard_map
except ImportError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map

from pilosa_tpu.engine import bsi as bsik
from pilosa_tpu.engine import kernels


# -- implicit-collective programs (inputs carry NamedSharding) --------------


@jax.jit
def intersect_count(a: jax.Array, b: jax.Array) -> jax.Array:
    """Count(Intersect(Row, Row)) over all shards: int64 scalar."""
    return jnp.sum(kernels.intersection_count(a, b))


@jax.jit
def union_count(a: jax.Array, b: jax.Array) -> jax.Array:
    return jnp.sum(kernels.union_count(a, b))


@partial(jax.jit, static_argnames=("n",))
def topn(plane: jax.Array, filter_words: jax.Array | None, n: int):
    """TopN over a [n_shards, R, W] plane: (counts[n], slots[n])."""
    counts = kernels.row_counts(plane, filter_words)
    return kernels.top_n(jnp.sum(counts, axis=0), n)


@jax.jit
def bsi_bit_counts(plane: jax.Array, filter_words: jax.Array | None):
    """Per-shard per-bit BSI counts over a [n_shards, depth+2, W] plane;
    finish with ``engine.bsi.combine_sum`` on host."""
    return bsik.bit_counts(plane, filter_words)


# -- explicit shard_map programs (collectives spelled out) -------------------


def make_intersect_count_psum(mesh: Mesh, axis: str = "shard"):
    """Explicit SPMD Count(Intersect): each chip reduces its resident
    shard block, then one ``psum`` over ICI."""

    def per_chip(a, b):
        return jax.lax.psum(jnp.sum(kernels.intersection_count(a, b)),
                            axis_name=axis)

    return jax.jit(shard_map(
        per_chip, mesh=mesh,
        in_specs=(P(axis, None), P(axis, None)),
        out_specs=P()))


def make_topn_psum(mesh: Mesh, n: int, axis: str = "shard"):
    """Explicit SPMD TopN: per-chip row popcounts, psum of the count
    matrix over ICI, replicated top_k."""

    def per_chip(plane, filter_words):
        counts = jnp.sum(kernels.row_counts(plane, filter_words), axis=0)
        counts = jax.lax.psum(counts, axis_name=axis)
        return kernels.top_n(counts, n)

    return jax.jit(shard_map(
        per_chip, mesh=mesh,
        in_specs=(P(axis, None, None), P(axis, None)),
        out_specs=(P(), P())))


def make_bsi_sum_psum(mesh: Mesh, axis: str = "shard"):
    """Cluster-wide per-bit count matrices via ICI psum (int32 — exact
    for <2047 full shards per bit); host combine_sum finishes."""

    def per_chip(plane, filter_words):
        pos, neg, cnt = bsik.bit_counts(plane, filter_words)
        return (jax.lax.psum(jnp.sum(pos, axis=0, dtype=jnp.int32),
                             axis_name=axis),
                jax.lax.psum(jnp.sum(neg, axis=0, dtype=jnp.int32),
                             axis_name=axis),
                jax.lax.psum(jnp.sum(cnt, dtype=jnp.int32),
                             axis_name=axis))

    return jax.jit(shard_map(
        per_chip, mesh=mesh,
        in_specs=(P(axis, None, None), P(axis, None)),
        out_specs=(P(), P(), P())))


def make_intersect_count_psum2d(mesh: Mesh, shard_axis: str = "shard",
                                words_axis: str = "words"):
    """Explicit 2D-SPMD Count(Intersect) over a (shard × words) mesh:
    each chip holds a block of shards × a slice of each row's words;
    partial popcounts psum over BOTH axes (SURVEY.md §6 long-context
    analogue — the word axis is the 'sequence' being split)."""

    def per_chip(a, b):
        partial = jnp.sum(kernels.intersection_count(a, b))
        return jax.lax.psum(partial, axis_name=(shard_axis, words_axis))

    return jax.jit(shard_map(
        per_chip, mesh=mesh,
        in_specs=(P(shard_axis, words_axis), P(shard_axis, words_axis)),
        out_specs=P()))


def make_topn_psum2d(mesh: Mesh, n: int, shard_axis: str = "shard",
                     words_axis: str = "words"):
    """2D TopN: per-chip partial row counts, psum over shards + word
    slices, replicated top_k."""

    def per_chip(plane, filter_words):
        counts = jnp.sum(kernels.row_counts(plane, filter_words), axis=0)
        counts = jax.lax.psum(counts, axis_name=(shard_axis, words_axis))
        return kernels.top_n(counts, n)

    return jax.jit(shard_map(
        per_chip, mesh=mesh,
        in_specs=(P(shard_axis, None, words_axis),
                  P(shard_axis, words_axis)),
        out_specs=(P(), P())))


def make_ingest_step(mesh: Mesh, axis: str = "shard"):
    """Sharded device-side mutation: apply coalesced (word_idx, mask)
    updates to each chip's resident rows (SURVEY.md §4.5 device half).
    Updates are per-shard: uint idx/mask arrays with leading shard axis."""

    def per_chip(words, word_idx, word_mask):
        # one scatter per resident shard (indices differ per shard)
        return jax.vmap(kernels.apply_word_or)(words, word_idx, word_mask)

    return jax.jit(shard_map(
        per_chip, mesh=mesh,
        in_specs=(P(axis, None), P(axis, None), P(axis, None)),
        out_specs=P(axis, None)))
