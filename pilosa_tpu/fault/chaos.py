"""Chaos harness: scripted fault schedules against the OS-process
cluster, with an in-memory oracle and invariant checks.

The distributed claims this repo reproduces (anti-entropy union-merge,
versioned placement with pull-on-mismatch, orphan handoff, CRC-framed
oplog replay, idempotent internode retry) are exercised HERE under
injected failure, not just on the happy path:

====================================  ==================================
scenario                              invariant asserted after faults
                                      clear
====================================  ==================================
partition_during_resize               no acked write lost; queries
                                      oracle-exact on every node; AAE
                                      re-converges every replica
crash_mid_oplog_append                replay yields a clean prefix:
                                      acked writes survive a kill -9,
                                      the torn record never corrupts
duplicate_delivery                    dropped internal responses ⇒
                                      retries redeliver; bits never
                                      double-count, replicas converge
dropped_placement_broadcast           a dropped resize-completion
                                      broadcast still converges via
                                      the heartbeat placement version
dropped_internal_response_trace       a redelivered fan-out leg is
                                      visible in the profile tree
                                      (``retried`` tag) — traces
                                      never lie under failure
node_kill_failover                    kill -9 mid-serve (replicas=2,
                                      handoff disabled): zero read
                                      failures via replica failover,
                                      breaker opens, strict writes
                                      refuse 503, rejoin closes the
                                      breaker
straggler_hedged_read                 a delayed leg is hedged to a
                                      replica: bounded latency, exact
                                      answer, ``hedged`` trace tag
breaker_lifecycle                     open → half_open → closed pinned
                                      through partition + heal; open
                                      routing pays no failover tax
clear_during_kill_handoff             kill -9 mid-serve (replicas=2,
                                      handoff ON): Set/Clear/ClearRow
                                      all keep serving, rejoin drains
                                      the hint log, every node ends
                                      oracle-exact and a forced AAE
                                      round resurrects nothing
coordinator_crash_hint_log            kill -9 the write coordinator
                                      mid-hint-append (torn record):
                                      recovery truncates the torn op
                                      (it never applies anywhere) and
                                      replays the clean prefix
hung_dispatch_serving                 a hung device dispatch on one
                                      plane: unaffected queries keep
                                      answering exact (availability
                                      1.0), the wedged caller gets a
                                      structured 504/500 naming the
                                      stage, the governor probes back
                                      to healthy, zero leaked threads
flaky_device_governor                 consecutive dispatch faults:
                                      answers stay exact (fallback),
                                      the governor degrades then
                                      probes back to healthy
====================================  ==================================

Oracle semantics are at-least-once honest: a write the harness saw FAIL
may still have applied on some replica (lost response, torn tail after
the memory mutation).  The standing bar — "no lost acknowledged
writes" — is therefore checked as ``acked ⊆ observed ⊆ attempted``;
observed bits outside ``attempted`` are corruption and fail loudly.
Clears sharpen it (r13): ``observed ∩ cleared = ∅`` — an acked Clear
not re-attempted since must stay absent on every node, forever; a bit
resurrected by anti-entropy is the loudest possible failure.

Every schedule is reproducible: all randomness (write placement, fault
parameters, drop probabilities) flows from one printed seed.

Runbook: ``python -m pilosa_tpu.fault.chaos [--seed N] [--scenario S]``
boots its own process clusters in a temp dir; ``tests/test_chaos.py``
drives the same scenarios under tier-1.
"""

from __future__ import annotations

import random
import time

from pilosa_tpu.api.client import Client, ClientError  # noqa: F401
from pilosa_tpu.engine.words import SHARD_WIDTH


class InvariantViolation(AssertionError):
    """A chaos invariant failed; the message carries the seed."""


def prom_counter_total(text: str, name: str) -> float:
    """Sum one counter family across its labels from Prometheus
    exposition text (shared by the harness and bench/config22)."""
    total = 0.0
    for line in text.splitlines():
        if line.startswith(name) and line[len(name)] in "{ ":
            total += float(line.rsplit(" ", 1)[1])
    return total


class ChaosHarness:
    """One scenario's state: a process cluster, a seeded RNG, and the
    acked/attempted write oracle."""

    N_ROWS = 3
    MAX_COL = 3 * SHARD_WIDTH - 1  # spread writes over ~3 shards

    def __init__(self, cluster, seed: int, index: str, field: str = "f"):
        self.cluster = cluster
        self.seed = seed
        self.rng = random.Random(seed)
        self.index, self.field = index, field
        self.acked: dict[int, set[int]] = {}
        self.attempted: dict[int, set[int]] = {}
        # bits whose Clear was ACKED and not re-attempted since: they
        # must be absent on every node once hints drain — the
        # resurrection oracle for the r13 handoff scenarios
        self.cleared: dict[int, set[int]] = {}
        print(f"[chaos] scenario index={index!r} seed={seed}", flush=True)

    def _fail(self, msg: str) -> "InvariantViolation":
        return InvariantViolation(
            f"{msg} (reproduce with seed={self.seed})")

    def client(self, i: int = 0) -> Client:
        return self.cluster.client(i)

    @property
    def n(self) -> int:
        return len(self.cluster.nodes)

    # -- fault control -------------------------------------------------------

    def set_fault(self, node_i: int, site: str, action: str, **kw) -> dict:
        return self.client(node_i)._json(
            "POST", "/internal/fault",
            {"site": site, "action": action, **kw})

    def clear_faults(self) -> None:
        for i in range(self.n):
            try:
                self.client(i)._json("POST", "/internal/fault/clear", {})
            except (ClientError, OSError):
                pass  # node mid-restart; its registry died with it

    def partition(self, i: int, j: int) -> None:
        """Sever the (i, j) node pair in both directions — each side's
        outbound requests to the other fail as connection-refused."""
        peer_j = f"127.0.0.1:{self.cluster.nodes[j].port}"
        peer_i = f"127.0.0.1:{self.cluster.nodes[i].port}"
        self.set_fault(i, "client.send", "partition",
                       match={"peer": peer_j})
        self.set_fault(j, "client.send", "partition",
                       match={"peer": peer_i})

    # -- cluster introspection ----------------------------------------------

    def node_id(self, i: int) -> str:
        return f"127.0.0.1:{self.cluster.nodes[i].port}"

    def breaker_state(self, via: int, peer_id: str) -> str | None:
        """Peer breaker state as node ``via`` reports it on the
        ``/status`` clusterHealth block."""
        st = self.client(via)._json("GET", "/status")
        for p in st.get("clusterHealth", {}).get("peers", []):
            if p["id"] == peer_id:
                return p["breaker"]
        return None

    def counter_total(self, via: int, name: str) -> float:
        """Sum a counter family across labels from ``/metrics``."""
        return prom_counter_total(self.client(via).metrics_text(), name)

    def await_hints_drained(self, via: int, timeout: float = 40.0) -> None:
        """Poll ``writeHealth`` on node ``via`` until its hint backlog
        is empty (the rejoined peer has replayed every queued op)."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            try:
                if not self.client(via).write_health().get(
                        "hintBacklogOps"):
                    return
            except (ClientError, OSError):
                pass
            time.sleep(0.3)
        raise self._fail("hint backlog never drained")

    def coordinator_index(self) -> int:
        status = self.client(0)._json("GET", "/status")
        primary = next(nd["id"] for nd in status["nodes"]
                       if nd.get("isPrimary"))
        port = int(primary.rsplit(":", 1)[1])
        for i, node in enumerate(self.cluster.nodes):
            if node.port == port:
                return i
        raise self._fail(f"coordinator {primary} is not in the harness")

    def placement_versions(self) -> list[float]:
        return [float(self.client(i)._json(
            "GET", "/internal/cluster/state")["placementVersion"])
            for i in range(self.n)]

    def await_all_normal(self, timeout: float = 60.0) -> None:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            try:
                if all(self.client(i)._json("GET", "/status")["state"]
                       == "NORMAL" for i in range(self.n)):
                    return
            except (ClientError, OSError):
                pass
            time.sleep(0.3)
        raise self._fail("cluster never returned to NORMAL")

    def await_coordinator_normal(self, timeout: float = 60.0) -> None:
        """NORMAL on the coordinator only — mid-partition, suspect
        peers legitimately report DEGRADED."""
        deadline = time.monotonic() + timeout
        coord = self.coordinator_index()
        while time.monotonic() < deadline:
            try:
                if (self.client(coord)._json("GET", "/status")["state"]
                        == "NORMAL"):
                    return
            except (ClientError, OSError):
                pass
            time.sleep(0.3)
        raise self._fail("coordinator never finished the resize")

    # -- workload ------------------------------------------------------------

    def setup(self) -> None:
        c = self.client(0)
        c.create_index(self.index)
        c.create_field(self.index, self.field)

    def write(self, row: int, col: int, via: int = 0) -> bool:
        """One ``Set``; records the attempt, and the ack only when the
        cluster answered 200.  A failed write may still have applied on
        some replica (at-least-once) — that is what ``attempted``
        captures.  The ATTEMPT also lifts the bit's cleared-ness: a
        Set racing an earlier acked Clear may legitimately re-appear."""
        self.attempted.setdefault(row, set()).add(col)
        self.cleared.setdefault(row, set()).discard(col)
        try:
            self.client(via).query(self.index,
                                   f"Set({col}, {self.field}={row})")
        except (ClientError, OSError):
            return False
        self.acked.setdefault(row, set()).add(col)
        return True

    def clear(self, row: int, col: int, via: int = 0) -> bool:
        """One ``Clear``.  The ATTEMPT removes the bit from ``acked``
        (a failed clear may still have applied — state unknown); an
        acked clear moves it to ``cleared``: the bit must be absent on
        every node once hints drain, and must NEVER be resurrected by
        anti-entropy."""
        self.acked.setdefault(row, set()).discard(col)
        try:
            self.client(via).query(self.index,
                                   f"Clear({col}, {self.field}={row})")
        except (ClientError, OSError):
            return False
        self.attempted.setdefault(row, set()).discard(col)
        self.cleared.setdefault(row, set()).add(col)
        return True

    def clear_row(self, row: int, via: int = 0) -> bool:
        """One ``ClearRow``; on ack, every bit the row might hold
        becomes cleared-and-must-stay-absent (until re-set)."""
        self.acked[row] = set()
        try:
            self.client(via).query(self.index,
                                   f"ClearRow({self.field}={row})")
        except (ClientError, OSError):
            return False
        self.cleared.setdefault(row, set()).update(
            self.attempted.get(row, set()))
        self.attempted[row] = set()
        return True

    def random_writes(self, count: int, via: int = 0) -> int:
        acked = 0
        for _ in range(count):
            row = self.rng.randrange(self.N_ROWS)
            col = self.rng.randrange(self.MAX_COL)
            acked += bool(self.write(row, col, via=via))
        return acked

    def bulk_import(self, pairs, via: int = 0,
                    clear: bool = False) -> bool:
        """One bulk-import batch (r15): all pairs in ONE request over
        the pair-import endpoint.  Oracle updates mirror
        :meth:`write`/:meth:`clear` per pair — a failed batch may have
        partially applied (per-shard commits), which ``attempted``
        absorbs."""
        for r, c in pairs:
            if clear:
                self.acked.setdefault(r, set()).discard(c)
            else:
                self.attempted.setdefault(r, set()).add(c)
                self.cleared.setdefault(r, set()).discard(c)
        try:
            self.client(via)._json(
                "POST", f"/index/{self.index}/field/{self.field}/import",
                {"rowIDs": [int(r) for r, _ in pairs],
                 "columnIDs": [int(c) for _, c in pairs],
                 "clear": clear})
        except (ClientError, OSError):
            return False
        for r, c in pairs:
            if clear:
                self.attempted.setdefault(r, set()).discard(c)
                self.cleared.setdefault(r, set()).add(c)
            else:
                self.acked.setdefault(r, set()).add(c)
        return True

    # -- invariants ----------------------------------------------------------

    def check_oracle(self, via: int | None = None) -> None:
        """Every node's answer for every row satisfies
        ``acked ⊆ observed ⊆ attempted`` and ``observed ∩ cleared = ∅``
        (and Count agrees with Row) — acked writes are never lost,
        nothing appears that was never written (corruption / replayed
        half-records), and an acked clear is never resurrected."""
        nodes = [via] if via is not None else range(self.n)
        for i in nodes:
            c = self.client(i)
            for row in range(self.N_ROWS):
                res = c.query(
                    self.index,
                    f"Row({self.field}={row})"
                    f"Count(Row({self.field}={row}))")
                got = set(res[0]["columns"])
                count = res[1]
                acked = self.acked.get(row, set())
                attempted = self.attempted.get(row, set())
                cleared = self.cleared.get(row, set())
                if not acked <= got:
                    raise self._fail(
                        f"node {i} row {row}: LOST acked writes "
                        f"{sorted(acked - got)[:10]}")
                if got & cleared:
                    raise self._fail(
                        f"node {i} row {row}: RESURRECTED cleared bits "
                        f"{sorted(got & cleared)[:10]}")
                if not got <= attempted:
                    raise self._fail(
                        f"node {i} row {row}: phantom bits "
                        f"{sorted(got - attempted)[:10]} never written")
                if count != len(got):
                    raise self._fail(
                        f"node {i} row {row}: Count={count} but "
                        f"Row has {len(got)} columns")

    def await_oracle(self, timeout: float = 90.0) -> None:
        """Poll until every node answers oracle-consistently (AAE has
        repaired what the faults diverged)."""
        deadline = time.monotonic() + timeout
        last: Exception | None = None
        while time.monotonic() < deadline:
            try:
                self.check_oracle()
                return
            except (InvariantViolation, ClientError, OSError) as e:
                last = e
            time.sleep(0.5)
        raise self._fail(f"oracle never converged: {last}")

    def await_replica_convergence(self, expected_holders: int,
                                  timeout: float = 90.0) -> None:
        """AAE/handoff end state: every fragment is held by exactly
        ``expected_holders`` nodes (orphans handed off and deleted,
        missing replicas re-filled) and all holders' position sets are
        byte-identical."""
        deadline = time.monotonic() + timeout
        last = "no fragments observed"
        while time.monotonic() < deadline:
            try:
                problem = self._replica_divergence(expected_holders)
            except (ClientError, OSError) as e:
                problem = f"transport: {e}"
            if problem is None:
                return
            last = problem
            time.sleep(0.7)
        raise self._fail(f"replicas never converged: {last}")

    def _replica_divergence(self, expected_holders: int) -> str | None:
        datas: dict[tuple, dict[int, bytes]] = {}
        for i in range(self.n):
            inv = self.client(i)._json(
                "GET", "/internal/fragments")["fragments"]
            for fr in inv:
                if fr["index"] != self.index:
                    continue  # other scenarios' data is not ours to judge
                key = (fr["index"], fr["field"], fr["view"], fr["shard"])
                qs = (f"index={fr['index']}&field={fr['field']}"
                      f"&view={fr['view']}&shard={fr['shard']}")
                blob = self.client(i)._do(
                    "GET", f"/internal/fragment/data?{qs}")
                datas.setdefault(key, {})[i] = blob
        if not datas:
            return "no fragments observed"
        for key, per_node in datas.items():
            if len(per_node) != expected_holders:
                return (f"{key} held by {sorted(per_node)} "
                        f"(want {expected_holders} holders)")
            if len(set(per_node.values())) != 1:
                return f"{key} differs across {sorted(per_node)}"
        return None

    def await_placement_convergence(self, min_version: float,
                                    timeout: float = 60.0) -> None:
        deadline = time.monotonic() + timeout
        last: object = None
        while time.monotonic() < deadline:
            try:
                versions = self.placement_versions()
                if (len(set(versions)) == 1
                        and versions[0] > min_version):
                    return
                last = versions
            except (ClientError, OSError) as e:
                last = e
            time.sleep(0.3)
        raise self._fail(
            f"placement never converged past {min_version}: {last}")


# ---------------------------------------------------------------------------
# scenarios
# ---------------------------------------------------------------------------


def scenario_partition_during_resize(cluster, seed: int) -> ChaosHarness:
    """A node pair partitions, a rebalance runs THROUGH the partition
    (its pushes to the unreachable side fail and the data stays behind
    as orphans), writes continue — after the partition heals, anti-
    entropy must hand every orphan to its owners and every node must
    answer oracle-exact."""
    h = ChaosHarness(cluster, seed, index="chaos_part")
    h.setup()
    h.random_writes(30)
    h.check_oracle()
    h.partition(1, 2)
    acked = h.random_writes(15)  # via node 0: reaches everyone
    if acked == 0:
        raise h._fail("no write acked during the partition")
    coord = h.coordinator_index()
    h.client(coord)._json("POST", "/internal/resize/trigger", {})
    time.sleep(0.5)  # let the resize thread flip into RESIZING
    h.await_coordinator_normal()
    h.random_writes(10)  # against the (possibly stale) new placement
    h.clear_faults()
    h.await_all_normal()
    h.await_oracle()
    h.await_replica_convergence(expected_holders=2)
    return h


def scenario_crash_mid_oplog_append(cluster, seed: int,
                                    tears: int = 2) -> ChaosHarness:
    """A torn oplog tail (the write 'crashes' after persisting only the
    first K bytes of the record), then a real kill -9 and restart:
    replay must recover the clean prefix — every acked write survives,
    the torn record never half-applies."""
    h = ChaosHarness(cluster, seed, index="chaos_crash")
    h.setup()
    h.random_writes(12)
    h.check_oracle()
    node = cluster.nodes[0]
    for _ in range(tears):
        # tear inside the 17-byte header or into the payload — both
        # classes must truncate cleanly on replay
        offset = h.rng.randrange(0, 25)
        h.set_fault(0, "oplog.append", "torn_write", nth=1,
                    args={"offset": offset})
        row = h.rng.randrange(h.N_ROWS)
        col = h.rng.randrange(h.MAX_COL)
        if h.write(row, col):
            raise h._fail("torn-write Set unexpectedly acked")
        node.kill9()
        node.stop()   # close the log handle; process is already dead
        node.start()
        node.await_up()
        h.await_oracle()      # replay recovered the clean prefix
        if h.random_writes(4) == 0:  # the truncated log appends again
            raise h._fail("no write acked after crash recovery")
        h.check_oracle()
    return h


def scenario_duplicate_delivery(cluster, seed: int) -> ChaosHarness:
    """A node processes internal POSTs but drops the responses
    (seeded-random, bounded): the idempotent internode retry redelivers
    every one — bits must never double-count and replicas must
    converge exactly."""
    h = ChaosHarness(cluster, seed, index="chaos_dup")
    h.setup()
    h.random_writes(10)
    h.set_fault(1, "server.response", "drop_response",
                prob=0.5, seed=seed, times=12,
                match={"path": "/internal/"})
    h.random_writes(25)
    h.clear_faults()
    h.await_oracle()
    h.await_replica_convergence(expected_holders=2)
    return h


def scenario_dropped_placement_broadcast(cluster,
                                         seed: int) -> ChaosHarness:
    """The coordinator's status broadcasts all drop (the one resize-
    completion message included): peers must still converge onto the
    new placement via the version riding every heartbeat
    (pull-on-mismatch), with the broadcasts STILL dropped."""
    h = ChaosHarness(cluster, seed, index="chaos_bcast")
    h.setup()
    h.random_writes(10)
    coord = h.coordinator_index()
    before = max(h.placement_versions())
    h.set_fault(coord, "cluster.broadcast", "drop")
    h.client(coord)._json("POST", "/internal/resize/trigger", {})
    # convergence must happen WHILE broadcasts are dropped — the
    # heartbeat version pull is the only remaining channel
    h.await_placement_convergence(min_version=before)
    h.clear_faults()
    h.await_all_normal()
    h.await_oracle()
    return h


def scenario_dropped_internal_response_trace(cluster,
                                             seed: int) -> ChaosHarness:
    """Traces must not lie under failure: a fan-out leg whose response
    is dropped (``client.recv`` failpoint — the peer answered, the
    coordinator never heard it) is transparently redelivered by the
    idempotent internode retry, and the coordinator's profile tree must
    SAY so — the grafted remote subtree carries a ``retried`` tag, the
    answer stays oracle-exact."""
    import json as _json

    h = ChaosHarness(cluster, seed, index="chaos_trace")
    h.setup()
    # row 0 populated in every shard, so any shard-restricted Count
    # has bits to count
    for s in range(3):
        if not h.write(0, s * SHARD_WIDTH + 1):
            raise h._fail("setup write did not ack")
    h.random_writes(10)
    h.check_oracle()
    # a remote leg must be GUARANTEED, not left to hash placement: pick
    # an entry node missing some shard and restrict the query to it
    # (with replicas < nodes such a pair always exists)
    entry = shard = None
    for i in range(h.n):
        held = h.client(i)._json(
            "GET", f"/internal/shards?index={h.index}")["shards"]
        missing = [s for s in range(3) if s not in held]
        if missing:
            entry, shard = i, missing[0]
            break
    if entry is None:
        raise h._fail("every node holds every shard; no remote leg")
    h.set_fault(entry, "client.recv", "drop", nth=1,
                match={"path": "/internal/query"})
    try:
        resp = h.client(entry)._do(
            "POST",
            f"/index/{h.index}/query?profile=true&shards={shard}",
            f"Count(Row({h.field}=0))".encode())
    finally:
        h.clear_faults()
    # the answer is still oracle-bounded (acked ⊆ observed ⊆ attempted,
    # restricted to the queried shard)
    count = resp["results"][0]
    acked = {c for c in h.acked.get(0, ()) if c // SHARD_WIDTH == shard}
    att = {c for c in h.attempted.get(0, ()) if c // SHARD_WIDTH == shard}
    if not len(acked) <= count <= len(att):
        raise h._fail(f"count {count} outside oracle "
                      f"[{len(acked)}, {len(att)}] after retry")

    def walk(span):
        yield span
        for child in span.get("children", []):
            yield from walk(child)

    spans = [s for root in resp["profile"] for s in walk(root)]
    retried = [s for s in spans if s.get("tags", {}).get("retried")]
    if not retried:
        raise h._fail(
            "trace hides the dropped-response redelivery: no span "
            f"tagged retried in {_json.dumps(resp['profile'])[:800]}")
    entry_id = f"127.0.0.1:{cluster.nodes[entry].port}"
    if not all(s["tags"].get("node") not in (None, entry_id)
               for s in retried):
        raise h._fail("retried tag landed on a non-remote span")
    h.check_oracle()
    return h


def scenario_node_kill_failover(cluster, seed: int) -> ChaosHarness:
    """kill -9 a replica-holding node MID-SERVE (replicas=2, hinted
    handoff DISABLED — the legacy strict-write pin): every read keeps
    answering oracle-exact through replica failover — zero query
    failures from the kill onward — the entry node's breaker for the
    dead peer opens (routing then skips it entirely), strict writes
    refuse loudly with the structured 503, and after a restart the
    breaker closes via heartbeat probes and every node serves again.
    Requires a cluster booted with ``PILOSA_HINT_MAX_AGE=0`` (see
    SCENARIOS) — the handoff-enabled write path has its own scenario,
    ``clear_during_kill_handoff``."""
    h = ChaosHarness(cluster, seed, index="chaos_kill")
    h.setup()
    # bits in every shard so every node's shard group is exercised
    for s in range(3):
        if not h.write(0, s * SHARD_WIDTH + 1):
            raise h._fail("setup write did not ack")
    h.random_writes(30)
    h.check_oracle()
    coord = h.coordinator_index()
    victim = next(i for i in range(h.n) if i != coord)
    entry = next(i for i in range(h.n) if i != victim)
    victim_id = h.node_id(victim)
    cluster.nodes[victim].kill9()
    # serve THROUGH the failure: every read from the kill to past
    # breaker-open must answer, oracle-exact — zero failures allowed
    # (pre-horizon legs to the corpse fail over; post-open routing
    # skips it outright)
    deadline = time.monotonic() + 30
    reads = 0
    opened = False
    while time.monotonic() < deadline:
        try:
            h.check_oracle(via=entry)
        except InvariantViolation:
            raise
        except (ClientError, OSError) as e:
            raise h._fail(f"read failed after kill -9: {e!r}")
        reads += 1
        if h.breaker_state(entry, victim_id) == "open":
            opened = True
            break
    if not opened:
        raise h._fail(f"breaker never opened for the dead peer "
                      f"({reads} reads served)")
    if h.counter_total(entry, "read_failover_total") < 1:
        raise h._fail("no read ever failed over to a replica")
    for _ in range(5):  # breaker open: reads keep serving
        h.check_oracle(via=entry)
    # write-path strictness (handoff disabled): ClearRow touches every
    # replica including the dead one and must refuse loudly with the
    # structured 503 (r13) — never half-apply
    try:
        h.client(entry).query(h.index, f"ClearRow({h.field}=0)")
    except (ClientError, OSError) as e:
        if getattr(e, "status", 0) != 503:
            raise h._fail(f"strict write failed oddly: {e!r}")
    else:
        raise h._fail("ClearRow succeeded with a replica dead and "
                      "handoff disabled")
    h.check_oracle(via=entry)  # the refused clear mutated nothing
    # restart: the breaker must close via the heartbeat probe and the
    # node must serve its shards again
    node = cluster.nodes[victim]
    node.stop()  # reap the corpse + release the log handle
    node.start()
    node.await_up()
    cluster.await_membership(3)
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        if h.breaker_state(entry, victim_id) == "closed":
            break
        time.sleep(0.3)
    else:
        raise h._fail("breaker never closed after the node returned")
    h.await_oracle()  # every node (the restarted one included) exact
    return h


def scenario_straggler_hedged_read(cluster, seed: int) -> ChaosHarness:
    """A straggler leg (``dist.fanout`` delay failpoint) with hedging
    on: the entry node duplicates the leg to a live replica after
    ``hedge_after``, the first answer wins — latency stays bounded by
    the hedge, the result stays oracle-exact, and the winning subtree
    carries the ``hedged`` trace tag.  Requires a cluster booted with
    ``PILOSA_HEDGE_AFTER`` (see SCENARIOS)."""
    h = ChaosHarness(cluster, seed, index="chaos_hedge")
    h.setup()
    for s in range(3):
        if not h.write(0, s * SHARD_WIDTH + 1):
            raise h._fail("setup write did not ack")
    h.random_writes(10)
    h.check_oracle()
    # guarantee a remote leg AND a remote hedge target: pick an entry
    # node holding none of some shard — with replicas=2 its two owners
    # are both other nodes (the dropped-response trace scenario's
    # discovery)
    entry = shard = None
    for i in range(h.n):
        held = h.client(i)._json(
            "GET", f"/internal/shards?index={h.index}")["shards"]
        missing = [s for s in range(3) if s not in held]
        if missing:
            entry, shard = i, missing[0]
            break
    if entry is None:
        raise h._fail("every node holds every shard; no remote leg")
    h.set_fault(entry, "dist.fanout", "delay", nth=1,
                match={"index": h.index}, args={"seconds": 1.5})
    t0 = time.monotonic()
    try:
        resp = h.client(entry)._do(
            "POST",
            f"/index/{h.index}/query?profile=true&shards={shard}",
            f"Count(Row({h.field}=0))".encode())
    finally:
        h.clear_faults()
    elapsed = time.monotonic() - t0
    count = resp["results"][0]
    acked = {c for c in h.acked.get(0, ()) if c // SHARD_WIDTH == shard}
    att = {c for c in h.attempted.get(0, ())
           if c // SHARD_WIDTH == shard}
    if not len(acked) <= count <= len(att):
        raise h._fail(f"hedged count {count} outside oracle "
                      f"[{len(acked)}, {len(att)}]")
    if elapsed >= 1.2:
        raise h._fail(f"hedge did not bound the straggler: the query "
                      f"took {elapsed:.2f}s against a 1.5s delay")

    def walk(span):
        yield span
        for child in span.get("children", []):
            yield from walk(child)

    spans = [s for root in resp["profile"] for s in walk(root)]
    if not any(s.get("tags", {}).get("hedged") for s in spans):
        raise h._fail("winning subtree lost its hedged trace tag")
    if h.counter_total(entry, "read_hedged_total") < 1:
        raise h._fail("read_hedged_total never incremented")
    h.check_oracle()
    return h


def scenario_breaker_lifecycle(cluster, seed: int) -> ChaosHarness:
    """Breaker lifecycle pinned end-to-end: an asymmetric partition
    (entry cannot reach the victim; the victim's inbound heartbeats
    keep it 'alive') accumulates transport failures until the breaker
    OPENS — reads stay exact throughout via failover, then stop
    detouring (routing skips the open peer: the failover counter goes
    quiet).  Healing the partition lets the heartbeat probe walk
    open → half_open → closed, visible in breaker_transitions_total."""
    h = ChaosHarness(cluster, seed, index="chaos_breaker")
    h.setup()
    h.random_writes(20)
    h.check_oracle()
    coord = h.coordinator_index()
    victim = next(i for i in range(h.n) if i != coord)
    entry = next(i for i in range(h.n) if i != victim)
    victim_id = h.node_id(victim)
    h.set_fault(entry, "client.send", "partition",
                match={"peer": victim_id})
    deadline = time.monotonic() + 20
    while time.monotonic() < deadline:
        h.check_oracle(via=entry)  # must never fail while opening
        if h.breaker_state(entry, victim_id) == "open":
            break
    else:
        raise h._fail("breaker never opened under the partition")
    # open: routing skips the peer — no more failover churn
    base = h.counter_total(entry, "read_failover_total")
    for _ in range(5):
        h.check_oracle(via=entry)
    if h.breaker_state(entry, victim_id) in ("open", "half_open") \
            and h.counter_total(entry, "read_failover_total") != base:
        raise h._fail("open breaker still paid per-query failovers")
    h.clear_faults()
    # heal: the heartbeat probe closes it
    deadline = time.monotonic() + 20
    while time.monotonic() < deadline:
        if h.breaker_state(entry, victim_id) == "closed":
            break
        time.sleep(0.2)
    else:
        raise h._fail("breaker never closed after the partition healed")
    text = h.client(entry).metrics_text()
    for leg in ('to="open"', 'to="half_open"', 'to="closed"'):
        if ("breaker_transitions_total{" not in text
                or leg not in text):
            raise h._fail(f"breaker transition {leg} not exported")
    h.check_oracle()
    return h


def scenario_clear_during_kill_handoff(cluster, seed: int) -> ChaosHarness:
    """kill -9 one of replicas=2 MID-SERVE with durable hinted handoff
    ON (the default): Set, Clear and ClearRow ALL keep serving — zero
    refusals from the kill through breaker-open — with the dead
    owner's copies durably hinted on the entry node.  After a restart
    the heartbeat-triggered drain replays the hint log in order; every
    node then answers oracle-exact, and a forced anti-entropy round on
    every node resurrects nothing (AAE deferred union-merge while the
    hints were pending — the r13 ordering rule)."""
    h = ChaosHarness(cluster, seed, index="chaos_handoff")
    h.setup()
    for s in range(3):
        if not h.write(0, s * SHARD_WIDTH + 1):
            raise h._fail("setup write did not ack")
    h.random_writes(24)
    h.check_oracle()
    coord = h.coordinator_index()
    victim = next(i for i in range(h.n) if i != coord)
    entry = next(i for i in range(h.n) if i != victim)
    victim_id = h.node_id(victim)
    cluster.nodes[victim].kill9()
    # serve writes THROUGH the corpse: every op class must keep acking
    # (pre-breaker legs to the dead node fail mid-apply and hand off;
    # post-open the split hints up front) — zero refusals allowed
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        row = h.rng.randrange(h.N_ROWS)
        if not h.write(row, h.rng.randrange(h.MAX_COL), via=entry):
            raise h._fail("Set refused with a replica dead")
        if not h.clear(row, h.rng.randrange(h.MAX_COL), via=entry):
            raise h._fail("Clear refused with a replica dead")
        if h.breaker_state(entry, victim_id) == "open":
            break
    else:
        raise h._fail("breaker never opened for the dead peer")
    if not h.clear_row(2, via=entry):
        raise h._fail("ClearRow refused with a replica dead")
    # post-open writes keep serving too (handoff up front now)
    if not h.write(2, 5, via=entry) or not h.clear(2, 5, via=entry):
        raise h._fail("write refused after breaker opened")
    # the missed copies are durably queued and visible on writeHealth
    wh = h.client(entry).write_health()
    if not wh.get("hintBacklogOps"):
        raise h._fail(f"no hint backlog after serving through a dead "
                      f"replica: {wh}")
    if victim_id not in {p["id"] for p in wh.get("peers", [])}:
        raise h._fail(f"dead peer missing from writeHealth: {wh}")
    for i in (coord, entry):
        h.check_oracle(via=i)  # live nodes exact while hints pend
    # restart: rejoin triggers the drain; the log must empty and every
    # node (the rejoined one included) answer oracle-exact
    node = cluster.nodes[victim]
    node.stop()  # reap the corpse + release handles
    node.start()
    node.await_up()
    cluster.await_membership(3, timeout=120)
    h.await_hints_drained(entry)
    h.await_oracle()
    if h.counter_total(entry, "hint_replay_total") < 1:
        raise h._fail("hint_replay_total never incremented")
    # the sharpest invariant: force AAE everywhere AFTER the drain —
    # union-merge must not resurrect a single cleared bit
    for i in range(h.n):
        h.client(i)._json("POST", "/internal/aae/run", {})
    h.check_oracle()
    return h


def scenario_coordinator_crash_hint_log(cluster, seed: int) -> ChaosHarness:
    """kill -9 the WRITE COORDINATOR mid-hint-append (replicas=2, one
    peer already dead and hinted): the ``hints.append`` torn-write
    failpoint persists only a prefix of the record before the crash.
    Recovery must yield a replayable-or-cleanly-truncated log — the
    acked clears (the clean prefix) replay to the rejoined peer and
    stay absent everywhere, while the torn op NEVER applies: its
    un-acked Clear's bit remains present on every node (hint-before-
    apply ordering means nothing mutated before the tear)."""
    h = ChaosHarness(cluster, seed, index="chaos_hintcrash")
    h.setup()
    for s in range(3):
        if not h.write(0, s * SHARD_WIDTH + 1):
            raise h._fail("setup write did not ack")
    h.random_writes(16)
    h.check_oracle()
    coord = h.coordinator_index()
    victim = next(i for i in range(h.n) if i != coord)
    entry = next(i for i in range(h.n) if i != victim)
    # shards the victim replicates: a strict Clear there must hint.
    # torn_col (set in setup, still acked) is the victim of the torn
    # append — the cleared loop below stays off offset 1 so it can
    # never be legitimately cleared first.
    held = sorted(h.client(victim)._json(
        "GET", f"/internal/shards?index={h.index}")["shards"])
    if not held:
        raise h._fail("victim holds no shard — scenario invalid")
    torn_col = held[0] * SHARD_WIDTH + 1
    cluster.nodes[victim].kill9()
    # acked clears while the peer is dead: these hints form the clean
    # prefix that must survive the coordinator crash and replay
    cleared_cols = []
    deadline = time.monotonic() + 30
    while len(cleared_cols) < 4 and time.monotonic() < deadline:
        s = h.rng.choice(held)
        col = s * SHARD_WIDTH + h.rng.randrange(2, 1000)
        if h.write(0, col, via=entry) and h.clear(0, col, via=entry):
            cleared_cols.append(col)
    if len(cleared_cols) < 4:
        raise h._fail("could not ack clears through the dead replica")
    wh = h.client(entry).write_health()
    if not wh.get("hintBacklogOps"):
        raise h._fail("no hints pending before the coordinator crash")
    # tear the NEXT hint append mid-record, then kill -9 the
    # coordinator (the tear IS the crash; the kill makes it real
    # before anything else can append behind the torn tail)
    h.set_fault(entry, "hints.append", "torn_write", nth=1,
                args={"offset": h.rng.randrange(1, 20)})
    try:
        h.client(entry).query(h.index, f"Clear({torn_col}, {h.field}=0)")
    except (ClientError, OSError):
        pass  # the op must FAIL: its hint never became durable
    else:
        raise h._fail("Clear acked despite a torn hint append")
    cluster.nodes[entry].kill9()
    # restart the coordinator FIRST (it recovers the hint log and
    # advertises the backlog on its heartbeats — AAE gating resumes
    # before the stale peer can sync), then the hinted peer
    for i in (entry, victim):
        node = cluster.nodes[i]
        node.stop()
        node.start()
        node.await_up()
    cluster.await_membership(3, timeout=120)
    h.await_hints_drained(entry)
    h.await_oracle()  # acked clears absent everywhere; torn-op bit
    #                   still present everywhere (it stayed acked)
    for i in range(h.n):
        h.client(i)._json("POST", "/internal/aae/run", {})
    h.check_oracle()
    return h


def scenario_bulk_import_kill_handoff(cluster, seed: int) -> ChaosHarness:
    """kill -9 one of replicas=2 MID-BULK-IMPORT (r15 ingest): import
    batches keep acking straight through the corpse — the dead owner's
    shard batches are durably hinted as ``kind: "import"`` records
    (visible as ``bulkOps`` on writeHealth) — and a CLEARING import
    (the strict class) serves through too.  After restart the
    heartbeat drain replays the import hints in order; every node then
    answers oracle-exact, forced AAE resurrects nothing that a
    clearing import removed, and a re-delivered replay batch is a
    NO-OP (op-id dedup covers bulk ops: the double-POST pin)."""
    h = ChaosHarness(cluster, seed, index="chaos_bulk")
    h.setup()
    # seed three shards via ONE bulk batch
    seed_pairs = [(r, s * SHARD_WIDTH + h.rng.randrange(1, 1000))
                  for s in range(3) for r in range(h.N_ROWS)]
    if not h.bulk_import(seed_pairs):
        raise h._fail("seed bulk import did not ack")
    h.check_oracle()
    coord = h.coordinator_index()
    victim = next(i for i in range(h.n) if i != coord)
    entry = next(i for i in range(h.n) if i != victim)
    victim_id = h.node_id(victim)
    cluster.nodes[victim].kill9()
    # bulk-import THROUGH the corpse: every batch must keep acking
    # (pre-breaker legs fail mid-apply and hand off; post-open the
    # split hints up front) — zero refusals allowed
    acked_pairs: list = []
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        batch = [(h.rng.randrange(h.N_ROWS),
                  h.rng.randrange(h.MAX_COL)) for _ in range(8)]
        if not h.bulk_import(batch, via=entry):
            raise h._fail("bulk import refused with a replica dead")
        acked_pairs.extend(batch)
        if h.breaker_state(entry, victim_id) == "open":
            break
    else:
        raise h._fail("breaker never opened for the dead peer")
    # a CLEARING import (strict class — a replica that missed it would
    # resurrect via AAE) must ALSO serve through, hinted
    if not h.bulk_import(acked_pairs[:4], via=entry, clear=True):
        raise h._fail("clearing import refused with a replica dead")
    # the missed batches are durably queued and counted as BULK ops
    wh = h.client(entry).write_health()
    if not wh.get("hintBulkOps"):
        raise h._fail(f"no bulk ops in the hint backlog: {wh}")
    for i in (coord, entry):
        h.check_oracle(via=i)  # live nodes exact while hints pend
    # op-id dedup pin: the SAME replay batch delivered twice applies
    # once — the second POST dedups every op
    held = h.client(entry)._json(
        "GET", f"/internal/shards?index={h.index}")["shards"]
    dedup_col = int(sorted(held)[0]) * SHARD_WIDTH + 1001
    ops = [{"id": "bulkdedup-" + format(seed, "x"), "index": h.index,
            "op": "Import", "field": h.field, "shards": [int(sorted(held)[0])],
            "kind": "import",
            "import": {"mode": "bits", "rows": [0], "cols": [dedup_col],
                       "clear": False}}]
    h.attempted.setdefault(0, set()).add(dedup_col)
    r1 = h.client(entry)._json("POST", "/internal/hints/replay",
                               {"ops": ops})
    r2 = h.client(entry)._json("POST", "/internal/hints/replay",
                               {"ops": ops})
    if r1.get("applied") != 1 or r2.get("deduped") != 1 \
            or r2.get("applied"):
        raise h._fail(f"bulk op-id dedup broken: first={r1} second={r2}")
    # restart: rejoin triggers the drain; every node answers
    # oracle-exact and forced AAE resurrects nothing cleared
    node = cluster.nodes[victim]
    node.stop()
    node.start()
    node.await_up()
    cluster.await_membership(3, timeout=120)
    h.await_hints_drained(entry)
    h.await_oracle()
    for i in range(h.n):
        h.client(i)._json("POST", "/internal/aae/run", {})
    h.check_oracle()
    return h


def scenario_corrupt_fragment_scrub_repair(cluster,
                                           seed: int) -> ChaosHarness:
    """Byte-flip a fragment snapshot on disk (replicas=2, r19): the
    background scrubber must DETECT the corruption (frame CRC),
    QUARANTINE the fragment — reads of the affected shard keep
    answering oracle-exact throughout, zero failures, because the
    victim's own routing skips the quarantined fragment and a peer's
    fan-out leg gets a 503 that rides the PR 6 replica-failover path —
    then AUTO-REPAIR it from the healthy replica (full position pull,
    wholesale rebuild, fresh framed snapshot, re-verify), after which
    a forced anti-entropy round on every node finds ZERO divergence
    (resurrects nothing).  Requires a cluster booted with a sub-second
    scrub interval and periodic AAE off (see SCENARIOS) — pre-
    detection, an AAE round could diff the corrupt copy outward; the
    scrub interval is exactly the knob that bounds that window."""
    import os as _os

    h = ChaosHarness(cluster, seed, index="chaos_scrub")
    h.setup()
    for s in range(3):
        if not h.write(0, s * SHARD_WIDTH + 1):
            raise h._fail("setup write did not ack")
    h.random_writes(24)
    h.check_oracle()
    coord = h.coordinator_index()
    victim = next(i for i in range(h.n) if i != coord)
    # force snapshots to disk on the victim (the tar-backup endpoint
    # compacts every dirty fragment), then flip one byte of shard 0's
    # snapshot blob IN PLACE (r+b: truncating would SIGBUS the mmap)
    h.client(victim)._do("GET", "/internal/backup")
    frag_path = _os.path.join(cluster.nodes[victim].data_dir,
                              h.index, h.field, "views", "standard",
                              "fragments", "0")
    with open(frag_path, "rb") as f:
        head = f.read(4)
    if head != b"PSF1":
        raise h._fail(f"snapshot at {frag_path} is not framed: {head!r}")
    size = _os.path.getsize(frag_path)
    with open(frag_path, "r+b") as f:
        f.seek(size - 2)
        byte = f.read(1)
        f.seek(size - 2)
        f.write(bytes([byte[0] ^ 0x55]))
    # the scrubber (sub-second interval) must detect the flip.  The
    # repair hook runs in the SAME pass, so the quarantine window can
    # be too short to observe on /status — the detection counter is
    # the reliable witness
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        if h.counter_total(victim,
                           "storage_corruption_detected_total") >= 1:
            break
        time.sleep(0.1)
    else:
        raise h._fail("scrubber never detected the flipped byte")
    # from detection on: EVERY read on EVERY node answers oracle-exact
    # — zero failures — while repair converges in the background
    # (quarantined legs 503 and ride the replica-failover path)
    repaired = False
    deadline = time.monotonic() + 40
    while time.monotonic() < deadline:
        for i in range(h.n):
            try:
                h.check_oracle(via=i)
            except InvariantViolation:
                raise
            except (ClientError, OSError) as e:
                raise h._fail(f"read failed during quarantine: {e!r}")
        sh = h.client(victim)._json(
            "GET", "/status").get("storageHealth", {})
        if not sh.get("quarantined") \
                and h.counter_total(victim, "storage_repair_total") >= 1:
            repaired = True
            break
    if not repaired:
        raise h._fail("quarantined fragment was never repaired")
    if h.counter_total(victim, "storage_corruption_detected_total") < 1:
        raise h._fail("storage_corruption_detected_total never counted")
    if h.counter_total(victim, "storage_repair_total") < 1:
        raise h._fail("storage_repair_total never counted")
    last = h.client(victim)._json(
        "GET", "/status")["storageHealth"].get("lastRepair")
    if not last:
        raise h._fail("storageHealth.lastRepair missing after repair")
    # the repaired bytes must re-verify as a healthy framed snapshot
    with open(frag_path, "rb") as f:
        if f.read(4) != b"PSF1":
            raise h._fail("repair did not rewrite a framed snapshot")
    # forced AAE everywhere: ZERO divergence (the repair pulled the
    # replica's full set — union-merge must find nothing to move)
    for i in range(h.n):
        got = h.client(i)._json("POST", "/internal/aae/run", {})
        if got.get("repaired"):
            raise h._fail(
                f"forced AAE on node {i} repaired "
                f"{got['repaired']} blocks after replica repair "
                "(divergence survived)")
    h.check_oracle()
    h.await_replica_convergence(expected_holders=2)
    return h


def scenario_disk_full_during_ingest(cluster, seed: int) -> ChaosHarness:
    """ENOSPC mid-bulk-import (replicas=2, r19): the victim's first
    failing op-log append flips it READ-ONLY — bulk-import batches via
    the healthy entry node keep ACKING (the victim's 507 legs are
    classified hint-worthy and durably hinted, the PR 8 machinery),
    direct writes at the victim refuse with the structured 507
    ``writeUnavailable{reason: "disk_full"}`` (never a crash, never a
    torn ack), reads keep answering on BOTH nodes — then 'freeing
    space' (clearing the fault) lets the probe restore HEALTHY, the
    heartbeat drain replays the hinted batches in order, and every
    node ends bit-exact (forced AAE resurrects nothing).  Requires a
    sub-second disk probe (see SCENARIOS)."""
    h = ChaosHarness(cluster, seed, index="chaos_enospc")
    h.setup()
    seed_pairs = [(r, s * SHARD_WIDTH + h.rng.randrange(1, 1000))
                  for s in range(3) for r in range(h.N_ROWS)]
    if not h.bulk_import(seed_pairs):
        raise h._fail("seed bulk import did not ack")
    h.check_oracle()
    # the mid-outage oracle: bits acked BEFORE the disk fills must
    # stay readable on every node throughout (the read-only replica
    # is merely STALE for the writes hinted PAST it — the standard
    # replica-consistency caveat — so the full oracle only applies
    # again after the drain)
    pre_acked = {r: set(c) for r, c in h.acked.items()}
    coord = h.coordinator_index()
    victim = next(i for i in range(h.n) if i != coord)
    entry = coord
    # ENOSPC on every durable write under the victim's data dir —
    # op-logs, snapshots AND the governor's probe file, so the node
    # stays read-only until the 'disk' recovers (fault cleared)
    h.set_fault(victim, "sys.write", "error",
                args={"errno": "ENOSPC"},
                match={"path": cluster.nodes[victim].data_dir})
    # bulk-import THROUGH the full disk: every batch must keep acking
    # (the victim's legs refuse 507 and hand off as hints)
    flipped = False
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        batch = [(h.rng.randrange(h.N_ROWS),
                  h.rng.randrange(h.MAX_COL)) for _ in range(8)]
        if not h.bulk_import(batch, via=entry):
            raise h._fail("bulk import refused while one replica's "
                          "disk is full")
        st = h.client(victim)._json(
            "GET", "/status").get("storageHealth", {})
        if st.get("state") == "read_only":
            flipped = True
            break
    if not flipped:
        raise h._fail("victim never flipped read-only under ENOSPC")
    # structured refusal at the read-only node: a direct strict write
    # must answer 507 with the writeUnavailable{disk_full} body (raw
    # request — the client helper strips the structured fields)
    import http.client as _httpc
    import json as _json
    # at-least-once honest: the healthy replica's leg may apply before
    # the read-only node's local leg refuses — an attempted, un-acked
    # write (exactly the torn-ack class the oracle absorbs)
    h.attempted.setdefault(0, set()).add(1)
    h.cleared.setdefault(0, set()).discard(1)
    conn = _httpc.HTTPConnection("127.0.0.1",
                                 cluster.nodes[victim].port, timeout=15)
    try:
        body = f"Set(1, {h.field}=0)".encode()
        conn.request("POST", f"/index/{h.index}/query", body,
                     headers={"Content-Length": str(len(body))})
        resp = conn.getresponse()
        payload = _json.loads(resp.read().decode())
    finally:
        conn.close()
    if resp.status != 507:
        raise h._fail(f"read-only write answered {resp.status}, want "
                      f"the structured 507: {payload}")
    wu = payload.get("writeUnavailable") or {}
    if wu.get("reason") != "disk_full":
        raise h._fail(f"507 body lacks writeUnavailable.disk_full: "
                      f"{payload}")
    if not resp.getheader("Retry-After"):
        raise h._fail("507 refusal carries no Retry-After header")
    # the hinted backlog for the victim is durably queued on the entry
    wh = h.client(entry).write_health()
    if not wh.get("hintBacklogOps"):
        raise h._fail(f"no hints queued for the disk-full replica: {wh}")
    # reads: full availability on BOTH nodes — every query answers,
    # pre-outage acked bits all present, nothing phantom, Count
    # consistent (writes hinted DURING the outage may lag on legs the
    # stale replica serves; the full oracle re-applies after drain)
    for i in range(h.n):
        for row in range(h.N_ROWS):
            try:
                res = h.client(i).query(
                    h.index,
                    f"Row({h.field}={row})"
                    f"Count(Row({h.field}={row}))")
            except (ClientError, OSError) as e:
                raise h._fail(
                    f"read failed on node {i} during disk-full: {e!r}")
            got = set(res[0]["columns"])
            if not pre_acked.get(row, set()) <= got:
                raise h._fail(
                    f"node {i} row {row}: pre-outage acked bits lost "
                    f"during disk-full degradation")
            if not got <= h.attempted.get(row, set()):
                raise h._fail(f"node {i} row {row}: phantom bits "
                              "during disk-full degradation")
            if res[1] != len(got):
                raise h._fail(f"node {i} row {row}: Count/Row mismatch "
                              "during disk-full degradation")
    if h.counter_total(victim, "fault_triggered_total") < 1:
        raise h._fail("the ENOSPC fault never actually fired")
    # 'free space': clear the fault — the probe restores HEALTHY
    h.clear_faults()
    deadline = time.monotonic() + 20
    while time.monotonic() < deadline:
        st = h.client(victim)._json(
            "GET", "/status").get("storageHealth", {})
        if st.get("state") == "healthy":
            break
        time.sleep(0.2)
    else:
        raise h._fail("victim never recovered after space freed")
    # the drain replays the hinted batches; every node ends bit-exact
    h.await_hints_drained(entry)
    h.await_oracle()
    if h.counter_total(entry, "hint_replay_total") < 1:
        raise h._fail("hint_replay_total never incremented")
    for i in range(h.n):
        h.client(i)._json("POST", "/internal/aae/run", {})
    h.check_oracle()
    return h


def scenario_hung_dispatch_serving(cluster, seed: int) -> ChaosHarness:
    """A device dispatch HANGS mid-serve (r18): the ``exec.dispatch_hang``
    failpoint stalls one plane's whole-plane row-count dispatch (the
    kind a multi-Count request over index A rides) while concurrent
    single-Count traffic against index B keeps flowing.  Invariants:

    - every B query from before the hang to after recovery answers
      oracle-exact — ZERO failures (availability 1.0 for unaffected
      work: the watchdog bounds the stall per group/window, so B's
      items are never wedged behind A's sick dispatch);
    - the wedged A caller receives a STRUCTURED error naming the
      stalled stage (504 timeout with ``stage`` or 500
      ``pipelineStall``) within its deadline + one watchdog period +
      grace — never a hung connection;
    - the watchdog trip degrades the governor and, after the fault
      clears, probing returns it to HEALTHY (visible on /status
      deviceHealth);
    - no leaked pipeline threads after recovery: exactly one collector
      and at most one readback worker remain once the zombie unwedges
      (the post-scenario thread census, via /debug/threads).

    Requires a cluster booted with a sub-second watchdog + probe and
    the solo fast lane off (see SCENARIOS extra_env) — the hang must
    land in the windowed dispatch the watchdog governs."""
    h = ChaosHarness(cluster, seed, index="chaos_hang_a")
    c = h.client(0)
    index_b = "chaos_hang_b"
    h.setup()
    c.create_index(index_b)
    c.create_field(index_b, h.field)
    # deterministic oracles: all writes happen BEFORE the fault
    want_a = {}
    for row in range(3):
        cols = {h.rng.randrange(h.MAX_COL) for _ in range(6)}
        for col in cols:
            c.query(h.index, f"Set({col}, {h.field}={row})")
        want_a[row] = len(cols)
    want_b = {}
    for row in range(3):
        cols = {h.rng.randrange(h.MAX_COL) for _ in range(5)}
        for col in cols:
            c.query(index_b, f"Set({col}, {h.field}={row})")
        want_b[row] = len(cols)
    # warm both planes: the multi-Count A request must ride the
    # resident whole-plane rowcounts path before the hang is armed.
    # Retried: the scenario boots with a 0.4s watchdog, and a
    # first-time XLA compile legitimately outliving it just gets a
    # quarantine 500 — a retry hits the now-cached program.
    pql_a = "".join(f"Count(Row({h.field}={r}))" for r in range(3))
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        try:
            if c.query(h.index, pql_a) == [want_a[r] for r in range(3)]:
                break
        except (ClientError, OSError):
            pass
        time.sleep(0.2)
    else:
        raise h._fail("index A plane never warmed")
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        try:
            if all(c.query(index_b, f"Count(Row({h.field}={row}))")
                   == [want_b[row]] for row in range(3)):
                break
        except (ClientError, OSError):
            pass
        time.sleep(0.2)
    else:
        raise h._fail("index B never warmed oracle-exact")
    # if a warm-up compile tripped the 0.4s watchdog, let the governor
    # probe back before the measured episode starts (queries must keep
    # flowing — probes ride collection windows)
    deadline = time.monotonic() + 20
    while time.monotonic() < deadline:
        try:
            c.query(index_b, f"Count(Row({h.field}=0))")
            if c._json("GET", "/status")["deviceHealth"]["state"] \
                    == "healthy":
                break
        except (ClientError, OSError):
            pass
        time.sleep(0.1)
    else:
        raise h._fail("governor not healthy before the hang")

    # unaffected traffic: hammer B single-Counts THROUGH the stall
    import threading
    b_errors: list = []
    b_served = [0]
    stop_at = [time.monotonic() + 12.0]

    def b_reader(i: int) -> None:
        bc = cluster.client(0)
        row = i % 3
        while time.monotonic() < stop_at[0]:
            try:
                got = bc.query(index_b,
                               f"Count(Row({h.field}={row}))")
            except (ClientError, OSError) as e:
                b_errors.append(f"B query failed: {e!r}")
                return
            if got != [want_b[row]]:
                b_errors.append(f"B answer diverged: {got} != "
                                f"[{want_b[row]}]")
                return
            b_served[0] += 1
    readers = [threading.Thread(target=b_reader, args=(i,))
               for i in range(4)]
    for t in readers:
        t.start()
    time.sleep(0.5)  # readers established through the healthy path
    # the hang: one plane's (index A's) rowcounts dispatch stalls for
    # 2s — well past the 0.4s watchdog — exactly once
    h.set_fault(0, "exec.dispatch_hang", "delay", times=1,
                match={"kind": "rowcounts"}, args={"seconds": 2.0})
    t0 = time.monotonic()
    try:
        c._do("POST", f"/index/{h.index}/query?timeout=1.0",
              pql_a.encode())
    except ClientError as e:
        elapsed = time.monotonic() - t0
        if e.status not in (500, 504):
            raise h._fail(
                f"wedged caller got status {e.status}, not a "
                f"structured 500/504: {e}")
        msg = str(e)
        if "dispatch" not in msg and "pipeline" not in msg \
                and "stage" not in msg:
            raise h._fail(f"error does not name the stalled stage: "
                          f"{msg!r}")
        # deadline (1.0) + one watchdog period (0.4) + grace
        if elapsed > 1.0 + 0.4 + 1.0:
            raise h._fail(f"wedged caller held {elapsed:.2f}s — past "
                          f"deadline + watchdog + grace")
    else:
        raise h._fail("query through a hung dispatch succeeded "
                      "inside its 1s deadline against a 2s stall")
    finally:
        h.clear_faults()
    # the governor tripped (watchdog) and must probe back to healthy
    deadline = time.monotonic() + 20
    saw_degraded = False
    while time.monotonic() < deadline:
        dh = c._json("GET", "/status").get("deviceHealth", {})
        if dh.get("state") in ("degraded", "probing"):
            saw_degraded = True
        if saw_degraded and dh.get("state") == "healthy":
            break
        time.sleep(0.1)
    else:
        raise h._fail(
            f"governor never walked degraded→healthy after the hang "
            f"(last state {dh.get('state')!r}, saw_degraded="
            f"{saw_degraded})")
    if h.counter_total(0, "pipeline_watchdog_trips_total") < 1:
        raise h._fail("pipeline_watchdog_trips_total never incremented")
    if h.counter_total(0, "pipeline_quarantined_windows_total") < 1:
        raise h._fail("no window was ever quarantined")
    # flight recorder (r19): the trip auto-dumped an artifact, the
    # live ring resolves via /debug/flight, and the quarantine event
    # names the stalled stage
    flight = c._json("GET", "/debug/flight")
    quar = [e for e in flight.get("events", ())
            if e.get("kind") == "quarantine"]
    if not quar:
        raise h._fail("no quarantine event in /debug/flight after "
                      "the watchdog trip")
    if not any(e.get("detail") in ("dispatch", "readback")
               for e in quar):
        raise h._fail(f"quarantine flight event does not name a "
                      f"pipeline stage: {quar[:3]}")
    dumps = flight.get("dumps", ())
    if not dumps:
        raise h._fail("watchdog trip produced no flight-dump artifact")
    import os as _os
    if not _os.path.exists(dumps[-1]):
        raise h._fail(f"flight dump path does not resolve on disk: "
                      f"{dumps[-1]}")
    # recovered: A serves exact again (fresh collector, healthy state)
    if c.query(h.index, pql_a) != [want_a[r] for r in range(3)]:
        raise h._fail("index A diverged after recovery")
    stop_at[0] = 0.0
    for t in readers:
        t.join(timeout=30)
    if b_errors:
        raise h._fail(f"unaffected traffic failed through the stall: "
                      f"{b_errors[:3]}")
    if b_served[0] < 8:
        raise h._fail(f"B readers served only {b_served[0]} queries — "
                      f"not meaningful coverage of the stall window")
    # thread census: after the 2s delay resolves, the superseded
    # zombie collector exits — exactly one live collector, at most
    # one readback worker, at most one watchdog remain
    deadline = time.monotonic() + 15
    while time.monotonic() < deadline:
        dump = h.client(0)._do("GET", "/debug/threads").decode()
        census = {
            name: dump.count(f"Thread {name} (")
            for name in ("pilosa-count-batcher",
                         "pilosa-batch-readback",
                         "pilosa-pipeline-watchdog")}
        if (census["pilosa-count-batcher"] == 1
                and census["pilosa-batch-readback"] <= 1
                and census["pilosa-pipeline-watchdog"] <= 1):
            break
        time.sleep(0.3)
    else:
        raise h._fail(f"pipeline threads leaked after recovery: "
                      f"{census}")
    return h


def scenario_flaky_device_governor(cluster, seed: int) -> ChaosHarness:
    """A FLAKY-then-healthy device (r18): ``exec.dispatch_error``
    fails consecutive fused dispatches (each falls back per item —
    answers stay oracle-exact) until the governor's breaker degrades
    serving; once the fault schedule exhausts, a probe window flips it
    back to healthy.  Invariants: every query answers exactly through
    the whole episode, the governor walks
    healthy→degraded→(probing)→healthy on /status, and
    ``device_health_state`` is exported on /metrics.  Requires a
    cluster booted with a sub-second probe interval and the solo fast
    lane off (see SCENARIOS extra_env)."""
    h = ChaosHarness(cluster, seed, index="chaos_flaky")
    c = h.client(0)
    h.setup()
    for row in range(3):
        for _ in range(5):
            if not h.write(row, h.rng.randrange(h.MAX_COL)):
                raise h._fail("setup write did not ack")
    want = {row: len(h.acked.get(row, ())) for row in range(3)}
    for row in range(3):  # warm the fused path
        if c.query(h.index, f"Count(Row({h.field}={row}))") \
                != [want[row]]:
            raise h._fail("warmup count diverged")
    if c._json("GET", "/status")["deviceHealth"]["state"] != "healthy":
        raise h._fail("governor not healthy before the fault")
    # enough consecutive faults to cross the breaker threshold (3),
    # plus one to fail the first probe — then the device 'heals'
    h.set_fault(0, "exec.dispatch_error", "error", times=4)
    saw = {"degraded": False, "healthy_again": False}
    deadline = time.monotonic() + 30
    i = 0
    try:
        while time.monotonic() < deadline:
            row = i % 3
            i += 1
            got = c.query(h.index, f"Count(Row({h.field}={row}))")
            if got != [want[row]]:
                raise h._fail(
                    f"answer diverged under dispatch faults: {got} != "
                    f"[{want[row]}] (degraded serving must stay exact)")
            state = c._json("GET", "/status")["deviceHealth"]["state"]
            if state in ("degraded", "probing"):
                saw["degraded"] = True
            elif state == "healthy" and saw["degraded"]:
                saw["healthy_again"] = True
                break
            time.sleep(0.05)
    finally:
        h.clear_faults()
    if not saw["degraded"]:
        raise h._fail("governor never degraded under consecutive "
                      "dispatch faults")
    if not saw["healthy_again"]:
        raise h._fail("governor never probed back to healthy after "
                      "the fault schedule exhausted")
    if h.counter_total(0, "fault_triggered_total") < 3:
        raise h._fail("dispatch faults never actually fired")
    if "device_health_state" not in c.metrics_text():
        raise h._fail("device_health_state missing from /metrics")
    h.check_oracle()
    return h


SCENARIOS = {
    "partition_during_resize": (scenario_partition_during_resize, 3),
    "crash_mid_oplog_append": (scenario_crash_mid_oplog_append, 1),
    "duplicate_delivery": (scenario_duplicate_delivery, 2),
    "dropped_placement_broadcast": (scenario_dropped_placement_broadcast,
                                    2),
    "dropped_internal_response_trace":
        (scenario_dropped_internal_response_trace, 3),
    # r11 — serving through failure (the third element, when present,
    # is extra env the scenario's cluster must boot with)
    "node_kill_failover": (scenario_node_kill_failover, 3,
                           {"PILOSA_HINT_MAX_AGE": "0"}),
    "straggler_hedged_read": (scenario_straggler_hedged_read, 3,
                              {"PILOSA_HEDGE_AFTER": "0.15"}),
    "breaker_lifecycle": (scenario_breaker_lifecycle, 3),
    # r13 — writes through failure (durable hinted handoff)
    "clear_during_kill_handoff": (scenario_clear_during_kill_handoff, 3),
    "coordinator_crash_hint_log": (scenario_coordinator_crash_hint_log,
                                   3),
    # r15 — ingest (bulk imports through failure, op-id dedup)
    "bulk_import_kill_handoff": (scenario_bulk_import_kill_handoff, 3),
    # r19 — storage integrity (scrub + quarantine + replica repair,
    # disk-full governor): sub-second scrub/probe so the drills finish
    # under tier-1; periodic AAE off for the corruption drill (pre-
    # detection, an AAE round could diff the corrupt copy outward —
    # the scrub interval is the knob bounding that window)
    "corrupt_fragment_scrub_repair":
        (scenario_corrupt_fragment_scrub_repair, 2,
         {"PILOSA_SCRUB_INTERVAL_SECONDS": "0.4",
          "PILOSA_ANTI_ENTROPY_INTERVAL": "0"}),
    "disk_full_during_ingest":
        (scenario_disk_full_during_ingest, 2,
         {"PILOSA_DISK_PROBE_SECONDS": "0.3"}),
    # r18 — self-healing dispatch pipeline (watchdog, quarantine,
    # device health governor): sub-second watchdog/probe so the
    # scenarios finish under tier-1, fast lane off so the injected
    # hang lands in the windowed dispatch the watchdog governs
    "hung_dispatch_serving": (scenario_hung_dispatch_serving, 1,
                              {"PILOSA_DISPATCH_WATCHDOG_SECONDS": "0.4",
                               "PILOSA_DEVICE_HEALTH_PROBE_SECONDS":
                                   "0.4",
                               "PILOSA_SOLO_FASTLANE": "0",
                               "PILOSA_COUNT_BATCH_WINDOW": "0.002"}),
    "flaky_device_governor": (scenario_flaky_device_governor, 1,
                              {"PILOSA_DEVICE_HEALTH_PROBE_SECONDS":
                                   "0.3",
                               "PILOSA_SOLO_FASTLANE": "0",
                               "PILOSA_COUNT_BATCH_WINDOW": "0.002"}),
}


def main(argv: list[str] | None = None) -> int:
    """Runbook entry: boot process clusters in a temp dir and run the
    scripted scenarios.  Exit 0 = every invariant held."""
    import argparse
    import tempfile

    from pilosa_tpu.testing import run_process_cluster

    ap = argparse.ArgumentParser(description="chaos harness")
    ap.add_argument("--seed", type=int, default=42)
    ap.add_argument("--scenario", default="all",
                    choices=["all", *SCENARIOS])
    args = ap.parse_args(argv)
    names = list(SCENARIOS) if args.scenario == "all" else [args.scenario]
    for name in names:
        fn, n_nodes, *rest = SCENARIOS[name]
        extra_env = rest[0] if rest else None
        replicas = 2 if n_nodes > 1 else 1
        with tempfile.TemporaryDirectory(prefix="chaos_") as tmp:
            with run_process_cluster(n_nodes, tmp, replicas=replicas,
                                     anti_entropy=1.0,
                                     extra_env=extra_env) as cluster:
                fn(cluster, args.seed)
        print(f"[chaos] {name}: OK (seed={args.seed})", flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
