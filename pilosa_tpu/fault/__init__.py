"""Deterministic fault injection: named failpoints woven through the
stack's I/O seams.

Reference shape: etcd's ``gofail`` / FoundationDB's simulation hooks —
a registry of *sites* (``client.send``, ``oplog.append``,
``server.response``, ``cluster.broadcast``, ``sys.write``, ``exec.oom``,
…) that production code consults through a zero-cost guard.  When no
fault is armed, an instrumented site costs one module-attribute load
and a falsy branch (``if fault.ACTIVE:``) — measured ~25 ns on this
host, invisible against any I/O it guards.

A failpoint is armed per-process via config/env (``PILOSA_FAULTS`` — a
JSON list of specs) or on a live node via the ``/internal/fault``
endpoints.  Triggers are deterministic: fire on the Nth hit of the
site, or with seeded-RNG probability per hit — either way a failure
schedule reproduces exactly from ``(spec, seed)``; there is no
wall-clock or global randomness in the trigger path.

Actions:

- ``error``       — raise :class:`FaultError` (an ``OSError``: looks
                    like the disk/socket fault it stands in for; an
                    ``errno`` arg — ``"ENOSPC"``/``"EIO"``/int — types
                    it for the disk-health governor's classification)
- ``delay``       — sleep ``seconds`` then continue
- ``oom``         — raise ``ValueError("RESOURCE_EXHAUSTED …")``, the
                    exact shape the executor's device-OOM recovery
                    classifies (:func:`exec.executor._is_device_oom`)
- ``torn_write``  — site-interpreted: write only the first ``offset``
                    bytes of the record, then raise (a crash mid-write)
- ``partition``   — site-interpreted at ``client.send``: the peer is
                    unreachable (connection refused), both no-delivery
                    directions when armed on both nodes
- ``drop_response`` — site-interpreted at ``server.response``: the
                    handler RUNS (the request is processed) but the
                    response is never written and the connection drops
                    — the peer's retry becomes a duplicate delivery
- ``drop``        — site-interpreted: skip the guarded operation
                    (e.g. ``cluster.broadcast`` silently not sent)

:func:`fire` applies the generic actions (error/delay/oom) itself and
returns the spec dict for site-interpreted ones, or ``None`` when the
failpoint did not trigger.  Every trigger increments
``fault_triggered_total{site,action}`` on the stats sink wired by the
server (visible on ``/metrics``).

Stdlib-only on purpose: this module sits below the client, store and
cluster layers and must import from none of them.
"""

from __future__ import annotations

import json
import random
import threading
import time

__all__ = ["ACTIVE", "FaultError", "set_fault", "clear", "list_faults",
           "fire", "configure", "set_stats", "triggered_total"]

# Zero-cost guard: instrumented sites check this module-level bool
# before calling fire().  Maintained by set_fault/clear/configure.
ACTIVE = False

_lock = threading.Lock()
_registry: dict[str, list["Failpoint"]] = {}
_triggered: dict[tuple[str, str], int] = {}
_stats = None  # optional metrics sink (obs.Stats duck type)


class FaultError(OSError):
    """An injected fault (subclasses OSError: at the store seams it
    stands in for a disk error, at process seams for a crash).  An
    ``errno`` fault arg (``"ENOSPC"``/``"EIO"``/an int) types the
    error so the disk-health governor's errno classification runs on
    injected faults exactly as on real ones."""


def resolve_errno(value) -> int:
    """An errno fault arg → its numeric value: int passthrough, or a
    symbolic name looked up in the stdlib ``errno`` module."""
    import errno as _errno_mod
    if isinstance(value, bool):
        raise ValueError(f"bad errno fault arg {value!r}")
    if isinstance(value, int):
        return value
    no = getattr(_errno_mod, str(value), None)
    if not isinstance(no, int):
        raise ValueError(
            f"unknown errno name {value!r} in fault args "
            "(use e.g. \"ENOSPC\", \"EIO\", or a number)")
    return no


class Failpoint:
    """One armed failpoint.  Trigger = nth-hit or seeded probability
    (both may combine with ``times``, the max number of fires)."""

    def __init__(self, site: str, action: str, nth: int | None = None,
                 prob: float | None = None, seed: int | None = None,
                 times: int | None = None, match: dict | None = None,
                 args: dict | None = None):
        if action not in ("error", "delay", "oom", "torn_write",
                          "partition", "drop_response", "drop"):
            raise ValueError(f"unknown fault action {action!r}")
        if prob is not None and not 0.0 <= prob <= 1.0:
            raise ValueError(f"fault prob must be in [0,1], got {prob}")
        self.site = site
        self.action = action
        self.nth = int(nth) if nth is not None else None
        self.prob = float(prob) if prob is not None else None
        self.seed = seed
        self.times = int(times) if times is not None else None
        self.match = dict(match or {})
        self.args = dict(args or {})
        if "errno" in self.args:
            # typed disk faults (r19): validate at arm time — a typo'd
            # errno name must fail the arming, not silently inject an
            # un-typed fault the governor then misclassifies
            self.args["errno"] = resolve_errno(self.args["errno"])
        self._rng = random.Random(seed if seed is not None else 0)
        self._hits = 0
        self._fired = 0
        self._flock = threading.Lock()

    def _matches(self, ctx: dict) -> bool:
        for key, needle in self.match.items():
            if str(needle) not in str(ctx.get(key, "")):
                return False
        return True

    def _eval(self, ctx: dict) -> bool:
        """True when this hit triggers.  Counters/RNG under the
        failpoint's own lock — concurrent hits stay deterministic in
        COUNT (each hit consumes exactly one trigger decision)."""
        if not self._matches(ctx):
            return False
        with self._flock:
            self._hits += 1
            if self.times is not None and self._fired >= self.times:
                return False
            if self.nth is not None and self._hits < self.nth:
                return False
            if self.prob is not None and self._rng.random() >= self.prob:
                return False
            if self.nth is not None and self.prob is None \
                    and self.times is None and self._hits > self.nth:
                return False  # bare nth= fires exactly once
            self._fired += 1
            return True

    def to_json(self) -> dict:
        return {"site": self.site, "action": self.action, "nth": self.nth,
                "prob": self.prob, "seed": self.seed, "times": self.times,
                "match": self.match, "args": self.args,
                "hits": self._hits, "fired": self._fired}


def set_stats(stats) -> None:
    """Wire the metrics sink (the server's Stats registry) so triggers
    surface as ``fault_triggered_total`` on ``/metrics``."""
    global _stats
    _stats = stats


def set_fault(site: str, action: str, **kw) -> dict:
    """Arm a failpoint at ``site``; multiple faults may stack on one
    site (e.g. two partition pairs).  Returns the armed spec."""
    global ACTIVE
    fp = Failpoint(site, action, **kw)
    with _lock:
        _registry.setdefault(site, []).append(fp)
        ACTIVE = True
    return fp.to_json()


def clear(site: str | None = None) -> int:
    """Disarm one site's faults (or all).  Returns the count removed."""
    global ACTIVE
    with _lock:
        if site is None:
            n = sum(len(v) for v in _registry.values())
            _registry.clear()
        else:
            n = len(_registry.pop(site, []))
        ACTIVE = bool(_registry)
    return n


def list_faults() -> list[dict]:
    with _lock:
        return [fp.to_json() for fps in _registry.values() for fp in fps]


def triggered_total() -> dict[tuple[str, str], int]:
    with _lock:
        return dict(_triggered)


def reset_triggered() -> None:
    """Zero the trigger counters (test isolation; a live node's
    counters are cumulative and never reset)."""
    with _lock:
        _triggered.clear()


def fire(site: str, **ctx) -> dict | None:
    """Evaluate ``site``'s failpoints against this hit.  Applies generic
    actions (error raises, delay sleeps, oom raises RESOURCE_EXHAUSTED)
    and returns the spec dict for site-interpreted actions — ``None``
    when nothing triggered.  Callers guard with ``if fault.ACTIVE:`` so
    the disabled path never reaches here."""
    with _lock:
        fps = list(_registry.get(site, ()))
    for fp in fps:
        if not fp._eval(ctx):
            continue
        with _lock:
            key = (site, fp.action)
            _triggered[key] = _triggered.get(key, 0) + 1
        if _stats is not None:
            _stats.count("fault_triggered_total", 1, site=site,
                         action=fp.action)
        if fp.action == "delay":
            time.sleep(float(fp.args.get("seconds", 0.05)))
            return fp.to_json()
        if fp.action == "error":
            err = FaultError(f"injected fault at {site}")
            if "errno" in fp.args:
                err.errno = fp.args["errno"]
                err.strerror = f"injected fault at {site}"
            raise err
        if fp.action == "oom":
            # the exact status-text + exception-type shape the
            # executor's _is_device_oom recovery classifier accepts
            raise ValueError(f"RESOURCE_EXHAUSTED: injected fault at {site}")
        return fp.to_json()
    return None


def torn_write(f, data: bytes, spec: dict) -> None:
    """Apply a triggered ``torn_write`` spec to an open file: persist
    only the first ``args.offset`` bytes of ``data``, flush, and raise
    :class:`FaultError` (the crash).  The single tear implementation
    every write seam shares (``sys.write`` and the record-relative
    ``oplog.append``), so tear semantics can never diverge by site."""
    args = spec.get("args", {})
    off = min(int(args.get("offset", 0)), len(data))
    f.write(data[:off])
    f.flush()
    err = FaultError(
        f"injected torn write: {off}/{len(data)} bytes persisted")
    if "errno" in args:
        # a typed tear: ENOSPC's short-write-then-error shape — the
        # process survives, the governor classifies, and recovery must
        # still find a clean record prefix
        err.errno = resolve_errno(args["errno"])
    raise err


def configure(spec: str | list | None, logger=None) -> int:
    """Arm failpoints from a config/env value: a JSON list of spec
    objects (the ``PILOSA_FAULTS`` format), e.g.::

        [{"site": "oplog.append", "action": "torn_write",
          "nth": 3, "args": {"offset": 7}}]

    Returns the number armed.  Bad specs raise ValueError — a typo'd
    fault config must fail loudly, not silently not-inject."""
    if not spec:
        return 0
    if isinstance(spec, str):
        try:
            spec = json.loads(spec)
        except json.JSONDecodeError as e:
            raise ValueError(f"PILOSA_FAULTS is not valid JSON: {e}")
    if isinstance(spec, dict):
        spec = [spec]
    n = 0
    for entry in spec:
        entry = dict(entry)
        site = entry.pop("site", None)
        action = entry.pop("action", None)
        if not site or not action:
            raise ValueError(
                f"fault spec requires site and action: {entry}")
        set_fault(site, action, **entry)
        n += 1
    if n and logger is not None:
        logger.warning("fault injection armed: %d failpoint(s) — %s",
                       n, [f["site"] for f in list_faults()])
    return n
