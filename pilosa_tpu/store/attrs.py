"""AttrStore: arbitrary key/value attributes per row or column.

Reference: ``attrstore.go`` (SURVEY.md §3.1) — BoltDB-backed KV with
block checksums for anti-entropy.  The rebuild uses stdlib sqlite3 (no
BoltDB in Python; sqlite is the boring durable KV at hand): one store
per index (column attrs) and per field (row attrs), attrs stored as a
JSON object per id, merged on write like upstream (``SetAttrs`` updates
keys, ``null`` deletes a key).

Block checksums (``HASH_BLOCK_SIZE`` ids per block) support the same
AAE diff protocol as fragments.
"""

from __future__ import annotations

import json
import os
import sqlite3
import threading
import zlib

HASH_BLOCK_SIZE = 100


class AttrStore:
    def __init__(self, path: str):
        self.path = path
        os.makedirs(os.path.dirname(path), exist_ok=True)
        self._local = threading.local()
        self._lock = threading.Lock()
        with self._conn() as c:
            c.execute("CREATE TABLE IF NOT EXISTS attrs ("
                      "id INTEGER PRIMARY KEY, data TEXT NOT NULL)")

    def _conn(self) -> sqlite3.Connection:
        conn = getattr(self._local, "conn", None)
        if conn is None:
            conn = sqlite3.connect(self.path)
            conn.isolation_level = None  # autocommit; writes are atomic
            self._local.conn = conn
        return conn

    # -- api ----------------------------------------------------------------

    def set_attrs(self, item_id: int, attrs: dict) -> dict:
        """Merge attrs into the item's map (``None`` value deletes the
        key, as upstream); returns the resulting map."""
        with self._lock:
            conn = self._conn()
            cur = conn.execute("SELECT data FROM attrs WHERE id=?",
                               (item_id,))
            row = cur.fetchone()
            current = json.loads(row[0]) if row else {}
            for k, v in attrs.items():
                if v is None:
                    current.pop(k, None)
                else:
                    current[k] = v
            if current:
                conn.execute(
                    "INSERT INTO attrs(id, data) VALUES(?, ?) "
                    "ON CONFLICT(id) DO UPDATE SET data=excluded.data",
                    (item_id, json.dumps(current, sort_keys=True)))
            else:
                conn.execute("DELETE FROM attrs WHERE id=?", (item_id,))
            return current

    def attrs(self, item_id: int) -> dict:
        cur = self._conn().execute("SELECT data FROM attrs WHERE id=?",
                                   (item_id,))
        row = cur.fetchone()
        return json.loads(row[0]) if row else {}

    def attrs_many(self, ids) -> list[dict]:
        return [self.attrs(int(i)) for i in ids]

    def find_ids(self, name: str, value) -> list[int]:
        """IDs whose attr ``name`` equals ``value`` (TopN attrName/
        attrValue filter, reference: ``fragment.top`` attr filtering)."""
        out = []
        cur = self._conn().execute("SELECT id, data FROM attrs")
        for item_id, data in cur.fetchall():
            if json.loads(data).get(name) == value:
                out.append(int(item_id))
        return out

    # -- anti-entropy -------------------------------------------------------

    def blocks(self) -> dict[int, int]:
        """Per-block CRC of (id, canonical-json) pairs."""
        out: dict[int, int] = {}
        cur = self._conn().execute("SELECT id, data FROM attrs ORDER BY id")
        for item_id, data in cur.fetchall():
            blk = int(item_id) // HASH_BLOCK_SIZE
            crc = out.get(blk, 0)
            crc = zlib.crc32(f"{item_id}:{data}".encode(), crc)
            out[blk] = crc
        return out

    def block_items(self, block: int) -> dict[int, dict]:
        lo, hi = block * HASH_BLOCK_SIZE, (block + 1) * HASH_BLOCK_SIZE
        cur = self._conn().execute(
            "SELECT id, data FROM attrs WHERE id>=? AND id<?", (lo, hi))
        return {int(i): json.loads(d) for i, d in cur.fetchall()}

    def merge_items(self, items: dict[int, dict]) -> int:
        """Union-merge attr maps (peer's keys fill in missing; local keys
        win conflicts — deterministic for AAE convergence both ways)."""
        changed = 0
        for item_id, attrs in items.items():
            with self._lock:
                conn = self._conn()
                cur = conn.execute("SELECT data FROM attrs WHERE id=?",
                                   (item_id,))
                row = cur.fetchone()
                current = json.loads(row[0]) if row else {}
                merged = {**attrs, **current}
                if merged != current:
                    conn.execute(
                        "INSERT INTO attrs(id, data) VALUES(?, ?) "
                        "ON CONFLICT(id) DO UPDATE SET data=excluded.data",
                        (item_id, json.dumps(merged, sort_keys=True)))
                    changed += 1
        return changed

    def close(self) -> None:
        conn = getattr(self._local, "conn", None)
        if conn is not None:
            conn.close()
            self._local.conn = None
