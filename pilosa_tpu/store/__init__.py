"""Host-truth storage tree (L1/L4 of SURVEY.md §2).

Device arrays are a cache; these modules are the durable truth —
roaring-format snapshot files plus CRC-framed op-logs under
``<data>/<index>/<field>/views/<view>/fragments/<shard>``.
"""

from pilosa_tpu.store.field import Field, FieldOptions
from pilosa_tpu.store.fragment import Fragment
from pilosa_tpu.store.holder import Holder
from pilosa_tpu.store.index import EXISTENCE_FIELD, Index
from pilosa_tpu.store.row import RowBits
from pilosa_tpu.store.view import VIEW_STANDARD, View

__all__ = [
    "Field", "FieldOptions", "Fragment", "Holder", "Index", "RowBits",
    "View", "VIEW_STANDARD", "EXISTENCE_FIELD",
]
