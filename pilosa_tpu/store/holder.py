"""Holder: root of the storage tree.

Reference: ``holder.go`` (SURVEY.md §3.1) — owns the data directory layout

    <data>/<index>/.meta
    <data>/<index>/<field>/.meta
    <data>/<index>/<field>/views/<view>/fragments/<shard>[.oplog]

and opens everything on startup.  Meta files are JSON (the reference uses
protobuf ``.meta``; JSON is a deliberate rebuild simplification — the
schema is tiny and human-debuggable).
"""

from __future__ import annotations

import os
import shutil
import threading

from pilosa_tpu.store.field import FieldOptions
from pilosa_tpu.store.index import Index


class SnapshotQueue:
    """Background op-log compaction (reference: the fragment snapshot
    queue in ``holder.go``): the write path hands over-threshold
    fragments here instead of paying serialize+fsync inline.  Dedupes
    by fragment identity; the worker starts lazily on first submit."""

    def __init__(self):
        self._pending: list = []
        self._inq: set[int] = set()
        self._cv = threading.Condition()
        self._stop = False
        self._thread: threading.Thread | None = None

    def submit(self, frag) -> None:
        with self._cv:
            if not self._stop:
                if id(frag) in self._inq:
                    return
                self._inq.add(id(frag))
                self._pending.append(frag)
                if self._thread is None:
                    self._thread = threading.Thread(
                        target=self._loop, name="pilosa-snapshot",
                        daemon=True)
                    self._thread.start()
                self._cv.notify()
                return
        frag.maybe_snapshot()  # queue closed: old inline behavior

    def _loop(self) -> None:
        while True:
            with self._cv:
                while not self._pending and not self._stop:
                    self._cv.wait()
                if self._stop and not self._pending:
                    return
                frag = self._pending.pop(0)
                self._inq.discard(id(frag))
            try:
                frag.maybe_snapshot()
            except Exception:  # noqa: BLE001 — a failed compaction only
                # defers (the op-log remains the truth), but never
                # silently: disk-full here would otherwise loop forever
                import logging
                logging.getLogger("pilosa_tpu.store").exception(
                    "background snapshot failed for %s", frag.path)

    def close(self) -> None:
        """Stop accepting work and DRAIN the backlog (the worker loop
        keeps popping after ``_stop`` until pending is empty).  A clean
        shutdown therefore never leaves an over-threshold op-log tail
        to replay on next open, and a backup taken right after close
        sees compacted fragments.  Anything still queued after the
        bounded join (no worker ever started, or it is wedged on one
        huge compaction) compacts inline here — close is the last
        chance."""
        with self._cv:
            self._stop = True
            self._cv.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=30)
        with self._cv:
            leftover, self._pending = self._pending, []
            self._inq.clear()
        for frag in leftover:
            try:
                frag.maybe_snapshot()
            except Exception:  # noqa: BLE001 — same contract as _loop
                import logging
                logging.getLogger("pilosa_tpu.store").exception(
                    "close-time snapshot failed for %s", frag.path)


class Holder:
    def __init__(self, path: str, *, fsync: bool = False,
                 async_snapshots: bool = True):
        from pilosa_tpu.store.health import StorageHealth
        self.path = path
        self.fsync = fsync
        self.indexes: dict[str, Index] = {}
        self._lock = threading.RLock()
        self._snap_queue = SnapshotQueue() if async_snapshots else None
        # disk-health governor + corruption quarantine (r19): one per
        # holder tree, threaded down to every fragment (the same chain
        # snapshot_submit rides); the server wires stats/knobs via
        # configure() after boot
        self.storage_health = StorageHealth(base=path)

    @property
    def _submit(self):
        return self._snap_queue.submit if self._snap_queue else None

    def open(self) -> "Holder":
        """Scan and open the whole tree; indexes open concurrently
        (reference: ``Holder.Open`` fragment worker pool — startup is
        dominated by snapshot reads + op-log replays)."""
        os.makedirs(self.path, exist_ok=True)
        entries = [e for e in sorted(os.listdir(self.path))
                   if os.path.isdir(os.path.join(self.path, e))
                   and not e.startswith(".")]
        if len(entries) <= 1:
            for entry in entries:
                self.indexes[entry] = Index(
                    os.path.join(self.path, entry), entry,
                    fsync=self.fsync, snapshot_submit=self._submit,
                    health=self.storage_health).open()
            return self
        from concurrent.futures import ThreadPoolExecutor
        with ThreadPoolExecutor(max_workers=min(8, len(entries))) as pool:
            opened = pool.map(
                lambda e: (e, Index(os.path.join(self.path, e), e,
                                    fsync=self.fsync,
                                    snapshot_submit=self._submit,
                                    health=self.storage_health).open()),
                entries)
            for entry, idx in opened:
                self.indexes[entry] = idx
        return self

    def close(self) -> None:
        self.storage_health.close()
        if self._snap_queue is not None:
            self._snap_queue.close()
        with self._lock:
            for idx in self.indexes.values():
                idx.close()
            self.indexes.clear()

    # -- index management ---------------------------------------------------

    def create_index(self, name: str, *, keys: bool = False,
                     track_existence: bool = True,
                     created_at: float = 0.0) -> Index:
        import time
        with self._lock:
            if name in self.indexes:
                raise ValueError(f"index {name!r} already exists")
            _validate_name(name)
            idx = Index(os.path.join(self.path, name), name, keys=keys,
                        track_existence=track_existence, fsync=self.fsync,
                        created_at=created_at or time.time(),
                        snapshot_submit=self._submit,
                        health=self.storage_health)
            os.makedirs(idx.path, exist_ok=True)
            idx.save_meta()
            idx.open()
            self.indexes[name] = idx
            return idx

    def ensure_index(self, name: str, **kw) -> Index:
        with self._lock:
            return self.indexes.get(name) or self.create_index(name, **kw)

    def index(self, name: str) -> Index | None:
        return self.indexes.get(name)

    def delete_index(self, name: str) -> None:
        with self._lock:
            idx = self.indexes.pop(name, None)
            if idx is None:
                raise KeyError(name)
            idx.close()
            shutil.rmtree(idx.path, ignore_errors=True)

    # -- schema -------------------------------------------------------------

    def schema(self) -> list[dict]:
        """JSON-able schema dump (reference: ``API.Schema``)."""
        out = []
        with self._lock:
            for iname, idx in sorted(self.indexes.items()):
                fields = []
                for fname, f in sorted(idx.fields.items()):
                    if fname.startswith("_"):
                        continue
                    o = f.options
                    fields.append({
                        "name": fname,
                        "options": {
                            "type": o.type, "keys": o.keys,
                            "cacheType": o.cache_type, "cacheSize": o.cache_size,
                            "timeQuantum": o.time_quantum,
                            "min": o.min, "max": o.max, "base": o.base,
                            "bitDepth": o.bit_depth, "scale": o.scale,
                            "epoch": o.epoch, "timeUnit": o.time_unit,
                        },
                        "createdAt": o.created_at,
                    })
                out.append({"name": iname,
                            "options": {"keys": idx.keys,
                                        "trackExistence": idx.track_existence},
                            "createdAt": idx.created_at,
                            "fields": fields})
        return out

    def apply_schema(self, schema: list[dict]) -> None:
        """Create any missing indexes/fields from a schema dump (used by
        restore and cluster schema sync)."""
        for ispec in schema:
            if ispec["name"] in self.indexes:
                idx = self.indexes[ispec["name"]]
            else:
                idx = self.create_index(
                    ispec["name"],
                    keys=ispec.get("options", {}).get("keys", False),
                    track_existence=ispec.get("options", {}).get(
                        "trackExistence", True),
                    created_at=ispec.get("createdAt", 0.0),
                )
            for fspec in ispec.get("fields", []):
                if fspec["name"] in idx.fields:
                    continue
                o = fspec.get("options", {})
                idx.create_field(fspec["name"], FieldOptions(
                    type=o.get("type", "set"), keys=o.get("keys", False),
                    cache_type=o.get("cacheType", "ranked"),
                    cache_size=o.get("cacheSize", 50000),
                    time_quantum=o.get("timeQuantum", ""),
                    min=o.get("min"), max=o.get("max"),
                    base=o.get("base", 0), bit_depth=o.get("bitDepth", 0),
                    scale=o.get("scale", 0), epoch=o.get("epoch", ""),
                    time_unit=o.get("timeUnit", "s"),
                    created_at=fspec.get("createdAt", 0.0),
                ))


def _validate_name(name: str) -> None:
    """Index/field naming rules (reference: lowercase, digits, -_)."""
    import re
    if not re.fullmatch(r"[a-z][a-z0-9_-]{0,229}", name):
        raise ValueError(f"invalid name {name!r}")
