"""Disk-health governor + corruption quarantine (r19).

The storage layer's sibling of :mod:`pilosa_tpu.exec.health` (the r18
device governor): one :class:`StorageHealth` per holder tree watches
the two ways a disk betrays an index —

- **write-path OSErrors**, classified by errno at the oplog/snapshot/
  hint/sidecar seams: ``ENOSPC``/``EDQUOT`` flips the whole node to
  READ_ONLY degraded serving (strict writes refuse with a structured
  507-style ``writeUnavailable{reason: "disk_full"}``; reads keep
  serving; peers hint the missed copies via the r13 machinery), with a
  probe loop (statvfs headroom + a real probe write) restoring HEALTHY
  once space frees.  Repeated ``EIO`` on one fragment quarantines just
  that fragment — a single bad sector must not take the node down;
- **corruption**, reported by checksum verification (snapshot frame
  CRCs at open/demote, the background scrubber's re-verification):
  the fragment is QUARANTINED — local reads route to a replica
  exactly as if the shard were remote (``Cluster.group_shards_by_node``
  skips self), local strict writes refuse with a structured 503
  ``storageFault{path, kind}``, and the scrubber's repair hook pulls a
  fresh copy from a healthy replica.

The happy path is lock-free: every fragment mutator reads one plain
bool (``gate_active``) and proceeds — the governor must cost a healthy
disk nothing.

State is exported as ``disk_health_state`` (0 healthy, 1 read_only),
``storage_fragment_quarantined`` (gauge), and
``storage_corruption_detected_total{kind}``; the ``storageHealth``
block on ``/status`` carries the full registry.
"""

from __future__ import annotations

import errno as _errno
import logging
import os
import threading
import time

HEALTHY = "healthy"
READ_ONLY = "read_only"

STATE_CODE = {HEALTHY: 0, READ_ONLY: 1}

# write-fault classes (by errno; FaultError carries an injected errno
# through the same path, so chaos schedules exercise real classification)
DISK_FULL = "disk_full"
IO_ERROR = "io_error"
OTHER = "other"

# consecutive EIO-class write failures on ONE fragment before that
# fragment (alone) is quarantined
EIO_QUARANTINE_THRESHOLD = 3

# suffixes that map an on-disk file back to its owning fragment's
# canonical (snapshot) path for quarantine identity
_FRAG_SUFFIXES = (".oplog", ".dense", ".tmp")

_LOG = logging.getLogger("pilosa_tpu.store")

# (site, path) pairs already logged by note_os_error — "log once":
# a per-stat-call warning on a hot loop would flood the log with the
# very fault it reports
_logged_once: set[tuple[str, str]] = set()
_logged_lock = threading.Lock()


class StorageFaultError(OSError):
    """A write refused (or failed) because the storage layer is sick:
    the node is READ_ONLY (``kind == "disk_full"``), the target
    fragment is quarantined (``kind == "corrupt"``/``"io_error"``), or
    the underlying write just failed with a classified errno.  The API
    edges map this to a structured 507/503 (see
    ``ApiError.storage_fault``) — storage unavailability is never a
    generic 500."""

    def __init__(self, msg: str, *, path: str, kind: str,
                 retry_after: float = 1.0):
        super().__init__(msg)
        self.path = path
        self.kind = kind
        self.retry_after = retry_after


def classify_oserror(err: BaseException) -> str:
    """errno → fault class.  ``EDQUOT`` counts as disk-full (a quota
    is a full disk from this process's point of view); ``EROFS`` too
    (the kernel remounted the filesystem read-only — the ext4 response
    to metadata I/O errors)."""
    no = getattr(err, "errno", None)
    if no in (_errno.ENOSPC, _errno.EDQUOT, _errno.EROFS):
        return DISK_FULL
    if no == _errno.EIO:
        return IO_ERROR
    return OTHER


def frag_path_of(path: str) -> str:
    """Canonical fragment (snapshot) path for any of its on-disk
    files (op-log, dense sidecar, tmp)."""
    for suf in _FRAG_SUFFIXES:
        if path.endswith(suf):
            return path[: -len(suf)]
    return path


def note_os_error(site: str, path: str, err: OSError,
                  health: "StorageHealth | None" = None,
                  logger=None) -> None:
    """The satellite contract for previously-silent ``except OSError``
    sites: log ONCE per (site, path) with path+errno, and feed the
    disk-health governor's fault counter when a governor is in reach.
    ``ENOENT`` is exempt — an absent file is the DELIBERATE fallback
    at every call site that uses this helper (no snapshot yet, no
    sidecar to restamp, already-removed key files) and must stay
    silent."""
    if getattr(err, "errno", None) == _errno.ENOENT:
        return
    key = (site, path)
    with _logged_lock:
        first = key not in _logged_once
        if first:
            _logged_once.add(key)
    if first:
        (logger or _LOG).warning(
            "storage: OSError at %s (%s): %s [errno=%s]",
            site, path, err, getattr(err, "errno", None))
    if health is not None:
        health.note_fault(path, err, site=site)


class StorageHealth:
    """One holder tree's disk-health governor + quarantine registry.

    Constructed by :class:`~pilosa_tpu.store.holder.Holder` and
    threaded down to every fragment (the same chain
    ``snapshot_submit`` rides); the server wires stats/logger/knobs via
    :meth:`configure` after boot."""

    def __init__(self, base: str = "", stats=None, logger=None,
                 min_free_bytes: int = 64 << 20,
                 probe_seconds: float = 5.0):
        from pilosa_tpu.obs import NopStats
        self.base = base
        self._stats = stats or NopStats()
        self._logger = logger or _LOG
        self.min_free_bytes = int(min_free_bytes)
        self.probe_seconds = max(0.05, float(probe_seconds))
        # hot-path guard: plain bool, GIL-atomic reads.  True only when
        # the node is read-only OR at least one fragment is quarantined
        # — the healthy fast path is one attribute load + falsy branch.
        self.gate_active = False
        self.state = HEALTHY
        self._since = time.monotonic()
        self._lock = threading.Lock()
        # canonical fragment path -> {kind, detail, path, key, ts}
        self._quarantined: dict[str, dict] = {}
        # (index, shard) pairs with >=1 quarantined fragment (routing
        # reads them per query; maintained under the lock)
        self._bad_shards: dict[tuple[str, int], int] = {}
        self._eio_counts: dict[str, int] = {}
        self._faults: dict[str, int] = {}  # kind -> count (status block)
        self._probe_thread: threading.Thread | None = None
        self._stop = threading.Event()
        self._last_repair: dict | None = None

    # -- wiring ---------------------------------------------------------------

    def configure(self, base: str | None = None, stats=None, logger=None,
                  min_free_bytes: int | None = None,
                  probe_seconds: float | None = None) -> "StorageHealth":
        if base is not None:
            self.base = base
        if stats is not None:
            self._stats = stats
        if logger is not None:
            self._logger = logger
        if min_free_bytes is not None:
            self.min_free_bytes = int(min_free_bytes)
        if probe_seconds is not None:
            self.probe_seconds = max(0.05, float(probe_seconds))
        return self

    def close(self) -> None:
        self._stop.set()

    # -- quarantine registry --------------------------------------------------

    def key_of_path(self, path: str) -> tuple | None:
        """(index, field, view, shard) parsed from a fragment path
        under ``base`` (layout:
        ``<base>/<index>/<field>/views/<view>/fragments/<shard>``), or
        None when the path is not a fragment of this tree."""
        if not self.base:
            return None
        try:
            rel = os.path.relpath(frag_path_of(path), self.base)
        except ValueError:
            return None
        parts = rel.split(os.sep)
        if (len(parts) == 6 and parts[2] == "views"
                and parts[4] == "fragments" and parts[5].isdigit()):
            return (parts[0], parts[1], parts[3], int(parts[5]))
        return None

    def quarantine(self, path: str, kind: str, detail: str = "") -> dict:
        """Register one fragment as untrustworthy.  Reads route to a
        replica (``shard_quarantined``), local writes refuse
        (``check_write``), the scrubber's repair hook pulls a fresh
        copy.  Idempotent per path."""
        cpath = frag_path_of(path)
        key = self.key_of_path(cpath)
        with self._lock:
            if cpath in self._quarantined:
                return self._quarantined[cpath]
            entry = {"path": cpath, "kind": kind, "detail": detail,
                     "key": key, "ts": time.time()}
            self._quarantined[cpath] = entry
            if key is not None:
                ks = (key[0], key[3])
                self._bad_shards[ks] = self._bad_shards.get(ks, 0) + 1
            self.gate_active = True
            n = len(self._quarantined)
        self._stats.count("storage_corruption_detected_total", 1,
                          kind=kind)
        self._stats.gauge("storage_fragment_quarantined", n)
        self._logger.warning(
            "storage: fragment QUARANTINED (%s) %s%s — reads served "
            "from replicas, local writes refuse until repaired",
            kind, cpath, f": {detail}" if detail else "")
        return entry

    def unquarantine(self, path: str) -> bool:
        cpath = frag_path_of(path)
        with self._lock:
            entry = self._quarantined.pop(cpath, None)
            if entry is None:
                return False
            key = entry.get("key")
            if key is not None:
                ks = (key[0], key[3])
                left = self._bad_shards.get(ks, 1) - 1
                if left <= 0:
                    self._bad_shards.pop(ks, None)
                else:
                    self._bad_shards[ks] = left
            self._eio_counts.pop(cpath, None)
            self.gate_active = bool(self._quarantined) \
                or self.state != HEALTHY
            n = len(self._quarantined)
        self._stats.gauge("storage_fragment_quarantined", n)
        self._logger.info("storage: fragment un-quarantined %s", cpath)
        return True

    def note_repair(self, path: str, source: str) -> None:
        """Record a completed replica repair (status visibility +
        ``storage_repair_total{source}``)."""
        self._stats.count("storage_repair_total", 1, source=source)
        with self._lock:
            self._last_repair = {"path": frag_path_of(path),
                                 "source": source, "ts": time.time()}

    def is_quarantined(self, path: str) -> bool:
        if not self.gate_active:
            return False
        with self._lock:
            return frag_path_of(path) in self._quarantined

    def quarantined_entries(self) -> list[dict]:
        with self._lock:
            return [dict(e) for e in self._quarantined.values()]

    def shard_quarantined(self, index: str, shard: int) -> bool:
        """Any fragment of (index, shard) quarantined locally?  The
        read-routing check: when True and a live replica exists, this
        node's legs for the shard go to the replica instead."""
        if not self.gate_active:
            return False
        with self._lock:
            return (index, int(shard)) in self._bad_shards

    # -- write gate -----------------------------------------------------------

    def check_write(self, path: str | None = None) -> None:
        """Raise :class:`StorageFaultError` when a write must refuse:
        node read-only (disk full) or the target fragment quarantined.
        Called by fragment mutators BEFORE any in-memory mutation, so
        a refusal can never half-apply (``gate_active`` keeps the
        healthy path to one bool read)."""
        if not self.gate_active:
            return
        if self.state == READ_ONLY:
            raise StorageFaultError(
                "node is read-only: disk full (writes refuse until the "
                "space probe succeeds; reads keep serving)",
                path=path or self.base, kind=DISK_FULL,
                retry_after=self.probe_seconds)
        if path is not None:
            cpath = frag_path_of(path)
            with self._lock:
                entry = self._quarantined.get(cpath)
            if entry is not None:
                raise StorageFaultError(
                    f"fragment quarantined ({entry['kind']}): {cpath} "
                    "(reads serve from replicas; repair pending)",
                    path=cpath, kind=entry["kind"])

    # -- fault intake ---------------------------------------------------------

    def note_fault(self, path: str, err: BaseException,
                   site: str = "") -> str:
        """Classify + account one write-path OSError.  ``disk_full``
        flips the node READ_ONLY and starts the probe loop; repeated
        ``io_error`` on one fragment quarantines just that fragment.
        Returns the fault class."""
        kind = classify_oserror(err)
        with self._lock:
            self._faults[kind] = self._faults.get(kind, 0) + 1
        if kind == DISK_FULL:
            self._degrade(site or path, err)
        elif kind == IO_ERROR:
            cpath = frag_path_of(path)
            with self._lock:
                n = self._eio_counts.get(cpath, 0) + 1
                self._eio_counts[cpath] = n
            if n >= EIO_QUARANTINE_THRESHOLD:
                self.quarantine(cpath, IO_ERROR,
                                f"{n} consecutive EIO write failures")
        return kind

    def write_failed(self, path: str, err: BaseException,
                     site: str = "") -> StorageFaultError:
        """The raising form of :meth:`note_fault`: classify, account,
        and return a :class:`StorageFaultError` for the caller to
        ``raise ... from err`` — the single conversion every durable
        write seam (oplog append, snapshot) shares."""
        kind = self.note_fault(path, err, site=site)
        return StorageFaultError(
            f"storage write failed ({kind}) at {site or path}: {err}",
            path=path, kind=kind,
            retry_after=self.probe_seconds if kind == DISK_FULL else 1.0)

    def note_write_success(self, path: str) -> None:
        """A successful durable write resets the fragment's EIO streak
        (the quarantine trigger is CONSECUTIVE failures)."""
        if self._eio_counts:
            with self._lock:
                self._eio_counts.pop(frag_path_of(path), None)

    # -- read-only degradation + probe ---------------------------------------

    def _degrade(self, what: str, err: BaseException) -> None:
        with self._lock:
            if self.state == READ_ONLY:
                return
            self.state = READ_ONLY
            self._since = time.monotonic()
            self.gate_active = True
            # probe lifecycle: the thread unregisters ITSELF under
            # this lock right before exiting (_probe_loop), so either
            # a live probe observes this READ_ONLY flip and keeps
            # probing, or it has already unregistered and we start a
            # fresh one — a HEALTHY→READ_ONLY flip can never race an
            # exiting probe into a probeless read-only limbo
            start_probe = self._probe_thread is None
            if start_probe:
                self._probe_thread = threading.Thread(
                    target=self._probe_loop, name="pilosa-disk-probe",
                    daemon=True)
        self._stats.gauge("disk_health_state", STATE_CODE[READ_ONLY])
        self._logger.error(
            "storage: disk FULL at %s (%s) — node flips to READ-ONLY "
            "degraded serving; strict writes refuse with "
            "writeUnavailable{disk_full}, probe every %.1fs",
            what, err, self.probe_seconds)
        if start_probe:
            self._probe_thread.start()

    def _probe_loop(self) -> None:
        while not self._stop.wait(self.probe_seconds):
            with self._lock:
                if self.state != READ_ONLY:
                    # exit-and-unregister atomically: a concurrent
                    # _degrade either sees READ_ONLY observed by this
                    # loop (we keep probing) or finds _probe_thread
                    # already None and starts a fresh thread
                    self._probe_thread = None
                    return
            self.probe_once()
        with self._lock:
            self._probe_thread = None

    def probe_once(self) -> bool:
        """One recovery probe: statvfs headroom AND a real probe write
        through the ``sys.write`` seam (quota/remount failures don't
        show in statvfs — only an actual write proves the disk takes
        bytes again).  Success restores HEALTHY."""
        base = self.base or "."
        try:
            st = os.statvfs(base)
            if st.f_bavail * st.f_frsize < self.min_free_bytes:
                return False
        except OSError:
            return False
        probe = os.path.join(base, "_diskprobe")
        try:
            from pilosa_tpu.store import syswrap
            with open(probe, "wb") as f:
                syswrap.checked_write(f, b"pilosa-disk-probe")
                f.flush()
            os.remove(probe)
        except OSError:
            try:
                os.remove(probe)
            except OSError:
                pass
            return False
        with self._lock:
            self.state = HEALTHY
            self._since = time.monotonic()
            self.gate_active = bool(self._quarantined)
        self._stats.gauge("disk_health_state", STATE_CODE[HEALTHY])
        self._logger.warning(
            "storage: disk probe succeeded — node restored to HEALTHY "
            "serving (hinted writes drain via the peers' heartbeats)")
        return True

    # -- introspection --------------------------------------------------------

    def payload(self) -> dict:
        """The ``storageHealth`` block on ``/status`` (the scrubber
        adds its own progress sub-block)."""
        with self._lock:
            quarantined = [
                {"path": e["path"], "kind": e["kind"],
                 "detail": e["detail"],
                 "key": (None if e["key"] is None else {
                     "index": e["key"][0], "field": e["key"][1],
                     "view": e["key"][2], "shard": e["key"][3]})}
                for e in self._quarantined.values()]
            return {
                "state": self.state,
                "stateCode": STATE_CODE[self.state],
                "sinceSeconds": round(
                    time.monotonic() - self._since, 3),
                "minFreeBytes": self.min_free_bytes,
                "probeSeconds": self.probe_seconds,
                "faults": dict(self._faults),
                "quarantined": quarantined,
                "lastRepair": (dict(self._last_repair)
                               if self._last_repair else None),
            }
