"""ctypes loader for the native roaring codec (native/roaring_codec.cpp).

The native slot of SURVEY.md §3.4: fragment blob parse/serialize and
dense-plane expansion in C++ at memory bandwidth.  Byte-compatible with
the pure-Python codec in :mod:`pilosa_tpu.store.roaring`, which remains
the always-available fallback (``PILOSA_NO_NATIVE=1`` forces it).

Build: ``make -C native`` → ``native/libroaring_codec.so``.
"""

from __future__ import annotations

import ctypes
import os

import numpy as np

_LIB_PATH = os.path.join(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))), "native",
    "libroaring_codec.so")

_ERRORS = {-1: "truncated buffer", -2: "bad magic/version",
           -3: "bad container type", -4: "output buffer too small",
           -5: "positions not sorted/unique"}


def _load():
    if os.environ.get("PILOSA_NO_NATIVE"):
        return None
    if not os.path.exists(_LIB_PATH):
        return None
    # an older .so may lack newer symbols: AttributeError below must
    # also mean "fall back to Python", not a hard import crash
    try:
        lib = ctypes.CDLL(_LIB_PATH)
    except OSError:
        return None
    u8p = ctypes.POINTER(ctypes.c_uint8)
    u32p = ctypes.POINTER(ctypes.c_uint32)
    u64p = ctypes.POINTER(ctypes.c_uint64)
    lib.rc_cardinality.restype = ctypes.c_int64
    lib.rc_cardinality.argtypes = [u8p, ctypes.c_size_t]
    lib.rc_deserialize.restype = ctypes.c_int64
    lib.rc_deserialize.argtypes = [u8p, ctypes.c_size_t, u64p,
                                   ctypes.c_size_t]
    lib.rc_serialize.restype = ctypes.c_int64
    lib.rc_serialize.argtypes = [u64p, ctypes.c_size_t, u8p,
                                 ctypes.c_size_t]
    lib.rc_serialized_bound.restype = ctypes.c_int64
    lib.rc_serialized_bound.argtypes = [u64p, ctypes.c_size_t]
    lib.rc_expand_plane.restype = ctypes.c_int64
    lib.rc_expand_plane.argtypes = [u8p, ctypes.c_size_t, ctypes.c_uint64,
                                    u64p, ctypes.c_size_t, u32p,
                                    ctypes.c_size_t]
    lib.rc_expand_rows_into.restype = ctypes.c_int64
    lib.rc_expand_rows_into.argtypes = [u8p, ctypes.c_size_t,
                                        ctypes.c_uint64, u64p, u64p,
                                        ctypes.c_size_t, u32p,
                                        ctypes.c_size_t, ctypes.c_size_t]
    # void* so callers can pass bare addresses (see _u32p)
    lib.rc_union_u32.restype = ctypes.c_int64
    lib.rc_union_u32.argtypes = [ctypes.c_void_p, ctypes.c_size_t,
                                 ctypes.c_void_p, ctypes.c_size_t,
                                 ctypes.c_void_p]
    lib.rc_diff_u32.restype = ctypes.c_int64
    lib.rc_diff_u32.argtypes = [ctypes.c_void_p, ctypes.c_size_t,
                                ctypes.c_void_p, ctypes.c_size_t,
                                ctypes.c_void_p]
    return lib


try:
    _lib = _load()
except AttributeError:  # stale .so missing newer symbols
    _lib = None


def available() -> bool:
    return _lib is not None


def _check(rc: int, what: str) -> int:
    if rc < 0:
        raise ValueError(
            f"native codec {what}: {_ERRORS.get(rc, f'error {rc}')}")
    return rc


def _u8(buf) -> ctypes.POINTER(ctypes.c_uint8):
    arr = np.frombuffer(buf, dtype=np.uint8)
    return arr.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)), arr


def deserialize(buf: bytes) -> np.ndarray:
    ptr, keep = _u8(buf)
    card = _check(_lib.rc_cardinality(ptr, len(buf)), "cardinality")
    out = np.empty(card, dtype=np.uint64)
    got = _check(_lib.rc_deserialize(
        ptr, len(buf), out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
        card), "deserialize")
    return out[:got]


def serialize(positions: np.ndarray) -> bytes:
    positions = np.ascontiguousarray(positions, dtype=np.uint64)
    p64 = positions.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64))
    bound = _check(_lib.rc_serialized_bound(p64, len(positions)), "bound")
    out = np.empty(bound, dtype=np.uint8)
    n = _check(_lib.rc_serialize(
        p64, len(positions),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)), bound),
        "serialize")
    return out[:n].tobytes()


def expand_plane(buf: bytes, row_width: int, row_slots: np.ndarray,
                 plane: np.ndarray) -> int:
    """Expand a fragment blob directly into a zeroed dense plane
    ``uint32[n_rows, words_per_row]``; ``row_slots`` = sorted row ids of
    the plane's rows.  Returns bits set."""
    ptr, keep = _u8(buf)
    row_slots = np.ascontiguousarray(row_slots, dtype=np.uint64)
    if plane.dtype != np.uint32 or not plane.flags.c_contiguous:
        raise ValueError("plane must be C-contiguous uint32")
    return _check(_lib.rc_expand_plane(
        ptr, len(buf), row_width,
        row_slots.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
        len(row_slots),
        plane.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
        plane.shape[-1]), "expand_plane")


def expand_rows_into(buf, row_width: int, row_ids: np.ndarray,
                     slots: np.ndarray, plane: np.ndarray) -> int:
    """Expand a fragment blob's rows straight into caller-chosen slots
    of ``plane`` (uint32[n_rows, words_per_row]): row ``row_ids[i]``
    (sorted ascending) ORs into ``plane[slots[i]]``; rows absent from
    ``row_ids`` are skipped.  Unlike :func:`expand_plane` the slots are
    arbitrary, so the parallel plane build writes each fragment's rows
    directly into their final chunk position — no tmp slab + reorder
    copy.  The C call releases the GIL, so per-fragment expansions
    genuinely overlap across builder threads.  Returns bits set."""
    ptr, keep = _u8(buf)
    row_ids = np.ascontiguousarray(row_ids, dtype=np.uint64)
    slots = np.ascontiguousarray(slots, dtype=np.uint64)
    if len(row_ids) != len(slots):
        raise ValueError("expand_rows_into: row_ids/slots length mismatch")
    if plane.dtype != np.uint32 or not plane.flags.c_contiguous:
        raise ValueError("plane must be C-contiguous uint32")
    if plane.ndim != 2:
        raise ValueError("plane must be 2-D [n_rows, words_per_row]")
    return _check(_lib.rc_expand_rows_into(
        ptr, len(buf), row_width,
        row_ids.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
        slots.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
        len(row_ids),
        plane.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
        plane.shape[-1], plane.shape[0]), "expand_rows_into")


def _u32p(arr):
    # bare address (ctypes accepts ints for pointer args): data_as +
    # POINTER cast measured ~4 us/call — material on the bulk-import
    # path, which unions thousands of tiny per-row chunks per batch
    return arr.__array_interface__["data"][0]


_U32 = np.dtype(np.uint32)


def _as_u32c(a: np.ndarray) -> np.ndarray:
    # fast-path the common case (already uint32 C-contiguous): a full
    # ascontiguousarray costs ~2 us/call on the tiny per-row chunks the
    # bulk-import path feeds through here
    if a.dtype is _U32 and a.flags.c_contiguous:
        return a
    return np.ascontiguousarray(a, dtype=np.uint32)


def union_sorted_u32(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """Linear merge-union of two sorted-unique uint32 arrays."""
    a = _as_u32c(a)
    b = _as_u32c(b)
    out = np.empty(len(a) + len(b), dtype=np.uint32)
    k = _lib.rc_union_u32(_u32p(a), len(a), _u32p(b), len(b), _u32p(out))
    # exact-size copy: callers hold the result long-term and a view
    # would pin the oversized merge buffer
    return out[:k].copy()


def diff_sorted_u32(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """Linear a-minus-b of sorted-unique uint32 arrays."""
    a = _as_u32c(a)
    b = _as_u32c(b)
    out = np.empty(len(a), dtype=np.uint32)
    k = _lib.rc_diff_u32(_u32p(a), len(a), _u32p(b), len(b), _u32p(out))
    return out[:k].copy()
