"""Resource caps for snapshot mmaps (reference: ``syswrap/`` —
``maxMapCount`` with transparent mmap→heap fallback, SURVEY.md §3.1).

Every open fragment holds one mmap of its snapshot file; a large holder
(hundreds of indexes × fields × shards) can exhaust ``vm.max_map_count``
or the fd limit.  The process-global :data:`GLOBAL` pool bounds live
maps: fragments register on mmap-open (LRU order, touched on read);
over the cap the least-recently-used fragment is DEMOTED — its
directory re-parses over a heap copy of the blob and the map is
released — and if demotion can't proceed (lock contention) the opener
itself falls back to a heap read.  Both fallbacks keep every query
path working, trading memory for map slots exactly like the
reference's heap fallback.

The map is never force-closed: demotion drops the owning references
and lets refcounting reclaim it once in-flight readers (numpy views
over the buffer) finish — avoiding ``BufferError`` on exported views.
"""

from __future__ import annotations

import os
import threading
import weakref
from collections import OrderedDict

from pilosa_tpu import fault

# Default cap: comfortably under Linux's vm.max_map_count default
# (65530), leaving headroom for the allocator/XLA's own mappings.
DEFAULT_MAX_MAPS = 32768


def checked_write(f, data: bytes) -> int:
    """``f.write`` through the ``sys.write`` failpoint: ``error``
    raises :class:`pilosa_tpu.fault.FaultError` (an OSError — a disk
    write failure); ``torn_write`` persists only the first
    ``args.offset`` bytes before raising (a crash mid-write).  Durable
    writers (oplog, snapshot) route here so chaos schedules can tear
    them at byte granularity."""
    if fault.ACTIVE:
        spec = fault.fire("sys.write", path=getattr(f, "name", ""))
        if spec is not None and spec["action"] == "torn_write":
            fault.torn_write(f, data, spec)
    return f.write(data)


def checked_fsync(f) -> None:
    """``os.fsync`` through the ``sys.fsync`` failpoint (``error``
    raises; ``delay`` models a stalling disk)."""
    if fault.ACTIVE:
        fault.fire("sys.fsync", path=getattr(f, "name", ""))
    os.fsync(f.fileno())


class MapPool:
    def __init__(self, max_maps: int = DEFAULT_MAX_MAPS):
        self.max_maps = max_maps
        self._lock = threading.Lock()
        self._order: "OrderedDict[int, weakref.ref]" = OrderedDict()

    def set_max(self, n: int) -> None:
        self.max_maps = max(1, int(n))

    @property
    def live(self) -> int:
        with self._lock:
            return len(self._order)

    def register(self, frag) -> None:
        """Register ``frag`` as a map holder.  Over the cap, LRU
        holders are demoted to heap (outside this pool's lock —
        demotion takes the victim fragment's own lock with a timeout;
        on contention the cap is soft for that victim rather than
        risking lock-order deadlock between two opening fragments)."""
        if fault.ACTIVE:
            # mmap-open seam: `error` models map-slot/fd exhaustion at
            # registration time (the caller's own heap fallback applies
            # only to demotion contention, so this surfaces loudly)
            fault.fire("sys.mmap", path=getattr(frag, "path", ""))
        victims = []
        with self._lock:
            while len(self._order) >= self.max_maps:
                _, ref = self._order.popitem(last=False)
                v = ref()
                if v is not None:
                    victims.append(v)
            self._order[id(frag)] = weakref.ref(frag)
        for v in victims:
            if not v._demote_map():
                # lock contention: the victim still holds its map —
                # re-track it at the LRU head so it stays countable
                # and demotable next time
                with self._lock:
                    self._order[id(v)] = weakref.ref(v)
                    self._order.move_to_end(id(v), last=False)

    def touch(self, frag) -> None:
        with self._lock:
            if id(frag) in self._order:
                self._order.move_to_end(id(frag))

    def release(self, frag) -> None:
        with self._lock:
            self._order.pop(id(frag), None)


GLOBAL = MapPool()
