"""Host-side representation of one row's bits within one shard.

The reference keeps rows inside a fragment's single roaring bitmap and
materializes ``*Row`` objects as container slices (``fragment.go#row``,
SURVEY.md §3.1).  Host truth here is per-row: a row is either a sorted
unique ``uint32`` array of column offsets (sparse) or a packed
``uint32[WORDS_PER_SHARD]`` word array (dense), auto-converting at the
break-even cardinality — the same array↔bitmap economics as roaring's
container conversion at 4096, applied at shard (2^20) granularity because
the device side is dense anyway.

All mutation is via numpy set algebra; no Python-level bit loops.
"""

from __future__ import annotations

import numpy as np

from pilosa_tpu.engine.words import (
    WORDS_PER_SHARD,
    SHARD_WIDTH,
    pack_columns,
    unpack_columns,
    popcount_words,
)

# A dense row costs WORDS_PER_SHARD uint32s; a sparse row of cardinality n
# costs n uint32s.  Convert to dense at equal footprint.
DENSE_THRESHOLD = WORDS_PER_SHARD


def _union_sorted(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """Union of sorted-unique uint32 arrays: native linear merge when
    built, numpy fallback (which re-sorts) otherwise."""
    from pilosa_tpu.store import native
    if native.available():
        return native.union_sorted_u32(a, b)
    return np.union1d(a, b)


def _diff_sorted(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    from pilosa_tpu.store import native
    if native.available():
        return native.diff_sorted_u32(a, b)
    return np.setdiff1d(a, b, assume_unique=True)


class RowBits:
    """Bits of one (row, shard) pair.  Not thread-safe; the owning
    fragment serializes access.

    Dense adds/removes count changed bits by PROBING the touched words
    before the OR/ANDNOT (r5) — not by re-popcounting all 32768 words
    per call, which made every micro-chunk import O(shard width).
    Micro-chunk WRITE amortization lives one level up, in the
    fragment's pending tier (``Fragment._pend_*``) — by the time bits
    reach ``add`` they arrive as large presorted chunks."""

    __slots__ = ("_cols", "_words", "_card")

    def __init__(self) -> None:
        self._cols: np.ndarray | None = np.empty(0, dtype=np.uint32)
        self._words: np.ndarray | None = None
        self._card: int = 0

    # -- constructors -------------------------------------------------------

    @classmethod
    def from_columns(cls, cols: np.ndarray) -> "RowBits":
        r = cls()
        cols = np.unique(np.asarray(cols, dtype=np.uint32))
        if len(cols) and int(cols[-1]) >= SHARD_WIDTH:
            raise ValueError(f"column {cols[-1]} out of shard range")
        r._cols = cols
        r._card = len(cols)
        r._maybe_densify()
        return r

    @classmethod
    def from_words(cls, words: np.ndarray) -> "RowBits":
        r = cls()
        words = np.ascontiguousarray(words, dtype=np.uint32)
        if words.shape != (WORDS_PER_SHARD,):
            raise ValueError(f"expected {WORDS_PER_SHARD} words, got {words.shape}")
        r._cols = None
        r._words = words.copy()
        r._card = popcount_words(words)
        return r

    # -- introspection ------------------------------------------------------

    @property
    def cardinality(self) -> int:
        return self._card

    @property
    def is_dense(self) -> bool:
        return self._words is not None

    def any(self) -> bool:
        return self._card > 0

    def columns(self) -> np.ndarray:
        """Sorted set-column offsets, uint32."""
        if self._cols is not None:
            return self._cols
        return unpack_columns(self._words).astype(np.uint32)

    def words(self) -> np.ndarray:
        """Packed uint32[WORDS_PER_SHARD].  Dense rows return the internal
        buffer — callers must not mutate it (plane assembly copies)."""
        if self._words is not None:
            return self._words
        return pack_columns(self._cols)

    def contains(self, col: int) -> bool:
        if self._words is not None:
            return bool((int(self._words[col >> 5]) >> (col & 31)) & 1)
        i = np.searchsorted(self._cols, np.uint32(col))
        return bool(i < len(self._cols) and self._cols[i] == col)

    # -- mutation -----------------------------------------------------------

    def add(self, cols: np.ndarray, presorted: bool = False) -> int:
        """Set columns; returns how many were newly set.  ``presorted``
        promises sorted-unique uint32 input (the bulk-import path dedups
        a whole fragment batch once instead of per row)."""
        if not presorted:
            cols = np.unique(np.asarray(cols, dtype=np.uint32))
        if len(cols) == 0:
            return 0
        if int(cols[-1]) >= SHARD_WIDTH:
            raise ValueError(f"column {cols[-1]} out of shard range")
        if self._words is not None:
            idx = (cols >> np.uint32(5)).astype(np.int64)
            bit = np.uint32(1) << (cols & np.uint32(31))
            # exact new-bit count by probing BEFORE the OR — not by
            # re-popcounting all 32768 words per call (cols are unique,
            # so (idx, bit) pairs are distinct)
            newly = int(np.count_nonzero(self._words[idx] & bit == 0))
            np.bitwise_or.at(self._words, idx, bit)
            self._card += newly
            return newly
        merged = _union_sorted(self._cols, cols)
        added = len(merged) - self._card
        self._cols = merged
        self._card = len(merged)
        self._maybe_densify()
        return added

    def remove(self, cols: np.ndarray, presorted: bool = False) -> int:
        """Clear columns; returns how many were previously set."""
        if not presorted:
            cols = np.unique(np.asarray(cols, dtype=np.uint32))
        if len(cols) == 0 or self._card == 0:
            return 0
        if self._words is not None:
            idx = (cols >> np.uint32(5)).astype(np.int64)
            bit = np.uint32(1) << (cols & np.uint32(31))
            removed = int(np.count_nonzero(self._words[idx] & bit != 0))
            np.bitwise_and.at(self._words, idx, ~bit)
            self._card -= removed
            return removed
        kept = _diff_sorted(self._cols, cols)
        removed = self._card - len(kept)
        self._cols = kept
        self._card = len(kept)
        return removed

    def clear(self) -> None:
        self._cols = np.empty(0, dtype=np.uint32)
        self._words = None
        self._card = 0

    # -- internal -----------------------------------------------------------

    def _maybe_densify(self) -> None:
        if self._cols is not None and self._card >= DENSE_THRESHOLD:
            self._words = pack_columns(self._cols)
            self._cols = None
