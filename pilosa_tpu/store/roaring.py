"""Roaring bitmap file codec (host/disk format).

Device side is dense packed words (SURVEY.md §8); roaring remains the
disk/interchange format for compactness and reference compatibility.

Two formats:

- **Pilosa 64-bit format** (primary, used for fragment snapshots).
  Layout reconstructed from memory of the reference
  (``roaring/roaring.go#WriteTo/UnmarshalBinary`` — unverified, the
  reference tree was not available; see SURVEY.md §0):

      bytes 0:2   magic   = 12348  (uint16 LE)
      bytes 2:4   version = 0      (uint16 LE)
      bytes 4:8   container count  (uint32 LE)
      per container, 12-byte descriptive header:
          key (uint64 LE, = position >> 16), type (uint16: 1=array,
          2=bitmap, 3=run), cardinality-1 (uint16)
      per container, offset header: uint32 LE byte offset of its data
      container data:
          array:  sorted uint16 LE values
          bitmap: 1024 × uint64 LE (8192 bytes)
          run:    uint16 run count, then (start, last) uint16 LE pairs
                  (inclusive intervals, as the reference's interval16)

- **Standard 32-bit roaring** (``RoaringFormatSpec``: cookies 12346/12347)
  for interop with other roaring implementations, used by import/export
  when positions fit in 32 bits.  Runs here are (start, length-1) pairs
  per the public spec — note the difference from the pilosa format.

All container assembly/expansion is vectorized numpy; the C++ codec
(store/native) accelerates the same formats with an identical interface.
"""

from __future__ import annotations

import struct

import numpy as np

MAGIC = 12348
VERSION = 0

TYPE_ARRAY = 1
TYPE_BITMAP = 2
TYPE_RUN = 3

ARRAY_MAX = 4096  # array container cardinality bound (standard roaring)

# standard-format cookies
COOKIE_NO_RUN = 12346
COOKIE_RUN = 12347
NO_OFFSET_THRESHOLD = 4


# ---------------------------------------------------------------------------
# container assembly from sorted low-16 values
# ---------------------------------------------------------------------------


def _runs_of(lows: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """(starts, lasts) of maximal consecutive runs in sorted uint16 array."""
    breaks = np.nonzero(np.diff(lows.astype(np.int64)) != 1)[0]
    starts = lows[np.concatenate(([0], breaks + 1))]
    lasts = lows[np.concatenate((breaks, [len(lows) - 1]))]
    return starts, lasts


def _best_container(lows: np.ndarray) -> tuple[int, object]:
    """Pick the smallest encoding for one container's sorted values.

    Returns (type, payload) where payload is the values array, a packed
    bitmap uint64[1024], or (starts, lasts).
    """
    n = len(lows)
    starts, lasts = _runs_of(lows)
    run_bytes = 2 + 4 * len(starts)
    array_bytes = 2 * n
    if run_bytes < min(array_bytes, 8192):
        return TYPE_RUN, (starts, lasts)
    if n <= ARRAY_MAX:
        return TYPE_ARRAY, lows
    bits = np.zeros(65536, dtype=np.uint8)
    bits[lows] = 1
    words = np.packbits(bits, bitorder="little").view(np.uint64)
    return TYPE_BITMAP, words


def _expand_bitmap(words8192: bytes) -> np.ndarray:
    if len(words8192) < 8192:
        raise ValueError(
            f"roaring: bitmap container truncated ({len(words8192)} < 8192 "
            "bytes)")
    buf = np.frombuffer(words8192, dtype=np.uint8)
    return np.nonzero(np.unpackbits(buf, bitorder="little"))[0].astype(np.uint16)


def _check_runs(starts: np.ndarray, lasts: np.ndarray) -> None:
    """Reject malformed run lists (untrusted input: imports, cluster
    merges, snapshot files).  Runs must be non-empty intervals, strictly
    ascending and non-overlapping — the same rule the native codec
    enforces (native/roaring_codec.cpp expand_container), which also
    bounds the expansion at 65536 values."""
    s = starts.astype(np.int64)
    e = lasts.astype(np.int64)
    if np.any(e < s) or np.any(s[1:] <= e[:-1]):
        raise ValueError("roaring: malformed run container "
                         "(runs must be ascending, non-overlapping)")


def _expand_runs(starts: np.ndarray, lasts: np.ndarray) -> np.ndarray:
    lens = lasts.astype(np.int64) - starts.astype(np.int64) + 1
    total = int(lens.sum())
    # vectorized multi-arange: offsets within concatenated runs
    idx = np.arange(total, dtype=np.int64)
    run_id = np.repeat(np.arange(len(starts), dtype=np.int64), lens)
    run_base = np.concatenate(([0], np.cumsum(lens)[:-1]))
    return (starts.astype(np.int64)[run_id] + (idx - run_base[run_id])).astype(np.uint16)


def _group_by_high(positions: np.ndarray, shift: int) -> tuple[np.ndarray, list[np.ndarray]]:
    """Split sorted positions into per-container low-16 arrays.

    Returns (keys, [lows...]) with keys = unique ``positions >> shift``.
    """
    highs = positions >> np.uint64(shift)
    keys, starts = np.unique(highs, return_index=True)
    bounds = np.append(starts, len(positions))
    lows = [
        (positions[bounds[i]:bounds[i + 1]] & np.uint64(0xFFFF)).astype(np.uint16)
        for i in range(len(keys))
    ]
    return keys, lows


# ---------------------------------------------------------------------------
# pilosa 64-bit format
# ---------------------------------------------------------------------------


def serialize(positions: np.ndarray) -> bytes:
    """Sorted-or-not uint64 bit positions -> pilosa-format bytes.
    Dispatches to the C++ codec when built (byte-identical output)."""
    positions = np.unique(np.asarray(positions, dtype=np.uint64))
    from pilosa_tpu.store import native
    if native.available():
        return native.serialize(positions)
    keys, lows_per = _group_by_high(positions, 16)
    n = len(keys)
    out = bytearray()
    out += struct.pack("<HHI", MAGIC, VERSION, n)
    payloads: list[bytes] = []
    meta: list[tuple[int, int, int]] = []  # key, type, cardinality
    for key, lows in zip(keys, lows_per):
        ctype, payload = _best_container(lows)
        if ctype == TYPE_ARRAY:
            data = payload.astype("<u2").tobytes()
        elif ctype == TYPE_BITMAP:
            data = payload.astype("<u8").tobytes()
        else:
            starts, lasts = payload
            data = struct.pack("<H", len(starts)) + np.column_stack(
                (starts, lasts)
            ).astype("<u2").tobytes()
        payloads.append(data)
        meta.append((int(key), ctype, len(lows)))
    for key, ctype, card in meta:
        out += struct.pack("<QHH", key, ctype, card - 1)
    data_start = len(out) + 4 * n
    off = data_start
    for data in payloads:
        out += struct.pack("<I", off)
        off += len(data)
    for data in payloads:
        out += data
    return bytes(out)


def serialize_dense(words: np.ndarray, row_ids: np.ndarray | None = None
                    ) -> bytes:
    """Packed row words -> pilosa-format bytes, fully vectorized.

    ``words`` is ``uint32[R, W]`` (row-major packed bits, W*32 = shard
    width); ``row_ids`` the global row id per slab row (default 0..R-1).
    Every non-empty 65536-bit block is written as a BITMAP container —
    valid format but not minimal for sparse/runny blocks (use
    :func:`serialize` for minimality).  This is the bulk writer for
    dense synthetic/bench indexes: no per-position work, essentially a
    popcount + memory-layout transform (reference:
    ``roaring/roaring.go#WriteTo``)."""
    words = np.ascontiguousarray(words, dtype=np.uint32)
    r, w = words.shape
    cw = 65536 // 32                      # uint32 words per container
    if w % cw:
        raise ValueError(f"roaring: row width {w * 32} not a multiple "
                         "of 65536 bits")
    per_row = w // cw
    if row_ids is None:
        row_ids = np.arange(r, dtype=np.uint64)
    conts = words.reshape(r * per_row, cw)
    cards = np.bitwise_count(conts).sum(axis=1, dtype=np.int64)
    keys = (np.repeat(np.asarray(row_ids, np.uint64), per_row)
            * np.uint64(per_row)
            + np.tile(np.arange(per_row, dtype=np.uint64), r))
    nz = cards > 0
    conts, cards, keys = conts[nz], cards[nz], keys[nz]
    n = len(keys)
    meta = np.zeros(n, dtype=[("k", "<u8"), ("t", "<u2"), ("c", "<u2")])
    meta["k"] = keys
    meta["t"] = TYPE_BITMAP
    meta["c"] = cards - 1                 # stored as cardinality-1
    data_start = 8 + 12 * n + 4 * n
    if data_start + 8192 * n > 0xFFFFFFFF:
        # the format's offsets are uint32: fail loudly like serialize()
        # does, never wrap silently into a corrupt-but-parseable blob
        raise ValueError(
            f"roaring: blob exceeds the 4 GB format limit ({n} bitmap "
            "containers)")
    offsets = (data_start
               + 8192 * np.arange(n, dtype=np.int64)).astype("<u4")
    return (struct.pack("<HHI", MAGIC, VERSION, n) + meta.tobytes()
            + offsets.tobytes() + conts.astype("<u4").tobytes())


def deserialize(buf: bytes | memoryview) -> np.ndarray:
    """Pilosa-format or standard-32-bit bytes -> sorted uint64 positions."""
    buf = memoryview(buf)
    if len(buf) < 4:
        raise ValueError("roaring: buffer too short")
    magic, = struct.unpack_from("<H", buf, 0)
    if magic == MAGIC:
        from pilosa_tpu.store import native
        if native.available():
            return native.deserialize(bytes(buf))
        return _deserialize_pilosa(buf)
    cookie, = struct.unpack_from("<I", buf, 0)
    if cookie == COOKIE_NO_RUN or (cookie & 0xFFFF) == COOKIE_RUN:
        return read_standard32(buf).astype(np.uint64)
    raise ValueError(f"roaring: unknown magic/cookie {magic}/{cookie}")


def _deserialize_pilosa(buf: memoryview) -> np.ndarray:
    magic, version, n = struct.unpack_from("<HHI", buf, 0)
    if version != VERSION:
        raise ValueError(f"roaring: unsupported version {version}")
    pos = 8
    keys = np.empty(n, dtype=np.uint64)
    types = np.empty(n, dtype=np.uint16)
    cards = np.empty(n, dtype=np.int64)
    for i in range(n):
        k, t, c = struct.unpack_from("<QHH", buf, pos)
        keys[i], types[i], cards[i] = k, t, c + 1
        pos += 12
    offsets = np.frombuffer(buf, dtype="<u4", count=n, offset=pos).astype(np.int64)
    parts: list[np.ndarray] = []
    for i in range(n):
        off = int(offsets[i])
        if types[i] == TYPE_ARRAY:
            lows = np.frombuffer(buf, dtype="<u2", count=int(cards[i]), offset=off)
        elif types[i] == TYPE_BITMAP:
            lows = _expand_bitmap(bytes(buf[off:off + 8192]))
        elif types[i] == TYPE_RUN:
            nr, = struct.unpack_from("<H", buf, off)
            pairs = np.frombuffer(buf, dtype="<u2", count=2 * nr, offset=off + 2)
            _check_runs(pairs[0::2], pairs[1::2])
            lows = _expand_runs(pairs[0::2], pairs[1::2])
        else:
            raise ValueError(f"roaring: bad container type {types[i]}")
        parts.append((keys[i] << np.uint64(16)) | lows.astype(np.uint64))
    if not parts:
        return np.empty(0, dtype=np.uint64)
    return np.concatenate(parts)


# ---------------------------------------------------------------------------
# zero-copy directory over a pilosa-format buffer (mmap cold-start path)
# ---------------------------------------------------------------------------


class Directory:
    """Parsed container directory over a pilosa-64 buffer WITHOUT
    expanding any bits — the ``roaring.FromBuffer`` analogue (reference:
    ``syswrap`` mmap open, SURVEY.md §3.1).  Holds only O(containers)
    header arrays; per-row expansion is on demand.  The buffer (usually
    an mmap) must outlive the directory."""

    ROW_SHIFT = 4  # key = position >> 16; row = position >> 20 = key >> 4

    def __init__(self, buf):
        self.buf = memoryview(buf)
        if len(self.buf) < 8:
            raise ValueError("roaring: buffer too short")
        magic, version, n = struct.unpack_from("<HHI", self.buf, 0)
        if magic != MAGIC:
            raise ValueError(f"roaring: bad magic {magic}")
        if version != VERSION:
            raise ValueError(f"roaring: unsupported version {version}")
        hdr_end = 8 + 12 * n
        if len(self.buf) < hdr_end + 4 * n:
            raise ValueError("roaring: truncated container headers")
        hdr = np.frombuffer(self.buf, dtype=np.uint8, count=12 * n,
                            offset=8).reshape(n, 12)
        self.keys = hdr[:, 0:8].copy().view("<u8").reshape(n)
        self.types = hdr[:, 8:10].copy().view("<u2").reshape(n)
        self.cards = (hdr[:, 10:12].copy().view("<u2").reshape(n)
                      .astype(np.int64) + 1)
        self.offsets = np.frombuffer(self.buf, dtype="<u4", count=n,
                                     offset=hdr_end).astype(np.int64)
        # bounds-check every container's payload now, VECTORIZED — a
        # sparse 5M-row snapshot legitimately has tens of millions of
        # tiny containers, so corruption checks cannot be a Python loop
        size = len(self.buf)
        t, off, cards = self.types, self.offsets, self.cards
        known = (t == TYPE_ARRAY) | (t == TYPE_BITMAP) | (t == TYPE_RUN)
        if not known.all():
            bad = int(t[~known][0])
            raise ValueError(f"roaring: bad container type {bad}")
        end = np.where(t == TYPE_ARRAY, off + 2 * cards, off + 8192)
        run_idx = np.nonzero(t == TYPE_RUN)[0]
        if len(run_idx):
            ro = off[run_idx]
            if int(ro.max()) + 2 > size:
                raise ValueError("roaring: truncated run container")
            u8 = np.frombuffer(self.buf, dtype=np.uint8)
            nr = u8[ro].astype(np.int64) | (u8[ro + 1].astype(np.int64)
                                            << 8)
            end[run_idx] = ro + 2 + 4 * nr
        if len(end) and int(end.max()) > size:
            raise ValueError("roaring: container data out of bounds")
        self._rows = (self.keys >> np.uint64(self.ROW_SHIFT)).astype(
            np.uint64)
        # keys ascend in every writer we know; a sorted row axis turns
        # per-row container lookup into searchsorted
        self._rows_sorted = bool(np.all(self._rows[1:] >= self._rows[:-1])) \
            if n > 1 else True

    def row_ids(self) -> np.ndarray:
        return np.unique(self._rows)

    def _row_container_idx(self, row: int) -> np.ndarray:
        if self._rows_sorted:
            lo = np.searchsorted(self._rows, np.uint64(row), "left")
            hi = np.searchsorted(self._rows, np.uint64(row), "right")
            return np.arange(lo, hi)
        return np.nonzero(self._rows == np.uint64(row))[0]

    def row_cardinality(self, row: int) -> int:
        return int(self.cards[self._row_container_idx(row)].sum())

    def row_cards(self) -> tuple[np.ndarray, np.ndarray]:
        """(row_ids uint64[R], cardinalities int64[R]) for every row —
        vectorized over the container directory, no expansion.  Cached:
        the buffer is immutable, and callers (per-query budget checks)
        hit this hot."""
        cached = getattr(self, "_row_cards_cache", None)
        if cached is None:
            uniq, inv = np.unique(self._rows, return_inverse=True)
            cards = np.zeros(len(uniq), np.int64)
            np.add.at(cards, inv, self.cards)
            cached = self._row_cards_cache = (uniq, cards)
        return cached

    def expand_container(self, i: int) -> np.ndarray:
        """Container i's low-16 values, sorted uint16."""
        off, t = int(self.offsets[i]), int(self.types[i])
        if t == TYPE_ARRAY:
            return np.frombuffer(self.buf, dtype="<u2",
                                 count=int(self.cards[i]), offset=off)
        if t == TYPE_BITMAP:
            return _expand_bitmap(bytes(self.buf[off:off + 8192]))
        nr, = struct.unpack_from("<H", self.buf, off)
        pairs = np.frombuffer(self.buf, dtype="<u2", count=2 * nr,
                              offset=off + 2)
        _check_runs(pairs[0::2], pairs[1::2])
        return _expand_runs(pairs[0::2], pairs[1::2])

    def row_words(self, row: int, out: np.ndarray) -> None:
        """OR one row's bits into ``out`` (uint32[32768], the row's
        packed words) straight from the blob: bitmap containers are a
        plain memcpy of their 8KB payload — no position expansion, no
        repacking.  The fast path for assembling device planes from
        mmap'd snapshots (array/run containers scatter their bits)."""
        for i in self._row_container_idx(row):
            i = int(i)
            base_word = (int(self.keys[i])
                         & ((1 << self.ROW_SHIFT) - 1)) * 2048
            if int(self.types[i]) == TYPE_BITMAP:
                off = int(self.offsets[i])
                out[base_word:base_word + 2048] |= np.frombuffer(
                    self.buf, dtype="<u4", count=2048, offset=off)
            else:
                lows = self.expand_container(i).astype(np.int32)
                np.bitwise_or.at(
                    out, base_word + (lows >> 5),
                    (np.uint32(1) << (lows & 31).astype(np.uint32)))

    def expand_row(self, row: int) -> np.ndarray:
        """One row's column offsets (sorted uint32) — touches only that
        row's containers."""
        idx = self._row_container_idx(row)
        parts = []
        for i in idx:
            base = (int(self.keys[i]) & ((1 << self.ROW_SHIFT) - 1)) << 16
            parts.append(self.expand_container(int(i)).astype(np.uint32)
                         | np.uint32(base))
        if not parts:
            return np.empty(0, np.uint32)
        return np.concatenate(parts)


# ---------------------------------------------------------------------------
# standard 32-bit roaring (public spec)
# ---------------------------------------------------------------------------


def write_standard32(values: np.ndarray) -> bytes:
    """Sorted-or-not uint32 values -> standard roaring format bytes."""
    values = np.unique(np.asarray(values, dtype=np.uint64))
    if len(values) and int(values[-1]) >> 32:
        raise ValueError("standard32: value exceeds 32 bits")
    keys, lows_per = _group_by_high(values, 16)
    n = len(keys)
    conts = [_best_container(lows) for lows in lows_per]
    has_run = any(t == TYPE_RUN for t, _ in conts)
    out = bytearray()
    if has_run:
        out += struct.pack("<I", COOKIE_RUN | ((n - 1) << 16))
        flags = np.zeros((n + 7) // 8, dtype=np.uint8)
        for i, (t, _) in enumerate(conts):
            if t == TYPE_RUN:
                flags[i // 8] |= 1 << (i % 8)
        out += flags.tobytes()
    else:
        out += struct.pack("<II", COOKIE_NO_RUN, n)
    for (t, _), key, lows in zip(conts, keys, lows_per):
        out += struct.pack("<HH", int(key), len(lows) - 1)
    payloads = []
    for t, payload in conts:
        if t == TYPE_ARRAY:
            payloads.append(payload.astype("<u2").tobytes())
        elif t == TYPE_BITMAP:
            payloads.append(payload.astype("<u8").tobytes())
        else:
            starts, lasts = payload
            lens1 = (lasts.astype(np.int64) - starts.astype(np.int64)).astype("<u2")
            payloads.append(
                struct.pack("<H", len(starts))
                + np.column_stack((starts.astype("<u2"), lens1)).tobytes()
            )
    if not has_run or n >= NO_OFFSET_THRESHOLD:
        off = len(out) + 4 * n
        for data in payloads:
            out += struct.pack("<I", off)
            off += len(data)
    for data in payloads:
        out += data
    return bytes(out)


def read_standard32(buf: bytes | memoryview) -> np.ndarray:
    """Standard roaring format bytes -> sorted uint32 values (as uint64)."""
    buf = memoryview(buf)
    cookie, = struct.unpack_from("<I", buf, 0)
    pos = 4
    run_flags = None
    if cookie == COOKIE_NO_RUN:
        n, = struct.unpack_from("<I", buf, pos)
        pos += 4
    elif (cookie & 0xFFFF) == COOKIE_RUN:
        n = (cookie >> 16) + 1
        nb = (n + 7) // 8
        run_flags = np.frombuffer(buf, dtype=np.uint8, count=nb, offset=pos)
        pos += nb
    else:
        raise ValueError(f"standard32: bad cookie {cookie}")
    keys = np.empty(n, dtype=np.uint64)
    cards = np.empty(n, dtype=np.int64)
    for i in range(n):
        k, c = struct.unpack_from("<HH", buf, pos)
        keys[i], cards[i] = k, c + 1
        pos += 4
    if run_flags is None or n >= NO_OFFSET_THRESHOLD:
        pos += 4 * n  # skip offset header; data is sequential anyway
    parts = []
    for i in range(n):
        is_run = run_flags is not None and (run_flags[i // 8] >> (i % 8)) & 1
        if is_run:
            nr, = struct.unpack_from("<H", buf, pos)
            pos += 2
            pairs = np.frombuffer(buf, dtype="<u2", count=2 * nr, offset=pos)
            pos += 4 * nr
            starts = pairs[0::2]
            lasts64 = pairs[0::2].astype(np.int64) + pairs[1::2]
            if np.any(lasts64 > 0xFFFF):
                raise ValueError("standard32: run exceeds container range")
            lasts = lasts64.astype(np.uint16)
            _check_runs(starts, lasts)
            lows = _expand_runs(starts, lasts)
        elif cards[i] > ARRAY_MAX:
            lows = _expand_bitmap(bytes(buf[pos:pos + 8192]))
            pos += 8192
        else:
            lows = np.frombuffer(buf, dtype="<u2", count=int(cards[i]), offset=pos)
            pos += 2 * int(cards[i])
        parts.append((keys[i] << np.uint64(16)) | lows.astype(np.uint64))
    if not parts:
        return np.empty(0, dtype=np.uint64)
    return np.concatenate(parts)
