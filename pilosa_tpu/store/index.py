"""Index: a collection of fields over one column space.

Reference: ``index.go`` (SURVEY.md §3.1) — per-index options ``keys`` and
``trackExistence``; when existence is tracked, an internal ``_exists``
field (one row, row 0) records which columns exist, enabling ``Not`` and
``All`` (``executor.go#executeNot``).
"""

from __future__ import annotations

import json
import os
import threading
from datetime import datetime

import numpy as np

from pilosa_tpu.store.field import Field, FieldOptions

EXISTENCE_FIELD = "_exists"


class Index:
    def __init__(self, path: str, name: str, *, keys: bool = False,
                 track_existence: bool = True, fsync: bool = False,
                 created_at: float = 0.0, snapshot_submit=None,
                 health=None):
        self.path = path
        self.name = name
        self.keys = keys
        self.track_existence = track_existence
        self.created_at = created_at
        self.fsync = fsync
        self.snapshot_submit = snapshot_submit
        self.health = health
        self.fields: dict[str, Field] = {}
        self._column_attrs = None
        self._lock = threading.RLock()

    # -- lifecycle ----------------------------------------------------------

    def open(self) -> "Index":
        meta = os.path.join(self.path, ".meta")
        if os.path.exists(meta):
            with open(meta) as f:
                opts = json.load(f)
            self.keys = opts.get("keys", False)
            self.track_existence = opts.get("track_existence", True)
            self.created_at = opts.get("created_at", 0.0)
        for entry in sorted(os.listdir(self.path)) if os.path.isdir(self.path) else []:
            fpath = os.path.join(self.path, entry)
            if os.path.isdir(fpath) and not entry.startswith("."):
                self.fields[entry] = Field(
                    fpath, self.name, entry, fsync=self.fsync,
                    snapshot_submit=self.snapshot_submit,
                    health=self.health).open()
        if self.track_existence and EXISTENCE_FIELD not in self.fields:
            self._create_existence()
        return self

    def save_meta(self) -> None:
        os.makedirs(self.path, exist_ok=True)
        tmp = os.path.join(self.path, ".meta.tmp")
        with open(tmp, "w") as f:
            json.dump({"keys": self.keys,
                       "track_existence": self.track_existence,
                       "created_at": self.created_at}, f)
        os.replace(tmp, os.path.join(self.path, ".meta"))

    def close(self) -> None:
        for f in self.fields.values():
            f.close()
        if self._column_attrs is not None:
            self._column_attrs.close()
            self._column_attrs = None

    # -- fields -------------------------------------------------------------

    def create_field(self, name: str, options: FieldOptions | None = None) -> Field:
        import time
        with self._lock:
            if name in self.fields:
                raise ValueError(f"field {name!r} already exists")
            options = options or FieldOptions()
            if not options.created_at:
                options.created_at = time.time()
            f = Field(os.path.join(self.path, name), self.name, name,
                      options, fsync=self.fsync,
                      snapshot_submit=self.snapshot_submit,
                      health=self.health)
            os.makedirs(f.path, exist_ok=True)
            f.save_meta()
            self.fields[name] = f
            return f

    def ensure_field(self, name: str, options: FieldOptions | None = None) -> Field:
        with self._lock:
            return self.fields.get(name) or self.create_field(name, options)

    def field(self, name: str) -> Field | None:
        return self.fields.get(name)

    def delete_field(self, name: str) -> None:
        import shutil
        with self._lock:
            f = self.fields.pop(name, None)
            if f is None:
                raise KeyError(name)
            f.close()
            shutil.rmtree(f.path, ignore_errors=True)

    def _create_existence(self) -> Field:
        return self.create_field(EXISTENCE_FIELD, FieldOptions(type="set"))

    @property
    def existence_field(self) -> Field | None:
        return self.fields.get(EXISTENCE_FIELD)

    @property
    def column_attrs(self):
        """Column attribute store (reference: index-level AttrStore,
        ``index.go``/``attrstore.go``), created on first use."""
        with self._lock:
            if self._column_attrs is None:
                from pilosa_tpu.store.attrs import AttrStore
                self._column_attrs = AttrStore(
                    os.path.join(self.path, "_attrs.db"))
            return self._column_attrs

    # -- column tracking ----------------------------------------------------

    def note_columns(self, cols: np.ndarray) -> None:
        """Record columns in the existence field (row 0) — called by every
        write path when ``trackExistence`` (reference: ``index.go``)."""
        ef = self.existence_field
        if ef is not None and len(cols):
            ef.import_bits(np.zeros(len(cols), np.uint64),
                           np.asarray(cols, np.uint64))

    def available_shards(self) -> list[int]:
        shards: set[int] = set()
        for f in self.fields.values():
            shards.update(f.available_shards())
        return sorted(shards)

    # -- write facade (used by API/executor) --------------------------------

    def set_bit(self, field: str, row_id: int, col: int,
                timestamp: datetime | None = None) -> bool:
        f = self.fields.get(field)
        if f is None:
            raise KeyError(f"field {field!r} not found")
        changed = f.set_bit(row_id, col, timestamp)
        self.note_columns(np.array([col], np.uint64))
        return changed

    def set_value(self, field: str, col: int, value) -> bool:
        f = self.fields.get(field)
        if f is None:
            raise KeyError(f"field {field!r} not found")
        changed = f.set_value(col, value)
        self.note_columns(np.array([col], np.uint64))
        return changed
