"""Fragment: one (field, view, shard) storage unit.

Reference: ``fragment.go`` (SURVEY.md §3.1) — bits of all rows of one view
of one shard in a single roaring bitmap keyed by
``rowID * ShardWidth + column``, persisted as an mmap'd snapshot plus an
op-log, compacted when ``opN > MaxOpN``.

This rebuild keeps the same on-disk contract (roaring snapshot file +
CRC-framed op-log, same position encoding) but host memory is per-row
:class:`~pilosa_tpu.store.row.RowBits` (sparse/dense auto-converting) —
the natural shape for assembling dense device planes.  The reference's
per-fragment TopN rank/LRU cache (``cache.go``) is intentionally absent:
on TPU, TopN recounts every row at HBM bandwidth (``engine.kernels.row_counts``),
so there is no cache to maintain or invalidate.

Concurrency: one RLock per fragment (reference: per-fragment
``sync.RWMutex``); mutators and plane assembly take it.
"""

from __future__ import annotations

import os
import struct
import threading
import zlib

import numpy as np

from pilosa_tpu.engine.words import SHARD_WIDTH
from pilosa_tpu.store import health as _storage_health
from pilosa_tpu.store import roaring
from pilosa_tpu.store.oplog import (OP_CLEAR_BITS, OP_CLEAR_ROW, OP_SET_BITS,
                                    OP_SET_ROW, OpLog)
from pilosa_tpu.store.row import RowBits

# Reference default: compact the op-log into a snapshot after ~2000 ops.
MAX_OP_N = 2000

# Rows per anti-entropy checksum block (reference: HashBlockSize = 100).
HASH_BLOCK_SIZE = 100

_SW = np.uint64(SHARD_WIDTH)


class Fragment:
    """Bits of one (field, view, shard)."""

    def __init__(self, path: str, shard: int, *, max_op_n: int = MAX_OP_N,
                 fsync: bool = False, snapshot_submit=None, health=None):
        self.path = path                      # snapshot file
        self.shard = shard
        self.max_op_n = max_op_n
        # disk-health governor + quarantine registry (r19), threaded
        # down from the holder like snapshot_submit; None for bare
        # fragments (unit tests) — every check is guarded
        self._health = health
        # when set, op-log compaction is handed to a background queue
        # (reference: the fragment snapshot queue in holder.go) instead
        # of running inline on the write path
        self._snapshot_submit = snapshot_submit
        self.rows: dict[int, RowBits] = {}    # materialized/overlay rows
        self.op_n = 0
        self.generation = 0                   # bumped per mutation; device
                                              # plane caches key on this
        self.lock = threading.RLock()
        self._oplog = OpLog(path + ".oplog", fsync=fsync)
        self._open = False
        # lazy snapshot (mmap FromBuffer path, SURVEY.md §3.1 syswrap):
        # rows still in _snap_pending live only in the mapped file;
        # _ensure_row materializes them into self.rows on first touch
        self._snap_mm = None
        self._snap_dir: roaring.Directory | None = None
        self._snap_pending: set[int] = set()
        # the framed snapshot's declared crc32 (None = legacy unframed
        # file): re-checked when the mmap demotes to a heap copy
        self._snap_crc: int | None = None
        # recent-mutation journal for incremental device-plane updates
        # (exec.planes): (generation_after, {row: word_idx set | None}),
        # None = whole row changed.  Bounded; a gap means "rebuild".
        from collections import deque
        self._recent: deque = deque(maxlen=self.RECENT_MAX)
        # LSM-style pending tier (r5; reference: the amortization
        # ``fragment.bulkImport`` gets from one bulk union, SURVEY.md
        # §4.5): OP_SET_BITS batches append their genuinely-new
        # positions to one sorted array instead of paying a
        # sorted-union per (row, fragment) micro-chunk — the cost that
        # bounded spread ingest at ~0.17M bits/s (BASELINE.md r4).
        # ``_probe_cache`` is the merged tier's sorted positions for
        # O(log n) exact-changed probes; invariant: pending non-empty
        # ⇒ probe cache valid.  The op-log write still precedes all of
        # this, so crash replay re-derives pending — durability
        # semantics unchanged.
        self._pend_pos: np.ndarray = np.empty(0, np.uint64)
        self._probe_cache: np.ndarray | None = None

    # journal bounds: entries beyond RECENT_MAX or ops touching more
    # cells than RECENT_CELL_CAP evict history (planes falls back to a
    # compaction/rebuild).  The cell cap covers import-batch-sized ops
    # (r15 delta planes absorb bulk writes into device overlays —
    # positions-form entries alias the batch's already-allocated array,
    # so the cap bounds only the dict-form classic path's word lists)
    RECENT_MAX = 128
    RECENT_CELL_CAP = 65536

    # pending tier: flush to per-row RowBits at this many buffered bits
    # (bounds pending memory at 8 B/bit and keeps the per-batch sorted
    # insert cheap); probe caches beyond this bit count are not built
    # (8 B/bit of extra host memory — huge fragments keep the classic
    # per-row path)
    PEND_FLUSH_N = 65536
    PROBE_CACHE_MAX_BITS = 8 << 20

    # -- lifecycle ----------------------------------------------------------

    def open(self) -> "Fragment":
        with self.lock:
            if self._open:
                return self
            if os.path.exists(self.path) and os.path.getsize(self.path) > 0:
                try:
                    self._open_snapshot()
                except Exception as e:  # noqa: BLE001 — a corrupt
                    # snapshot must quarantine the FRAGMENT, never
                    # fail the whole holder open (the node still
                    # serves every healthy fragment; this one reads
                    # from replicas until repaired)
                    self._mark_corrupt("snapshot", f"open failed: {e}")
            for op, aux, positions in self._oplog.replay():
                self._apply(op, aux, positions)
                self.op_n += 1
            self._open = True
        return self

    # r19 snapshot frame: versioned header + crc32 of the roaring blob
    # (the end-to-end checksum the `.dense` sidecar already had).
    # Legacy unframed snapshots (raw roaring, first two bytes ==
    # roaring.MAGIC) still load — they just carry no checksum.
    SNAP_MAGIC = b"PSF1"
    SNAP_VERSION = 1
    _SNAP_HDR = struct.Struct("<4sHHQI")  # magic, ver, rsvd, len, crc

    def _open_snapshot(self) -> None:
        """mmap the snapshot and parse only its container directory —
        zero-copy cold start (the reference's ``roaring.FromBuffer`` over
        ``syswrap.Mmap``): no bit is expanded until a row is touched.
        Map count is bounded by ``syswrap.GLOBAL`` (LRU demotion to a
        heap copy — the reference's mmap→heap fallback).  Framed (r19)
        snapshots verify their crc BEFORE any bit can be served —
        corruption that would still parse (a flipped container key
        silently misroutes bits) quarantines instead."""
        import mmap as _mmaplib

        from pilosa_tpu.store import syswrap
        with open(self.path, "rb") as f:
            head = f.read(self._SNAP_HDR.size)
            if head[:4] == self.SNAP_MAGIC:
                if len(head) < self._SNAP_HDR.size:
                    self._mark_corrupt("snapshot",
                                       "truncated frame header")
                    return
                _m, ver, _r, blen, crc = self._SNAP_HDR.unpack(head)
                if ver != self.SNAP_VERSION:
                    self._mark_corrupt(
                        "snapshot", f"unknown frame version {ver}")
                    return
                mm = _mmaplib.mmap(f.fileno(), 0,
                                   access=_mmaplib.ACCESS_READ)
                blob = memoryview(mm)[self._SNAP_HDR.size:]
                # integrity before use; zlib releases the GIL so
                # concurrent fragment opens overlap the passes
                bad = len(blob) != blen or zlib.crc32(blob) != crc
                if bad:
                    del blob
                    try:
                        mm.close()
                    except BufferError:
                        pass
                    self._mark_corrupt(
                        "snapshot",
                        "frame length/crc mismatch (disk corruption)")
                    return
                self._snap_mm = mm
                self._snap_crc = crc
                self._snap_dir = roaring.Directory(blob)
                self._snap_pending = set(
                    int(r) for r in self._snap_dir.row_ids())
                syswrap.GLOBAL.register(self)
                return
            if len(head) >= 2 and struct.unpack("<H", head[:2])[0] == \
                    roaring.MAGIC:
                # legacy unframed (pre-r19) snapshot: no checksum
                mm = _mmaplib.mmap(f.fileno(), 0,
                                   access=_mmaplib.ACCESS_READ)
                self._snap_mm = mm
                self._snap_crc = None
                self._snap_dir = roaring.Directory(memoryview(mm))
                self._snap_pending = set(
                    int(r) for r in self._snap_dir.row_ids())
                syswrap.GLOBAL.register(self)
                return
            # non-pilosa (e.g. standard32) snapshot: legacy eager load
            f.seek(0)
            self._load_positions(roaring.deserialize(f.read()))

    def poison_snapshot(self) -> None:
        """Scrub-detected snapshot corruption on a LIVE fragment: drop
        the in-memory mapping so lazily-pending rows can no longer
        expand from the corrupt blob (reads then serve the overlay
        rows only — loud and quarantined, never silently wrong; the
        generation bump invalidates device planes built over the bad
        bytes).  The registry entry is the caller's job."""
        with self.lock:
            self._drop_snapshot()
            self._snap_crc = None
            self.generation += 1
            self._recent.clear()
            self._recent.append((self.generation, None))

    def _mark_corrupt(self, kind: str, detail: str) -> None:
        """Quarantine this fragment after an end-to-end checksum (or
        parse) failure: drop the snapshot refs and serve EMPTY locally
        — in cluster mode reads route to a replica and the scrubber's
        repair pulls a fresh copy; single-node, a loud quarantined
        empty beats silently-wrong bits."""
        self._drop_snapshot()
        self._snap_crc = None
        h = self._health
        if h is not None:
            h.quarantine(self.path, kind, detail)
        else:
            import logging
            logging.getLogger("pilosa_tpu.store").error(
                "fragment snapshot corrupt (%s) at %s: %s",
                kind, self.path, detail)

    def _demote_map(self) -> bool:
        """Swap the mmap'd snapshot for a heap copy (syswrap LRU
        eviction); returns False when the timed lock acquire fails so
        the pool can keep tracking this fragment (on contention the cap
        stays soft rather than deadlocking against a concurrent
        opener)."""
        if not self.lock.acquire(timeout=1.0):
            return False
        try:
            if self._snap_mm is None or self._snap_dir is None:
                return True  # nothing to demote — already heap/absent
            heap = bytes(self._snap_dir.buf)
            if self._snap_crc is not None \
                    and zlib.crc32(heap) != self._snap_crc:
                # the mapped bytes changed under us (disk/page-cache
                # corruption): the heap copy is poisoned — quarantine
                # at the demotion re-parse instead of serving it
                self._mark_corrupt(
                    "snapshot", "crc mismatch at mmap demotion")
                return True
            self._snap_dir = roaring.Directory(memoryview(heap))
            self._snap_mm = None  # closed when the last view dies
            return True
        finally:
            self.lock.release()

    def _drop_snapshot(self) -> None:
        from pilosa_tpu.store import syswrap
        syswrap.GLOBAL.release(self)
        self._snap_dir = None
        self._snap_pending = set()
        if self._snap_mm is not None:
            try:
                self._snap_mm.close()
            except BufferError:
                pass  # in-flight views; refcounting closes it later
            self._snap_mm = None

    def _ensure_row(self, row_id: int) -> None:
        """Materialize one snapshot-resident row into the overlay."""
        if row_id in self._snap_pending:
            self.rows[row_id] = RowBits.from_columns(
                self._snap_dir.expand_row(row_id))
            self._snap_pending.discard(row_id)

    def _materialize_all(self) -> None:
        for r in sorted(self._snap_pending):
            self._ensure_row(r)

    # -- pending tier -------------------------------------------------------

    def _flush_pending(self) -> None:
        """Merge the pending tier into per-row RowBits: ONE presorted
        union per touched row per flush, however many batches
        accumulated.  Callers hold the lock."""
        if not len(self._pend_pos):
            return
        pend = self._pend_pos
        self._pend_pos = np.empty(0, np.uint64)
        self._probe_cache = None
        for r, chunk in _split_by_row(pend, presorted=True):
            self._ensure_row(r)
            row = self.rows.get(r)
            if row is None:
                row = self.rows[r] = RowBits()
            row.add(chunk, presorted=True)

    def _pend_add(self, positions: np.ndarray) -> np.ndarray | None:
        """Append the genuinely-new subset of sorted-unique
        ``positions`` to the pending tier; returns that subset (exact
        changed count = its length), or None when the tier can't serve
        this fragment (probe cache would exceed its bit cap — caller
        falls back to the classic per-row path)."""
        if self._probe_cache is None:
            # pending is empty whenever the cache is absent, so
            # positions() here is merged-tier truth
            if self.cardinality() > self.PROBE_CACHE_MAX_BITS:
                return None
            self._probe_cache = self.positions()
        cache = self._probe_cache
        if len(cache):
            i = np.searchsorted(cache, positions)
            ic = np.minimum(i, len(cache) - 1)
            new = positions[~((i < len(cache)) & (cache[ic] == positions))]
        else:
            new = positions
        pend = self._pend_pos
        if len(pend) and len(new):
            j = np.searchsorted(pend, new)
            jc = np.minimum(j, len(pend) - 1)
            new = new[~((j < len(pend)) & (pend[jc] == new))]
        if len(new):
            self._pend_pos = np.insert(pend, np.searchsorted(pend, new),
                                       new)
            if len(self._pend_pos) >= self.PEND_FLUSH_N:
                self._flush_pending()
        return new

    def close(self) -> None:
        with self.lock:
            if self.op_n > 0:
                self.snapshot()
            self._drop_snapshot()
            self._oplog.close()
            self._open = False

    # -- reads --------------------------------------------------------------

    def _touch_map(self) -> None:
        if self._snap_mm is not None:
            from pilosa_tpu.store import syswrap
            syswrap.GLOBAL.touch(self)

    def row(self, row_id: int) -> RowBits:
        with self.lock:
            self._touch_map()
            self._flush_pending()
            self._ensure_row(row_id)
            return self.rows.get(row_id) or RowBits()

    def row_ids(self) -> list[int]:
        with self.lock:
            live = {r for r, b in self.rows.items() if b.any()}
            if len(self._pend_pos):
                live |= set((self._pend_pos // _SW).tolist())
            return sorted(live | self._snap_pending)

    def row_ids_array(self) -> np.ndarray:
        """Live row ids as an UNSORTED uint64 array, duplicates
        possible across tiers (callers np.unique) — the vectorized
        form for cross-shard unions (a 5M-row field's per-query
        set-union/sort through ``row_ids`` measured ~7 s across 954
        shards)."""
        with self.lock:
            live = [r for r, b in self.rows.items() if b.any()]
            pend = (_dedup_sorted(self._pend_pos // _SW)
                    if len(self._pend_pos) else ())
            n = len(live) + len(self._snap_pending) + len(pend)
            out = np.empty(n, np.uint64)
            out[:len(live)] = live
            out[len(live):len(live) + len(self._snap_pending)] = \
                list(self._snap_pending)
            out[len(live) + len(self._snap_pending):] = pend
            return out

    @property
    def present(self) -> bool:
        """Cheap row-presence check WITHOUT expanding snapshot bits:
        overlay rows, rows still resident in the mmap'd snapshot, or
        pending-tier bits.  (``rows`` alone misses lazily-opened
        snapshot fragments — a cold-reopened multi-shard index would
        report no shards and queries would silently cover only
        shard 0.)"""
        return (bool(self.rows) or bool(self._snap_pending)
                or len(self._pend_pos) > 0)

    def max_row_id(self) -> int:
        ids = self.row_ids()
        return ids[-1] if ids else 0

    def cardinality(self) -> int:
        with self.lock:
            cached = getattr(self, "_card_cache", None)
            if cached is not None and cached[0] == self.generation:
                return cached[1]
            # vectorized via row_cardinalities: a sparse snapshot can
            # hold millions of pending rows
            _, cards = self.row_cardinalities()
            total = int(cards.sum())
            self._card_cache = (self.generation, total)
            return total

    def positions(self) -> np.ndarray:
        """All set bits as sorted uint64 ``row*ShardWidth + col``.

        Snapshot-resident rows decode straight from the blob (native
        codec when built) WITHOUT materializing host ``RowBits`` — the
        bulk path for snapshot compaction and the sparse device build."""
        with self.lock:
            self._touch_map()
            parts = []
            if self._snap_pending:
                snap = roaring.deserialize(self._snap_dir.buf)
                if len(self._snap_pending) != len(
                        self._snap_dir.row_ids()):
                    # some snapshot rows were materialized (overlay wins)
                    pend = np.fromiter(self._snap_pending, np.uint64,
                                       len(self._snap_pending))
                    keep = np.isin(snap // _SW, pend)
                    snap = snap[keep]
                parts.append(snap)
            parts += [
                np.uint64(r) * _SW + b.columns().astype(np.uint64)
                for r, b in sorted(self.rows.items())
                if b.any()
            ]
            if len(self._pend_pos):
                # disjoint from both other tiers by construction
                parts.append(self._pend_pos)
        if not parts:
            return np.empty(0, dtype=np.uint64)
        if len(parts) == 1:
            return parts[0]
        return np.sort(np.concatenate(parts))

    def row_cardinalities(self) -> tuple[np.ndarray, np.ndarray]:
        """(row_ids uint64[R] sorted, cards int64[R]) without expanding
        any bits: directory sums for snapshot-resident rows, RowBits
        cardinality for overlay rows."""
        with self.lock:
            ids, cards = [], []
            if self._snap_pending and self._snap_dir is not None:
                uniq, ucards = self._snap_dir.row_cards()
                if len(self._snap_pending) != len(uniq):
                    pend = np.fromiter(self._snap_pending, np.uint64,
                                       len(self._snap_pending))
                    keep = np.isin(uniq, pend)
                    uniq, ucards = uniq[keep], ucards[keep]
                ids.append(uniq)
                cards.append(ucards)
            live = [(r, b.cardinality) for r, b in self.rows.items()
                    if b.any()]
            if live:
                live.sort()
                ids.append(np.array([r for r, _ in live], np.uint64))
                cards.append(np.array([c for _, c in live], np.int64))
            if len(self._pend_pos):
                # pending rows may ALSO exist in the overlay/snapshot —
                # sum-merge below folds the duplicates
                pr = self._pend_pos // _SW
                uniq = _dedup_sorted(pr)
                bounds = np.searchsorted(pr, uniq)
                ids.append(uniq)
                cards.append(np.diff(np.append(bounds, len(pr)))
                             .astype(np.int64))
        if not ids:
            return np.empty(0, np.uint64), np.empty(0, np.int64)
        if len(ids) == 1:
            return ids[0], cards[0]
        all_ids = np.concatenate(ids)
        all_cards = np.concatenate(cards)
        uniq = np.unique(all_ids)
        if len(uniq) == len(all_ids):
            order = np.argsort(all_ids, kind="stable")
            return all_ids[order], all_cards[order]
        sums = np.zeros(len(uniq), np.int64)
        np.add.at(sums, np.searchsorted(uniq, all_ids), all_cards)
        return uniq, sums

    def plane_rows(self, row_ids, out: np.ndarray, slots=None) -> None:
        """Fill ``out[slots[i]] = words of row_ids[i]`` (uint32[.., W]).

        The plane-assembly fast path: rows still resident in the mmap'd
        snapshot expand straight from the blob — via the C++
        ``rc_expand_plane`` when built (one pass over the file's
        containers for any number of rows), else per-row — without ever
        materializing host ``RowBits``.  Overlay rows copy their packed
        words.  Rows absent everywhere leave ``out`` untouched (callers
        pass zeroed slabs)."""
        from pilosa_tpu.store import native
        if slots is None:
            slots = range(len(row_ids))
        with self.lock:
            self._touch_map()
            self._flush_pending()
            pend, pend_slots = [], []
            for r, s in zip(row_ids, slots):
                r = int(r)
                if r in self._snap_pending:
                    pend.append(r)
                    pend_slots.append(s)
                else:
                    b = self.rows.get(r)
                    if b is not None and b.any():
                        out[s] = b.words()
            if not pend:
                return
            if native.available() and len(pend) >= 8:
                order = np.argsort(pend)
                pend_sorted = np.array(pend, np.uint64)[order]
                tmp = np.zeros((len(pend), out.shape[-1]), np.uint32)
                native.expand_plane(self._snap_dir.buf, SHARD_WIDTH,
                                    pend_sorted, tmp)
                out[np.array(pend_slots)[order]] = tmp
            else:
                # few rows: per-row directory slices (bitmap containers
                # memcpy from the blob) — no RowBits materialization,
                # and unlike the native one-pass expand it never walks
                # containers of rows that weren't asked for
                for r, s in zip(pend, pend_slots):
                    self._snap_dir.row_words(r, out[s])

    # -- bulk expansion + dense sidecar (r10 plane pipeline) ----------------

    # <snapshot>.dense sidecar: header + a serialize_dense roaring
    # image of the fragment's full dense rows.  The header stamps the
    # on-disk state the image captured plus a crc32 of the image; any
    # write grows the op-log and any compaction replaces the snapshot,
    # so a stamp mismatch is the (restart-stable) invalidation, and
    # the crc catches byte corruption that would otherwise still parse
    # (a flipped container key silently misroutes bits).
    DENSE_MAGIC = b"PDN1"
    DENSE_VERSION = 1
    _DENSE_HDR = struct.Struct("<4sHHQQQQI")

    @property
    def dense_path(self) -> str:
        return self.path + ".dense"

    def _dense_stamp(self) -> tuple[int, int, int]:
        """Restart-stable identity of this fragment's on-disk state:
        (snapshot size, snapshot mtime_ns, op-log size).  The op-log is
        flushed per append, so the size moves with every mutation."""
        try:
            st = os.stat(self.path)
            snap = (st.st_size, st.st_mtime_ns)
        except OSError as e:
            # an ABSENT snapshot (ENOENT) is the deliberate fallback —
            # the fragment has never compacted, stamp (0, 0).  Any
            # other errno is a disk fault: log once + feed the
            # governor, then keep the conservative fallback (a zero
            # stamp can only make the next build go cold, never wrong)
            _storage_health.note_os_error("fragment.stamp", self.path,
                                          e, health=self._health)
            snap = (0, 0)
        return (snap[0], snap[1], self._oplog.size())

    def expand_rows_into(self, row_ids, out: np.ndarray, slots=None, *,
                         sidecar: bool = False,
                         sidecar_submit=None) -> str:
        """Bulk-direct :meth:`plane_rows`: OR ``row_ids[i]``'s packed
        words into ``out[slots[i]]`` (caller passes zeroed slabs),
        writing straight into the destination via the native codec —
        no tmp slab + reorder copy, and the C call releases the GIL so
        builder threads genuinely overlap.  ``plane_rows`` remains the
        pure-Python fallback and oracle.

        With ``sidecar=True``: a fresh ``<path>.dense`` image
        short-cuts the whole expansion (all-bitmap containers — the
        word-aligned memcpy fast path), and a cold expansion covering
        the fragment's full row set writes one for the next restart.
        ``sidecar_submit`` (a ``(path, header, blob)`` callable) defers
        the disk write off the expansion critical path — safe because
        content and stamp are captured together under the fragment
        lock; a mutation racing the deferred write only stale-stamps
        the file, which the next reader rejects.
        Returns ``"warm"`` or ``"cold"`` for cache accounting."""
        row_ids = np.asarray(row_ids, dtype=np.uint64)
        if slots is None:
            slots = np.arange(len(row_ids), dtype=np.uint64)
        else:
            slots = np.asarray(slots, dtype=np.uint64)
        if len(row_ids) > 1 and not (row_ids[1:] >= row_ids[:-1]).all():
            # the native lookup binary-searches row_ids: unsorted input
            # would silently MISS rows, not error
            order = np.argsort(row_ids, kind="stable")
            row_ids, slots = row_ids[order], slots[order]
        with self.lock:
            self._touch_map()
            if sidecar and self._expand_sidecar(row_ids, slots, out):
                return "warm"
            self._flush_pending()
            pend, pend_slots = [], []
            for r, s in zip(row_ids, slots):
                r = int(r)
                if r in self._snap_pending:
                    pend.append(r)
                    pend_slots.append(int(s))
                else:
                    b = self.rows.get(r)
                    if b is not None and b.any():
                        out[int(s)] |= b.words()
            if pend:
                from pilosa_tpu.store import native
                if native.available():
                    order = np.argsort(pend)
                    native.expand_rows_into(
                        self._snap_dir.buf, SHARD_WIDTH,
                        np.array(pend, np.uint64)[order],
                        np.array(pend_slots, np.uint64)[order], out)
                else:
                    for r, s in zip(pend, pend_slots):
                        self._snap_dir.row_words(r, out[s])
            if sidecar:
                self._write_sidecar(row_ids, slots, out, sidecar_submit)
            return "cold"

    def _expand_sidecar(self, row_ids: np.ndarray, slots: np.ndarray,
                        out: np.ndarray) -> bool:
        """OR a valid sidecar image into ``out``; False when absent,
        stale (stamp mismatch) or corrupt (caller cold-builds and
        rewrites).  Caller holds the fragment lock."""
        import mmap as _mmaplib
        try:
            with open(self.dense_path, "rb") as f:
                hdr = f.read(self._DENSE_HDR.size)
                if len(hdr) != self._DENSE_HDR.size:
                    return False
                magic, ver, _, s0, s1, s2, blen, crc = \
                    self._DENSE_HDR.unpack(hdr)
                if (magic != self.DENSE_MAGIC or ver != self.DENSE_VERSION
                        or (s0, s1, s2) != self._dense_stamp()):
                    return False
                if os.fstat(f.fileno()).st_size \
                        != self._DENSE_HDR.size + blen:
                    return False
                mm = _mmaplib.mmap(f.fileno(), 0,
                                   access=_mmaplib.ACCESS_READ)
        except (OSError, ValueError):
            return False
        try:
            blob = memoryview(mm)[self._DENSE_HDR.size:]
            # integrity before use: corruption inside the image can
            # still PARSE (silently wrong bits).  zlib releases the
            # GIL, so the pass overlaps across builder threads.
            if zlib.crc32(blob) != crc:
                return False
            from pilosa_tpu.store import native
            if native.available():
                native.expand_rows_into(blob, SHARD_WIDTH, row_ids,
                                        slots, out)
            else:
                d = roaring.Directory(blob)
                for r, s in zip(row_ids, slots):
                    d.row_words(int(r), out[int(s)])
                del d
            return True
        except ValueError:
            return False  # corrupt image: cold build overwrites it
        finally:
            del blob
            try:
                mm.close()
            except BufferError:  # a stray view: freed on GC instead
                pass

    def _write_sidecar(self, row_ids: np.ndarray, slots: np.ndarray,
                       out: np.ndarray, submit=None) -> None:
        """Persist the just-expanded dense image (best-effort: sidecar
        failure must never fail a plane build).  Only written when the
        expansion covered the fragment's FULL row set — a partial image
        would serve missing rows as absent on the next warm load."""
        live = np.asarray(self.row_ids(), np.uint64)
        if not len(live) or not np.isin(live, row_ids).all():
            return
        stamp = self._dense_stamp()
        try:
            img = out[slots.astype(np.intp)]
            blob = roaring.serialize_dense(img, row_ids)
        except ValueError:
            return  # image exceeds the format limit: stay cold
        hdr = self._DENSE_HDR.pack(
            self.DENSE_MAGIC, self.DENSE_VERSION, 0, *stamp,
            len(blob), zlib.crc32(blob))
        if submit is not None:
            submit(self.dense_path, hdr, blob)
        else:
            self.write_sidecar_file(self.dense_path, hdr, blob,
                                    health=self._health)

    @staticmethod
    def write_sidecar_file(path: str, hdr: bytes, blob: bytes,
                           health=None) -> None:
        """Atomic best-effort sidecar write (also the deferred-writer
        entry point — the blob is immutable bytes, so writing after
        the build moved on is safe).  Failure is DELIBERATELY
        swallowed (a sidecar is a cache; a plane build must never fail
        on it) but no longer silently: log once + feed the disk-health
        governor — an ENOSPC here is the same full disk the oplog seam
        would hit next."""
        tmp = path + ".tmp"
        try:
            with open(tmp, "wb") as f:
                f.write(hdr)
                f.write(blob)
            os.replace(tmp, path)
        except OSError as e:
            _storage_health.note_os_error("sidecar.write", path, e,
                                          health=health)
            try:
                os.unlink(tmp)
            except OSError:
                pass  # tmp may never have been created (ENOENT)

    # Cap on the generation-cached inverted index (sparse bits copied
    # into one flat array): 64M bits = 256MB.  Beyond it a second flat
    # copy of a huge field is not held.
    COLINDEX_MAX_BITS = 64 << 20

    # Building the colindex materializes every row as a host RowBits —
    # fine for 100k rows, pathological for a 5M-row lazy snapshot (GBs
    # of per-object overhead for 20M actual bits).  Row-counts beyond
    # this cap skip the cache regardless of bit count.
    COLINDEX_MAX_ROWS = 100_000

    # With the colindex unavailable, fragments with at most this many
    # rows answer by per-row O(1) word probes; beyond it, one
    # vectorized positions() scan of the blob (O(bits) numpy, zero
    # materialization).  Regime crossover measured on this host
    # (round 3): 64 dense rows × 15M bits — probes 132 ms vs scan
    # 984 ms (7×); 500k sparse rows × 2M bits — scan 213 ms vs
    # probe-loop ≈4.5 s extrapolated (20×, and the scan materializes
    # zero host rows).
    COLINDEX_CONTAINS_MAX_ROWS = 4096

    def rows_containing(self, col: int) -> np.ndarray:
        """Sorted row IDs whose bit ``col`` is set — the ``Rows(column=)``
        membership check (reference: per-row ``row.Includes`` walk in
        ``executor.go#executeRowsShard``).

        One decision, three regimes, chosen from directory metadata
        BEFORE any row materializes (unified in round 3 — the old
        over-cap path materialized every row first):

        1. bits ≤ COLINDEX_MAX_BITS: generation-cached flat (col, row)
           index, vectorized scan per query (the common case);
        2. few rows of many bits: per-row O(1) word probes;
        3. many rows of many bits: one vectorized blob positions()
           scan, no host row objects."""
        with self.lock:
            ids, cards = self.row_cardinalities()
            if (int(cards.sum()) <= self.COLINDEX_MAX_BITS
                    and len(ids) <= self.COLINDEX_MAX_ROWS):
                sp_cols, sp_rows, dense = self._colindex()
                hits = sp_rows[sp_cols == np.uint32(col)]
                w, bit = col >> 5, np.uint32(1 << (col & 31))
                dense_hits = [r for r, words in dense if words[w] & bit]
                out = np.concatenate(
                    [hits, np.array(dense_hits, np.uint64)]) \
                    if dense_hits else hits
                out.sort()
                return out.astype(np.uint64)
            if len(ids) <= self.COLINDEX_CONTAINS_MAX_ROWS:
                return np.array(
                    [int(r) for r in ids if self.row(int(r)).contains(col)],
                    dtype=np.uint64)
            pos = self.positions()  # blob-composed, no materialize
            rows = pos[pos % _SW == np.uint64(col)] // _SW
            rows.sort()
            return rows.astype(np.uint64)

    def _colindex(self):
        """(sparse_cols, sparse_rows, dense_list) cached per generation.
        Only called with total bits pre-checked under the cap."""
        cached = getattr(self, "_colindex_cache", None)
        if cached is not None and cached[0] == self.generation:
            return cached[1]
        self._flush_pending()
        self._materialize_all()
        sp_parts, sp_ids, dense = [], [], []
        for r, b in self.rows.items():
            if not b.any():
                continue
            if b.is_dense:
                dense.append((r, b.words()))
                continue
            sp_parts.append(b.columns())
            sp_ids.append(r)
        if sp_parts:
            sp_cols = np.concatenate(sp_parts)
            sp_rows = np.repeat(
                np.array(sp_ids, np.uint64),
                np.array([len(p) for p in sp_parts]))
        else:
            sp_cols = np.empty(0, np.uint32)
            sp_rows = np.empty(0, np.uint64)
        idx = (sp_cols, sp_rows, dense)
        self._colindex_cache = (self.generation, idx)
        return idx

    # -- mutation -----------------------------------------------------------

    def _write_gate(self) -> None:
        """Refuse mutations BEFORE any in-memory change when the
        storage layer is sick (node read-only on disk-full, or this
        fragment quarantined) — a refusal can never half-apply.  The
        healthy path costs one attribute load and a falsy branch
        (``StorageHealth.gate_active``)."""
        h = self._health
        if h is not None and h.gate_active:
            h.check_write(self.path)

    def set_bit(self, row_id: int, col: int) -> bool:
        return self.set_bits(np.array([row_id], np.uint64),
                             np.array([col], np.uint64)) > 0

    def clear_bit(self, row_id: int, col: int) -> bool:
        return self.clear_bits(np.array([row_id], np.uint64),
                               np.array([col], np.uint64)) > 0

    def set_bits(self, row_ids: np.ndarray, cols: np.ndarray,
                 sync_batch=None) -> int:
        """Bulk set; returns number of newly-set bits (reference:
        ``fragment.bulkImport``, SURVEY.md §4.5).  ``sync_batch`` (an
        :class:`~pilosa_tpu.store.oplog.SyncBatch`) defers the op-log
        fsync to the import batch boundary — one fsync per batch per
        touched fragment, not one per record."""
        self._write_gate()
        positions = (np.asarray(row_ids, np.uint64) * _SW
                     + np.asarray(cols, np.uint64))
        with self.lock:
            changed = self._apply(OP_SET_BITS, 0, positions)
            if changed:
                self._log(OP_SET_BITS, 0, positions,
                          sync_batch=sync_batch)
            return changed

    def clear_bits(self, row_ids: np.ndarray, cols: np.ndarray,
                   sync_batch=None) -> int:
        self._write_gate()
        positions = (np.asarray(row_ids, np.uint64) * _SW
                     + np.asarray(cols, np.uint64))
        with self.lock:
            changed = self._apply(OP_CLEAR_BITS, 0, positions)
            if changed:
                self._log(OP_CLEAR_BITS, 0, positions,
                          sync_batch=sync_batch)
            return changed

    def set_bits_grouped(self, groups: list[tuple[int, np.ndarray]]) -> int:
        """Bulk set with pre-grouped (row_id, cols) — skips the global
        position sort/segmentation when the caller already has per-row
        columns (BSI imports build exactly this shape)."""
        return self._apply_grouped(groups, clear=False)

    def clear_bits_grouped(self, groups: list[tuple[int, np.ndarray]]) -> int:
        return self._apply_grouped(groups, clear=True)

    def _apply_grouped(self, groups, clear: bool) -> int:
        self._write_gate()
        op = OP_CLEAR_BITS if clear else OP_SET_BITS
        with self.lock:
            self._probe_cache = None  # mutates merged truth directly
            self._flush_pending()
            changed = 0
            parts = []
            delta: dict = {}
            for row_id, cols in groups:
                cols = np.asarray(cols, dtype=np.uint32)
                if len(cols) == 0:
                    continue
                self._ensure_row(int(row_id))  # lazy snapshot rows
                if clear:
                    row = self.rows.get(int(row_id))
                    if row is not None:
                        changed += row.remove(cols)
                        if not row.any():
                            del self.rows[int(row_id)]
                else:
                    row = self.rows.get(int(row_id))
                    if row is None:
                        row = self.rows[int(row_id)] = RowBits()
                    changed += row.add(cols)
                words = np.unique(cols >> np.uint32(5))
                prev = delta.get(int(row_id))
                delta[int(row_id)] = (words if prev is None
                                      else np.union1d(prev, words))
                parts.append(np.uint64(row_id) * _SW + cols.astype(np.uint64))
            if changed:
                self.generation += 1
                self._note_delta(delta)
                self._log(op, 0, np.concatenate(parts))
            return changed

    def clear_row(self, row_id: int) -> int:
        """Clear every bit of a row (reference: ``fragment.clearRow``)."""
        self._write_gate()
        with self.lock:
            changed = self._apply(OP_CLEAR_ROW, row_id, None)
            if changed:
                self._log(OP_CLEAR_ROW, row_id, None)
            return changed

    def set_row(self, row_id: int, cols: np.ndarray) -> bool:
        """Replace a row's bits wholesale (reference: ``Store()`` /
        ``fragment.setRow``).  Logged as ONE op-log record carrying the
        row's complete new contents, so a crash mid-call can never replay
        a cleared row without its replacement bits."""
        self._write_gate()
        with self.lock:
            self._flush_pending()     # equality check needs merged truth
            self._ensure_row(row_id)  # no-op check needs snapshot truth
            before = self.rows.get(row_id)
            new = RowBits.from_columns(cols)
            before_cols = before.columns() if before is not None else np.empty(0, np.uint32)
            if np.array_equal(before_cols, new.columns()):
                return False
            positions = np.uint64(row_id) * _SW + new.columns().astype(np.uint64)
            self._apply(OP_SET_ROW, row_id, positions)
            self._log(OP_SET_ROW, row_id, positions)
            return True

    def import_roaring(self, blob: bytes, clear: bool = False,
                       sync_batch=None) -> int:
        """Union (or clear) an already-roaring-encoded bit set — the bulk
        loader fast path (reference: ``API.ImportRoaring``, SURVEY.md §4.5)."""
        self._write_gate()
        positions = roaring.deserialize(blob)
        op = OP_CLEAR_BITS if clear else OP_SET_BITS
        with self.lock:
            changed = self._apply(op, 0, positions)
            if changed:
                self._log(op, 0, positions, sync_batch=sync_batch)
            return changed

    # -- durability ---------------------------------------------------------

    def snapshot(self) -> None:
        """Rewrite the snapshot file and truncate the op-log (reference:
        ``fragment.snapshot``).  Atomic via temp+rename.  Afterwards the
        fragment re-opens the NEW file as its lazy backing and drops the
        overlay — compaction is also the host-memory release point
        (positions() composes from the old blob + overlay without
        materializing, so rows must not be left half-resident)."""
        h = self._health
        if (h is not None and not getattr(self, "_rebuilding", False)
                and h.is_quarantined(self.path)):
            # compacting a QUARANTINED fragment would overwrite the
            # corrupt-but-detectable file with a validly-framed
            # snapshot of whatever partial state memory holds —
            # masking the corruption forever (the registry is
            # in-memory; a restart would open 'healthy').  Keep the
            # evidence; replica repair owns the way out.
            import logging
            logging.getLogger("pilosa_tpu.store").warning(
                "refusing to compact quarantined fragment %s "
                "(would mask corruption as valid data)", self.path)
            return
        from pilosa_tpu.store import syswrap
        with self.lock:
            pre_stamp = self._dense_stamp()  # state the sidecar may match
            # merge the pending tier into rows FIRST: a failed file
            # write below (disk full) must leave merged in-memory
            # truth intact, not drop the pending bits with the blob
            self._flush_pending()
            blob = roaring.serialize(self.positions())
            tmp = self.path + ".tmp"
            os.makedirs(os.path.dirname(self.path), exist_ok=True)
            # r19 frame: versioned header + crc32 of the blob, written
            # through the sys.write/sys.fsync failpoints so chaos
            # schedules cover snapshots exactly like op-logs
            hdr = self._SNAP_HDR.pack(self.SNAP_MAGIC, self.SNAP_VERSION,
                                      0, len(blob), zlib.crc32(blob))
            try:
                with open(tmp, "wb") as f:
                    syswrap.checked_write(f, hdr)
                    syswrap.checked_write(f, blob)
                    f.flush()
                    syswrap.checked_fsync(f)
                os.replace(tmp, self.path)
            except OSError as e:
                try:
                    os.unlink(tmp)
                except OSError:
                    pass
                h = self._health
                if h is not None \
                        and not isinstance(
                            e, _storage_health.StorageFaultError):
                    raise h.write_failed(self.path, e,
                                         site="fragment.snapshot") from e
                raise
            self._drop_snapshot()
            self.rows = {}
            try:
                if os.path.getsize(self.path) > 0:
                    self._open_snapshot()
            except Exception:
                # mmap/fd failure must not leave the fragment EMPTY in
                # memory (a later compaction would persist that empty
                # state over the good file): fall back to eager load
                # from the blob just written
                self._load_positions(roaring.deserialize(blob))
            self._oplog.truncate()
            self.op_n = 0
            # compaction preserves CONTENT, so a sidecar that matched
            # the pre-compaction state stays byte-valid: re-stamp it
            # against the new snapshot+empty-oplog identity instead of
            # discarding it (a clean shutdown compacts every dirty
            # fragment — deleting here would strand every restart cold)
            self._restamp_sidecar(pre_stamp)

    def _restamp_sidecar(self, pre_stamp: tuple[int, int, int]) -> None:
        """After compaction: carry a still-valid sidecar forward to the
        new on-disk identity, drop a stale one.  Caller holds the lock.
        A crash mid-rewrite only tears the header — the stamp then
        mismatches and the next build goes cold (never wrong)."""
        hdr_s = self._DENSE_HDR
        try:
            with open(self.dense_path, "r+b") as f:
                hdr = f.read(hdr_s.size)
                valid = False
                if len(hdr) == hdr_s.size:
                    magic, ver, _, s0, s1, s2, blen, crc = \
                        hdr_s.unpack(hdr)
                    valid = (magic == self.DENSE_MAGIC
                             and ver == self.DENSE_VERSION
                             and (s0, s1, s2) == pre_stamp)
                if valid:
                    f.seek(0)
                    f.write(hdr_s.pack(magic, ver, 0,
                                       *self._dense_stamp(), blen, crc))
                    return
        except OSError as e:
            # ENOENT (no sidecar) is the deliberate no-op; any other
            # errno (unreadable, disk fault) logs once + feeds the
            # governor — the stale-stamp fallback stays safe either
            # way (the next build just goes cold)
            _storage_health.note_os_error("sidecar.restamp",
                                          self.dense_path, e,
                                          health=self._health)
            return
        try:
            os.unlink(self.dense_path)
        except OSError as e:
            _storage_health.note_os_error("sidecar.unlink",
                                          self.dense_path, e,
                                          health=self._health)

    # -- anti-entropy -------------------------------------------------------

    def blocks(self) -> dict[int, int]:
        """Per-block checksums: block = ``row_id // HASH_BLOCK_SIZE``;
        checksum = crc32 over the block's sorted positions (reference:
        ``fragment.Blocks``, SURVEY.md §4.6).

        Generation-cached: decoding every position of a dense fragment
        is ~0.9 s on the bench host (config17 r5 — a no-op AAE round at
        954 fragments cost 14 minutes, recomputed on BOTH ends).  An
        unchanged fragment answers from the cache, so steady-state
        sweeps only pay for fragments that actually mutated."""
        with self.lock:
            cached = getattr(self, "_blocks_cache", None)
            if cached is not None and cached[0] == self.generation:
                return cached[1]
            gen = self.generation
            # one vectorized pass over positions() (snapshot rows decode
            # from the blob — no RowBits materialization, so AAE stays
            # cheap on multi-million-row sparse fragments)
            pos = self.positions()
        out: dict[int, int] = {}
        if len(pos):
            blocks = (pos // _SW
                      // np.uint64(HASH_BLOCK_SIZE)).astype(np.int64)
            uniq, starts = np.unique(blocks, return_index=True)
            bounds = np.append(starts, len(pos))
            data = pos.astype("<u8")
            for i, blk in enumerate(uniq):
                out[int(blk)] = zlib.crc32(
                    data[bounds[i]:bounds[i + 1]].tobytes())
        with self.lock:
            if self.generation == gen:
                self._blocks_cache = (gen, out)
        return out

    def block_positions(self, block: int) -> np.ndarray:
        """All positions of one checksum block (for AAE data exchange)."""
        lo = np.uint64(block * HASH_BLOCK_SIZE) * _SW
        hi = np.uint64((block + 1) * HASH_BLOCK_SIZE) * _SW
        with self.lock:
            pos = self.positions()
        return pos[(pos >= lo) & (pos < hi)]

    def merge_positions(self, positions: np.ndarray) -> int:
        """Union positions in (AAE repair receive path)."""
        self._write_gate()
        with self.lock:
            changed = self._apply(OP_SET_BITS, 0, positions)
            if changed:
                self._log(OP_SET_BITS, 0, positions)
            return changed

    # -- internal -----------------------------------------------------------

    def _note_delta(self, rows_words: dict) -> None:
        """Journal one mutation's touched cells for incremental device
        updates: {row: unique word idxs | None = whole row}."""
        cells = sum(64 if v is None else len(v)
                    for v in rows_words.values())
        if cells > self.RECENT_CELL_CAP:
            self._recent.clear()
            self._recent.append((self.generation, None))  # gap marker
        else:
            self._recent.append((self.generation, rows_words))

    def _note_delta_positions(self, positions: np.ndarray) -> None:
        """Positions-form journal entry (pending-tier writes): the
        {row: words} dict is derived lazily in changed_cells_since —
        per-row dict assembly at write time cost more than the whole
        pending append."""
        if len(positions) > self.RECENT_CELL_CAP:
            self._recent.clear()
            self._recent.append((self.generation, None))
        else:
            self._recent.append((self.generation, ("pos", positions)))

    def changed_cells_since(self, gen: int):
        """Merged {row: word idx set | None} covering generations
        (gen, current], or None if the journal has gaps (caller must
        rebuild).  ``{}`` when nothing changed."""
        with self.lock:
            if gen == self.generation:
                return {}
            if gen > self.generation:
                # cached gens AHEAD of this fragment: it was replaced
                # (e.g. field dropped+recreated) — force a rebuild
                return None
            entries = [(g, rw) for g, rw in self._recent if g > gen]
            if [g for g, _ in entries] != list(range(gen + 1,
                                                     self.generation + 1)):
                return None
            merged: dict = {}
            for _, rw in entries:
                if rw is None:
                    return None  # oversized op: rebuild
                if isinstance(rw, tuple):  # ("pos", positions) form
                    arr = rw[1]
                    rws = (arr // _SW).tolist()
                    wds = ((arr % _SW) >> np.uint64(5)).tolist()
                    for r, w in zip(rws, wds):
                        if merged.get(r, 0) is None:
                            continue
                        merged.setdefault(r, set()).add(int(w))
                    continue
                for r, words in rw.items():
                    if words is None or merged.get(r, 0) is None:
                        merged[r] = None
                    else:
                        merged.setdefault(r, set()).update(
                            int(w) for w in words)
            return merged

    def _apply(self, op: int, aux: int, positions: np.ndarray | None) -> int:
        """Apply an op to memory; returns bits changed.  Shared by the
        mutation API and op-log replay."""
        changed = 0
        delta: dict = {}
        if op == OP_SET_BITS and positions is not None \
                and len(positions) < self.PEND_FLUSH_N:
            # pending-tier fast path: probe + append, no per-row
            # unions.  Batches at/over the flush size skip it — they
            # are already amortized, and staging them through the
            # pending tier costs an extra probe+insert pass (measured
            # 2× on ImportRoaring blobs)
            if not len(positions):
                return 0
            self._check_rows(positions)
            positions = np.unique(np.asarray(positions, np.uint64))
            new = self._pend_add(positions)
            if new is not None:
                if len(new):
                    self.generation += 1
                    self._note_delta_positions(new)
                return len(new)
            # probe cache over cap: classic per-row path below
        # every classic path below mutates merged truth: a probe cache
        # built earlier is stale the moment rows change — even when
        # pending is empty and the flush below is a no-op (a stale
        # cache would silently drop re-sets of cleared bits)
        self._probe_cache = None
        if len(self._pend_pos):
            # row-level ops, clears, and big batches need merged
            # per-row truth
            self._flush_pending()
        if op == OP_CLEAR_ROW:
            if aux in self._snap_pending:
                # whole row drops: count from the directory, never expand
                changed = self._snap_dir.row_cardinality(aux)
                self._snap_pending.discard(aux)
            row = self.rows.get(aux)
            if row is not None and row.any():
                changed += row.cardinality
            self.rows.pop(aux, None)
            delta[aux] = None
        elif op == OP_SET_ROW:
            if aux in self._snap_pending:
                changed += self._snap_dir.row_cardinality(aux)
                self._snap_pending.discard(aux)
            old = self.rows.pop(aux, None)
            if old is not None and old.any():
                changed += old.cardinality
            delta[aux] = None
            if positions is not None and len(positions):
                self._check_rows(positions)
                for r, chunk in _split_by_row(positions):
                    self._snap_pending.discard(r)
                    row = self.rows[r] = RowBits()
                    changed += row.add(chunk)
                    delta[r] = None
        elif op in (OP_SET_BITS, OP_CLEAR_BITS):
            assert positions is not None
            self._check_rows(positions)
            # ONE global sort+dedup; per-row chunks are then sorted-
            # unique, so row.add/remove skip their per-chunk np.unique
            # (a 100k-pair import touching every shard makes ~30 tiny
            # per-row calls per fragment — per-call work dominates)
            positions = np.unique(np.asarray(positions, np.uint64))
            for r, chunk in _split_by_row(positions, presorted=True):
                self._ensure_row(r)
                if op == OP_SET_BITS:
                    row = self.rows.get(r)
                    if row is None:
                        row = self.rows[r] = RowBits()
                    changed += row.add(chunk, presorted=True)
                else:
                    row = self.rows.get(r)
                    if row is not None:
                        changed += row.remove(chunk, presorted=True)
                        if not row.any():
                            del self.rows[r]
                # dedup without a re-sort (chunk is sorted): delta
                # cells count against RECENT_CELL_CAP, and one entry
                # per POSITION would inflate a dense-clustered batch
                # ~32x, tripping the journal-gap full-rebuild path
                delta[r] = _dedup_sorted(chunk >> np.uint32(5))
        else:
            raise ValueError(f"fragment: unknown op {op}")
        if changed:
            self.generation += 1
            self._note_delta(delta)
        return changed

    def _check_rows(self, positions: np.ndarray) -> None:
        if len(positions) and int(positions.max() // _SW) >= (1 << 40):
            raise ValueError("row id out of range (>= 2^40)")

    def _log(self, op: int, aux: int, positions: np.ndarray | None,
             sync_batch=None) -> None:
        try:
            self._oplog.append(op, aux, positions, sync_batch=sync_batch)
        except OSError as e:
            # disk-fault governor seam (r19): classify by errno —
            # ENOSPC flips the node read-only (this op is NOT acked;
            # memory ran ahead of disk, which the at-least-once
            # contract absorbs exactly like a torn write), repeated
            # EIO quarantines just this fragment
            h = self._health
            if h is not None \
                    and not isinstance(e, _storage_health.StorageFaultError):
                raise h.write_failed(self._oplog.path, e,
                                     site="oplog.append") from e
            raise
        h = self._health
        if h is not None:
            h.note_write_success(self.path)
        self.op_n += 1
        if self.op_n > self.max_op_n:
            if self._snapshot_submit is not None:
                self._snapshot_submit(self)  # background compaction
            else:
                self.snapshot()

    def rebuild_from_positions(self, positions: np.ndarray) -> None:
        """Replace this fragment's ENTIRE state with ``positions`` —
        the quarantine-repair receive path (r19): the local copy is
        untrustworthy (corrupt snapshot/op-log), so a healthy replica's
        full position set becomes the new truth.  Discards the old
        snapshot, op-log and overlay wholesale, loads the new bits,
        and compacts them into a fresh framed snapshot (verified by
        the caller before un-quarantine).  Deliberately bypasses the
        write gate — this IS the path out of quarantine."""
        with self.lock:
            self._drop_snapshot()
            self.rows = {}
            self._pend_pos = np.empty(0, np.uint64)
            self._probe_cache = None
            self._oplog.truncate()
            self.op_n = 0
            self._load_positions(positions)
            self.generation += 1
            # device-plane journals cannot describe a wholesale
            # replacement: force the rebuild path
            self._recent.clear()
            self._recent.append((self.generation, None))
            try:
                os.unlink(self.dense_path)  # sidecar captured old bytes
            except OSError:
                pass
            # the one compaction allowed while still quarantined:
            # this snapshot IS the replacement of the corrupt bytes
            self._rebuilding = True
            try:
                self.snapshot()
            finally:
                self._rebuilding = False

    def maybe_snapshot(self) -> None:
        """Background-queue entry point: compact only if still OVER the
        threshold — a dedup race can enqueue a fragment twice, and the
        duplicate must not re-serialize a huge fragment for one op."""
        with self.lock:
            if self._open and self.op_n > self.max_op_n:
                self.snapshot()

    def _load_positions(self, positions: np.ndarray) -> None:
        for r, cols in _split_by_row(positions):
            self.rows[r] = RowBits.from_columns(cols)


def _dedup_sorted(a: np.ndarray) -> np.ndarray:
    """Unique values of an already-sorted array, no re-sort."""
    if len(a) < 2:
        return a
    return a[np.concatenate(([True], a[1:] != a[:-1]))]


def _split_by_row(positions: np.ndarray,
                  presorted: bool = False) -> list[tuple[int, np.ndarray]]:
    """Split positions (any order, duplicates OK) into per-row column
    chunks: [(row_id, uint32 cols), ...].  The single place that owns the
    position→(row, col) segmentation invariant."""
    positions = np.asarray(positions, dtype=np.uint64)
    if len(positions) == 0:
        return []
    if not presorted:
        positions = np.sort(positions)
    row_ids = positions // _SW
    cols = (positions % _SW).astype(np.uint32)
    uniq, starts = np.unique(row_ids, return_index=True)
    bounds = np.append(starts, len(positions))
    return [(int(uniq[i]), cols[bounds[i]:bounds[i + 1]])
            for i in range(len(uniq))]
