"""Fragment: one (field, view, shard) storage unit.

Reference: ``fragment.go`` (SURVEY.md §3.1) — bits of all rows of one view
of one shard in a single roaring bitmap keyed by
``rowID * ShardWidth + column``, persisted as an mmap'd snapshot plus an
op-log, compacted when ``opN > MaxOpN``.

This rebuild keeps the same on-disk contract (roaring snapshot file +
CRC-framed op-log, same position encoding) but host memory is per-row
:class:`~pilosa_tpu.store.row.RowBits` (sparse/dense auto-converting) —
the natural shape for assembling dense device planes.  The reference's
per-fragment TopN rank/LRU cache (``cache.go``) is intentionally absent:
on TPU, TopN recounts every row at HBM bandwidth (``engine.kernels.row_counts``),
so there is no cache to maintain or invalidate.

Concurrency: one RLock per fragment (reference: per-fragment
``sync.RWMutex``); mutators and plane assembly take it.
"""

from __future__ import annotations

import os
import threading
import zlib

import numpy as np

from pilosa_tpu.engine.words import SHARD_WIDTH
from pilosa_tpu.store import roaring
from pilosa_tpu.store.oplog import (OP_CLEAR_BITS, OP_CLEAR_ROW, OP_SET_BITS,
                                    OP_SET_ROW, OpLog)
from pilosa_tpu.store.row import RowBits

# Reference default: compact the op-log into a snapshot after ~2000 ops.
MAX_OP_N = 2000

# Rows per anti-entropy checksum block (reference: HashBlockSize = 100).
HASH_BLOCK_SIZE = 100

_SW = np.uint64(SHARD_WIDTH)


class Fragment:
    """Bits of one (field, view, shard)."""

    def __init__(self, path: str, shard: int, *, max_op_n: int = MAX_OP_N,
                 fsync: bool = False):
        self.path = path                      # snapshot file
        self.shard = shard
        self.max_op_n = max_op_n
        self.rows: dict[int, RowBits] = {}
        self.op_n = 0
        self.generation = 0                   # bumped per mutation; device
                                              # plane caches key on this
        self.lock = threading.RLock()
        self._oplog = OpLog(path + ".oplog", fsync=fsync)
        self._open = False

    # -- lifecycle ----------------------------------------------------------

    def open(self) -> "Fragment":
        with self.lock:
            if self._open:
                return self
            if os.path.exists(self.path):
                with open(self.path, "rb") as f:
                    self._load_positions(roaring.deserialize(f.read()))
            for op, aux, positions in self._oplog.replay():
                self._apply(op, aux, positions)
                self.op_n += 1
            self._open = True
        return self

    def close(self) -> None:
        with self.lock:
            if self.op_n > 0:
                self.snapshot()
            self._oplog.close()
            self._open = False

    # -- reads --------------------------------------------------------------

    def row(self, row_id: int) -> RowBits:
        with self.lock:
            return self.rows.get(row_id) or RowBits()

    def row_ids(self) -> list[int]:
        with self.lock:
            return sorted(r for r, b in self.rows.items() if b.any())

    def max_row_id(self) -> int:
        ids = self.row_ids()
        return ids[-1] if ids else 0

    def cardinality(self) -> int:
        with self.lock:
            return sum(b.cardinality for b in self.rows.values())

    def positions(self) -> np.ndarray:
        """All set bits as sorted uint64 ``row*ShardWidth + col``."""
        with self.lock:
            parts = [
                np.uint64(r) * _SW + b.columns().astype(np.uint64)
                for r, b in sorted(self.rows.items())
                if b.any()
            ]
        if not parts:
            return np.empty(0, dtype=np.uint64)
        return np.concatenate(parts)

    # -- mutation -----------------------------------------------------------

    def set_bit(self, row_id: int, col: int) -> bool:
        return self.set_bits(np.array([row_id], np.uint64),
                             np.array([col], np.uint64)) > 0

    def clear_bit(self, row_id: int, col: int) -> bool:
        return self.clear_bits(np.array([row_id], np.uint64),
                               np.array([col], np.uint64)) > 0

    def set_bits(self, row_ids: np.ndarray, cols: np.ndarray) -> int:
        """Bulk set; returns number of newly-set bits (reference:
        ``fragment.bulkImport``, SURVEY.md §4.5)."""
        positions = (np.asarray(row_ids, np.uint64) * _SW
                     + np.asarray(cols, np.uint64))
        with self.lock:
            changed = self._apply(OP_SET_BITS, 0, positions)
            if changed:
                self._log(OP_SET_BITS, 0, positions)
            return changed

    def clear_bits(self, row_ids: np.ndarray, cols: np.ndarray) -> int:
        positions = (np.asarray(row_ids, np.uint64) * _SW
                     + np.asarray(cols, np.uint64))
        with self.lock:
            changed = self._apply(OP_CLEAR_BITS, 0, positions)
            if changed:
                self._log(OP_CLEAR_BITS, 0, positions)
            return changed

    def set_bits_grouped(self, groups: list[tuple[int, np.ndarray]]) -> int:
        """Bulk set with pre-grouped (row_id, cols) — skips the global
        position sort/segmentation when the caller already has per-row
        columns (BSI imports build exactly this shape)."""
        return self._apply_grouped(groups, clear=False)

    def clear_bits_grouped(self, groups: list[tuple[int, np.ndarray]]) -> int:
        return self._apply_grouped(groups, clear=True)

    def _apply_grouped(self, groups, clear: bool) -> int:
        op = OP_CLEAR_BITS if clear else OP_SET_BITS
        with self.lock:
            changed = 0
            parts = []
            for row_id, cols in groups:
                cols = np.asarray(cols, dtype=np.uint32)
                if len(cols) == 0:
                    continue
                if clear:
                    row = self.rows.get(int(row_id))
                    if row is not None:
                        changed += row.remove(cols)
                        if not row.any():
                            del self.rows[int(row_id)]
                else:
                    row = self.rows.get(int(row_id))
                    if row is None:
                        row = self.rows[int(row_id)] = RowBits()
                    changed += row.add(cols)
                parts.append(np.uint64(row_id) * _SW + cols.astype(np.uint64))
            if changed:
                self.generation += 1
                self._log(op, 0, np.concatenate(parts))
            return changed

    def clear_row(self, row_id: int) -> int:
        """Clear every bit of a row (reference: ``fragment.clearRow``)."""
        with self.lock:
            changed = self._apply(OP_CLEAR_ROW, row_id, None)
            if changed:
                self._log(OP_CLEAR_ROW, row_id, None)
            return changed

    def set_row(self, row_id: int, cols: np.ndarray) -> bool:
        """Replace a row's bits wholesale (reference: ``Store()`` /
        ``fragment.setRow``).  Logged as ONE op-log record carrying the
        row's complete new contents, so a crash mid-call can never replay
        a cleared row without its replacement bits."""
        with self.lock:
            before = self.rows.get(row_id)
            new = RowBits.from_columns(cols)
            before_cols = before.columns() if before is not None else np.empty(0, np.uint32)
            if np.array_equal(before_cols, new.columns()):
                return False
            positions = np.uint64(row_id) * _SW + new.columns().astype(np.uint64)
            self._apply(OP_SET_ROW, row_id, positions)
            self._log(OP_SET_ROW, row_id, positions)
            return True

    def import_roaring(self, blob: bytes, clear: bool = False) -> int:
        """Union (or clear) an already-roaring-encoded bit set — the bulk
        loader fast path (reference: ``API.ImportRoaring``, SURVEY.md §4.5)."""
        positions = roaring.deserialize(blob)
        op = OP_CLEAR_BITS if clear else OP_SET_BITS
        with self.lock:
            changed = self._apply(op, 0, positions)
            if changed:
                self._log(op, 0, positions)
            return changed

    # -- durability ---------------------------------------------------------

    def snapshot(self) -> None:
        """Rewrite the snapshot file from memory and truncate the op-log
        (reference: ``fragment.snapshot``).  Atomic via temp+rename."""
        with self.lock:
            blob = roaring.serialize(self.positions())
            tmp = self.path + ".tmp"
            os.makedirs(os.path.dirname(self.path), exist_ok=True)
            with open(tmp, "wb") as f:
                f.write(blob)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self.path)
            self._oplog.truncate()
            self.op_n = 0

    # -- anti-entropy -------------------------------------------------------

    def blocks(self) -> dict[int, int]:
        """Per-block checksums: block = ``row_id // HASH_BLOCK_SIZE``;
        checksum = crc32 over the block's sorted positions (reference:
        ``fragment.Blocks``, SURVEY.md §4.6)."""
        out: dict[int, int] = {}
        with self.lock:
            by_block: dict[int, list[tuple[int, RowBits]]] = {}
            for r, b in self.rows.items():
                if b.any():
                    by_block.setdefault(r // HASH_BLOCK_SIZE, []).append((r, b))
            for blk, members in by_block.items():
                crc = 0
                for r, b in sorted(members):
                    pos = np.uint64(r) * _SW + b.columns().astype(np.uint64)
                    crc = zlib.crc32(pos.astype("<u8").tobytes(), crc)
                out[blk] = crc
        return out

    def block_positions(self, block: int) -> np.ndarray:
        """All positions of one checksum block (for AAE data exchange)."""
        lo, hi = block * HASH_BLOCK_SIZE, (block + 1) * HASH_BLOCK_SIZE
        with self.lock:
            parts = [
                np.uint64(r) * _SW + b.columns().astype(np.uint64)
                for r, b in sorted(self.rows.items())
                if lo <= r < hi and b.any()
            ]
        if not parts:
            return np.empty(0, dtype=np.uint64)
        return np.concatenate(parts)

    def merge_positions(self, positions: np.ndarray) -> int:
        """Union positions in (AAE repair receive path)."""
        with self.lock:
            changed = self._apply(OP_SET_BITS, 0, positions)
            if changed:
                self._log(OP_SET_BITS, 0, positions)
            return changed

    # -- internal -----------------------------------------------------------

    def _apply(self, op: int, aux: int, positions: np.ndarray | None) -> int:
        """Apply an op to memory; returns bits changed.  Shared by the
        mutation API and op-log replay."""
        changed = 0
        if op == OP_CLEAR_ROW:
            row = self.rows.get(aux)
            if row is not None and row.any():
                changed = row.cardinality
                del self.rows[aux]
        elif op == OP_SET_ROW:
            old = self.rows.pop(aux, None)
            if old is not None and old.any():
                changed += old.cardinality
            if positions is not None and len(positions):
                self._check_rows(positions)
                for r, chunk in _split_by_row(positions):
                    row = self.rows[r] = RowBits()
                    changed += row.add(chunk)
        elif op in (OP_SET_BITS, OP_CLEAR_BITS):
            assert positions is not None
            self._check_rows(positions)
            for r, chunk in _split_by_row(positions):
                if op == OP_SET_BITS:
                    row = self.rows.get(r)
                    if row is None:
                        row = self.rows[r] = RowBits()
                    changed += row.add(chunk)
                else:
                    row = self.rows.get(r)
                    if row is not None:
                        changed += row.remove(chunk)
                        if not row.any():
                            del self.rows[r]
        else:
            raise ValueError(f"fragment: unknown op {op}")
        if changed:
            self.generation += 1
        return changed

    def _check_rows(self, positions: np.ndarray) -> None:
        if len(positions) and int(positions.max() // _SW) >= (1 << 40):
            raise ValueError("row id out of range (>= 2^40)")

    def _log(self, op: int, aux: int, positions: np.ndarray | None) -> None:
        self._oplog.append(op, aux, positions)
        self.op_n += 1
        if self.op_n > self.max_op_n:
            self.snapshot()

    def _load_positions(self, positions: np.ndarray) -> None:
        for r, cols in _split_by_row(positions):
            self.rows[r] = RowBits.from_columns(cols)


def _split_by_row(positions: np.ndarray) -> list[tuple[int, np.ndarray]]:
    """Split positions (any order, duplicates OK) into per-row column
    chunks: [(row_id, uint32 cols), ...].  The single place that owns the
    position→(row, col) segmentation invariant."""
    positions = np.asarray(positions, dtype=np.uint64)
    if len(positions) == 0:
        return []
    positions = np.sort(positions)
    row_ids = positions // _SW
    cols = (positions % _SW).astype(np.uint32)
    uniq, starts = np.unique(row_ids, return_index=True)
    bounds = np.append(starts, len(positions))
    return [(int(uniq[i]), cols[bounds[i]:bounds[i + 1]])
            for i in range(len(uniq))]
