"""Background storage scrubber (r19): low-priority re-verification of
every on-disk checksum, feeding the quarantine + repair pipeline.

The anti-entropy loop (cluster/cluster.py ``sync_once``) keeps
*replicas* honest with per-block checksums; this module keeps a single
node's *disk* honest.  A single-flight walker re-reads, at a
configurable byte-rate budget, every durable artifact that carries a
checksum:

- fragment **snapshots** (the r19 ``PSF1`` frame CRC; legacy unframed
  snapshots are parse-verified instead — they predate the checksum),
- fragment **op-logs** (CRC-framed records; a bad record mid-file is
  corruption — a live node's log is always a clean record sequence,
  because boot replay truncates torn tails and failed appends truncate
  their own tear),
- dense **sidecars** (header CRC; corrupt = cache, so it is unlinked
  and counted, never quarantined — the next build goes cold),
- **hint logs** (CRC-framed; corruption is counted and logged loudly —
  recovery-by-clean-prefix happens at the HintLog layer).

A corrupt snapshot or op-log QUARANTINES the fragment via
:class:`~pilosa_tpu.store.health.StorageHealth` and hands the entry to
``on_corrupt`` (in cluster mode: replica repair through the AAE data
path, re-verified here before un-quarantine).

Knobs: ``scrub_interval_seconds`` (pause between passes) and
``scrub_bytes_per_second`` (the I/O budget; ``0`` disables the
scrubber entirely — the pre-r19 contract, no thread).  Progress rides
the ``storageHealth.scrub`` block on ``/status`` and
``storage_scrub_bytes_total``.
"""

from __future__ import annotations

import os
import threading
import time
import zlib

from pilosa_tpu.store import roaring
from pilosa_tpu.store.oplog import _HEADER as _OPLOG_HEADER
from pilosa_tpu.store.oplog import clean_prefix_end

# paced-read chunk: the byte budget is enforced BETWEEN chunks, so one
# huge file cannot blow scrub_bytes_per_second in a single burst
_READ_CHUNK = 4 << 20


def _read_paced(path: str, pace=None) -> bytes:
    """Read a whole file in budget-paced chunks (``pace(nbytes)`` is
    the scrubber's token bucket; None = unpaced, the repair re-verify
    path)."""
    parts = []
    with open(path, "rb") as f:
        while True:
            chunk = f.read(_READ_CHUNK)
            if not chunk:
                break
            parts.append(chunk)
            if pace is not None:
                pace(len(chunk))
    return b"".join(parts)


# -- per-file verifiers (shared by the scrub pass and repair re-verify) -------


def verify_snapshot_file(path: str, pace=None) -> tuple[str | None, int]:
    """(problem or None, bytes read).  Framed snapshots verify length
    + CRC; legacy unframed ones parse-verify (they carry no checksum —
    a parse error is the only corruption signal they can give)."""
    from pilosa_tpu.store.fragment import Fragment
    try:
        buf = _read_paced(path, pace)
    except FileNotFoundError:
        return None, 0
    except OSError as e:
        return f"unreadable: {e}", 0
    if not buf:
        return None, 0
    hdr_s = Fragment._SNAP_HDR
    if buf[:4] == Fragment.SNAP_MAGIC:
        if len(buf) < hdr_s.size:
            return "truncated frame header", len(buf)
        _m, ver, _r, blen, crc = hdr_s.unpack_from(buf)
        blob = memoryview(buf)[hdr_s.size:]
        if ver != Fragment.SNAP_VERSION:
            return f"unknown frame version {ver}", len(buf)
        if len(blob) != blen:
            return (f"length mismatch: header says {blen}, "
                    f"file has {len(blob)}", len(buf))
        if zlib.crc32(blob) != crc:
            return "crc mismatch", len(buf)
        return None, len(buf)
    # legacy (pre-r19) snapshot: no checksum — full parse is the check
    try:
        roaring.deserialize(buf)
    except Exception as e:  # noqa: BLE001 — any parse failure = corrupt
        return f"legacy snapshot unparsable: {e}", len(buf)
    return None, len(buf)


def verify_oplog_file(path: str, pace=None) -> tuple[str | None, int]:
    """A clean op-log is a whole-record prefix covering the entire
    file: boot replay truncates crash tears and a failed append
    truncates its own, so on a settled file any mid-file CRC/frame
    mismatch is byte corruption, not an in-flight write.
    (:func:`verify_fragment` detects a concurrent append via the
    before/after stamp and withholds the verdict.)"""
    try:
        buf = _read_paced(path, pace)
    except FileNotFoundError:
        return None, 0
    except OSError as e:
        return f"unreadable: {e}", 0
    pos = clean_prefix_end(buf, _OPLOG_HEADER)
    if pos < len(buf):
        return (f"corrupt record at byte {pos} "
                f"({len(buf) - pos} trailing bytes)", len(buf))
    return None, len(buf)


def verify_sidecar_file(path: str, pace=None) -> tuple[str | None, int]:
    """Dense-sidecar image CRC (header-declared).  A stamp mismatch is
    NOT corruption (any write stales the stamp by design); only a
    byte-level CRC/length failure reports."""
    from pilosa_tpu.store.fragment import Fragment
    hdr_s = Fragment._DENSE_HDR
    try:
        buf = _read_paced(path, pace)
    except FileNotFoundError:
        return None, 0
    except OSError as e:
        return f"unreadable: {e}", 0
    if len(buf) < hdr_s.size:
        return "truncated header", len(buf)
    magic, ver, _, _s0, _s1, _s2, blen, crc = hdr_s.unpack_from(buf)
    if magic != Fragment.DENSE_MAGIC or ver != Fragment.DENSE_VERSION:
        return "bad magic/version", len(buf)
    blob = memoryview(buf)[hdr_s.size:]
    if len(blob) != blen:
        return f"length mismatch ({len(blob)} != {blen})", len(buf)
    if zlib.crc32(blob) != crc:
        return "crc mismatch", len(buf)
    return None, len(buf)


def verify_hintlog_file(path: str, pace=None) -> tuple[str | None, int]:
    """Hint-log frame scan (same rule as the op-log: a live log is a
    whole-record file — HintLog truncates tears at recovery AND at
    failed appends)."""
    # the authoritative frame layout lives with the hint log itself
    # (deferred import: store must not import cluster at module load)
    from pilosa_tpu.cluster.hints import _FRAME
    try:
        buf = _read_paced(path, pace)
    except FileNotFoundError:
        return None, 0
    except OSError as e:
        return f"unreadable: {e}", 0
    pos = clean_prefix_end(buf, _FRAME)
    if pos < len(buf):
        return (f"corrupt record at byte {pos} "
                f"({len(buf) - pos} trailing bytes)", len(buf))
    return None, len(buf)


def _frag_stamp(frag) -> tuple:
    """(snapshot size, snapshot mtime_ns, op-log size): changes with
    every compaction and every append — the settledness witness."""
    try:
        st = os.stat(frag.path)
        snap = (st.st_size, st.st_mtime_ns)
    except OSError:
        snap = (0, 0)
    try:
        osz = os.path.getsize(frag._oplog.path)
    except OSError:
        osz = 0
    return (snap[0], snap[1], osz)


def verify_fragment(frag, pace=None) -> tuple[dict[str, str] | None, int]:
    """Verify one fragment's snapshot + op-log WITHOUT its lock (the
    scrub must never stall serving behind a multi-second file read):
    the on-disk stamp is captured before and after, and a mismatch —
    a compaction or append raced the scan, so a mid-file 'tear' may
    just be an in-flight write — withholds the verdict entirely
    (returns ``(None, bytes)``; the next pass, or the repair retry,
    re-scans a settled image).  ``({}, bytes)`` = verified clean."""
    before = _frag_stamp(frag)
    try:
        snap_p, snap_b = verify_snapshot_file(frag.path, pace)
        op_p, op_b = verify_oplog_file(frag._oplog.path, pace)
    except Exception:  # noqa: BLE001 — unreadable mid-swap: no verdict
        return None, 0
    if _frag_stamp(frag) != before:
        return None, snap_b + op_b
    problems: dict[str, str] = {}
    if snap_p:
        problems["snapshot"] = snap_p
    if op_p:
        problems["oplog"] = op_p
    return problems, snap_b + op_b


class Scrubber:
    """Single-flight background walker re-verifying every on-disk
    checksum at ``bytes_per_second``; corrupt fragments quarantine and
    flow to ``on_corrupt`` (the cluster's replica-repair hook)."""

    def __init__(self, holder, *, interval: float = 600.0,
                 bytes_per_second: int = 32 << 20, stats=None,
                 logger=None, on_corrupt=None):
        from pilosa_tpu.obs import NopStats, get_logger
        self.holder = holder
        self.health = holder.storage_health
        self.interval = float(interval)
        self.bytes_per_second = int(bytes_per_second)
        self.stats = stats or NopStats()
        self.logger = logger or get_logger("pilosa_tpu.store")
        # on_corrupt(entry) — called once per quarantined entry per
        # pass (fresh detections AND still-pending older ones, so a
        # failed repair retries every pass)
        self.on_corrupt = on_corrupt
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._flight = threading.Lock()
        # progress (read by the /status storageHealth.scrub block)
        self._passes = 0
        self._bytes_total = 0
        self._corruptions = 0
        self._last_pass_seconds = 0.0
        self._last_pass_at = 0.0
        self._pace_t0 = 0.0
        self._pace_bytes = 0

    @property
    def enabled(self) -> bool:
        """False restores the pre-r19 contract byte-for-byte: no
        scrubber thread, no re-verification, no repair hook."""
        return self.bytes_per_second > 0 and self.interval > 0

    def start(self) -> "Scrubber":
        if self.enabled and self._thread is None:
            self._thread = threading.Thread(
                target=self._loop, name="pilosa-scrub", daemon=True)
            self._thread.start()
        return self

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def _loop(self) -> None:
        while not self._stop.wait(self.interval):
            try:
                self.run_once()
            except Exception as e:  # noqa: BLE001 — scrub must not die
                self.logger.warning("scrub pass failed: %s", e)

    # -- one pass -------------------------------------------------------------

    def _pace(self, nbytes: int) -> None:
        """Token-bucket byte budget: sleep so the pass's cumulative
        read rate stays at/under ``bytes_per_second`` — scrubbing is
        strictly lower priority than serving I/O."""
        if self.bytes_per_second <= 0 or nbytes <= 0:
            return
        self._pace_bytes += nbytes
        ahead = (self._pace_bytes / self.bytes_per_second
                 - (time.monotonic() - self._pace_t0))
        if ahead > 0:
            self._stop.wait(min(ahead, 1.0))

    def run_once(self) -> dict:
        """One full verification pass (single-flight; concurrent calls
        return without scanning).  Returns the pass summary."""
        if not self._flight.acquire(blocking=False):
            return {"skipped": "pass already running"}
        try:
            return self._run_once_locked()
        finally:
            self._flight.release()

    def _run_once_locked(self) -> dict:
        t0 = time.monotonic()
        self._pace_t0 = t0
        self._pace_bytes = 0
        scanned = corrupt = files = 0
        health = self.health
        for frag in self._fragments():
            if self._stop.is_set():
                break
            if health.is_quarantined(frag.path):
                continue  # repair owns it; re-verify happens there
            problems, nbytes = verify_fragment(frag, pace=self._pace)
            scanned += nbytes
            files += 2
            if problems is None:
                continue  # raced a write/compaction: next pass retries
            for artifact, problem in problems.items():
                corrupt += 1
                health.quarantine(frag.path, artifact, problem)
                if artifact == "snapshot":
                    # drop the live mmap/heap refs too: without this a
                    # SINGLE-NODE deployment (no replica routing, no
                    # internal-query gate) would keep lazily expanding
                    # rows from the corrupt blob — a loud quarantined
                    # empty beats silently-wrong bits (the same
                    # contract _mark_corrupt applies at open/demote)
                    frag.poison_snapshot()
            # sidecar: a cache, never quarantined — corrupt unlinks so
            # the next plane build goes cold instead of wrong (the
            # loader's own CRC would catch it too; scrubbing surfaces
            # it before a restart does)
            side_p, side_b = verify_sidecar_file(frag.dense_path,
                                                 pace=self._pace)
            scanned += side_b
            if side_b:
                files += 1
            if side_p:
                corrupt += 1
                self.stats.count("storage_corruption_detected_total", 1,
                                 kind="sidecar")
                self.logger.warning(
                    "scrub: corrupt dense sidecar %s (%s) — unlinked, "
                    "next plane build goes cold",
                    frag.dense_path, side_p)
                try:
                    os.remove(frag.dense_path)
                except OSError:
                    pass
        hints_dir = os.path.join(self.holder.path, "_hints")
        if os.path.isdir(hints_dir):
            for name in sorted(os.listdir(hints_dir)):
                if not name.endswith(".hints"):
                    continue
                p = os.path.join(hints_dir, name)
                try:
                    before = os.path.getsize(p)
                except OSError:
                    continue
                problem, nbytes = verify_hintlog_file(
                    p, pace=self._pace)
                scanned += nbytes
                files += 1
                try:
                    settled = os.path.getsize(p) == before
                except OSError:
                    settled = False
                if problem and not settled:
                    # raced a live append or an ack-compaction rename:
                    # a half-flushed tail is not corruption — withhold
                    # the verdict, the next pass re-scans settled bytes
                    # (the same stamp rule verify_fragment applies)
                    continue
                if problem:
                    corrupt += 1
                    self.stats.count(
                        "storage_corruption_detected_total", 1,
                        kind="hintlog")
                    self.logger.error(
                        "scrub: corrupt hint log %s (%s) — acked "
                        "hinted writes past the tear are LOST; "
                        "anti-entropy repairs the divergence after "
                        "hint gating expires", p, problem)
        # hand every pending quarantined entry (fresh + older failed
        # repairs) to the repair hook
        repaired = 0
        if self.on_corrupt is not None:
            for entry in health.quarantined_entries():
                if self._stop.is_set():
                    break
                try:
                    if self.on_corrupt(entry):
                        repaired += 1
                except Exception as e:  # noqa: BLE001 — retried next pass
                    self.logger.warning(
                        "scrub: repair hook failed for %s: %s",
                        entry["path"], e)
        self._passes += 1
        self._bytes_total += scanned
        self._corruptions += corrupt
        self._last_pass_seconds = time.monotonic() - t0
        self._last_pass_at = time.time()
        if scanned:
            self.stats.count("storage_scrub_bytes_total", scanned)
        if corrupt:
            self.logger.warning(
                "scrub pass: %d corrupt artifact(s) in %d files "
                "(%d bytes, %.2fs)", corrupt, files, scanned,
                self._last_pass_seconds)
        return {"files": files, "bytes": scanned, "corrupt": corrupt,
                "repaired": repaired,
                "seconds": round(self._last_pass_seconds, 3)}

    def _fragments(self):
        for idx in list(self.holder.indexes.values()):
            for f in list(idx.fields.values()):
                for v in list(f.views.values()):
                    yield from list(v.fragments.values())

    def payload(self) -> dict:
        """The ``scrub`` sub-block of ``storageHealth`` on /status."""
        return {
            "enabled": self.enabled,
            "intervalSeconds": self.interval,
            "bytesPerSecond": self.bytes_per_second,
            "passes": self._passes,
            "bytesScanned": self._bytes_total,
            "corruptionsFound": self._corruptions,
            "lastPassSeconds": round(self._last_pass_seconds, 3),
            "lastPassAt": self._last_pass_at,
        }
