"""View: groups the fragments of one flavor of one field.

Reference: ``view.go`` (SURVEY.md §3.1) — a field has a ``standard`` view
plus time-quantum views (``standard_2017``, …); an int (BSI) field keeps
its bit-planes in a ``bsi_<field>`` view.  Fragments are created on
demand per shard.
"""

from __future__ import annotations

import os
import threading

from pilosa_tpu.store.fragment import Fragment

VIEW_STANDARD = "standard"
VIEW_BSI_PREFIX = "bsi_"


class View:
    def __init__(self, path: str, name: str, *, fsync: bool = False,
                 snapshot_submit=None, health=None):
        self.path = path  # <field>/views/<name>
        self.name = name
        self.fsync = fsync
        self.snapshot_submit = snapshot_submit
        self.health = health  # disk-health governor (r19), holder's
        self.fragments: dict[int, Fragment] = {}
        self._lock = threading.RLock()

    def open(self) -> "View":
        frag_dir = os.path.join(self.path, "fragments")
        if os.path.isdir(frag_dir):
            # a fragment exists if EITHER its snapshot or its op-log does
            # (a crash before the first snapshot leaves only the op-log —
            # it must still be discovered or replay never runs)
            shards: set[int] = set()
            for entry in os.listdir(frag_dir):
                if entry.isdigit():
                    shards.add(int(entry))
                elif entry.endswith(".oplog") and entry[:-6].isdigit():
                    shards.add(int(entry[:-6]))
            for shard in shards:
                frag = Fragment(os.path.join(frag_dir, str(shard)), shard,
                                fsync=self.fsync,
                                snapshot_submit=self.snapshot_submit,
                                health=self.health)
                self.fragments[shard] = frag.open()
        return self

    def fragment(self, shard: int, create: bool = False) -> Fragment | None:
        with self._lock:
            frag = self.fragments.get(shard)
            if frag is None and create:
                path = os.path.join(self.path, "fragments", str(shard))
                os.makedirs(os.path.dirname(path), exist_ok=True)
                frag = Fragment(path, shard, fsync=self.fsync,
                                snapshot_submit=self.snapshot_submit,
                                health=self.health).open()
                self.fragments[shard] = frag
            return frag

    def available_shards(self) -> list[int]:
        with self._lock:
            return sorted(s for s, f in self.fragments.items() if f.present)

    def generations(self, shards) -> tuple:
        """Fragment generation per shard (-1 = absent), ONE lock
        acquisition for the whole list — the device plane cache
        revalidates on every query, so per-shard ``fragment()`` calls
        (954 lock round trips on a 1B-column index) are serving-path
        poison."""
        with self._lock:
            frags = self.fragments
            return tuple(
                frags[s].generation if s in frags else -1 for s in shards)

    def generations_fast(self, shards) -> tuple:
        """Lock-free :meth:`generations`: dict lookups and int reads
        are GIL-atomic, and the view lock never serialized against
        fragment mutations anyway (those bump ``Fragment.generation``
        under the FRAGMENT lock) — so the freshness semantics are
        identical while the serving hot path stops taking the view
        lock per plane revalidation.  A torn read across a concurrent
        fragment creation only yields a conservative mismatch (the
        caller rebuilds), never a stale hit."""
        frags = self.fragments
        out = []
        for s in shards:
            # .get, not membership+subscript: a fragment popped between
            # the two (empty-orphan deletion) must read as absent, not
            # raise on the serving hot path
            f = frags.get(s)
            out.append(f.generation if f is not None else -1)
        return tuple(out)

    def max_row_id(self) -> int:
        with self._lock:
            return max((f.max_row_id() for f in self.fragments.values()),
                       default=0)

    def close(self) -> None:
        with self._lock:
            for frag in self.fragments.values():
                frag.close()
