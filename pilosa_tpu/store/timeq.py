"""Time quantum views: names and minimal range covers.

Reference: ``time.go`` — ``viewsByTime`` (which granularity views a write
lands in) and ``viewsByTimeRange`` (minimal set of views covering a query
range), with view names like ``standard_2017``, ``standard_201701``,
``standard_20170102``, ``standard_2017010203`` (SURVEY.md §3.1).

Quantum strings are contiguous subsets of ``"YMDH"`` (as upstream:
``Y, M, D, H, YM, MD, DH, YMD, MDH, YMDH``).

Range semantics: ``[from, to)`` with both endpoints truncated down to the
quantum's finest unit.  The cover uses the smallest units at the edges and
the largest units in the middle, exactly covering the truncated range.
"""

from __future__ import annotations

from datetime import datetime

UNITS = "YMDH"
_FMT = {"Y": "%Y", "M": "%Y%m", "D": "%Y%m%d", "H": "%Y%m%d%H"}


def validate_quantum(q: str) -> str:
    q = q.upper()
    if q and q in UNITS or q in ("YM", "MD", "DH", "YMD", "MDH", "YMDH"):
        return q
    raise ValueError(f"invalid time quantum {q!r}")


def view_name(base: str, t: datetime, unit: str) -> str:
    return f"{base}_{t.strftime(_FMT[unit])}"


def views_by_time(base: str, t: datetime, quantum: str) -> list[str]:
    """All granularity views a timestamped write lands in."""
    return [view_name(base, t, u) for u in quantum]


def _floor(t: datetime, unit: str) -> datetime:
    if unit == "Y":
        return t.replace(month=1, day=1, hour=0, minute=0, second=0, microsecond=0)
    if unit == "M":
        return t.replace(day=1, hour=0, minute=0, second=0, microsecond=0)
    if unit == "D":
        return t.replace(hour=0, minute=0, second=0, microsecond=0)
    return t.replace(minute=0, second=0, microsecond=0)


def _next(t: datetime, unit: str) -> datetime:
    if unit == "Y":
        return t.replace(year=t.year + 1)
    if unit == "M":
        return t.replace(year=t.year + (t.month == 12), month=t.month % 12 + 1)
    if unit == "D":
        from datetime import timedelta
        return t + timedelta(days=1)
    from datetime import timedelta
    return t + timedelta(hours=1)


def _ceil(t: datetime, unit: str) -> datetime:
    f = _floor(t, unit)
    return f if f == t else _next(f, unit)


def views_by_time_range(base: str, start: datetime, end: datetime,
                        quantum: str) -> list[str]:
    """Minimal exact cover of ``[start, end)`` with the quantum's units."""
    quantum = validate_quantum(quantum)
    finest = quantum[-1]
    start, end = _floor(start, finest), _floor(end, finest)

    def cover(lo: datetime, hi: datetime, units: str) -> list[str]:
        if lo >= hi:
            return []
        u = units[0]
        if len(units) == 1:
            out, t = [], _floor(lo, u)
            while t < hi:
                out.append(view_name(base, t, u))
                t = _next(t, u)
            return out
        a1, a2 = _ceil(lo, u), _floor(hi, u)
        if a1 >= a2:
            return cover(lo, hi, units[1:])
        mid, t = [], a1
        while t < a2:
            mid.append(view_name(base, t, u))
            t = _next(t, u)
        return cover(lo, a1, units[1:]) + mid + cover(a2, hi, units[1:])

    return cover(start, end, quantum)


_SUFFIX_UNIT = {4: "Y", 6: "M", 8: "D", 10: "H"}


def parse_view_time(suffix: str) -> tuple[datetime, str]:
    """Inverse of :func:`view_name`'s suffix: ``"201701"`` →
    ``(2017-01-01, "M")``.  Raises ValueError for non-time suffixes."""
    unit = _SUFFIX_UNIT.get(len(suffix))
    if unit is None or not suffix.isdigit():
        raise ValueError(f"not a time view suffix: {suffix!r}")
    return datetime.strptime(suffix, _FMT[unit]), unit


def view_span(suffix: str) -> tuple[datetime, datetime]:
    """The ``[start, end)`` period a time view covers."""
    t, unit = parse_view_time(suffix)
    return t, _next(t, unit)


def parse_pql_time(s: str) -> datetime:
    """Timestamps as PQL accepts them (reference grammar's timestamp
    literal): ``2017-01-02T03:04`` (seconds optional) or ``2017-01-02``."""
    for fmt in ("%Y-%m-%dT%H:%M:%S", "%Y-%m-%dT%H:%M", "%Y-%m-%d"):
        try:
            return datetime.strptime(s, fmt)
        except ValueError:
            continue
    raise ValueError(f"cannot parse timestamp {s!r}")
