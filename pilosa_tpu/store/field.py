"""Field: a typed attribute group within an index.

Reference: ``field.go`` (SURVEY.md §3.1) — field types ``set``, ``int``
(BSI), ``time``, ``mutex``, ``bool`` plus v2's ``decimal`` and
``timestamp``; options (cache type/size kept for API parity, keys, time
quantum, min/max); the ``bsiGroup`` bit-sliced encoding with an exists
row, a sign row, and one row per magnitude bit of ``value - base``.

BSI row layout matches :mod:`pilosa_tpu.engine.bsi` exactly (EXISTS=0,
SIGN=1, OFFSET=2) — the device kernels consume fragment planes without
re-indexing.  ``bit_depth`` grows dynamically as larger values arrive
(reference: ``bsiGroup.bitDepth`` growth) and is persisted in the field
meta.
"""

from __future__ import annotations

import json
import os
import threading
from dataclasses import asdict, dataclass, field as dc_field
from datetime import datetime, timezone

import numpy as np

from pilosa_tpu.engine.bsi import EXISTS_ROW, OFFSET_ROW, SIGN_ROW
from pilosa_tpu.store import timeq
from pilosa_tpu.store.view import VIEW_BSI_PREFIX, VIEW_STANDARD, View

TYPE_SET = "set"
TYPE_INT = "int"
TYPE_TIME = "time"
TYPE_MUTEX = "mutex"
TYPE_BOOL = "bool"
TYPE_DECIMAL = "decimal"
TYPE_TIMESTAMP = "timestamp"

BSI_TYPES = (TYPE_INT, TYPE_DECIMAL, TYPE_TIMESTAMP)

_UNIX_EPOCH = datetime(1970, 1, 1, tzinfo=timezone.utc)
_TS_UNITS = {"s": 1, "ms": 10**3, "us": 10**6, "ns": 10**9}


@dataclass
class FieldOptions:
    """Reference: ``field.go#FieldOptions`` / ``fieldOptions``."""

    type: str = TYPE_SET
    keys: bool = False
    cache_type: str = "ranked"   # ranked | lru | none (API parity; the TPU
    cache_size: int = 50000      # TopN path recounts, caches are not used)
    time_quantum: str = ""
    min: int | None = None
    max: int | None = None
    base: int = 0
    bit_depth: int = 0
    scale: int = 0               # decimal: value stored as int(v * 10^scale)
    epoch: str = ""              # timestamp: ISO epoch, default Unix
    time_unit: str = "s"         # timestamp: s | ms | us | ns
    created_at: float = 0.0      # wall time of creation (cluster schema
                                 # tombstones compare against this)

    def __post_init__(self):
        if self.type not in (TYPE_SET, TYPE_INT, TYPE_TIME, TYPE_MUTEX,
                             TYPE_BOOL, TYPE_DECIMAL, TYPE_TIMESTAMP):
            raise ValueError(f"invalid field type {self.type!r}")
        if self.type == TYPE_TIME and self.time_quantum:
            self.time_quantum = timeq.validate_quantum(self.time_quantum)
        if self.type == TYPE_TIMESTAMP and self.time_unit not in _TS_UNITS:
            raise ValueError(f"invalid timestamp unit {self.time_unit!r}")
        if self.type in BSI_TYPES and self.min is not None and self.max is not None:
            if self.min > self.max:
                raise ValueError("field min > max")
            # base minimizes stored magnitudes (reference: v2 base offset)
            if self.base == 0:
                if self.min > 0:
                    self.base = self.min
                elif self.max < 0:
                    self.base = self.max
            if self.bit_depth == 0:
                span = max(abs(self.min - self.base), abs(self.max - self.base))
                self.bit_depth = max(1, int(span).bit_length())
        if self.type in BSI_TYPES and self.bit_depth == 0:
            self.bit_depth = 1


class Field:
    def __init__(self, path: str, index_name: str, name: str,
                 options: FieldOptions | None = None, *, fsync: bool = False,
                 snapshot_submit=None, health=None):
        self.path = path
        self.index_name = index_name
        self.name = name
        self.options = options or FieldOptions()
        self.fsync = fsync
        self.snapshot_submit = snapshot_submit
        self.health = health
        self.views: dict[str, View] = {}
        self._row_attrs = None
        self._lock = threading.RLock()

    # -- lifecycle ----------------------------------------------------------

    def open(self) -> "Field":
        meta = os.path.join(self.path, ".meta")
        if os.path.exists(meta):
            with open(meta) as f:
                self.options = FieldOptions(**json.load(f))
        views_dir = os.path.join(self.path, "views")
        if os.path.isdir(views_dir):
            for name in os.listdir(views_dir):
                v = View(os.path.join(views_dir, name), name,
                         fsync=self.fsync,
                         snapshot_submit=self.snapshot_submit,
                         health=self.health)
                self.views[name] = v.open()
        return self

    def save_meta(self) -> None:
        os.makedirs(self.path, exist_ok=True)
        tmp = os.path.join(self.path, ".meta.tmp")
        with open(tmp, "w") as f:
            json.dump(asdict(self.options), f)
        os.replace(tmp, os.path.join(self.path, ".meta"))

    def close(self) -> None:
        for v in self.views.values():
            v.close()
        if self._row_attrs is not None:
            self._row_attrs.close()
            self._row_attrs = None

    @property
    def row_attrs(self):
        """Row attribute store (reference: field-level AttrStore,
        ``field.go``), created on first use."""
        with self._lock:
            if self._row_attrs is None:
                from pilosa_tpu.store.attrs import AttrStore
                self._row_attrs = AttrStore(
                    os.path.join(self.path, "_attrs.db"))
            return self._row_attrs

    @property
    def has_row_attrs(self) -> bool:
        """Whether an attr store EXISTS, without creating one — pure
        read paths (Row results attaching attrs) must not write a
        sqlite file to a possibly read-only data dir."""
        with self._lock:
            if self._row_attrs is not None:
                return True
        return os.path.exists(os.path.join(self.path, "_attrs.db"))

    # -- views --------------------------------------------------------------

    def view(self, name: str, create: bool = False) -> View | None:
        with self._lock:
            v = self.views.get(name)
            if v is None and create:
                v = View(os.path.join(self.path, "views", name), name,
                         fsync=self.fsync,
                         snapshot_submit=self.snapshot_submit,
                         health=self.health).open()
                self.views[name] = v
            return v

    @property
    def bsi_view_name(self) -> str:
        return VIEW_BSI_PREFIX + self.name

    def standard_view(self, create: bool = False) -> View | None:
        return self.view(VIEW_STANDARD, create)

    def bsi_view(self, create: bool = False) -> View | None:
        return self.view(self.bsi_view_name, create)

    def available_shards(self) -> list[int]:
        shards: set[int] = set()
        with self._lock:
            for v in self.views.values():
                shards.update(v.available_shards())
        return sorted(shards)

    def max_row_id(self) -> int:
        v = self.standard_view()
        return v.max_row_id() if v else 0

    # -- bit writes (set / time / mutex / bool) -----------------------------

    def set_bit(self, row_id: int, col: int, timestamp: datetime | None = None) -> bool:
        return self.import_bits(np.array([row_id], np.uint64),
                                np.array([col], np.uint64),
                                [timestamp] if timestamp else None) > 0

    def clear_bit(self, row_id: int, col: int) -> bool:
        if self.options.type in BSI_TYPES:
            raise ValueError(f"field {self.name}: Clear on BSI field")
        from pilosa_tpu.engine.words import SHARD_WIDTH
        shard, off = col // SHARD_WIDTH, col % SHARD_WIDTH
        changed = 0
        with self._lock:
            for v in self.views.values():
                frag = v.fragment(shard)
                if frag is not None:
                    changed += frag.clear_bits(np.array([row_id], np.uint64),
                                               np.array([off], np.uint64))
        return changed > 0

    def import_bits(self, row_ids: np.ndarray, cols: np.ndarray,
                    timestamps: list[datetime | None] | None = None,
                    sync_batch=None) -> int:
        """Bulk (row, col[, ts]) writes routed to standard + time views
        (reference: ``field.Import`` → view fan-out, SURVEY.md §4.5).
        ``sync_batch`` (an :class:`~pilosa_tpu.store.oplog.SyncBatch`)
        coalesces durable op-log fsyncs to one per touched fragment at
        the batch boundary (the caller flushes)."""
        from pilosa_tpu.engine.words import SHARD_WIDTH
        opts = self.options
        if opts.type in BSI_TYPES:
            raise ValueError(f"field {self.name}: bit import on BSI field")
        row_ids = np.asarray(row_ids, np.uint64)
        cols = np.asarray(cols, np.uint64)
        if len(row_ids) != len(cols):
            raise ValueError(
                f"import_bits: {len(row_ids)} rows vs {len(cols)} columns")
        if opts.type == TYPE_BOOL and len(row_ids) and int(row_ids.max()) > 1:
            raise ValueError("bool field rows must be 0 or 1")
        shards = cols // np.uint64(SHARD_WIDTH)
        offs = cols % np.uint64(SHARD_WIDTH)
        # one sort + boundary slices, not a boolean mask per shard (an
        # O(batch × n_shards) rescan that dominated the 954-shard
        # spread — BASELINE.md r4 ingest profile)
        order = np.argsort(shards, kind="stable")
        shards_s, rows_s, offs_s = shards[order], row_ids[order], offs[order]
        uniq = np.unique(shards_s)
        bounds = np.searchsorted(shards_s, uniq)
        bounds = np.append(bounds, len(shards_s))
        changed = 0
        for i, shard in enumerate(uniq):
            lo, hi = bounds[i], bounds[i + 1]
            r, c = rows_s[lo:hi], offs_s[lo:hi]
            if opts.type in (TYPE_MUTEX, TYPE_BOOL):
                changed += self._set_mutex(int(shard), r, c)
            else:
                frag = self.standard_view(create=True).fragment(int(shard), create=True)
                changed += frag.set_bits(r, c, sync_batch=sync_batch)
            if opts.type == TYPE_TIME and timestamps is not None and opts.time_quantum:
                idx = order[lo:hi]
                for j, (rr, cc) in enumerate(zip(r, c)):
                    ts = timestamps[idx[j]] if idx[j] < len(timestamps) else None
                    if ts is None:
                        continue
                    for vname in timeq.views_by_time(VIEW_STANDARD, ts, opts.time_quantum):
                        tf = self.view(vname, create=True).fragment(int(shard), create=True)
                        tf.set_bits(np.array([rr], np.uint64),
                                    np.array([cc], np.uint64),
                                    sync_batch=sync_batch)
        return changed

    def clear_import(self, row_ids: np.ndarray, cols: np.ndarray,
                     sync_batch=None) -> int:
        """Bulk clear of (row, col) pairs — the ``clear=true`` half of
        the import endpoint, batched per fragment (one op-log record +
        one deferred fsync per touched fragment instead of a
        ``clear_bit`` round trip per pair).  Clears apply to EVERY view
        (a time-view copy left set would resurface in range queries),
        like :meth:`clear_bit`."""
        from pilosa_tpu.engine.words import SHARD_WIDTH
        if self.options.type in BSI_TYPES:
            raise ValueError(f"field {self.name}: bit clear on BSI field")
        row_ids = np.asarray(row_ids, np.uint64)
        cols = np.asarray(cols, np.uint64)
        if len(row_ids) != len(cols):
            raise ValueError(
                f"clear_import: {len(row_ids)} rows vs {len(cols)} columns")
        shards = cols // np.uint64(SHARD_WIDTH)
        offs = cols % np.uint64(SHARD_WIDTH)
        order = np.argsort(shards, kind="stable")
        shards_s, rows_s, offs_s = shards[order], row_ids[order], offs[order]
        uniq = np.unique(shards_s)
        bounds = np.append(np.searchsorted(shards_s, uniq), len(shards_s))
        changed = 0
        with self._lock:
            views = list(self.views.values())
        for i, shard in enumerate(uniq):
            lo, hi = bounds[i], bounds[i + 1]
            for v in views:
                frag = v.fragment(int(shard))
                if frag is not None:
                    changed_v = frag.clear_bits(rows_s[lo:hi],
                                                offs_s[lo:hi],
                                                sync_batch=sync_batch)
                    if v.name == VIEW_STANDARD:
                        changed += changed_v
        return changed

    def _set_mutex(self, shard: int, row_ids: np.ndarray, cols: np.ndarray) -> int:
        """Mutex semantics: setting (row, col) clears every other row of
        col (reference: mutex enforcement in ``fragment.setMutex``).
        Vectorized: one clear per existing row, one set per target row."""
        frag = self.standard_view(create=True).fragment(shard, create=True)
        # last write per column wins within the batch
        _, last_idx = np.unique(cols[::-1], return_index=True)
        keep = len(cols) - 1 - last_idx
        row_ids, cols = row_ids[keep].astype(np.uint64), cols[keep].astype(np.uint32)
        changed = 0
        for existing in frag.row_ids():
            # clear batch columns set in `existing` unless being set there
            to_clear = cols[np.isin(cols, frag.row(existing).columns())
                            & (row_ids != existing)]
            if len(to_clear):
                changed += frag.clear_bits(
                    np.full(len(to_clear), existing, np.uint64), to_clear)
        changed += frag.set_bits(row_ids, cols)
        return changed

    # -- BSI value writes ---------------------------------------------------

    def to_stored(self, value) -> int:
        """API value -> stored integer (decimal scaling / timestamp epoch)."""
        opts = self.options
        if opts.type == TYPE_DECIMAL:
            return int(round(float(value) * 10**opts.scale))
        if opts.type == TYPE_TIMESTAMP:
            if isinstance(value, str):
                value = timeq.parse_pql_time(value).replace(tzinfo=timezone.utc)
            if isinstance(value, datetime):
                epoch = (datetime.fromisoformat(opts.epoch)
                         if opts.epoch else _UNIX_EPOCH)
                if value.tzinfo is None:
                    value = value.replace(tzinfo=timezone.utc)
                return int((value - epoch).total_seconds() * _TS_UNITS[opts.time_unit])
            return int(value)
        return int(value)

    def _to_stored_batch(self, values) -> np.ndarray:
        """Vectorized :meth:`to_stored` for bulk imports (a python-level
        per-value loop dominates ingest otherwise)."""
        opts = self.options
        if opts.type == TYPE_INT:
            return np.asarray(values, dtype=np.int64)
        if opts.type == TYPE_DECIMAL and not any(
                isinstance(v, str) for v in values[:1]):
            return np.round(np.asarray(values, dtype=np.float64)
                            * 10**opts.scale).astype(np.int64)
        return np.array([self.to_stored(v) for v in values], dtype=np.int64)

    def from_stored(self, stored: int):
        opts = self.options
        if opts.type == TYPE_DECIMAL:
            return stored / 10**opts.scale
        return stored

    def set_value(self, col: int, value) -> bool:
        return self.import_values(np.array([col], np.uint64), [value]) > 0

    def import_values(self, cols: np.ndarray, values) -> int:
        """Bulk BSI writes: per bit-plane set/clear so overwrites need no
        read-back (reference: ``field.importValue`` → ``fragment.importValue``)."""
        opts = self.options
        if opts.type not in BSI_TYPES:
            raise ValueError(f"field {self.name}: value import on non-BSI field")
        from pilosa_tpu.engine.words import SHARD_WIDTH
        cols = np.asarray(cols, np.uint64)
        stored = self._to_stored_batch(values)
        if opts.min is not None and (stored < self.to_stored(opts.min)).any():
            raise ValueError(f"value below field min {opts.min}")
        if opts.max is not None and (stored > self.to_stored(opts.max)).any():
            raise ValueError(f"value above field max {opts.max}")
        offs = stored - np.int64(opts.base)
        mag = np.abs(offs).astype(np.uint64)
        need = (max(1, int(mag.max()).bit_length()) if len(mag) else 1)
        if need > opts.bit_depth:
            opts.bit_depth = need
            self.save_meta()
        depth = opts.bit_depth

        shards = cols // np.uint64(SHARD_WIDTH)
        col_offs = cols % np.uint64(SHARD_WIDTH)
        changed = 0
        for shard in np.unique(shards):
            m = shards == shard
            c, o, g = col_offs[m], offs[m], mag[m]
            frag = self.bsi_view(create=True).fragment(int(shard), create=True)
            # last write per column wins within the batch
            _, last = np.unique(c[::-1], return_index=True)
            keep = len(c) - 1 - last
            c, o, g = c[keep], o[keep], g[keep]
            # pre-grouped per-plane batches: ONE set op + ONE clear op
            # per shard (2 op-log records instead of 2*depth+3) with no
            # global position re-sort — the bulk-ingest hot path
            neg = o < 0
            set_groups = [(EXISTS_ROW, c), (SIGN_ROW, c[neg])]
            clr_groups = [(SIGN_ROW, c[~neg])]
            for b in range(depth):
                hit = (g >> np.uint64(b)) & np.uint64(1) != 0
                set_groups.append((OFFSET_ROW + b, c[hit]))
                clr_groups.append((OFFSET_ROW + b, c[~hit]))
            changed += frag.set_bits_grouped(set_groups)
            changed += frag.clear_bits_grouped(clr_groups)
        return changed

    def value(self, col: int) -> tuple[int, bool]:
        """Read one column's BSI value: (value, exists)."""
        from pilosa_tpu.engine.words import SHARD_WIDTH
        opts = self.options
        v = self.bsi_view()
        if v is None:
            return 0, False
        frag = v.fragment(col // SHARD_WIDTH)
        if frag is None:
            return 0, False
        off = col % SHARD_WIDTH
        if not frag.row(EXISTS_ROW).contains(off):
            return 0, False
        mag = 0
        for b in range(opts.bit_depth):
            if frag.row(OFFSET_ROW + b).contains(off):
                mag |= 1 << b
        if frag.row(SIGN_ROW).contains(off):
            mag = -mag
        return self.from_stored(mag + opts.base), True

    def clear_value(self, col: int) -> bool:
        """Remove a column's BSI value entirely."""
        from pilosa_tpu.engine.words import SHARD_WIDTH
        v = self.bsi_view()
        if v is None:
            return False
        frag = v.fragment(col // SHARD_WIDTH)
        if frag is None:
            return False
        off = col % SHARD_WIDTH
        rows = np.arange(OFFSET_ROW + self.options.bit_depth, dtype=np.uint64)
        return frag.clear_bits(rows, np.full(len(rows), off, np.uint64)) > 0
