"""String key ↔ uint64 ID translation stores.

Reference: ``translate.go`` (SURVEY.md §3.3) — per-index column-key store
and per-field row-key store.  v1 used an append-only translate log
replicated from the coordinator and replayed into memory on open; v2
moved to persistent per-partition BoltDB stores because the in-memory
map does not scale to high-cardinality keyed indexes.

This rebuild keeps the v1 *replication protocol* (sequential IDs from 1,
coordinator-assigned batches, ``tail``/``append_replicated`` streaming —
the cluster layer is unchanged) but replaces the replay-into-dict
storage with the v2-style persistent store: one sqlite database per key
log (sqlite is the same role BoltDB plays upstream), with

- O(1) open — no replay; ``max(id)`` is read from the index, so a
  10M-key store opens in milliseconds with flat memory;
- bounded host RAM — two LRU read caches (key→id, id→key) in front of
  the database instead of the whole mapping resident;
- batched transactions — ``translate``/``append_replicated`` write a
  whole batch in one fsynced commit, lookups run chunked ``IN`` queries.

IDs are assigned sequentially from 1 (0 never maps to a key, so a zero
result can't be mistranslated).

Cluster note: upstream v2 partitions column keys over 256 hash
partitions with per-partition primaries; here partition assignment
(``partition_of``) is computed the same way for placement parity, while
ID allocation stays sequential per store — the cluster layer routes
keyed writes through the partition owner and replicates the single
sequential log (v1 protocol over v2 storage).

Legacy migration: pre-round-5 stores wrote a CRC-framed append-only
``.keys`` log.  On first open of an empty sqlite store next to such a
log, the log is replayed once into sqlite (same IDs) and renamed to
``.keys.migrated``; nothing is deleted.
"""

from __future__ import annotations

import os
import sqlite3
import struct
import threading
import zlib
from collections import OrderedDict

import numpy as np

PARTITION_N = 256  # reference: cluster-wide constant

# Default per-direction LRU capacity (entries).  ~50-150 MB combined at
# typical key lengths; override per-store via the cache_size ctor arg.
DEFAULT_CACHE_SIZE = 1 << 19

_SQL_CHUNK = 3000  # max bound variables per IN query (sqlite cap 32766)


def fnv1a64(data: bytes) -> int:
    """FNV-1a 64-bit — the reference's key-hash for partition placement."""
    h = 0xCBF29CE484222325
    for b in data:
        h ^= b
        h = (h * 0x100000001B3) & 0xFFFFFFFFFFFFFFFF
    return h


def partition_of(key: str, n: int = PARTITION_N) -> int:
    return fnv1a64(key.encode()) % n


def _read_legacy_log(path: str):
    """Yield keys from a pre-round-5 CRC-framed ``.keys`` log, stopping
    at the first torn/corrupt record (same recovery rule the old replay
    used: everything before the tear is good)."""
    with open(path, "rb") as f:
        buf = f.read()
    pos = 0
    while pos + 8 <= len(buf):
        crc, ln = struct.unpack_from("<II", buf, pos)
        end = pos + 8 + ln
        if end > len(buf) or zlib.crc32(buf[pos + 4:end]) != crc:
            return
        yield buf[pos + 8:end].decode()
        pos = end


class _LRU:
    """Tiny bounded LRU map (OrderedDict move-to-end)."""

    __slots__ = ("cap", "_d")

    def __init__(self, cap: int):
        self.cap = cap
        self._d: OrderedDict = OrderedDict()

    def get(self, k):
        d = self._d
        v = d.get(k)
        if v is not None:
            d.move_to_end(k)
        return v

    def put(self, k, v) -> None:
        d = self._d
        d[k] = v
        d.move_to_end(k)
        if len(d) > self.cap:
            d.popitem(last=False)

    def __len__(self) -> int:
        return len(self._d)


class KeyStore:
    """One persistent key store: sqlite table ``keys(id PRIMARY KEY,
    key UNIQUE)`` with sequential IDs.  The ID of the i-th created key
    is ``i + 1``; ``len(store)`` is the high-water ID.

    All methods are safe under concurrent callers (one RLock, one
    connection); writes commit per batch, one fsync each.
    """

    def __init__(self, path: str, cache_size: int = DEFAULT_CACHE_SIZE):
        self.path = path
        self._lock = threading.RLock()
        self._key2id = _LRU(cache_size)
        self._id2key = _LRU(cache_size)
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._db = sqlite3.connect(path, check_same_thread=False)
        self._db.execute("PRAGMA journal_mode=WAL")
        self._db.execute("PRAGMA synchronous=FULL")
        # ceiling, not allocation: random-order key inserts churn the
        # UNIQUE btree; the 2MB default cache collapses create
        # throughput ~3x once the tree outgrows it (measured at 10M)
        self._db.execute("PRAGMA cache_size=-131072")
        self._db.execute(
            "CREATE TABLE IF NOT EXISTS keys"
            "(id INTEGER PRIMARY KEY, key TEXT NOT NULL UNIQUE)")
        self._db.commit()
        row = self._db.execute("SELECT max(id) FROM keys").fetchone()
        self._n = int(row[0] or 0)
        self._migrate_legacy()

    def _migrate_legacy(self) -> None:
        legacy = self.path[:-len(".sqlite")] + ".keys" \
            if self.path.endswith(".sqlite") else self.path + ".keys"
        if self._n or not os.path.exists(legacy):
            return
        batch: list[tuple[int, str]] = []
        for key in _read_legacy_log(legacy):
            self._n += 1
            batch.append((self._n, key))
            if len(batch) >= 65536:
                self._db.executemany(
                    "INSERT INTO keys(id, key) VALUES(?, ?)", batch)
                batch.clear()
        if batch:
            self._db.executemany(
                "INSERT INTO keys(id, key) VALUES(?, ?)", batch)
        self._db.commit()
        os.rename(legacy, legacy + ".migrated")

    # -- lookup helpers -----------------------------------------------------

    def _fetch_ids(self, keys: list[str]) -> dict[str, int]:
        """DB lookup for keys (no cache check); fills the cache."""
        found: dict[str, int] = {}
        for i in range(0, len(keys), _SQL_CHUNK):
            chunk = keys[i:i + _SQL_CHUNK]
            q = ("SELECT key, id FROM keys WHERE key IN (%s)"
                 % ",".join("?" * len(chunk)))
            for k, kid in self._db.execute(q, chunk):
                found[k] = kid
                self._key2id.put(k, kid)
        return found

    def _fetch_keys(self, ids: list[int]) -> dict[int, str]:
        found: dict[int, str] = {}
        for i in range(0, len(ids), _SQL_CHUNK):
            chunk = ids[i:i + _SQL_CHUNK]
            q = ("SELECT id, key FROM keys WHERE id IN (%s)"
                 % ",".join("?" * len(chunk)))
            for kid, k in self._db.execute(q, chunk):
                found[kid] = k
                self._id2key.put(kid, k)
        return found

    # -- api ----------------------------------------------------------------

    def translate(self, keys: list[str], create: bool = False) -> list[int | None]:
        """Keys → IDs; unknown keys get new IDs if ``create`` else None.
        A key repeated within the batch gets one ID.  The whole created
        tail commits in one transaction (one fsync per batch)."""
        out: list[int | None] = [None] * len(keys)
        with self._lock:
            misses: list[int] = []
            for i, k in enumerate(keys):
                kid = self._key2id.get(k)
                if kid is None:
                    misses.append(i)
                else:
                    out[i] = kid
            if misses:
                found = self._fetch_ids(
                    list({keys[i]: None for i in misses}))
                new: dict[str, int] = {}
                rows: list[tuple[int, str]] = []
                n0 = self._n
                for i in misses:
                    k = keys[i]
                    kid = found.get(k)
                    if kid is None:
                        kid = new.get(k)
                        if kid is None and create:
                            self._n += 1
                            kid = new[k] = self._n
                            rows.append((kid, k))
                    out[i] = kid
                if rows:
                    try:
                        self._db.executemany(
                            "INSERT INTO keys(id, key) VALUES(?, ?)", rows)
                        self._db.commit()
                    except sqlite3.Error:
                        # a failed commit must not advance the ID
                        # high-water mark: replication arithmetic uses
                        # len(store), and a divergent counter would remap
                        # keys to different IDs on coordinator vs replica
                        self._db.rollback()
                        self._n = n0
                        raise
                    for kid, k in rows:
                        self._key2id.put(k, kid)
        return out

    def append_replicated(self, start_id: int, keys: list[str]) -> None:
        """Apply a replicated batch assigned by the coordinator
        (reference: v1 translate-log streaming, SURVEY.md §3.3).  Batches
        may overlap what we have (idempotent); a gap means we missed a
        batch and must pull the tail first."""
        with self._lock:
            if start_id > self._n + 1:
                raise KeyError(
                    f"translate gap: have {self._n} keys, batch starts at "
                    f"{start_id}")
            skip = self._n + 1 - start_id
            rows = []
            n0 = self._n
            for k in keys[skip:]:
                self._n += 1
                rows.append((self._n, k))
            if rows:
                try:
                    self._db.executemany(
                        "INSERT INTO keys(id, key) VALUES(?, ?)", rows)
                    self._db.commit()
                except sqlite3.Error:
                    self._db.rollback()
                    self._n = n0
                    raise
                for kid, k in rows:
                    self._key2id.put(k, kid)

    def tail(self, after_id: int, limit: int | None = None) -> list[str]:
        """Keys with IDs > after_id, in ID order; at most ``limit`` when
        given (peers page large tails instead of one giant response)."""
        with self._lock:
            if limit is None:
                cur = self._db.execute(
                    "SELECT key FROM keys WHERE id > ? ORDER BY id",
                    (after_id,))
            else:
                cur = self._db.execute(
                    "SELECT key FROM keys WHERE id > ? ORDER BY id "
                    "LIMIT ?", (after_id, limit))
            return [r[0] for r in cur]

    def key_of(self, kid: int) -> str | None:
        with self._lock:
            if not 1 <= kid <= self._n:
                return None
            k = self._id2key.get(kid)
            if k is None:
                row = self._db.execute(
                    "SELECT key FROM keys WHERE id = ?", (kid,)).fetchone()
                if row is None:
                    return None
                k = row[0]
                self._id2key.put(kid, k)
            return k

    def keys_of(self, ids: np.ndarray, strict: bool = True) -> list[str]:
        """Batched id→key lookup under ONE lock acquisition.  ``strict``
        raises on an unknown id; otherwise unknown ids yield ``None``
        (the per-id ``key_of`` semantics)."""
        with self._lock:
            out: list[str | None] = [None] * len(ids)
            misses: list[int] = []
            for i, kid in enumerate(ids):
                kid = int(kid)
                k = self._id2key.get(kid)
                if k is None:
                    misses.append(i)
                out[i] = k
            if misses:
                found = self._fetch_keys(
                    list({int(ids[i]): None for i in misses}))
                for i in misses:
                    out[i] = found.get(int(ids[i]))
            if strict:
                for i, k in enumerate(out):
                    if k is None:
                        raise KeyError(f"no key for id {int(ids[i])}")
            return out

    def __len__(self) -> int:
        with self._lock:
            return self._n

    def cache_info(self) -> dict:
        """Diagnostic: resident cache entries per direction."""
        with self._lock:
            return {"key2id": len(self._key2id), "id2key": len(self._id2key),
                    "cap": self._key2id.cap, "n": self._n}

    def close(self) -> None:
        with self._lock:
            if self._db is not None:
                self._db.commit()
                self._db.close()
                self._db = None


# Pre-round-5 name; same interface, storage moved from replay-log to sqlite.
KeyLog = KeyStore


class TranslateStore:
    """All key stores of one holder:
    ``<data>/<index>/_keys/_columns.sqlite`` for column keys,
    ``<data>/<index>/_keys/<field>.sqlite`` per field."""

    def __init__(self, holder_path: str, cache_size: int = DEFAULT_CACHE_SIZE,
                 health=None):
        self.holder_path = holder_path
        self.cache_size = cache_size
        # disk-health governor (r19): previously-silent OSError sites
        # below feed its fault counter (note_os_error)
        self._health = health
        self._logs: dict[tuple[str, str | None], KeyStore] = {}
        self._lock = threading.Lock()

    def _log(self, index: str, field: str | None) -> KeyStore:
        with self._lock:
            log = self._logs.get((index, field))
            if log is None:
                name = "_columns" if field is None else field
                path = os.path.join(self.holder_path, index, "_keys",
                                    f"{name}.sqlite")
                log = self._logs[(index, field)] = KeyStore(
                    path, cache_size=self.cache_size)
            return log

    def columns(self, index: str) -> KeyStore:
        return self._log(index, None)

    def rows(self, index: str, field: str) -> KeyStore:
        return self._log(index, field)

    def list_stores(self) -> list[tuple[str, str | None]]:
        """Every ``(index, field|None)`` key store this holder has —
        opened in-process or persisted on disk from a previous run
        (sqlite stores survive restarts, so a rebooted node must still
        advertise them to cluster joiners)."""
        seen: set[tuple[str, str | None]] = set()
        with self._lock:
            seen.update(self._logs)
        try:
            for index in os.listdir(self.holder_path):
                kdir = os.path.join(self.holder_path, index, "_keys")
                if not os.path.isdir(kdir):
                    continue
                for fn in os.listdir(kdir):
                    if fn.endswith(".sqlite"):
                        name = fn[:-len(".sqlite")]
                        seen.add((index,
                                  None if name == "_columns" else name))
        except OSError as e:
            # an ABSENT holder dir (ENOENT, fresh node) is the
            # deliberate fallback: in-process stores alone are the
            # answer.  Any other errno means persisted stores may be
            # hidden from cluster joiners — log once + feed the
            # governor, still answer with what we have (degraded,
            # never an error)
            from pilosa_tpu.store.health import note_os_error
            note_os_error("translate.list", self.holder_path, e,
                          health=self._health)
        return sorted(seen, key=lambda t: (t[0], t[1] or ""))

    def _paths(self, index: str, name: str) -> list[str]:
        base = os.path.join(self.holder_path, index, "_keys", name)
        return [base + s for s in
                (".sqlite", ".sqlite-wal", ".sqlite-shm",
                 ".keys", ".keys.migrated")]

    def drop(self, index: str, field: str | None = None,
             remove_files: bool = False) -> None:
        """Forget cached key stores for a deleted index (all its stores)
        or one field — a recreated index/field must start from empty key
        state, not inherit the dead one's mappings."""
        with self._lock:
            if field is not None:
                log = self._logs.pop((index, field), None)
                if log is not None:
                    log.close()
                if remove_files:
                    for path in self._paths(index, field):
                        try:
                            os.remove(path)
                        except OSError as e:
                            # most of these files are OPTIONAL (wal/
                            # shm/legacy logs): ENOENT is the
                            # deliberate no-op.  A remove that fails
                            # for any other reason leaves a dead
                            # field's key state to haunt a recreated
                            # field — log once + feed the governor
                            from pilosa_tpu.store.health import \
                                note_os_error
                            note_os_error("translate.drop", path, e,
                                          health=self._health)
                return
            for key in [k for k in self._logs if k[0] == index]:
                self._logs.pop(key).close()

    def close(self) -> None:
        with self._lock:
            for log in self._logs.values():
                log.close()
            self._logs.clear()
