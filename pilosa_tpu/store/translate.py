"""String key ↔ uint64 ID translation stores.

Reference: ``translate.go`` (SURVEY.md §3.3) — per-index column-key store
and per-field row-key store; v1 used an append-only translate log
replicated from the coordinator.  This rebuild keeps the append-only log
(CRC-framed, replayed into memory on open); IDs are assigned
sequentially from 1 (0 never maps to a key, so a zero result can't be
mistranslated).

Cluster note: upstream v2 partitions column keys over 256 hash
partitions with per-partition primaries; here partition assignment
(``partition_of``) is computed the same way for placement parity, while
ID allocation stays sequential per store — the cluster layer routes
keyed writes through the partition owner.
"""

from __future__ import annotations

import os
import struct
import threading
import zlib

import numpy as np

PARTITION_N = 256  # reference: cluster-wide constant


def fnv1a64(data: bytes) -> int:
    """FNV-1a 64-bit — the reference's key-hash for partition placement."""
    h = 0xCBF29CE484222325
    for b in data:
        h ^= b
        h = (h * 0x100000001B3) & 0xFFFFFFFFFFFFFFFF
    return h


def partition_of(key: str, n: int = PARTITION_N) -> int:
    return fnv1a64(key.encode()) % n


class KeyLog:
    """One append-only key log: record = u32 crc | u32 len | utf8 key.
    ID of the i-th appended key is ``i + 1``."""

    def __init__(self, path: str):
        self.path = path
        self._keys: list[str] = []
        self._ids: dict[str, int] = {}
        self._lock = threading.RLock()
        self._f = None
        self._load()

    def _load(self) -> None:
        if not os.path.exists(self.path):
            return
        with open(self.path, "rb") as f:
            buf = f.read()
        pos, good = 0, 0
        while pos + 8 <= len(buf):
            crc, ln = struct.unpack_from("<II", buf, pos)
            end = pos + 8 + ln
            if end > len(buf) or zlib.crc32(buf[pos + 4:end]) != crc:
                break
            key = buf[pos + 8:end].decode()
            self._ids[key] = len(self._keys) + 1
            self._keys.append(key)
            pos = good = end
        if good < len(buf):
            with open(self.path, "r+b") as f:
                f.truncate(good)

    def _append(self, key: str) -> None:
        if self._f is None:
            os.makedirs(os.path.dirname(self.path), exist_ok=True)
            self._f = open(self.path, "ab")
        data = key.encode()
        body = struct.pack("<I", len(data)) + data
        self._f.write(struct.pack("<I", zlib.crc32(body)) + body)
        self._f.flush()

    # -- api ----------------------------------------------------------------

    def translate(self, keys: list[str], create: bool = False) -> list[int | None]:
        """Keys → IDs; unknown keys get new IDs if ``create`` else None."""
        out: list[int | None] = []
        with self._lock:
            for k in keys:
                kid = self._ids.get(k)
                if kid is None and create:
                    self._append(k)
                    kid = len(self._keys) + 1
                    self._ids[k] = kid
                    self._keys.append(k)
                out.append(kid)
        return out

    def append_replicated(self, start_id: int, keys: list[str]) -> None:
        """Apply a replicated batch assigned by the coordinator
        (reference: v1 translate-log streaming, SURVEY.md §3.3).  Batches
        may overlap what we have (idempotent); a gap means we missed a
        batch and must pull the tail first."""
        with self._lock:
            have = len(self._keys)
            if start_id > have + 1:
                raise KeyError(
                    f"translate gap: have {have} keys, batch starts at "
                    f"{start_id}")
            skip = have + 1 - start_id
            for k in keys[skip:]:
                self._append(k)
                self._ids[k] = len(self._keys) + 1
                self._keys.append(k)

    def tail(self, after_id: int) -> list[str]:
        """Keys with IDs > after_id, in ID order."""
        with self._lock:
            return list(self._keys[after_id:])

    def key_of(self, kid: int) -> str | None:
        with self._lock:
            if 1 <= kid <= len(self._keys):
                return self._keys[kid - 1]
            return None

    def keys_of(self, ids: np.ndarray, strict: bool = True) -> list[str]:
        """Batched id→key lookup under ONE lock acquisition.  ``strict``
        raises on an unknown id; otherwise unknown ids yield ``None``
        (the per-id ``key_of`` semantics)."""
        with self._lock:
            keys = self._keys
            n = len(keys)
            out: list[str | None] = []
            for kid in ids:
                kid = int(kid)
                if 1 <= kid <= n:
                    out.append(keys[kid - 1])
                elif strict:
                    raise KeyError(f"no key for id {kid}")
                else:
                    out.append(None)
            return out

    def __len__(self) -> int:
        with self._lock:
            return len(self._keys)

    def close(self) -> None:
        with self._lock:
            if self._f is not None:
                self._f.close()
                self._f = None


class TranslateStore:
    """All key logs of one holder: ``<data>/<index>/_keys/_columns.keys``
    for column keys, ``<data>/<index>/_keys/<field>.keys`` per field."""

    def __init__(self, holder_path: str):
        self.holder_path = holder_path
        self._logs: dict[tuple[str, str | None], KeyLog] = {}
        self._lock = threading.Lock()

    def _log(self, index: str, field: str | None) -> KeyLog:
        with self._lock:
            log = self._logs.get((index, field))
            if log is None:
                name = "_columns" if field is None else field
                path = os.path.join(self.holder_path, index, "_keys",
                                    f"{name}.keys")
                log = self._logs[(index, field)] = KeyLog(path)
            return log

    def columns(self, index: str) -> KeyLog:
        return self._log(index, None)

    def rows(self, index: str, field: str) -> KeyLog:
        return self._log(index, field)

    def drop(self, index: str, field: str | None = None,
             remove_files: bool = False) -> None:
        """Forget cached key logs for a deleted index (all its logs) or
        one field — a recreated index/field must start from empty key
        state, not inherit the dead one's mappings."""
        with self._lock:
            if field is not None:
                log = self._logs.pop((index, field), None)
                if log is not None:
                    log.close()
                if remove_files:
                    path = os.path.join(self.holder_path, index, "_keys",
                                        f"{field}.keys")
                    try:
                        os.remove(path)
                    except OSError:
                        pass
                return
            for key in [k for k in self._logs if k[0] == index]:
                self._logs.pop(key).close()

    def close(self) -> None:
        with self._lock:
            for log in self._logs.values():
                log.close()
            self._logs.clear()
