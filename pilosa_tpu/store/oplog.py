"""Append-only operation log for fragment durability.

Reference: the op-log appended after a fragment snapshot, replayed on open
and compacted into a new snapshot when ``opN > MaxOpN``
(``fragment.go#snapshot``; SURVEY.md §3.1, §4.5).  Here the log is a
separate file beside the snapshot; records are CRC-framed so a torn tail
write truncates cleanly on replay instead of corrupting the fragment.

Record layout (little-endian):

    u32 crc32 (of everything after this field)
    u8  op     (1=SET_BITS, 2=CLEAR_BITS, 3=CLEAR_ROW, 4=SET_ROW;
                high bit 0x80 = raw payload, see below)
    u64 aux    (row id for CLEAR_ROW/SET_ROW, else 0)
    u32 len    payload byte length
    payload    roaring-serialized bit positions (SET/CLEAR_BITS; for
               SET_ROW the row's complete new contents — one atomic
               record, so a crash can never replay the clear half of a
               row replacement without its set half)

Small batches (r5): records whose position count is under
``RAW_MAX_POSITIONS`` set the 0x80 flag on the op byte and carry raw
little-endian u64 positions instead of roaring — the roaring encoder's
fixed cost (~70 µs) dominated per-record time at the many-fragment
ingest spread (BASELINE.md r4), and at ~100 positions raw bytes are no
larger than a one-container roaring blob.  Replay handles both forms;
old logs (no flag) read unchanged.
"""

from __future__ import annotations

import os
import struct
import threading
import zlib
from collections import OrderedDict
from typing import Iterator

import numpy as np

from pilosa_tpu import fault
from pilosa_tpu.store import roaring, syswrap

OP_SET_BITS = 1
OP_CLEAR_BITS = 2
OP_CLEAR_ROW = 3
OP_SET_ROW = 4

_HEADER = struct.Struct("<IBQI")

RAW_FLAG = 0x80           # op-byte flag: payload is raw <u8 positions
RAW_MAX_POSITIONS = 4096  # beyond this, roaring wins on size


def clean_prefix_end(buf: bytes, header: struct.Struct) -> int:
    """Byte offset where the whole-record prefix of a CRC-framed log
    ends (== ``len(buf)`` for a clean file).  Works for every frame
    this repo uses — op-log, hint log — because they share one shape:
    a u32 crc32 of everything after it comes FIRST, the payload byte
    length comes LAST in the header.  The single copy of the scan the
    failed-append truncation and the scrubber's verifiers share (the
    decode-as-you-go replay loops keep their own walk — they need the
    payloads)."""
    pos = 0
    while pos + header.size <= len(buf):
        fields = header.unpack_from(buf, pos)
        end = pos + header.size + fields[-1]
        if end > len(buf) or zlib.crc32(buf[pos + 4:end]) != fields[0]:
            break
        pos = end
    return pos


class SyncBatch:
    """Fsync coalescer for one import batch (r15 ingest): every op-log
    append inside the batch notes its log here instead of fsyncing
    inline, and :meth:`flush` issues ONE fsync per touched log file at
    the batch boundary.  Durability unit becomes the batch — a crash
    before the flush may lose the whole unsynced tail, but CRC framing
    still truncates any torn record cleanly on replay, so recovery is
    always a record-boundary prefix of the batch.  Fsyncs go through
    ``syswrap.checked_fsync``, so the ``sys.fsync`` failpoint covers
    the batch boundary exactly like a per-record sync."""

    def __init__(self):
        self._logs: dict[int, "OpLog"] = {}

    def note(self, log: "OpLog") -> None:
        self._logs[id(log)] = log

    def flush(self) -> int:
        """Fsync every noted log once; returns how many were synced."""
        logs, self._logs = list(self._logs.values()), {}
        for log in logs:
            log.sync()
        return len(logs)


class OpLog:
    """One fragment's op log.  Not thread-safe; the fragment serializes."""

    def __init__(self, path: str, fsync: bool = False):
        self.path = path
        self.fsync = fsync
        self._f = None

    def _file(self):
        if self._f is None:
            self._f = open(self.path, "ab")
        return self._f

    def append(self, op: int, aux: int = 0,
               positions: np.ndarray | None = None,
               sync_batch: SyncBatch | None = None) -> None:
        """Append one record.  With ``sync_batch`` (the batched-append
        API), a durability-enabled log defers its fsync to the batch's
        single :meth:`SyncBatch.flush` — one fsync per import batch
        per file instead of one per record."""
        if positions is None:
            payload = b""
        elif len(positions) <= RAW_MAX_POSITIONS:
            payload = np.asarray(positions, "<u8").tobytes()
            op |= RAW_FLAG
        else:
            payload = roaring.serialize(positions)
        body = struct.pack("<BQI", op, aux, len(payload)) + payload
        record = struct.pack("<I", zlib.crc32(body)) + body
        f = self._file()
        try:
            if fault.ACTIVE:
                # record-relative torn tail: persist only args.offset
                # bytes of THIS record then "crash" — replay must
                # recover the clean prefix (CRC framing) whatever the
                # offset
                spec = fault.fire("oplog.append", path=self.path, op=op)
                if spec is not None and spec["action"] == "torn_write":
                    fault.torn_write(f, record, spec)
            syswrap.checked_write(f, record)
            # flush INSIDE the tear handler: small records are
            # buffered by checked_write without a syscall, so a real
            # ENOSPC usually surfaces HERE — a flush failure is the
            # same partial-bytes-on-disk state as a failed write
            f.flush()
            if self.fsync:
                if sync_batch is not None:
                    sync_batch.note(self)
                else:
                    syswrap.checked_fsync(f)
        except OSError:
            # a SHORT write without a crash (ENOSPC, quota): partial
            # record bytes may be on disk.  Truncate back to the clean
            # record prefix NOW — once the disk recovers, the next
            # append must land on a record boundary, or replay would
            # stop at this tear and silently discard every later
            # acked record (the same poisoned-tail rule HintLog
            # enforces, r13)
            self.truncate_torn_tail()
            raise

    def sync(self) -> None:
        """Fsync the log file if durability is on (the deferred half of
        a batched append; no-op when the file was never opened)."""
        if self.fsync and self._f is not None:
            syswrap.checked_fsync(self._f)

    def replay(self) -> Iterator[tuple[int, int, np.ndarray | None]]:
        """Yield (op, aux, positions).  Stops (and truncates the file) at
        the first torn/corrupt record — crash-consistent replay."""
        if not os.path.exists(self.path):
            return
        with open(self.path, "rb") as f:
            buf = f.read()
        pos = 0
        good_end = 0
        while pos + _HEADER.size <= len(buf):
            crc, op, aux, plen = _HEADER.unpack_from(buf, pos)
            end = pos + _HEADER.size + plen
            if end > len(buf):
                break
            body = buf[pos + 4:end]
            if zlib.crc32(body) != crc:
                break
            payload = buf[pos + _HEADER.size:end]
            if op & RAW_FLAG:
                positions = np.frombuffer(payload, "<u8").astype(np.uint64)
                op &= ~RAW_FLAG
            else:
                positions = roaring.deserialize(payload) if plen else None
            yield op, aux, positions
            pos = end
            good_end = end
        if good_end < len(buf):
            with open(self.path, "r+b") as f:
                f.truncate(good_end)

    def truncate_torn_tail(self) -> None:
        """Physically truncate any torn/corrupt tail back to the
        whole-record prefix (frame scan, no payload decode — this is
        the failed-append recovery path).  Best-effort: a disk that
        cannot even truncate leaves the tear for boot replay's own
        clean-prefix recovery."""
        self.close()
        try:
            with open(self.path, "rb") as f:
                buf = f.read()
        except OSError:
            return
        pos = clean_prefix_end(buf, _HEADER)
        if pos < len(buf):
            try:
                with open(self.path, "r+b") as f:
                    f.truncate(pos)
            except OSError:
                pass

    def truncate(self) -> None:
        """Discard the log (after a snapshot compaction)."""
        self.close()
        with open(self.path, "wb"):
            pass

    def size(self) -> int:
        try:
            return os.path.getsize(self.path)
        except OSError:
            return 0

    def close(self) -> None:
        if self._f is not None:
            self._f.close()
            self._f = None


class IdWindow:
    """Durable bounded set of applied operation ids — the receiver-side
    dedup window behind the idempotent hint-replay endpoint (a
    re-delivered or re-sent batch must be a no-op, or a replayed
    ``Clear`` could land AFTER a newer direct ``Set`` and destroy it).

    Same recovery discipline as :class:`OpLog`: CRC-framed appends
    through the ``sys.write`` torn-write seam, clean-prefix replay on
    open (a torn tail record truncates away).  Record layout::

        u32 crc32 (of everything after this field)
        u8  len   id byte length
        id        utf-8 op id

    The newest ``cap`` ids are held in memory; once the file carries
    more than ``2 * cap`` records it is compacted (tmp + rename) down
    to the in-memory window.  Ids are random 128-bit tokens, so a
    window of thousands is far wider than any in-flight replay batch.
    """

    _HEAD = struct.Struct("<IB")

    def __init__(self, path: str, cap: int = 8192):
        self.path = path
        self.cap = int(cap)
        self._lock = threading.Lock()
        self._ids: OrderedDict[str, None] = OrderedDict()
        self._f = None
        self._file_records = 0
        self._load()

    def _load(self) -> None:
        if not os.path.exists(self.path):
            return
        with open(self.path, "rb") as f:
            buf = f.read()
        pos = 0
        good_end = 0
        while pos + self._HEAD.size <= len(buf):
            crc, ln = self._HEAD.unpack_from(buf, pos)
            end = pos + self._HEAD.size + ln
            if end > len(buf):
                break
            body = buf[pos + 4:end]
            if zlib.crc32(body) != crc:
                break
            try:
                op_id = buf[pos + self._HEAD.size:end].decode()
            except UnicodeDecodeError:
                break
            self._ids[op_id] = None
            self._ids.move_to_end(op_id)
            self._file_records += 1
            pos = end
            good_end = end
        if good_end < len(buf):
            with open(self.path, "r+b") as f:
                f.truncate(good_end)
        while len(self._ids) > self.cap:
            self._ids.popitem(last=False)

    def __contains__(self, op_id: str) -> bool:
        with self._lock:
            return op_id in self._ids

    def add(self, op_id: str) -> bool:
        """Record one applied id durably; False when already present
        (the caller skips the op — dedup hit)."""
        with self._lock:
            if op_id in self._ids:
                return False
            raw = op_id.encode()
            body = struct.pack("<B", len(raw)) + raw
            record = struct.pack("<I", zlib.crc32(body)) + body
            if self._f is None:
                d = os.path.dirname(self.path)
                if d:
                    os.makedirs(d, exist_ok=True)
                self._f = open(self.path, "ab")
            syswrap.checked_write(self._f, record)
            self._f.flush()
            self._ids[op_id] = None
            self._file_records += 1
            while len(self._ids) > self.cap:
                self._ids.popitem(last=False)
            if self._file_records > 2 * self.cap:
                self._compact()
            return True

    def _compact(self) -> None:
        """Rewrite the file down to the in-memory window (caller holds
        the lock).  Atomic via tmp + rename; a crash leaves either file
        and both recover cleanly."""
        if self._f is not None:
            self._f.close()
            self._f = None
        tmp = self.path + ".tmp"
        with open(tmp, "wb") as f:
            for op_id in self._ids:
                raw = op_id.encode()
                body = struct.pack("<B", len(raw)) + raw
                f.write(struct.pack("<I", zlib.crc32(body)) + body)
        os.replace(tmp, self.path)
        self._file_records = len(self._ids)

    def __len__(self) -> int:
        with self._lock:
            return len(self._ids)

    def close(self) -> None:
        with self._lock:
            if self._f is not None:
                self._f.close()
                self._f = None
