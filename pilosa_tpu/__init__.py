"""pilosa_tpu — a TPU-native distributed bitmap index.

A from-scratch rebuild of the capabilities of Pilosa (the distributed
roaring-bitmap index; see SURVEY.md for the reference layer map), designed
TPU-first on JAX/XLA:

- The roaring container boolean algebra (reference: ``roaring/roaring.go``,
  SURVEY.md §3.1) becomes fused bitwise+popcount XLA kernels over packed
  ``uint32`` device arrays (:mod:`pilosa_tpu.engine`).
- The per-shard map-reduce executor (reference: ``executor.go#mapReduce``,
  SURVEY.md §4.2) becomes a sharded, jit-compiled program over a
  ``jax.sharding.Mesh`` with ICI collective reductions in place of HTTP
  merges (:mod:`pilosa_tpu.parallel`, :mod:`pilosa_tpu.exec`).
- Host-side storage keeps a roaring-style container format on disk with an
  op-log + snapshot durability model (reference: ``fragment.go``, SURVEY.md
  §3.1/§6) (:mod:`pilosa_tpu.store`).
- The PQL query language front end is re-implemented as a hand-rolled
  lexer + recursive-descent parser (reference: ``pql/``, SURVEY.md §3.2)
  (:mod:`pilosa_tpu.pql`).

Layer map (mirrors SURVEY.md §2):

====  =====================  ===========================================
L0    pilosa_tpu.engine      packed-word bitmap kernels (XLA), BSI, TopN
L1    pilosa_tpu.store       holder/index/field/view/fragment, codec,
                             attrs, key translation (+ native/ C++ codec)
L2    pilosa_tpu.pql         PQL front end
L2    pilosa_tpu.exec        AST -> one fused XLA program per call shape
L3    pilosa_tpu.parallel    shard/words device mesh, SPMD psum programs
L3    pilosa_tpu.cluster     membership, fan-out/merge, AAE, resize
L5    pilosa_tpu.api         REST surface + client
L6    pilosa_tpu.cli         command line + config
LX    pilosa_tpu.obs         metrics / tracing / logging / diagnostics
====  =====================  ===========================================
"""

__version__ = "0.1.0"

from pilosa_tpu.engine.words import SHARD_WIDTH, WORD_BITS, WORDS_PER_SHARD

__all__ = ["SHARD_WIDTH", "WORD_BITS", "WORDS_PER_SHARD", "__version__"]
