"""Packed-word layout constants and host-side (numpy) pack/unpack helpers.

Layout decision (SURVEY.md §8): a shard is ``SHARD_WIDTH = 2**20`` columns
(reference: ``pilosa.ShardWidth``, root pkg const) packed into
``WORDS_PER_SHARD = 32768`` little-endian ``uint32`` words — uint32 is the
native TPU lane width, so bitwise ops and ``lax.population_count`` map
directly onto the VPU without 64-bit emulation.

Bit order: column ``c`` of a shard lives at word ``c >> 5``, bit ``c & 31``
(LSB-first within a word).  This matches numpy ``unpackbits`` with
``bitorder='little'`` over the words viewed as bytes, which the host codec
relies on.
"""

from __future__ import annotations

import numpy as np

# One shard = 2**20 columns; the unit of distribution, parallelism and
# storage (reference: ShardWidth const, SURVEY.md §1).
SHARD_WIDTH = 1 << 20

WORD_BITS = 32
WORDS_PER_SHARD = SHARD_WIDTH // WORD_BITS  # 32768

_ONE = np.uint32(1)


def pack_columns(cols: np.ndarray, n_words: int = WORDS_PER_SHARD) -> np.ndarray:
    """Pack sorted-or-not column offsets (within one shard) into uint32 words.

    Host-side analogue of building one dense row from roaring containers
    (reference: ``fragment.row`` materializing a ``*Row`` from container
    slices; SURVEY.md §4.2).
    """
    words = np.zeros(n_words, dtype=np.uint32)
    if len(cols) == 0:
        return words
    cols = np.asarray(cols, dtype=np.uint64)
    if cols.max() >= n_words * WORD_BITS:
        raise ValueError(
            f"column {cols.max()} out of range for {n_words * WORD_BITS} bits"
        )
    np.bitwise_or.at(words, (cols >> 5).astype(np.int64),
                     _ONE << (cols & np.uint64(31)).astype(np.uint32))
    return words


def unpack_columns(words: np.ndarray) -> np.ndarray:
    """Inverse of :func:`pack_columns`: set-bit positions, sorted ascending."""
    words = np.ascontiguousarray(words, dtype=np.uint32)
    bits = np.unpackbits(words.view(np.uint8), bitorder="little")
    return np.nonzero(bits)[0].astype(np.uint64)


def bsi_encode(
    cols: np.ndarray,
    values: np.ndarray,
    base: int,
    depth: int,
    n_words: int = WORDS_PER_SHARD,
) -> np.ndarray:
    """Encode (column, int value) pairs into a dense BSI plane.

    Layout matches :mod:`pilosa_tpu.engine.bsi` (exists row, sign row, then
    ``depth`` magnitude bit rows of ``value - base``); the reference
    analogue is ``bsiGroup`` writing one roaring row per bit
    (``field.go#SetValue``, SURVEY.md §3.1).  Returns
    ``uint32[depth + 2, n_words]``.
    """
    plane = np.zeros((depth + 2, n_words), dtype=np.uint32)
    cols = np.asarray(cols, dtype=np.uint64)
    offs = np.asarray(values, dtype=np.int64) - np.int64(base)
    if len(cols) == 0:
        return plane
    mag = np.abs(offs).astype(np.uint64)
    if depth < 64 and mag.max() >= (1 << depth):
        raise ValueError(f"magnitude {mag.max()} exceeds bit depth {depth}")
    plane[0] = pack_columns(cols, n_words)                      # exists
    plane[1] = pack_columns(cols[offs < 0], n_words)            # sign
    for b in range(depth):
        hit = (mag >> np.uint64(b)) & np.uint64(1) != 0
        plane[2 + b] = pack_columns(cols[hit], n_words)
    return plane


def coalesce_updates(
    positions: np.ndarray, n_words: int = WORDS_PER_SHARD
) -> tuple[np.ndarray, np.ndarray]:
    """Reduce raw bit positions to unique ``(word_idx, word_mask)`` pairs.

    Host half of the device mutation path (see
    :func:`pilosa_tpu.engine.kernels.apply_word_or`): XLA scatter with
    duplicate indices has unspecified combine order, so the host ORs all
    bits that land in the same word first.

    Raises on positions outside ``n_words * 32`` bits: the device scatter
    drops out-of-bounds indices as padding, so an unvalidated bad position
    would be a silently lost write.
    """
    positions = np.asarray(positions, dtype=np.uint64)
    if len(positions) == 0:
        return (np.empty(0, np.int64), np.empty(0, np.uint32))
    if positions.max() >= n_words * WORD_BITS:
        raise ValueError(
            f"position {positions.max()} out of range for {n_words * WORD_BITS} bits"
        )
    idx = (positions >> 5).astype(np.int64)
    bit = _ONE << (positions & np.uint64(31)).astype(np.uint32)
    order = np.argsort(idx, kind="stable")
    idx, bit = idx[order], bit[order]
    uniq, starts = np.unique(idx, return_index=True)
    masks = np.bitwise_or.reduceat(bit, starts)
    return uniq, masks


def popcount_words(words: np.ndarray) -> int:
    """Host/numpy popcount oracle (used by tests and the CPU fallback)."""
    words = np.ascontiguousarray(words, dtype=np.uint32)
    return int(np.unpackbits(words.view(np.uint8)).sum())
