"""Bit-sliced integer (BSI) kernels: Range / Sum / Min / Max over bit planes.

Reference: ``field.go#bsiGroup`` + ``fragment.go`` range decomposition
(``fragment.rangeOp``, ``fragment.sum``; SURVEY.md §3.1, §4.4).  The
reference stores an int field as one roaring row per bit position plus an
existence ("not null") row and a sign row, and answers ``Range``/``Sum``
with boolean algebra over those rows.  We keep exactly that encoding — it
is already the right layout for a vector machine — as a dense plane:

    plane: uint32[..., depth + 2, W]
      plane[..., EXISTS_ROW, :]   not-null bitmap
      plane[..., SIGN_ROW, :]     sign bitmap (1 = negative)
      plane[..., OFFSET_ROW+b, :] bit b of |value - base|

Invariant (maintained by the store): a column never has SIGN set with zero
magnitude — there is no negative zero.

Predicates arrive as *traced* scalars/bit-masks so one compiled kernel
serves every predicate value (no recompile per query): ``pred_masks`` is
``uint32[depth]`` with lane-broadcast 0x00000000/0xFFFFFFFF per bit of
``|p|``, built by :func:`predicate_masks`.

All kernels accept arbitrary leading batch axes (the executor batches
``[n_shards, depth+2, W]``).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from pilosa_tpu.engine import _jaxcfg  # noqa: F401  (device int32 policy)
from pilosa_tpu.engine import kernels

EXISTS_ROW = 0
SIGN_ROW = 1
OFFSET_ROW = 2



def depth_of(plane: jax.Array) -> int:
    return plane.shape[-2] - OFFSET_ROW


def predicate_masks(magnitude: int, depth: int) -> np.ndarray:
    """Lane-broadcast per-bit masks of ``|p|`` for :func:`unsigned_cmp`.

    Raises if ``|p|`` does not fit in ``depth`` bits — silently truncating
    would invert comparison results.  Callers (the executor) must saturate
    out-of-depth predicates first: a bound beyond the representable range
    has a trivial answer (everything / nothing) that needs no kernel.
    """
    if magnitude < 0:
        raise ValueError("magnitude must be non-negative")
    if depth < 64 and magnitude >= (1 << depth):
        raise ValueError(f"predicate magnitude {magnitude} exceeds bit depth {depth}")
    bits = [(magnitude >> b) & 1 for b in range(depth)]
    return np.array([0xFFFFFFFF if b else 0 for b in bits], dtype=np.uint32)


def unsigned_cmp(
    mag: jax.Array, pred_masks: jax.Array, universe: jax.Array
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Columns' magnitude vs predicate magnitude: (lt, eq, gt) bitmaps.

    MSB->LSB digital comparison, the same strictly-greater accumulator
    pattern as the reference's ``fragment.rangeOp`` walk (SURVEY.md §4.4),
    vectorized over 2**20 columns at once.

    mag: uint32[..., depth, W]; pred_masks: uint32[depth] (see
    :func:`predicate_masks`); universe: uint32[..., W] — the columns under
    consideration (typically the exists row).
    """
    depth = mag.shape[-2]
    eq = universe
    lt = jnp.zeros_like(universe)
    gt = jnp.zeros_like(universe)
    for b in reversed(range(depth)):
        bitplane = mag[..., b, :]
        pmask = pred_masks[b]
        lt = jnp.bitwise_or(lt, eq & ~bitplane & pmask)
        gt = jnp.bitwise_or(gt, eq & bitplane & ~pmask)
        eq = eq & ~(bitplane ^ pmask)
    return lt, eq, gt


def range_cmp(
    plane: jax.Array,
    pred_masks: jax.Array,
    pred_negative: jax.Array,
    filter_words: jax.Array | None = None,
) -> dict[str, jax.Array]:
    """All six signed comparisons of stored values vs predicate ``p``.

    Returns bitmaps {"lt","le","gt","ge","eq","ne"}; the executor picks one
    (or combines two for between).  ``pred_negative`` is a traced bool
    scalar (sign of p); ``pred_masks`` encodes ``|p|``.
    """
    exists = plane[..., EXISTS_ROW, :]
    if filter_words is not None:
        exists = exists & filter_words
    sign = plane[..., SIGN_ROW, :] & exists
    pos = exists & ~sign
    mag = plane[..., OFFSET_ROW:, :]

    m_lt, m_eq, m_gt = unsigned_cmp(mag, pred_masks, exists)

    # p >= 0: v < p  <=>  v negative, or v >= 0 with |v| < |p|
    lt_nonneg = sign | (pos & m_lt)
    # p < 0:  v < p  <=>  v negative with |v| > |p|
    lt_neg = sign & m_gt
    # p >= 0: v > p  <=>  v >= 0 with |v| > |p|
    gt_nonneg = pos & m_gt
    # p < 0:  v > p  <=>  v >= 0, or v negative with |v| < |p|
    gt_neg = pos | (sign & m_lt)
    eq_signed = jnp.where(pred_negative, sign & m_eq, pos & m_eq)

    lt = jnp.where(pred_negative, lt_neg, lt_nonneg)
    gt = jnp.where(pred_negative, gt_neg, gt_nonneg)
    return {
        "lt": lt,
        "le": lt | eq_signed,
        "gt": gt,
        "ge": gt | eq_signed,
        "eq": eq_signed,
        "ne": exists & ~eq_signed,
    }


def not_null(plane: jax.Array, filter_words: jax.Array | None = None) -> jax.Array:
    exists = plane[..., EXISTS_ROW, :]
    if filter_words is not None:
        exists = exists & filter_words
    return exists


def bit_counts(
    plane: jax.Array, filter_words: jax.Array | None = None
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Per-bit positive/negative popcounts + non-null count, all int32.

    Reference: ``fragment.sum`` decomposition (SURVEY.md §4.4) — per bit
    b, ``popcount(filter & bitrow_b)`` split by sign.  The device stays
    in int32 (each count <= 2^20 per shard); :func:`combine_sum` does the
    ``<< b`` weighting exactly on the host.

    Returns (pos[..., depth], neg[..., depth], count[...]), jit-safe.
    """
    exists = not_null(plane, filter_words)
    sign = plane[..., SIGN_ROW, :] & exists
    pos = exists & ~sign
    mag = plane[..., OFFSET_ROW:, :]
    pos_c = kernels.count(mag & pos[..., None, :])
    neg_c = kernels.count(mag & sign[..., None, :])
    return pos_c, neg_c, kernels.count(exists)


def combine_sum(pos_c, neg_c, cnt) -> tuple[int, int]:
    """Host combine of :func:`bit_counts` outputs over ALL leading axes:
    exact python-int (sum_of_offsets, count)."""
    pos_c = np.asarray(pos_c, dtype=np.int64)
    neg_c = np.asarray(neg_c, dtype=np.int64)
    depth = pos_c.shape[-1]
    flat_p = pos_c.reshape(-1, depth).sum(axis=0)
    flat_n = neg_c.reshape(-1, depth).sum(axis=0)
    total = sum((int(flat_p[b]) - int(flat_n[b])) << b
                for b in range(depth))
    return total, int(np.asarray(cnt, dtype=np.int64).sum())


def sum_count(
    plane: jax.Array, filter_words: jax.Array | None = None
) -> tuple[int, int]:
    """(sum of offsets, count of non-null) over all batch elements —
    device bit counts + exact host combine.  NOT jit-safe (host
    finishing); inside compiled programs use :func:`bit_counts`."""
    return combine_sum(*bit_counts(plane, filter_words))


def _mag_max(cand: jax.Array, mag: jax.Array) -> tuple[jax.Array, jax.Array]:
    """Largest magnitude among candidate columns: (bits bool[..., depth],
    final candidate bitmap).  Data-dependent bit descent done branch-free
    with ``where`` on per-batch "any" scalars (jit/TPU friendly); the
    value is reconstructed exactly on host from the bit flags (int64-free
    device path)."""
    depth = mag.shape[-2]
    bits = []
    for b in reversed(range(depth)):
        hit = cand & mag[..., b, :]
        has = kernels.any_bit(hit)
        cand = jnp.where(has[..., None], hit, cand)
        bits.append(has)
    return jnp.stack(bits[::-1], axis=-1), cand


def _mag_min(cand: jax.Array, mag: jax.Array) -> tuple[jax.Array, jax.Array]:
    """Smallest magnitude among candidate columns (bit flags, see
    :func:`_mag_max`)."""
    depth = mag.shape[-2]
    bits = []
    for b in reversed(range(depth)):
        zero_side = cand & ~mag[..., b, :]
        has_zero = kernels.any_bit(zero_side)
        cand = jnp.where(has_zero[..., None], zero_side, cand)
        bits.append(~has_zero)
    # columns that survived only because no zero-side existed at some bit
    # all share the same magnitude, so the flags are exact
    return jnp.stack(bits[::-1], axis=-1), cand


def min_max_bits(
    plane: jax.Array, filter_words: jax.Array | None = None
) -> dict[str, jax.Array]:
    """Per-batch min/max as device-side bit flags + counts (jit-safe,
    int64-free).  Host reconstruction in :func:`combine_min_max`.

    Reference: ``fragment.min``/``fragment.max`` bit descent (SURVEY.md
    §3.1)."""
    exists = not_null(plane, filter_words)
    sign = plane[..., SIGN_ROW, :] & exists
    pos = exists & ~sign
    mag = plane[..., OFFSET_ROW:, :]

    has_neg = kernels.any_bit(sign)
    has_pos = kernels.any_bit(pos)

    # min: most-negative (largest |.| among negatives) else smallest positive
    neg_bits, neg_cand = _mag_max(sign, mag)
    posmin_bits, posmin_cand = _mag_min(pos, mag)
    min_bits = jnp.where(has_neg[..., None], neg_bits, posmin_bits)
    min_cand = jnp.where(has_neg[..., None], neg_cand, posmin_cand)
    min_cnt = jnp.where(has_neg | has_pos, kernels.count(min_cand), 0)

    # max: largest positive else least-negative (smallest |.| among negatives)
    posmax_bits, posmax_cand = _mag_max(pos, mag)
    negmin_bits, negmin_cand = _mag_min(sign, mag)
    max_bits = jnp.where(has_pos[..., None], posmax_bits, negmin_bits)
    max_cand = jnp.where(has_pos[..., None], posmax_cand, negmin_cand)
    max_cnt = jnp.where(has_neg | has_pos, kernels.count(max_cand), 0)

    return {"min_bits": min_bits, "min_neg": has_neg, "min_cnt": min_cnt,
            "max_bits": max_bits, "max_neg": has_neg & ~has_pos,
            "max_cnt": max_cnt}


def combine_min_max(out: dict) -> list[tuple[int, int, int, int]]:
    """Host reconstruction of :func:`min_max_bits` per batch element:
    [(min_value, min_count, max_value, max_count), ...] exact python
    ints (offsets relative to base; counts 0 = no non-null columns)."""
    min_bits = np.asarray(out["min_bits"]).reshape(-1,
                                                   out["min_bits"].shape[-1])
    max_bits = np.asarray(out["max_bits"]).reshape(-1,
                                                   out["max_bits"].shape[-1])
    min_neg = np.asarray(out["min_neg"]).reshape(-1)
    max_neg = np.asarray(out["max_neg"]).reshape(-1)
    min_cnt = np.asarray(out["min_cnt"]).reshape(-1)
    max_cnt = np.asarray(out["max_cnt"]).reshape(-1)

    def val(bits) -> int:
        return sum(1 << b for b, hit in enumerate(bits) if hit)

    res = []
    for i in range(len(min_neg)):
        mn = -val(min_bits[i]) if min_neg[i] else val(min_bits[i])
        mx = -val(max_bits[i]) if max_neg[i] else val(max_bits[i])
        res.append((mn, int(min_cnt[i]), mx, int(max_cnt[i])))
    return res


# Shards per distinct_presence scan step: bounds the program's scratch
# (per-column decoded values are 4 B/col — an UNBLOCKED expansion of a
# 1B-col field materialized ~4 GB values + ~9 GB masks/indices and
# OOM'd a 16 GB chip; found by bench/config16 r5).  32 shards ≈ 0.5 GB
# peak per step.
DISTINCT_BLOCK = 32

# Value-space cutover: at depth <= this, presence is computed per VALUE
# on packed words (bit-plane XNOR-AND algebra — no per-column decode,
# no scatter; work ∝ 2^depth × plane, 14 s → sub-second at depth 7 /
# 1B cols).  Deeper fields keep the column-scatter scan (work ∝ cols).
DISTINCT_VALUE_DEPTH = 10
_DISTINCT_VALUE_BLOCK = 8  # values per scan step (scratch ∝ block×plane)


def distinct_presence(
    plane: jax.Array, filter_words: jax.Array | None = None
) -> tuple[jax.Array, jax.Array]:
    """Presence bitmaps over the value space: which offsets occur among
    the (filtered) columns — the device core of ``Distinct`` (v2 PQL).

    Scans shard blocks (``DISTINCT_BLOCK`` per step): each step expands
    its block's magnitudes from the bit planes and scatters into the
    carried boolean presence arrays of size ``2^depth`` (positive and
    negative offsets separately), so scratch stays per-block no matter
    the field size.  Requires ``depth <= 24`` (a 16M-entry presence
    array); the executor enforces the cap.

    plane: uint32[S, depth+2, W] -> (pos bool[2^depth], neg bool[2^depth]).
    """
    depth = depth_of(plane)
    if depth <= DISTINCT_VALUE_DEPTH:
        return _distinct_by_value(plane, filter_words)
    size = 1 << depth
    s, rows, w = plane.shape
    block = min(DISTINCT_BLOCK, s)
    pad = (-s) % block
    if pad:
        # zero shards: exists=0 -> every column maps to the dropped
        # sentinel, so padding never adds presence
        plane = jnp.concatenate(
            [plane, jnp.zeros((pad, rows, w), plane.dtype)])
        if filter_words is not None:
            filter_words = jnp.concatenate(
                [filter_words, jnp.zeros((pad, w), filter_words.dtype)])
    n_blocks = plane.shape[0] // block
    plane_blocks = plane.reshape(n_blocks, block, rows, w)
    fw_blocks = (jnp.zeros((n_blocks, 0), plane.dtype)
                 if filter_words is None
                 else filter_words.reshape(n_blocks, block, w))

    def expand(words: jax.Array) -> jax.Array:
        # uint32[..., W] -> uint32[..., W*32] (column-major LSB-first)
        bits = (words[..., None] >> jnp.arange(32, dtype=jnp.uint32)) & 1
        return bits.reshape(*words.shape[:-1], -1)

    def step(carry, inputs):
        pos, neg = carry
        pl, fw = inputs
        exists = not_null(pl, fw if fw.size else None)
        sign = pl[..., SIGN_ROW, :] & exists
        mag = pl[..., OFFSET_ROW:, :]
        values = jnp.zeros((block, w * 32), dtype=jnp.uint32)
        for b in range(depth):
            values = values | (expand(mag[..., b, :]) << b)
        exists_b = expand(exists).astype(bool)
        sign_b = expand(sign).astype(bool)
        # out-of-range sentinel drops non-participating columns
        pos_idx = jnp.where(exists_b & ~sign_b, values, size)
        neg_idx = jnp.where(exists_b & sign_b, values, size)
        pos = pos.at[pos_idx.reshape(-1)].set(True, mode="drop")
        neg = neg.at[neg_idx.reshape(-1)].set(True, mode="drop")
        return (pos, neg), None

    init = (jnp.zeros(size, bool), jnp.zeros(size, bool))
    (pos, neg), _ = jax.lax.scan(step, init, (plane_blocks, fw_blocks))
    return pos, neg


def _distinct_by_value(plane: jax.Array,
                       filter_words: jax.Array | None):
    """Small-value-space Distinct: for each magnitude ``v`` the match
    words are ``AND_b (bit_b(v) ? mag_b : ~mag_b) & exists`` — packed
    32-cols-per-word algebra, scanned ``_DISTINCT_VALUE_BLOCK`` values
    per step.  presence[v] = any match word nonzero, split by sign."""
    depth = depth_of(plane)
    size = 1 << depth
    exists = not_null(plane, filter_words)
    sign = plane[..., SIGN_ROW, :] & exists
    mag = plane[..., OFFSET_ROW:, :]
    vb = min(_DISTINCT_VALUE_BLOCK, size)
    vals = jnp.arange(size, dtype=jnp.uint32).reshape(-1, vb)

    def step(_, block_vals):
        m = jnp.broadcast_to(exists, (vb,) + exists.shape)
        for b in range(depth):
            pb = mag[..., b, :]
            bit = ((block_vals >> b) & 1).astype(bool)
            m = m & jnp.where(bit[:, None, None], pb, ~pb)
        pos = jnp.any((m & ~sign).astype(bool), axis=(1, 2))
        neg = jnp.any((m & sign).astype(bool), axis=(1, 2))
        return None, (pos, neg)

    _, (pos, neg) = jax.lax.scan(step, None, vals)
    return pos.reshape(-1), neg.reshape(-1)


def min_max(
    plane: jax.Array, filter_words: jax.Array | None = None
) -> list[tuple[int, int, int, int]]:
    """Per-batch (min_offset, min_count, max_offset, max_count) — device
    bit descent + exact host reconstruction.  NOT jit-safe; inside
    compiled programs use :func:`min_max_bits`."""
    return combine_min_max(min_max_bits(plane, filter_words))


def decode_sum_packed(row: np.ndarray) -> tuple[int, int]:
    """Host decode of one ``fused.run_sum_batch`` row
    (int32[n_shards, 2*depth+1]) -> exact (sum of offsets, count)."""
    depth = (row.shape[-1] - 1) // 2
    return combine_sum(row[:, :depth], row[:, depth:2 * depth], row[:, -1])


def decode_minmax_packed(row: np.ndarray):
    """Host decode of one ``fused.run_minmax_plane_batch`` row
    (int32[n_shards (+ overlay columns), 2*depth+4]) -> per-entry
    (min, min_cnt, max, max_cnt) tuples (zero-count entries are
    dropped by the caller's combine)."""
    depth = (row.shape[-1] - 4) // 2
    return combine_min_max({
        "min_bits": row[:, :depth],
        "max_bits": row[:, depth:2 * depth],
        "min_neg": row[:, 2 * depth].astype(bool),
        "min_cnt": row[:, 2 * depth + 1],
        "max_neg": row[:, 2 * depth + 2].astype(bool),
        "max_cnt": row[:, 2 * depth + 3]})


# ---------------------------------------------------------------------------
# Percentile: the whole binary search on device, one dispatch
# ---------------------------------------------------------------------------


def _count_le_device(plane: jax.Array, filter_words, v: jax.Array,
                     depth: int) -> jax.Array:
    """count of columns with stored offset <= signed ``v`` — traced-value
    variant of the executor's compare path (one :func:`range_cmp` with
    masks derived from the traced scalar instead of host-built)."""
    neg = v < 0
    mag_v = jnp.abs(v).astype(jnp.uint32)
    bits = (mag_v >> jnp.arange(depth, dtype=jnp.uint32)) & jnp.uint32(1)
    masks = jnp.where(bits > 0, jnp.uint32(0xFFFFFFFF), jnp.uint32(0))
    le = range_cmp(plane, masks, neg, filter_words)["le"]
    # int32-exact: total bits <= n_shards * 2^20 < 2^31 for <= 2047 shards
    return jnp.sum(kernels.popcount(le), dtype=jnp.int32)


def percentile_total(plane: jax.Array,
                     filter_words: jax.Array | None) -> jax.Array:
    """Non-null (filtered) column count, int32 — the rank universe for
    :func:`percentile_search`.  The host computes the exact integer
    target rank from this (device float32 would misround products past
    2^24; int64 is emulated on TPU)."""
    return jnp.sum(kernels.popcount(not_null(plane, filter_words)),
                   dtype=jnp.int32)


def percentile_search(plane: jax.Array, filter_words: jax.Array | None,
                      target: jax.Array):
    """[offset, count_at_offset] stacked int32: the smallest stored
    offset whose ``count_le`` reaches ``target`` — the whole binary
    search as ONE program via ``lax.while_loop`` over compare+popcount
    steps (the reference's ``executeSumCountShard``-style per-step
    dispatch pays a device round trip per bit of depth; SURVEY.md §4.4).

    ``target`` is a traced int32 rank >= 1 (exact, host-computed).

    Iteration is bounded STATICALLY by the bit depth (r20): the
    search interval is ``2^(depth+1) - 1`` wide and halves per step,
    so ``depth + 1`` steps always converge — a ``fori_loop`` with
    that trip count replaces the data-dependent ``while_loop``, which
    XLA must lower as a device-side dynamic loop with a convergence
    check per step (the fori form's trip count is auditable and it
    unrolls/pipelines freely).  Converged steps are no-ops (``lo >=
    hi`` keeps both bounds via the ``where``).  Microbench (CPU, 4
    shards × depth 16, warm programs): host-driven bisection pays 17
    device dispatches/call at 8.0 ms; this one cached program answers
    in 3.7 ms — 2.2x, and on the ~100 ms/read tunneled transport the
    gap is the read count itself (18 reads → 2)."""
    depth = depth_of(plane)
    bound = (1 << depth) - 1

    def body(_, state):
        lo, hi = state
        mid = (lo + hi) >> 1  # arithmetic shift: floor for negatives
        le = _count_le_device(plane, filter_words, mid, depth)
        done = lo >= hi
        new_lo = jnp.where(done | (le >= target), lo, mid + 1)
        new_hi = jnp.where(done, hi, jnp.where(le >= target, mid, hi))
        return new_lo, new_hi

    lo, _ = jax.lax.fori_loop(
        0, depth + 1, body, (jnp.int32(-bound), jnp.int32(bound)))
    at = _count_le_device(plane, filter_words, lo, depth)
    below = jnp.where(
        lo > -bound,
        _count_le_device(plane, filter_words, lo - 1, depth), 0)
    return jnp.stack([lo, at - below])
