"""Pallas/Mosaic TPU kernels for the popcount hot loop.

The XLA-fused kernels in :mod:`pilosa_tpu.engine.kernels` are the
default compute path; these Pallas variants give explicit control of
the HBM→VMEM streaming and accumulation for the two hottest shapes
(reference hot loops: container-pairwise intersect kernels and the
popcount matrix behind TopN, ``roaring/roaring.go`` /
``fragment.top``; SURVEY.md §4.2–4.3):

- :func:`intersect_count`: ``uint32[S, W] × uint32[S, W] → int32[S]``
  (and + popcount + per-shard reduce, one VMEM pass);
- :func:`row_counts`: ``uint32[S, R, W] (× filter) → int32[S, R]``
  (the TopN matrix), gridded over shards × row blocks so each block
  streams ~1MB through VMEM;
- :func:`count`: ``uint32[S, W] → int32[S]`` (the whole-bitmap count
  chain), word-blocked so a wide scan accumulates through VMEM-sized
  tiles like :func:`kernels.count`'s tiled reduce;
- :func:`selected_row_counts`: ``uint32[S, R, W] + int32[N] →
  int32[S, N]`` — the TopN/product gather scan.  The slot list rides
  the scalar-prefetch channel so Mosaic knows the next gathered row
  block before the grid step runs (matches
  ``kernels.selected_row_counts``'s sorted-slot contract: ascending
  slots walk the row axis in ascending stride order).

These are the ``kernel_tier="pallas"`` serving tier: ``exec/fused.py``
routes the hottest fused families here when the knob is on, keeping
the XLA kernels as the correctness oracle and fallback.  Delta-overlay
adjustment (base⊕delta) stays one program: the fused layer composes
these base scans with the overlay scatter inside a single jit.

Popcount uses the SWAR bit-twiddling reduction (shift/mask adds) —
portable across Mosaic versions regardless of ``population_count``
support.  Tests run the same kernels in interpreter mode on CPU
against the numpy oracle; on TPU they compile to Mosaic.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

def _popcount_u32(x: jax.Array) -> jax.Array:
    """SWAR popcount per uint32 lane -> int32.  Masks are weak python
    ints (pallas kernels must not close over concrete arrays)."""
    x = x - ((x >> 1) & 0x55555555)
    x = (x & 0x33333333) + ((x >> 2) & 0x33333333)
    x = (x + (x >> 4)) & 0x0F0F0F0F
    # sum the 4 bytes via shifts (byte values <= 8, no overflow)
    x = x + (x >> 8)
    x = x + (x >> 16)
    return (x & 0x3F).astype(jnp.int32)


def _intersect_count_kernel(a_ref, b_ref, out_ref):
    words = a_ref[...] & b_ref[...]
    out_ref[...] = jnp.sum(_popcount_u32(words), axis=-1, keepdims=True)


@functools.partial(jax.jit, static_argnames=("interpret",))
def intersect_count(a: jax.Array, b: jax.Array,
                    interpret: bool = False) -> jax.Array:
    """Count(Intersect) per shard: uint32[S, W] x2 -> int32[S].

    Shards stream in blocks of 8 (Mosaic requires the sublane block dim
    divisible by 8); each grid step moves 2x8x4W bytes through VMEM.
    """
    s, w = a.shape
    sb = 8
    pad = (-s) % sb
    if pad:
        a = jnp.pad(a, ((0, pad), (0, 0)))
        b = jnp.pad(b, ((0, pad), (0, 0)))
    s_pad = s + pad
    out = pl.pallas_call(
        _intersect_count_kernel,
        grid=(s_pad // sb,),
        in_specs=[pl.BlockSpec((sb, w), lambda i: (i, 0)),
                  pl.BlockSpec((sb, w), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((sb, 1), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((s_pad, 1), jnp.int32),
        interpret=interpret,
    )(a, b)
    return out[:s, 0]


_SB = 8      # shard block (Mosaic sublane granule)
_RB = 128    # row block (int32 lane granule)
_WB = 1024   # word block: 8 x 128 x 1024 x 4B = 4MB tile through VMEM


def _row_counts_kernel(plane_ref, filter_ref, out_ref):
    k = pl.program_id(2)
    # plane (SB, rb, wb) & filter (SB, 1, wb) -> broadcast over rows
    words = plane_ref[...] & filter_ref[...]
    counts = jnp.sum(_popcount_u32(words), axis=-1)  # (SB, rb)

    @pl.when(k == 0)
    def _init():
        out_ref[...] = counts

    @pl.when(k != 0)
    def _acc():
        out_ref[...] += counts


@functools.partial(jax.jit, static_argnames=("interpret",))
def row_counts(plane: jax.Array, filter_words: jax.Array | None = None,
               interpret: bool = False) -> jax.Array:
    """Per-row popcounts (the TopN matrix): uint32[S, R, W] -> int32[S, R].

    Grid (shard blocks, row blocks, word blocks): each step streams an
    8-shard x <=128-row x 1K-word tile (4MB) through VMEM; the output
    tile is indexed (i, j) only, so it persists across the innermost
    word-block axis and accumulates partial counts.
    """
    s, r, w = plane.shape
    if filter_words is None:
        filter_words = jnp.full((s, w), 0xFFFFFFFF, dtype=jnp.uint32)
    # rows pad to one full block (<=128 rows) or to 128-row blocks
    rb = r if r <= _RB else _RB
    s_pad, r_pad = (-s) % _SB, (-r) % rb
    # words pad with zeros to a _WB multiple (zero words popcount to
    # zero under any filter) — NEVER stream the whole word axis in one
    # grid step: an 8 x 128 x w tile blows the ~4MB VMEM budget at
    # real plane widths when w % _WB != 0
    wb, w_pad = (w, 0) if w <= _WB else (_WB, (-w) % _WB)
    if s_pad or r_pad or w_pad:
        plane = jnp.pad(plane, ((0, s_pad), (0, r_pad), (0, w_pad)))
        filter_words = jnp.pad(filter_words, ((0, s_pad), (0, w_pad)))
    sp, rp, wp = s + s_pad, r + r_pad, w + w_pad
    filt3 = filter_words.reshape(sp, 1, wp)
    out = pl.pallas_call(
        _row_counts_kernel,
        grid=(sp // _SB, rp // rb, wp // wb),
        in_specs=[
            pl.BlockSpec((_SB, rb, wb), lambda i, j, k: (i, j, k)),
            pl.BlockSpec((_SB, 1, wb), lambda i, j, k: (i, 0, k)),
        ],
        out_specs=pl.BlockSpec((_SB, rb), lambda i, j, k: (i, j)),
        out_shape=jax.ShapeDtypeStruct((sp, rp), jnp.int32),
        interpret=interpret,
    )(plane, filt3)
    return out[:s, :r]


_CWB = 128 * 1024  # count word block: 8 x 128K x 4B = 4MB tile


def _count_kernel(w_ref, out_ref):
    k = pl.program_id(1)
    counts = jnp.sum(_popcount_u32(w_ref[...]), axis=-1, keepdims=True)

    @pl.when(k == 0)
    def _init():
        out_ref[...] = counts

    @pl.when(k != 0)
    def _acc():
        out_ref[...] += counts


@functools.partial(jax.jit, static_argnames=("interpret",))
def count(words: jax.Array, interpret: bool = False) -> jax.Array:
    """Whole-bitmap count chain: uint32[S, W] -> int32[S].

    The Pallas face of :func:`kernels.count`'s tiled reduce — grid
    (shard blocks, word blocks), the output tile indexed by shard
    block only so it persists across the word axis and accumulates
    partial popcounts (each step streams a <=4MB tile through VMEM).
    """
    s, w = words.shape
    s_pad = (-s) % _SB
    wb, w_pad = (w, 0) if w <= _CWB else (_CWB, (-w) % _CWB)
    if s_pad or w_pad:
        words = jnp.pad(words, ((0, s_pad), (0, w_pad)))
    sp, wp = s + s_pad, w + w_pad
    out = pl.pallas_call(
        _count_kernel,
        grid=(sp // _SB, wp // wb),
        in_specs=[pl.BlockSpec((_SB, wb), lambda i, k: (i, k))],
        out_specs=pl.BlockSpec((_SB, 1), lambda i, k: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((sp, 1), jnp.int32),
        interpret=interpret,
    )(words)
    return out[:s, 0]


def _selected_kernel(idx_ref, plane_ref, out_ref):
    del idx_ref  # consumed by the index maps
    k = pl.program_id(2)
    counts = jnp.sum(_popcount_u32(plane_ref[...]), axis=-1)  # (SB, 1)

    @pl.when(k == 0)
    def _init():
        out_ref[...] = counts

    @pl.when(k != 0)
    def _acc():
        out_ref[...] += counts


@functools.partial(jax.jit, static_argnames=("interpret",))
def selected_row_counts(plane: jax.Array, row_idx: jax.Array,
                        interpret: bool = False) -> jax.Array:
    """Gathered-row popcounts: uint32[S, R, W] + int32[N] -> int32[S, N].

    The Pallas face of :func:`kernels.selected_row_counts`: the slot
    list rides the scalar-prefetch channel, so each grid step's block
    index map reads ``idx_ref[j]`` and Mosaic can start the next
    gathered row block's HBM→VMEM copy before the step runs.  Sorted
    ascending slots (the fused layer's contract) make those copies
    walk the row axis in ascending stride order.  Slots may repeat
    (padded asks); each output column accumulates independently.
    """
    s, r, w = plane.shape
    n = row_idx.shape[0]
    s_pad = (-s) % _SB
    wb, w_pad = (w, 0) if w <= _WB else (_WB, (-w) % _WB)
    if s_pad or w_pad:
        plane = jnp.pad(plane, ((0, s_pad), (0, 0), (0, w_pad)))
    sp, wp = s + s_pad, w + w_pad
    idx = row_idx.astype(jnp.int32)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(n, sp // _SB, wp // wb),
        in_specs=[pl.BlockSpec((_SB, 1, wb),
                               lambda j, i, k, idx_ref: (i, idx_ref[j], k))],
        out_specs=pl.BlockSpec((_SB, 1), lambda j, i, k, idx_ref: (i, j)),
    )
    out = pl.pallas_call(
        _selected_kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((sp, n), jnp.int32),
        interpret=interpret,
    )(idx, plane)
    return out[:s]
