"""Pallas/Mosaic TPU kernels for the popcount hot loop.

The XLA-fused kernels in :mod:`pilosa_tpu.engine.kernels` are the
default compute path; these Pallas variants give explicit control of
the HBM→VMEM streaming and accumulation for the two hottest shapes
(reference hot loops: container-pairwise intersect kernels and the
popcount matrix behind TopN, ``roaring/roaring.go`` /
``fragment.top``; SURVEY.md §4.2–4.3):

- :func:`intersect_count`: ``uint32[S, W] × uint32[S, W] → int32[S]``
  (and + popcount + per-shard reduce, one VMEM pass);
- :func:`row_counts`: ``uint32[S, R, W] (× filter) → int32[S, R]``
  (the TopN matrix), gridded over shards × row blocks so each block
  streams ~1MB through VMEM.

Popcount uses the SWAR bit-twiddling reduction (shift/mask adds) —
portable across Mosaic versions regardless of ``population_count``
support.  Tests run the same kernels in interpreter mode on CPU
against the numpy oracle; on TPU they compile to Mosaic.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

def _popcount_u32(x: jax.Array) -> jax.Array:
    """SWAR popcount per uint32 lane -> int32.  Masks are weak python
    ints (pallas kernels must not close over concrete arrays)."""
    x = x - ((x >> 1) & 0x55555555)
    x = (x & 0x33333333) + ((x >> 2) & 0x33333333)
    x = (x + (x >> 4)) & 0x0F0F0F0F
    # sum the 4 bytes via shifts (byte values <= 8, no overflow)
    x = x + (x >> 8)
    x = x + (x >> 16)
    return (x & 0x3F).astype(jnp.int32)


def _intersect_count_kernel(a_ref, b_ref, out_ref):
    words = a_ref[...] & b_ref[...]
    out_ref[...] = jnp.sum(_popcount_u32(words), axis=-1, keepdims=True)


@functools.partial(jax.jit, static_argnames=("interpret",))
def intersect_count(a: jax.Array, b: jax.Array,
                    interpret: bool = False) -> jax.Array:
    """Count(Intersect) per shard: uint32[S, W] x2 -> int32[S].

    Shards stream in blocks of 8 (Mosaic requires the sublane block dim
    divisible by 8); each grid step moves 2x8x4W bytes through VMEM.
    """
    s, w = a.shape
    sb = 8
    pad = (-s) % sb
    if pad:
        a = jnp.pad(a, ((0, pad), (0, 0)))
        b = jnp.pad(b, ((0, pad), (0, 0)))
    s_pad = s + pad
    out = pl.pallas_call(
        _intersect_count_kernel,
        grid=(s_pad // sb,),
        in_specs=[pl.BlockSpec((sb, w), lambda i: (i, 0)),
                  pl.BlockSpec((sb, w), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((sb, 1), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((s_pad, 1), jnp.int32),
        interpret=interpret,
    )(a, b)
    return out[:s, 0]


_SB = 8      # shard block (Mosaic sublane granule)
_RB = 128    # row block (int32 lane granule)
_WB = 1024   # word block: 8 x 128 x 1024 x 4B = 4MB tile through VMEM


def _row_counts_kernel(plane_ref, filter_ref, out_ref):
    k = pl.program_id(2)
    # plane (SB, rb, wb) & filter (SB, 1, wb) -> broadcast over rows
    words = plane_ref[...] & filter_ref[...]
    counts = jnp.sum(_popcount_u32(words), axis=-1)  # (SB, rb)

    @pl.when(k == 0)
    def _init():
        out_ref[...] = counts

    @pl.when(k != 0)
    def _acc():
        out_ref[...] += counts


@functools.partial(jax.jit, static_argnames=("interpret",))
def row_counts(plane: jax.Array, filter_words: jax.Array | None = None,
               interpret: bool = False) -> jax.Array:
    """Per-row popcounts (the TopN matrix): uint32[S, R, W] -> int32[S, R].

    Grid (shard blocks, row blocks, word blocks): each step streams an
    8-shard x <=128-row x 1K-word tile (4MB) through VMEM; the output
    tile is indexed (i, j) only, so it persists across the innermost
    word-block axis and accumulates partial counts.
    """
    s, r, w = plane.shape
    if filter_words is None:
        filter_words = jnp.full((s, w), 0xFFFFFFFF, dtype=jnp.uint32)
    # rows pad to one full block (<=128 rows) or to 128-row blocks
    rb = r if r <= _RB else _RB
    s_pad, r_pad = (-s) % _SB, (-r) % rb
    wb = _WB if w % _WB == 0 else w
    if s_pad or r_pad:
        plane = jnp.pad(plane, ((0, s_pad), (0, r_pad), (0, 0)))
        filter_words = jnp.pad(filter_words, ((0, s_pad), (0, 0)))
    sp, rp = s + s_pad, r + r_pad
    filt3 = filter_words.reshape(sp, 1, w)
    out = pl.pallas_call(
        _row_counts_kernel,
        grid=(sp // _SB, rp // rb, w // wb),
        in_specs=[
            pl.BlockSpec((_SB, rb, wb), lambda i, j, k: (i, j, k)),
            pl.BlockSpec((_SB, 1, wb), lambda i, j, k: (i, 0, k)),
        ],
        out_specs=pl.BlockSpec((_SB, rb), lambda i, j, k: (i, j)),
        out_shape=jax.ShapeDtypeStruct((sp, rp), jnp.int32),
        interpret=interpret,
    )(plane, filt3)
    return out[:s, :r]
