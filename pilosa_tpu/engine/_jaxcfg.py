"""One-time JAX runtime configuration for the compute path.

Imported by every jax-using engine module (kernels, bsi, mesh) and nothing
else, so ``import pilosa_tpu`` stays side-effect free while any actual
device compute gets x64 reductions (cluster-wide counts on 1B+ columns
exceed int32; see engine/__init__ docstring).
"""

import jax

jax.config.update("jax_enable_x64", True)
