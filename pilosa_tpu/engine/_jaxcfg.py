"""One-time JAX runtime configuration for the compute path.

Deliberately does NOT enable global x64: TPUs have no native int64 —
under ``jax_enable_x64`` every count/reduce lowers to emulated 64-bit
arithmetic, measured ~1000x slower than int32 on the popcount matrix
path.  The engine's contract instead is:

- device accumulations are int32, which is always exact per
  (shard, row): one shard holds 2^20 columns, so any per-shard popcount
  fits comfortably (2^20 << 2^31);
- cross-shard totals that could exceed int32 (>2047 full shards ≈ 2.1B
  columns) are finished on the HOST in int64/python ints — see
  ``engine.kernels.shard_totals`` and the host combine helpers in
  ``engine.bsi``.
"""

import os
import warnings

import jax  # noqa: F401  (kept as the single config hook point)

# Donated ping-pong buffer chains (r17): the chain families pass a
# retired output buffer as a donated scratch argument so consecutive
# dispatches reuse its device memory instead of allocating fresh
# output each window.  The CPU backend (the tier-1 test platform)
# ignores the donation and warns per dispatch; the fallback is
# correct, so the warning is noise there — but ONLY there: on TPU a
# donation that cannot alias is a silent perf regression, so the
# warning must stay audible.  Env-gated (not jax.default_backend())
# to avoid initializing backends at import time.
if os.environ.get("JAX_PLATFORMS", "").strip().lower().startswith("cpu"):
    warnings.filterwarnings(
        "ignore", message="Some donated buffers were not usable")
