"""Core bitmap kernels: XLA bitwise + popcount over packed uint32 words.

These replace the reference's pairwise container kernels — nine
container-type-pair specializations per op like ``intersectArrayBitmap`` /
``unionBitmapBitmap`` / ``intersectionCountArrayRun`` in
``roaring/roaring.go`` (SURVEY.md §3.1) — with single dense ops that XLA
fuses end-to-end (e.g. ``Intersect + Count`` compiles to one
and+popcount+reduce pass at HBM bandwidth).

All kernels are shape-polymorphic over leading batch axes: a "bitmap" is
``uint32[..., W]`` where the trailing axis is packed words.  The executor
batches ``[n_shards, W]`` (one row across resident shards) or
``[n_shards, n_rows, W]`` (a whole field plane) and the same kernels apply.

Counts are ``int32`` on device — always exact per (shard, row) since a
shard is 2^20 columns — and finished in int64 on the host where
cluster-wide totals could overflow (:func:`shard_totals`).  TPUs have no
native int64; keeping the device path int32 avoids ~1000x emulation
overhead on the popcount matrix (see ``engine._jaxcfg``).

Kernel tiers (r24): this module is the XLA ORACLE tier — the default
serving tier, the bit-exactness reference every other tier is tested
against, and the path degraded serving and Pallas lowering failures
always fall back to.  ``engine.pallas_kernels`` carries the optional
hand-written Pallas tier the executor's ``kernel_tier="pallas"`` knob
selects for the hottest fused families.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from pilosa_tpu.engine import _jaxcfg  # noqa: F401  (device int32 policy)

# ---------------------------------------------------------------------------
# Boolean algebra (reference: roaring.Bitmap Intersect/Union/Difference/Xor)
# ---------------------------------------------------------------------------


def intersect(a: jax.Array, b: jax.Array) -> jax.Array:
    return jnp.bitwise_and(a, b)


def union(a: jax.Array, b: jax.Array) -> jax.Array:
    return jnp.bitwise_or(a, b)


def difference(a: jax.Array, b: jax.Array) -> jax.Array:
    return jnp.bitwise_and(a, jnp.bitwise_not(b))


def xor(a: jax.Array, b: jax.Array) -> jax.Array:
    return jnp.bitwise_xor(a, b)


def complement(a: jax.Array, exists: jax.Array) -> jax.Array:
    """``Not(a)`` against an existence bitmap (reference: ``Not`` via the
    ``_exists`` field ANDNOT, ``executor.go#executeNot``; SURVEY.md §3.2)."""
    return jnp.bitwise_and(exists, jnp.bitwise_not(a))


# ---------------------------------------------------------------------------
# Popcount / Count (reference: Bitmap.Count, IntersectionCount)
# ---------------------------------------------------------------------------


def popcount(words: jax.Array) -> jax.Array:
    return lax.population_count(words)


# words per inner reduce tile of the popcount chain (r17 roofline
# chase).  A flat jnp.sum over a 32K-word trailing axis emits one long
# serial int32 accumulation chain per (shard, row); splitting the axis
# into COUNT_TILE-word tiles reduced innermost-first gives the
# vectorizer W/COUNT_TILE independent partial sums to interleave
# (measured per-kind in bench/config23's before/after detail).  Exact
# at any tiling: every partial sum of per-word popcounts (<=32 each)
# stays far under int32.
COUNT_TILE = 512


def count_ref(words: jax.Array) -> jax.Array:
    """Flat single-pass reduce — the pre-r17 :func:`count`, kept as
    the before-side of config23's per-kernel before/after sweep."""
    return jnp.sum(popcount(words), axis=-1, dtype=jnp.int32)


def count(words: jax.Array) -> jax.Array:
    """Total set bits over the trailing word axis -> int32[...] (exact:
    one shard's 2^20 bits << 2^31)."""
    w = words.shape[-1]
    if w >= 2 * COUNT_TILE and w % COUNT_TILE == 0:
        tiles = words.reshape(words.shape[:-1] + (w // COUNT_TILE,
                                                  COUNT_TILE))
        inner = jnp.sum(popcount(tiles), axis=-1, dtype=jnp.int32)
        return jnp.sum(inner, axis=-1, dtype=jnp.int32)
    return jnp.sum(popcount(words), axis=-1, dtype=jnp.int32)


def intersection_count(a: jax.Array, b: jax.Array) -> jax.Array:
    """Fused and+popcount+sum (reference: ``Bitmap.IntersectionCount`` — the
    no-materialize fast path used by ``Count(Intersect(..))``)."""
    return count(jnp.bitwise_and(a, b))


def union_count(a: jax.Array, b: jax.Array) -> jax.Array:
    return count(jnp.bitwise_or(a, b))


def difference_count(a: jax.Array, b: jax.Array) -> jax.Array:
    return count(jnp.bitwise_and(a, jnp.bitwise_not(b)))


def xor_count(a: jax.Array, b: jax.Array) -> jax.Array:
    return count(jnp.bitwise_xor(a, b))


def any_bit(words: jax.Array) -> jax.Array:
    """True if any bit set over trailing axis (reference: ``Bitmap.Any``)."""
    return jnp.any(words != 0, axis=-1)


# ---------------------------------------------------------------------------
# Plane-level kernels: one field's rows as uint32[..., n_rows, W]
# ---------------------------------------------------------------------------


def row_counts(plane: jax.Array, filter_words: jax.Array | None = None) -> jax.Array:
    """Per-row popcounts, optionally intersected with a filter bitmap.

    This is the brute-force TPU replacement for the reference's per-fragment
    rank/LRU TopN cache (``cache.go#RankCache``, ``fragment.top``; SURVEY.md
    §3.2/§4.3): recount every row at HBM bandwidth instead of maintaining a
    cache + two-phase threshold protocol.

    plane: uint32[..., R, W]; filter: uint32[..., W] -> int32[..., R].
    """
    if filter_words is not None:
        plane = jnp.bitwise_and(plane, filter_words[..., None, :])
    return count(plane)


def selected_row_counts(plane: jax.Array, row_idx: jax.Array,
                        sorted_idx: bool = False) -> jax.Array:
    """Popcounts of N SELECTED rows in one pass over only their memory.

    plane: uint32[..., R, W]; row_idx: int32[N] -> int32[..., N].

    The multi-query fused popcount (ROADMAP item 5): where
    :func:`row_counts` scans every row of the plane to answer any
    subset, this gathers exactly the requested rows — one memory pass
    over ``N/R`` of the plane, N accumulators — so a batch of Counts
    touching a small fraction of a wide plane stops paying the whole
    plane's bandwidth.  ``row_idx`` is a traced operand: one compiled
    program serves any row selection of the same width.  Duplicate
    indices are fine (each answers independently); indices must be in
    range (callers resolve through the plane's slot map first).

    ``sorted_idx`` is a STATIC promise (part of the compiled program)
    that the traced indices arrive in non-decreasing order, letting
    the gather walk the row axis in ascending memory stride instead of
    request order (r17 roofline chase — the batcher sorts its slot
    unions before dispatch).  A program compiled with the promise must
    never be fed unsorted indices.
    """
    sel = jnp.take(plane, row_idx, axis=-2,
                   indices_are_sorted=sorted_idx)
    return count(sel)


# ---------------------------------------------------------------------------
# Whole-tree boolean programs (compound PQL compilation, ROADMAP item 3)
# ---------------------------------------------------------------------------
#
# A compound boolean query (``Count(Intersect(Row, Union(Row, Row),
# Not(Row)))``) evaluates here as ONE kernel: the operand bitmaps are a
# stacked ``uint32[G, ..., W]`` array (rows gathered from a resident
# plane plus any extra bitmaps), and each query's call tree is a small
# POSTFIX program folded word-wise over them.  The fold splits the
# program into a STATIC skeleton (the opcode sequence — part of the
# compiled-program cache key, like every other fused family's shape)
# and TRACED push arguments (which operand each push reads), so any
# tree of the same skeleton — any row ids, any predicate values —
# reuses one executable, and XLA fuses the whole fold into a single
# bitwise+popcount pass with no interpreter machinery at run time.
# ``Not`` needs no opcode: the planner lowers it to ``ANDNOT(exists,
# x)`` with the existence row pushed as an operand.

TREE_NOP = 0     # padding: no-op (pow2 program-length buckets)
TREE_PUSH = 1    # push rows[arg] (gathered plane rows)
TREE_PUSHX = 2   # push extras[arg] (exists / other-field / predicate)
TREE_ZERO = 3    # push an all-zero bitmap (empty Union, absent rows)
TREE_AND = 4
TREE_OR = 5
TREE_ANDNOT = 6  # a & ~b (Difference; Not via ANDNOT(exists, x))
TREE_XOR = 7
TREE_SHIFT = 8   # unary: shift top of stack by STATIC arg n columns
TREE_LIMIT = 9   # unary: keep bits ranked [off, off+lim); STATIC args

# STATIC ops carry their argument IN the skeleton (a ``(op, arg)``
# entry instead of a bare opcode): shift distances and limit bounds
# are compile-time structure, exactly like the fused "shift" node's
# ``n`` — the LRU program cache bounds the key space they open.
TREE_STATIC_OPS = (TREE_SHIFT, TREE_LIMIT)

# postfix evaluation of a depth-d call tree needs ~d+1 live values;
# the planner rejects (falls back past) this bound so a hostile tree
# cannot explode the fused expression
TREE_STACK_DEPTH = 8

_TREE_BIN = {TREE_AND: intersect, TREE_OR: union,
             TREE_ANDNOT: difference, TREE_XOR: xor}


def tree_fold(rows, skeleton: tuple, row_args: jax.Array,
              extras: jax.Array | None = None,
              extra_args: jax.Array | None = None,
              zero: jax.Array | None = None) -> jax.Array:
    """Fold ONE postfix boolean program over operand bitmaps.

    ``rows``: uint32[G, ..., W] gathered plane rows, OR a callable
    ``rows(arg) -> uint32[..., W]`` that materializes one row per
    push (the solo path passes a direct plane indexer so XLA fuses
    each row read straight into the bitwise chain — no intermediate
    gathered array); ``extras``: uint32[E, ..., W] extra bitmaps or
    None; ``skeleton``: the STATIC opcode tuple (``TREE_*`` values;
    binary ops pop two, push one); ``row_args``/``extra_args``:
    traced int32 operand indices consumed in order by the
    ``TREE_PUSH``/``TREE_PUSHX`` ops; ``zero``: the empty-bitmap
    template for ``TREE_ZERO`` (defaults to ``zeros_like(rows[0])``
    when ``rows`` is an array).  Returns the final uint32[..., W]
    bitmap.  The skeleton is trace-time structure — the emitted XLA
    is a plain fused bitwise expression chain; only the operand
    CHOICE is a runtime gather, so any tree of the same skeleton
    (any row ids, any predicate values) reuses one compiled
    program."""
    fetch = rows if callable(rows) else (lambda a: rows[a])
    stack: list = []
    ri = xi = 0
    for entry in skeleton:
        op, sarg = entry if isinstance(entry, tuple) else (entry, None)
        if op == TREE_PUSH:
            stack.append(fetch(row_args[ri]))
            ri += 1
        elif op == TREE_PUSHX:
            stack.append(extras[extra_args[xi]])
            xi += 1
        elif op == TREE_ZERO:
            stack.append(jnp.zeros_like(rows[0]) if zero is None
                         else zero)
        elif op == TREE_NOP:
            continue
        elif op == TREE_SHIFT:
            stack.append(shift(stack.pop(), sarg))
        elif op == TREE_LIMIT:
            stack.append(rank_limit(stack.pop(), sarg[0], sarg[1]))
        else:
            b = stack.pop()
            a = stack.pop()
            stack.append(_TREE_BIN[op](a, b))
    return stack[-1]


def top_n(counts: jax.Array, n: int) -> tuple[jax.Array, jax.Array]:
    """(values, row_ids) of the n largest counts (reference: two-phase
    ``executeTopN`` merge, SURVEY.md §4.3 — exact by construction here).

    counts: int32[R] (already reduced across shards) -> (int32[k], int32[k])
    with ``k = min(n, R)`` — an oversized ``n`` returns every row, matching
    the reference's TopN semantics.  Rows with zero count may appear;
    callers filter them.
    """
    vals, idx = lax.top_k(counts, min(n, counts.shape[-1]))
    return vals, idx


def union_rows(plane: jax.Array, row_mask: jax.Array) -> jax.Array:
    """OR together the rows of ``plane`` selected by boolean ``row_mask``.

    Used for time-quantum range unions (reference: ``viewsByTimeRange`` then
    row union; SURVEY.md §3.1) and ``Rows``-driven unions.
    plane: uint32[..., R, W], row_mask: bool[R] -> uint32[..., W].
    """
    masked = jnp.where(row_mask[..., :, None], plane, jnp.uint32(0))
    return jax.lax.reduce(
        masked,
        jnp.uint32(0),
        lambda x, y: jnp.bitwise_or(x, y),
        dimensions=(masked.ndim - 2,),
    )


def column_bits(plane: jax.Array, word_idx: jax.Array,
                bit_idx: jax.Array) -> jax.Array:
    """Membership of k columns in every row: one gather per column word.

    plane: uint32[S, R, W]; word_idx int32[k] (word of each column
    within its shard), bit_idx uint32[k] -> uint32[S, R, k] 0/1.  The
    device half of ``Extract`` (reference: v2 ``executeExtract``) — k
    column probes against all rows in ONE program instead of a host
    walk per (column, row).
    """
    g = plane[:, :, word_idx]
    return (g >> bit_idx[None, None, :]) & jnp.uint32(1)


def column_bits_grouped(plane: jax.Array, word_idx: jax.Array,
                        bit_idx: jax.Array) -> jax.Array:
    """Per-shard column probes: word_idx int32[S, k] / bit_idx
    uint32[S, k] select DIFFERENT columns in each shard ->
    uint32[S, R, k].  One program (and one host read) covers an entire
    Extract regardless of how many shards the selected columns span —
    the per-shard :func:`column_bits` dispatch loop costs one read per
    shard, ruinous on transports with a per-read floor (BASELINE.md)."""
    g = jnp.take_along_axis(plane, word_idx[:, None, :], axis=2)
    return (g >> bit_idx[:, None, :]) & jnp.uint32(1)


def shift(words: jax.Array, n: int = 1) -> jax.Array:
    """Shift every bit's column position up by ``n`` within its shard
    (reference: v2 ``Shift(row, n)`` — bits crossing the shard boundary
    drop, matching upstream's per-fragment shift).

    words: uint32[..., W]; bit order is LSB-first within a word, so a
    +1 column shift is a logical LEFT shift with carry between words.
    """
    if n < 0:
        raise ValueError("shift n must be non-negative")
    word_n, bit_n = divmod(n, 32)
    w = words.shape[-1]
    if word_n:
        # move whole words towards higher indices, zero-fill the bottom
        pad = jnp.zeros(words.shape[:-1] + (word_n,), dtype=words.dtype)
        words = jnp.concatenate([pad, words[..., :w - word_n]], axis=-1)
    if bit_n:
        carry_in = jnp.concatenate(
            [jnp.zeros(words.shape[:-1] + (1,), dtype=words.dtype),
             words[..., :-1]], axis=-1) >> (32 - bit_n)
        words = (words << bit_n) | carry_in
    return words


def rank_limit(words: jax.Array, offset: int, limit: int) -> jax.Array:
    """Keep only the bits whose global rank falls in ``[offset,
    offset + limit)`` — the device form of ``Limit(x, limit, offset)``.

    ``words``: uint32[S, W] in GLOBAL column order (shard axis in the
    serving shard order, words ascending, bits LSB-first within each
    word — the same order the host ``_limit_bitmap`` oracle walks);
    ``offset``/``limit`` are STATIC (``limit < 0`` = unbounded).  Rank
    arithmetic is int32 — safe while the shard axis stays under the
    executor's ``_REDUCE_SHARD_MAX`` (2^31 bits total), the same bound
    every fused count family already lives by."""
    shape = words.shape
    flat = words.reshape(-1)                       # [N] shard-major
    pw = popcount(flat).astype(jnp.int32)          # per-word set bits
    start = jnp.cumsum(pw) - pw                    # exclusive prefix
    lanes = jnp.arange(32, dtype=jnp.uint32)
    bits = ((flat[:, None] >> lanes[None, :])
            & jnp.uint32(1)).astype(jnp.int32)     # [N, 32]
    within = jnp.cumsum(bits, axis=1) - bits       # exclusive, per word
    rank = start[:, None] + within
    keep = (bits != 0) & (rank >= offset)
    if limit >= 0:
        keep = keep & (rank < offset + limit)
    packed = jnp.sum(jnp.where(keep, jnp.uint32(1) << lanes[None, :],
                               jnp.uint32(0)), axis=1, dtype=jnp.uint32)
    return packed.reshape(shape)


# ---------------------------------------------------------------------------
# Mutation kernels (device-side scatter of bit updates)
# ---------------------------------------------------------------------------
#
# Device analogue of ``fragment.setBit``/``clearBit`` bulk application
# (SURVEY.md §4.5).  The host op-log remains the durability truth; these
# kernels refresh a resident plane in place without a full rebuild.  To keep
# the scatter well-defined under XLA (duplicate scatter indices have
# unspecified combine order), the *host* first reduces raw bit positions to
# unique ``(word_idx, word_mask)`` pairs (``coalesce_updates``); the device
# then applies one gather + bitwise op + scatter with unique indices.
# Padding entries use ``word_idx >= n_words`` (out-of-bounds high; JAX wraps
# negative indices, so -1 is NOT a safe sentinel) and are dropped.


def apply_word_or(words: jax.Array, word_idx: jax.Array, word_mask: jax.Array) -> jax.Array:
    """words[idx] |= mask over trailing word axis; idx unique, >=W = pad."""
    words = jnp.asarray(words)
    gathered = words.at[..., word_idx].get(mode="fill", fill_value=0)
    return words.at[..., word_idx].set(
        jnp.bitwise_or(gathered, word_mask), mode="drop"
    )


def apply_word_andnot(words: jax.Array, word_idx: jax.Array, word_mask: jax.Array) -> jax.Array:
    """words[idx] &= ~mask over trailing word axis; idx unique, >=W = pad."""
    words = jnp.asarray(words)
    gathered = words.at[..., word_idx].get(mode="fill", fill_value=0)
    return words.at[..., word_idx].set(
        jnp.bitwise_and(gathered, jnp.bitwise_not(word_mask)), mode="drop"
    )


# ---------------------------------------------------------------------------
# Host-finished reductions (int64 exactness beyond int32 device range)
# ---------------------------------------------------------------------------

# Summing int32 per-shard counts over more shards than this could
# overflow int32 (2047 full shards of 2^20 bits ~ 2^31); beyond it the
# reduction chunks on device and finishes in int64 on host.
SAFE_SHARD_SUM = 2047


def shard_totals(counts: jax.Array) -> np.ndarray:
    """Reduce int32 per-shard counts over axis 0 exactly -> np.int64[...].

    Device-sums chunks that cannot overflow; the (tiny) chunk totals are
    finished in int64 on the host.  This is the cross-shard merge for
    Count/TopN/Rows at any scale without device int64 emulation.
    """
    s = counts.shape[0]
    if s <= SAFE_SHARD_SUM:
        return np.asarray(jnp.sum(counts, axis=0, dtype=jnp.int32)
                          ).astype(np.int64)
    parts = [np.asarray(jnp.sum(counts[i:i + SAFE_SHARD_SUM], axis=0,
                                dtype=jnp.int32))
             for i in range(0, s, SAFE_SHARD_SUM)]
    return np.stack(parts).astype(np.int64).sum(axis=0)
