"""Sparse (container-blocked) device layout for high-row-cardinality fields.

SURVEY.md §8 "dense blowup": a field with millions of distinct sparse
rows cannot live as a dense plane (5M rows × 128KB/shard ≈ 640GB), and
round 1's fallback re-streamed row blocks through the device on every
query.  This module keeps such fields DEVICE-RESIDENT in a form whose
memory scales with SET BITS, not rows × shard width:

    word_idx int32[N_pad]    flat index of each bit's word in the
                             flattened (n_shards · W) filter
    mask    uint32[N_pad]    the bit's lane mask (0 for padding)
    row_ptr int32[R_pad + 1] CSR row boundaries into the bit arrays
                             (bits sorted by row; pad rows repeat N)

8 bytes per set bit + 4 per row — a 100M-bit 5M-row field is ~820MB
instead of 640GB dense.  ``TopN(filter)`` is one compiled program:
gather the filter word per bit, AND the mask, then a SEGMENTED SUM via
cumsum + boundary gathers — deliberately NOT ``segment_sum``: XLA
lowers that to scatter-add, which serializes on TPU (measured 16×
slower than the cumsum form on a v5e for 32M bits / 8M rows).  The
filter bitmap is the only per-query device input; the CSR arrays stay
in HBM until the field mutates (the dense planes' generation protocol).

Unfiltered TopN never touches the device at all: row cardinalities come
from host fragment metadata (:mod:`pilosa_tpu.exec.planes`).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from pilosa_tpu.engine import _jaxcfg  # noqa: F401  (device int32 policy)


def _counts(filter_words: jax.Array, word_idx: jax.Array,
            mask: jax.Array, row_ptr: jax.Array) -> jax.Array:
    """int32[R_pad] per-row |row ∧ filter| — gather + cumsum + boundary
    difference.  Padding bits carry mask 0 (contribute nothing); padding
    rows have ptr[i] == ptr[i+1] (count 0)."""
    flat = filter_words.reshape(-1)
    hits = (jnp.bitwise_and(flat[word_idx], mask) != 0).astype(jnp.int32)
    cum = jnp.concatenate([jnp.zeros(1, jnp.int32),
                           jnp.cumsum(hits, dtype=jnp.int32)])
    return cum[row_ptr[1:]] - cum[row_ptr[:-1]]


@partial(jax.jit, static_argnames=("k",))
def topn_sparse(filter_words: jax.Array, word_idx: jax.Array,
                mask: jax.Array, row_ptr: jax.Array, k: int):
    """(values int32[k], slots int32[k]) of |row ∧ filter| ranked desc."""
    return jax.lax.top_k(_counts(filter_words, word_idx, mask, row_ptr), k)


@jax.jit
def sparse_row_counts(filter_words: jax.Array, word_idx: jax.Array,
                      mask: jax.Array, row_ptr: jax.Array) -> jax.Array:
    """Full per-row count vector — for callers that need every row
    (tanimoto thresholding, ids= restriction, cluster partials)."""
    return _counts(filter_words, word_idx, mask, row_ptr)
