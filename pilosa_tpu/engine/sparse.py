"""Sparse (container-blocked) device layout for high-row-cardinality fields.

SURVEY.md §8 "dense blowup": a field with millions of distinct sparse
rows cannot live as a dense plane (5M rows × 128KB/shard ≈ 640GB), and
round 1's fallback re-streamed row blocks through the device on every
query.  This module keeps such fields DEVICE-RESIDENT in a form whose
memory scales with SET BITS, not rows × shard width:

    word_idx int32[N_pad]    flat index of each bit's word in the
                             flattened (n_shards · W) filter
    mask    uint32[N_pad]    the bit's lane mask (0 for padding)
    row_ptr int32[R_pad + 1] CSR row boundaries into the bit arrays
                             (bits sorted by row; pad rows repeat N)

8 bytes per set bit + 4 per row — a 100M-bit 5M-row field is ~820MB
instead of 640GB dense.  ``TopN(filter)`` is one compiled program:
gather the filter word per bit, AND the mask, then a SEGMENTED SUM via
cumsum + boundary gathers — deliberately NOT ``segment_sum``: XLA
lowers that to scatter-add, which serializes on TPU (measured 16×
slower than the cumsum form on a v5e for 32M bits / 8M rows).  The
filter bitmap is the only per-query device input; the CSR arrays stay
in HBM until the field mutates (the dense planes' generation protocol).

Unfiltered TopN never touches the device at all: row cardinalities come
from host fragment metadata (:mod:`pilosa_tpu.exec.planes`).
"""

from __future__ import annotations

import functools as _functools
from functools import partial

import jax
import jax.numpy as jnp

from pilosa_tpu.engine import _jaxcfg  # noqa: F401  (device int32 policy)


def _counts(filter_words: jax.Array, word_idx: jax.Array,
            mask: jax.Array, row_ptr: jax.Array) -> jax.Array:
    """int32[R_pad] per-row |row ∧ filter| — gather + cumsum + boundary
    difference.  Padding bits carry mask 0 (contribute nothing); padding
    rows have ptr[i] == ptr[i+1] (count 0)."""
    flat = filter_words.reshape(-1)
    hits = (jnp.bitwise_and(flat[word_idx], mask) != 0).astype(jnp.int32)
    cum = jnp.concatenate([jnp.zeros(1, jnp.int32),
                           jnp.cumsum(hits, dtype=jnp.int32)])
    return cum[row_ptr[1:]] - cum[row_ptr[:-1]]


@partial(jax.jit, static_argnames=("k",))
def topn_sparse(filter_words: jax.Array, word_idx: jax.Array,
                mask: jax.Array, row_ptr: jax.Array, k: int):
    """(values int32[k], slots int32[k]) of |row ∧ filter| ranked desc."""
    return jax.lax.top_k(_counts(filter_words, word_idx, mask, row_ptr), k)


@jax.jit
def sparse_row_counts(filter_words: jax.Array, word_idx: jax.Array,
                      mask: jax.Array, row_ptr: jax.Array) -> jax.Array:
    """Full per-row count vector — for callers that need every row
    (tanimoto thresholding, ids= restriction, cluster partials)."""
    return _counts(filter_words, word_idx, mask, row_ptr)


# ---------------------------------------------------------------------------
# mesh-sharded form: shard-local CSR blocks + psum over ICI
# ---------------------------------------------------------------------------
#
# Under a device mesh the filter plane is sharded over its shard axis;
# a global-index gather would force XLA to all-gather the filter to
# every chip.  Instead the CSR arrays are built PER DEVICE (word
# indices local to the device's filter block, see
# ``planes.PlaneCache._build_sparse``): each chip gathers only from its
# resident filter words, computes partial per-row counts over its own
# bits, and one ``psum`` over ICI produces exact global counts — which
# also divides the measured ~50M gathers/s single-chip floor
# (BASELINE.md r2) by the device count.


def _partial_counts(axis: str):
    def block(fw, wi, mask, rp):
        # block shapes: fw (S/D, W), wi/mask (1, Nd), rp (1, R_pad+1)
        local = _counts(fw, wi[0], mask[0], rp[0])
        return jax.lax.psum(local, axis)
    return block


@_functools.lru_cache(maxsize=64)
def _mesh_program(mesh, axis: str, k: int | None):
    """jitted (filter, word_idx, mask, row_ptr) -> counts | top_k.
    Cached per (mesh, axis, k): shard_map re-wrapping per call would
    retrace every query."""
    try:
        from jax import shard_map
    except ImportError:  # older jax
        from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    sm = shard_map(
        _partial_counts(axis), mesh=mesh,
        in_specs=(P(axis, None), P(axis, None), P(axis, None),
                  P(axis, None)),
        out_specs=P())
    if k is None:
        return jax.jit(sm)
    # top_k runs on the replicated (tiny) count vector post-collective
    return jax.jit(lambda fw, wi, m, rp: jax.lax.top_k(
        sm(fw, wi, m, rp), k))


def topn_sparse_meshed(mesh, axis: str, filter_words: jax.Array,
                       word_idx: jax.Array, mask: jax.Array,
                       row_ptr: jax.Array, k: int):
    """(values int32[k], slots int32[k]) over device-blocked CSR arrays
    (word_idx/mask int32|uint32[D, Nd_pad], row_ptr int32[D, R_pad+1],
    axis 0 sharded over ``mesh``)."""
    return _mesh_program(mesh, axis, int(k))(filter_words, word_idx,
                                             mask, row_ptr)


def sparse_row_counts_meshed(mesh, axis: str, filter_words: jax.Array,
                             word_idx: jax.Array, mask: jax.Array,
                             row_ptr: jax.Array) -> jax.Array:
    """Exact global int32[R_pad] counts from per-device partials."""
    return _mesh_program(mesh, axis, None)(filter_words, word_idx,
                                           mask, row_ptr)
