"""L0 device engine: packed-word bitmap kernels on JAX/XLA.

This package replaces the reference's roaring container hot path
(``roaring/roaring.go`` — array/bitmap/run containers with pairwise
specialized AND/OR/XOR/ANDNOT kernels and ``math/bits.OnesCount64``
popcounts; SURVEY.md §3.1) with dense packed ``uint32`` planes in HBM and
fused XLA bitwise + ``lax.population_count`` kernels.  Roaring remains the
host/disk format (:mod:`pilosa_tpu.store.codec`); the device side is dense:
XLA wants static shapes, and bitwise+popcount over dense words at HBM
bandwidth beats container branching on a vector machine.

This module is deliberately jax-free (host layout constants and numpy
helpers only) so that ``import pilosa_tpu`` has no side effects; the
compute modules (:mod:`.kernels`, :mod:`.bsi`) enable JAX x64 on *their*
import via :mod:`._jaxcfg` — cross-shard counts on a 1B-column index
exceed ``int32``, and all engine arrays use explicit dtypes so the global
flag only widens our reductions.
"""

from pilosa_tpu.engine.words import (
    SHARD_WIDTH,
    WORD_BITS,
    WORDS_PER_SHARD,
    pack_columns,
    unpack_columns,
)

__all__ = [
    "SHARD_WIDTH",
    "WORD_BITS",
    "WORDS_PER_SHARD",
    "pack_columns",
    "unpack_columns",
]
