"""Headline benchmark: Intersect(Row,Row)+Count QPS on a 1B-column index.

BASELINE.json north star: ">=10x CPU QPS on Intersect+Count at 1B
columns".  1B columns = 954 shards x 2^20; both rows resident in HBM as
packed uint32 planes [954, 32768]; one query = fused and+popcount+reduce
over 250MB — exactly the reference's hot loop
(``roaring.Bitmap.IntersectionCount`` under ``executor.go#mapReduce``,
SURVEY.md §4.2) with ICI/HTTP merge replaced by an on-chip reduction.

The reference publishes no numbers and no Go toolchain exists in this
image (SURVEY.md §7), so the baseline column is measured here as the CPU
stand-in for the Go roaring executor: numpy bitwise-and + popcount over
the same packed words on this host.

Prints exactly ONE JSON line:
    {"metric": ..., "value": qps, "unit": "qps", "vs_baseline": ratio}
Diagnostics go to stderr.
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np

N_SHARDS = 954  # ceil(1e9 / 2^20) -> 1.0003e9 columns
WORDS = 32768


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def cpu_popcount(words: np.ndarray) -> int:
    if hasattr(np, "bitwise_count"):
        return int(np.bitwise_count(words).sum(dtype=np.int64))
    return int(np.unpackbits(words.view(np.uint8)).sum(dtype=np.int64))


def bench_cpu(a: np.ndarray, b: np.ndarray, iters: int) -> tuple[float, int]:
    got = cpu_popcount(np.bitwise_and(a, b))  # warm
    t0 = time.perf_counter()
    for _ in range(iters):
        got = cpu_popcount(np.bitwise_and(a, b))
    return iters / (time.perf_counter() - t0), got


def bench_device(a: np.ndarray, b: np.ndarray, iters: int) -> tuple[float, int]:
    import jax

    from pilosa_tpu.parallel import spmd

    t0 = time.perf_counter()
    da, db = jax.device_put(a), jax.device_put(b)
    jax.block_until_ready((da, db))
    log(f"host->HBM transfer of {(a.nbytes + b.nbytes) / 1e6:.0f}MB: "
        f"{time.perf_counter() - t0:.2f}s")
    out = spmd.intersect_count(da, db)
    jax.block_until_ready(out)  # compile + warm
    # conservative: sync every iteration (per-query latency, no pipeline
    # credit).  NOTE: on the axon-tunneled chip this still measures far
    # above nominal HBM bandwidth (verified with data-dependent chains);
    # values are correct but treat absolute wall-clock with caution.
    lat = []
    for _ in range(iters):
        t0 = time.perf_counter()
        out = spmd.intersect_count(da, db)
        jax.block_until_ready(out)
        lat.append(time.perf_counter() - t0)
    p50 = float(np.median(lat))
    return 1.0 / p50, int(out)


def main() -> None:
    rng = np.random.default_rng(42)
    # ~30%-density rows over 1B columns (and-of-two-randoms ~ 25% x 1B bits)
    a = rng.integers(0, 1 << 32, size=(N_SHARDS, WORDS), dtype=np.uint32)
    b = rng.integers(0, 1 << 32, size=(N_SHARDS, WORDS), dtype=np.uint32)
    a &= rng.integers(0, 1 << 32, size=a.shape, dtype=np.uint32)
    b &= rng.integers(0, 1 << 32, size=b.shape, dtype=np.uint32)

    cpu_qps, cpu_count = bench_cpu(a, b, iters=20)
    log(f"cpu stand-in reference: {cpu_qps:,.2f} qps @ 1B cols")

    import jax
    platform = jax.devices()[0].platform
    dev_qps, got = bench_device(a, b, iters=200)
    assert got == cpu_count, f"device count {got} != cpu oracle {cpu_count}"
    log(f"device ({platform}): {dev_qps:,.2f} qps @ 1B cols, "
        f"count verified == {got}")

    print(json.dumps({
        "metric": f"intersect_count_qps_1b_cols_{platform}",
        "value": round(dev_qps, 2),
        "unit": "qps",
        "vs_baseline": round(dev_qps / cpu_qps, 3),
    }))


if __name__ == "__main__":
    main()
