"""Headline benchmark: Count(Row) throughput on a 1B-column index.

BASELINE.json north star: ">=10x CPU QPS on Intersect+Count at 1B
columns".  1B columns = 954 shards x 2^20; a 64-row field plane is
resident in HBM and one fused XLA program answers 64 Count queries (the
per-row popcount matrix reduced over shards) with a single host read.

Measurement honesty (determined empirically on this image's axon
tunnel): the tunnel imposes a fixed ~100ms RPC cost on EVERY
synchronous device->host read, independent of data size, and enqueues
without reads are lazily acknowledged (wall-clock there measures
nothing).  A real local TPU reads a scalar in ~10us.  We therefore
measure the batched form — K queries per dispatch, one read — timing
execution + result read together, with values verified against a numpy
oracle.  The single-query sync latency (~102ms = tunnel floor) is
logged to stderr for the record.

The baseline column is the CPU stand-in for the reference's Go roaring
executor: numpy popcount over the same packed words on this host.

Prints exactly ONE JSON line:
    {"metric": ..., "value": qps, "unit": "qps", "vs_baseline": ratio}
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np

N_SHARDS = 954  # ceil(1e9 / 2^20) -> 1.0003e9 columns
N_ROWS = 32     # queries per dispatch (4GB plane: the tunnel's transfer
                # and read-RPC costs vary run to run; keep total bounded)
WORDS = 32768


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def cpu_counts(plane: np.ndarray) -> np.ndarray:
    if hasattr(np, "bitwise_count"):
        return plane_bitcount(plane)
    return np.array([
        int(np.unpackbits(plane[:, r].reshape(-1).view(np.uint8)).sum())
        for r in range(plane.shape[1])], np.int64)


def plane_bitcount(plane: np.ndarray) -> np.ndarray:
    return np.bitwise_count(plane).sum(axis=(0, 2), dtype=np.int64)


def main() -> None:
    import jax

    from pilosa_tpu.engine import kernels

    rng = np.random.default_rng(42)
    # ~25% density rows over 1B columns
    plane = rng.integers(0, 1 << 32, size=(N_SHARDS, N_ROWS, WORDS),
                         dtype=np.uint32)
    plane &= rng.integers(0, 1 << 32, size=plane.shape, dtype=np.uint32)
    log(f"plane: {plane.nbytes / 1e9:.2f} GB, {N_ROWS} rows x 1B cols")

    t0 = time.perf_counter()
    oracle = cpu_counts(plane)
    t_cpu_total = time.perf_counter() - t0
    cpu_qps = N_ROWS / t_cpu_total
    log(f"cpu stand-in reference: {cpu_qps:,.2f} count-queries/s @ 1B cols")

    platform = jax.devices()[0].platform
    t0 = time.perf_counter()
    d = jax.device_put(plane)
    jax.block_until_ready(d)
    log(f"host->HBM {plane.nbytes / 1e9:.1f}GB: "
        f"{time.perf_counter() - t0:.2f}s")

    import jax.numpy as jnp

    @jax.jit
    def count_batch(p):
        # 64 Count(Row) queries in one program: per-row popcounts
        # reduced over the shard axis (ICI collective when meshed)
        return jnp.sum(kernels.row_counts(p), axis=0, dtype=jnp.int32)

    # warm + verify (the first read also switches the tunnel to
    # synchronous mode, so everything after is honestly timed)
    got = np.asarray(count_batch(d)).astype(np.int64)
    np.testing.assert_array_equal(got, oracle)
    log("counts verified against numpy oracle")

    lat = []
    deadline = time.monotonic() + 90  # bounded even if the tunnel is slow
    for i in range(10):
        t0 = time.perf_counter()
        vals = np.asarray(count_batch(d))  # execute + read
        lat.append(time.perf_counter() - t0)
        if time.monotonic() > deadline and len(lat) >= 5:
            break
    p50 = float(np.median(lat))
    log(f"single-stream: {N_ROWS} queries in {p50 * 1e3:.1f} ms -> "
        f"{N_ROWS / p50:,.1f} qps (floor ~= one read RPC per dispatch)")

    # device-only roofline: N in-order dispatches, ONE final read —
    # amortizes enqueue/read overhead to expose the kernel's own
    # throughput (device executes the queue in order; the final read
    # waits for it all)
    for n_chain in (8, 32):
        t0 = time.perf_counter()
        outs = [count_batch(d) for _ in range(n_chain)]
        np.asarray(outs[-1])
        t = time.perf_counter() - t0
        log(f"roofline chain n={n_chain}: {t / n_chain * 1e3:.2f} "
            f"ms/dispatch = {plane.nbytes / (t / n_chain) / 1e9:.0f} GB/s "
            f"device throughput (HBM spec ~819 GB/s on v5e)")

    # headline: the realistic serving condition — concurrent clients.
    # The tunnel overlaps reads across threads (BASELINE.md), so
    # throughput scales with dispatch concurrency; 32 streams recover
    # ~84% of HBM bandwidth end-to-end; every read is oracle-verified.
    import threading

    def serve(n_threads, iters=6):
        barrier = threading.Barrier(n_threads + 1)
        errors = []

        def worker():
            barrier.wait()
            for _ in range(iters):
                try:
                    got = np.asarray(count_batch(d)).astype(np.int64)
                    if not np.array_equal(got, oracle):
                        errors.append("mismatch")
                except Exception as e:  # noqa: BLE001 — surface after join
                    errors.append(repr(e))

        threads = [threading.Thread(target=worker)
                   for _ in range(n_threads)]
        for t in threads:
            t.start()
        barrier.wait()
        t0 = time.perf_counter()
        for t in threads:
            t.join()
        dt = time.perf_counter() - t0
        if errors:
            return None, errors
        return N_ROWS * iters * n_threads / dt, []

    n_threads = 32
    qps, errs = serve(n_threads)
    if qps is None:
        # a flaky tunnel day: fall back to the r1-proven concurrency
        # rather than losing the headline outright
        log(f"32-stream serving failed ({errs[:2]}); retrying at 8")
        n_threads = 8
        qps, errs = serve(n_threads)
    assert qps is not None, f"concurrent serving failed: {errs[:3]}"
    log(f"device ({platform}): {n_threads}-way concurrent batched counts "
        f"-> {qps:,.1f} count-queries/s @ 1B cols, all reads verified")

    print(json.dumps({
        "metric": f"concurrent_count_qps_1b_cols_{platform}",
        "value": round(qps, 2),
        "unit": "qps",
        "vs_baseline": round(qps / cpu_qps, 3),
    }))


if __name__ == "__main__":
    main()
