"""Headline benchmark: Count(Row) throughput on a 1B-column index,
measured THROUGH THE PRODUCT PATH (real on-disk index -> Holder ->
Executor -> fused count batch -> API), with the raw-kernel roofline
alongside for the breakdown.

BASELINE.json north star: ">=10x CPU QPS on Intersect+Count at 1B
columns".  1B columns = 954 shards x 2^20; a 32-row field plane is
resident in HBM and one fused XLA program answers 32 Count queries (the
per-row popcount matrix reduced over shards) with a single host read.

Two measurement tiers, same data, same concurrency:

- **raw kernel**: jitted count over an in-memory device plane — the
  device ceiling.
- **product**: the index is written to disk as real roaring fragment
  snapshot files, opened through Holder (mmap + directory parse),
  served via ``API.query`` running 32-Count PQL requests through the
  executor's fused count-batch (one program + one read per request),
  every response verified against the numpy oracle.  A REST variant
  (HTTP server, JSON) is timed for the wire overhead figure.

Measurement honesty (determined empirically on this image's axon
tunnel): the tunnel imposes a fixed ~100ms RPC cost on EVERY
synchronous device->host read, independent of data size, and enqueues
without reads are lazily acknowledged (wall-clock there measures
nothing).  A real local TPU reads a scalar in ~10us.  We therefore
measure the batched form — K queries per dispatch, one read — timing
execution + result read together, with values verified against a numpy
oracle.  The single-query sync latency (~102ms = tunnel floor) is
logged to stderr for the record.

The baseline column is the CPU stand-in for the reference's Go roaring
executor: numpy popcount over the same packed words on this host.

Prints exactly ONE JSON line:
    {"metric": ..., "value": qps, "unit": "qps", "vs_baseline": ratio,
     "regressions": [...]}

``regressions`` is the regression guard: the headline is compared
against the most recent ``BENCH_r*.json`` round artifact carrying the
SAME metric name; a drop past REGRESSION_RATIO lands in the list (with
the prior round's figure) so a 2.4×-class product-path slide can never
again go unremarked in the round record.  ``PILOSA_BENCH_BASELINE_DIR``
overrides where prior rounds are read from (the smoke test uses it).
"""

from __future__ import annotations

import json
import os
import shutil
import subprocess  # noqa: F401 — used by the watchdog parent
import sys
import tempfile
import threading
import time

import numpy as np

# headline scale: 954 shards = ceil(1e9 / 2^20) -> 1.0003e9 columns;
# env overrides exist for small-scale smoke tests of the full watchdog
# + serving pipeline (never set by the driver)
N_SHARDS = int(os.environ.get("PILOSA_BENCH_SHARDS", "954"))
N_ROWS = int(os.environ.get("PILOSA_BENCH_ROWS", "32"))
                # N_ROWS = queries per dispatch (4GB plane: the tunnel's
                # transfer and read costs vary run to run; keep bounded)
WORDS = 32768

INDEX = "bench"
FIELD = "f"

# headline drops below this fraction of the last recorded round flag a
# regression in the output JSON (0.8 = tolerate tunnel wander, catch
# the 2.4x-class slides that motivated the guard)
REGRESSION_RATIO = 0.8

# the product path must serve at the raw-kernel ceiling: a full-scale
# round whose product/raw ratio falls under this lands in the
# `regressions` list (the r05 slide was 0.41 and went unremarked for a
# round — never again).  Toy-scale smoke runs skip the check: per-query
# fixed host costs dominate there and the ratio measures nothing.
PRODUCT_RAW_RATIO_FLOOR = 0.95
FULL_SCALE_SHARDS = 64  # below this the run is a smoke/toy override


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def _prior_rounds(metric: str):
    """Yield prior ``BENCH_r*.json`` ``parsed`` payloads carrying the
    SAME metric name, newest first (a CPU smoke run never judges
    itself against a TPU round).  Malformed artifacts are skipped —
    they must not cost the round its benchmark."""
    import glob
    import re

    base_dir = os.environ.get("PILOSA_BENCH_BASELINE_DIR") or \
        os.path.dirname(os.path.abspath(__file__))
    rounds = []
    for path in glob.glob(os.path.join(base_dir, "BENCH_r*.json")):
        m = re.search(r"BENCH_r(\d+)\.json$", path)
        if m:
            rounds.append((int(m.group(1)), path))
    for _, path in sorted(rounds, reverse=True):
        try:
            with open(path) as f:
                parsed = json.load(f).get("parsed") or {}
            if parsed.get("metric") != metric:
                continue
        except (OSError, ValueError, TypeError, AttributeError):
            continue  # malformed artifact: try the next round
        yield os.path.basename(path), parsed


def _dig(tree, path: tuple):
    """Walk nested dicts by key path; None on any miss / non-number."""
    cur = tree
    for key in path:
        if not isinstance(cur, dict) or key not in cur:
            return None
        cur = cur[key]
    return float(cur) if isinstance(cur, (int, float)) else None


def detail_regression_guard(metric: str, detail: dict, tracked: dict,
                            ratio: float = REGRESSION_RATIO) -> list[dict]:
    """Sub-metric regression guard (r17): compare named values INSIDE
    a config's ``detail`` payload against the newest prior round of
    the same headline metric that recorded a detail.  ``tracked`` maps
    a label to its key path in the detail tree, e.g.
    ``{"single_stream_qps": ("solo", "fastlane_qps")}`` — so a future
    change that tanks the solo floor or one kernel kind's GB/s fails
    the guard even while the concurrent headline hides it.  Rounds
    whose artifacts carry no detail (pre-r17) simply don't match;
    never raises."""
    prev_detail = None
    prev_name = None
    for name, parsed in _prior_rounds(metric):
        d = parsed.get("detail")
        if isinstance(d, dict) and any(
                _dig(d, path) is not None for path in tracked.values()):
            prev_detail, prev_name = d, name
            break
    if prev_detail is None:
        log(f"detail guard: no prior round carries detail for "
            f"{metric!r}; skipped")
        return []
    out = []
    for label, path in tracked.items():
        cur = _dig(detail, path)
        prev = _dig(prev_detail, path)
        if cur is None or not prev or prev <= 0:
            continue
        r = cur / prev
        if r < ratio:
            log(f"REGRESSION: {label} {cur:,.1f} is {r:.2f}x of "
                f"{prev_name}'s {prev:,.1f}")
            out.append({"metric": label, "value": round(cur, 2),
                        "previous": round(prev, 2),
                        "previous_round": prev_name,
                        "ratio": round(r, 3)})
        else:
            log(f"detail guard: {label} at {r:.2f}x of {prev_name} "
                f"— OK")
    return out


def regression_guard(metric: str, value: float) -> list[dict]:
    """Compare the headline against the newest prior ``BENCH_r*.json``
    whose recorded metric matches ``metric`` exactly.  Returns the
    (possibly empty) ``regressions`` list for the output JSON; never
    raises."""
    for path_name, parsed in _prior_rounds(metric):
        try:
            prev = float(parsed.get("value") or 0)
        except (ValueError, TypeError):
            continue
        if prev <= 0:
            continue
        ratio = value / prev
        if ratio < REGRESSION_RATIO:
            log(f"REGRESSION: {metric} {value:,.1f} qps is "
                f"{ratio:.2f}x of {path_name}'s {prev:,.1f} qps")
            return [{"metric": metric, "value": round(value, 2),
                     "previous": round(prev, 2),
                     "previous_round": path_name,
                     "ratio": round(ratio, 3)}]
        log(f"regression guard: {metric} at {ratio:.2f}x of "
            f"{path_name} — OK")
        return []
    log(f"regression guard: no prior round carries {metric!r}; skipped")
    return []


def ratio_guard(prod_qps: float | None, raw_qps: float | None,
                n_shards: int | None = None) -> list[dict]:
    """Product/raw ratio regression entry (empty list when healthy).

    Flags any FULL-SCALE round serving under ``PRODUCT_RAW_RATIO_FLOOR``
    of the raw-kernel ceiling at the same concurrency; toy-scale smoke
    rounds (shards < FULL_SCALE_SHARDS) and rounds missing either tier
    return clean — absence of a measurement is reported elsewhere, not
    as a ratio regression."""
    n_shards = N_SHARDS if n_shards is None else n_shards
    if (prod_qps is None or not raw_qps
            or n_shards < FULL_SCALE_SHARDS):
        return []
    ratio = prod_qps / raw_qps
    if ratio >= PRODUCT_RAW_RATIO_FLOOR:
        return []
    log(f"REGRESSION: product/raw ratio {ratio:.2f} is under the "
        f"{PRODUCT_RAW_RATIO_FLOOR} floor (product {prod_qps:,.1f} qps "
        f"vs raw {raw_qps:,.1f} qps)")
    return [{"metric": "product_raw_ratio", "value": round(ratio, 3),
             "floor": PRODUCT_RAW_RATIO_FLOOR,
             "product_qps": round(prod_qps, 2),
             "raw_qps": round(raw_qps, 2)}]


def cpu_counts(plane: np.ndarray) -> np.ndarray:
    if hasattr(np, "bitwise_count"):
        return plane_bitcount(plane)
    return np.array([
        int(np.unpackbits(plane[:, r].reshape(-1).view(np.uint8)).sum())
        for r in range(plane.shape[1])], np.int64)


def plane_bitcount(plane: np.ndarray) -> np.ndarray:
    return np.bitwise_count(plane).sum(axis=(0, 2), dtype=np.int64)


def median_serve(run_once, label: str, max_runs: int = 5,
                 min_runs: int = 3, budget_s: float = 180.0):
    """Median-of-N burst qps: the tunnel's throughput wanders run to run
    (r2 saw +-36% on one shot), so one JSON line must not be a dice
    roll.  Every individual run goes to stderr."""
    runs: list[float] = []
    deadline = time.monotonic() + budget_s
    for rep in range(max_runs):
        qps = run_once()
        if qps is not None:
            runs.append(qps)
            log(f"{label} run {rep + 1}: {qps:,.1f} qps")
        if time.monotonic() > deadline and len(runs) >= min_runs:
            break
    if not runs:
        return None, []
    return float(np.median(runs)), runs


def concurrent_burst(fn_verify, n_threads: int, iters: int,
                     queries_per_call: int):
    """Run ``fn_verify()`` (one batched dispatch + oracle check) from
    ``n_threads`` concurrent clients; returns qps or None on error."""
    barrier = threading.Barrier(n_threads + 1)
    errors: list[str] = []

    def worker():
        barrier.wait()
        for _ in range(iters):
            try:
                fn_verify()
            except Exception as e:  # noqa: BLE001 — surface after join
                errors.append(repr(e))
                return

    threads = [threading.Thread(target=worker) for _ in range(n_threads)]
    for t in threads:
        t.start()
    barrier.wait()
    t0 = time.perf_counter()
    for t in threads:
        t.join()
    dt = time.perf_counter() - t0
    if errors:
        log(f"burst errors: {errors[:3]}")
        return None
    return queries_per_call * iters * n_threads / dt


# ---------------------------------------------------------------------------
# tier 1: raw kernel (device ceiling)
# ---------------------------------------------------------------------------


def raw_kernel_tier(plane: np.ndarray, oracle: np.ndarray):
    import jax
    import jax.numpy as jnp

    from pilosa_tpu.engine import kernels

    platform = jax.devices()[0].platform
    t0 = time.perf_counter()
    d = jax.device_put(plane)
    jax.block_until_ready(d)
    log(f"host->HBM {plane.nbytes / 1e9:.1f}GB: "
        f"{time.perf_counter() - t0:.2f}s")

    @jax.jit
    def count_batch(p):
        # 32 Count(Row) queries in one program: per-row popcounts
        # reduced over the shard axis (ICI collective when meshed)
        return jnp.sum(kernels.row_counts(p), axis=0, dtype=jnp.int32)

    # warm + verify (the first read also switches the tunnel to
    # synchronous mode, so everything after is honestly timed)
    got = np.asarray(count_batch(d)).astype(np.int64)
    np.testing.assert_array_equal(got, oracle)
    log("raw-kernel counts verified against numpy oracle")

    lat = []
    deadline = time.monotonic() + 90  # bounded even if the tunnel is slow
    for _ in range(10):
        t0 = time.perf_counter()
        np.asarray(count_batch(d))  # execute + read
        lat.append(time.perf_counter() - t0)
        if time.monotonic() > deadline and len(lat) >= 5:
            break
    p50 = float(np.median(lat))
    log(f"single-stream: {N_ROWS} queries in {p50 * 1e3:.1f} ms -> "
        f"{N_ROWS / p50:,.1f} qps (floor ~= one read RPC per dispatch)")

    # device-only roofline: N in-order dispatches, ONE final read —
    # amortizes enqueue/read overhead to expose the kernel's own
    # throughput (device executes the queue in order; the final read
    # waits for it all)
    for n_chain in (8, 32):
        t0 = time.perf_counter()
        outs = [count_batch(d) for _ in range(n_chain)]
        np.asarray(outs[-1])
        t = time.perf_counter() - t0
        log(f"roofline chain n={n_chain}: {t / n_chain * 1e3:.2f} "
            f"ms/dispatch = {plane.nbytes / (t / n_chain) / 1e9:.0f} GB/s "
            f"device throughput (HBM spec ~819 GB/s on v5e)")

    def one_call():
        got = np.asarray(count_batch(d)).astype(np.int64)
        if not np.array_equal(got, oracle):
            raise AssertionError("count mismatch")

    n_threads = 32

    def burst():
        return concurrent_burst(one_call, n_threads, iters=6,
                                queries_per_call=N_ROWS)

    qps, runs = median_serve(burst, "raw-kernel")
    if qps is None:
        log("32-stream raw serving failed; retrying at 8")
        n_threads = 8
        qps, runs = median_serve(burst, "raw-kernel@8")
    assert qps is not None, "raw-kernel concurrent serving failed"
    log(f"raw kernel ({platform}): {n_threads}-way concurrent batched "
        f"counts -> median {qps:,.1f} qps @ 1B cols over {len(runs)} "
        f"runs (spread {min(runs):,.0f}-{max(runs):,.0f})")
    del d
    return platform, qps, n_threads


# stderr marker the watchdog parent scans for: a measured-but-not-final
# result published as soon as a tier completes, so a tunnel wedge in a
# LATER phase cannot cost the round its benchmark (observed: the
# product tier's second 4 GB transfer wedging after a clean raw tier)
SALVAGE_PREFIX = "BENCH-SALVAGE "


# ---------------------------------------------------------------------------
# tier 2: product path (Holder -> Executor -> API [-> REST])
# ---------------------------------------------------------------------------


def write_product_index(plane: np.ndarray, data_dir: str) -> None:
    """Write the plane as a REAL on-disk index: schema through the
    Holder, one pilosa-format roaring snapshot file per shard
    (vectorized bulk writer ``roaring.serialize_dense``)."""
    from pilosa_tpu.store import Holder, roaring

    t0 = time.perf_counter()
    h = Holder(data_dir).open()
    idx = h.create_index(INDEX, track_existence=False)
    idx.create_field(FIELD)
    h.close()
    frag_dir = os.path.join(data_dir, INDEX, FIELD, "views", "standard",
                            "fragments")
    os.makedirs(frag_dir, exist_ok=True)
    total = 0
    for s in range(plane.shape[0]):
        blob = roaring.serialize_dense(plane[s])
        total += len(blob)
        with open(os.path.join(frag_dir, str(s)), "wb") as fh:
            fh.write(blob)
    log(f"product index written: {plane.shape[0]} fragment snapshots, "
        f"{total / 1e9:.2f} GB in {time.perf_counter() - t0:.1f}s")


def product_tier(data_dir: str, oracle: np.ndarray, n_threads: int):
    from pilosa_tpu.api import API, Server
    from pilosa_tpu.exec import Executor
    from pilosa_tpu.pql.parser import parse
    from pilosa_tpu.store import Holder

    t0 = time.perf_counter()
    holder = Holder(data_dir).open()
    log(f"holder cold open: {(time.perf_counter() - t0) * 1e3:.0f} ms")
    api = API(holder, Executor(holder))

    pql = "".join(f"Count(Row({FIELD}={r}))" for r in range(N_ROWS))
    t0 = time.perf_counter()
    parse(pql)
    log(f"PQL parse ({N_ROWS} calls): "
        f"{(time.perf_counter() - t0) * 1e3:.2f} ms/request")

    # decomposed warmup: host plane assembly + HBM transfer first,
    # then the first query (compile + dispatch + read) on top
    ex = api.executor
    idx = holder.index(INDEX)
    fld = idx.field(FIELD)
    shards = tuple(idx.available_shards())
    t0 = time.perf_counter()
    ps = ex.planes.field_plane(INDEX, fld, "standard", shards)
    import jax as _jax
    _jax.block_until_ready(ps.plane)
    log(f"plane build (mmap expand + device_put): "
        f"{time.perf_counter() - t0:.1f}s")

    want = [int(c) for c in oracle]
    t0 = time.perf_counter()
    res = api.query(INDEX, pql)["results"]
    log(f"first product query (compile + dispatch + read): "
        f"{time.perf_counter() - t0:.1f}s")
    assert res == want, "product-path counts diverge from oracle"
    log("product-path counts verified against numpy oracle")

    def one_call():
        if api.query(INDEX, pql)["results"] != want:
            raise AssertionError("product count mismatch")

    def burst():
        return concurrent_burst(one_call, n_threads, iters=6,
                                queries_per_call=N_ROWS)

    qps, runs = median_serve(burst, "product")
    if qps is not None:
        log(f"product path: {n_threads}-way concurrent 32-Count PQL "
            f"requests -> median {qps:,.1f} qps @ 1B cols over "
            f"{len(runs)} runs (spread {min(runs):,.0f}-{max(runs):,.0f})")

    # REST variant: same workload over HTTP, JSON and protobuf wires
    # (VERDICT r3 #4: is the REST gap JSON marshalling or socket cost?)
    rest_qps = None
    try:
        import urllib.request

        from pilosa_tpu.api import proto

        srv = Server(api, host="127.0.0.1", port=0)
        st = threading.Thread(target=srv.serve_forever, daemon=True)
        st.start()
        url = (f"http://127.0.0.1:{srv.address[1]}"
               f"/index/{INDEX}/query")
        jbody = pql.encode()
        pbody = proto.encode_query_request(pql)

        def rest_json():
            req = urllib.request.Request(url, data=jbody, method="POST")
            with urllib.request.urlopen(req) as resp:
                if json.loads(resp.read())["results"] != want:
                    raise AssertionError("REST count mismatch")

        def rest_proto():
            req = urllib.request.Request(
                url, data=pbody, method="POST",
                headers={"Content-Type": proto.CONTENT_TYPE,
                         "Accept": proto.CONTENT_TYPE})
            with urllib.request.urlopen(req) as resp:
                got = proto.decode_query_response(resp.read())["results"]
                if got != want:
                    raise AssertionError("REST proto count mismatch")

        try:
            rest_json()  # warm
            json_qps = concurrent_burst(rest_json, n_threads, iters=3,
                                        queries_per_call=N_ROWS)
            proto_qps = None
            try:  # a proto-leg failure must not cost the JSON figure
                rest_proto()
                proto_qps = concurrent_burst(rest_proto, n_threads,
                                             iters=3,
                                             queries_per_call=N_ROWS)
            except Exception as e:  # noqa: BLE001
                log(f"REST proto leg failed (non-fatal): {e!r}")
            for name, q_ in (("JSON", json_qps), ("proto", proto_qps)):
                if q_ is not None:
                    log(f"REST {name}: {n_threads}-way concurrent -> "
                        f"{q_:,.1f} qps")
            rest_qps = max((q_ for q_ in (json_qps, proto_qps)
                            if q_ is not None), default=None)
        finally:
            srv.close()
    except Exception as e:  # noqa: BLE001 — REST figure is informative
        log(f"REST variant failed (non-fatal): {e!r}")

    holder.close()
    return qps, rest_qps


def main() -> None:
    """Watchdog wrapper: the axon tunnel intermittently wedges
    multi-GB programs at their first device read (observed round 3:
    ~half of runs; small programs unaffected).  The measurement runs in
    a child process; if the child logs nothing for STALL_S seconds it
    is killed and retried, so one wedge cannot cost the round its
    benchmark.  The child prints the single JSON line; the parent
    forwards it."""
    if os.environ.get("PILOSA_BENCH_CHILD"):
        _measure()
        return
    attempts = int(os.environ.get("PILOSA_BENCH_ATTEMPTS", "3"))
    stall_s = float(os.environ.get("PILOSA_BENCH_STALL_S", "420"))
    salvage: list[str] = []  # newest measured-tier JSON from any attempt
    for attempt in range(1, attempts + 1):
        env = dict(os.environ, PILOSA_BENCH_CHILD="1")
        proc = subprocess.Popen(
            [sys.executable, os.path.abspath(__file__)],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, env=env)
        last = [time.monotonic()]

        def pump(stream=proc.stderr):
            for line in stream:
                text = line.decode(errors="replace")
                if text.startswith(SALVAGE_PREFIX):
                    salvage.append(text[len(SALVAGE_PREFIX):].strip())
                sys.stderr.buffer.write(line)
                sys.stderr.flush()
                last[0] = time.monotonic()

        t = threading.Thread(target=pump, daemon=True)
        t.start()
        stalled = False
        while True:
            rc = proc.poll()
            if rc is not None:
                break
            if time.monotonic() - last[0] > stall_s:
                log(f"bench child silent >{stall_s:.0f}s (tunnel wedge); "
                    f"terminating — attempt {attempt}/{attempts}")
                # SIGTERM first: a hard kill of the TPU-holding process
                # is itself implicated in prolonging tunnel wedges
                proc.terminate()
                try:
                    proc.wait(timeout=15)
                except subprocess.TimeoutExpired:
                    proc.kill()
                    proc.wait()
                stalled = True
                break
            time.sleep(5)
        if not stalled and proc.returncode == 0:
            out = proc.stdout.read().decode().strip()
            if out:
                print(out.splitlines()[-1])
                return
            log("bench child produced no output; retrying")
        elif not stalled:
            log(f"bench child exited rc={proc.returncode}; retrying")
        if attempt < attempts:
            time.sleep(180)  # let the tunnel-side session drain
    if salvage:
        # every attempt wedged before finishing the PRODUCT tier, but a
        # completed tier's measurement survived — emit it rather than
        # losing the round's benchmark
        log("bench: emitting salvaged raw-kernel result (product tier "
            "never completed through the tunnel)")
        print(salvage[-1])
        return
    raise SystemExit("bench: every attempt stalled or failed")


def _measure() -> None:
    rng = np.random.default_rng(42)
    # ~25% density rows over 1B columns
    plane = rng.integers(0, 1 << 32, size=(N_SHARDS, N_ROWS, WORDS),
                         dtype=np.uint32)
    plane &= rng.integers(0, 1 << 32, size=plane.shape, dtype=np.uint32)
    log(f"plane: {plane.nbytes / 1e9:.2f} GB, {N_ROWS} rows x 1B cols")

    t0 = time.perf_counter()
    oracle = cpu_counts(plane)
    t_cpu_total = time.perf_counter() - t0
    cpu_qps = N_ROWS / t_cpu_total
    log(f"cpu stand-in reference: {cpu_qps:,.2f} count-queries/s @ 1B cols")

    platform, raw_qps, n_threads = raw_kernel_tier(plane, oracle)
    log(SALVAGE_PREFIX + json.dumps({
        "metric": f"concurrent_count_qps_1b_cols_{platform}",
        "value": round(raw_qps, 2), "unit": "qps",
        "vs_baseline": round(raw_qps / cpu_qps, 3)}))

    data_dir = tempfile.mkdtemp(prefix="pilosa_bench_")
    try:
        write_product_index(plane, data_dir)
        del plane  # holder/mmap is the source of truth from here on
        prod_qps, rest_qps = product_tier(data_dir, oracle, n_threads)
    finally:
        shutil.rmtree(data_dir, ignore_errors=True)

    # headline: the product path IS the database (VERDICT r2 #1).  Fall
    # back to the raw-kernel figure only if the product tier failed
    # outright; the stderr log always carries both for the breakdown.
    if prod_qps is not None:
        headline, metric = prod_qps, "product_count_qps_1b_cols"
        log(f"product/raw ratio: {prod_qps / raw_qps:.2f} "
            f"(product serves {prod_qps / raw_qps * 100:.0f}% of the "
            f"raw-kernel ceiling at the same concurrency)")
    else:
        headline, metric = raw_qps, "concurrent_count_qps_1b_cols"
        log("product tier failed; headline falls back to raw kernel")

    full_metric = f"{metric}_{platform}"
    print(json.dumps({
        "metric": full_metric,
        "value": round(headline, 2),
        "unit": "qps",
        "vs_baseline": round(headline / cpu_qps, 3),
        # two independent guards: headline vs the newest same-metric
        # round, and the product/raw ratio vs its floor (full scale)
        "regressions": (regression_guard(full_metric, headline)
                        + ratio_guard(prod_qps, raw_qps)),
    }))


if __name__ == "__main__":
    main()
