"""Multi-tenant HBM economy (r17): paged plane residency, the
governor-driven eviction order, tenant byte quotas, and per-tenant QoS
shedding.  Covers the ISSUE 17 satellite checklist: explicit eviction
order (incl. the leased-entry-skipped case), paged-plane correctness
under ingest (writes into a NON-resident page stay exact), the /status
``tenancy`` block, and the new metrics' emit sites."""

import json
import urllib.error
import urllib.request

import numpy as np
import pytest

from pilosa_tpu.engine.words import SHARD_WIDTH
from pilosa_tpu.exec import Executor
from pilosa_tpu.obs import Stats
from pilosa_tpu.store import Holder
from pilosa_tpu.tenancy import (PlanePager, ResidencyGovernor, TenantQos,
                                TenantThrottledError)


# ---------------------------------------------------------------- eviction

class FakePlaneSet:
    def __init__(self, nbytes=1024):
        self.plane = np.zeros(max(1, nbytes // 4), dtype=np.uint32)


def _cache(budget=1 << 30, governor=None):
    from pilosa_tpu.exec.planes import PlaneCache
    return PlaneCache(place=lambda h: h, budget_bytes=budget,
                      governor=governor)


def _seed(cache, keys, nbytes=1024):
    for k in keys:
        cache._insert_entry(k, (0,), FakePlaneSet(nbytes), nbytes)


class TestEvictionOrder:
    """Satellite 1: eviction order is explicit and unit-testable —
    stamped LRU fallback, governor cost/value override, leases pin."""

    K1 = ("plane", "a", "f", "standard", (0,))
    K2 = ("plane", "a", "g", "standard", (0,))
    K3 = ("plane", "b", "f", "standard", (0,))

    def test_lru_fallback_without_governor(self):
        cache = _cache()
        _seed(cache, [self.K1, self.K2, self.K3])
        cache._touch(self.K1)  # K1 newest → evicted last
        order = cache._eviction_order(set())
        assert order == [self.K2, self.K3, self.K1]

    def test_governor_score_overrides_recency(self):
        g = ResidencyGovernor()
        cache = _cache(governor=g)
        _seed(cache, [self.K1, self.K2])
        # K2 is hot AND expensive to rebuild: keep-score ranks it
        # after K1 even though K1 was touched more recently
        for _ in range(5):
            g.note_hit(self.K2)
        g.note_build(self.K2, 2.0)
        cache._touch(self.K1)
        order = cache._eviction_order(set())
        assert order[0] == self.K1 and order[-1] == self.K2

    def test_leased_entries_are_skipped(self):
        cache = _cache()
        _seed(cache, [self.K1, self.K2])
        cache.begin_query()
        try:
            cache._lease(self.K1)
            freed = cache.evict_unpinned()
            assert self.K1 in cache._entries      # pinned survives
            assert self.K2 not in cache._entries  # unpinned went
            assert freed == 1024
        finally:
            cache.end_query()

    def test_target_bytes_stops_early(self):
        cache = _cache()
        _seed(cache, [self.K1, self.K2, self.K3])
        freed = cache.evict_unpinned(target_bytes=1)
        assert freed == 1024 and len(cache._entries) == 2

    def test_eviction_reasons_counted_and_emitted(self):
        stats = Stats()
        cache = _cache()
        cache._stats = stats
        _seed(cache, [self.K1, self.K2])
        cache.evict_unpinned(reason="oom")
        assert cache.evictions == 2
        assert cache._evictions_by_reason == {"oom": 2}
        ctrs = stats.snapshot()["counters"]["plane_evictions_total"]
        assert any(("reason", "oom") in k for k in ctrs)

    def test_evict_tenant_scopes_to_one_index(self):
        cache = _cache()
        _seed(cache, [self.K1, self.K2, self.K3])
        freed = cache.evict_tenant("a", need_bytes=1 << 30)
        assert freed == 2048
        assert self.K3 in cache._entries  # tenant "b" untouched
        assert cache.tenant_bytes("a") == 0
        assert cache.tenant_bytes("b") == 1024


class TestGovernor:
    def test_no_hits_means_zero_score_lru_tiebreak(self):
        g = ResidencyGovernor()
        assert g.keep_score(("k",), 4096) == 0.0

    def test_score_scales_with_hits_bytes_and_cost(self):
        g = ResidencyGovernor()
        g.note_hit(("k",))
        base = g.keep_score(("k",), 1000)
        g.note_build(("k",), 10.0)
        assert g.keep_score(("k",), 1000) > base

    def test_byte_quota_admission(self):
        g = ResidencyGovernor(byte_quota=100)
        assert g.admit_bytes(40, 60)
        assert not g.admit_bytes(50, 60)
        assert ResidencyGovernor().admit_bytes(1 << 60, 1)  # quota off


# ------------------------------------------------------------ paged planes

def _fill(ex, index, field, n_shards, n_rows, per_row=3):
    """Deterministic bits; returns the per-row Count oracle."""
    pql, want = [], [0] * n_rows
    for s in range(n_shards):
        for r in range(n_rows):
            for o in range(per_row):
                pql.append(f"Set({s * SHARD_WIDTH + o * 11 + r}, "
                           f"{field}={r})")
                want[r] += 1
    ex.execute(index, " ".join(pql))
    return want


def _counts(ex, index, field, n_rows):
    return ex.execute(index, "".join(f"Count(Row({field}={r}))"
                                     for r in range(n_rows)))


@pytest.fixture
def paged(tmp_path):
    """3-shard plane (~1.5 MiB at r_pad 4) over a 1.2 MiB budget —
    paging engages, ~2 single-shard pages fit at once."""
    holder = Holder(str(tmp_path)).open()
    holder.create_index("t1").create_field("f")
    ex = Executor(holder, plane_budget=1200 * 1024,
                  plane_page_bytes=1 << 20, stats=Stats())
    yield holder, ex
    ex.translate.close()
    holder.close()


class TestPagedPlanes:
    def test_cold_and_warm_counts_oracle_exact(self, paged):
        _, ex = paged
        want = _fill(ex, "t1", "f", 3, 4)
        assert _counts(ex, "t1", "f", 4) == want       # cold: page-ins
        st = ex.tenancy_status()
        assert st["paging"] and st["pageIns"] >= 2
        assert st["residentPages"] >= 1
        assert ex.planes.builds == 0                    # never a full build
        for _ in range(3):                              # warm: page hits
            assert _counts(ex, "t1", "f", 4) == want
        t = ex.tenancy_status()["tenants"]["t1"]
        assert t["pageHits"] >= 1 and t["hitRatio"] > 0
        assert ex.planes.builds == 0

    def test_write_into_non_resident_page_stays_exact(self, paged):
        """Satellite 3: a write landing in a page that is NOT resident
        goes to the journal/overlay and the next paged read answers it
        exactly — no full rebuild."""
        _, ex = paged
        want = _fill(ex, "t1", "f", 3, 4)
        assert _counts(ex, "t1", "f", 4) == want
        # shrink residency to at most one page, so at least one of the
        # three shards' pages is non-resident when the write lands
        ex.planes.evict_unpinned(reason="test")
        assert ex.tenancy_status()["residentPages"] == 0
        ex.execute("t1", f"Set({2 * SHARD_WIDTH + 99999}, f=0) "
                         f"Set({1 * SHARD_WIDTH + 55555}, f=1)")
        want[0] += 1
        want[1] += 1
        assert _counts(ex, "t1", "f", 4) == want
        assert ex.planes.builds == 0

    def test_write_into_resident_page_absorbs_exact(self, paged):
        _, ex = paged
        want = _fill(ex, "t1", "f", 3, 4)
        assert _counts(ex, "t1", "f", 4) == want
        resident_before = ex.tenancy_status()["residentPages"]
        assert resident_before >= 1
        ex.execute("t1", f"Set(77777, f=2)")  # shard 0
        want[2] += 1
        assert _counts(ex, "t1", "f", 4) == want
        assert ex.planes.builds == 0

    def test_under_budget_plane_never_pages(self, tmp_path):
        holder = Holder(str(tmp_path)).open()
        holder.create_index("t1").create_field("f")
        ex = Executor(holder)  # default budget: whole plane fits
        try:
            want = _fill(ex, "t1", "f", 3, 4)
            assert _counts(ex, "t1", "f", 4) == want
            st = ex.tenancy_status()
            assert st["pageIns"] == 0 and st["residentPages"] == 0
            assert ex.planes.builds >= 1  # classic whole-plane path
        finally:
            ex.translate.close()
            holder.close()

    def test_byte_quota_denial_serves_oracle(self, tmp_path):
        """A tenant quota too small for even one page: every page is
        answered by the directory oracle — still exact, zero resident
        bytes for that tenant."""
        holder = Holder(str(tmp_path)).open()
        holder.create_index("t1").create_field("f")
        ex = Executor(holder, plane_budget=1200 * 1024,
                      plane_page_bytes=1 << 20,
                      tenant_byte_quota=64 * 1024)
        try:
            want = _fill(ex, "t1", "f", 3, 4)
            assert _counts(ex, "t1", "f", 4) == want
            st = ex.tenancy_status()
            assert st["oracleServes"] >= 1 or st["quotaDenials"] >= 1
            assert st["tenants"]["t1"]["residentBytes"] <= 64 * 1024
        finally:
            ex.translate.close()
            holder.close()

    def test_page_in_seconds_metric_observed(self, paged):
        _, ex = paged
        _fill(ex, "t1", "f", 3, 4)
        _counts(ex, "t1", "f", 4)
        snap = ex.stats.full_snapshot()
        h = snap["histograms"].get("plane_page_in_seconds")
        assert h is not None and h["series"][0]["count"] >= 1

    def test_resident_pages_gauge_refreshes_on_scrape(self, paged):
        _, ex = paged
        _fill(ex, "t1", "f", 3, 4)
        _counts(ex, "t1", "f", 4)
        n = ex.tenancy_status()["residentPages"]  # payload() scrapes
        gauges = ex.stats.snapshot()["gauges"]["plane_resident_pages"]
        assert any(v == n for v in gauges.values())


# -------------------------------------------------------------------- QoS

class TestTenantQos:
    def test_qps_bucket_sheds_and_refills(self):
        qos = TenantQos(qps_quota=1.0)
        qos.admit("a")  # burst token
        with pytest.raises(TenantThrottledError) as ei:
            qos.admit("a")
        e = ei.value
        assert e.tenant == "a" and e.kind == "qps" and e.quota == 1.0
        assert e.retry_after > 0
        qos.admit("b")  # an in-quota tenant is unaffected
        assert qos.sheds("a") == 1 and qos.sheds("b") == 0

    def test_slot_quota_caps_inflight(self):
        qos = TenantQos(slot_quota=2)
        qos.admit("a")
        qos.admit("a")
        with pytest.raises(TenantThrottledError) as ei:
            qos.admit("a")
        assert ei.value.kind == "slots"
        qos.release("a")
        qos.admit("a")  # a release frees a slot
        assert qos.payload()["inflight"] == {"a": 2}

    def test_disabled_quotas_admit_everything(self):
        qos = TenantQos()
        assert not qos.enabled
        for _ in range(100):
            qos.admit("a")
            qos.release("a")
        assert qos.payload()["shedTotal"] == 0

    def test_shed_emits_tenant_labelled_metric(self):
        stats = Stats()
        qos = TenantQos(slot_quota=1, stats=stats)
        qos.admit("a")
        with pytest.raises(TenantThrottledError):
            qos.admit("a")
        ctrs = stats.snapshot()["counters"]["tenant_shed_total"]
        assert any(("tenant", "a") in k for k in ctrs)


class TestQosHttpEdge:
    def test_shed_is_structured_503_with_retry_after(self, tmp_path):
        """Satellite: quota sheds ride the existing 503 + Retry-After
        machinery with a structured tenantThrottled body — and another
        tenant keeps serving through the shed."""
        from pilosa_tpu.api import API, Server

        holder = Holder(str(tmp_path)).open()
        holder.create_index("a").create_field("f")
        holder.create_index("b").create_field("f")
        ex = Executor(holder, tenant_slot_quota=1)
        api = API(holder, ex)
        server = Server(api, "127.0.0.1", 0, stats=Stats()).start()
        port = server.address[1]
        try:
            # hold tenant a's only slot open from inside the executor
            ex.qos.admit("a")
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/index/a/query",
                data=b"Count(Row(f=1))", method="POST")
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(req)
            err = ei.value
            assert err.code == 503
            assert err.headers.get("Retry-After") is not None
            body = json.loads(err.read())
            tt = body["tenantThrottled"]
            assert tt["tenant"] == "a" and tt["kind"] == "slots"
            assert tt["quota"] == 1
            # tenant b serves through a's shed
            req_b = urllib.request.Request(
                f"http://127.0.0.1:{port}/index/b/query",
                data=b"Count(Row(f=1))", method="POST")
            with urllib.request.urlopen(req_b) as resp:
                assert resp.status == 200
            ex.qos.release("a")
            with urllib.request.urlopen(urllib.request.Request(
                    f"http://127.0.0.1:{port}/index/a/query",
                    data=b"Count(Row(f=1))", method="POST")) as resp:
                assert resp.status == 200
        finally:
            server.close()
            ex.translate.close()
            holder.close()


# ------------------------------------------------------------ status block

class TestStatusAndDiagnostics:
    def test_status_tenancy_block_shape(self, paged):
        from pilosa_tpu.api import API

        holder, ex = paged
        want = _fill(ex, "t1", "f", 3, 4)
        assert _counts(ex, "t1", "f", 4) == want
        api = API(holder, ex)
        ten = api.status()["tenancy"]
        assert ten["paging"] is True
        assert "qos" in ten and "evictionsByReason" in ten
        t1 = ten["tenants"]["t1"]
        for k in ("residentBytes", "residentPages", "pageHits",
                  "pageMisses", "hitRatio", "pageIns", "sheds"):
            assert k in t1, k
        assert t1["residentPages"] >= 1

    def test_diagnostics_payload_counts_only(self, paged):
        from pilosa_tpu.obs.diagnostics import build_payload

        holder, ex = paged
        _fill(ex, "t1", "f", 3, 4)
        _counts(ex, "t1", "f", 4)
        payload = build_payload(holder, executor=ex)
        ten = payload["tenancy"]
        assert ten["tenants"] == 1 and ten["residentPages"] >= 1
        assert ten["pageIns"] >= 1
        # anonymized: no index names anywhere in the block
        assert "t1" not in json.dumps(ten)


# ------------------------------------------------------------- pager unit

class TestPagerPartition:
    def test_partition_respects_budget_clamp(self, paged):
        _, ex = paged
        _fill(ex, "t1", "f", 3, 4)
        field = ex.holder.index("t1").field("f")
        pages = ex.pager.partition(field, "standard", (0, 1, 2))
        assert pages is not None
        assert [s for p in pages for s in p] == [0, 1, 2]
        # every page must fit under half the budget (or one slab)
        est = ex.planes.plane_bytes(field, "standard", (0, 1, 2))
        slab = est // 3
        for p in pages:
            assert len(p) * slab <= max(slab, ex.planes.budget // 2)

    def test_single_shard_plane_never_partitions(self, paged):
        _, ex = paged
        _fill(ex, "t1", "f", 1, 4)
        field = ex.holder.index("t1").field("f")
        assert ex.pager.partition(field, "standard", (0,)) is None

    def test_oracle_counts_match_fragment_truth(self, paged):
        _, ex = paged
        want = _fill(ex, "t1", "f", 3, 4)
        field = ex.holder.index("t1").field("f")
        row_ids = ex.planes._union_row_ids(field, "standard", (0, 1, 2))
        got = ex.pager.oracle_counts(field, "standard", (0, 1, 2),
                                     np.asarray(row_ids))
        assert got[:4] == want
