"""BSI kernel tests against a numpy oracle.

Mirrors the reference's BSI range/sum edge-case tests (sign, base,
boundaries; ``fragment_test.go``, SURVEY.md §5)."""

import numpy as np
from hypothesis import given, settings, strategies as st

import jax.numpy as jnp

from pilosa_tpu.engine import bsi, kernels, words

W = 64
NBITS = W * 32
DEPTH = 12
LO, HI = -(1 << (DEPTH - 1)), (1 << (DEPTH - 1)) - 1


def encode(cols, vals, base=0):
    return words.bsi_encode(np.array(cols, np.uint64), np.array(vals, np.int64),
                            base, DEPTH, W)


def to_set(ws):
    return set(words.unpack_columns(np.asarray(ws)).tolist())


values_strategy = st.lists(
    st.tuples(
        st.integers(min_value=0, max_value=NBITS - 1),
        st.integers(min_value=LO, max_value=HI),
    ),
    max_size=100,
    unique_by=lambda t: t[0],
)


@settings(max_examples=20, deadline=None)
@given(pairs=values_strategy, pred=st.integers(min_value=LO, max_value=HI))
def test_range_cmp(pairs, pred):
    cols = [c for c, _ in pairs]
    vals = [v for _, v in pairs]
    plane = encode(cols, vals)
    masks = jnp.asarray(bsi.predicate_masks(abs(pred), DEPTH))
    out = bsi.range_cmp(plane, masks, jnp.asarray(pred < 0))
    d = dict(zip(cols, vals))
    oracles = {
        "lt": {c for c, v in d.items() if v < pred},
        "le": {c for c, v in d.items() if v <= pred},
        "gt": {c for c, v in d.items() if v > pred},
        "ge": {c for c, v in d.items() if v >= pred},
        "eq": {c for c, v in d.items() if v == pred},
        "ne": {c for c, v in d.items() if v != pred},
    }
    for op, expect in oracles.items():
        assert to_set(out[op]) == expect, op


@settings(max_examples=20, deadline=None)
@given(pairs=values_strategy)
def test_sum_count_min_max(pairs):
    cols = [c for c, _ in pairs]
    vals = [v for _, v in pairs]
    plane = encode(cols, vals)
    total, cnt = bsi.sum_count(plane)
    assert cnt == len(cols)
    assert total == sum(vals)

    ((mn, mn_c, mx, mx_c),) = bsi.min_max(plane)
    if cols:
        assert mn == min(vals)
        assert mn_c == vals.count(min(vals))
        assert mx == max(vals)
        assert mx_c == vals.count(max(vals))
    else:
        assert mn_c == 0 and mx_c == 0


def test_base_offset_encoding():
    # base shifts stored offsets; kernels work in offset space
    cols, vals = [1, 2, 3], [100, 150, 90]
    base = 100
    plane = words.bsi_encode(np.array(cols, np.uint64), np.array(vals, np.int64),
                             base, DEPTH, W)
    total, cnt = bsi.sum_count(plane)
    assert total + base * cnt == sum(vals)
    masks = jnp.asarray(bsi.predicate_masks(abs(120 - base), DEPTH))
    out = bsi.range_cmp(plane, masks, jnp.asarray(120 - base < 0))
    assert to_set(out["lt"]) == {1, 3}  # values < 120


def test_filtered_sum_and_range():
    cols, vals = [0, 1, 2, 3], [5, -7, 9, 11]
    plane = encode(cols, vals)
    filt = words.pack_columns(np.array([0, 1], np.uint64), W)
    total, cnt = bsi.sum_count(plane, jnp.asarray(filt))
    assert (total, cnt) == (-2, 2)
    ((mn, mn_c, mx, mx_c),) = bsi.min_max(plane, jnp.asarray(filt))
    assert (mn, mn_c, mx, mx_c) == (-7, 1, 5, 1)


def test_batched_shard_axis(rng):
    # [n_shards, depth+2, W] batching
    p0 = encode([1, 2], [3, -4])
    p1 = encode([5], [7])
    planes = jnp.stack([jnp.asarray(p0), jnp.asarray(p1)])
    # sum_count combines over ALL leading axes (the executor's use);
    # per-shard splits come from bit_counts
    total, cnt = bsi.sum_count(planes)
    assert (total, cnt) == (6, 3)
    pos, neg, c = bsi.bit_counts(planes)
    assert np.asarray(c).tolist() == [2, 1]
    per_shard = bsi.min_max(planes)
    assert [t[0] for t in per_shard] == [-4, 7]   # per-shard min
    assert [t[2] for t in per_shard] == [3, 7]    # per-shard max
