"""Whole-tree PQL compilation (r16 tentpole): compound boolean trees
compile to ONE fused XLA program — rows gathered from the resident
plane as traced operands, ops folded as a postfix program — and must
agree BIT-EXACTLY with the op-at-a-time path (the eager per-node
``_bitmap`` evaluator) and a pure-python set oracle on every shape:
pinned edge semantics, seeded random trees (the repo carries no
hypothesis), and interleaved writes riding the delta overlay.  The
batcher acceptance — concurrent compound queries over one plane share
one memory pass and one packed readback per window — is asserted via
batcher metrics."""

import threading

import numpy as np
import pytest

from pilosa_tpu.engine import kernels
from pilosa_tpu.engine.words import SHARD_WIDTH
from pilosa_tpu.exec import Executor
from pilosa_tpu.exec.executor import ExecutionError, _Ctx
from pilosa_tpu.obs import Stats
from pilosa_tpu.pql.ast import Condition
from pilosa_tpu.pql.parser import parse
from pilosa_tpu.store import FieldOptions, Holder

N_SHARDS = 3
F_ROWS = 6       # rows 6..7 stay absent (zeros leaves)
G_ROWS = 3
V_MIN, V_MAX = -100, 100


class Truth:
    """Host-side set oracle: the bits the fixture wrote."""

    def __init__(self):
        self.rows: dict[tuple, set] = {}   # (field, row) -> cols
        self.vals: dict[int, int] = {}     # BSI col -> value
        self.all_cols: set = set()

    def set_bit(self, field, row, col):
        self.rows.setdefault((field, row), set()).add(col)
        self.all_cols.add(col)

    def clear_bit(self, field, row, col):
        self.rows.get((field, row), set()).discard(col)

    def row(self, field, row) -> set:
        return set(self.rows.get((field, row), set()))

    def cond(self, cond: Condition) -> set:
        return {c for c, v in self.vals.items() if cond.matches(v)}

    def eval(self, call) -> set:
        name = call.name
        if name in ("Row", "Range"):
            (fname, value), = [(k, v) for k, v in call.args.items()
                               if not k.startswith("_")]
            if fname == "v" or isinstance(value, Condition):
                cond = (value if isinstance(value, Condition)
                        else Condition("==", value))
                return self.cond(cond)
            return self.row(fname, int(value))
        if name == "All":
            return set(self.all_cols)
        if name == "Not":
            return self.all_cols - self.eval(call.children[0])
        if name == "UnionRows":
            out: set = set()
            for rc in call.children:
                fname = str(rc.args.get("_field") or rc.args.get("field"))
                for (f, _r), cols in self.rows.items():
                    if f == fname and cols:
                        out |= cols
            return out
        kids = [self.eval(k) for k in call.children]
        if name == "Union":
            out = set()
            for k in kids:
                out |= k
            return out
        acc = kids[0]
        for k in kids[1:]:
            if name == "Intersect":
                acc = acc & k
            elif name == "Difference":
                acc = acc - k
            else:
                acc = acc ^ k
        return acc


@pytest.fixture(scope="module")
def env(tmp_path_factory):
    holder = Holder(str(tmp_path_factory.mktemp("tree"))).open()
    idx = holder.create_index("i")
    idx.create_field("f")
    idx.create_field("g")
    idx.create_field("v", FieldOptions(type="int", min=V_MIN, max=V_MAX))
    stats = Stats()
    ex = Executor(holder, stats=stats)
    # the op-at-a-time baseline: whole-tree compilation OFF restores
    # the pre-r16 path; _bitmap on it is the per-node eager evaluator
    ex_eager = Executor(holder, tree_fusion=False, count_batch_window=0)
    truth = Truth()
    rng = np.random.default_rng(16)
    cols = sorted(int(s) * SHARD_WIDTH + int(o)
                  for s in range(N_SHARDS)
                  for o in rng.choice(SHARD_WIDTH, size=60, replace=False))
    for c in cols:
        r = int(rng.integers(0, F_ROWS))
        ex.execute("i", f"Set({c}, f={r})")
        truth.set_bit("f", r, c)
        if rng.random() < 0.5:
            gr = int(rng.integers(0, G_ROWS))
            ex.execute("i", f"Set({c}, g={gr})")
            truth.set_bit("g", gr, c)
        if rng.random() < 0.7:
            v = int(rng.integers(V_MIN // 2, V_MAX // 2))
            ex.execute("i", f"Set({c}, v={v})")
            truth.vals[c] = v
    # make the anchor planes resident up front: the tree path's
    # admission (like _count_batch_plane's) declines to build a whole
    # plane for a tiny row slice, and these tests pin the FUSED path
    from pilosa_tpu.store.view import VIEW_STANDARD
    shards = tuple(idx.available_shards())
    ex.planes.field_plane("i", idx.field("f"), VIEW_STANDARD, shards)
    ex.planes.field_plane("i", idx.field("g"), VIEW_STANDARD, shards)
    yield holder, idx, ex, ex_eager, truth, stats
    holder.close()


def eager_count(ex, idx, tree_pql: str) -> int:
    """Op-at-a-time evaluation: the per-node eager ``_bitmap`` fold —
    one kernel dispatch per AST node, no fusion anywhere."""
    call = parse(f"Count({tree_pql})").calls[0].children[0]
    ctx = _Ctx(idx, tuple(idx.available_shards()), True)
    ex.planes.begin_query()
    try:
        words = ex._bitmap(ctx, call)
        return int(kernels.shard_totals(kernels.count(words)))
    finally:
        ex.planes.end_query()


def three_way(env_t, tree_pql: str):
    """fused-tree vs generic-fused (tree off) vs eager op-at-a-time
    vs the set oracle — all four must agree bit-exactly."""
    holder, idx, ex, ex_eager, truth, _ = env_t
    want = len(truth.eval(parse(f"Count({tree_pql})").calls[0]
                          .children[0]))
    got_tree = ex.execute("i", f"Count({tree_pql})")[0]
    got_generic = ex_eager.execute("i", f"Count({tree_pql})")[0]
    got_eager = eager_count(ex_eager, idx, tree_pql)
    assert got_tree == got_generic == got_eager == want, \
        (tree_pql, got_tree, got_generic, got_eager, want)
    return want


class TestEdgeSemantics:
    """Satellite: pinned compound-tree edge semantics — fused and
    op-at-a-time must agree on every one of them."""

    def test_union_no_children(self, env):
        assert three_way(env, "Union()") == 0

    def test_union_empty_inside_intersect(self, env):
        assert three_way(env, "Intersect(Row(f=0), Union())") == 0

    def test_difference_single_child(self, env):
        _, _, ex, _, truth, _ = env
        want = three_way(env, "Difference(Row(f=1))")
        assert want == len(truth.row("f", 1))

    def test_not_requires_existence_both_paths(self, env):
        holder, _, ex, ex_eager, _, _ = env
        holder.create_index("noex", track_existence=False)
        holder.index("noex").create_field("f")
        ex.execute("noex", "Set(1, f=1)")
        for e in (ex, ex_eager):
            with pytest.raises(ExecutionError, match="track existence"):
                e.execute("noex", "Count(Not(Row(f=1)))")

    def test_duplicate_row_cse(self, env):
        _, _, ex, _, truth, stats = env
        before = sum(stats.snapshot()["counters"]
                     .get("tree_cse_hits_total", {}).values())
        want = three_way(env, "Union(Row(f=1), Row(f=1), Row(f=1))")
        assert want == len(truth.row("f", 1))
        after = sum(stats.snapshot()["counters"]
                    .get("tree_cse_hits_total", {}).values())
        assert after > before, "duplicate leaves must CSE to one operand"

    def test_absent_row_is_zeros(self, env):
        assert three_way(env, "Union(Row(f=7), Row(f=7))") == 0
        three_way(env, "Difference(Row(f=0), Row(f=7))")

    def test_wide_flat_union_stays_iterative(self, env):
        """A 1500-child flat Union is legal PQL and lands on the
        generic path (past TREE_MAX_PROG): the shared fold must build
        ONE n-ary plan node — a nested pair per child recursed once
        per child in _build/shift_leaves and blew the recursion limit
        at ~966 children (review regression, pinned)."""
        _, _, ex, _, truth, _ = env
        rows = [int(r) for r in
                np.random.default_rng(5).integers(0, F_ROWS, 1500)]
        pql = "Count(Union(" + ", ".join(f"Row(f={r})"
                                         for r in rows) + "))"
        want = len(set().union(*(truth.row("f", r) for r in rows)))
        assert ex.execute("i", pql) == [want]

    def test_bsi_saturated_predicates(self, env):
        # beyond ±(2^depth - 1): everything-not-null vs nothing
        three_way(env, f"Intersect(Row(f=0), Row(v < {V_MAX * 10}))")
        three_way(env, f"Intersect(Row(f=0), Row(v > {V_MAX * 10}))")

    def test_bitmap_tree_columns_match(self, env):
        """Bitmap-valued compound trees (want=words) return the same
        column set through the tree program and the eager path."""
        holder, idx, ex, ex_eager, truth, _ = env
        pql = "Difference(Union(Row(f=0), Row(f=1)), Row(g=0))"
        (got,) = ex.execute("i", pql)
        (got2,) = ex_eager.execute("i", pql)
        want = sorted((truth.row("f", 0) | truth.row("f", 1))
                      - truth.row("g", 0))
        assert [int(c) for c in got.columns] == want
        assert [int(c) for c in got2.columns] == want


def gen_tree(rng, depth: int) -> str:
    """One random PQL tree: mixed ops, duplicate leaves (tiny row
    space), BSI range leaves (incl. saturating values and betweens),
    absent rows, empty Unions."""
    if depth == 0 or rng.random() < 0.35:
        kind = int(rng.integers(0, 6))
        if kind == 0:
            return f"Row(f={int(rng.integers(0, F_ROWS + 2))})"
        if kind == 1:
            return f"Row(g={int(rng.integers(0, G_ROWS + 1))})"
        if kind == 2:
            op = str(rng.choice(["<", "<=", ">", ">=", "==", "!="]))
            k = int(rng.integers(V_MIN * 2, V_MAX * 2))
            return f"Row(v {op} {k})"
        if kind == 3:
            lo = int(rng.integers(V_MIN, 0))
            hi = int(rng.integers(0, V_MAX))
            return f"Row({lo} < v < {hi})"
        if kind == 4:
            return "All()"
        return f"Row(f={int(rng.integers(0, 3))})"  # duplicates likely
    op = str(rng.choice(["Union", "Intersect", "Difference", "Xor",
                         "Not", "Union", "Intersect"]))
    if op == "Not":
        return f"Not({gen_tree(rng, depth - 1)})"
    lo = 0 if op == "Union" else 1
    n = int(rng.integers(lo, 4))
    kids = ", ".join(gen_tree(rng, depth - 1) for _ in range(n))
    return f"{op}({kids})"


class TestPropertyFusedVsOracle:
    """Satellite: random PQL trees (depth <= 4), fused vs op-at-a-time
    vs set oracle, bit-exact — seeded exhaustively instead of
    hypothesis (absent from the image)."""

    def test_random_trees_three_way(self, env):
        rng = np.random.default_rng(27)
        for trial in range(60):
            depth = int(rng.integers(1, 5))
            three_way(env, gen_tree(rng, depth))

    def test_random_trees_under_interleaved_writes(self, env):
        """Writes between queries ride the resident plane's delta
        overlay: answers stay three-way exact with ZERO base-plane
        rebuilds (the r15 zero-rebuild guarantee extended to fused
        trees)."""
        holder, idx, ex, ex_eager, truth, _ = env
        rng = np.random.default_rng(28)
        # warm the anchor plane so writes absorb instead of building
        three_way(env, "Intersect(Row(f=0), Row(f=1))")
        builds0 = ex.planes.stats()["builds"]
        absorbs0 = ex.planes.delta_stats()["absorbs"]
        universe = sorted(truth.all_cols)
        for _step in range(8):
            for _w in range(4):
                c = int(rng.choice(universe))
                r = int(rng.integers(0, F_ROWS))
                if rng.random() < 0.3 and c in truth.row("f", r):
                    ex.execute("i", f"Clear({c}, f={r})")
                    truth.clear_bit("f", r, c)
                else:
                    ex.execute("i", f"Set({c}, f={r})")
                    truth.set_bit("f", r, c)
            for _q in range(3):
                three_way(env, gen_tree(rng, int(rng.integers(1, 4))))
        st = ex.planes.stats()
        assert st["builds"] == builds0, \
            "interleaved writes must absorb into the delta overlay, " \
            "not rebuild the base plane"
        assert ex.planes.delta_stats()["absorbs"] > absorbs0, \
            "the write gap should have ridden the delta overlay"


class TestWindowSharing:
    """Acceptance: concurrent compound queries over the same plane
    share one memory pass (one tree-kind group dispatch, slot-union
    bytes) and one packed readback per batch window."""

    def _tree_counters(self, stats):
        snap = stats.snapshot()["counters"]

        def total(name):
            return sum(snap.get(name, {}).values())
        full = stats.full_snapshot()
        disp = 0
        for series in (full["histograms"]
                       .get("kernel_dispatch_seconds", {})
                       .get("series", [])):
            if series["labels"].get("kind") == "tree":
                disp += series["count"]
        return (total("batcher_batches"), total("batcher_items"), disp,
                sum(v for k, v in snap
                    .get("kernel_bytes_scanned_total", {}).items()
                    if dict(k).get("kind") == "tree"))

    def test_concurrent_trees_one_pass_one_window(self, env):
        holder, idx, ex, _, truth, _ = env
        stats = Stats()
        exw = Executor(holder, stats=stats, count_batch_window=0.05)
        pqls = ["Count(Intersect(Row(f=0), Union(Row(f=1), Row(f=2))))",
                "Count(Difference(Union(Row(f=1), Row(f=2)), Row(f=3)))"]
        wants = [len(truth.eval(parse(p).calls[0].children[0]))
                 for p in pqls]
        assert [exw.execute("i", p)[0] for p in pqls] == wants  # warm
        got: dict = {}
        errors: list = []
        for _attempt in range(20):
            b0, i0, d0, by0 = self._tree_counters(stats)
            barrier = threading.Barrier(2)

            def worker(k, p):
                try:
                    barrier.wait()
                    got[k] = exw.execute("i", p)[0]
                except Exception as e:  # noqa: BLE001
                    errors.append(repr(e))

            ts = [threading.Thread(target=worker, args=(k, p))
                  for k, p in enumerate(pqls)]
            for t in ts:
                t.start()
            for t in ts:
                t.join()
            assert not errors, errors
            b1, i1, d1, by1 = self._tree_counters(stats)
            if b1 - b0 == 1 and i1 - i0 == 2:
                # both landed in ONE window: the group must have
                # dispatched ONE fused tree program (one memory pass)
                assert d1 - d0 == 1, \
                    "two same-plane trees in one window must share " \
                    "one fused dispatch"
                # and the scanned bytes are the slot UNION (4 distinct
                # rows + exists-free extras), not the 6-leaf sum
                plane = exw.planes.field_plane_nowait(
                    "i", idx.field("f"), "standard",
                    tuple(idx.available_shards()))
                per_row = plane.plane.shape[0] * plane.plane.shape[-1] * 4
                assert by1 - by0 == 4 * per_row, (by1 - by0, per_row)
                break
        else:
            pytest.fail("two concurrent trees never landed in one window")
        assert [got[0], got[1]] == wants

    def test_mixed_window_packs_to_one_read(self, env):
        """A window holding a tree item AND a whole-plane rowcounts
        item comes back through ONE packed device→host read."""
        from pilosa_tpu.engine.kernels import TREE_AND, TREE_PUSH
        from pilosa_tpu.store.view import VIEW_STANDARD
        holder, idx, ex, _, truth, _ = env
        stats = Stats()
        exw = Executor(holder, stats=stats, count_batch_window=0.05)
        fld = idx.field("f")
        shards = tuple(idx.available_shards())
        ps = exw.planes.field_plane("i", fld, VIEW_STANDARD, shards)
        s0, s1 = ps.slot_of[0], ps.slot_of[1]
        prog = ((TREE_PUSH, 0), (TREE_PUSH, 1), (TREE_AND, 0))
        results: dict = {}
        errors: list = []
        barrier = threading.Barrier(2)

        def tree():
            try:
                barrier.wait()
                results["tree"] = exw.batcher.submit_tree(
                    ps.plane, (s0, s1), prog)
            except Exception as e:  # noqa: BLE001
                errors.append(repr(e))

        def rows():
            try:
                barrier.wait()
                results["rows"] = exw.batcher.submit_rowcounts(ps.plane)
            except Exception as e:  # noqa: BLE001
                errors.append(repr(e))

        packed = 0
        for _ in range(20):
            before = sum(stats.snapshot()["counters"]
                         .get("batcher_readback_packed", {}).values())
            ts = [threading.Thread(target=tree),
                  threading.Thread(target=rows)]
            for t in ts:
                t.start()
            for t in ts:
                t.join()
            assert not errors, errors
            packed = sum(stats.snapshot()["counters"]
                         .get("batcher_readback_packed", {}).values()) \
                - before
            if packed:
                break
        assert packed >= 1, "mixed tree+rowcounts window never packed"
        assert results["tree"] == len(truth.row("f", 0)
                                      & truth.row("f", 1))
        np.testing.assert_array_equal(
            np.asarray(results["rows"])[:1],
            np.array([len(truth.row("f", 0))]))


class TestTreeMetrics:
    def test_depth_histogram_and_build_counter(self, env):
        holder, _, _, _, _, _ = env
        stats = Stats()
        exm = Executor(holder, stats=stats, count_batch_window=0)
        exm.execute("i", "Count(Intersect(Row(f=0), Union(Row(f=1), "
                         "Row(f=2)), Not(Row(f=3))))")
        snap = stats.snapshot()["counters"]
        assert sum(snap.get("tree_programs_built_total", {}).values()) >= 1
        full = stats.full_snapshot()
        fam = full["histograms"].get("tree_fusion_depth")
        assert fam is not None and fam["series"], \
            "tree_fusion_depth must be observed"
        assert fam["series"][0]["count"] >= 1
