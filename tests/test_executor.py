"""Executor integration tests: table-driven PQL → expected results against
a temp-dir holder, mirroring the reference's ``executor_test.go`` strategy
(SURVEY.md §5)."""

import numpy as np
import pytest

from pilosa_tpu.engine.words import SHARD_WIDTH
from pilosa_tpu.exec import ExecutionError, Executor
from pilosa_tpu.store import FieldOptions, Holder


@pytest.fixture
def env(tmp_path):
    holder = Holder(str(tmp_path)).open()
    idx = holder.create_index("i")
    idx.create_field("f")
    idx.create_field("g")
    idx.create_field("amount", FieldOptions(type="int", min=-1000, max=1000))
    ex = Executor(holder)
    return holder, idx, ex


def q(ex, pql, index="i", shards=None):
    return ex.execute(index, pql, shards=shards)


class TestDeviceOomRetry:
    def test_oom_evicts_planes_and_retries(self, env, monkeypatch):
        """Device RESOURCE_EXHAUSTED on a call must evict unpinned
        planes and retry, not surface a 500 (regression: REST filtered
        TopN OOM'd at 1B cols after BSI+sparse residency filled HBM —
        bench/config10; r5 narrows the eviction to unpinned entries so
        concurrent queries' planes stay resident)."""
        _, _, ex = env
        q(ex, "Set(1, f=1) Set(2, f=1)")

        class XlaRuntimeError(Exception):
            pass

        calls = {"n": 0}
        evicted = {"n": 0}
        real = ex._execute_count

        def flaky(ctx, call):
            calls["n"] += 1
            if calls["n"] == 1:
                raise XlaRuntimeError(
                    "RESOURCE_EXHAUSTED: TPU backend error")
            return real(ctx, call)

        real_evict = ex.planes.evict_unpinned

        def spy_evict():
            evicted["n"] += 1
            return real_evict()

        monkeypatch.setattr(ex, "_execute_count", flaky)
        monkeypatch.setattr(ex.planes, "evict_unpinned", spy_evict)
        assert q(ex, "Count(Row(f=1))") == [2]
        assert calls["n"] == 2 and evicted["n"] == 1

    def test_non_oom_errors_propagate_without_retry(self, env,
                                                    monkeypatch):
        _, _, ex = env
        calls = {"n": 0}

        def boom(ctx, call):
            calls["n"] += 1
            raise RuntimeError("INTERNAL: something else")

        monkeypatch.setattr(ex, "_execute_count", boom)
        with pytest.raises(RuntimeError, match="something else"):
            q(ex, "Count(Row(f=1))")
        assert calls["n"] == 1


class TestBitmapCalls:
    def test_row_and_set(self, env):
        _, _, ex = env
        assert q(ex, "Set(10, f=1)") == [True]
        assert q(ex, "Set(10, f=1)") == [False]  # already set
        (r,) = q(ex, "Row(f=1)")
        np.testing.assert_array_equal(r.columns, [10])

    def test_cross_shard_row(self, env):
        _, _, ex = env
        c2 = SHARD_WIDTH + 7
        q(ex, f"Set(3, f=1) Set({c2}, f=1)")
        (r,) = q(ex, "Row(f=1)")
        np.testing.assert_array_equal(r.columns, [3, c2])

    def test_boolean_algebra(self, env):
        _, _, ex = env
        q(ex, "Set(1, f=1) Set(2, f=1) Set(3, f=1)"
              "Set(2, g=1) Set(3, g=1) Set(4, g=1)")
        (i,) = q(ex, "Intersect(Row(f=1), Row(g=1))")
        np.testing.assert_array_equal(i.columns, [2, 3])
        (u,) = q(ex, "Union(Row(f=1), Row(g=1))")
        np.testing.assert_array_equal(u.columns, [1, 2, 3, 4])
        (d,) = q(ex, "Difference(Row(f=1), Row(g=1))")
        np.testing.assert_array_equal(d.columns, [1])
        (x,) = q(ex, "Xor(Row(f=1), Row(g=1))")
        np.testing.assert_array_equal(x.columns, [1, 4])

    def test_not_and_all(self, env):
        _, _, ex = env
        q(ex, "Set(1, f=1) Set(2, f=1) Set(5, g=1)")
        (n,) = q(ex, "Not(Row(f=1))")
        np.testing.assert_array_equal(n.columns, [5])
        (a,) = q(ex, "All()")
        np.testing.assert_array_equal(a.columns, [1, 2, 5])

    def test_count(self, env):
        _, _, ex = env
        q(ex, "Set(1, f=1) Set(2, f=1) Set(2, g=1)")
        assert q(ex, "Count(Row(f=1))") == [2]
        assert q(ex, "Count(Intersect(Row(f=1), Row(g=1)))") == [1]

    def test_missing_row_is_empty(self, env):
        _, _, ex = env
        q(ex, "Set(1, f=1)")
        (r,) = q(ex, "Row(f=99)")
        assert len(r.columns) == 0

    def test_unknown_field_errors(self, env):
        _, _, ex = env
        with pytest.raises(ExecutionError):
            q(ex, "Row(nope=1)")

    def test_clear_and_clearrow(self, env):
        _, _, ex = env
        q(ex, "Set(1, f=1) Set(2, f=1)")
        assert q(ex, "Clear(1, f=1)") == [True]
        assert q(ex, "Clear(1, f=1)") == [False]
        (r,) = q(ex, "Row(f=1)")
        np.testing.assert_array_equal(r.columns, [2])
        assert q(ex, "ClearRow(f=1)") == [True]
        assert q(ex, "Count(Row(f=1))") == [0]

    def test_store(self, env):
        _, _, ex = env
        q(ex, "Set(1, f=1) Set(2, f=1)")
        assert q(ex, "Store(Row(f=1), g=7)") == [True]
        (r,) = q(ex, "Row(g=7)")
        np.testing.assert_array_equal(r.columns, [1, 2])


class TestBSI:
    def test_range_operators(self, env):
        _, _, ex = env
        q(ex, "Set(1, amount=-42) Set(2, amount=0) Set(3, amount=7)"
              "Set(4, amount=977)")
        cases = {
            "Row(amount > 0)": [3, 4],
            "Row(amount >= 0)": [2, 3, 4],
            "Row(amount < 0)": [1],
            "Row(amount <= 7)": [1, 2, 3],
            "Row(amount == 7)": [3],
            "Row(amount != 7)": [1, 2, 4],
            "Row(0 < amount < 100)": [3],
            "Row(0 <= amount <= 7)": [2, 3],
        }
        for pql, expect in cases.items():
            (r,) = q(ex, pql)
            np.testing.assert_array_equal(r.columns, expect, err_msg=pql)

    def test_range_saturation(self, env):
        _, _, ex = env
        q(ex, "Set(1, amount=5)")
        (r,) = q(ex, "Row(amount < 100000000)")
        np.testing.assert_array_equal(r.columns, [1])
        (r,) = q(ex, "Row(amount > 100000000)")
        assert len(r.columns) == 0
        (r,) = q(ex, "Row(amount > -100000000)")
        np.testing.assert_array_equal(r.columns, [1])

    def test_sum_min_max(self, env):
        _, _, ex = env
        q(ex, "Set(1, amount=-42) Set(2, amount=0) Set(3, amount=7)"
              "Set(4, amount=977)")
        (s,) = q(ex, "Sum(field=amount)")
        assert (s.value, s.count) == (-42 + 0 + 7 + 977, 4)
        (mn,) = q(ex, "Min(field=amount)")
        assert (mn.value, mn.count) == (-42, 1)
        (mx,) = q(ex, "Max(field=amount)")
        assert (mx.value, mx.count) == (977, 1)

    def test_sum_with_filter(self, env):
        _, _, ex = env
        q(ex, "Set(1, amount=10) Set(2, amount=20) Set(1, f=1)")
        (s,) = q(ex, "Sum(Row(f=1), field=amount)")
        assert (s.value, s.count) == (10, 1)

    def test_cross_shard_bsi(self, env):
        _, _, ex = env
        c2 = SHARD_WIDTH + 1
        q(ex, f"Set(1, amount=5) Set({c2}, amount=9)")
        (s,) = q(ex, "Sum(field=amount)")
        assert (s.value, s.count) == (14, 2)
        (r,) = q(ex, "Row(amount > 6)")
        np.testing.assert_array_equal(r.columns, [c2])

    def test_row_equals_on_bsi(self, env):
        _, _, ex = env
        q(ex, "Set(1, amount=7)")
        (r,) = q(ex, "Row(amount=7)")
        np.testing.assert_array_equal(r.columns, [1])


class TestTopNRowsGroupBy:
    def test_topn(self, env):
        _, _, ex = env
        q(ex, "Set(1, f=10) Set(2, f=10) Set(3, f=10)"
              "Set(1, f=20) Set(2, f=20) Set(9, f=30)")
        (p,) = q(ex, "TopN(f, n=2)")
        assert [(x.id, x.count) for x in p.pairs] == [(10, 3), (20, 2)]
        (p_all,) = q(ex, "TopN(f)")
        assert [(x.id, x.count) for x in p_all.pairs] == [
            (10, 3), (20, 2), (30, 1)]

    def test_topn_with_filter(self, env):
        _, _, ex = env
        q(ex, "Set(1, f=10) Set(2, f=10) Set(2, f=20) Set(2, g=1)")
        (p,) = q(ex, "TopN(f, filter=Row(g=1), n=5)")
        assert [(x.id, x.count) for x in p.pairs] == [(10, 1), (20, 1)]

    def test_topn_ids_restriction(self, env):
        _, _, ex = env
        q(ex, "Set(1, f=10) Set(2, f=10) Set(3, f=20)")
        (p,) = q(ex, "TopN(f, ids=[20])")
        assert [(x.id, x.count) for x in p.pairs] == [(20, 1)]

    def test_topn_cross_shard_merge(self, env):
        _, _, ex = env
        c2 = SHARD_WIDTH
        q(ex, f"Set(1, f=10) Set({c2}, f=10) Set({c2 + 1}, f=20)")
        (p,) = q(ex, "TopN(f, n=1)")
        assert [(x.id, x.count) for x in p.pairs] == [(10, 2)]

    def test_topn_tanimoto(self, env):
        # tanimoto = 100·|row∧src| / |row∪src| (fragment.go#top):
        # src={1,2,3,4}; row10={1..5} → 80; row20={1,2,9} → 40; row30 → 0
        _, _, ex = env
        q(ex, "Set(1, g=1) Set(2, g=1) Set(3, g=1) Set(4, g=1)")
        q(ex, "Set(1, f=10) Set(2, f=10) Set(3, f=10) Set(4, f=10)"
              "Set(5, f=10)")
        q(ex, "Set(1, f=20) Set(2, f=20) Set(9, f=20)")
        q(ex, "Set(7, f=30)")
        (p,) = q(ex, "TopN(f, filter=Row(g=1), tanimoto=50)")
        assert [(x.id, x.count) for x in p.pairs] == [(10, 4)]
        (p,) = q(ex, "TopN(f, filter=Row(g=1), tanimoto=40)")  # 40 inclusive
        assert [(x.id, x.count) for x in p.pairs] == [(10, 4), (20, 2)]
        (p,) = q(ex, "TopN(f, filter=Row(g=1), tanimoto=81)")
        assert p.pairs == []

    def test_topn_tanimoto_cross_shard(self, env):
        # bits split across shards: the ratio must use global counts
        _, _, ex = env
        c2 = SHARD_WIDTH
        q(ex, f"Set(1, g=1) Set({c2 + 1}, g=1)")
        q(ex, f"Set(1, f=10) Set({c2 + 1}, f=10) Set({c2 + 2}, f=10)")
        (p,) = q(ex, "TopN(f, filter=Row(g=1), tanimoto=66)")
        assert [(x.id, x.count) for x in p.pairs] == [(10, 2)]  # 2/3 ≈ 66.7

    def test_topn_tanimoto_errors(self, env):
        _, _, ex = env
        q(ex, "Set(1, f=10)")
        with pytest.raises(ExecutionError):
            q(ex, "TopN(f, tanimoto=50)")  # requires a filter
        with pytest.raises(ExecutionError):
            q(ex, "TopN(f, filter=Row(g=1), tanimoto=0)")
        with pytest.raises(ExecutionError):
            q(ex, "TopN(f, filter=Row(g=1), tanimoto=101)")

    def test_rows(self, env):
        _, _, ex = env
        q(ex, "Set(1, f=10) Set(1, f=20) Set(2, f=30)")
        (r,) = q(ex, "Rows(f)")
        np.testing.assert_array_equal(r.rows, [10, 20, 30])
        (r,) = q(ex, "Rows(f, limit=2)")
        np.testing.assert_array_equal(r.rows, [10, 20])
        (r,) = q(ex, "Rows(f, previous=10)")
        np.testing.assert_array_equal(r.rows, [20, 30])
        (r,) = q(ex, "Rows(f, column=2)")
        np.testing.assert_array_equal(r.rows, [30])

    def test_groupby(self, env):
        _, _, ex = env
        q(ex, "Set(1, f=10) Set(2, f=10) Set(1, g=5) Set(2, g=6)")
        (g,) = q(ex, "GroupBy(Rows(f), Rows(g))")
        got = [([fr.row_id for fr in gc.group], gc.count) for gc in g.groups]
        assert got == [([10, 5], 1), ([10, 6], 1)]

    def test_groupby_large_row_ids(self, env):
        # row ids live in uint64 space (capped at 2^40 by the
        # fragment position encoding — fragment._check_rows, mirroring
        # the upstream bound); the columnar assembly keeps them exact
        # end to end in uint64
        _, _, ex = env
        big = (1 << 39) + 5
        q(ex, f"Set(1, f={big}) Set(2, f={big}) Set(1, g=7) Set(2, g=8)")
        (g,) = q(ex, "GroupBy(Rows(f), Rows(g))")
        got = [([fr.row_id for fr in gc.group], gc.count) for gc in g.groups]
        assert got == [([big, 7], 1), ([big, 8], 1)]
        blob = g.to_json()
        assert blob[0]["group"][0]["rowID"] == big

    def test_groupby_filter_and_aggregate(self, env):
        _, _, ex = env
        q(ex, "Set(1, f=10) Set(2, f=10) Set(1, amount=100) Set(2, amount=50)")
        (g,) = q(ex, "GroupBy(Rows(f), filter=Row(amount > 60),"
                     "aggregate=Sum(field=amount))")
        assert len(g.groups) == 1
        gc = g.groups[0]
        assert gc.count == 1 and gc.agg == 100

    def test_groupby_having_count(self, env):
        _, _, ex = env
        q(ex, "Set(1, f=10) Set(2, f=10) Set(3, f=20)")
        (g,) = q(ex, "GroupBy(Rows(f), having=Condition(count > 1))")
        assert [(gc.group[0].row_id, gc.count) for gc in g.groups] == \
            [(10, 2)]
        (g,) = q(ex, "GroupBy(Rows(f), having=Condition(count == 1))")
        assert [(gc.group[0].row_id, gc.count) for gc in g.groups] == \
            [(20, 1)]
        # between form
        (g,) = q(ex, "GroupBy(Rows(f), having=Condition(1 <= count <= 1))")
        assert [(gc.group[0].row_id, gc.count) for gc in g.groups] == \
            [(20, 1)]

    def test_groupby_having_sum(self, env):
        _, _, ex = env
        q(ex, "Set(1, f=10) Set(2, f=10) Set(3, f=20)"
              "Set(1, amount=100) Set(2, amount=-30) Set(3, amount=5)")
        (g,) = q(ex, "GroupBy(Rows(f), aggregate=Sum(field=amount),"
                     "having=Condition(sum > 60))")
        assert [(gc.group[0].row_id, gc.count, gc.agg)
                for gc in g.groups] == [(10, 2, 70)]
        # having applies BEFORE limit
        (g,) = q(ex, "GroupBy(Rows(f), aggregate=Sum(field=amount),"
                     "having=Condition(sum < 60), limit=1)")
        assert [(gc.group[0].row_id, gc.agg) for gc in g.groups] == \
            [(20, 5)]

    def test_groupby_having_validation(self, env):
        _, _, ex = env
        q(ex, "Set(1, f=10)")
        with pytest.raises(ExecutionError):
            q(ex, "GroupBy(Rows(f), having=Condition(sum > 1))")  # no Sum
        with pytest.raises(ExecutionError):
            q(ex, "GroupBy(Rows(f), having=Condition(nope > 1))")
        with pytest.raises(ExecutionError):
            q(ex, "GroupBy(Rows(f), having=Row(f=1))")

    def test_groupby_count_min_max_aggregates(self, env):
        _, _, ex = env
        q(ex, "Set(1, f=10) Set(2, f=10) Set(3, f=20)"
              "Set(1, amount=-5) Set(2, amount=8) Set(3, amount=3)")
        (g,) = q(ex, "GroupBy(Rows(f), aggregate=Count())")
        assert [(gc.group[0].row_id, gc.count, gc.agg) for gc in g.groups] \
            == [(10, 2, 2), (20, 1, 1)]
        (g,) = q(ex, "GroupBy(Rows(f), aggregate=Min(field=amount))")
        assert [(gc.group[0].row_id, gc.agg) for gc in g.groups] \
            == [(10, -5), (20, 3)]
        (g,) = q(ex, "GroupBy(Rows(f), aggregate=Max(field=amount))")
        assert [(gc.group[0].row_id, gc.agg) for gc in g.groups] \
            == [(10, 8), (20, 3)]

    def test_groupby_minmax_agg_empty_group_cells(self, env):
        # a group with no non-null aggregate columns reports agg=None
        _, _, ex = env
        q(ex, "Set(1, f=10) Set(2, f=20) Set(2, amount=7)")
        (g,) = q(ex, "GroupBy(Rows(f), aggregate=Min(field=amount))")
        got = {gc.group[0].row_id: gc.agg for gc in g.groups}
        assert got == {10: None, 20: 7}

    def test_groupby_three_levels_oracle(self, env):
        holder, idx, ex = env
        idx.create_field("h")
        rng = np.random.default_rng(11)
        oracle: dict[str, dict[int, set[int]]] = {"f": {}, "g": {}, "h": {}}
        stmts = []
        for fld in ("f", "g", "h"):
            for _ in range(60):
                r, c = int(rng.integers(1, 5)), int(rng.integers(0, 200))
                oracle[fld].setdefault(r, set()).add(c)
                stmts.append(f"Set({c}, {fld}={r})")
        q(ex, " ".join(stmts))
        (g,) = q(ex, "GroupBy(Rows(f), Rows(g), Rows(h))")
        expect = []
        for rf in sorted(oracle["f"]):
            for rg in sorted(oracle["g"]):
                for rh in sorted(oracle["h"]):
                    n = len(oracle["f"][rf] & oracle["g"][rg]
                            & oracle["h"][rh])
                    if n:
                        expect.append(([rf, rg, rh], n))
        got = [([fr.row_id for fr in gc.group], gc.count) for gc in g.groups]
        assert got == expect

    def test_groupby_blocked_matches_unblocked(self, env, monkeypatch):
        # force tiny combination blocks: results must equal the
        # single-block run (and limit= stops the stream early)
        from pilosa_tpu.exec import groupby as gb
        _, _, ex = env
        rng = np.random.default_rng(13)
        stmts = []
        for fld in ("f", "g"):
            for _ in range(80):
                stmts.append(f"Set({int(rng.integers(0, 300))}, "
                             f"{fld}={int(rng.integers(1, 8))})")
        for col in range(0, 300, 3):
            stmts.append(f"Set({col}, amount={int(rng.integers(-50, 50))})")
        q(ex, " ".join(stmts))
        pql = "GroupBy(Rows(f), Rows(g), aggregate=Sum(field=amount))"
        (full,) = q(ex, pql)
        monkeypatch.setattr(gb, "BLOCK_OUT_BYTES", 1)  # 1 combo per block
        (blocked,) = q(ex, pql)
        as_tuples = lambda g: [([fr.row_id for fr in gc.group], gc.count,
                                gc.agg) for gc in g.groups]
        assert as_tuples(full) == as_tuples(blocked)
        (lim,) = q(ex, "GroupBy(Rows(f), Rows(g), limit=3)")
        assert len(lim.groups) == 3
        assert as_tuples(lim) == [t[:2] + (None,)
                                  for t in as_tuples(full)[:3]]

    def test_groupby_cross_shard_aggregate(self, env):
        # min/max must reduce across shards, not per shard
        _, _, ex = env
        c2 = SHARD_WIDTH + 1
        q(ex, f"Set(1, f=10) Set({c2}, f=10)"
              f"Set(1, amount=9) Set({c2}, amount=-4)")
        (g,) = q(ex, "GroupBy(Rows(f), aggregate=Min(field=amount))")
        assert [(gc.group[0].row_id, gc.count, gc.agg)
                for gc in g.groups] == [(10, 2, -4)]
        (g,) = q(ex, "GroupBy(Rows(f), aggregate=Sum(field=amount))")
        assert g.groups[0].agg == 5


class TestTimeFields:
    def test_time_range_row(self, env):
        holder, idx, ex = env
        idx.create_field("t", FieldOptions(type="time", time_quantum="YMD"))
        q(ex, "Set(1, t=1, 2017-01-02T00:00)"
              "Set(2, t=1, 2017-03-05T00:00)"
              "Set(3, t=1, 2018-01-01T00:00)")
        (r,) = q(ex, "Row(t=1, from=2017-01-01T00:00, to=2017-12-31T00:00)")
        np.testing.assert_array_equal(r.columns, [1, 2])
        (r_all,) = q(ex, "Row(t=1)")
        np.testing.assert_array_equal(r_all.columns, [1, 2, 3])


class TestKeys:
    def test_keyed_index_and_field(self, tmp_path):
        holder = Holder(str(tmp_path)).open()
        idx = holder.create_index("k", keys=True)
        idx.create_field("f", FieldOptions(keys=True))
        ex = Executor(holder)
        assert q(ex, 'Set("alice", f="admin")', index="k") == [True]
        assert q(ex, 'Set("bob", f="admin")', index="k") == [True]
        (r,) = q(ex, 'Row(f="admin")', index="k")
        assert sorted(r.keys) == ["alice", "bob"]
        (p,) = q(ex, "TopN(f)", index="k")
        assert [(x.key, x.count) for x in p.pairs] == [("admin", 2)]

    def test_missing_key_reads_empty(self, tmp_path):
        holder = Holder(str(tmp_path)).open()
        idx = holder.create_index("k", keys=True)
        idx.create_field("f", FieldOptions(keys=True))
        ex = Executor(holder)
        q(ex, 'Set("alice", f="admin")', index="k")
        (r,) = q(ex, 'Row(f="nosuch")', index="k")
        assert r.keys == []

    def test_type_mismatch_errors(self, tmp_path):
        holder = Holder(str(tmp_path)).open()
        idx = holder.create_index("k", keys=True)
        idx.create_field("f")
        ex = Executor(holder)
        with pytest.raises(ExecutionError):
            q(ex, "Set(1, f=1)", index="k")  # int col on keyed index


class TestPersistenceAcrossReopen:
    def test_query_after_reopen(self, tmp_path):
        holder = Holder(str(tmp_path)).open()
        idx = holder.create_index("i")
        idx.create_field("f")
        ex = Executor(holder)
        q(ex, "Set(1, f=1) Set(2, f=1)")
        holder.close()

        h2 = Holder(str(tmp_path)).open()
        ex2 = Executor(h2)
        assert q(ex2, "Count(Row(f=1))") == [2]

    def test_plane_cache_invalidation(self, env):
        _, _, ex = env
        q(ex, "Set(1, f=1)")
        assert q(ex, "Count(Row(f=1))") == [1]
        q(ex, "Set(2, f=1)")  # mutation bumps generation → rebuild
        assert q(ex, "Count(Row(f=1))") == [2]


class TestTimeRangeClamping:
    def test_open_ended_range_terminates(self, env):
        """Regression: omitted from/to used year-1/year-9999 sentinels and
        enumerated the whole calendar at the finest quantum."""
        holder, idx, ex = env
        idx.create_field("td", FieldOptions(type="time", time_quantum="YMDH"))
        q(ex, "Set(1, td=1, 2020-01-02T03:00) Set(2, td=1, 2020-06-01T00:00)")
        (r,) = q(ex, "Row(td=1, from=2020-01-01T00:00)")
        np.testing.assert_array_equal(r.columns, [1, 2])
        (r,) = q(ex, "Row(td=1, to=2020-05-01T00:00)")
        np.testing.assert_array_equal(r.columns, [1])

    def test_range_on_field_without_views(self, env):
        holder, idx, ex = env
        idx.create_field("t2", FieldOptions(type="time", time_quantum="D"))
        (r,) = q(ex, "Row(t2=1, from=2020-01-01T00:00, to=2021-01-01T00:00)")
        assert len(r.columns) == 0


class TestParityBatch:
    def test_shift(self, env):
        _, _, ex = env
        q(ex, "Set(1, f=1) Set(40, f=1)")
        (r,) = q(ex, "Shift(Row(f=1), n=1)")
        np.testing.assert_array_equal(r.columns, [2, 41])
        (r2,) = q(ex, "Shift(Row(f=1), n=40)")  # crosses word boundary
        np.testing.assert_array_equal(r2.columns, [41, 80])
        assert q(ex, "Count(Shift(Row(f=1), n=1))") == [2]

    def test_shift_drops_at_shard_boundary(self, env):
        _, _, ex = env
        last = SHARD_WIDTH - 1
        q(ex, f"Set({last}, f=1) Set(0, f=1)")
        (r,) = q(ex, "Shift(Row(f=1), n=1)")
        np.testing.assert_array_equal(r.columns, [1])

    def test_union_rows(self, env):
        _, _, ex = env
        q(ex, "Set(1, f=10) Set(2, f=20) Set(3, f=30) Set(2, g=1)")
        (r,) = q(ex, "UnionRows(Rows(f))")
        np.testing.assert_array_equal(r.columns, [1, 2, 3])
        (r2,) = q(ex, "UnionRows(Rows(f, limit=2))")
        np.testing.assert_array_equal(r2.columns, [1, 2])
        assert q(ex, "Count(Intersect(UnionRows(Rows(f)), Row(g=1)))") == [1]

    def test_all_limit_offset(self, env):
        _, _, ex = env
        q(ex, "Set(1, f=1) Set(2, f=1) Set(3, f=1) Set(4, f=1)")
        (r,) = q(ex, "All(limit=2)")
        np.testing.assert_array_equal(r.columns, [1, 2])
        (r2,) = q(ex, "All(limit=2, offset=1)")
        np.testing.assert_array_equal(r2.columns, [2, 3])

    def test_profile_spans(self, tmp_path):
        from pilosa_tpu.api import API
        from pilosa_tpu.store import Holder
        holder = Holder(str(tmp_path)).open()
        holder.create_index("i").create_field("f")
        api = API(holder)
        api.query("i", "Set(1, f=1)")
        out = api.query("i", "Count(Row(f=1)) Row(f=1)", profile=True)
        assert out["results"][0] == 1
        # ONE tree per query (r9): a "query" root span wraps the
        # executor call spans (+ stage.* attribution children)
        (root,) = out["profile"]
        assert root["name"] == "query" and root["tags"]["node"] == "local"
        names = [c["name"] for c in root["children"]
                 if c["name"].startswith("executor.")]
        assert names == ["executor.Count", "executor.Row"]
        assert any(c["name"].startswith("stage.")
                   for c in root["children"])
        assert root["durationUs"] >= 0
        assert out["traceId"] == root["traceId"]


class TestCountBatching:
    def test_batched_counts_match_individual(self, env):
        _, _, ex = env
        q(ex, "Set(1, f=1) Set(2, f=1) Set(2, g=1) Set(3, g=1)"
              "Set(1, amount=5) Set(2, amount=-3)")
        batch = q(ex, "Count(Row(f=1)) Count(Row(g=1)) "
                      "Count(Intersect(Row(f=1), Row(g=1))) "
                      "Count(Row(amount > 0))")
        assert batch == [2, 2, 1, 1]
        # individually identical
        for pql, expect in [("Count(Row(f=1))", 2), ("Count(Row(g=1))", 2)]:
            assert q(ex, pql) == [expect]

    def test_writes_between_counts_stay_ordered(self, env):
        _, _, ex = env
        out = q(ex, "Set(1, f=1) Count(Row(f=1)) Set(2, f=1) Count(Row(f=1))")
        assert out == [True, 1, True, 2]

    def test_one_program_for_the_batch(self, env):
        _, _, ex = env
        q(ex, "Set(1, f=1) Set(1, g=1)")
        before = len(ex.fused._programs)
        q(ex, "Count(Row(f=1)) Count(Row(g=1))")
        after = len(ex.fused._programs)
        assert after == before + 1  # one count-batch program, not two
        # repeat hits the cache
        q(ex, "Count(Row(g=1)) Count(Row(f=1))")
        q(ex, "Count(Row(f=1)) Count(Row(g=1))")
        assert len(ex.fused._programs) <= after + 1


class TestParityBatch2:
    def test_groupby_previous_paging(self, env):
        _, _, ex = env
        q(ex, "Set(1, f=10) Set(1, f=20) Set(1, g=5) Set(1, g=6)")
        (all_g,) = q(ex, "GroupBy(Rows(f), Rows(g))")
        combos = [tuple(fr.row_id for fr in gc.group) for gc in all_g.groups]
        assert combos == [(10, 5), (10, 6), (20, 5), (20, 6)]
        (page,) = q(ex, "GroupBy(Rows(f), Rows(g), previous=[10, 6], limit=1)")
        assert [tuple(fr.row_id for fr in gc.group)
                for gc in page.groups] == [(20, 5)]

    def test_rows_like(self, tmp_path):
        from pilosa_tpu.store import FieldOptions, Holder
        holder = Holder(str(tmp_path)).open()
        idx = holder.create_index("i")
        idx.create_field("f", FieldOptions(keys=True))
        ex = Executor(holder)
        ex.execute("i", 'Set(1, f="apple") Set(2, f="apricot") Set(3, f="banana")')
        (r,) = ex.execute("i", 'Rows(f, like="ap%")')
        assert sorted(r.keys) == ["apple", "apricot"]
        (r2,) = ex.execute("i", 'Rows(f, like="_anana")')
        assert r2.keys == ["banana"]

    def test_rows_like_requires_keys(self, env):
        _, _, ex = env
        q(ex, "Set(1, f=1)")
        with pytest.raises(ExecutionError):
            q(ex, 'Rows(f, like="x%")')

    def test_exclude_columns(self, env):
        _, _, ex = env
        q(ex, "Set(1, f=1) Set(2, f=1)")
        (r,) = q(ex, "Options(Row(f=1), excludeColumns=true)")
        assert len(r.columns) == 0


class TestDistinct:
    def test_distinct_values(self, env):
        _, _, ex = env
        q(ex, "Set(1, amount=5) Set(2, amount=-3) Set(3, amount=5)"
              "Set(4, amount=0) Set(5, amount=977)")
        (d,) = q(ex, "Distinct(field=amount)")
        assert d.values == [-3, 0, 5, 977]

    def test_distinct_with_filter(self, env):
        _, _, ex = env
        q(ex, "Set(1, amount=5) Set(2, amount=9) Set(1, f=1)")
        (d,) = q(ex, "Distinct(Row(f=1), field=amount)")
        assert d.values == [5]

    def test_distinct_cross_shard(self, env):
        _, _, ex = env
        c2 = SHARD_WIDTH + 1
        q(ex, f"Set(1, amount=7) Set({c2}, amount=7) Set({c2 + 1}, amount=9)")
        (d,) = q(ex, "Distinct(field=amount)")
        assert d.values == [7, 9]

    def test_distinct_decimal(self, tmp_path):
        from pilosa_tpu.store import FieldOptions, Holder
        holder = Holder(str(tmp_path)).open()
        idx = holder.create_index("i")
        idx.create_field("d", FieldOptions(type="decimal", scale=2))
        ex = Executor(holder)
        ex.execute("i", "Set(1, d=1.25) Set(2, d=-0.5)")
        (r,) = ex.execute("i", "Distinct(field=d)")
        assert r.values == [-0.5, 1.25]


class TestLegacyRangeSyntax:
    def test_positional_time_range(self, env):
        holder, idx, ex = env
        idx.create_field("t", FieldOptions(type="time", time_quantum="YMD"))
        q(ex, "Set(1, t=1, 2017-01-02T00:00) Set(2, t=1, 2017-05-01T00:00)"
              "Set(3, t=1, 2018-06-01T00:00)")
        (r,) = q(ex, "Range(t=1, 2017-01-01T00:00, 2017-12-31T00:00)")
        np.testing.assert_array_equal(r.columns, [1, 2])
        # round-trips through the printer too
        from pilosa_tpu.pql import parse
        src = "Range(t=1, 2017-01-01T00:00, 2017-12-31T00:00)"
        assert parse(str(parse(src))) == parse(src)


class TestStreamingTopN:
    def test_streamed_matches_resident(self, tmp_path, rng):
        """Force the streaming path with a tiny plane budget; results
        must match a resident-plane executor exactly."""
        holder = Holder(str(tmp_path)).open()
        idx = holder.create_index("i")
        idx.create_field("f")
        n = 4000
        rows = rng.integers(0, 500, size=n).astype(np.uint64)
        cols = rng.choice(2 * SHARD_WIDTH, size=n, replace=False).astype(np.uint64)
        idx.field("f").import_bits(rows, cols)
        idx.note_columns(cols)

        resident = Executor(holder)
        # budget too small for the ~500-row plane -> streaming path
        streaming = Executor(holder, plane_budget=8 << 20)
        for pql in ["TopN(f, n=10)", "TopN(f)", "TopN(f, ids=[3, 7, 9])"]:
            (a,) = resident.execute("i", pql)
            (b,) = streaming.execute("i", pql)
            assert [(p.id, p.count) for p in a.pairs] == \
                   [(p.id, p.count) for p in b.pairs], pql

    def test_streamed_with_filter(self, tmp_path, rng):
        holder = Holder(str(tmp_path)).open()
        idx = holder.create_index("i")
        idx.create_field("f")
        idx.create_field("g")
        rows = rng.integers(0, 300, size=2000).astype(np.uint64)
        cols = rng.choice(SHARD_WIDTH, size=2000, replace=False).astype(np.uint64)
        idx.field("f").import_bits(rows, cols)
        idx.field("g").import_bits(np.ones(1000, np.uint64), cols[:1000])
        idx.note_columns(cols)
        resident = Executor(holder)
        streaming = Executor(holder, plane_budget=4 << 20)
        (a,) = resident.execute("i", "TopN(f, filter=Row(g=1), n=5)")
        (b,) = streaming.execute("i", "TopN(f, filter=Row(g=1), n=5)")
        assert [(p.id, p.count) for p in a.pairs] == \
               [(p.id, p.count) for p in b.pairs]

    def test_streamed_tanimoto_matches_resident(self, tmp_path, rng):
        holder = Holder(str(tmp_path)).open()
        idx = holder.create_index("i")
        idx.create_field("f")
        idx.create_field("g")
        rows = rng.integers(0, 300, size=2000).astype(np.uint64)
        cols = rng.choice(SHARD_WIDTH, size=2000, replace=False).astype(np.uint64)
        idx.field("f").import_bits(rows, cols)
        # small source row so |row∧src|/|row∪src| clears a 1% threshold
        idx.field("g").import_bits(np.ones(50, np.uint64), cols[:50])
        idx.note_columns(cols)
        resident = Executor(holder)
        streaming = Executor(holder, plane_budget=4 << 20)
        pql = "TopN(f, filter=Row(g=1), tanimoto=1)"
        (a,) = resident.execute("i", pql)
        (b,) = streaming.execute("i", pql)
        assert a.pairs and [(p.id, p.count) for p in a.pairs] == \
               [(p.id, p.count) for p in b.pairs]


class TestConstRowLimitExtract:
    """v2 PQL parity: ConstRow / Limit / Extract."""

    def test_constrow(self, env):
        _, _, ex = env
        q(ex, "Set(1, f=10) Set(5, f=10) Set(9, f=10)")
        (r,) = q(ex, "ConstRow(columns=[1, 9, 77])")
        np.testing.assert_array_equal(r.columns, [1, 9, 77])
        (r,) = q(ex, "Intersect(Row(f=10), ConstRow(columns=[1, 9, 77]))")
        np.testing.assert_array_equal(r.columns, [1, 9])
        assert q(ex, "Count(ConstRow(columns=[]))") == [0]

    def test_limit(self, env):
        _, _, ex = env
        c2 = SHARD_WIDTH + 3
        q(ex, f"Set(1, f=10) Set(5, f=10) Set(9, f=10) Set({c2}, f=10)")
        (r,) = q(ex, "Limit(Row(f=10), limit=2)")
        np.testing.assert_array_equal(r.columns, [1, 5])
        (r,) = q(ex, "Limit(Row(f=10), limit=2, offset=1)")
        np.testing.assert_array_equal(r.columns, [5, 9])
        (r,) = q(ex, "Limit(Row(f=10), offset=3)")  # crosses shards
        np.testing.assert_array_equal(r.columns, [c2])
        assert q(ex, "Count(Limit(Row(f=10), limit=3))") == [3]
        with pytest.raises(ExecutionError):
            q(ex, "Limit(Row(f=10), limit=-1)")

    def test_extract(self, env):
        holder, idx, ex = env
        q(ex, "Set(1, f=10) Set(1, f=20) Set(2, f=10) Set(3, g=7)"
              "Set(1, amount=-5) Set(3, amount=8)")
        (r,) = q(ex, "Extract(ConstRow(columns=[1, 2, 3]),"
                     "Rows(f), Rows(g), Rows(amount))")
        assert r.field_specs == [("f", "set"), ("g", "set"),
                                 ("amount", "int")]
        assert r.columns == [
            (1, [[10, 20], [], -5]),
            (2, [[10], [], None]),
            (3, [[], [7], 8]),
        ]

    def test_extract_with_limit_filter(self, env):
        _, _, ex = env
        q(ex, "Set(1, f=10) Set(2, f=10) Set(3, f=10)")
        (r,) = q(ex, "Extract(Limit(Row(f=10), limit=2), Rows(f))")
        assert [c for c, _ in r.columns] == [1, 2]

    def test_extract_column_cap(self, env, monkeypatch):
        _, _, ex = env
        q(ex, "Set(1, f=10) Set(2, f=10) Set(3, f=10)")
        monkeypatch.setattr(Executor, "MAX_EXTRACT_COLUMNS", 2)
        with pytest.raises(ExecutionError):
            q(ex, "Extract(Row(f=10), Rows(f))")

    def test_extract_bool_and_mutex(self, tmp_path):
        holder = Holder(str(tmp_path)).open()
        idx = holder.create_index("i")
        idx.create_field("b", FieldOptions(type="bool"))
        idx.create_field("m", FieldOptions(type="mutex"))
        ex = Executor(holder)
        q(ex, "Set(1, b=true) Set(2, b=false) Set(1, m=5) Set(1, m=9)")
        (r,) = q(ex, "Extract(ConstRow(columns=[1, 2, 4]),"
                     "Rows(b), Rows(m))")
        assert r.columns == [
            (1, [True, 9]),   # mutex: last Set wins
            (2, [False, None]),
            (4, [None, None]),
        ]


class TestSparseTopN:
    """Container-blocked sparse residency (engine/sparse.py): fields too
    big for a dense plane stay device-resident as per-bit triplets; every
    representation must agree with the dense resident path."""

    def _setup(self, tmp_path, rng, n_rows=500, n_bits=4000):
        holder = Holder(str(tmp_path)).open()
        idx = holder.create_index("i")
        idx.create_field("f")
        idx.create_field("g")
        rows = rng.integers(0, n_rows, size=n_bits).astype(np.uint64)
        cols = rng.choice(SHARD_WIDTH + 1000, size=n_bits,
                          replace=False).astype(np.uint64)
        idx.field("f").import_bits(rows, cols)  # spans 2 shards
        idx.field("g").import_bits(np.ones(n_bits // 2, np.uint64),
                                   cols[: n_bits // 2])
        idx.create_field("h")  # small source row: tanimoto can pass
        idx.field("h").import_bits(np.ones(40, np.uint64), cols[:40])
        idx.note_columns(cols)
        resident = Executor(holder)
        # dense (512-row bucket × 2 shards = 128MB) over budget;
        # sparse (4000 bits × 12B) well under → sparse path
        sparse_ex = Executor(holder, plane_budget=1 << 20)
        return resident, sparse_ex

    def test_sparse_matches_resident(self, tmp_path, rng):
        resident, sparse_ex = self._setup(tmp_path, rng)
        for pql in ["TopN(f, filter=Row(g=1), n=10)",
                    "TopN(f, filter=Row(g=1))",
                    "TopN(f, filter=Row(g=1), ids=[3, 7, 9])",
                    "TopN(f, filter=Row(h=1), tanimoto=1)"]:
            (a,) = resident.execute("i", pql)
            (b,) = sparse_ex.execute("i", pql)
            assert a.pairs, pql  # must exercise non-empty results
            assert [(p.id, p.count) for p in a.pairs] == \
                   [(p.id, p.count) for p in b.pairs], pql
        # the sparse residency is cached on device, not per-query
        assert any(k[0] == "sparse" for k in sparse_ex.planes._entries)

    def test_unfiltered_uses_host_cards(self, tmp_path, rng):
        resident, sparse_ex = self._setup(tmp_path, rng)
        (a,) = resident.execute("i", "TopN(f, n=20)")
        (b,) = sparse_ex.execute("i", "TopN(f, n=20)")
        assert [(p.id, p.count) for p in a.pairs] == \
               [(p.id, p.count) for p in b.pairs]
        # no device representation needed for unfiltered TopN
        assert not any(k[0] in ("sparse", "plane")
                       for k in sparse_ex.planes._entries)

    def test_streaming_when_sparse_over_budget(self, tmp_path, rng):
        resident, _ = self._setup(tmp_path, rng)
        holder = resident.holder
        tiny = Executor(holder, plane_budget=16 << 10)  # < bits × 12
        (a,) = resident.execute("i", "TopN(f, filter=Row(g=1), n=10)")
        (b,) = tiny.execute("i", "TopN(f, filter=Row(g=1), n=10)")
        assert [(p.id, p.count) for p in a.pairs] == \
               [(p.id, p.count) for p in b.pairs]

    def test_sparse_invalidates_on_mutation(self, tmp_path, rng):
        resident, sparse_ex = self._setup(tmp_path, rng)
        pql = "TopN(f, filter=Row(g=1), n=5)"
        sparse_ex.execute("i", pql)
        # mutate: a column of g's row 1 gains an f bit in a fresh row
        resident.execute("i", "Set(0, g=1) Set(0, f=499)")
        (a,) = resident.execute("i", pql)
        (b,) = sparse_ex.execute("i", pql)
        assert [(p.id, p.count) for p in a.pairs] == \
               [(p.id, p.count) for p in b.pairs]


class TestReservedKeyScoping:
    def test_field_named_like_option(self, tmp_path):
        holder = Holder(str(tmp_path)).open()
        idx = holder.create_index("i")
        idx.create_field("n", FieldOptions(type="int", min=0, max=1000))
        idx.create_field("limit")
        ex = Executor(holder)
        assert ex.execute("i", "Set(5, n=777)") == [True]
        (s,) = ex.execute("i", "Sum(field=n)")
        assert (s.value, s.count) == (777, 1)
        assert ex.execute("i", "Set(5, limit=3)") == [True]
        (r,) = ex.execute("i", "Row(limit=3)")
        np.testing.assert_array_equal(r.columns, [5])

    def test_ambiguous_args_is_query_error(self, env):
        _, _, ex = env
        with pytest.raises(ExecutionError):
            q(ex, "Set(5, f=1, g=2)")


class TestPercentile:
    def test_percentiles(self, env):
        _, _, ex = env
        vals = list(range(1, 101))  # 1..100 on cols 1..100
        sets = " ".join(f"Set({c}, amount={v})"
                        for c, v in zip(range(1, 101), vals))
        q(ex, sets)
        (p50,) = q(ex, "Percentile(field=amount, nth=50)")
        assert p50.value == 50
        (p99,) = q(ex, "Percentile(field=amount, nth=99)")
        assert p99.value == 99
        (p100,) = q(ex, "Percentile(field=amount, nth=100)")
        assert p100.value == 100

    def test_percentile_negative_and_filter(self, env):
        _, _, ex = env
        q(ex, "Set(1, amount=-10) Set(2, amount=0) Set(3, amount=10)"
              "Set(1, f=1) Set(2, f=1)")
        (p,) = q(ex, "Percentile(field=amount, nth=50)")
        assert p.value == 0
        (pf,) = q(ex, "Percentile(Row(f=1), field=amount, nth=100)")
        assert pf.value == 0  # among cols {1, 2}: values {-10, 0}

    def test_percentile_empty(self, env):
        _, _, ex = env
        (p,) = q(ex, "Percentile(field=amount, nth=50)")
        assert (p.value, p.count) == (0, 0)

    def test_percentile_decimal(self, tmp_path):
        from pilosa_tpu.store import FieldOptions, Holder
        holder = Holder(str(tmp_path)).open()
        idx = holder.create_index("i")
        idx.create_field("d", FieldOptions(type="decimal", scale=1))
        ex = Executor(holder)
        ex.execute("i", "Set(1, d=1.5) Set(2, d=2.5) Set(3, d=9.5)")
        (p,) = ex.execute("i", "Percentile(field=d, nth=50)")
        assert p.value == 2.5


class TestIncludesColumn:
    def test_includes(self, env):
        _, _, ex = env
        q(ex, "Set(1, f=1) Set(2, f=1)")
        assert q(ex, "IncludesColumn(Row(f=1), column=1)") == [True]
        assert q(ex, "IncludesColumn(Row(f=1), column=3)") == [False]
        assert q(ex, "IncludesColumn(Intersect(Row(f=1), Row(g=1)), column=1)") == [False]


class TestExtractBsiDevicePath:
    """VERDICT r2 #6: BSI Extract values come off the resident bit-plane
    in one device program — oracle: per-column ``field.value`` reads."""

    def test_bulk_int_extract_matches_field_value(self, tmp_path, rng):
        holder = Holder(str(tmp_path)).open()
        idx = holder.create_index("i")
        idx.create_field("v", FieldOptions(type="int", min=-100_000,
                                           max=100_000))
        n = 3000
        # columns spread over 3 shards; ~1/3 of probed columns null
        cols = np.unique(rng.choice(3 * SHARD_WIDTH, size=n,
                                    replace=False)).astype(np.uint64)
        vals = rng.integers(-100_000, 100_000, size=len(cols))
        idx.field("v").import_values(cols, vals)
        probe = np.unique(np.concatenate(
            [cols[::2],
             rng.choice(3 * SHARD_WIDTH, size=n // 2).astype(np.uint64)]))
        idx.note_columns(probe)  # make probed columns extractable
        ex = Executor(holder)
        cols_pql = ",".join(str(int(c)) for c in probe)
        (r,) = ex.execute("i", f"Extract(ConstRow(columns=[{cols_pql}]),"
                               "Rows(v))")
        field = idx.field("v")
        got = {c: v[0] for c, v in r.columns}
        for c in probe:
            v, ok = field.value(int(c))
            assert got[int(c)] == (v if ok else None), int(c)

    def test_decimal_and_timestamp_extract(self, tmp_path):
        holder = Holder(str(tmp_path)).open()
        idx = holder.create_index("i")
        idx.create_field("d", FieldOptions(type="decimal", scale=2))
        idx.create_field("t", FieldOptions(type="timestamp"))
        idx.field("d").import_values(np.array([1, 2], np.uint64),
                                     [3.25, -0.5])
        idx.field("t").import_values(np.array([1], np.uint64),
                                     ["2021-06-01T12:00:00"])
        idx.note_columns(np.array([1, 2, 3], np.uint64))
        ex = Executor(holder)
        (r,) = ex.execute("i", "Extract(ConstRow(columns=[1, 2, 3]),"
                               "Rows(d), Rows(t))")
        by_col = {c: v for c, v in r.columns}
        dfield, tfield = idx.field("d"), idx.field("t")
        for c in (1, 2, 3):
            dv, dok = dfield.value(c)
            tv, tok = tfield.value(c)
            assert by_col[c][0] == (dv if dok else None)
            assert by_col[c][1] == (tv if tok else None)


class TestCountBatchPlanePath:
    """The same-field Count-batch whole-plane fast path must be
    indistinguishable from per-call execution."""

    def test_batched_counts_match_individual(self, env):
        _, _, ex = env
        q(ex, "Set(1, f=10) Set(2, f=10) Set(3, f=20)"
              f"Set({SHARD_WIDTH + 4}, f=20) Set(5, f=30)")
        pql = ("Count(Row(f=10)) Count(Row(f=20)) Count(Row(f=30))"
               "Count(Row(f=99))")  # 99: absent row counts 0
        batched = q(ex, pql)
        singles = [q(ex, p)[0] for p in
                   ["Count(Row(f=10))", "Count(Row(f=20))",
                    "Count(Row(f=30))", "Count(Row(f=99))"]]
        assert batched == singles == [2, 2, 1, 0]

    def test_mixed_fields_fall_back(self, env):
        _, _, ex = env
        q(ex, "Set(1, f=10) Set(2, g=7) Set(3, amount=5)")
        assert q(ex, "Count(Row(f=10)) Count(Row(g=7))"
                     "Count(Row(amount > 0))") == [1, 1, 1]

    def test_write_between_counts_stays_ordered(self, env):
        _, _, ex = env
        q(ex, "Set(1, f=10)")
        out = q(ex, "Count(Row(f=10)) Set(2, f=10) Count(Row(f=10))")
        assert out == [1, True, 2]

    def test_empty_shard_restriction(self, env):
        _, _, ex = env
        q(ex, "Set(1, f=10) Set(2, f=10)")
        # shards=[]: both the batched and single forms answer zeros,
        # never a ZeroDivisionError (review r3 finding)
        assert q(ex, "Count(Row(f=10)) Count(Row(f=10))",
                 shards=[]) == [0, 0]
        assert q(ex, "Count(Row(f=10))", shards=[]) == [0]


class TestRowAttrsOnRowResults:
    def test_row_result_carries_row_attrs(self, env):
        _, _, ex = env
        q(ex, "Set(1, f=10) SetRowAttrs(f, 10, team=\"infra\", rank=3)")
        (r,) = q(ex, "Row(f=10)")
        assert r.row_attrs == {"team": "infra", "rank": 3}
        # excludeRowAttrs suppresses (reference: QueryRequest flag)
        (r2,) = q(ex, "Row(f=10, excludeRowAttrs=true)")
        assert r2.row_attrs is None
        # rows with no attrs attach nothing
        (r3,) = q(ex, "Row(f=99)")
        assert r3.row_attrs is None
        # composite calls don't attach
        (r4,) = q(ex, "Union(Row(f=10))")
        assert r4.row_attrs is None

    def test_read_never_creates_attr_store(self, env):
        import os
        holder, idx, ex = env
        q(ex, "Set(1, g=5)")
        (r,) = q(ex, "Row(g=5)")
        assert r.row_attrs is None
        assert not os.path.exists(
            os.path.join(idx.field("g").path, "_attrs.db"))
