"""Chaos scenarios under tier-1: scripted fault schedules against REAL
OS-process clusters (pilosa_tpu.fault.chaos), each asserting its
distributed invariant after faults clear:

- partition during resize      → no lost acked writes, AAE re-converges
- crash mid-oplog-append       → replay recovers the clean prefix
- duplicate delivery           → idempotent redelivery never corrupts
- dropped placement broadcast  → heartbeat pull-on-mismatch converges
- dropped internal response    → the redelivered fan-out leg surfaces
                                 as a `retried` tag in the profile tree
- node kill failover           → kill -9 mid-serve (handoff off): zero
                                 read failures (replica failover),
                                 breaker opens, strict writes refuse
                                 503, rejoin closes it
- straggler hedged read        → hedging bounds a delayed leg; the
                                 winner carries the `hedged` trace tag
- breaker lifecycle            → open→half_open→closed pinned through
                                 partition and heal
- clear during kill handoff    → kill -9 mid-serve (handoff on):
                                 Set/Clear/ClearRow all keep serving,
                                 rejoin drains hints, oracle-exact
                                 everywhere, AAE resurrects nothing
- coordinator crash hint log   → kill -9 mid-hint-append: the torn op
                                 never applies, the clean prefix
                                 replays after restart
- bulk import kill handoff     → kill -9 mid-bulk-import: batches keep
                                 acking (hinted as import records),
                                 the drain replays, op-id dedup no-ops
                                 redelivery, AAE resurrects nothing
- hung dispatch serving        → a hung device dispatch: unaffected
                                 queries keep answering oracle-exact,
                                 the wedged caller gets a structured
                                 504/500 naming the stage, the
                                 governor probes back to healthy, no
                                 leaked pipeline threads
- flaky device governor        → consecutive dispatch faults: answers
                                 stay exact on the fallback path while
                                 the governor degrades, then probes
                                 back to healthy
- corrupt fragment scrub repair→ a byte-flipped snapshot: the scrubber
                                 detects (frame CRC), reads stay
                                 oracle-exact via replica failover,
                                 the fragment repairs from its
                                 replica, forced AAE finds zero
                                 divergence
- disk full during ingest      → ENOSPC mid-bulk-import: the node
                                 flips read-only with structured 507
                                 refusals, batches keep acking via
                                 peer hints, freeing space restores
                                 healthy and the drain lands bit-exact

Every schedule reproduces from the printed seed (override with
PILOSA_CHAOS_SEED).  The multi-node scenarios share one module-scoped
3-node cluster (replicas=2, fast AAE) — fault configs are cleared and
each scenario writes its own index, so boot cost is paid once."""

import os

import pytest

from pilosa_tpu.fault import chaos
from pilosa_tpu.testing import run_process_cluster

SEED = int(os.environ.get("PILOSA_CHAOS_SEED", "42"))


@pytest.fixture(scope="module")
def trio(tmp_path_factory):
    base = tmp_path_factory.mktemp("chaos_trio")
    with run_process_cluster(3, str(base), replicas=2,
                             anti_entropy=1.0) as cluster:
        yield cluster


def test_partition_during_resize(trio):
    chaos.scenario_partition_during_resize(trio, SEED)


def test_duplicate_delivery_on_internal_posts(trio):
    chaos.scenario_duplicate_delivery(trio, SEED)


def test_dropped_placement_broadcast(trio):
    chaos.scenario_dropped_placement_broadcast(trio, SEED)


def test_dropped_internal_response_trace(trio):
    chaos.scenario_dropped_internal_response_trace(trio, SEED)


def test_breaker_lifecycle(trio):
    chaos.scenario_breaker_lifecycle(trio, SEED)


def test_crash_mid_oplog_append(tmp_path):
    with run_process_cluster(1, str(tmp_path)) as cluster:
        chaos.scenario_crash_mid_oplog_append(cluster, SEED)


def test_node_kill_failover(tmp_path):
    # own cluster: the scenario kill -9s and restarts a member — the
    # shared trio must stay pristine for its other scenarios.  Hinted
    # handoff is disabled (the legacy strict-write pin).
    env = dict(chaos.SCENARIOS["node_kill_failover"][2])
    with run_process_cluster(3, str(tmp_path), replicas=2,
                             anti_entropy=1.0,
                             extra_env=env) as cluster:
        chaos.scenario_node_kill_failover(cluster, SEED)


def test_clear_during_kill_handoff(tmp_path):
    # own cluster (kill -9 + restart); handoff on by default — the r13
    # write-availability proof: every write class serves through the
    # kill, the rejoin drain replays, forced AAE resurrects nothing
    with run_process_cluster(3, str(tmp_path), replicas=2,
                             anti_entropy=1.0) as cluster:
        chaos.scenario_clear_during_kill_handoff(cluster, SEED)


def test_bulk_import_kill_handoff(tmp_path):
    # own cluster (kill -9 + restart): the r15 ingest proof — bulk
    # import batches serve through a dead replica (hinted as import
    # records), the rejoin drain replays them in order, op-id dedup
    # no-ops a re-delivered batch, forced AAE resurrects nothing a
    # clearing import removed
    with run_process_cluster(3, str(tmp_path), replicas=2,
                             anti_entropy=1.0) as cluster:
        chaos.scenario_bulk_import_kill_handoff(cluster, SEED)


def test_coordinator_crash_hint_log(tmp_path):
    # own cluster: tears a hint append mid-record and kill -9s the
    # write coordinator — recovery must truncate the torn op and
    # replay the clean prefix
    with run_process_cluster(3, str(tmp_path), replicas=2,
                             anti_entropy=1.0) as cluster:
        chaos.scenario_coordinator_crash_hint_log(cluster, SEED)


def test_straggler_hedged_read(tmp_path):
    # own cluster: hedging is a boot-time knob (off by default)
    env = dict(chaos.SCENARIOS["straggler_hedged_read"][2])
    with run_process_cluster(3, str(tmp_path), replicas=2,
                             extra_env=env) as cluster:
        chaos.scenario_straggler_hedged_read(cluster, SEED)


def test_hung_dispatch_serving(tmp_path):
    # own single-node cluster: sub-second watchdog/probe knobs (r18) —
    # a hung dispatch on one plane must cost its caller a structured
    # error and nobody else anything
    env = dict(chaos.SCENARIOS["hung_dispatch_serving"][2])
    with run_process_cluster(1, str(tmp_path),
                             extra_env=env) as cluster:
        chaos.scenario_hung_dispatch_serving(cluster, SEED)


def test_corrupt_fragment_scrub_repair(tmp_path):
    # own 2-node replicas=2 cluster: sub-second scrub interval,
    # periodic AAE off (r19) — a byte-flipped snapshot must be
    # detected by the scrubber, served through via replica failover
    # with zero read failures, repaired from the replica, and leave
    # zero divergence for a forced AAE round
    env = dict(chaos.SCENARIOS["corrupt_fragment_scrub_repair"][2])
    with run_process_cluster(2, str(tmp_path), replicas=2,
                             extra_env=env) as cluster:
        chaos.scenario_corrupt_fragment_scrub_repair(cluster, SEED)


def test_disk_full_during_ingest(tmp_path):
    # own 2-node replicas=2 cluster: sub-second disk probe (r19) —
    # injected ENOSPC must flip the victim read-only with structured
    # 507 refusals while bulk imports keep acking (peer hints), and
    # freeing space must restore healthy serving with the drain
    # landing bit-exact everywhere
    env = dict(chaos.SCENARIOS["disk_full_during_ingest"][2])
    with run_process_cluster(2, str(tmp_path), replicas=2,
                             extra_env=env) as cluster:
        chaos.scenario_disk_full_during_ingest(cluster, SEED)


def test_flaky_device_governor(tmp_path):
    # own single-node cluster: sub-second probe interval (r18) — the
    # governor must degrade under consecutive dispatch faults and
    # probe back once the device heals, answers exact throughout
    env = dict(chaos.SCENARIOS["flaky_device_governor"][2])
    with run_process_cluster(1, str(tmp_path),
                             extra_env=env) as cluster:
        chaos.scenario_flaky_device_governor(cluster, SEED)
