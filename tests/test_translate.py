"""Persistent sqlite key-translation stores (reference: v2 per-partition
BoltDB translate stores, SURVEY.md §3.3 — here one sqlite store per key
log with LRU read caches, keeping the v1 sequential-ID replication
protocol)."""

from __future__ import annotations

import os
import struct
import zlib

import numpy as np
import pytest

from pilosa_tpu.store.translate import (
    DEFAULT_CACHE_SIZE, KeyStore, TranslateStore, partition_of)


@pytest.fixture
def store(tmp_path):
    s = KeyStore(str(tmp_path / "k.sqlite"))
    yield s
    s.close()


class TestKeyStore:
    def test_sequential_ids_and_lookup(self, store):
        assert store.translate(["a", "b", "a", "c"], create=True) == \
            [1, 2, 1, 3]
        assert store.translate(["c", "zz", "b"]) == [3, None, 2]
        assert len(store) == 3
        assert store.key_of(2) == "b"
        assert store.key_of(0) is None
        assert store.key_of(4) is None

    def test_keys_of_batched(self, store):
        store.translate([f"u{i}" for i in range(100)], create=True)
        ids = np.array([7, 3, 99], np.uint64) + 1
        assert store.keys_of(ids) == ["u7", "u3", "u99"]
        with pytest.raises(KeyError):
            store.keys_of(np.array([1000]))
        assert store.keys_of(np.array([1, 1000]), strict=False) == \
            ["u0", None]

    def test_persistent_reopen_no_replay(self, tmp_path):
        path = str(tmp_path / "k.sqlite")
        s = KeyStore(path)
        s.translate([f"u{i}" for i in range(1000)], create=True)
        s.close()
        s2 = KeyStore(path)
        try:
            # no replay: nothing enters the cache until it is read
            assert s2.cache_info()["key2id"] == 0
            assert len(s2) == 1000
            assert s2.translate(["u500"]) == [501]
            assert s2.translate(["new"], create=True) == [1001]
        finally:
            s2.close()

    def test_cache_bounded(self, tmp_path):
        s = KeyStore(str(tmp_path / "k.sqlite"), cache_size=64)
        try:
            s.translate([f"u{i}" for i in range(1000)], create=True)
            info = s.cache_info()
            assert info["key2id"] <= 64
            s.keys_of(np.arange(1, 1001))
            assert s.cache_info()["id2key"] <= 64
            # evicted entries still resolve (from sqlite, not the cache)
            assert s.translate(["u0"]) == [1]
            assert s.key_of(1) == "u0"
        finally:
            s.close()

    def test_tail_paged(self, store):
        store.translate([f"u{i}" for i in range(10)], create=True)
        assert store.tail(0, limit=4) == ["u0", "u1", "u2", "u3"]
        assert store.tail(4, limit=4) == ["u4", "u5", "u6", "u7"]
        assert store.tail(8) == ["u8", "u9"]
        assert store.tail(10) == []

    def test_append_replicated_overlap_and_gap(self, store):
        store.append_replicated(1, ["a", "b"])
        # overlapping batches dedupe by position
        store.append_replicated(1, ["a", "b", "c"])
        assert store.translate(["a", "b", "c"]) == [1, 2, 3]
        with pytest.raises(KeyError):
            store.append_replicated(10, ["z"])

    def test_legacy_log_migration(self, tmp_path):
        # write a pre-round-5 CRC-framed .keys log, open the sqlite
        # store next to it: same IDs, log renamed, nothing lost
        legacy = str(tmp_path / "f.keys")
        with open(legacy, "wb") as f:
            for key in ["alice", "bob", "carol"]:
                body = struct.pack("<I", len(key)) + key.encode()
                f.write(struct.pack("<I", zlib.crc32(body)) + body)
            f.write(b"\x01\x02")  # torn tail record — ignored
        s = KeyStore(str(tmp_path / "f.sqlite"))
        try:
            assert s.translate(["alice", "bob", "carol"]) == [1, 2, 3]
            assert len(s) == 3
            assert not os.path.exists(legacy)
            assert os.path.exists(legacy + ".migrated")
            # migration runs once — a reopen must not re-apply
            s.translate(["dave"], create=True)
        finally:
            s.close()
        s2 = KeyStore(str(tmp_path / "f.sqlite"))
        try:
            assert len(s2) == 4
        finally:
            s2.close()

    def test_concurrent_translate(self, store):
        import threading
        errs = []

        def worker(base):
            try:
                for i in range(50):
                    store.translate([f"k{base}-{i}", "shared"], create=True)
            except Exception as e:  # noqa: BLE001
                errs.append(e)

        ts = [threading.Thread(target=worker, args=(b,)) for b in range(4)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert not errs
        # 4*50 unique keys + 1 shared, dense sequential IDs
        assert len(store) == 201
        ids = store.translate([f"k{b}-{i}" for b in range(4)
                               for i in range(50)])
        assert sorted(ids + store.translate(["shared"])) == \
            list(range(1, 202))


class TestTranslateStore:
    def test_paths_and_drop(self, tmp_path):
        ts = TranslateStore(str(tmp_path))
        ts.columns("i").translate(["c1"], create=True)
        ts.rows("i", "f").translate(["r1"], create=True)
        assert os.path.exists(tmp_path / "i" / "_keys" / "_columns.sqlite")
        assert os.path.exists(tmp_path / "i" / "_keys" / "f.sqlite")
        ts.drop("i", "f", remove_files=True)
        assert not os.path.exists(tmp_path / "i" / "_keys" / "f.sqlite")
        # recreated field starts fresh
        assert ts.rows("i", "f").translate(["r1"]) == [None]
        ts.close()

    def test_cache_size_flows_through(self, tmp_path):
        ts = TranslateStore(str(tmp_path), cache_size=16)
        assert ts.columns("i").cache_info()["cap"] == 16
        ts.close()

    def test_default_cache_cap(self, tmp_path):
        ts = TranslateStore(str(tmp_path))
        assert ts.columns("i").cache_info()["cap"] == DEFAULT_CACHE_SIZE
        ts.close()


def test_partition_stable():
    # placement parity: FNV-1a over the key, mod 256 — pinned values so
    # a refactor can't silently re-partition existing clusters
    assert partition_of("") == fnv_expected("")
    assert partition_of("alice") == fnv_expected("alice")


def fnv_expected(key: str) -> int:
    h = 0xCBF29CE484222325
    for b in key.encode():
        h ^= b
        h = (h * 0x100000001B3) & 0xFFFFFFFFFFFFFFFF
    return h % 256
