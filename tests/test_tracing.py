"""End-to-end distributed query tracing (r9 tentpole): traceparent
validation, always-on sampled tracing with `X-Pilosa-Trace-Id` +
`/internal/traces?trace_id=` lookup, slow-query capture behind
`/debug/slow`, and the headline claim — a 3-node `profile=true` query
returns ONE span tree containing node-tagged spans from every node,
with per-stage children and intact parent linkage."""

import json
import urllib.request

import pytest

from pilosa_tpu.api import API, Client, Server
from pilosa_tpu.engine.words import SHARD_WIDTH
from pilosa_tpu.obs import Stats, Tracer, parse_traceparent
from pilosa_tpu.store import Holder
from pilosa_tpu.testing import run_cluster


def walk(span: dict):
    yield span
    for child in span.get("children", []):
        yield from walk(child)


class TestTraceparentValidation:
    """Satellite: Tracer.extract must treat any malformed traceparent
    as absent — fresh root span, never an exception, never a fabricated
    trace identity."""

    @pytest.mark.parametrize("bad", [
        None, "", "00-aaaa-bbbb",            # too few segments
        "00-aaaa-bbbb-01-ff",                # too many segments
        "00--bbbb-01", "00-aaaa--01",        # empty ids
        "00-zzzz-bbbb-01", "00-aaaa-qqqq-01",  # non-hex ids
        # int(x, 16) literal quirks are NOT hex ids: underscores,
        # signs, surrounding whitespace
        "00-1_f-bbbb-01", "00-aaaa-+2a-01", "00- 2a -bbbb-01",
    ])
    def test_malformed_rejected(self, bad):
        assert parse_traceparent(bad) is None

    def test_wellformed_accepted(self):
        assert parse_traceparent("00-deadbeef-cafe-01") == \
            ("deadbeef", "cafe", "01")
        # flags ride through verbatim (the retain decision)
        assert parse_traceparent("00-deadbeef-cafe-00")[2] == "00"

    @pytest.mark.parametrize("header", [
        "00-aaaa-bbbb-01-junk", "garbage", "00-xyzw-bbbb-01",
    ])
    def test_extract_falls_back_to_fresh_root(self, header):
        t = Tracer()
        with t.extract({"Traceparent": header}, "server-side") as s:
            assert s.parent_id is None     # fresh root, not continuation
            assert s.trace_id not in ("aaaa", "xyzw")
        (root,) = t.finished()
        assert root.name == "server-side"

    def test_extract_garbage_never_raises_or_pollutes(self):
        t = Tracer()
        with t.extract({"Traceparent": "1-2"}, "a"):
            pass
        # the thread-local stack is balanced after a malformed header
        # (a stale synthetic parent would corrupt every later trace)
        with t.span("clean") as s:
            assert s.parent_id is None


@pytest.fixture
def traced_srv(tmp_path):
    holder = Holder(str(tmp_path)).open()
    api = API(holder, trace_sample_rate=1.0, slow_query_threshold=0.0)
    server = Server(api, "127.0.0.1", 0, stats=Stats()).start()
    client = Client("127.0.0.1", server.address[1])
    client.create_index("i")
    client.create_field("i", "f")
    client.query("i", "Set(1, f=1)")
    yield api, server, client
    server.close()
    holder.close()


def _post_query(port, pql, qs=""):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/index/i/query{qs}",
        data=pql.encode(), method="POST")
    with urllib.request.urlopen(req) as resp:
        return json.loads(resp.read()), dict(resp.headers)


class TestSampledTracing:
    def test_trace_id_header_on_every_response(self, traced_srv):
        _, server, _ = traced_srv
        body, headers = _post_query(server.address[1], "Count(Row(f=1))")
        assert body == {"results": [1]}  # trace id rides a HEADER only
        assert headers["X-Pilosa-Trace-Id"]

    def test_sampled_trace_resolvable_by_id(self, traced_srv):
        _, server, c = traced_srv
        _, headers = _post_query(server.address[1], "Count(Row(f=1))")
        tid = headers["X-Pilosa-Trace-Id"]
        traces = c._json("GET",
                         f"/internal/traces?trace_id={tid}")["traces"]
        assert len(traces) == 1
        spans = list(walk(traces[0]))
        assert traces[0]["traceId"] == tid
        assert any(s["name"] == "executor.Count" for s in spans)
        assert any(s["name"].startswith("stage.") for s in spans)

    def test_unsampled_not_retained(self, traced_srv):
        api, server, c = traced_srv
        api.trace_sample_rate = 0.0
        _, headers = _post_query(server.address[1], "Count(Row(f=1))")
        tid = headers["X-Pilosa-Trace-Id"]  # header still present
        assert c._json("GET",
                       f"/internal/traces?trace_id={tid}")["traces"] == []

    def test_sampled_counter_on_metrics(self, tmp_path):
        from pilosa_tpu.exec import Executor
        holder = Holder(str(tmp_path)).open()
        stats = Stats()
        api = API(holder, Executor(holder, stats=stats),
                  trace_sample_rate=1.0, slow_query_threshold=0.0)
        server = Server(api, "127.0.0.1", 0, stats=stats).start()
        c = Client("127.0.0.1", server.address[1])
        try:
            c.create_index("i")
            c.create_field("i", "f")
            c.query("i", "Count(Row(f=1))")
            assert "trace_sampled_total 1" in c.metrics_text()
        finally:
            server.close()
            holder.close()

    def test_proto_response_carries_trace_header(self, traced_srv):
        from pilosa_tpu.api import proto
        _, server, _ = traced_srv
        req = urllib.request.Request(
            f"http://127.0.0.1:{server.address[1]}/index/i/query",
            data=b"Count(Row(f=1))", method="POST",
            headers={"Accept": proto.CONTENT_TYPE})
        with urllib.request.urlopen(req) as resp:
            assert resp.headers["X-Pilosa-Trace-Id"]
            assert proto.decode_query_response(resp.read())["results"] \
                == [1]


class TestSlowQueryCapture:
    def test_slow_query_recorded_with_span_tree(self, tmp_path):
        holder = Holder(str(tmp_path)).open()
        stats = Stats()
        from pilosa_tpu.exec import Executor
        api = API(holder, Executor(holder, stats=stats),
                  trace_sample_rate=0.0, slow_query_threshold=1e-9)
        server = Server(api, "127.0.0.1", 0, stats=stats).start()
        c = Client("127.0.0.1", server.address[1])
        try:
            c.create_index("i")
            c.create_field("i", "f")
            c.query("i", "Set(1, f=1)")
            c.query("i", "Count(Row(f=1))", )
            slow = c._json("GET", "/debug/slow")
            assert slow["thresholdSeconds"] == 1e-9
            assert slow["total"] >= 2 and slow["kept"] >= 2
            entry = slow["slow"][0]  # newest first
            assert entry["pql"] == "Count(Row(f=1))"
            assert entry["index"] == "i" and entry["durationMs"] > 0
            assert entry["traceId"]
            # r19 satellite: every slow entry names which path
            # answered — triage starts with "was this on the fast
            # path at all"
            assert entry["path"] in (
                "fused", "op-at-a-time fallback", "paged",
                "row-directory oracle", "degraded governor")
            spans = list(walk(entry["profile"]))
            assert any(s["name"] == "executor.Count" for s in spans)
            # slow traces are retained: the id resolves in the ring
            got = c._json(
                "GET",
                f"/internal/traces?trace_id={entry['traceId']}")["traces"]
            assert len(got) == 1
            # counter + /status visibility
            text = c.metrics_text()
            assert "slow_query_total" in text
            st = c.status()
            assert st["slowQueries"]["total"] >= 2
            assert st["slowQueries"]["slowestMs"] > 0
        finally:
            server.close()
            holder.close()

    def test_threshold_zero_disables(self, traced_srv):
        api, server, c = traced_srv
        assert api.slow_query_threshold == 0.0
        _post_query(server.address[1], "Count(Row(f=1))")
        assert c._json("GET", "/debug/slow")["total"] == 0

    def test_slow_ring_is_bounded(self):
        from pilosa_tpu.obs import SlowQueryLog
        log = SlowQueryLog(keep=4)
        for i in range(10):
            log.record({"durationMs": float(i)})
        s = log.summary()
        assert s["total"] == 10 and s["kept"] == 4
        assert [e["durationMs"] for e in log.entries()] == \
            [9.0, 8.0, 7.0, 6.0]

    def test_diagnostics_payload_carries_slow_summary(self, tmp_path):
        from pilosa_tpu.obs import SlowQueryLog
        from pilosa_tpu.obs.diagnostics import build_payload
        h = Holder(str(tmp_path)).open()
        log = SlowQueryLog()
        log.record({"durationMs": 12.0})
        p = build_payload(h, slow_log=log)
        assert p["slowQueries"]["total"] == 1
        h.close()


class TestLiteTracePath:
    """ISSUE 7 satellite: the retention decision (sampling / profile /
    slow-hunt floor) is made BEFORE any span materializes — an
    unsampled, unprofiled query must never build a span tree, while
    keeping its X-Pilosa-Trace-Id and slow-query capture."""

    @pytest.fixture
    def api_holder(self, tmp_path):
        holder = Holder(str(tmp_path)).open()
        api = API(holder, trace_sample_rate=0.0,
                  slow_query_threshold=0.0)
        yield api, holder
        holder.close()

    def _seed(self, api):
        api.create_index("i")
        api.create_field("i", "f")
        api.query("i", "Set(1, f=1)")

    def test_unsampled_query_builds_no_spans(self, api_holder,
                                             monkeypatch):
        """Pin the structural fix: Tracer.span (the tree builder) is
        never entered for an unsampled, unprofiled query — but the
        response still carries a trace id."""
        import pilosa_tpu.obs.tracing as tr
        api, _ = api_holder
        self._seed(api)
        calls = []
        orig = tr.Tracer.span

        def counting(self, name, **tags):
            calls.append(name)
            return orig(self, name, **tags)

        monkeypatch.setattr(tr.Tracer, "span", counting)
        out = api.query("i", "Count(Row(f=1))")
        assert out["results"] == [1] and out["traceId"]
        assert calls == [], f"unsampled query materialized: {calls}"
        # the SAME query profiled builds the full tree
        out = api.query("i", "Count(Row(f=1))", profile=True)
        assert any(n == "query" for n in calls)
        assert any(n.startswith("executor.") for n in calls)
        spans = list(walk(out["profile"][0]))
        assert any(s["name"].startswith("stage.") for s in spans)

    def test_slow_hunt_threshold_materializes_full_trees(self,
                                                         api_holder):
        """slow_query_threshold at/under SLOW_TRACE_FLOOR = the
        operator is slow-hunting: full executor trees on capture (the
        pre-r12 slow-capture contract, unchanged)."""
        api, _ = api_holder
        self._seed(api)
        api.slow_query_threshold = 1e-9
        assert api.slow_query_threshold <= api.SLOW_TRACE_FLOOR
        api.query("i", "Count(Row(f=1))")
        entry = api.slow_log.entries()[0]
        spans = list(walk(entry["profile"]))
        assert any(s["name"].startswith("executor.") for s in spans)

    def test_lite_slow_capture_has_stage_breakdown(self, api_holder):
        """A slow query on the LITE path (threshold above the floor)
        is still captured — PQL, duration, trace id, and a root with
        the per-stage breakdown — and its id resolves in the ring;
        only the per-call executor spans are absent (they were never
        built)."""
        from pilosa_tpu.obs import GLOBAL_TRACER
        api, _ = api_holder
        self._seed(api)
        api.slow_query_threshold = 1e-9
        api.SLOW_TRACE_FLOOR = 0.0  # instance override: stay lite
        out = api.query("i", "Count(Row(f=1))")
        entry = api.slow_log.entries()[0]
        assert entry["pql"] == "Count(Row(f=1))"
        assert entry["durationMs"] > 0
        assert entry["traceId"] == out["traceId"]
        root = entry["profile"]
        assert root["tags"].get("liteTrace") is True
        names = {s["name"] for s in walk(root)}
        assert any(n.startswith("stage.") for n in names)
        assert not any(n.startswith("executor.") for n in names)
        assert any(s.trace_id == out["traceId"]
                   for s in GLOBAL_TRACER.finished())

    def test_lite_trace_id_unique_per_request(self, api_holder):
        api, _ = api_holder
        self._seed(api)
        ids = {api.query("i", "Count(Row(f=1))")["traceId"]
               for _ in range(16)}
        assert len(ids) == 16


class TestDistributedProfile:
    """Acceptance: a 3-node profile=true query returns a SINGLE span
    tree containing node-tagged spans from all 3 nodes, with per-stage
    children, and remote spans parent-linked to the coordinator's
    cluster.* span."""

    @staticmethod
    def _write_until_all_nodes_own(cl, c, want_nodes: int) -> int:
        """Grow the shard set until every node owns at least one shard
        (ownership is hash-placed over random test ports, so a fixed
        shard count would flake); returns the shard count."""
        n_shards = 0
        while True:
            n_shards += 8
            assert n_shards <= 64, "placement never covered every node"
            c.query("i", "".join(f"Set({s * SHARD_WIDTH + 1}, f=1)"
                                 for s in range(n_shards)))
            groups = cl.servers[0].cluster.group_shards_by_node(
                "i", tuple(range(n_shards)))
            if len(groups) == want_nodes:
                return n_shards

    def test_three_node_single_tree(self, tmp_path):
        with run_cluster(3, str(tmp_path)) as cl:
            c = cl.client(0)
            c.create_index("i")
            c.create_field("i", "f")
            n_shards = self._write_until_all_nodes_own(cl, c, 3)
            port = cl.servers[0].http.address[1]
            body, headers = _post_query(port, "Count(Row(f=1))",
                                        qs="?profile=true")
            assert body["results"] == [n_shards]
            (root,) = body["profile"]          # ONE tree
            assert root["name"] == "query"
            spans = list(walk(root))
            by_id = {s["spanId"]: s for s in spans}
            node_ids = set(cl.node_ids())
            seen_nodes = {s["tags"].get("node") for s in spans
                          if s["tags"].get("node")}
            assert seen_nodes == node_ids, \
                f"spans missing nodes: {node_ids - seen_nodes}"
            # one trace id spans the whole tree, and it is the header's
            assert {s["traceId"] for s in spans} == \
                {headers["X-Pilosa-Trace-Id"]}
            # remote continuation spans hang off the coordinator's
            # cluster.* span: parent linkage intact across the wire
            remotes = [s for s in spans if s["name"] == "internal.query"]
            assert len(remotes) >= 2  # both peers contributed
            for r in remotes:
                parent = by_id.get(r["parentId"])
                assert parent is not None and \
                    parent["name"].startswith("cluster."), \
                    f"remote span not grafted under cluster.*: {r}"
                # per-stage children on the REMOTE side too
                sub = list(walk(r))
                assert any(s["name"].startswith("stage.") for s in sub)
                assert any(s["name"].startswith("executor.")
                           for s in sub)
            # per-stage children on the coordinator side
            assert any(s["name"].startswith("stage.") for s in spans)

    def test_remote_node_ring_keeps_its_fragment(self, tmp_path):
        """Every involved node can resolve the trace id for ITS spans
        via /internal/traces?trace_id= (the runbook's per-node view)."""
        with run_cluster(2, str(tmp_path)) as cl:
            c = cl.client(0)
            c.create_index("i")
            c.create_field("i", "f")
            self._write_until_all_nodes_own(cl, c, 2)
            port = cl.servers[0].http.address[1]
            body, headers = _post_query(port, "Count(Row(f=1))",
                                        qs="?profile=true")
            tid = headers["X-Pilosa-Trace-Id"]
            spans = [s for root in body["profile"] for s in walk(root)]
            peer_id = cl.servers[1].cluster.node_id
            assert any(s["tags"].get("node") == peer_id for s in spans)
            got = cl.client(1)._json(
                "GET", f"/internal/traces?trace_id={tid}")["traces"]
            assert got and all(t["traceId"] == tid for t in got)
            assert any(s["name"].startswith("executor.")
                       for t in got for s in walk(t))

    def test_unsampled_legs_do_not_churn_peer_ring(self, tmp_path):
        """A lite-path query (rate=0, no profile, no slow-hunt
        threshold) propagates its trace IDENTITY with flags "00":
        peers build NO subtree and must NOT record anything into
        their own 128-slot ring (at serving rates that churn would
        evict every trace an operator is actually chasing).  Full
        remote subtrees require the materialize decision — sampling,
        profile, or a slow-hunt threshold at/under SLOW_TRACE_FLOOR,
        which flips the flags to "01"."""
        with run_cluster(2, str(tmp_path), trace_sample_rate=0.0,
                         slow_query_threshold=0.0) as cl:
            c = cl.client(0)
            c.create_index("i")
            c.create_field("i", "f")
            self._write_until_all_nodes_own(cl, c, 2)
            port = cl.servers[0].http.address[1]
            _, headers = _post_query(port, "Count(Row(f=1))")
            tid = headers["X-Pilosa-Trace-Id"]
            for i in (0, 1):
                assert cl.client(i)._json(
                    "GET",
                    f"/internal/traces?trace_id={tid}")["traces"] == []
            # a slow-HUNT threshold (<= SLOW_TRACE_FLOOR) promotes
            # queries to the materializing path with flags "02": slow
            # captures carry the peers' remote subtrees, but peers
            # STILL don't churn their rings — at serving rates that
            # churn would evict the very traces being chased
            cl.servers[0].api.slow_query_threshold = 1e-9
            body, headers = _post_query(port, "Count(Row(f=1))")
            slow = c._json("GET", "/debug/slow")["slow"][0]
            peer_id = cl.servers[1].cluster.node_id
            assert any(s["tags"].get("node") == peer_id
                       for s in walk(slow["profile"]))
            # the coordinator's slow retention legitimately records
            # the "query" root (nodes share one in-process ring here);
            # what must NOT appear is a peer-side "internal.query"
            # continuation root — that's what flags "01" would have
            # ring-retained and "02" must not
            got = cl.client(1)._json(
                "GET",
                f"/internal/traces?trace_id={slow['traceId']}")["traces"]
            assert not any(t["name"] == "internal.query" for t in got)
            # lite-path queries on a CLUSTER still accumulate per-call
            # marks (dist records them on the LiteTracer), so a lite
            # slow capture has a breakdown even when the coordinator
            # owns no shards
            from pilosa_tpu.obs import LiteTracer
            lt = LiteTracer()
            cl.servers[0].cluster.dist.execute_json(
                "i", "Count(Row(f=1))", tracer=lt)
            assert any(n.startswith("cluster.") for n, _ in lt.marks)


class TestSinglePaneJoin:
    """r14 acceptance: a slow query is traceable end-to-end — a
    ``query_stage_seconds`` exemplar → ``/internal/traces?trace_id=`` →
    JSON log lines carrying the same trace id."""

    def _boot(self, tmp_path, **api_kw):
        from pilosa_tpu.exec import Executor
        holder = Holder(str(tmp_path)).open()
        stats = Stats()
        api = API(holder, Executor(holder, stats=stats), **api_kw)
        server = Server(api, "127.0.0.1", 0, stats=stats).start()
        return holder, server, Client("127.0.0.1", server.address[1])

    def test_exemplar_trace_and_logs_join_on_one_id(self, tmp_path):
        import io
        import logging as _logging
        holder, server, c = self._boot(
            tmp_path, trace_sample_rate=0.0, slow_query_threshold=1e-9)
        # route the pilosa_tpu logger through the JSON formatter into a
        # buffer (fresh handler so other tests' config can't interfere)
        from pilosa_tpu.obs import get_logger
        logger = _logging.getLogger("pilosa_tpu")
        saved = logger.handlers[:]
        logger.handlers = []
        buf = io.StringIO()
        get_logger(stream=buf, fmt="json")
        try:
            c.create_index("i")
            c.create_field("i", "f")
            c.query("i", "Set(1, f=1)")
            _, headers = _post_query(server.address[1], "Count(Row(f=1))")
            tid = headers["X-Pilosa-Trace-Id"]
            # leg 1: a latency bucket's exemplar names the trace (the
            # Count is the LATEST observation of every stage series,
            # so its id is the one the exemplars carry)
            assert [ln for ln in
                    c.metrics_text(openmetrics=True).splitlines()
                    if ln.startswith("query_stage_seconds_bucket")
                    and f'trace_id="{tid}"' in ln]
            # the classic 0.0.4 rendering must NOT carry the exemplar
            # (its parser rejects the suffix and fails the scrape)
            assert "trace_id" not in c.metrics_text()
            # leg 2: the id resolves to the retained span tree
            traces = c._json(
                "GET", f"/internal/traces?trace_id={tid}")["traces"]
            assert traces and traces[0]["traceId"] == tid
            # leg 3: the slow-capture log line carries the same id
            recs = [json.loads(ln)
                    for ln in buf.getvalue().splitlines()]
            slow = [r for r in recs if "slow query" in r["message"]]
            assert any(r.get("traceId") == tid for r in slow)
        finally:
            logger.handlers = saved
            server.close()
            holder.close()

    def test_lite_path_exemplar_carries_cheap_id(self, tmp_path):
        """The zero-span serving path still feeds exemplars: the
        LiteTracer's cheap id rides every stage observation (the
        config20 overhead bar holds because nothing else changes)."""
        holder, server, c = self._boot(
            tmp_path, trace_sample_rate=0.0, slow_query_threshold=1.0)
        try:
            c.create_index("i")
            c.create_field("i", "f")
            c.query("i", "Set(1, f=1)")
            _, headers = _post_query(server.address[1], "Count(Row(f=1))")
            tid = headers["X-Pilosa-Trace-Id"]
            assert [ln for ln in
                    c.metrics_text(openmetrics=True).splitlines()
                    if ln.startswith("query_stage_seconds_bucket")
                    and f'trace_id="{tid}"' in ln]
        finally:
            server.close()
            holder.close()
