"""Cluster observability pane (r14 tentpole): ``GET /metrics/cluster``
merges every live node's registry into ONE Prometheus document —
counters/gauges as per-node series under a ``node`` label, histograms
bucket-wise EXACT — and ``GET /status/cluster`` returns every node's
``/status`` keyed by node id.  A dead peer degrades both to partial +
``staleNodes``, never an error."""

from pilosa_tpu.engine.words import SHARD_WIDTH
from pilosa_tpu.testing import run_cluster


class TestClusterPane:
    def test_merged_document_is_bucket_exact(self, tmp_path):
        with run_cluster(3, str(tmp_path), heartbeat=0.1) as c:
            c.client(0).create_index("i")
            c.client(0).create_field("i", "f")
            cols = [s * SHARD_WIDTH for s in range(6)]
            c.client(0).import_bits("i", "f", rowIDs=[1] * 6,
                                    columnIDs=cols)
            for cl in c.clients:  # every node serves (and observes)
                (n,) = cl.query("i", "Count(Row(f=1))")
                assert n == 6
            # oracle: the per-node registry snapshots the fan-in merges
            # (no queries run between here and the scrape, so the
            # query_stage_seconds family is stable)
            snaps = {}
            for cl in c.clients:
                body = cl._json("GET", "/internal/metrics/snapshot")
                snaps[body["node"]] = body["snapshot"]
            ids = c.node_ids()
            assert set(snaps) == set(ids)

            text = c.client(0)._do("GET", "/metrics/cluster").decode()
            for nid in ids:
                assert f'cluster_metrics_node_up{{node="{nid}"}} 1' in text
            assert "cluster_metrics_stale_nodes 0" in text

            # histogram merge is bucket-exact: per label set, the
            # merged cumulative bucket counts equal the element-wise
            # sum of every node's raw counts (pinned against the
            # snapshots, not against the merge code)
            # a node owning no shard of the index never ran the
            # executor — the family is absent there, and the merge
            # covers the nodes that do report it
            fams = [s["histograms"]["query_stage_seconds"]
                    for s in snaps.values()
                    if "query_stage_seconds" in s["histograms"]]
            assert len(fams) >= 2  # fan-out legs observed on >1 node
            buckets = fams[0]["buckets"]
            expected: dict = {}
            for fam in fams:
                assert fam["buckets"] == buckets  # one version: agree
                for series in fam["series"]:
                    key = tuple(sorted(series["labels"].items()))
                    agg = expected.setdefault(
                        key, [0] * (len(buckets) + 1) + [0])
                    for i, cnt in enumerate(series["counts"]):
                        agg[i] += cnt
                    agg[-1] += series["count"]
            assert expected  # the three Counts observed stages
            for key, agg in expected.items():
                labels = ",".join(f'{k}="{v}"' for k, v in key)
                cum = 0
                for i, ub in enumerate(buckets):
                    cum += agg[i]
                    assert (f'query_stage_seconds_bucket{{{labels},'
                            f'le="{ub!r}"}} {cum}') in text
                cum += agg[len(buckets)]
                assert (f'query_stage_seconds_bucket{{{labels},'
                        f'le="+Inf"}} {cum}') in text
                assert (f'query_stage_seconds_count{{{labels}}} '
                        f'{agg[-1]}') in text

            # counters/gauges stay per-node under a node label
            for nid in ids:
                assert [ln for ln in text.splitlines()
                        if ln.startswith("http_requests_total{")
                        and f'node="{nid}"' in ln]

    def test_dead_peer_degrades_to_partial(self, tmp_path):
        with run_cluster(3, str(tmp_path), heartbeat=0.1) as c:
            ids = c.node_ids()
            st = c.client(0)._json("GET", "/status/cluster")
            assert set(st["nodes"]) == set(ids)
            assert st["staleNodes"] == []
            assert st["coordinator"] == c.servers[0].cluster.coordinator_id()

            victim = c.servers[2]
            vid = victim.cluster.node_id
            victim.close()
            # no liveness wait needed: the fan-in's own fetch failure
            # marks the peer stale (degraded, never an error)
            import urllib.request
            port = c.servers[0].http.address[1]
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/metrics/cluster",
                    timeout=10) as resp:
                assert resp.status == 200
                assert resp.headers["X-Pilosa-Stale-Nodes"] == vid
                text = resp.read().decode()
            assert f'cluster_metrics_node_up{{node="{vid}"}} 0' in text
            assert "cluster_metrics_stale_nodes 1" in text
            for nid in ids:
                if nid != vid:
                    assert (f'cluster_metrics_node_up{{node="{nid}"}} 1'
                            in text)

            st = c.client(0)._json("GET", "/status/cluster")
            assert st["staleNodes"] == [vid]
            assert set(st["nodes"]) == set(ids) - {vid}

    def test_single_node_serves_cluster_endpoints(self, tmp_path):
        """Without a cluster layer the pane degrades to one node: the
        endpoints answer (labelled ``local``) instead of 404ing — one
        dashboard works at every deployment size."""
        from pilosa_tpu.api import API, Client, Server
        from pilosa_tpu.exec import Executor
        from pilosa_tpu.obs import Stats
        from pilosa_tpu.store import Holder
        holder = Holder(str(tmp_path)).open()
        stats = Stats()
        api = API(holder, Executor(holder, stats=stats))
        server = Server(api, "127.0.0.1", 0, stats=stats).start()
        c = Client("127.0.0.1", server.address[1])
        try:
            c.create_index("i")
            c.create_field("i", "f")
            c.query("i", "Set(1, f=1)")
            text = c._do("GET", "/metrics/cluster").decode()
            assert 'cluster_metrics_node_up{node="local"} 1' in text
            assert "query_stage_seconds_bucket" in text
            st = c._json("GET", "/status/cluster")
            assert st["staleNodes"] == []
            assert "local" in st["nodes"]
        finally:
            server.close()
            holder.close()
