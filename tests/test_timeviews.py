"""Time-view planes (r23, ISSUE 18): the fused bucket-range scan must
answer every time-range shape bit-exactly like the op-at-a-time span
oracle (``Executor._time_row_span``), the static postfix tail
(Shift/Limit/ConstRow) must answer identically through the fused tree
path and the eager path, and in-bucket ingest must absorb into the
time plane's delta overlay with ZERO rebuilds."""

from datetime import datetime, timedelta

import numpy as np
import pytest

from pilosa_tpu.engine.words import SHARD_WIDTH
from pilosa_tpu.exec import ExecutionError, Executor
from pilosa_tpu.store import FieldOptions, Holder
from pilosa_tpu.store import timeq

T0 = datetime(2020, 1, 1)


def ts(h: int) -> str:
    return (T0 + timedelta(hours=h)).strftime("%Y-%m-%dT%H:%M")


@pytest.fixture
def env(tmp_path):
    holder = Holder(str(tmp_path)).open()
    idx = holder.create_index("i")
    idx.create_field("f")
    idx.create_field("t", FieldOptions(type="time", time_quantum="YMDH"))
    ex = Executor(holder)
    return holder, idx, ex


def q(ex, pql, index="i"):
    return ex.execute(index, pql)


def seed_events(idx, events):
    """events: list of (row, col, hour)."""
    rows = np.array([e[0] for e in events], np.uint64)
    cols = np.array([e[1] for e in events], np.uint64)
    stamps = [T0 + timedelta(hours=e[2]) for e in events]
    idx.field("t").import_bits(rows, cols, stamps)


def oracle_cols(ex, field, row_id, start, end):
    """Columns via the op-at-a-time span oracle, directly."""
    from pilosa_tpu.exec.executor import _Ctx
    idx = ex.holder.index("i")
    ctx = _Ctx(idx, tuple(idx.available_shards()), False)
    words = ex._time_row_span(ctx, field, row_id, start, end)
    host = np.asarray(words)
    out = []
    for si, s in enumerate(ctx.shards):
        w = host[si]
        bits = np.unpackbits(w.view(np.uint8), bitorder="little")
        out.extend(int(s) * SHARD_WIDTH + int(o)
                   for o in np.nonzero(bits)[0])
    return sorted(out)


class TestFusedVsOracle:
    """The tentpole equivalence: fused bucket-range union over finest
    existing buckets == the oracle's mixed-granularity minimal cover,
    for random and boundary ranges."""

    def test_random_ranges_bit_exact(self, env):
        holder, idx, ex = env
        rng = np.random.default_rng(18)
        events = []
        for i in range(120):
            row = int(rng.integers(1, 4))
            col = int(rng.integers(0, 3) * SHARD_WIDTH
                      + rng.integers(0, 200))
            h = int(rng.integers(0, 72))
            events.append((row, col, h))
        seed_events(idx, events)
        field = idx.field("t")
        for _ in range(25):
            row = int(rng.integers(1, 4))
            h0 = int(rng.integers(0, 72))
            h1 = int(rng.integers(h0, 73))
            (r,) = q(ex, f"Row(t={row}, from={ts(h0)}, to={ts(h1)})")
            start = T0 + timedelta(hours=h0)
            end = T0 + timedelta(hours=h1)
            want = oracle_cols(ex, field, row, start, end)
            assert [int(c) for c in r.columns] == want, (row, h0, h1)

    def test_quantum_boundary_ranges(self, env):
        """Endpoints exactly on / just off year, month, day and hour
        boundaries — the minimal-cover recursion's edge cases."""
        holder, idx, ex = env
        # events at the edges of calendar units
        events = [(1, 1, 0),          # 2020-01-01T00
                  (1, 2, 23),         # 2020-01-01T23
                  (1, 3, 24),         # 2020-01-02T00
                  (1, 4, 31 * 24),    # 2020-02-01T00
                  (1, 5, 31 * 24 - 1)]  # 2020-01-31T23
        seed_events(idx, events)
        field = idx.field("t")
        cases = [(0, 24), (0, 23), (1, 24), (23, 25), (24, 31 * 24),
                 (0, 31 * 24), (31 * 24 - 1, 31 * 24 + 1),
                 (0, 31 * 24 + 1)]
        for h0, h1 in cases:
            (r,) = q(ex, f"Row(t=1, from={ts(h0)}, to={ts(h1)})")
            want = oracle_cols(ex, field, 1,
                               T0 + timedelta(hours=h0),
                               T0 + timedelta(hours=h1))
            assert [int(c) for c in r.columns] == want, (h0, h1)

    def test_omitted_bounds_clamp_to_existing_span(self, env):
        """from/to omitted (one or both) clamps to the covered span —
        same answer fused and oracle, no calendar enumeration."""
        holder, idx, ex = env
        seed_events(idx, [(1, 1, 0), (1, 2, 48), (1, 3, 71)])
        field = idx.field("t")
        (r,) = q(ex, f"Row(t=1, from={ts(24)})")
        assert [int(c) for c in r.columns] == \
            oracle_cols(ex, field, 1, T0 + timedelta(hours=24), None)
        (r,) = q(ex, f"Row(t=1, to={ts(49)})")
        assert [int(c) for c in r.columns] == \
            oracle_cols(ex, field, 1, None, T0 + timedelta(hours=49))
        # half-open: the hour-48 event is INSIDE to=49, outside to=48
        assert [int(c) for c in r.columns] == [1, 2]
        (r,) = q(ex, f"Row(t=1, to={ts(48)})")
        assert [int(c) for c in r.columns] == [1]

    def test_legacy_positional_range(self, env):
        """Range(f=1, <ts>, <ts>) — positional timestamps land in
        _timestamp/_timestamp2 and must hit the same fused path."""
        holder, idx, ex = env
        seed_events(idx, [(1, 1, 0), (1, 2, 30), (1, 3, 60)])
        (r,) = q(ex, f"Range(t=1, {ts(0)}, {ts(31)})")
        assert [int(c) for c in r.columns] == [1, 2]
        want = oracle_cols(ex, idx.field("t"), 1, T0,
                           T0 + timedelta(hours=31))
        assert [int(c) for c in r.columns] == want

    def test_empty_range_and_absent_row(self, env):
        holder, idx, ex = env
        seed_events(idx, [(1, 1, 5)])
        (r,) = q(ex, f"Row(t=1, from={ts(10)}, to={ts(10)})")
        assert len(r.columns) == 0
        (r,) = q(ex, f"Row(t=1, from={ts(6)}, to={ts(5)})")  # inverted
        assert len(r.columns) == 0
        (r,) = q(ex, f"Row(t=99, from={ts(0)}, to={ts(10)})")
        assert len(r.columns) == 0

    def test_not_a_time_field_errors(self, env):
        holder, idx, ex = env
        q(ex, "Set(1, f=1)")
        with pytest.raises(ExecutionError, match="not a time field"):
            q(ex, f"Row(f=1, from={ts(0)}, to={ts(1)})")


class TestTimeqCover:
    """store.timeq minimal-cover edge cases the plane's bucket-range
    equivalence rests on."""

    def test_cover_prefers_coarse_units(self):
        views = timeq.views_by_time_range(
            "standard", datetime(2020, 1, 1), datetime(2021, 1, 1),
            "YMDH")
        assert views == ["standard_2020"]

    def test_cover_splits_partial_units(self):
        views = timeq.views_by_time_range(
            "standard", datetime(2020, 1, 31, 22), datetime(2020, 3, 1),
            "YMDH")
        assert views == ["standard_2020013122", "standard_2020013123",
                         "standard_202002"]

    def test_cover_floors_sub_unit_endpoints(self):
        # minutes floor away at the finest unit (H)
        a = timeq.views_by_time_range(
            "standard", datetime(2020, 1, 1, 3, 59),
            datetime(2020, 1, 1, 5, 1), "YMDH")
        b = timeq.views_by_time_range(
            "standard", datetime(2020, 1, 1, 3),
            datetime(2020, 1, 1, 5), "YMDH")
        assert a == b

    def test_cover_empty_for_inverted_range(self):
        assert timeq.views_by_time_range(
            "standard", datetime(2020, 2, 1), datetime(2020, 1, 1),
            "YMDH") == []

    def test_bucket_range_floors_endpoints(self, env):
        """TimePlaneSet.bucket_range matches the oracle's truncation:
        bucket b is selected iff floor(start) <= start_b < floor(end)."""
        holder, idx, ex = env
        seed_events(idx, [(1, 1, 0), (1, 2, 1), (1, 3, 2)])
        tps = ex.planes.time_plane_nowait("i", idx.field("t"),
                                          tuple(idx.available_shards()))
        assert tps is not None and tps.n_buckets == 3
        # minutes inside hour 1 floor to hour 1
        b0, b1 = tps.bucket_range(T0 + timedelta(hours=1, minutes=30),
                                  T0 + timedelta(hours=2, minutes=59))
        assert (b0, b1) == (1, 2)
        assert tps.bucket_range(None, None) == (0, 3)
        b0, b1 = tps.bucket_range(T0 + timedelta(hours=9),
                                  T0 + timedelta(hours=10))
        assert b0 == b1  # off the end: empty


class TestStaticTreeOps:
    """Shift/Limit/ConstRow through the fused tree path answer exactly
    like the eager op-at-a-time path, including error parity."""

    def seed(self, ex):
        cols = [1, 40, SHARD_WIDTH - 1, SHARD_WIDTH + 3,
                2 * SHARD_WIDTH + 7]
        q(ex, " ".join(f"Set({c}, f=1)" for c in cols))
        return cols

    def test_shift_tree_vs_eager(self, env, tmp_path):
        holder, idx, ex = env
        self.seed(ex)
        eager = Executor(holder, tree_fusion=False)
        for n in (0, 1, 40):
            pql = f"Count(Shift(Row(f=1), n={n}))"
            assert q(ex, pql) == q(eager, pql), n

    def test_limit_tree_vs_eager(self, env):
        holder, idx, ex = env
        cols = self.seed(ex)
        eager = Executor(holder, tree_fusion=False)
        for off, lim in [(0, 2), (1, 2), (2, None), (4, 10), (0, None),
                         (3, 1), (99, 2)]:
            lim_s = "" if lim is None else f", limit={lim}"
            pql = f"Limit(Row(f=1), offset={off}{lim_s})"
            (a,) = q(ex, pql)
            (b,) = q(eager, pql)
            want = cols[off:(None if lim is None else off + lim)]
            assert [int(c) for c in a.columns] == want, (off, lim)
            assert [int(c) for c in b.columns] == want, (off, lim)

    def test_constrow_tree_vs_eager(self, env):
        holder, idx, ex = env
        self.seed(ex)
        eager = Executor(holder, tree_fusion=False)
        pql = ("Count(Intersect(Row(f=1), "
               f"ConstRow(columns=[1, 40, {3 * SHARD_WIDTH}])))")
        assert q(ex, pql) == q(eager, pql) == [2]

    def test_compound_static_and_time(self, env):
        """A tree mixing a time-range leaf, a static Shift and a plain
        anchor row — the full r23 tail in one program."""
        holder, idx, ex = env
        seed_events(idx, [(1, 1, 0), (1, 2, 30), (2, 2, 10)])
        q(ex, "Set(1, f=1) Set(2, f=1) Set(3, f=1)")
        eager = Executor(holder, tree_fusion=False)
        pql = (f"Count(Intersect(Row(f=1), "
               f"Shift(Row(t=1, from={ts(0)}, to={ts(31)}), n=0)))")
        assert q(ex, pql) == q(eager, pql) == [2]

    def test_error_parity(self, env):
        holder, idx, ex = env
        self.seed(ex)
        eager = Executor(holder, tree_fusion=False)
        for pql, msg in [
                ("Count(Shift(Row(f=1), n=-1))", "n must be in"),
                (f"Count(Shift(Row(f=1), n={SHARD_WIDTH}))",
                 "n must be in"),
                ("Count(Limit(Row(f=1), limit=-1))", "must be >= 0"),
                ("Count(Limit(Row(f=1), offset=-2))", "must be >= 0"),
                ("Count(ConstRow())", "missing columns")]:
            with pytest.raises(ExecutionError, match=msg):
                q(ex, pql)
            with pytest.raises(ExecutionError, match=msg):
                q(eager, pql)


class TestIngestAbsorb:
    """Time-bucketed ingest into EXISTING buckets absorbs into the
    plane's delta overlay: zero rebuilds, answers exact."""

    def test_in_bucket_write_absorbs(self, env):
        holder, idx, ex = env
        seed_events(idx, [(1, 1, 0), (1, 2, 5)])
        (r,) = q(ex, f"Row(t=1, from={ts(0)}, to={ts(6)})")
        assert [int(c) for c in r.columns] == [1, 2]
        builds0 = ex.planes.builds
        # same row, same hour bucket, new column -> overlay absorb
        seed_events(idx, [(1, 7, 5)])
        (r,) = q(ex, f"Row(t=1, from={ts(0)}, to={ts(6)})")
        assert [int(c) for c in r.columns] == [1, 2, 7]
        assert ex.planes.builds == builds0
        assert ex.planes.delta_absorbs >= 1
        # the absorbed bit respects bucket boundaries
        (r,) = q(ex, f"Row(t=1, from={ts(0)}, to={ts(5)})")
        assert [int(c) for c in r.columns] == [1]

    def test_new_bucket_rebuilds_and_serves(self, env):
        holder, idx, ex = env
        seed_events(idx, [(1, 1, 0)])
        q(ex, f"Row(t=1, from={ts(0)}, to={ts(1)})")
        builds0 = ex.planes.builds
        seed_events(idx, [(1, 2, 3)])  # fresh hour bucket
        (r,) = q(ex, f"Row(t=1, from={ts(0)}, to={ts(4)})")
        assert [int(c) for c in r.columns] == [1, 2]
        assert ex.planes.builds == builds0 + 1

    def test_new_row_rebuilds_and_serves(self, env):
        holder, idx, ex = env
        seed_events(idx, [(1, 1, 0)])
        q(ex, f"Row(t=1, from={ts(0)}, to={ts(1)})")
        seed_events(idx, [(5, 2, 0)])  # fresh row, existing bucket
        (r,) = q(ex, f"Row(t=5, from={ts(0)}, to={ts(1)})")
        assert [int(c) for c in r.columns] == [2]
        (r,) = q(ex, f"Row(t=1, from={ts(0)}, to={ts(1)})")
        assert [int(c) for c in r.columns] == [1]


class TestRowsTimeFilter:
    """Rows()/GroupBy from=/to= restrict candidates to the range's
    minimal view cover."""

    def test_rows_time_filtered(self, env):
        holder, idx, ex = env
        seed_events(idx, [(1, 1, 0), (2, 2, 30), (3, 3, 60)])
        (r,) = q(ex, f"Rows(t, from={ts(0)}, to={ts(31)})")
        assert sorted(int(x) for x in r.rows) == [1, 2]
        (r,) = q(ex, f"Rows(t, from={ts(31)})")
        assert sorted(int(x) for x in r.rows) == [3]
        (r,) = q(ex, "Rows(t)")
        assert sorted(int(x) for x in r.rows) == [1, 2, 3]

    def test_rows_time_filter_with_column(self, env):
        holder, idx, ex = env
        seed_events(idx, [(1, 9, 0), (2, 9, 30), (2, 1, 0)])
        (r,) = q(ex, f"Rows(t, column=9, from={ts(0)}, to={ts(1)})")
        assert sorted(int(x) for x in r.rows) == [1]
        (r,) = q(ex, "Rows(t, column=9)")
        assert sorted(int(x) for x in r.rows) == [1, 2]

    def test_groupby_time_filtered(self, env):
        holder, idx, ex = env
        seed_events(idx, [(1, 1, 0), (1, 2, 0), (2, 2, 30)])
        (g,) = q(ex, f"GroupBy(Rows(t, from={ts(0)}, to={ts(1)}))")
        got = {gc.group[0].row_id: gc.count for gc in g.groups}
        assert got == {1: 2}
        (g,) = q(ex, "GroupBy(Rows(t))")
        got = {gc.group[0].row_id: gc.count for gc in g.groups}
        assert got == {1: 2, 2: 1}


class TestStatusAndMetrics:
    def test_time_status_block(self, env):
        holder, idx, ex = env
        seed_events(idx, [(1, 1, 0), (1, 2, 5)])
        q(ex, f"Row(t=1, from={ts(0)}, to={ts(6)})")
        st = ex.time_status()
        assert st["planes"] and st["planes"][0]["field"] == "t"
        assert st["planes"][0]["buckets"] == 2
        assert st["residentBytes"] > 0

    def test_fallback_when_degraded(self, env):
        """A degraded device governor keeps time ranges OFF the fused
        plane path — answers still exact via the span oracle."""
        holder, idx, ex = env
        seed_events(idx, [(1, 1, 0), (1, 2, 30)])
        if ex.batcher is None:
            pytest.skip("no batcher wired")
        gov = ex.batcher.governor
        for _ in range(gov.FAULT_THRESHOLD):
            gov.record_fault()
        assert not gov.fastlane_ok()
        (r,) = q(ex, f"Row(t=1, from={ts(0)}, to={ts(31)})")
        assert [int(c) for c in r.columns] == [1, 2]
