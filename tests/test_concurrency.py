"""Concurrency stress: writers + readers racing on one holder/executor.

The reference runs its whole suite under ``go test -race`` (SURVEY.md
§5/§6); Python has no TSAN, so the mitigation is lock discipline
(per-fragment RLock, plane-cache generation invalidation) exercised
here under real thread contention: no exceptions, no torn reads, exact
final counts."""

import threading

import numpy as np
import pytest

from pilosa_tpu.exec import Executor
from pilosa_tpu.store import FieldOptions, Holder


@pytest.mark.parametrize("n_writers,n_readers", [(4, 4)])
def test_concurrent_writes_and_queries(tmp_path, n_writers, n_readers):
    holder = Holder(str(tmp_path)).open()
    idx = holder.create_index("i")
    idx.create_field("f")
    idx.create_field("amount", FieldOptions(type="int", min=0, max=10**6))
    ex = Executor(holder)

    per_writer = 300
    errors: list[Exception] = []
    start = threading.Barrier(n_writers + n_readers)

    def writer(wid: int):
        try:
            start.wait()
            rng = np.random.default_rng(wid)
            for i in range(per_writer):
                col = wid * per_writer + i
                ex.execute("i", f"Set({col}, f={wid})")
                if i % 7 == 0:
                    ex.execute("i", f"Set({col}, amount={int(rng.integers(1000))})")
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    def reader():
        try:
            start.wait()
            for _ in range(50):
                (n,) = ex.execute("i", "Count(All())")
                assert 0 <= n <= n_writers * per_writer
                ex.execute("i", "TopN(f, n=3)")
                ex.execute("i", "Sum(field=amount)")
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    threads = [threading.Thread(target=writer, args=(w,))
               for w in range(n_writers)]
    threads += [threading.Thread(target=reader) for _ in range(n_readers)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    assert not errors, errors[:3]

    # exact final state
    for w in range(n_writers):
        (cnt,) = ex.execute("i", f"Count(Row(f={w}))")
        assert cnt == per_writer, f"writer {w}"
    (total,) = ex.execute("i", "Count(All())")
    assert total == n_writers * per_writer


def test_concurrent_fragment_mutation(tmp_path):
    """Many threads hammering one fragment: bits must be a clean union."""
    from pilosa_tpu.store.fragment import Fragment
    frag = Fragment(str(tmp_path / "0"), 0, max_op_n=50).open()
    errors = []

    def worker(wid: int):
        try:
            cols = np.arange(wid * 1000, (wid + 1) * 1000, dtype=np.uint64)
            for chunk in np.array_split(cols, 10):
                frag.set_bits(np.full(len(chunk), 1, np.uint64), chunk)
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    threads = [threading.Thread(target=worker, args=(w,)) for w in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    assert not errors
    assert frag.row(1).cardinality == 8000
    # crash-replay under the concurrent op-log interleaving
    g = Fragment(str(tmp_path / "0"), 0).open()
    assert g.row(1).cardinality == 8000


def test_parallel_holder_open(tmp_path):
    h = Holder(str(tmp_path)).open()
    for i in range(5):
        idx = h.create_index(f"idx{i}")
        idx.create_field("f")
        idx.set_bit("f", 1, i * 10)
    h.close()
    h2 = Holder(str(tmp_path)).open()  # concurrent index opens
    assert sorted(h2.indexes) == [f"idx{i}" for i in range(5)]
    ex = Executor(h2)
    for i in range(5):
        assert ex.execute(f"idx{i}", "Count(Row(f=1))") == [1]
