"""Concurrency stress: writers + readers racing on one holder/executor.

The reference runs its whole suite under ``go test -race`` (SURVEY.md
§5/§6); Python has no TSAN, so the mitigation is lock discipline
(per-fragment RLock, plane-cache generation invalidation) exercised
here under real thread contention: no exceptions, no torn reads, exact
final counts."""

import threading
import time

import numpy as np
import pytest

from pilosa_tpu.exec import Executor
from pilosa_tpu.store import FieldOptions, Holder


@pytest.mark.parametrize("n_writers,n_readers", [(4, 4)])
def test_concurrent_writes_and_queries(tmp_path, n_writers, n_readers):
    holder = Holder(str(tmp_path)).open()
    idx = holder.create_index("i")
    idx.create_field("f")
    idx.create_field("amount", FieldOptions(type="int", min=0, max=10**6))
    ex = Executor(holder)

    per_writer = 300
    errors: list[Exception] = []
    start = threading.Barrier(n_writers + n_readers)

    def writer(wid: int):
        try:
            start.wait()
            rng = np.random.default_rng(wid)
            for i in range(per_writer):
                col = wid * per_writer + i
                ex.execute("i", f"Set({col}, f={wid})")
                if i % 7 == 0:
                    ex.execute("i", f"Set({col}, amount={int(rng.integers(1000))})")
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    def reader():
        try:
            start.wait()
            for _ in range(50):
                (n,) = ex.execute("i", "Count(All())")
                assert 0 <= n <= n_writers * per_writer
                ex.execute("i", "TopN(f, n=3)")
                ex.execute("i", "Sum(field=amount)")
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    threads = [threading.Thread(target=writer, args=(w,))
               for w in range(n_writers)]
    threads += [threading.Thread(target=reader) for _ in range(n_readers)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    assert not errors, errors[:3]

    # exact final state
    for w in range(n_writers):
        (cnt,) = ex.execute("i", f"Count(Row(f={w}))")
        assert cnt == per_writer, f"writer {w}"
    (total,) = ex.execute("i", "Count(All())")
    assert total == n_writers * per_writer


def test_concurrent_fragment_mutation(tmp_path):
    """Many threads hammering one fragment: bits must be a clean union."""
    from pilosa_tpu.store.fragment import Fragment
    frag = Fragment(str(tmp_path / "0"), 0, max_op_n=50).open()
    errors = []

    def worker(wid: int):
        try:
            cols = np.arange(wid * 1000, (wid + 1) * 1000, dtype=np.uint64)
            for chunk in np.array_split(cols, 10):
                frag.set_bits(np.full(len(chunk), 1, np.uint64), chunk)
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    threads = [threading.Thread(target=worker, args=(w,)) for w in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    assert not errors
    assert frag.row(1).cardinality == 8000
    # crash-replay under the concurrent op-log interleaving
    g = Fragment(str(tmp_path / "0"), 0).open()
    assert g.row(1).cardinality == 8000


def test_parallel_holder_open(tmp_path):
    h = Holder(str(tmp_path)).open()
    for i in range(5):
        idx = h.create_index(f"idx{i}")
        idx.create_field("f")
        idx.set_bit("f", 1, i * 10)
    h.close()
    h2 = Holder(str(tmp_path)).open()  # concurrent index opens
    assert sorted(h2.indexes) == [f"idx{i}" for i in range(5)]
    ex = Executor(h2)
    for i in range(5):
        assert ex.execute(f"idx{i}", "Count(Row(f=1))") == [1]


def test_kill9_server_durability(tmp_path):
    """Full-process crash: start a real server, write over HTTP, SIGKILL
    it mid-life, restart on the same data dir — everything written and
    acknowledged must still be there (snapshot + op-log replay)."""
    import os
    import signal
    import subprocess
    import sys
    import time
    import urllib.request

    from pilosa_tpu.api.client import Client

    data = str(tmp_path / "data")
    # blank PALLAS_AXON_POOL_IPS makes the image's sitecustomize skip
    # axon TPU registration (see .claude/skills/verify/SKILL.md)
    env = dict(os.environ, PALLAS_AXON_POOL_IPS="", JAX_PLATFORMS="cpu")
    # ask the OS for a free port first
    import socket
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    proc = subprocess.Popen(
        [sys.executable, "-m", "pilosa_tpu.cli", "server",
         "--data-dir", data, "--bind", f"127.0.0.1:{port}"],
        env=env, stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
    try:
        cl = Client("127.0.0.1", port, timeout=5)
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            try:
                cl.version()
                break
            except Exception:
                time.sleep(0.2)
        else:
            raise TimeoutError("server did not come up")
        cl.create_index("i")
        cl.create_field("i", "f")
        cl.create_field("i", "n", {"type": "int", "min": 0, "max": 1000})
        cl.import_bits("i", "f", rowIDs=[1, 2, 3], columnIDs=[10, 20, 30])
        cl.query("i", "Set(40, f=1) Set(5, n=777)")
        assert cl.query("i", "Count(Row(f=1))") == [2]
    finally:
        proc.send_signal(signal.SIGKILL)
        proc.wait(timeout=10)

    # reopen the data dir in-process: acknowledged writes must survive
    from pilosa_tpu.exec import Executor
    from pilosa_tpu.store import Holder
    h = Holder(data).open()
    ex = Executor(h)
    assert ex.execute("i", "Count(Row(f=1))") == [2]
    (r,) = ex.execute("i", "Row(f=1)")
    assert list(r.columns) == [10, 40]
    (s_,) = ex.execute("i", "Sum(field=n)")
    assert (s_.value, s_.count) == (777, 1)


class XlaRuntimeError(Exception):
    """Shape of jax's device-OOM error (_is_device_oom matches on the
    type NAME + RESOURCE_EXHAUSTED in the message)."""


def _pressure_fixture(tmp_path):
    holder = Holder(str(tmp_path)).open()
    idx = holder.create_index("i")
    idx.create_field("f")
    idx.create_field("g")
    ex = Executor(holder)
    for r in range(1, 6):
        for c in range(10 * r):
            ex.execute("i", f"Set({c}, f={r})")
    for c in range(25):
        ex.execute("i", f"Set({c}, g=1)")
    return holder, ex


def test_oom_recovery_under_concurrency(tmp_path):
    """Concurrent queries each hitting a device OOM must ALL recover
    and answer exactly — no 5xx, no thrash (r5: the r4 evict-all retry
    ping-ponged under concurrent over-budget load and a second OOM
    escaped as 500)."""
    _, ex = _pressure_fixture(tmp_path)
    expected = ex.execute("i", "TopN(f, Row(g=1), n=3)")[0].pairs

    real_build = ex.planes._build_plane
    seen: set[int] = set()
    inject = threading.Lock()

    def flaky(field, view_name, shards):
        with inject:
            first = threading.get_ident() not in seen
            seen.add(threading.get_ident())
        if first:
            raise XlaRuntimeError("RESOURCE_EXHAUSTED: out of memory "
                                  "allocating plane")
        return real_build(field, view_name, shards)

    ex.planes.invalidate()
    ex.planes._build_plane = flaky
    results, errors = {}, []
    start = threading.Barrier(8)

    def worker(i):
        try:
            start.wait()
            results[i] = ex.execute("i", "TopN(f, Row(g=1), n=3)")[0].pairs
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    assert not errors, errors[:3]
    assert len(seen) >= 1  # at least one thread took the OOM path
    assert all(results[i] == expected for i in range(8))
    # the recovery must leave no in-flight bookkeeping behind
    assert ex._inflight == 0
    assert not ex.planes._leases


def test_oom_exclusive_stage_recovers(tmp_path):
    """A query whose stage-1 retry ALSO OOMs drains to exclusivity,
    drops all residency, and still answers (r4: the second OOM was a
    500)."""
    _, ex = _pressure_fixture(tmp_path)
    expected = ex.execute("i", "TopN(f, Row(g=1), n=3)")[0].pairs
    ex.planes.invalidate()

    real_build = ex.planes._build_plane
    fails = {"n": 2}

    def flaky(field, view_name, shards):
        if fails["n"] > 0:
            fails["n"] -= 1
            raise XlaRuntimeError("RESOURCE_EXHAUSTED: out of memory")
        return real_build(field, view_name, shards)

    ex.planes._build_plane = flaky
    got = ex.execute("i", "TopN(f, Row(g=1), n=3)")[0].pairs
    assert got == expected
    assert fails["n"] == 0
    assert ex._inflight == 0


def test_leased_planes_survive_unpinned_eviction(tmp_path):
    """Stage-1 eviction frees only planes NO in-flight query holds:
    evicting leased entries frees no HBM (live refs) and forces
    mid-flight rebuilds."""
    holder = Holder(str(tmp_path)).open()
    idx = holder.create_index("i")
    idx.create_field("f")
    ex = Executor(holder)
    ex.execute("i", "Set(1, f=1)")
    field = idx.field("f")
    cache = ex.planes

    cache.begin_query()
    try:
        cache.field_plane("i", field, "standard", (0,))
        assert cache.has_plane("i", field, "standard", (0,))
        cache.evict_unpinned()
        assert cache.has_plane("i", field, "standard", (0,)), \
            "leased plane must survive unpinned eviction"
    finally:
        cache.end_query()
    cache.evict_unpinned()
    assert not cache.has_plane("i", field, "standard", (0,))


def test_cross_request_count_batching(tmp_path):
    """Concurrent Counts through a batching executor coalesce into few
    programs with exact results."""
    import threading

    from pilosa_tpu.store import Holder
    from pilosa_tpu.exec import Executor

    holder = Holder(str(tmp_path)).open()
    idx = holder.create_index("i")
    idx.create_field("f")
    ex = Executor(holder, count_batch_window=0.01)
    for r in range(1, 9):
        for c in range(r):
            ex.execute("i", f"Set({c}, f={r})")

    results = {}
    start = threading.Barrier(8)

    def worker(r):
        start.wait()
        (cnt,) = ex.execute("i", f"Count(Row(f={r}))")
        results[r] = cnt

    threads = [threading.Thread(target=worker, args=(r,))
               for r in range(1, 9)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    assert results == {r: r for r in range(1, 9)}
    # coalesced: far fewer programs than counts (8 concurrent -> 1-2
    # batch programs; exact number depends on arrival timing)
    batch_programs = [k for k in ex.fused._programs
                     if k[1] == "count-batch"]
    assert 1 <= len(batch_programs) <= 4


def test_cross_request_bsi_aggregate_batching(tmp_path):
    """Concurrent Sum/Min/Max join the same batcher window as Counts
    (VERDICT r1: BSI paths must amortize the per-read floor too)."""
    import threading

    from pilosa_tpu.exec import Executor
    from pilosa_tpu.store import FieldOptions, Holder

    holder = Holder(str(tmp_path)).open()
    idx = holder.create_index("i")
    idx.create_field("f")
    idx.create_field("v", FieldOptions(type="int", min=-100, max=100))
    ex = Executor(holder, count_batch_window=0.01)
    vals = {1: -42, 2: 17, 3: 5, 4: 99}
    for c, v in vals.items():
        ex.execute("i", f"Set({c}, v={v})")
    ex.execute("i", "Set(2, f=1) Set(3, f=1)")

    results = {}
    start = threading.Barrier(8)

    def worker(i, pql):
        start.wait()
        (r,) = ex.execute("i", pql)
        results[i] = r

    cases = ["Sum(field=v)", "Min(field=v)", "Max(field=v)",
             "Sum(Row(f=1), field=v)", "Min(Row(f=1), field=v)",
             "Max(Row(f=1), field=v)", "Count(Row(f=1))",
             "Count(Row(v > 10))"]
    threads = [threading.Thread(target=worker, args=(i, p))
               for i, p in enumerate(cases)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    assert (results[0].value, results[0].count) == (sum(vals.values()), 4)
    assert (results[1].value, results[1].count) == (-42, 1)
    assert (results[2].value, results[2].count) == (99, 1)
    assert (results[3].value, results[3].count) == (22, 2)
    assert (results[4].value, results[4].count) == (5, 1)
    assert (results[5].value, results[5].count) == (17, 1)
    assert results[6] == 2
    assert results[7] == 2
    agg_programs = [k for k in ex.fused._programs
                    if isinstance(k[0], tuple)
                    and k[0][0] in ("sum-plane", "minmax-plane")]
    assert agg_programs, "aggregates must run through the batch programs"


def test_oom_matcher_catches_async_read_valueerror(tmp_path):
    """The axon backend surfaces an async execution's device OOM at the
    HOST READ as a plain ValueError carrying RESOURCE_EXHAUSTED (not
    XlaRuntimeError) — config14 r5: the typed matcher missed it and 32
    concurrent streams all answered 500 with zero recovery attempts."""
    _, ex = _pressure_fixture(tmp_path)
    expected = ex.execute("i", "TopN(f, Row(g=1), n=3)")[0].pairs

    real_build = ex.planes._build_plane
    hits = []

    def flaky(field, view_name, shards):
        if not hits:
            hits.append(1)
            raise ValueError(
                "RESOURCE_EXHAUSTED: TPU backend error (ResourceExhausted).")
        return real_build(field, view_name, shards)

    ex.planes.invalidate()
    ex.planes._build_plane = flaky
    got = ex.execute("i", "TopN(f, Row(g=1), n=3)")[0].pairs
    assert got == expected and hits


def test_bounded_concurrency_queues_excess_queries(tmp_path):
    """max_concurrent admission: with 2 slots and 6 clients, no more
    than 2 queries EXECUTE at once; all 6 answer exactly."""
    from pilosa_tpu.exec import Executor
    from pilosa_tpu.store import Holder

    holder = Holder(str(tmp_path)).open()
    idx = holder.create_index("i")
    idx.create_field("f")
    ex = Executor(holder, max_concurrent=2)
    for c in range(50):
        ex.execute("i", f"Set({c}, f={c % 3})")
    want = ex.execute("i", "Count(Row(f=1))")[0]

    active = [0]
    peak = [0]
    gate = threading.Lock()
    real = ex._execute_calls

    def spy(*a, **kw):
        with gate:
            active[0] += 1
            peak[0] = max(peak[0], active[0])
        try:
            time.sleep(0.05)
            return real(*a, **kw)
        finally:
            with gate:
                active[0] -= 1

    ex._execute_calls = spy
    errors, results = [], []

    def worker():
        try:
            results.append(ex.execute("i", "Count(Row(f=1))")[0])
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    threads = [threading.Thread(target=worker) for _ in range(6)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    assert not errors, errors[:2]
    assert results == [want] * 6
    assert peak[0] <= 2, f"peak concurrent executions {peak[0]}"


def test_admission_slot_survives_setup_failure(tmp_path):
    """ADVICE r5: the admission semaphore used to leak its slot when
    begin_query() raised after acquisition — max_concurrent such
    failures turned into a permanent 180s-timeout outage.  Force the
    failure max_concurrent times; queries must still admit."""
    from pilosa_tpu.exec import Executor
    from pilosa_tpu.store import Holder

    holder = Holder(str(tmp_path)).open()
    idx = holder.create_index("i")
    idx.create_field("f")
    ex = Executor(holder, max_concurrent=2)
    for c in range(10):
        ex.execute("i", f"Set({c}, f=1)")

    real = ex.planes.begin_query
    failures = [0]

    def flaky():
        if failures[0] < 2:  # == max_concurrent
            failures[0] += 1
            raise RuntimeError("injected begin_query failure")
        return real()

    ex.planes.begin_query = flaky
    for _ in range(2):
        with pytest.raises(RuntimeError):
            ex.execute("i", "Count(Row(f=1))")
    # both slots must have been released: this admits immediately
    # (a leak would park it behind the 180s acquire timeout)
    assert ex.execute("i", "Count(Row(f=1))")[0] == 10
    assert failures[0] == 2


def test_adaptive_batcher_default_on_no_solo_window(tmp_path):
    """The batcher is the default serving spine with an ADAPTIVE
    window: solo traffic must never wait out a collection window (the
    window stays 0), and sequential queries answer exactly."""
    from pilosa_tpu.exec import Executor
    from pilosa_tpu.store import Holder

    holder = Holder(str(tmp_path)).open()
    idx = holder.create_index("i")
    idx.create_field("f")
    ex = Executor(holder)  # default: count_batch_window="adaptive"
    assert ex.batcher is not None and ex.batcher.adaptive
    for c in range(7):
        ex.execute("i", f"Set({c}, f=1)")
    t0 = time.perf_counter()
    for _ in range(10):
        assert ex.execute("i", "Count(Row(f=1))")[0] == 7
    solo = (time.perf_counter() - t0) / 10
    # the window never opened for solo traffic…
    assert ex.batcher.current_window == 0.0
    # …and per-query latency is nowhere near the max window (50ms is
    # generous vs ADAPT_MAX=5ms: a regression that waits the window
    # per solo query would trip this on any CI box)
    assert solo < 0.05, f"solo count took {solo * 1e3:.1f} ms"


def test_adaptive_batcher_window_grows_and_decays(tmp_path):
    """Under queue pressure the window opens (requests coalesce into
    shared batches); once traffic is solo again it decays back to 0."""
    from pilosa_tpu.obs import Stats
    from pilosa_tpu.exec import Executor
    from pilosa_tpu.store import Holder

    holder = Holder(str(tmp_path)).open()
    idx = holder.create_index("i")
    idx.create_field("f")
    stats = Stats()
    ex = Executor(holder, stats=stats)
    for r in range(1, 9):
        for c in range(r):
            ex.execute("i", f"Set({c}, f={r})")

    coalesced = False
    for _ in range(3):  # retry: arrival overlap is scheduler-dependent
        start = threading.Barrier(8)
        errors = []

        def worker(r):
            try:
                start.wait()
                for _ in range(4):
                    assert ex.execute("i", f"Count(Row(f={r}))")[0] == r
            except Exception as e:  # noqa: BLE001
                errors.append(e)

        threads = [threading.Thread(target=worker, args=(r,))
                   for r in range(1, 9)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        assert not errors, errors[:2]
        counters = stats.snapshot()["counters"]
        items = sum(counters.get("batcher_items", {}).values())
        batches = sum(counters.get("batcher_batches", {}).values())
        if items > batches:
            coalesced = True
            break
    assert coalesced, "concurrent counts never coalesced"
    # solo traffic decays the window back to zero
    for _ in range(12):
        assert ex.execute("i", "Count(Row(f=3))")[0] == 3
    assert ex.batcher.current_window == 0.0


def test_topn_and_distinct_coalesce(tmp_path):
    """The remaining one-dispatch-one-read families ride the batcher:
    concurrent dense TopN shares a rowcounts program (identical planes
    dedupe), Distinct shares a presence scan — all answers exact."""
    from pilosa_tpu.exec import Executor
    from pilosa_tpu.store import FieldOptions, Holder

    holder = Holder(str(tmp_path)).open()
    idx = holder.create_index("i")
    idx.create_field("f")
    idx.create_field("v", FieldOptions(type="int", min=0, max=200))
    ex = Executor(holder)
    for r in range(1, 5):
        for c in range(r * 3):
            ex.execute("i", f"Set({c}, f={r})")
    for c in range(12):
        ex.execute("i", f"Set({c}, v={(c % 3) * 7})")

    want_topn = ex.execute("i", "TopN(f, n=4)")[0].pairs
    want_distinct = ex.execute("i", "Distinct(field=v)")[0].values
    assert want_distinct == [0, 7, 14]

    errors = []
    start = threading.Barrier(8)

    def worker(i):
        try:
            start.wait()
            for _ in range(3):
                if i % 2:
                    got = ex.execute("i", "TopN(f, n=4)")[0].pairs
                    assert got == want_topn
                else:
                    got = ex.execute("i", "Distinct(field=v)")[0].values
                    assert got == want_distinct
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    threads = [threading.Thread(target=worker, args=(i,))
               for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    assert not errors, errors[:3]
    # the dense TopN counts ran through the batched rowcounts program
    assert any(isinstance(k, tuple) and k[-1] == "rowcounts-batch"
               for k in ex.fused._programs), \
        "TopN never used the coalesced rowcounts program"
