"""Durable hinted handoff, exhaustively (ISSUE 8 satellite): the hint
log's crash recovery at EVERY record boundary and at mid-record
offsets — driven through the ``hints.append`` record-relative failpoint
and the shared ``sys.write`` seam, the same sites the chaos harness
tears on live nodes — plus the receiver-side op-id dedup window that
makes replay delivery idempotent (a re-sent batch must be a no-op, or
a replayed Clear could land after a newer direct Set and destroy it).
"""

import os

import pytest

from pilosa_tpu import fault
from pilosa_tpu.cluster.hints import HintBoard, HintLog
from pilosa_tpu.store.oplog import IdWindow


@pytest.fixture(autouse=True)
def _clean_registry():
    fault.clear()
    yield
    fault.clear()


def _payload(i: int) -> dict:
    return {"id": f"{i:032x}", "index": "i", "pql": f"Set({i}, f=0)",
            "op": "Set", "field": "f", "shards": [i % 3]}


PAYLOADS = [_payload(i) for i in range(4)]


def _record_bytes(seq: int, payload: dict) -> bytes:
    """One CRC-framed record exactly as HintLog.append lays it out."""
    import json
    import struct
    import time
    import zlib
    pb = json.dumps(payload, separators=(",", ":")).encode()
    body = struct.pack("<QdI", seq, time.time(), len(pb)) + pb
    return struct.pack("<I", zlib.crc32(body)) + body


def _write_torn_log(path: str, n_full: int, torn_offset: int) -> None:
    """A log holding PAYLOADS[:n_full] intact plus ``torn_offset``
    raw bytes of PAYLOADS[n_full]'s record — the on-disk state a
    coordinator crashed MID-APPEND leaves behind.  (Written directly:
    a failed append in a SURVIVING process truncates its own tear —
    see test_failed_append_truncates_tear — so only a real crash can
    leave these bytes.)"""
    log = HintLog(path)
    for p in PAYLOADS[:n_full]:
        log.append(p)
    log.close()
    with open(path, "ab") as f:
        f.write(_record_bytes(n_full + 1, PAYLOADS[n_full])[:torn_offset])


def _assert_clean_prefix(path: str, n_full: int) -> None:
    log = HintLog(path)
    assert [p for _s, _t, p in log.records] == PAYLOADS[:n_full], (
        f"recovery did not yield the clean {n_full}-record prefix")
    # recovery physically truncated the torn tail: appending again
    # yields a parseable log with exactly n_full + 1 records
    log.append({"id": "aa" * 16, "index": "i", "pql": "Set(9, f=0)",
                "op": "Set", "field": "f", "shards": [0]})
    log.close()
    re = HintLog(path)
    assert len(re.records) == n_full + 1
    assert re.records[-1][2]["pql"] == "Set(9, f=0)"
    re.close()
    os.remove(path)


class TestHintLogTornRecovery:
    """The crash-safety proof: a tear at any byte offset recovers to a
    replayable-or-cleanly-truncated log."""

    def test_torn_at_every_record_boundary(self, tmp_path):
        # offset 0 = crash BETWEEN records: the boundary case at every
        # prefix length, zero records through all of them
        for n_full in range(len(PAYLOADS)):
            path = str(tmp_path / f"b{n_full}.hints")
            _write_torn_log(path, n_full, torn_offset=0)
            _assert_clean_prefix(path, n_full)

    def test_torn_at_mid_record_offsets(self, tmp_path):
        # tears inside the 24-byte frame header and into the JSON
        # payload — every class must truncate cleanly
        for n_full in (0, 2):
            for offset in (1, 4, 12, 23, 24, 30, 60):
                path = str(tmp_path / f"m{n_full}_{offset}.hints")
                _write_torn_log(path, n_full, torn_offset=offset)
                _assert_clean_prefix(path, n_full)

    def test_truncated_at_every_byte(self, tmp_path):
        """Brute force: a log cut at EVERY byte offset recovers exactly
        the whole records that fit — no parse error, no phantom op."""
        full = str(tmp_path / "full.hints")
        log = HintLog(full)
        ends = []
        for p in PAYLOADS:
            log.append(p)
            ends.append(os.path.getsize(full))
        log.close()
        blob = open(full, "rb").read()
        for cut in range(len(blob) + 1):
            path = str(tmp_path / "cut.hints")
            with open(path, "wb") as f:
                f.write(blob[:cut])
            want = sum(1 for e in ends if e <= cut)
            re = HintLog(path)
            assert [p for _s, _t, p in re.records] == PAYLOADS[:want], (
                f"cut at byte {cut}: want prefix {want}")
            re.close()
            os.remove(path)

    def test_torn_via_sys_write_seam(self, tmp_path):
        """The shared ``sys.write`` failpoint tears hint appends too
        (chaos schedules that tear every durable writer at once)."""
        path = str(tmp_path / "sys.hints")
        log = HintLog(path)
        log.append(PAYLOADS[0])
        fault.set_fault("sys.write", "torn_write", nth=1,
                        args={"offset": 7})
        with pytest.raises(fault.FaultError):
            log.append(PAYLOADS[1])
        log.close()
        fault.clear()
        re = HintLog(path)
        assert [p for _s, _t, p in re.records] == PAYLOADS[:1]
        re.close()

    def test_failed_append_truncates_tear(self, tmp_path):
        """Regression (r13 review): a FAILED append in a SURVIVING
        process must not leave torn bytes in the file.  The op
        correctly fails to the client, but the process keeps serving —
        a later GOOD append landing BEHIND leftover torn bytes would
        be silently discarded (along with every acked hint after it)
        by clean-prefix recovery at the next boot, losing acked
        Clears to AAE resurrection."""
        path = str(tmp_path / "survive.hints")
        log = HintLog(path)
        log.append(PAYLOADS[0])
        clean = os.path.getsize(path)
        fault.set_fault("hints.append", "torn_write", nth=1,
                        args={"offset": 9})
        with pytest.raises(fault.FaultError):
            log.append(PAYLOADS[1])
        fault.clear()
        assert os.path.getsize(path) == clean  # tear truncated away
        # the next hint ACKS and SURVIVES a reboot
        assert log.append(PAYLOADS[2]) == 2
        log.close()
        re = HintLog(path)
        assert [p for _s, _t, p in re.records] == [PAYLOADS[0],
                                                   PAYLOADS[2]]
        re.close()

    def test_seq_monotonic_across_reopen(self, tmp_path):
        path = str(tmp_path / "seq.hints")
        log = HintLog(path)
        assert [log.append(p) for p in PAYLOADS[:3]] == [1, 2, 3]
        log.close()
        re = HintLog(path)
        assert re.append(PAYLOADS[3]) == 4
        re.close()


class TestHintBoard:
    def _board(self, tmp_path, **kw) -> HintBoard:
        return HintBoard(str(tmp_path / "_hints"), **kw)

    def test_add_ack_compacts_and_survives_reboot(self, tmp_path):
        b = self._board(tmp_path)
        for p in PAYLOADS:
            b.add("peer:1", p)
        assert b.pending_ops("peer:1") == 4
        assert b.pending_peers() == {"peer:1"}
        # ack through seq 2: the file compacts to the surviving suffix
        assert b.ack("peer:1", 2) == 2
        assert [p for _s, p in b.peek("peer:1", 10)] == PAYLOADS[2:]
        b.close()
        # boot recovery reloads the surviving log
        rb = self._board(tmp_path)
        assert rb.pending_ops("peer:1") == 2
        assert [p for _s, p in rb.peek("peer:1", 10)] == PAYLOADS[2:]
        # draining to empty drops the peer from the pending set
        rb.ack("peer:1", 10 ** 9)
        assert rb.pending_peers() == set()
        assert not rb.has_pending("peer:1")
        rb.close()

    def test_overflow_flips_after_max_age(self, tmp_path):
        import time

        b = self._board(tmp_path, max_age=0.05)
        b.add("peer:1", PAYLOADS[0])
        assert not b.overflowed("peer:1")
        time.sleep(0.08)
        assert b.overflowed("peer:1")
        assert b.summary()["peers"][0]["overflowed"] is True
        # never-hinted peers are not overflowed
        assert not b.overflowed("peer:2")
        b.close()

    def test_gated_fragment_covers_hinted_shards(self, tmp_path):
        b = self._board(tmp_path)
        b.add("peer:1", {"id": "00" * 16, "index": "i", "op": "Clear",
                         "pql": "Clear(1, f=0)", "field": "f",
                         "shards": [1, 2]})
        assert b.gated_fragment("i", "f", 1)
        assert b.gated_fragment("i", "f", 2)
        assert not b.gated_fragment("i", "f", 3)
        assert not b.gated_fragment("i", "g", 1)
        assert not b.gated_fragment("j", "f", 1)
        # shards=None (ClearRow-wide hint) gates every shard; a hint
        # with no field gates every field — conservative, never unsound
        b.add("peer:1", {"id": "01" * 16, "index": "j", "op": "Store",
                         "pql": "Store(Row(f=0), f=1)", "field": None,
                         "shards": None})
        assert b.gated_fragment("j", "anything", 7)
        # ack-compaction un-gates: the coverage summary must track
        # removals, not just appends
        b.ack("peer:1", 2)
        assert not b.gated_fragment("i", "f", 1)
        assert not b.gated_fragment("j", "anything", 7)
        b.close()

    def test_peer_filename_roundtrip_odd_ids(self, tmp_path):
        b = self._board(tmp_path)
        odd = "10.0.0.1:10101"
        b.add(odd, PAYLOADS[0])
        b.close()
        rb = self._board(tmp_path)
        assert rb.pending_peers() == {odd}
        rb.close()


class TestIdWindow:
    def test_dedup_and_persistence(self, tmp_path):
        path = str(tmp_path / "ids.log")
        w = IdWindow(path)
        assert w.add("a" * 32) is True
        assert w.add("a" * 32) is False  # dup
        assert w.add("b" * 32) is True
        assert "a" * 32 in w and "b" * 32 in w and "c" * 32 not in w
        w.close()
        rw = IdWindow(path)
        assert "a" * 32 in rw and "b" * 32 in rw
        assert rw.add("a" * 32) is False  # dedup survives reboot
        rw.close()

    def test_truncated_at_every_byte(self, tmp_path):
        full = str(tmp_path / "full.log")
        w = IdWindow(full)
        ids = [f"{i:032x}" for i in range(3)]
        ends = []
        for i in ids:
            w.add(i)
            ends.append(os.path.getsize(full))
        w.close()
        blob = open(full, "rb").read()
        for cut in range(len(blob) + 1):
            path = str(tmp_path / "cut.log")
            with open(path, "wb") as f:
                f.write(blob[:cut])
            want = sum(1 for e in ends if e <= cut)
            rw = IdWindow(path)
            assert len(rw) == want, f"cut at byte {cut}"
            assert all(i in rw for i in ids[:want])
            rw.close()
            os.remove(path)

    def test_compaction_keeps_newest_cap(self, tmp_path):
        path = str(tmp_path / "cap.log")
        w = IdWindow(path, cap=4)
        for i in range(12):  # > 2 * cap forces compaction
            w.add(f"{i:032x}")
        assert len(w) == 4
        assert f"{11:032x}" in w and f"{0:032x}" not in w
        w.close()
        rw = IdWindow(path, cap=4)
        assert len(rw) == 4
        assert f"{11:032x}" in rw
        rw.close()


class TestReplayEndpointIdempotent:
    """Duplicate replay delivery through the real endpoint is a no-op
    (op-id dedup pinned) — and a replayed Clear can never undo a Set
    it was already delivered before."""

    def test_double_replay_is_noop(self, tmp_path):
        from pilosa_tpu.testing import run_cluster

        with run_cluster(2, str(tmp_path), replicas=2,
                         heartbeat=0.1) as c:
            c.client(0).create_index("i")
            c.client(0).create_field("i", "f")
            ops = [
                {"id": "11" * 16, "index": "i", "op": "Set",
                 "pql": "Set(3, f=1)", "field": "f", "shards": [0]},
                {"id": "22" * 16, "index": "i", "op": "Clear",
                 "pql": "Clear(4, f=1)", "field": "f", "shards": [0]},
            ]
            first = c.client(0)._json("POST", "/internal/hints/replay",
                                      {"ops": ops})
            assert first == {"applied": 2, "deduped": 0, "dropped": 0}
            # the bit landed; now the cluster moves ON: a newer direct
            # write clears it
            c.client(0).query("i", "Clear(3, f=1)")
            # a duplicate batch delivery (lost ack, sender crash
            # mid-compaction) must dedup — NOT re-set the cleared bit
            second = c.client(0)._json("POST", "/internal/hints/replay",
                                       {"ops": ops})
            assert second == {"applied": 0, "deduped": 2, "dropped": 0}
            (got,) = c.client(0).query("i", "Row(f=1)")
            assert 3 not in got["columns"]

    def test_unreplayable_op_dropped_not_wedged(self, tmp_path):
        from pilosa_tpu.testing import run_cluster

        with run_cluster(2, str(tmp_path), replicas=2,
                         heartbeat=0.1) as c:
            ops = [{"id": "33" * 16, "index": "gone", "op": "Set",
                    "pql": "Set(1, f=0)", "field": "f", "shards": [0]}]
            out = c.client(0)._json("POST", "/internal/hints/replay",
                                    {"ops": ops})
            assert out["dropped"] == 1
            # the drop is remembered: redelivery dedups instead of
            # re-warning forever
            out2 = c.client(0)._json("POST", "/internal/hints/replay",
                                     {"ops": ops})
            assert out2 == {"applied": 0, "deduped": 1, "dropped": 0}

    def test_replay_defers_until_schema_settled(self, tmp_path):
        """Regression (r13 review): a drain racing a rejoiner's
        boot-time schema pull must not permanently drop an acked op
        for an index the receiver simply hasn't learned yet — inside
        the boot window a missing index answers 503 (the sender's
        drain retries next heartbeat) and the op is NOT consumed; a
        tombstoned deletion still drops even inside the window."""
        from pilosa_tpu.api.client import ClientError
        from pilosa_tpu.testing import run_cluster

        with run_cluster(2, str(tmp_path), replicas=2,
                         heartbeat=0.1) as c:
            ops = [{"id": "44" * 16, "index": "late", "op": "Set",
                    "pql": "Set(1, f=0)", "field": "f", "shards": [0]}]
            cl0 = c.servers[0].cluster
            cl0._schema_ready.clear()  # re-enter the boot window
            try:
                with pytest.raises(ClientError) as ei:
                    c.client(0)._json("POST", "/internal/hints/replay",
                                      {"ops": ops})
                assert ei.value.status == 503
            finally:
                cl0._schema_ready.set()
            # the deferred op was not consumed: once the schema lands
            # the very same batch applies
            c.client(0).create_index("late")
            c.client(0).create_field("late", "f")
            out = c.client(0)._json("POST", "/internal/hints/replay",
                                    {"ops": ops})
            assert out == {"applied": 1, "deduped": 0, "dropped": 0}
            # a recorded deletion is judged deleted even mid-boot
            c.client(0).delete_index("late")
            cl0._schema_ready.clear()
            try:
                ops2 = [{"id": "55" * 16, "index": "late",
                         "op": "Set", "pql": "Set(2, f=0)",
                         "field": "f", "shards": [0]}]
                out2 = c.client(0)._json(
                    "POST", "/internal/hints/replay", {"ops": ops2})
                assert out2["dropped"] == 1
            finally:
                cl0._schema_ready.set()
