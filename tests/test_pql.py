"""PQL parser tests (reference test model: ``pql/pql_test.go`` grammar +
error cases; SURVEY.md §5)."""

import pytest

from pilosa_tpu import pql
from pilosa_tpu.pql.ast import Condition


def parse1(src):
    q = pql.parse(src)
    assert len(q.calls) == 1
    return q.calls[0]


class TestBasicCalls:
    def test_row(self):
        c = parse1("Row(f=1)")
        assert c.name == "Row"
        assert c.args == {"f": 1}
        assert c.children == []

    def test_row_string_key(self):
        c = parse1('Row(f="foo")')
        assert c.args == {"f": "foo"}

    def test_single_quotes(self):
        c = parse1("Row(f='foo')")
        assert c.args == {"f": "foo"}

    def test_nested(self):
        c = parse1("Count(Intersect(Row(a=1), Row(b=2)))")
        assert c.name == "Count"
        inner = c.children[0]
        assert inner.name == "Intersect"
        assert [ch.name for ch in inner.children] == ["Row", "Row"]
        assert inner.children[0].args == {"a": 1}
        assert inner.children[1].args == {"b": 2}

    def test_multiple_toplevel_calls(self):
        q = pql.parse("Row(f=1) Count(Row(f=2))")
        assert [c.name for c in q.calls] == ["Row", "Count"]

    def test_all_no_args(self):
        c = parse1("All()")
        assert c.name == "All"
        assert c.args == {} and c.children == []

    def test_mixed_children_and_args(self):
        c = parse1("TopN(f, Row(other=5), n=10)")
        assert c.args["_field"] == "f"
        assert c.args["n"] == 10
        assert c.children[0].name == "Row"

    def test_bool_null_values(self):
        c = parse1("Options(Row(f=1), excludeColumns=true, x=null, y=false)")
        assert c.args == {"excludeColumns": True, "x": None, "y": False}

    def test_list_value(self):
        c = parse1("Options(Row(f=1), shards=[0, 2, 4])")
        assert c.args["shards"] == [0, 2, 4]

    def test_list_of_strings(self):
        c = parse1('Rows(f, in=["a", "b"])')
        assert c.args["in"] == ["a", "b"]

    def test_bareword_value_is_string(self):
        c = parse1("Sum(field=amount)")
        assert c.args == {"field": "amount"}

    def test_negative_and_float(self):
        calls = pql.parse("Row(f=-3) Row(g=1.5)").calls
        assert calls[0].args == {"f": -3}
        assert calls[1].args == {"g": 1.5}

    def test_dashed_field_name(self):
        c = parse1("Row(my-field=1)")
        assert c.args == {"my-field": 1}


class TestPositionalRewrites:
    def test_set(self):
        c = parse1("Set(10, f=1)")
        assert c.args == {"_col": 10, "f": 1}

    def test_set_with_timestamp(self):
        c = parse1("Set(10, f=1, 2017-01-02T03:04)")
        assert c.args == {"_col": 10, "f": 1, "_timestamp": "2017-01-02T03:04"}

    def test_set_string_col_key(self):
        c = parse1('Set("col-key", f="row-key")')
        assert c.args == {"_col": "col-key", "f": "row-key"}

    def test_clear(self):
        c = parse1("Clear(7, f=2)")
        assert c.args == {"_col": 7, "f": 2}

    def test_topn_field(self):
        c = parse1("TopN(f, n=25)")
        assert c.args == {"_field": "f", "n": 25}

    def test_rows_field(self):
        c = parse1("Rows(f)")
        assert c.args == {"_field": "f"}

    def test_setrowattrs(self):
        c = parse1('SetRowAttrs(f, 10, color="red")')
        assert c.args == {"_field": "f", "_row": 10, "color": "red"}

    def test_setcolumnattrs(self):
        c = parse1("SetColumnAttrs(10, active=true)")
        assert c.args == {"_col": 10, "active": True}

    def test_row_time_range(self):
        c = parse1("Row(f=1, from='2010-01-01T00:00', to='2012-01-01T00:00')")
        assert c.args["from"] == "2010-01-01T00:00"
        assert c.args["to"] == "2012-01-01T00:00"

    def test_bare_timestamp_value(self):
        c = parse1("Row(f=1, from=2010-01-01T00:00)")
        assert c.args["from"] == "2010-01-01T00:00"


class TestConditions:
    @pytest.mark.parametrize("op", ["==", "!=", "<", "<=", ">", ">="])
    def test_scalar_ops(self, op):
        c = parse1(f"Row(amount {op} 5)")
        assert c.args["amount"] == Condition(op, 5)

    def test_negative_predicate(self):
        c = parse1("Row(amount > -10)")
        assert c.args["amount"] == Condition(">", -10)

    def test_between_strict(self):
        c = parse1("Row(5 < amount < 10)")
        assert c.args["amount"] == Condition("<><", [5, 10])

    def test_between_inclusive(self):
        c = parse1("Row(5 <= amount <= 10)")
        assert c.args["amount"] == Condition("<=><=", [5, 10])

    def test_between_mixed(self):
        c = parse1("Row(5 <= amount < 10)")
        assert c.args["amount"] == Condition("<=><", [5, 10])

    def test_left_bound_only_flips(self):
        c = parse1("Row(5 < amount)")
        assert c.args["amount"] == Condition(">", 5)

    def test_condition_ne_null(self):
        c = parse1("Row(amount != null)")
        assert c.args["amount"] == Condition("!=", None)

    def test_condition_in_count(self):
        c = parse1("Count(Row(amount >= 100))")
        assert c.children[0].args["amount"] == Condition(">=", 100)


class TestCallValuedArgs:
    def test_groupby_filter(self):
        c = parse1("GroupBy(Rows(a), Rows(b), filter=Row(x=1), limit=10)")
        assert [ch.name for ch in c.children] == ["Rows", "Rows"]
        filt = c.args["filter"]
        assert filt.name == "Row" and filt.args == {"x": 1}
        assert c.args["limit"] == 10


class TestErrors:
    @pytest.mark.parametrize("src", [
        "",
        "Row(",
        "Row)",
        "Row(f=)",
        "Row(f=1",
        "Row(f==)",
        "Set(10, 20, f=1)",          # too many positionals
        "TopN(f, g)",                 # two barewords
        "Row(f=1, f=2)",              # duplicate key
        "Row(amount > 5, amount < 3)",  # duplicate condition
        "Row(5 > amount > 3)",        # bad between ops
        'Row(f="unterminated)',
        "Row(f=1) garbage(",
    ])
    def test_raises(self, src):
        with pytest.raises(pql.ParseError):
            pql.parse(src)

    def test_roundtrip_str(self):
        src = "Count(Intersect(Row(a=1), Row(b=2)))"
        c = parse1(src)
        assert pql.parse(str(c)).calls[0] == c


class TestPqlRoundTrip:
    """str(parse(s)) must re-parse to an identical AST — sub-queries are
    shipped to peer nodes as PQL text."""

    CASES = [
        "Row(f=1)",
        'Row(f="key with \\"quotes\\"")',
        "Count(Intersect(Row(f=1), Row(g=2)))",
        "Set(10, f=1, 2017-01-02T03:04)",
        "Clear(10, f=1)",
        "TopN(f, n=5, filter=Row(g=1))",
        "TopN(f, ids=[1, 2, 3])",
        "Row(amount > 5)",
        "Row(amount <= -3)",
        "Row(0 < amount < 100)",
        "Row(5 <= amount <= 10)",
        "Row(t=1, from=2017-01-01T00:00, to=2018-01-01T00:00)",
        "Rows(f, limit=10, previous=3)",
        "GroupBy(Rows(a), Rows(b), filter=Row(x=1), limit=7)",
        "Store(Row(f=1), g=7)",
        "Sum(Row(f=1), field=amount)",
        "Options(Row(f=1), shards=[0, 2])",
        "Row(b=true) Row(c=false)",
    ]

    def test_round_trip(self):
        from pilosa_tpu.pql import parse
        for src in self.CASES:
            q1 = parse(src)
            q2 = parse(str(q1))
            assert q1 == q2, f"{src!r} -> {str(q1)!r}"
