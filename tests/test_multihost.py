"""Multi-host execution: TWO controller processes joined via
``jax.distributed`` (DCN analogue; SURVEY.md §3.6) running one sharded
query program over the union of their devices.

The reference scales across hosts with memberlist gossip + HTTP fan-out;
the rebuild's host-level cluster does that part (tests/test_cluster.py).
THIS test exercises the other axis — one *pod slice* spanning hosts,
where every process joins a single JAX runtime and collectives ride
ICI/DCN — through the real server config path
(``Config.jax_coordinator`` → ``PilosaTPUServer.open``).

Runs on CPU: each child forces 4 virtual CPU devices, so the global
mesh has 8 devices across 2 processes.
"""

import os
import socket
import subprocess
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

CHILD = r"""
import sys
pid, coord, data_dir = int(sys.argv[1]), sys.argv[2], sys.argv[3]

from pilosa_tpu.cli.config import Config
from pilosa_tpu.server import PilosaTPUServer

cfg = Config(bind="127.0.0.1:0", data_dir=data_dir,
             jax_coordinator=coord, jax_num_processes=2,
             jax_process_id=pid, mesh=False,
             anti_entropy_interval=0.0)
srv = PilosaTPUServer(cfg).open()
try:
    import jax
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    assert jax.process_count() == 2, jax.process_count()
    assert jax.local_device_count() == 4
    assert jax.device_count() == 8

    from pilosa_tpu.parallel import spmd

    # one query program over the union of both processes' devices:
    # every process holds 4 of the 8 shard blocks
    rng = np.random.default_rng(0)  # same seed everywhere: shared oracle
    a = rng.integers(0, 1 << 32, size=(8, 256), dtype=np.uint32)
    b = rng.integers(0, 1 << 32, size=(8, 256), dtype=np.uint32)
    mesh = Mesh(np.array(jax.devices()), ("shard",))
    sh = NamedSharding(mesh, P("shard", None))
    lo = pid * 4
    da = jax.make_array_from_process_local_data(sh, a[lo:lo + 4])
    db = jax.make_array_from_process_local_data(sh, b[lo:lo + 4])
    got = int(spmd.make_intersect_count_psum(mesh)(da, db))
    expect = int(np.unpackbits((a & b).view(np.uint8)).sum())
    assert got == expect, (got, expect)
    print(f"MULTIHOST_OK {pid} {got}", flush=True)
finally:
    srv.close()
"""


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_two_process_jax_distributed(tmp_path):
    coord = f"127.0.0.1:{_free_port()}"
    env = {k: v for k, v in os.environ.items()
           if not k.startswith(("JAX_", "XLA_", "TPU_", "LIBTPU"))}
    env.update(JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="",
               XLA_FLAGS="--xla_force_host_platform_device_count=4",
               PYTHONPATH=ROOT)
    procs = []
    for pid in range(2):
        data = tmp_path / f"n{pid}"
        data.mkdir()
        procs.append(subprocess.Popen(
            [sys.executable, "-c", CHILD, str(pid), coord, str(data)],
            env=env, cwd=ROOT, stdout=subprocess.PIPE,
            stderr=subprocess.PIPE, text=True))
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=240)
            outs.append((p.returncode, out, err))
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    counts = set()
    for rc, out, err in outs:
        assert rc == 0, f"child failed rc={rc}\nstdout:{out}\nstderr:{err}"
        line = [l for l in out.splitlines() if l.startswith("MULTIHOST_OK")]
        assert line, out
        counts.add(line[0].split()[2])
    assert len(counts) == 1  # both processes agree on the global count
