"""Multi-host execution: TWO controller processes joined via
``jax.distributed`` (DCN analogue; SURVEY.md §3.6) running one sharded
query program over the union of their devices.

The reference scales across hosts with memberlist gossip + HTTP fan-out;
the rebuild's host-level cluster does that part (tests/test_cluster.py).
THIS test exercises the other axis — one *pod slice* spanning hosts,
where every process joins a single JAX runtime and collectives ride
ICI/DCN — through the real server config path
(``Config.jax_coordinator`` → ``PilosaTPUServer.open``).

Runs on CPU: each child forces 4 virtual CPU devices, so the global
mesh has 8 devices across 2 processes.
"""

import os
import socket
import subprocess
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# shared between both child scripts: one psum program over the union
# of both processes' devices (every process holds 4 of the 8 shard
# blocks; same seed everywhere = shared oracle).  Defines psum_check()
# returning the verified global count.
PSUM_SNIPPET = r"""
def psum_check(pid, seed, width):
    import jax
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    from pilosa_tpu.parallel import spmd

    rng = np.random.default_rng(seed)
    a = rng.integers(0, 1 << 32, size=(8, width), dtype=np.uint32)
    b = rng.integers(0, 1 << 32, size=(8, width), dtype=np.uint32)
    mesh = Mesh(np.array(jax.devices()), ("shard",))
    sh = NamedSharding(mesh, P("shard", None))
    lo = pid * 4
    da = jax.make_array_from_process_local_data(sh, a[lo:lo + 4])
    db = jax.make_array_from_process_local_data(sh, b[lo:lo + 4])
    got = int(spmd.make_intersect_count_psum(mesh)(da, db))
    expect = int(np.unpackbits((a & b).view(np.uint8)).sum())
    assert got == expect, (got, expect)
    return got
"""

CHILD = PSUM_SNIPPET + r"""
import sys
pid, coord, data_dir = int(sys.argv[1]), sys.argv[2], sys.argv[3]

from pilosa_tpu.cli.config import Config
from pilosa_tpu.server import PilosaTPUServer

cfg = Config(bind="127.0.0.1:0", data_dir=data_dir,
             jax_coordinator=coord, jax_num_processes=2,
             jax_process_id=pid, mesh=False,
             anti_entropy_interval=0.0)
srv = PilosaTPUServer(cfg).open()
try:
    import jax

    assert jax.process_count() == 2, jax.process_count()
    assert jax.local_device_count() == 4
    assert jax.device_count() == 8

    got = psum_check(pid, seed=0, width=256)
    print(f"MULTIHOST_OK {pid} {got}", flush=True)
finally:
    srv.close()
"""


from pilosa_tpu.testing import free_ports as _free_ports


def _free_port() -> int:
    return _free_ports(1)[0]


# holder + cluster layers UNDER a multi-process jax runtime (VERDICT r3
# weak #6: the psum smoke alone left those layers unexercised): the two
# processes form a real HTTP cluster (schema broadcast, shard-routed
# writes, distributed query fan-out) while sharing one jax.distributed
# runtime whose mesh spans both processes' devices.
CHILD_CLUSTER = PSUM_SNIPPET + r"""
import os, sys, time
pid, coord, data_dir, p0, p1, barrier_dir = (
    int(sys.argv[1]), sys.argv[2], sys.argv[3], int(sys.argv[4]),
    int(sys.argv[5]), sys.argv[6])

from pilosa_tpu.cli.config import Config
from pilosa_tpu.server import PilosaTPUServer

cfg = Config(bind=f"127.0.0.1:{p0 if pid == 0 else p1}",
             data_dir=data_dir,
             jax_coordinator=coord, jax_num_processes=2,
             jax_process_id=pid, mesh=False,
             cluster_enabled=True,
             seeds=[] if pid == 0 else [f"127.0.0.1:{p0}"],
             # generous beats: two jax processes share ONE core here,
             # and a several-second XLA compile on a peer's main thread
             # starves its heartbeat loop past a tight suspect horizon
             heartbeat_interval=2.0, anti_entropy_interval=0.0)
srv = PilosaTPUServer(cfg).open()
try:
    import jax
    import numpy as np

    assert jax.process_count() == 2
    # psum FIRST, straight after jax.distributed init while both
    # processes are at the same point: the first collective builds the
    # Gloo context with a 30s rendezvous window, and running it after
    # the (single-core, wall-clock-heavy) cluster phase made the two
    # processes arrive far enough apart to flake the timeout
    got_c = psum_check(pid, seed=1, width=128)

    from pilosa_tpu.api.client import Client
    from pilosa_tpu.engine.words import SHARD_WIDTH

    me = Client("127.0.0.1", cfg.port)
    deadline = time.monotonic() + 60
    while time.monotonic() < deadline:
        nodes = me.status()["nodes"]
        if len([n for n in nodes if n["state"] == "NORMAL"]) == 2:
            break
        time.sleep(0.1)
    else:
        raise TimeoutError(f"membership never converged: {nodes}")

    cols = [1, SHARD_WIDTH + 2, 2 * SHARD_WIDTH + 3, 3 * SHARD_WIDTH + 4]
    if pid == 0:
        me.create_index("mi")
        me.create_field("mi", "f")
        # shard-routed writes cross the process boundary over HTTP
        me.query("mi", "".join(f"Set({c}, f=1)" for c in cols))
    want = [len(cols)]
    deadline = time.monotonic() + 60
    got = None
    last_err = None
    while time.monotonic() < deadline:
        try:
            got = me.query("mi", "Count(Row(f=1))")
            if got == want:
                break
        except Exception as e:  # schema may not have propagated yet
            last_err = e
        time.sleep(0.2)
    assert got == want, (got, want, repr(last_err))
    # exit barrier: this node's server must stay up until the PEER'S
    # checks pass too (the fast child exiting first tears down half
    # the cluster under the slow child's queries)
    open(os.path.join(barrier_dir, f"done-{pid}"), "w").close()
    other = os.path.join(barrier_dir, f"done-{1 - pid}")
    deadline = time.monotonic() + 120
    while not os.path.exists(other):
        if time.monotonic() > deadline:
            raise TimeoutError("peer never finished")
        time.sleep(0.1)
    print(f"MULTIHOST_CLUSTER_OK {pid} {got[0]} {got_c}", flush=True)
finally:
    srv.close()
"""


def test_cluster_layer_over_multiprocess_jax(tmp_path):
    cport, p0, p1 = _free_ports(3)
    coord = f"127.0.0.1:{cport}"
    env = {k: v for k, v in os.environ.items()
           if not k.startswith(("JAX_", "XLA_", "TPU_", "LIBTPU"))}
    env.update(JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="",
               XLA_FLAGS="--xla_force_host_platform_device_count=4",
               PYTHONPATH=ROOT)
    procs = []
    for pid in range(2):
        data = tmp_path / f"c{pid}"
        data.mkdir()
        procs.append(subprocess.Popen(
            [sys.executable, "-c", CHILD_CLUSTER, str(pid), coord,
             str(data), str(p0), str(p1), str(tmp_path)],
            env=env, cwd=ROOT, stdout=subprocess.PIPE,
            stderr=subprocess.PIPE, text=True))
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=300)
            outs.append((p.returncode, out, err))
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    seen = set()
    for rc, out, err in outs:
        assert rc == 0, f"child failed rc={rc}\nstdout:{out}\nstderr:{err}"
        line = [l for l in out.splitlines()
                if l.startswith("MULTIHOST_CLUSTER_OK")]
        assert line, out
        seen.add(tuple(line[0].split()[2:]))
    assert len(seen) == 1  # both processes agree on count and psum


def test_two_process_jax_distributed(tmp_path):
    coord = f"127.0.0.1:{_free_port()}"
    env = {k: v for k, v in os.environ.items()
           if not k.startswith(("JAX_", "XLA_", "TPU_", "LIBTPU"))}
    env.update(JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="",
               XLA_FLAGS="--xla_force_host_platform_device_count=4",
               PYTHONPATH=ROOT)
    procs = []
    for pid in range(2):
        data = tmp_path / f"n{pid}"
        data.mkdir()
        procs.append(subprocess.Popen(
            [sys.executable, "-c", CHILD, str(pid), coord, str(data)],
            env=env, cwd=ROOT, stdout=subprocess.PIPE,
            stderr=subprocess.PIPE, text=True))
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=240)
            outs.append((p.returncode, out, err))
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    counts = set()
    for rc, out, err in outs:
        assert rc == 0, f"child failed rc={rc}\nstdout:{out}\nstderr:{err}"
        line = [l for l in out.splitlines() if l.startswith("MULTIHOST_OK")]
        assert line, out
        counts.add(line[0].split()[2])
    assert len(counts) == 1  # both processes agree on the global count
