"""Property-based tests with hypothesis against numpy oracles.

The rebuild of the reference's ``testing/quick`` property tests and fuzz
corpora (``roaring/roaring_test.go``, ``pql/fuzz``; SURVEY.md §5): every
kernel checked against an independent numpy model, the codec against
round-trip identity (including the native C++ path when built), and the
fragment as a stateful system against a dict-of-sets model.
"""

import numpy as np
from hypothesis import given, settings, strategies as st
from hypothesis.stateful import RuleBasedStateMachine, initialize, rule

from pilosa_tpu.engine import bsi as bsik
from pilosa_tpu.engine import kernels
from pilosa_tpu.engine.words import pack_columns, unpack_columns
from pilosa_tpu.store import roaring

# small word counts keep cases fast; kernels are shape-polymorphic
N_WORDS = 64
N_BITS = N_WORDS * 32

positions64 = st.lists(st.integers(0, (1 << 48) - 1), max_size=300)
cols = st.lists(st.integers(0, N_BITS - 1), max_size=200)


def to_words(col_list) -> np.ndarray:
    return pack_columns(np.array(sorted(set(col_list)), np.uint64),
                        n_words=N_WORDS)


def to_set(col_list) -> set:
    return set(col_list)


class TestCodecProperties:
    @given(positions64)
    @settings(max_examples=200, deadline=None)
    def test_round_trip(self, pos):
        arr = np.array(sorted(set(pos)), np.uint64)
        out = roaring.deserialize(roaring.serialize(arr))
        np.testing.assert_array_equal(out, arr)

    @given(st.lists(st.integers(0, (1 << 32) - 1), max_size=300))
    @settings(max_examples=100, deadline=None)
    def test_standard32_round_trip(self, vals):
        arr = np.array(sorted(set(vals)), np.uint64)
        out = roaring.read_standard32(roaring.write_standard32(arr))
        np.testing.assert_array_equal(out, arr)

    @given(positions64)
    @settings(max_examples=100, deadline=None)
    def test_native_matches_python(self, pos):
        from pilosa_tpu.store import native
        if not native.available():
            return
        arr = np.array(sorted(set(pos)), np.uint64)
        import pilosa_tpu.store.roaring as r

        # python encoder, bypassing native dispatch
        keys, lows_per = r._group_by_high(arr, 16)
        import struct
        out = bytearray(struct.pack("<HHI", r.MAGIC, r.VERSION, len(keys)))
        payloads, meta = [], []
        for key, lows in zip(keys, lows_per):
            ctype, payload = r._best_container(lows)
            if ctype == r.TYPE_ARRAY:
                data = payload.astype("<u2").tobytes()
            elif ctype == r.TYPE_BITMAP:
                data = payload.astype("<u8").tobytes()
            else:
                starts, lasts = payload
                data = struct.pack("<H", len(starts)) + np.column_stack(
                    (starts, lasts)).astype("<u2").tobytes()
            payloads.append(data)
            meta.append((int(key), ctype, len(lows)))
        for key, ctype, card in meta:
            out += struct.pack("<QHH", key, ctype, card - 1)
        off = len(out) + 4 * len(keys)
        for data in payloads:
            out += struct.pack("<I", off)
            off += len(data)
        for data in payloads:
            out += data
        assert native.serialize(arr) == bytes(out)


class TestDirectoryProperties:
    """roaring.Directory (the lazy mmap view) must agree with full
    deserialization for every serializable bit set."""

    @given(st.lists(st.tuples(st.integers(0, 300),
                              st.integers(0, (1 << 20) - 1)),
                    max_size=300, unique=True))
    @settings(max_examples=100, deadline=None)
    def test_directory_vs_deserialize(self, bits):
        from pilosa_tpu.engine.words import SHARD_WIDTH
        positions = np.unique(np.array(
            [r * SHARD_WIDTH + c for r, c in bits], np.uint64))
        blob = roaring.serialize(positions)
        d = roaring.Directory(memoryview(blob))
        rows = {r for r, _ in bits}
        assert set(map(int, d.row_ids())) == rows
        ids, cards = d.row_cards()
        assert cards.sum() == len(positions)
        for r in rows:
            expect = sorted(c for rr, c in bits if rr == r)
            np.testing.assert_array_equal(d.expand_row(r), expect,
                                          err_msg=f"row {r}")
            assert d.row_cardinality(r) == len(expect)

    @given(st.lists(st.tuples(st.integers(0, 50),
                              st.integers(0, (1 << 20) - 1)),
                    min_size=1, max_size=100, unique=True),
           st.integers(1, 60))
    @settings(max_examples=50, deadline=None)
    def test_truncated_blob_rejected_at_open(self, bits, cut):
        from pilosa_tpu.engine.words import SHARD_WIDTH
        positions = np.unique(np.array(
            [r * SHARD_WIDTH + c for r, c in bits], np.uint64))
        blob = roaring.serialize(positions)
        cut = min(cut, len(blob) - 1)
        try:
            d = roaring.Directory(memoryview(blob[:len(blob) - cut]))
        except ValueError:
            return  # rejected at open: the desired outcome
        # a shorter prefix may still contain a complete, valid
        # directory whose containers all fit — then reads must not crash
        for r in d.row_ids():
            d.expand_row(int(r))


class TestSparseLayoutProperties:
    """engine/sparse.py gather+segment-sum vs a numpy set oracle."""

    bits = st.lists(
        st.tuples(st.integers(0, 200),          # row
                  st.integers(0, 3 * 4096)),    # column (small word range)
        min_size=0, max_size=400, unique=True)
    filt = st.lists(st.integers(0, 3 * 4096), max_size=200, unique=True)

    @given(bits, filt)
    @settings(max_examples=100, deadline=None)
    def test_sparse_counts_vs_oracle(self, bits, filt):
        from pilosa_tpu.engine import sparse as sparsek
        from pilosa_tpu.engine.words import WORDS_PER_SHARD

        rows = sorted({r for r, _ in bits})
        slot = {r: i for i, r in enumerate(rows)}
        n_rows = max(1, len(rows))
        order = sorted(bits, key=lambda rc: (slot[rc[0]], rc[1]))
        word_idx = np.array([c >> 5 for _, c in order], np.int32)
        mask = np.array([1 << (c & 31) for _, c in order], np.uint32)
        rowslot = np.array([slot[r] for r, _ in order], np.int32)
        # pad rows AND bits to uneven sizes: padding must contribute 0
        r_pad = n_rows + 3
        row_ptr = np.searchsorted(
            rowslot, np.arange(r_pad + 1, dtype=np.int64)).astype(np.int32)
        pad = 7
        word_idx = np.concatenate([word_idx, np.zeros(pad, np.int32)])
        mask = np.concatenate([mask, np.zeros(pad, np.uint32)])

        fw = np.zeros((1, WORDS_PER_SHARD), np.uint32)
        for c in filt:
            fw[0, c >> 5] |= np.uint32(1) << np.uint32(c & 31)

        counts = np.asarray(sparsek.sparse_row_counts(
            fw, word_idx, mask, row_ptr))
        assert counts.shape == (r_pad,)
        fset = set(filt)
        for r in rows:
            expect = len({c for rr, c in bits if rr == r} & fset)
            assert counts[slot[r]] == expect, f"row {r}"
        assert (counts[n_rows:] == 0).all()  # pad rows count 0
        vals, slots = sparsek.topn_sparse(fw, word_idx, mask, row_ptr,
                                          min(5, n_rows))
        order_np = np.argsort(-counts, kind="stable")[: min(5, n_rows)]
        assert list(np.asarray(vals)) == list(counts[order_np])


class TestKernelProperties:
    @given(cols, cols)
    @settings(max_examples=100, deadline=None)
    def test_boolean_algebra_vs_sets(self, a, b):
        wa, wb = to_words(a), to_words(b)
        sa, sb = to_set(a), to_set(b)
        cases = {
            kernels.intersect: sa & sb,
            kernels.union: sa | sb,
            kernels.difference: sa - sb,
            kernels.xor: sa ^ sb,
        }
        for fn, expect in cases.items():
            got = set(unpack_columns(np.asarray(fn(wa, wb))).tolist())
            assert got == expect, fn.__name__

    @given(cols, cols)
    @settings(max_examples=100, deadline=None)
    def test_counts(self, a, b):
        wa, wb = to_words(a), to_words(b)
        sa, sb = to_set(a), to_set(b)
        assert int(kernels.count(wa)) == len(sa)
        assert int(kernels.intersection_count(wa, wb)) == len(sa & sb)
        assert int(kernels.union_count(wa, wb)) == len(sa | sb)
        assert int(kernels.xor_count(wa, wb)) == len(sa ^ sb)

    @given(st.lists(cols, min_size=1, max_size=6),
           st.integers(1, 6))
    @settings(max_examples=50, deadline=None)
    def test_topn_matches_sorted_counts(self, rows, n):
        plane = np.stack([to_words(r) for r in rows])
        counts = np.asarray(kernels.row_counts(plane))
        expect = sorted(((len(set(r)), -i) for i, r in enumerate(rows)),
                        reverse=True)
        vals, idx = kernels.top_n(np.asarray(
            kernels.row_counts(plane)), n)
        vals = np.asarray(vals)
        k = min(n, len(rows))
        assert list(vals[:k]) == [e[0] for e in expect[:k]]
        np.testing.assert_array_equal(counts,
                                      [len(set(r)) for r in rows])


class TestBsiProperties:
    @given(st.lists(st.tuples(st.integers(0, N_BITS - 1),
                              st.integers(-(10**6), 10**6)),
                    max_size=100),
           st.integers(-(10**6), 10**6))
    @settings(max_examples=100, deadline=None)
    def test_range_cmp_vs_numpy(self, pairs, pred):
        # last write wins per column
        model = {}
        for c, v in pairs:
            model[c] = v
        if not model:
            return
        cs = np.array(sorted(model), np.uint64)
        vs = np.array([model[int(c)] for c in cs], np.int64)
        depth = max(1, int(np.abs(vs).max()).bit_length())
        from pilosa_tpu.engine.words import bsi_encode
        plane = bsi_encode(cs, vs, base=0, depth=depth, n_words=N_WORDS)
        bound = (1 << depth) - 1
        if abs(pred) > bound:
            return  # saturation handled at executor level
        masks = bsik.predicate_masks(abs(pred), depth)
        out = bsik.range_cmp(plane, np.asarray(masks),
                             np.asarray(pred < 0))
        ops = {"lt": np.less, "le": np.less_equal, "gt": np.greater,
               "ge": np.greater_equal, "eq": np.equal,
               "ne": np.not_equal}
        for key, npop in ops.items():
            got = set(unpack_columns(np.asarray(out[key])).tolist())
            expect = set(int(c) for c, v in zip(cs, vs) if npop(v, pred))
            assert got == expect, key

    @given(st.lists(st.tuples(st.integers(0, N_BITS - 1),
                              st.integers(-(10**6), 10**6)),
                    max_size=100))
    @settings(max_examples=100, deadline=None)
    def test_sum_min_max_vs_numpy(self, pairs):
        model = {}
        for c, v in pairs:
            model[c] = v
        if not model:
            return
        cs = np.array(sorted(model), np.uint64)
        vs = np.array([model[int(c)] for c in cs], np.int64)
        depth = max(1, int(np.abs(vs).max()).bit_length())
        from pilosa_tpu.engine.words import bsi_encode
        plane = bsi_encode(cs, vs, base=0, depth=depth, n_words=N_WORDS)
        total, cnt = bsik.sum_count(plane)
        assert int(total) == int(vs.sum()) and int(cnt) == len(vs)
        ((mn, mn_c, mx, mx_c),) = bsik.min_max(plane)
        assert int(mn) == int(vs.min())
        assert int(mn_c) == int((vs == vs.min()).sum())
        assert int(mx) == int(vs.max())
        assert int(mx_c) == int((vs == vs.max()).sum())


class FragmentMachine(RuleBasedStateMachine):
    """Stateful fragment test: random op sequences vs a dict-of-sets
    model, with crash-replay equivalence checked at every step boundary
    (reference: fragment snapshot/op-log crash tests, SURVEY.md §5)."""

    @initialize(tmp=st.just(None))
    def setup(self, tmp):
        import tempfile
        from pilosa_tpu.store.fragment import Fragment
        self.dir = tempfile.mkdtemp()
        self.frag = Fragment(self.dir + "/0", 0, max_op_n=7).open()
        self.model: dict[int, set] = {}

    rows = st.integers(0, 5)
    columns = st.lists(st.integers(0, 2000), min_size=1, max_size=20)

    @rule(row=rows, cs=columns)
    def set_bits(self, row, cs):
        arr = np.array(cs, np.uint64)
        self.frag.set_bits(np.full(len(cs), row, np.uint64), arr)
        self.model.setdefault(row, set()).update(cs)

    @rule(row=rows, cs=columns)
    def clear_bits(self, row, cs):
        arr = np.array(cs, np.uint64)
        self.frag.clear_bits(np.full(len(cs), row, np.uint64), arr)
        if row in self.model:
            self.model[row] -= set(cs)
            if not self.model[row]:
                del self.model[row]

    @rule(row=rows)
    def clear_row(self, row):
        self.frag.clear_row(row)
        self.model.pop(row, None)

    @rule(row=rows, cs=columns)
    def set_row(self, row, cs):
        self.frag.set_row(row, np.array(cs, np.uint32))
        self.model[row] = set(cs)

    @rule()
    def check_contents(self):
        assert self.frag.row_ids() == sorted(self.model)
        for r, expect in self.model.items():
            got = set(self.frag.row(r).columns().tolist())
            assert got == expect

    @rule()
    def crash_and_reopen(self):
        """Abandon the open fragment (no close/snapshot) and replay."""
        from pilosa_tpu.store.fragment import Fragment
        self.frag._oplog.close()
        self.frag = Fragment(self.dir + "/0", 0, max_op_n=7).open()
        self.check_contents()


TestFragmentStateful = FragmentMachine.TestCase
TestFragmentStateful.settings = settings(
    max_examples=30, stateful_step_count=20, deadline=None)


class TestPqlProperties:
    @given(st.recursive(
        st.sampled_from(["Row(f=1)", 'Row(g="key")', "Row(amount > 5)",
                         "All()"]),
        lambda children: st.builds(
            lambda op, kids: f"{op}({', '.join(kids)})",
            st.sampled_from(["Intersect", "Union", "Difference", "Xor"]),
            st.lists(children, min_size=1, max_size=3)),
        max_leaves=8))
    @settings(max_examples=100, deadline=None)
    def test_parse_print_round_trip(self, src):
        from pilosa_tpu.pql import parse
        q1 = parse(src)
        assert parse(str(q1)) == q1


class TestExecutorProperties:
    """Whole-query equivalence vs a set-algebra oracle: random writes,
    then every query class checked (the rebuild's analogue of upstream's
    table-driven executor tests, generated instead of enumerated)."""

    @given(st.lists(st.tuples(st.integers(1, 5),
                              st.integers(0, 3000)),
                    min_size=1, max_size=60),
           st.lists(st.tuples(st.integers(1, 5),
                              st.integers(0, 3000)),
                    max_size=20))
    @settings(max_examples=25, deadline=None)
    def test_set_clear_count_vs_oracle(self, sets, clears):
        import tempfile
        from pilosa_tpu.exec import Executor
        from pilosa_tpu.store import Holder
        holder = Holder(tempfile.mkdtemp()).open()
        idx = holder.create_index("i")
        idx.create_field("f")
        ex = Executor(holder)
        model: dict[int, set] = {}
        for r, c in sets:
            ex.execute("i", f"Set({c}, f={r})")
            model.setdefault(r, set()).add(c)
        for r, c in clears:
            ex.execute("i", f"Clear({c}, f={r})")
            model.get(r, set()).discard(c)
        for r in range(1, 6):
            (cnt,) = ex.execute("i", f"Count(Row(f={r}))")
            assert cnt == len(model.get(r, set())), f"row {r}"
        a, b = model.get(1, set()), model.get(2, set())
        (i_,) = ex.execute("i", "Count(Intersect(Row(f=1), Row(f=2)))")
        assert i_ == len(a & b)
        (u_,) = ex.execute("i", "Count(Union(Row(f=1), Row(f=2)))")
        assert u_ == len(a | b)
        (x_,) = ex.execute("i", "Count(Xor(Row(f=1), Row(f=2)))")
        assert x_ == len(a ^ b)
        (t,) = ex.execute("i", "TopN(f)")
        expect = sorted(((len(cs), -r) for r, cs in model.items() if cs),
                        reverse=True)
        assert [(p.count, -p.id) for p in t.pairs] == expect


class TestProtoCodecProperties:
    """The wire codec (api/proto.py) round-trips arbitrary inputs and
    never crashes on arbitrary bytes — the fuzz-corpus analogue for the
    internal wire (reference: internal/ proto + http fuzzing)."""

    @given(st.lists(st.integers(0, (1 << 64) - 1), max_size=300))
    @settings(max_examples=150, deadline=None)
    def test_packed_varints_round_trip(self, vals):
        from pilosa_tpu.api.proto import _packed_uints, _vec_varints
        assert _packed_uints(_vec_varints(vals)) == vals

    @given(st.lists(st.integers(-(1 << 63), (1 << 63) - 1), max_size=200))
    @settings(max_examples=150, deadline=None)
    def test_zigzag_round_trip(self, vals):
        from pilosa_tpu.api.proto import _unzigzag, _vec_zigzag
        assert [_unzigzag(int(z)) for z in _vec_zigzag(vals)] == vals

    @given(rows=st.lists(st.integers(0, (1 << 60)), max_size=80),
           ts=st.one_of(
               st.none(),
               st.lists(st.integers(-(1 << 62), 1 << 62), max_size=80)),
           clear=st.booleans())
    @settings(max_examples=100, deadline=None)
    def test_import_request_round_trip(self, rows, ts, clear):
        from pilosa_tpu.api import proto
        cols = list(range(len(rows)))
        if ts is not None:
            ts = ts[:len(rows)] + [0] * max(0, len(rows) - len(ts))
        raw = proto.encode_import_request(
            row_ids=rows, col_ids=cols, timestamps=ts, clear=clear)
        b = proto.decode_import_request(raw)
        assert b["row_ids"] == (rows or None)
        assert b["col_ids"] == (cols or None)
        assert b["timestamps"] == (ts if ts else None)
        assert b["clear"] == clear

    @given(st.binary(max_size=400))
    @settings(max_examples=300, deadline=None)
    def test_arbitrary_bytes_never_crash(self, blob):
        from pilosa_tpu.api import proto
        for dec in (proto.decode_query_request,
                    proto.decode_query_request_indexed,
                    proto.decode_import_request,
                    proto.decode_import_value_request,
                    proto.decode_query_response,
                    proto.decode_import_response):
            try:
                dec(blob)
            except ValueError:
                pass  # the one allowed failure mode
