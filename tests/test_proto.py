"""Protobuf content negotiation on the query endpoint: the hand-rolled
wire codec round-trips every result shape, and proto responses over HTTP
carry exactly the JSON path's values (api/internal.proto; reference:
``http/handler.go`` content-type negotiation)."""

import json
import urllib.request

import pytest

from pilosa_tpu.api import proto


RESULT_CASES = [
    None,
    True,
    False,
    0,
    12345678901234,
    {"columns": [1, 5, 1 << 40]},
    {"columns": []},
    {"keys": ["alice", "bob"]},
    {"keys": []},  # keyed row with zero columns must stay key-shaped
    {"columns": [1, 2], "rowAttrs": {"team": "infra", "rank": 3}},
    {"columns": [5], "attrs": {"5": {"region": "eu"}}},
    [{"id": 10, "count": 3}, {"id": 0, "count": 1}],
    [{"key": "admin", "count": 7}],
    [],
    {"value": -42, "count": 2},
    {"value": 1.5, "count": 3},
    {"rows": [1, 2, 3]},
    {"rows": []},
    [{"group": [{"field": "f", "rowID": 10}], "count": 2, "agg": -5},
     {"group": [{"field": "f", "rowKey": "x"},
                {"field": "g", "rowID": 0}], "count": 1}],
    {"values": [-3, 0, 9]},
    {"values": [0.5, -1.25]},
    {"values": []},
]


def test_result_round_trips():
    raw = proto.encode_query_response(RESULT_CASES)
    out = proto.decode_query_response(raw)
    assert out["results"] == RESULT_CASES


def test_request_round_trip():
    raw = proto.encode_query_request("Count(Row(f=1))", [0, 5, 954])
    assert proto.decode_query_request(raw) == ("Count(Row(f=1))",
                                               [0, 5, 954])
    raw = proto.encode_query_request("All()")
    assert proto.decode_query_request(raw) == ("All()", None)


def test_error_response():
    raw = proto.encode_query_response(err="field 'nope' not found")
    out = proto.decode_query_response(raw)
    assert out["error"] == "field 'nope' not found"
    assert out["results"] == []


def test_truncated_buffer_rejected():
    raw = proto.encode_query_response(RESULT_CASES)
    with pytest.raises(ValueError):
        proto.decode_query_response(raw[:-3])


@pytest.fixture
def served(tmp_path):
    import threading

    from pilosa_tpu.api import API, Server
    from pilosa_tpu.exec import Executor
    from pilosa_tpu.store import Holder

    holder = Holder(str(tmp_path)).open()
    api = API(holder, Executor(holder))
    srv = Server(api, host="127.0.0.1", port=0)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    yield f"http://127.0.0.1:{srv.address[1]}", api
    srv.close()


def _post(url, path, body, headers=None):
    req = urllib.request.Request(url + path, data=body, method="POST",
                                 headers=headers or {})
    with urllib.request.urlopen(req) as resp:
        return resp.headers.get("Content-Type"), resp.read()


def test_http_negotiation_matches_json(served):
    url, api = served
    _post(url, "/index/i", json.dumps({}).encode())
    _post(url, "/index/i/field/f", json.dumps({}).encode())
    _post(url, "/index/i/field/v", json.dumps(
        {"options": {"type": "int", "min": -50, "max": 50}}).encode())
    _post(url, "/index/i/query",
          b"Set(1, f=10) Set(2, f=10) Set(2, f=20) Set(1, v=-7)")

    # a write through the proto surface (changed / no-op statuses)
    _, raw = _post(url, "/index/i/query", b"Set(9, f=10)",
                   {"Accept": proto.CONTENT_TYPE})
    assert proto.decode_query_response(raw)["results"] == [True]
    _, raw = _post(url, "/index/i/query", b"Set(9, f=10)",
                   {"Accept": proto.CONTENT_TYPE})
    assert proto.decode_query_response(raw)["results"] == [False]

    for pql in [b"Count(Row(f=10))", b"Row(f=10)", b"TopN(f)",
                b"Sum(field=v)", b"Min(field=v)",
                b"GroupBy(Rows(f), aggregate=Count())"]:
        ct_j, raw_j = _post(url, "/index/i/query", pql)
        ct_p, raw_p = _post(url, "/index/i/query", pql,
                            {"Accept": proto.CONTENT_TYPE})
        assert proto.CONTENT_TYPE in ct_p
        assert proto.decode_query_response(raw_p)["results"] == \
            json.loads(raw_j)["results"], pql

    # protobuf-encoded request body
    body = proto.encode_query_request("Count(Row(f=10))")
    _, raw = _post(url, "/index/i/query", body,
                   {"Content-Type": proto.CONTENT_TYPE,
                    "Accept": proto.CONTENT_TYPE})
    assert proto.decode_query_response(raw)["results"] == [3]

    # query errors carry the same HTTP status as the JSON surface (400),
    # with a decodable proto QueryResponse.err body
    import urllib.error
    with pytest.raises(urllib.error.HTTPError) as exc:
        _post(url, "/index/i/query", b"Row(nope=1)",
              {"Accept": proto.CONTENT_TYPE})
    assert exc.value.code == 400
    raw = exc.value.read()
    assert "nope" in proto.decode_query_response(raw)["error"]

    # ?profile has no proto representation: explicit 400, not silence
    with pytest.raises(urllib.error.HTTPError):
        _post(url, "/index/i/query?profile=1", b"Count(Row(f=10))",
              {"Accept": proto.CONTENT_TYPE})

    # Extract is tabular — no proto encoding; 400 with the error as a
    # decodable proto QueryResponse.err, not a JSON body
    with pytest.raises(urllib.error.HTTPError) as exc:
        _post(url, "/index/i/query",
              b"Extract(ConstRow(columns=[1]), Rows(f))",
              {"Accept": proto.CONTENT_TYPE})
    assert exc.value.code == 400
    raw = exc.value.read()
    assert "not representable" in proto.decode_query_response(raw)["error"]
