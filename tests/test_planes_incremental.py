"""Incremental device-plane refresh: small mutations scatter deltas
into the resident plane (planes._incremental) instead of rebuilding +
re-uploading; results must be indistinguishable from a fresh build."""

import numpy as np
import pytest

from pilosa_tpu.engine.words import SHARD_WIDTH
from pilosa_tpu.exec import Executor
from pilosa_tpu.store import FieldOptions, Holder


@pytest.fixture
def env(tmp_path):
    holder = Holder(str(tmp_path)).open()
    idx = holder.create_index("i")
    idx.create_field("f")
    idx.create_field("amount", FieldOptions(type="int", min=-100, max=100))
    ex = Executor(holder)
    return holder, idx, ex


def fresh(holder):
    return Executor(holder)


def test_set_clear_refresh_incrementally(env):
    holder, idx, ex = env
    c2 = SHARD_WIDTH + 9
    ex.execute("i", f"Set(1, f=10) Set(2, f=10) Set({c2}, f=20)")
    (p,) = ex.execute("i", "TopN(f)")  # warms the field plane
    assert [(x.id, x.count) for x in p.pairs] == [(10, 2), (20, 1)]
    before = ex.planes.incremental_applied

    before_absorbs = ex.planes.delta_absorbs
    ex.execute("i", f"Set(3, f=10) Clear(1, f=10) Set({c2 + 1}, f=20)")
    (p,) = ex.execute("i", "TopN(f)")
    assert (ex.planes.incremental_applied > before
            or ex.planes.delta_absorbs > before_absorbs), \
        "small mutations must take the delta-overlay/scatter path"
    assert ex.planes.stats()["builds"] == 1, \
        "small mutations must not rebuild the plane"
    assert [(x.id, x.count) for x in p.pairs] == \
        [(x.id, x.count) for x in fresh(holder).execute("i", "TopN(f)")[0].pairs]


def test_clearrow_and_store_refresh(env):
    holder, idx, ex = env
    ex.execute("i", "Set(1, f=10) Set(2, f=10) Set(3, f=20)")
    ex.execute("i", "TopN(f)")
    before = ex.planes.incremental_applied
    # Store into an EXISTING row id — a brand-new row changes the plane
    # row set and correctly forces a rebuild instead
    ex.execute("i", "ClearRow(f=10) Store(Row(f=20), f=10)")
    (p,) = ex.execute("i", "TopN(f)")
    assert ex.planes.incremental_applied > before
    assert [(x.id, x.count) for x in p.pairs] == \
        [(x.id, x.count) for x in fresh(holder).execute("i", "TopN(f)")[0].pairs]


def test_bsi_plane_refresh(env):
    holder, idx, ex = env
    ex.execute("i", "Set(1, amount=5) Set(2, amount=-3)")
    (s,) = ex.execute("i", "Sum(field=amount)")
    assert (s.value, s.count) == (2, 2)
    before = ex.planes.incremental_applied
    before_absorbs = ex.planes.delta_absorbs
    builds = ex.planes.builds
    ex.execute("i", "Set(3, amount=40) Set(1, amount=7)")
    (s,) = ex.execute("i", "Sum(field=amount)")
    # r20: the BSI plane absorbs the write gap into a device overlay
    # (base⊕delta on the aggregate path) — or scatters incrementally
    # when overlays are off; either way, never a rebuild
    assert (ex.planes.delta_absorbs > before_absorbs
            or ex.planes.incremental_applied > before)
    assert ex.planes.builds == builds
    assert (s.value, s.count) == (7 - 3 + 40, 3)
    (mx,) = ex.execute("i", "Max(field=amount)")
    assert (mx.value, mx.count) == (40, 1)


def test_new_row_forces_rebuild_correctly(env):
    holder, idx, ex = env
    ex.execute("i", "Set(1, f=10)")
    ex.execute("i", "TopN(f)")
    ex.execute("i", "Set(1, f=99)")  # new row id: plane row set changes
    (p,) = ex.execute("i", "TopN(f)")
    assert sorted((x.id, x.count) for x in p.pairs) == [(10, 1), (99, 1)]


def test_bulk_import_rebuilds(env):
    holder, idx, ex = env
    ex.execute("i", "Set(1, f=10)")
    ex.execute("i", "TopN(f)")
    before = ex.planes.incremental_applied
    rng = np.random.default_rng(3)
    idx.field("f").import_bits(
        rng.integers(0, 20, 20000).astype(np.uint64),
        rng.choice(SHARD_WIDTH, 20000, replace=False).astype(np.uint64))
    (p,) = ex.execute("i", "TopN(f, n=3)")
    assert ex.planes.incremental_applied == before  # over cell cap
    assert [(x.id, x.count) for x in p.pairs] == \
        [(x.id, x.count)
         for x in fresh(holder).execute("i", "TopN(f, n=3)")[0].pairs]


def test_recreated_field_does_not_serve_stale_plane(env):
    # drop + recreate via the Index directly (no api-level invalidate):
    # the new fragment's generation is BEHIND the cached one — the cache
    # must rebuild, never scatter onto the dead field's plane
    holder, idx, ex = env
    ex.execute("i", "Set(1, f=10) Set(2, f=10)")
    ex.execute("i", "TopN(f)")
    idx.delete_field("f")
    idx.create_field("f")
    ex.execute("i", "Set(5, f=30)")
    (p,) = ex.execute("i", "TopN(f)")
    assert [(x.id, x.count) for x in p.pairs] == [(30, 1)]


def test_serve_while_plane_builds(env):
    """Big planes build on a background thread; queries answer through
    the streaming path mid-build and flip to the resident plane after —
    same results throughout (r5, VERDICT r4 weak #6: nothing served
    during the ~4.4-min 1B-col plane build)."""
    import threading
    import time

    holder, idx, ex = env
    idx.create_field("g")
    rng = np.random.default_rng(5)
    rows = rng.integers(1, 30, size=3000).astype(np.uint64)
    cols = rng.integers(0, 2 * SHARD_WIDTH, size=3000).astype(np.uint64)
    idx.field("f").import_bits(rows, cols)
    idx.field("g").import_bits(np.ones(500, np.uint64),
                               cols[:500])
    expected = ex.execute("i", "TopN(f, Row(g=1), n=5)")[0].pairs
    assert expected

    # force the background path for any size, and gate the build so
    # the first query provably runs mid-build
    ex.planes.invalidate()
    ex.planes.SYNC_BUILD_MAX = 0
    gate = threading.Event()
    real = ex.planes._build_plane_chunked

    def gated(*a, **k):
        gate.wait(120)
        return real(*a, **k)

    ex.planes._build_plane_chunked = gated
    got_streaming = ex.execute("i", "TopN(f, Row(g=1), n=5)")[0].pairs
    assert got_streaming == expected, "mid-build (streaming) answer"
    field = idx.field("f")
    assert not ex.planes.has_plane("i", field, "standard",
                                   tuple(idx.available_shards()))
    gate.set()
    deadline = time.monotonic() + 60
    while time.monotonic() < deadline and ex.planes._building:
        time.sleep(0.02)
    assert not ex.planes._building, "background build never finished"
    got_resident = ex.execute("i", "TopN(f, Row(g=1), n=5)")[0].pairs
    assert got_resident == expected, "post-flip (resident) answer"
    assert ex.planes.has_plane("i", field, "standard",
                               tuple(idx.available_shards()))


def test_chunked_build_matches_monolithic(env):
    """The donated dynamic-update assembly must produce a plane
    byte-identical to the single-transfer build, including the pow2
    row-pad tail and multi-chunk tiling."""
    holder, idx, ex = env
    rng = np.random.default_rng(7)
    rows = rng.integers(1, 70, size=5000).astype(np.uint64)  # r_pad 128
    cols = rng.integers(0, 3 * SHARD_WIDTH, size=5000).astype(np.uint64)
    idx.field("f").import_bits(rows, cols)
    field = idx.field("f")
    shards = tuple(idx.available_shards())
    mono = ex.planes._build_plane(field, "standard", shards)
    ex.planes.BUILD_CHUNK_BYTES = 3 * 16 * 32768 * 4  # 16-row chunks
    chunked = ex.planes._build_plane_chunked(field, "standard", shards)
    np.testing.assert_array_equal(np.asarray(mono.plane),
                                  np.asarray(chunked.plane))
    np.testing.assert_array_equal(mono.row_ids, chunked.row_ids)


def test_random_mutation_equivalence(env):
    holder, idx, ex = env
    rng = np.random.default_rng(17)
    ex.execute("i", " ".join(
        f"Set({int(rng.integers(0, 200))}, f={int(rng.integers(1, 5))})"
        for _ in range(60)))
    ex.execute("i", "TopN(f)")
    for step in range(15):
        op = rng.integers(0, 3)
        col = int(rng.integers(0, 200))
        row = int(rng.integers(1, 5))
        if op == 0:
            ex.execute("i", f"Set({col}, f={row})")
        elif op == 1:
            ex.execute("i", f"Clear({col}, f={row})")
        else:
            ex.execute("i", f"Set({col}, amount={int(rng.integers(-99, 99))})")
        for pql in ("TopN(f)", "Count(Row(f=1))", "Sum(field=amount)"):
            a = ex.execute("i", pql)[0]
            b = fresh(holder).execute("i", pql)[0]
            if hasattr(a, "pairs"):
                assert [(x.id, x.count) for x in a.pairs] == \
                    [(x.id, x.count) for x in b.pairs], (step, pql)
            elif hasattr(a, "value"):
                assert (a.value, a.count) == (b.value, b.count), (step, pql)
            else:
                assert a == b, (step, pql)
    # r20: cell-level write gaps absorb into delta overlays (BSI and
    # set planes alike) or scatter incrementally — both rebuild-free
    assert ex.planes.incremental_applied + ex.planes.delta_absorbs > 0
