"""r18 tentpole: deadline-aware, self-healing dispatch pipeline.

The batcher is one shared device stream — these tests pin the three
r18 guarantees against injected sickness:

- **deadlines reach the window**: a caller's expiry mid-window raises
  a structured ``QueryTimeoutError`` naming the stage, the abandoned
  item is skipped by the shared readback, and co-batched callers'
  answers are untouched;
- **watchdog + quarantine**: a hung dispatch or readback is bounded —
  the stuck window's items fail with ``PipelineStalledError`` naming
  the stage, the wedged worker is superseded, the queue keeps
  draining, and no threads leak once the hang resolves;
- **health governor**: consecutive dispatch faults degrade serving to
  the per-item fallback path (answers stay exact), probing restores
  healthy.

Plus the knob-off regression pin: ``dispatch_pipeline_depth<=1`` +
``dispatch_watchdog_seconds=0`` restores the exact pre-r18 inline
contract (no reader, no watchdog thread, same answers).
"""

import os
import threading
import time

import numpy as np
import pytest

from pilosa_tpu import fault
from pilosa_tpu.engine.words import SHARD_WIDTH
from pilosa_tpu.exec import Executor
from pilosa_tpu.exec.executor import (PipelineStalledError,
                                      QueryTimeoutError)
from pilosa_tpu.exec.health import DeviceHealthGovernor
from pilosa_tpu.obs import Stats
from pilosa_tpu.store import Holder

WORDS = SHARD_WIDTH // 32


def _np_row_counts(plane: np.ndarray) -> np.ndarray:
    if hasattr(np, "bitwise_count"):
        return np.bitwise_count(plane).sum(axis=(0, 2), dtype=np.int64)
    return np.array([int(np.unpackbits(
        plane[:, r].reshape(-1).view(np.uint8)).sum())
        for r in range(plane.shape[1])], dtype=np.int64)


def _counter(stats, name: str) -> int:
    return int(sum(stats.snapshot()["counters"].get(name, {}).values()))


@pytest.fixture(autouse=True)
def _clean_faults():
    fault.clear()
    yield
    fault.clear()


@pytest.fixture
def served_index(tmp_path):
    """A 2-shard, 16-row on-disk field (the test_multiquery recipe)."""
    from pilosa_tpu.store import roaring

    n_shards, n_rows = 2, 16
    rng = np.random.default_rng(23)
    plane = rng.integers(0, 1 << 32, size=(n_shards, n_rows, WORDS),
                         dtype=np.uint32)
    plane &= rng.integers(0, 1 << 32, size=plane.shape, dtype=np.uint32)
    h = Holder(str(tmp_path)).open()
    idx = h.create_index("i", track_existence=False)
    idx.create_field("f")
    h.close()
    frag_dir = os.path.join(str(tmp_path), "i", "f", "views", "standard",
                            "fragments")
    os.makedirs(frag_dir, exist_ok=True)
    for s in range(n_shards):
        with open(os.path.join(frag_dir, str(s)), "wb") as fh:
            fh.write(roaring.serialize_dense(plane[s]))
    holder = Holder(str(tmp_path)).open()
    yield holder, _np_row_counts(plane), n_rows
    holder.close()


def _resident_plane(ex, holder):
    idx = holder.index("i")
    fld = idx.field("f")
    shards = tuple(idx.available_shards())
    return ex.planes.field_plane("i", fld, "standard", shards)


def _pipeline_census() -> dict:
    """Process-wide batcher thread counts by name prefix.  Other
    tests' executors leave parked collectors behind (pre-existing:
    daemon threads holding their batcher alive), so assertions compare
    against a BASELINE taken inside each test, never absolutes."""
    names = [t.name for t in threading.enumerate()]
    return {n: sum(1 for x in names if x.startswith(n))
            for n in ("pilosa-count-batcher", "pilosa-batch-readback",
                      "pilosa-pipeline-watchdog")}


def _await_census_back_to(baseline: dict,
                          timeout: float = 20.0) -> dict:
    """Poll until the census is back at (or under) the baseline —
    quarantine zombies exit on their own schedule once a hang
    resolves, so this trades latency, never signal."""
    deadline = time.monotonic() + timeout
    census = {}
    while time.monotonic() < deadline:
        census = _pipeline_census()
        if all(census[k] <= baseline[k] for k in baseline):
            return census
        time.sleep(0.2)
    return census


class TestDeadlinePropagation:
    def test_expired_deadline_refused_before_dispatch(self, served_index):
        """The fast-lane/enqueue guard: a deadline already in the past
        never occupies a window slot — it fails up front, naming the
        stage."""
        holder, oracle, _ = served_index
        ex = Executor(holder, stats=Stats())
        ps = _resident_plane(ex, holder)
        with pytest.raises(QueryTimeoutError) as ei:
            ex.batcher.submit_rowcounts(
                ps.plane, deadline=time.monotonic() - 1.0)
        assert ei.value.stage == "dispatch"

    def test_wait_deadline_boundary_never_returns_none(self):
        """The deadline/delivery boundary, both interleavings: a late
        deliverer that observed the abandon mark leaves nothing stored
        (wait must raise, NEVER return None as the answer), while a
        store that landed first is a real answer (wait returns it)."""
        from pilosa_tpu.exec.batcher import CountBatcher, _Pending
        p = _Pending("count", None, (None,),
                     deadline=time.monotonic() - 0.01)
        p.abandoned = True          # as wait() sets at its timeout
        CountBatcher._deliver(p, [42])  # skips the store, sets event
        assert p.event.is_set() and p.result is None
        with pytest.raises(QueryTimeoutError):
            CountBatcher.wait(None, p)
        q = _Pending("count", None, (None,),
                     deadline=time.monotonic() - 0.01)
        CountBatcher._deliver(q, [42])  # the store landed first
        assert CountBatcher.wait(None, q) == [42]

    def test_deadline_expiry_mid_window_leaves_cobatched_exact(
            self, served_index):
        """One caller's expiry mid-window must not corrupt co-batched
        answers: the abandoned item is skipped by the shared finish,
        the surviving caller's answer stays oracle-exact, and the
        expired caller's error names the stage."""
        holder, oracle, n_rows = served_index
        ex = Executor(holder, stats=Stats(), count_batch_window=0.005,
                      solo_fastlane=False,
                      dispatch_watchdog_seconds=0)  # deadline, not
        # quarantine, must be what fails the expiring caller here
        ps = _resident_plane(ex, holder)
        batcher = ex.batcher
        # the window's dispatch stalls 0.4s — caller A (deadline 0.1s)
        # expires mid-window; caller B (no deadline) rides it out
        fault.set_fault("exec.dispatch_hang", "delay", times=1,
                        match={"kind": "rowcounts"},
                        args={"seconds": 0.4})
        results = {}
        errors = {}
        start = threading.Barrier(2)

        def caller(name, deadline):
            try:
                start.wait()
                results[name] = np.asarray(batcher.submit_rowcounts(
                    ps.plane, deadline=deadline))
            except Exception as e:  # noqa: BLE001
                errors[name] = e

        t_a = threading.Thread(
            target=caller, args=("a", time.monotonic() + 0.15))
        t_b = threading.Thread(target=caller, args=("b", None))
        t_a.start()
        t_b.start()
        t_a.join(timeout=30)
        t_b.join(timeout=30)
        assert "a" in errors, "expiring caller should have timed out"
        assert isinstance(errors["a"], QueryTimeoutError)
        assert errors["a"].stage in ("queued", "dispatch", "readback")
        assert "b" in results, f"survivor failed: {errors.get('b')!r}"
        np.testing.assert_array_equal(results["b"][:n_rows], oracle)
        # the pipeline is unharmed: a fresh submit answers exactly
        got = np.asarray(batcher.submit_rowcounts(ps.plane))
        np.testing.assert_array_equal(got[:n_rows], oracle)

    def test_mixed_kinds_with_deadline_churn_interleaved_ingest(
            self, tmp_path):
        """32-way acceptance pin (r18 satellite): mixed-kind readers
        (counts, selected counts, compound trees) stay oracle-exact
        while DOOMED callers churn tiny deadlines through the same
        windows and writers stream bits into the same plane.  A doomed
        caller either times out (QueryTimeoutError) or answers exactly
        — never a wrong answer, never a foreign error."""
        holder = Holder(str(tmp_path)).open()
        idx = holder.create_index("i")
        idx.create_field("f")
        stats = Stats()
        ex = Executor(holder, stats=stats, delta_cells=32)
        n_read_rows = 4
        write_row = 9
        rng = np.random.default_rng(17)
        counts = [0] * n_read_rows
        f = holder.index("i").field("f")
        rows_l, cols_l = [], []
        for s in range(2):
            offs = rng.choice(SHARD_WIDTH // 2, size=64, replace=False)
            rr = rng.integers(0, n_read_rows, size=64)
            for r, o in zip(rr, offs):
                rows_l.append(int(r))
                cols_l.append(s * SHARD_WIDTH + int(o))
                counts[int(r)] += 1
        f.import_bits(np.asarray(rows_l, np.uint64),
                      np.asarray(cols_l, np.uint64))
        holder.index("i").note_columns(np.asarray(cols_l, np.uint64))
        tree_pql = ("Count(Intersect(Row(f=0), "
                    "Union(Row(f=1), Row(f=2))))")
        sets = [set() for _ in range(n_read_rows)]
        for r, c in zip(rows_l, cols_l):
            if r < n_read_rows:
                sets[r].add(c)
        tree_want = len(sets[0] & (sets[1] | sets[2]))
        for r in range(n_read_rows):
            assert ex.execute("i", f"Count(Row(f={r}))") == [counts[r]]
        assert ex.execute("i", tree_pql) == [tree_want]

        errors: list = []
        timeouts = [0]
        stop = time.monotonic() + 2.5
        start = threading.Barrier(33)

        def reader(i):
            kind = i % 2
            try:
                start.wait()
                while time.monotonic() < stop:
                    if kind == 0:
                        r = i % n_read_rows
                        got = ex.execute("i", f"Count(Row(f={r}))")
                        assert got == [counts[r]], got
                    else:
                        got = ex.execute("i", tree_pql)
                        assert got == [tree_want], got
            except Exception as e:  # noqa: BLE001
                errors.append(repr(e))

        def doomed(i):
            try:
                start.wait()
                while time.monotonic() < stop:
                    r = i % n_read_rows
                    try:
                        got = ex.execute(
                            "i", f"Count(Row(f={r}))",
                            deadline=time.monotonic() + 0.002)
                    except QueryTimeoutError:
                        timeouts[0] += 1
                        continue
                    assert got == [counts[r]], \
                        f"doomed caller got a WRONG answer: {got}"
            except Exception as e:  # noqa: BLE001
                errors.append(f"doomed: {e!r}")

        def writer(w):
            wrng = np.random.default_rng(100 + w)
            try:
                start.wait()
                while time.monotonic() < stop:
                    s = int(wrng.integers(0, 2))
                    c = (s * SHARD_WIDTH + SHARD_WIDTH // 2
                         + int(wrng.integers(0, SHARD_WIDTH // 2)))
                    ex.execute("i", f"Set({c}, f={write_row})")
            except Exception as e:  # noqa: BLE001
                errors.append(f"writer: {e!r}")

        threads = ([threading.Thread(target=reader, args=(i,))
                    for i in range(22)]
                   + [threading.Thread(target=doomed, args=(i,))
                      for i in range(8)]
                   + [threading.Thread(target=writer, args=(w,))
                      for w in range(2)])
        for t in threads:
            t.start()
        start.wait()
        for t in threads:
            t.join(timeout=120)
        assert not errors, errors[:5]
        # fresh reads after the churn: still exact
        for r in range(n_read_rows):
            assert ex.execute("i", f"Count(Row(f={r}))") == [counts[r]]
        holder.close()


class TestWatchdogQuarantine:
    def test_hung_dispatch_quarantined_and_recovers(self, served_index):
        """A hung single-group dispatch: the watchdog quarantines the
        window (structured error naming the stage), a fresh collector
        keeps serving, the governor degrades then probes back, and the
        zombie thread exits once the hang resolves."""
        holder, oracle, n_rows = served_index
        stats = Stats()
        # warm with a GENEROUS bound (a first-time XLA compile is a
        # legitimate multi-hundred-ms dispatch), then shrink the knob
        # at runtime — the monitor re-derives its tick every sweep
        ex = Executor(holder, stats=stats, count_batch_window=0.002,
                      solo_fastlane=False,
                      dispatch_watchdog_seconds=5.0,
                      device_health_probe_seconds=0.1)
        assert ex.execute("i", "Count(Row(f=3))") == [int(oracle[3])]
        baseline = _pipeline_census()
        ex.batcher.watchdog_s = 0.1
        fault.set_fault("exec.dispatch_hang", "delay", times=1,
                        match={"kind": "count"}, args={"seconds": 3.0})
        t0 = time.monotonic()
        with pytest.raises(PipelineStalledError) as ei:
            ex.execute("i", "Count(Row(f=3))")
        elapsed = time.monotonic() - t0
        assert ei.value.stage == "dispatch"
        assert "quarantin" in str(ei.value)
        # bounded by the watchdog (plus one stale 1s monitor tick from
        # before the runtime shrink), far under the 3s hang
        assert elapsed < 2.5, \
            f"caller held {elapsed:.2f}s — the watchdog never fired"
        assert _counter(stats, "pipeline_watchdog_trips_total") >= 1
        assert _counter(stats, "pipeline_quarantined_windows_total") >= 1
        # the queue keeps draining on the fresh collector (degraded
        # serving answers exactly), and probing restores healthy
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            assert ex.execute("i", "Count(Row(f=5))") == \
                [int(oracle[5])]
            if ex.batcher.governor.state == "healthy":
                break
            time.sleep(0.05)
        assert ex.batcher.governor.state == "healthy"
        # zombie collector exits once the 3s delay resolves
        census = _await_census_back_to(baseline)
        assert census["pilosa-count-batcher"] <= \
            baseline["pilosa-count-batcher"], (census, baseline)

    def test_hung_readback_quarantined(self, served_index):
        """A wedged device→host read: the readback-stage watchdog
        fails the window (stage=readback), supersedes the reader, and
        subsequent queries answer exactly."""
        holder, oracle, n_rows = served_index
        stats = Stats()
        ex = Executor(holder, stats=stats, count_batch_window=0.002,
                      solo_fastlane=False, dispatch_pipeline_depth=2,
                      dispatch_watchdog_seconds=5.0,
                      device_health_probe_seconds=0.1)
        assert ex.execute("i", "Count(Row(f=1))") == [int(oracle[1])]
        baseline = _pipeline_census()
        ex.batcher.watchdog_s = 0.1
        fault.set_fault("exec.readback_hang", "delay", times=1,
                        args={"seconds": 3.0})
        with pytest.raises(PipelineStalledError) as ei:
            ex.execute("i", "Count(Row(f=1))")
        assert ei.value.stage == "readback"
        # recovery: fresh reader, exact answers, healthy again
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            assert ex.execute("i", "Count(Row(f=2))") == \
                [int(oracle[2])]
            if ex.batcher.governor.state == "healthy":
                break
            time.sleep(0.05)
        assert ex.batcher.governor.state == "healthy"
        census = _await_census_back_to(baseline)
        assert census["pilosa-batch-readback"] <= \
            baseline["pilosa-batch-readback"], (census, baseline)

    def test_finish_window_failure_fails_items_not_wedges(
            self, served_index):
        """r18 satellite fix: an exception escaping _finish_window
        OUTSIDE _readback's per-item fallbacks used to leave every
        _Pending.event unset forever — now it fails the whole window
        loudly."""
        holder, oracle, _ = served_index
        ex = Executor(holder, stats=Stats(), count_batch_window=0.002,
                      solo_fastlane=False, dispatch_pipeline_depth=2)
        ps = _resident_plane(ex, holder)
        batcher = ex.batcher
        orig = batcher._readback
        batcher._readback = lambda w: (_ for _ in ()).throw(
            RuntimeError("synthetic readback explosion"))
        try:
            with pytest.raises(PipelineStalledError) as ei:
                batcher.submit_rowcounts(ps.plane)
            assert ei.value.stage == "readback"
            assert "synthetic readback explosion" in str(ei.value)
        finally:
            batcher._readback = orig
        got = np.asarray(batcher.submit_rowcounts(ps.plane))
        np.testing.assert_array_equal(got[:16], oracle)

    def test_collector_death_fails_backlog_immediately(
            self, served_index):
        """r18 satellite fix: a collector that dies with items queued
        fails the backlog with structured errors and keeps serving —
        the items are never orphaned until the next enqueue."""
        holder, oracle, _ = served_index
        ex = Executor(holder, stats=Stats(), count_batch_window=0.002,
                      solo_fastlane=False)
        ps = _resident_plane(ex, holder)
        batcher = ex.batcher
        orig = batcher._collect_once
        died = []

        def dying_collect():
            batcher._kick.wait()
            if not died:
                died.append(True)
                raise RuntimeError("synthetic collector death")
            return orig()

        batcher._collect_once = dying_collect
        try:
            h = batcher.enqueue_rowcounts(ps.plane)
            with pytest.raises(PipelineStalledError) as ei:
                batcher.wait(h)
            assert ei.value.stage == "collect"
            assert "collector failed" in str(ei.value)
        finally:
            batcher._collect_once = orig
        # the same worker thread survived and keeps serving
        got = np.asarray(batcher.submit_rowcounts(ps.plane))
        np.testing.assert_array_equal(got[:16], oracle)

    def test_no_thread_leak_after_repeated_quarantines(
            self, served_index):
        """The thread-leak pin extended to the r18 machinery: three
        quarantine-and-recover cycles must not accumulate collector /
        readback / watchdog threads."""
        holder, oracle, _ = served_index
        ex = Executor(holder, stats=Stats(), count_batch_window=0.002,
                      solo_fastlane=False,
                      dispatch_watchdog_seconds=5.0,
                      device_health_probe_seconds=0.05)
        assert ex.execute("i", "Count(Row(f=0))") == [int(oracle[0])]
        baseline_census = _pipeline_census()
        baseline = threading.active_count()
        ex.batcher.watchdog_s = 0.08
        for _ in range(3):
            fault.set_fault("exec.dispatch_hang", "delay", times=1,
                            match={"kind": "count"},
                            args={"seconds": 2.0})
            with pytest.raises(PipelineStalledError):
                ex.execute("i", "Count(Row(f=0))")
            # serve back to healthy before the next cycle
            deadline = time.monotonic() + 10
            while (ex.batcher.governor.state != "healthy"
                   and time.monotonic() < deadline):
                ex.execute("i", "Count(Row(f=1))")
                time.sleep(0.02)
        census = _await_census_back_to(baseline_census)
        for name, count in baseline_census.items():
            assert census[name] <= count, (census, baseline_census)
        # zombies drain on their own schedule; poll, don't sleep-assert
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            leaked = threading.active_count() - baseline
            if leaked <= 2:
                break
            time.sleep(0.2)
        assert leaked <= 2, \
            f"{leaked} threads leaked across 3 quarantine cycles"


class TestGovernor:
    def test_unit_transitions(self):
        g = DeviceHealthGovernor(probe_after_s=0.05)
        assert g.state == "healthy" and g.admit() and g.fastlane_ok()
        g.record_fault()
        g.record_fault()
        assert g.state == "healthy"  # below threshold
        g.record_success()
        g.record_fault()
        g.record_fault()
        assert g.state == "healthy"  # streak was reset
        g.record_fault()
        assert g.state == "degraded"
        assert not g.admit() and not g.fastlane_ok()
        assert not g.pipelining_ok()
        time.sleep(0.06)
        assert g.admit()  # the probe window
        assert g.state == "probing"
        assert not g.admit()  # only ONE probe at a time
        g.record_fault()  # probe failed
        assert g.state == "degraded"
        time.sleep(0.06)
        assert g.admit()
        g.record_success()  # probe succeeded
        assert g.state == "healthy" and g.admit()
        # a watchdog trip degrades immediately, regardless of streak
        g.record_trip()
        assert g.state == "degraded"
        payload = g.payload()
        assert payload["state"] == "degraded"
        assert payload["watchdogTrips"] == 1

    def test_degraded_serving_stays_exact_then_reprobes(
            self, served_index):
        """Consecutive dispatch faults degrade the governor; every
        answer through the episode is exact (per-item fallback), and
        once the fault schedule exhausts a probe restores healthy."""
        holder, oracle, _ = served_index
        stats = Stats()
        ex = Executor(holder, stats=stats, count_batch_window=0.002,
                      solo_fastlane=False,
                      device_health_probe_seconds=0.05)
        assert ex.execute("i", "Count(Row(f=0))") == [int(oracle[0])]
        fault.set_fault("exec.dispatch_error", "error", times=4)
        saw_degraded = False
        deadline = time.monotonic() + 20
        i = 0
        while time.monotonic() < deadline:
            r = i % 8
            i += 1
            assert ex.execute("i", f"Count(Row(f={r}))") == \
                [int(oracle[r])]
            state = ex.batcher.governor.state
            if state in ("degraded", "probing"):
                saw_degraded = True
            elif state == "healthy" and saw_degraded:
                break
            time.sleep(0.01)
        assert saw_degraded, "governor never degraded"
        assert ex.batcher.governor.state == "healthy"
        # the deviceHealth surface carries the episode
        dh = ex.device_health()
        assert dh["state"] == "healthy"
        assert dh["faultsTotal"] >= 3

    def test_fastlane_gated_off_while_degraded(self, served_index):
        holder, oracle, _ = served_index
        stats = Stats()
        ex = Executor(holder, stats=stats)  # adaptive + fast lane on
        assert ex.execute("i", "Count(Row(f=2))") == [int(oracle[2])]
        base_hits = _counter(stats, "solo_fastlane_hits_total")
        assert base_hits >= 1
        ex.batcher.governor.record_trip()  # force degraded
        assert ex.execute("i", "Count(Row(f=2))") == [int(oracle[2])]
        assert _counter(stats, "solo_fastlane_hits_total") == base_hits, \
            "fast lane admitted a dispatch while degraded"


class TestKnobOffContract:
    def test_depth_one_watchdog_off_restores_inline_contract(
            self, served_index):
        """pipeline_depth<=1 + watchdog off = the pre-r18 inline loop:
        no reader thread, no watchdog thread, no window registry
        churn, identical answers."""
        holder, oracle, n_rows = served_index
        ex = Executor(holder, stats=Stats(), count_batch_window=0.001,
                      dispatch_pipeline_depth=1,
                      dispatch_watchdog_seconds=0)
        for r in (2, 9):
            assert ex.execute("i", f"Count(Row(f={r}))") == \
                [int(oracle[r])]
        b = ex.batcher
        assert b._readq is None
        assert b._read_thread is None
        # knob off = THIS batcher never started a monitor (other
        # tests' executors may still be draining theirs process-wide)
        assert b._watchdog is None
        assert not b._windows
        # the governor exists but never intervened
        assert b.governor.state == "healthy"
        assert ex.device_health()["watchdogSeconds"] == 0.0

    def test_watchdog_on_happy_path_answers_unchanged(
            self, served_index):
        """The monitor must cost nothing on the happy path: with the
        watchdog armed tight, a clean serve pattern never trips it."""
        holder, oracle, n_rows = served_index
        stats = Stats()
        ex = Executor(holder, stats=stats, count_batch_window=0.002,
                      solo_fastlane=False,
                      dispatch_watchdog_seconds=0.5)
        for _ in range(3):
            for r in range(n_rows):
                assert ex.execute("i", f"Count(Row(f={r}))") == \
                    [int(oracle[r])]
        assert _counter(stats, "pipeline_watchdog_trips_total") == 0
        assert _counter(stats,
                        "pipeline_quarantined_windows_total") == 0
        assert ex.batcher.governor.state == "healthy"
