"""Storage tree tests: RowBits, fragment persistence + op-log replay,
field types (set/int/time/mutex/bool), holder reopen — the rebuild's
equivalent of ``fragment_test.go`` / ``field_test.go`` temp-dir fixtures
with crash-replay (SURVEY.md §5)."""

import os
from datetime import datetime

import numpy as np
import pytest

from pilosa_tpu.engine.words import SHARD_WIDTH
from pilosa_tpu.store import (EXISTENCE_FIELD, FieldOptions, Fragment, Holder,
                              RowBits)
from pilosa_tpu.store import timeq
from pilosa_tpu.store.oplog import OpLog, OP_SET_BITS


class TestRowBits:
    def test_add_remove(self):
        r = RowBits()
        assert r.add(np.array([1, 5, 9])) == 3
        assert r.add(np.array([5, 7])) == 1
        assert r.cardinality == 4
        assert r.remove(np.array([5, 100])) == 1
        np.testing.assert_array_equal(r.columns(), [1, 7, 9])

    def test_dense_conversion(self, rng):
        cols = rng.choice(SHARD_WIDTH, size=40000, replace=False)
        r = RowBits.from_columns(cols)
        assert r._words is not None  # crossed DENSE_THRESHOLD
        np.testing.assert_array_equal(r.columns(), np.sort(cols))
        assert r.contains(int(cols[0]))

    def test_dense_mutation(self, rng):
        cols = rng.choice(SHARD_WIDTH, size=40000, replace=False)
        r = RowBits.from_columns(cols)
        extra = np.setdiff1d(np.arange(50000, 50100, dtype=np.uint32), cols)
        assert r.add(extra) == len(extra)
        assert r.remove(extra) == len(extra)
        np.testing.assert_array_equal(r.columns(), np.sort(cols))

    def test_words_round_trip(self, rng):
        cols = rng.choice(SHARD_WIDTH, size=1000, replace=False)
        r = RowBits.from_columns(cols)
        r2 = RowBits.from_words(r.words())
        np.testing.assert_array_equal(r2.columns(), np.sort(cols))

    def test_out_of_range(self):
        with pytest.raises(ValueError):
            RowBits.from_columns(np.array([SHARD_WIDTH]))


class TestFragment:
    def test_set_clear_persist(self, tmp_path):
        path = str(tmp_path / "0")
        f = Fragment(path, 0).open()
        assert f.set_bit(3, 100)
        assert not f.set_bit(3, 100)  # already set
        assert f.set_bit(7, 200)
        assert f.clear_bit(7, 200)
        f.close()

        g = Fragment(path, 0).open()
        assert g.row(3).contains(100)
        assert not g.row(7).any()
        assert g.row_ids() == [3]

    def test_oplog_replay_without_snapshot(self, tmp_path):
        path = str(tmp_path / "0")
        f = Fragment(path, 0).open()
        f.set_bits(np.array([1, 1, 2], np.uint64), np.array([10, 11, 12], np.uint64))
        # no close/snapshot — simulate crash; oplog alone must restore
        g = Fragment(path, 0).open()
        assert g.row(1).cardinality == 2
        assert g.row(2).contains(12)

    def test_torn_oplog_tail(self, tmp_path):
        path = str(tmp_path / "0")
        f = Fragment(path, 0).open()
        f.set_bit(1, 1)
        f.set_bit(2, 2)
        with open(path + ".oplog", "ab") as fh:
            fh.write(b"\x01\x02\x03")  # torn partial record
        g = Fragment(path, 0).open()
        assert g.row(1).contains(1) and g.row(2).contains(2)

    def test_auto_snapshot_at_max_op_n(self, tmp_path):
        path = str(tmp_path / "0")
        f = Fragment(path, 0, max_op_n=10).open()
        for i in range(12):
            f.set_bit(0, i)
        assert f.op_n <= 10
        assert os.path.exists(path)
        g = Fragment(path, 0).open()
        assert g.row(0).cardinality == 12

    def test_set_row_and_clear_row(self, tmp_path):
        f = Fragment(str(tmp_path / "0"), 0).open()
        f.set_bits(np.array([5, 5, 5], np.uint64), np.array([1, 2, 3], np.uint64))
        assert f.set_row(5, np.array([2, 9]))
        np.testing.assert_array_equal(f.row(5).columns(), [2, 9])
        assert f.clear_row(5) == 2
        assert not f.row(5).any()

    def test_blocks_checksums(self, tmp_path):
        f = Fragment(str(tmp_path / "a"), 0).open()
        g = Fragment(str(tmp_path / "b"), 0).open()
        f.set_bit(5, 100)
        g.set_bit(5, 100)
        assert f.blocks() == g.blocks()
        g.set_bit(205, 1)  # different block
        bf, bg = f.blocks(), g.blocks()
        assert bf[0] == bg[0] and 2 in bg and 2 not in bf

    def test_import_roaring(self, tmp_path):
        from pilosa_tpu.store import roaring
        f = Fragment(str(tmp_path / "0"), 0).open()
        positions = np.array([0, 1, SHARD_WIDTH + 5], np.uint64)  # rows 0,1
        assert f.import_roaring(roaring.serialize(positions)) == 3
        assert f.row(1).contains(5)

    def test_rows_containing(self, tmp_path, rng):
        # sparse + dense rows, against a per-row contains() oracle;
        # the cache must invalidate on mutation
        f = Fragment(str(tmp_path / "0"), 0).open()
        n = 5000
        rows = rng.integers(0, 200, size=n).astype(np.uint64)
        cols = rng.integers(0, 1 << 14, size=n).astype(np.uint64)
        f.set_bits(rows, cols)
        f.set_bits(np.full(6000, 201, np.uint64),  # one dense row
                   rng.choice(SHARD_WIDTH, 6000, replace=False).astype(np.uint64))
        for col in [int(cols[0]), int(cols[7]), 12345, 0]:
            expect = sorted(r for r in f.row_ids()
                            if f.row(r).contains(col))
            np.testing.assert_array_equal(
                f.rows_containing(col), np.array(expect, np.uint64),
                err_msg=f"col {col}")
        probe = int(cols[0])
        before = f.rows_containing(probe)
        f.set_bit(199, probe)
        after = f.rows_containing(probe)
        assert 199 in after and set(map(int, before)) - {199} \
            == set(map(int, after)) - {199}

    def test_rows_containing_over_cap_fallback(self, tmp_path,
                                               monkeypatch, rng):
        monkeypatch.setattr(Fragment, "COLINDEX_MAX_BITS", 100)
        f = Fragment(str(tmp_path / "0"), 0).open()
        rows = np.arange(300, dtype=np.uint64)
        f.set_bits(rows, np.full(300, 77, np.uint64))
        np.testing.assert_array_equal(f.rows_containing(77), rows)
        assert f.rows_containing(78).size == 0

    def test_lazy_snapshot_open(self, tmp_path, rng):
        # reopen must NOT expand bits eagerly (mmap FromBuffer path);
        # reads materialize on demand and stay correct
        path = str(tmp_path / "0")
        f = Fragment(path, 0).open()
        n = 3000
        rows = rng.integers(0, 50, size=n).astype(np.uint64)
        cols = rng.choice(1 << 16, size=n, replace=False).astype(np.uint64)
        f.set_bits(rows, cols)
        card = f.cardinality()
        ids = f.row_ids()
        row7 = f.row(7).columns().copy()
        f.close()

        g = Fragment(path, 0).open()
        assert g._snap_dir is not None and len(g._snap_pending) > 0
        assert not g.rows, "no row may be materialized at open"
        assert g.row_ids() == ids          # directory-only
        assert g.cardinality() == card     # directory-only
        assert 7 in g._snap_pending
        np.testing.assert_array_equal(g.row(7).columns(), row7)
        assert 7 not in g._snap_pending    # materialized on touch

        # mutations against still-lazy rows
        some = int(ids[3])
        before = g.row(some).cardinality
        assert g.set_bit(some, 1 << 17)
        assert g.row(some).cardinality == before + 1
        assert g.clear_row(int(ids[4])) > 0
        assert int(ids[4]) not in g.row_ids()
        g.close()

        h = Fragment(path, 0).open()
        assert int(ids[4]) not in h.row_ids()
        np.testing.assert_array_equal(h.row(7).columns(), row7)
        assert h.cardinality() == len(h.positions())

    def test_blocks_and_rows_containing_stay_lazy(self, tmp_path,
                                                  monkeypatch, rng):
        # AAE checksums + Rows(column=) on a lazy fragment must not
        # materialize the row set; results equal the materialized truth
        path = str(tmp_path / "0")
        f = Fragment(path, 0).open()
        n = 4000
        rows = rng.integers(0, 500, size=n).astype(np.uint64)
        cols = rng.integers(0, 1 << 14, size=n).astype(np.uint64)
        f.set_bits(rows, cols)
        truth_blocks = f.blocks()
        probe = int(cols[0])
        truth_rows = f.rows_containing(probe)
        truth_bp = f.block_positions(2)
        f.close()

        g = Fragment(path, 0).open()
        # force the no-materialize positions-scan regime
        monkeypatch.setattr(Fragment, "COLINDEX_MAX_ROWS", 10)
        monkeypatch.setattr(Fragment, "COLINDEX_CONTAINS_MAX_ROWS", 0)
        assert g.blocks() == truth_blocks
        np.testing.assert_array_equal(g.rows_containing(probe), truth_rows)
        np.testing.assert_array_equal(g.block_positions(2), truth_bp)
        assert not g.rows, "lazy reads must not materialize rows"

    def test_auto_snapshot_keeps_lazy_rows_visible(self, tmp_path):
        # compaction during serving must not lose snapshot-resident
        # rows that were never materialized: after snapshot() the
        # fragment re-opens the new blob as its lazy backing
        path = str(tmp_path / "0")
        f = Fragment(path, 0, max_op_n=5).open()
        f.set_bits(np.arange(50, dtype=np.uint64),
                   np.arange(50, dtype=np.uint64))
        f.close()

        g = Fragment(path, 0, max_op_n=5).open()
        assert len(g._snap_pending) == 50
        for i in range(8):  # crosses max_op_n -> auto snapshot
            g.set_bit(100 + i, 7)
        assert g.op_n <= 5
        assert g.cardinality() == 58
        assert g.row(3).contains(3)          # pre-compaction lazy row
        assert len(g.row_ids()) == 58
        # and the new backing file is the merged truth
        g.close()
        h = Fragment(path, 0).open()
        assert h.cardinality() == 58 and h.row(105).contains(7)

    def test_grouped_mutation_on_lazy_rows(self, tmp_path):
        # set_bits_grouped / clear_bits_grouped (the BSI import path)
        # must materialize snapshot-resident rows before mutating
        path = str(tmp_path / "0")
        f = Fragment(path, 0).open()
        f.set_bits(np.array([3, 3, 3], np.uint64),
                   np.array([10, 11, 12], np.uint64))
        f.close()

        g = Fragment(path, 0).open()
        assert 3 in g._snap_pending
        assert g.set_bits_grouped([(3, np.array([12, 13], np.uint32))]) == 1
        np.testing.assert_array_equal(g.row(3).columns(), [10, 11, 12, 13])
        assert g.cardinality() == 4
        g.close()
        h = Fragment(path, 0).open()
        assert 3 in h._snap_pending
        assert h.clear_bits_grouped([(3, np.array([10, 99], np.uint32))]) == 1
        np.testing.assert_array_equal(h.row(3).columns(), [11, 12, 13])
        # Store() no-op check against a still-lazy row
        h.close()
        k = Fragment(path, 0).open()
        assert not k.set_row(3, np.array([11, 12, 13]))  # identical: no-op
        assert k.set_row(3, np.array([11]))

    def test_plane_rows_matches_words(self, tmp_path, rng):
        # plane assembly from the mmap blob (native fast path when
        # built) must equal per-row words() materialization
        path = str(tmp_path / "0")
        f = Fragment(path, 0).open()
        n = 4000
        rows = rng.integers(0, 40, size=n).astype(np.uint64)
        cols = rng.choice(1 << 15, size=n, replace=False).astype(np.uint64)
        f.set_bits(rows, cols)
        # one dense row to cross representations
        f.set_bits(np.full(5000, 41, np.uint64),
                   rng.choice(SHARD_WIDTH, 5000, replace=False).astype(np.uint64))
        f.close()

        g = Fragment(path, 0).open()
        ids = g.row_ids()
        from pilosa_tpu.engine.words import WORDS_PER_SHARD
        out = np.zeros((len(ids), WORDS_PER_SHARD), np.uint32)
        g.plane_rows(ids, out)
        # compare against materialized truth, and overlay precedence
        for i, r in enumerate(ids):
            np.testing.assert_array_equal(out[i], g.row(r).words(),
                                          err_msg=f"row {r}")
        g.set_bit(int(ids[0]), 3)  # overlay row 0; rebuild
        out2 = np.zeros_like(out)
        g.plane_rows(ids, out2)
        np.testing.assert_array_equal(out2[0], g.row(int(ids[0])).words())
        g.close()


class TestSnapshotQueue:
    def test_background_compaction(self, tmp_path):
        import time

        from pilosa_tpu.store.holder import SnapshotQueue
        q = SnapshotQueue()
        f = Fragment(str(tmp_path / "0"), 0, max_op_n=10,
                     snapshot_submit=q.submit).open()
        for i in range(25):
            f.set_bit(0, i)
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and f.op_n > 10:
            time.sleep(0.02)
        assert f.op_n <= 10, "background queue never compacted"
        assert os.path.exists(str(tmp_path / "0"))
        assert f.cardinality() == 25
        q.close()
        # queue closed: the write path falls back to inline compaction
        for i in range(25, 45):
            f.set_bit(0, i)
        assert f.op_n <= 10
        f.close()
        g = Fragment(str(tmp_path / "0"), 0).open()
        assert g.cardinality() == 45

    def test_holder_wires_the_queue(self, tmp_path):
        h = Holder(str(tmp_path)).open()
        idx = h.create_index("i", track_existence=False)
        f = idx.create_field("f")
        frag = f.view("standard", create=True).fragment(0, create=True)
        assert frag._snapshot_submit is not None
        h.close()
        h2 = Holder(str(tmp_path), async_snapshots=False).open()
        frag2 = (h2.index("i").field("f").view("standard", create=True)
                 .fragment(0, create=True))
        assert frag2._snapshot_submit is None
        h2.close()


class TestOpLog:
    def test_crc_rejects_corruption(self, tmp_path):
        path = str(tmp_path / "log")
        log = OpLog(path)
        log.append(OP_SET_BITS, 0, np.array([1, 2, 3], np.uint64))
        log.close()
        data = bytearray(open(path, "rb").read())
        data[10] ^= 0xFF
        open(path, "wb").write(bytes(data))
        assert list(OpLog(path).replay()) == []


class TestField:
    def make(self, tmp_path, **opts):
        h = Holder(str(tmp_path)).open()
        idx = h.create_index("i")
        return h, idx

    def test_set_field(self, tmp_path):
        h, idx = self.make(tmp_path)
        f = idx.create_field("f")
        idx.set_bit("f", 1, 10)
        idx.set_bit("f", 1, SHARD_WIDTH + 3)  # second shard
        assert f.available_shards() == [0, 1]
        assert idx.existence_field.available_shards() == [0, 1]

    def test_int_field_round_trip(self, tmp_path):
        h, idx = self.make(tmp_path)
        f = idx.create_field("amount", FieldOptions(type="int", min=-1000, max=1000))
        idx.set_value("amount", 5, -42)
        idx.set_value("amount", 9, 977)
        assert f.value(5) == (-42, True)
        assert f.value(9) == (977, True)
        assert f.value(6) == (0, False)
        # overwrite clears stale bits
        idx.set_value("amount", 5, 7)
        assert f.value(5) == (7, True)

    def test_int_field_bit_depth_growth(self, tmp_path):
        h, idx = self.make(tmp_path)
        f = idx.create_field("n", FieldOptions(type="int"))
        f.set_value(1, 3)
        d1 = f.options.bit_depth
        f.set_value(2, 1 << 20)
        assert f.options.bit_depth > d1
        assert f.value(2) == (1 << 20, True)
        assert f.value(1) == (3, True)

    def test_bounds_enforced(self, tmp_path):
        h, idx = self.make(tmp_path)
        f = idx.create_field("n", FieldOptions(type="int", min=0, max=10))
        with pytest.raises(ValueError):
            f.set_value(1, 11)

    def test_mutex_field(self, tmp_path):
        h, idx = self.make(tmp_path)
        f = idx.create_field("m", FieldOptions(type="mutex"))
        f.set_bit(1, 100)
        f.set_bit(2, 100)  # must clear row 1
        assert not f.standard_view().fragment(0).row(1).contains(100)
        assert f.standard_view().fragment(0).row(2).contains(100)

    def test_bool_field(self, tmp_path):
        h, idx = self.make(tmp_path)
        f = idx.create_field("b", FieldOptions(type="bool"))
        f.set_bit(1, 7)
        f.set_bit(0, 7)
        frag = f.standard_view().fragment(0)
        assert frag.row(0).contains(7) and not frag.row(1).contains(7)
        with pytest.raises(ValueError):
            f.set_bit(2, 7)

    def test_time_field_views(self, tmp_path):
        h, idx = self.make(tmp_path)
        f = idx.create_field("t", FieldOptions(type="time", time_quantum="YMD"))
        f.set_bit(1, 5, timestamp=datetime(2017, 1, 2))
        names = set(f.views.keys())
        assert {"standard", "standard_2017", "standard_201701",
                "standard_20170102"} <= names

    def test_decimal_field(self, tmp_path):
        h, idx = self.make(tmp_path)
        f = idx.create_field("d", FieldOptions(type="decimal", scale=2))
        f.set_value(1, 12.34)
        assert f.value(1) == (12.34, True)

    def test_timestamp_field(self, tmp_path):
        h, idx = self.make(tmp_path)
        f = idx.create_field("ts", FieldOptions(type="timestamp"))
        f.set_value(1, "2020-06-01T12:00:00")
        stored, ok = f.value(1)
        assert ok and stored == int(datetime(2020, 6, 1, 12).timestamp())


class TestHolder:
    def test_reopen_preserves_everything(self, tmp_path):
        h = Holder(str(tmp_path)).open()
        idx = h.create_index("myidx", keys=False)
        idx.create_field("f")
        idx.create_field("amount", FieldOptions(type="int", min=0, max=100))
        idx.set_bit("f", 1, 10)
        idx.set_value("amount", 10, 55)
        h.close()

        h2 = Holder(str(tmp_path)).open()
        idx2 = h2.index("myidx")
        assert idx2 is not None
        assert idx2.field("f").standard_view().fragment(0).row(1).contains(10)
        assert idx2.field("amount").value(10) == (55, True)
        assert idx2.field("amount").options.type == "int"
        assert EXISTENCE_FIELD in idx2.fields

    def test_schema_dump_apply(self, tmp_path):
        h = Holder(str(tmp_path / "a")).open()
        idx = h.create_index("i1", keys=True)
        idx.create_field("f1", FieldOptions(type="time", time_quantum="YM"))
        schema = h.schema()

        h2 = Holder(str(tmp_path / "b")).open()
        h2.apply_schema(schema)
        assert h2.index("i1").keys
        assert h2.index("i1").field("f1").options.time_quantum == "YM"

    def test_delete_index(self, tmp_path):
        h = Holder(str(tmp_path)).open()
        h.create_index("gone")
        h.delete_index("gone")
        assert h.index("gone") is None
        assert not os.path.exists(os.path.join(str(tmp_path), "gone"))

    def test_invalid_names(self, tmp_path):
        h = Holder(str(tmp_path)).open()
        for bad in ("Upper", "1num", "sp ace", ""):
            with pytest.raises(ValueError):
                h.create_index(bad)


class TestTimeQuantum:
    def test_views_by_time(self):
        t = datetime(2017, 1, 2, 3)
        assert timeq.views_by_time("standard", t, "YMDH") == [
            "standard_2017", "standard_201701", "standard_20170102",
            "standard_2017010203"]

    def test_range_cover_exact(self):
        views = timeq.views_by_time_range(
            "standard", datetime(2016, 11, 2), datetime(2017, 2, 3), "YMD")
        assert views == [
            "standard_20161102", "standard_20161103", "standard_20161104",
            "standard_20161105", "standard_20161106", "standard_20161107",
            "standard_20161108", "standard_20161109", "standard_20161110",
            "standard_20161111", "standard_20161112", "standard_20161113",
            "standard_20161114", "standard_20161115", "standard_20161116",
            "standard_20161117", "standard_20161118", "standard_20161119",
            "standard_20161120", "standard_20161121", "standard_20161122",
            "standard_20161123", "standard_20161124", "standard_20161125",
            "standard_20161126", "standard_20161127", "standard_20161128",
            "standard_20161129", "standard_20161130", "standard_201612",
            "standard_201701", "standard_20170201", "standard_20170202"]

    def test_range_cover_uses_coarse_middle(self):
        views = timeq.views_by_time_range(
            "standard", datetime(2016, 1, 1), datetime(2018, 1, 1), "YMDH")
        assert views == ["standard_2016", "standard_2017"]

    def test_invalid_quantum(self):
        with pytest.raises(ValueError):
            timeq.validate_quantum("YD")


class TestReviewRegressions:
    """Regressions for the round-1 code-review findings."""

    def test_unsorted_set_bits(self, tmp_path):
        from pilosa_tpu.store import Fragment
        f = Fragment(str(tmp_path / "0"), 0).open()
        assert f.set_bits(np.array([2, 1], np.uint64),
                          np.array([5, 6], np.uint64)) == 2
        np.testing.assert_array_equal(f.row(1).columns(), [6])
        np.testing.assert_array_equal(f.row(2).columns(), [5])
        # replay must agree with memory
        g = Fragment(str(tmp_path / "0"), 0).open()
        np.testing.assert_array_equal(g.row(1).columns(), [6])
        np.testing.assert_array_equal(g.row(2).columns(), [5])

    def test_bsi_overwrite_reports_changed(self, tmp_path):
        h = Holder(str(tmp_path)).open()
        idx = h.create_index("i")
        f = idx.create_field("n", FieldOptions(type="int", min=0, max=100))
        assert f.set_value(7, 5)
        assert f.set_value(7, 9)      # overwrite: different value → changed
        assert not f.set_value(7, 9)  # same value → unchanged
        assert f.value(7) == (9, True)

    def test_empty_store_on_empty_row_is_noop(self, tmp_path):
        from pilosa_tpu.store import Fragment
        f = Fragment(str(tmp_path / "0"), 0).open()
        assert not f.set_row(1, np.empty(0, np.uint32))
        assert f.op_n == 0

    def test_schema_preserves_timestamp_options(self, tmp_path):
        h = Holder(str(tmp_path / "a")).open()
        idx = h.create_index("i")
        idx.create_field("ts", FieldOptions(type="timestamp", time_unit="ms",
                                            epoch="2020-01-01T00:00:00"))
        h2 = Holder(str(tmp_path / "b")).open()
        h2.apply_schema(h.schema())
        o = h2.index("i").field("ts").options
        assert o.time_unit == "ms" and o.epoch == "2020-01-01T00:00:00"

    def test_mutex_bulk_import(self, tmp_path):
        h = Holder(str(tmp_path)).open()
        idx = h.create_index("i")
        f = idx.create_field("m", FieldOptions(type="mutex"))
        cols = np.arange(500, dtype=np.uint64)
        f.import_bits(np.ones(500, np.uint64), cols)          # all row 1
        f.import_bits(np.full(250, 2, np.uint64), cols[:250])  # move half
        frag = f.standard_view().fragment(0)
        assert frag.row(1).cardinality == 250
        assert frag.row(2).cardinality == 250

    def test_crash_before_first_snapshot_is_durable(self, tmp_path):
        """Regression: a fragment whose only on-disk state is the op-log
        (crash before any snapshot) must be discovered on reopen."""
        h = Holder(str(tmp_path)).open()
        idx = h.create_index("i")
        f = idx.create_field("f")
        idx.set_bit("f", 1, 10)   # 1 op; far below MAX_OP_N, no snapshot
        # no h.close() — simulate crash
        h2 = Holder(str(tmp_path)).open()
        frag = h2.index("i").field("f").standard_view().fragment(0)
        assert frag is not None and frag.row(1).contains(10)

    def test_pending_tier_semantics(self, tmp_path, rng):
        """The r5 pending tier (fragment LSM buffer) must be invisible:
        exact changed counts including duplicate probes, pending-aware
        reads, and crash replay of un-flushed pending (the op-log write
        precedes the buffer append)."""
        from pilosa_tpu.store.fragment import Fragment
        f = Fragment(str(tmp_path / "0"), 0).open()
        rows = rng.integers(0, 40, size=2000).astype(np.uint64)
        cols = rng.integers(0, SHARD_WIDTH, size=2000).astype(np.uint64)
        uniq = len({(int(r), int(c)) for r, c in zip(rows, cols)})
        assert f.set_bits(rows, cols) == uniq
        # re-setting the same bits: exact zero changed, all from probes
        assert f.set_bits(rows, cols) == 0
        assert len(f._pend_pos) > 0, "bits should still be pending"
        # pending-aware reads without flushing
        assert f.cardinality() == uniq
        ids, cards = f.row_cardinalities()
        assert int(cards.sum()) == uniq
        assert f.present
        # crash now (no close/flush): replay must rebuild everything
        g = Fragment(str(tmp_path / "0"), 0).open()
        assert g.cardinality() == uniq
        np.testing.assert_array_equal(g.positions(), f.positions())
        # reads flush; post-flush truth identical
        probe_row = int(rows[0])
        np.testing.assert_array_equal(
            g.row(probe_row).columns(), f.row(probe_row).columns())
        assert len(f._pend_pos) == 0, "row() read must flush"

    def test_reset_after_clear_with_stale_probe_cache(self, tmp_path):
        """Regression (r5 review): a duplicates-only batch leaves the
        probe cache built with EMPTY pending; a clear through the
        classic path must invalidate that cache or the following re-set
        is silently dropped as 'already present' — a lost acknowledged
        write."""
        from pilosa_tpu.store.fragment import Fragment
        f = Fragment(str(tmp_path / "0"), 0).open()
        r = np.array([3], np.uint64)
        c = np.array([77], np.uint64)
        assert f.set_bits(r, c) == 1
        assert f.set_bits(r, c) == 0   # builds probe cache, pending empty
        assert f.clear_bits(r, c) == 1  # classic path mutates merged truth
        assert f.set_bits(r, c) == 1, "re-set after clear must land"
        assert f.row(3).contains(77)
        # same for row-level ops
        assert f.set_bits(r, c) == 0
        f.clear_row(3)
        assert f.set_bits(r, c) == 1
        assert f.cardinality() == 1

    def test_pending_tier_interleaved_with_clears(self, tmp_path, rng):
        """Clears and row ops force a flush and stay exact against a
        position-set oracle under interleaving."""
        from pilosa_tpu.store.fragment import Fragment
        f = Fragment(str(tmp_path / "0"), 0).open()
        oracle: set[tuple[int, int]] = set()
        for step in range(30):
            r = int(rng.integers(0, 8))
            cs = rng.integers(0, 4096, size=50).astype(np.uint64)
            if step % 3 == 2:
                got = f.clear_bits(np.full(50, r, np.uint64), cs)
                want = len({(r, int(c)) for c in cs} & oracle)
                oracle -= {(r, int(c)) for c in cs}
            else:
                got = f.set_bits(np.full(50, r, np.uint64), cs)
                want = len({(r, int(c)) for c in cs} - oracle)
                oracle |= {(r, int(c)) for c in cs}
            assert got == want, f"step {step}"
        expect = np.array(sorted(r * SHARD_WIDTH + c for r, c in oracle),
                          np.uint64)
        np.testing.assert_array_equal(f.positions(), expect)
        # crash replay of the interleaved log
        g = Fragment(str(tmp_path / "0"), 0).open()
        np.testing.assert_array_equal(g.positions(), expect)

    def test_crash_replay_bsi_grouped(self, tmp_path):
        h = Holder(str(tmp_path)).open()
        idx = h.create_index("i", track_existence=False)
        f = idx.create_field("n", FieldOptions(type="int", min=-10, max=10))
        f.import_values(np.array([1, 2], np.uint64), [5, -3])
        h2 = Holder(str(tmp_path)).open()
        f2 = h2.index("i").field("n")
        assert f2.value(1) == (5, True)
        assert f2.value(2) == (-3, True)

    def test_recreated_index_fresh_keys(self, tmp_path):
        """Single-node: deleting an index must drop cached key logs so a
        recreated index starts from empty key state."""
        from pilosa_tpu.exec import Executor
        h = Holder(str(tmp_path)).open()
        h.create_index("k", keys=True)
        h.index("k").create_field("f", FieldOptions(keys=True))
        from pilosa_tpu.api import API
        api = API(h)
        api.query("k", 'Set("alice", f="admin")')
        api.delete_index("k")
        h.create_index("k", keys=True)
        h.index("k").create_field("f", FieldOptions(keys=True))
        log = api.executor.translate.columns("k")
        assert log.translate(["alice"], create=False) == [None]


class TestSetRowAtomicity:
    """Row replacement must be ONE op-log record (round-2 advisory: a
    crash between a CLEAR_ROW and SET_BITS pair replayed as a cleared
    row with the replacement lost)."""

    def test_set_row_is_single_oplog_record(self, tmp_path):
        path = str(tmp_path / "0")
        f = Fragment(path, 0).open()
        f.set_bits(np.array([5, 5], np.uint64), np.array([1, 2], np.uint64))
        n_before = sum(1 for _ in OpLog(path + ".oplog").replay())
        assert f.set_row(5, np.array([7, 8, 9]))
        n_after = sum(1 for _ in OpLog(path + ".oplog").replay())
        assert n_after == n_before + 1

    def test_set_row_crash_replay(self, tmp_path):
        path = str(tmp_path / "0")
        f = Fragment(path, 0).open()
        f.set_bits(np.array([5, 5], np.uint64), np.array([1, 2], np.uint64))
        assert f.set_row(5, np.array([7, 8, 9]))
        # no close/snapshot — simulate crash; replay must see the NEW row
        g = Fragment(path, 0).open()
        np.testing.assert_array_equal(g.row(5).columns(), [7, 8, 9])

    def test_set_row_to_empty_crash_replay(self, tmp_path):
        path = str(tmp_path / "0")
        f = Fragment(path, 0).open()
        f.set_bits(np.array([5], np.uint64), np.array([1], np.uint64))
        assert f.set_row(5, np.empty(0, np.uint32))
        g = Fragment(path, 0).open()
        assert not g.row(5).any()


class TestColdReopenShardDiscovery:
    def test_available_shards_after_snapshot_reopen(self, tmp_path):
        """Lazily-opened snapshot fragments (no overlay rows yet) must
        still count as available — before the fix, a cold-reopened
        multi-shard index reported no shards and the executor silently
        fell back to shard 0 only."""
        h = Holder(str(tmp_path)).open()
        idx = h.create_index("i")
        f = idx.create_field("f")
        cols = np.array([5, SHARD_WIDTH + 6, 2 * SHARD_WIDTH + 7],
                        np.uint64)
        f.import_bits(np.array([1, 1, 1], np.uint64), cols)
        for s in (0, 1, 2):
            f.view("standard").fragment(s).snapshot()
        h.close()

        h2 = Holder(str(tmp_path)).open()
        try:
            idx2 = h2.index("i")
            assert idx2.available_shards() == [0, 1, 2]
            # end-to-end: a shard-unrestricted Count must cover them all
            from pilosa_tpu.exec import Executor
            ex = Executor(h2)
            assert ex.execute("i", "Count(Row(f=1))") == [3]
        finally:
            h2.close()


class TestSyswrapMapCap:
    def test_holder_survives_more_fragments_than_map_cap(self, tmp_path):
        """syswrap parity (reference: syswrap maxMapCount): open far
        more snapshot fragments than the live-map cap; LRU fragments
        demote to heap copies, every query stays exact, and the live
        map count respects the cap."""
        from pilosa_tpu.exec import Executor
        from pilosa_tpu.store import syswrap

        n_shards, cap = 120, 10
        h = Holder(str(tmp_path)).open()
        idx = h.create_index("i")
        f = idx.create_field("f")
        cols = (np.arange(n_shards, dtype=np.uint64) * SHARD_WIDTH + 7)
        f.import_bits(np.ones(n_shards, np.uint64), cols)
        for s in range(n_shards):
            f.view("standard").fragment(s).snapshot()
        h.close()

        old_max = syswrap.GLOBAL.max_maps
        syswrap.GLOBAL.set_max(cap)
        try:
            h2 = Holder(str(tmp_path)).open()
            frags = [h2.index("i").field("f").view("standard").fragment(s)
                     for s in range(n_shards)]
            live = sum(1 for fr in frags if fr._snap_mm is not None)
            assert live <= cap, live
            assert syswrap.GLOBAL.live <= cap
            # demoted fragments answer from their heap copy
            ex = Executor(h2)
            assert ex.execute("i", "Count(Row(f=1))") == [n_shards]
            (row,) = ex.execute("i", "Row(f=1)")
            np.testing.assert_array_equal(row.columns, cols)
            h2.close()
        finally:
            syswrap.GLOBAL.set_max(old_max)

    def test_demoted_fragment_still_mutates(self, tmp_path):
        from pilosa_tpu.store import syswrap
        h = Holder(str(tmp_path)).open()
        idx = h.create_index("i")
        f = idx.create_field("f")
        f.import_bits(np.array([1], np.uint64), np.array([5], np.uint64))
        frag = f.view("standard").fragment(0)
        frag.snapshot()
        h.close()
        h2 = Holder(str(tmp_path)).open()
        frag2 = h2.index("i").field("f").view("standard").fragment(0)
        assert frag2._snap_mm is not None
        frag2._demote_map()
        assert frag2._snap_mm is None
        assert frag2.set_bit(1, 9)
        np.testing.assert_array_equal(frag2.row(1).columns(), [5, 9])
        h2.close()

    def test_demotion_races_concurrent_readers(self, tmp_path):
        """Readers holding views over the mmap while the pool demotes:
        results stay exact and nothing deadlocks (the demote uses a
        timed lock acquire; failed victims stay tracked)."""
        import threading

        from pilosa_tpu.store import syswrap

        n_frags, cap = 24, 4
        h = Holder(str(tmp_path)).open()
        idx = h.create_index("i")
        f = idx.create_field("f")
        cols = (np.arange(n_frags, dtype=np.uint64) * SHARD_WIDTH + 3)
        f.import_bits(np.ones(n_frags, np.uint64), cols)
        for s in range(n_frags):
            f.view("standard").fragment(s).snapshot()
        h.close()

        old_max = syswrap.GLOBAL.max_maps
        syswrap.GLOBAL.set_max(cap)
        try:
            h2 = Holder(str(tmp_path)).open()
            frags = [h2.index("i").field("f").view("standard").fragment(s)
                     for s in range(n_frags)]
            errors = []

            def reader():
                out = np.zeros((1, 32768), np.uint32)
                for _ in range(50):
                    for fr in frags:
                        out[:] = 0
                        fr.plane_rows([1], out, slots=[0])
                        if int(np.bitwise_count(out).sum()) != 1:
                            errors.append("bad bits")
                            return

            def demoter():
                for _ in range(100):
                    for fr in frags:
                        fr._demote_map()

            threads = ([threading.Thread(target=reader) for _ in range(3)]
                       + [threading.Thread(target=demoter)])
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=60)
                assert not t.is_alive(), "deadlock"
            assert not errors, errors
            h2.close()
        finally:
            syswrap.GLOBAL.set_max(old_max)
