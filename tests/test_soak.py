"""Soak: sustained serving + ingest + periodic AAE with resource-growth
assertions (VERDICT r4 #8 — "the weakref map pool, mutation journals,
generation caches, and sqlite handles have never run long enough to
prove they don't leak").

Gated behind ``PILOSA_SOAK=1`` (10+ minutes of wall time; the driver's
suite run must stay fast).  Run manually:

    PILOSA_SOAK=1 PILOSA_SOAK_SECONDS=600 \
        python -m pytest tests/test_soak.py -q -s

Asserts, across the whole run on a 2-node replicated cluster under
4 query clients + 1 continuous importer + 2s-interval anti-entropy:

  - host RSS growth after warmup stays under 30%
  - open fds and memory maps stay bounded (syswrap MapPool cap)
  - throughput in the last quarter >= 60% of the first quarter
    (no qps decay from accumulating state)
  - exact count oracle holds at quiescent checkpoints
"""

from __future__ import annotations

import os
import threading
import time

import numpy as np
import pytest

pytestmark = pytest.mark.skipif(
    not os.environ.get("PILOSA_SOAK"),
    reason="soak is opt-in: PILOSA_SOAK=1 (runs 10+ minutes)")

SECONDS = int(os.environ.get("PILOSA_SOAK_SECONDS", "600"))
N_SHARDS = 32
N_ROWS = 16


from pilosa_tpu.testing import rss_mb  # noqa: E402


def fd_count() -> int:
    return len(os.listdir("/proc/self/fd"))


def map_count() -> int:
    with open("/proc/self/maps") as f:
        return sum(1 for _ in f)


def test_soak_serving_ingest_aae(tmp_path):
    from pilosa_tpu.engine.words import SHARD_WIDTH
    from pilosa_tpu.testing import run_cluster

    rng = np.random.default_rng(8)
    with run_cluster(2, str(tmp_path), replicas=2,
                     anti_entropy=2.0) as tc:
        c = tc.client(0)
        c.create_index("i")
        c.create_field("i", "f")
        c.create_field("i", "amount",
                       {"type": "int", "min": 0, "max": 10 ** 6})

        # seed: bits spread over all shards
        seed_rows = rng.integers(0, N_ROWS, 200_000).astype(np.uint64)
        seed_cols = rng.integers(0, N_SHARDS * SHARD_WIDTH,
                                 200_000).astype(np.uint64)
        key = np.unique((seed_rows << np.uint64(40)) | seed_cols)
        seed_rows = (key >> np.uint64(40)).astype(np.uint64)
        seed_cols = (key & np.uint64((1 << 40) - 1))
        c.import_bits("i", "f", rowIDs=seed_rows.tolist(),
                      columnIDs=seed_cols.tolist())
        total_bits = [len(key)]

        stop = threading.Event()
        errors: list = []
        qdone = []  # (t, count) per completed query
        pql = ("Count(Row(f=0))Count(Row(f=1))TopN(f, n=4)"
               "Sum(field=amount)GroupBy(Rows(f, limit=4))")

        def reader():
            try:
                while not stop.is_set():
                    c.query("i", pql)
                    qdone.append(time.monotonic())
            except Exception as e:  # noqa: BLE001
                errors.append(("reader", repr(e)))

        # importer: deterministic fresh columns per batch, so the
        # oracle is exact at quiescent checkpoints
        def writer():
            try:
                cursor = 0
                wrng = np.random.default_rng(9)
                while not stop.is_set():
                    rows = wrng.integers(0, N_ROWS, 2000)
                    cols = (np.arange(2000) * N_SHARDS + cursor) \
                        % (N_SHARDS * SHARD_WIDTH)
                    cursor += 7919  # prime stride; collisions possible
                    changed = c.import_bits(
                        "i", "f", rowIDs=rows.tolist(),
                        columnIDs=cols.tolist())
                    total_bits[0] += changed
                    vals = wrng.integers(0, 10 ** 6, 500)
                    c._json("POST", "/index/i/field/amount/importValue",
                            {"columnIDs": cols[:500].tolist(),
                             "values": vals.tolist()})
                    time.sleep(0.05)
            except Exception as e:  # noqa: BLE001
                errors.append(("writer", repr(e)))

        threads = [threading.Thread(target=reader) for _ in range(4)]
        threads.append(threading.Thread(target=writer))
        for t in threads:
            t.start()

        warmup = min(60.0, SECONDS / 5)
        time.sleep(warmup)
        base_rss, base_fd, base_maps = rss_mb(), fd_count(), map_count()
        samples = []
        t_start = time.monotonic()
        while time.monotonic() - t_start < SECONDS - warmup:
            time.sleep(10)
            samples.append((time.monotonic() - t_start, rss_mb(),
                            fd_count(), map_count(), len(qdone)))
            s = samples[-1]
            print(f"t+{s[0]:.0f}s rss={s[1]:.0f}MB fd={s[2]} "
                  f"maps={s[3]} queries={s[4]}", flush=True)
            assert not errors, errors[:3]

        stop.set()
        for t in threads:
            t.join(timeout=60)
        assert not errors, errors[:3]

        # -- resource growth --------------------------------------------
        final_rss, final_fd, final_maps = rss_mb(), fd_count(), map_count()
        print(f"rss {base_rss:.0f} -> {final_rss:.0f} MB, "
              f"fd {base_fd} -> {final_fd}, maps {base_maps} -> "
              f"{final_maps}, queries {len(qdone)}, "
              f"bits {total_bits[0]}", flush=True)
        assert final_rss < base_rss * 1.3 + 200, \
            f"RSS grew {base_rss:.0f} -> {final_rss:.0f} MB"
        assert final_fd < base_fd + 64, f"fds {base_fd} -> {final_fd}"
        assert final_maps < base_maps + 512, \
            f"maps {base_maps} -> {final_maps}"

        # -- qps decay --------------------------------------------------
        times = np.array(qdone) - (t_start - warmup)
        horizon = float(times.max())
        q1 = int(((times > warmup) & (times < warmup
                                      + (horizon - warmup) / 4)).sum())
        q4 = int((times > horizon - (horizon - warmup) / 4).sum())
        print(f"first-quarter queries {q1}, last-quarter {q4}", flush=True)
        assert q4 >= 0.6 * q1, f"throughput decayed: {q1} -> {q4}"

        # -- quiescent exact oracle ------------------------------------
        time.sleep(3.0)  # let AAE + compaction settle
        (n,) = c.query("i", "Count(Union(" + ", ".join(
            f"Row(f={r})" for r in range(N_ROWS)) + "))")
        # total_bits counts (row, col) pairs; union counts distinct
        # cols — compare pair total via per-row counts instead
        per_row = c.query("i", "".join(
            f"Count(Row(f={r}))" for r in range(N_ROWS)))
        assert sum(per_row) == total_bits[0], \
            f"pair total {sum(per_row)} != oracle {total_bits[0]}"
        assert n <= sum(per_row)
