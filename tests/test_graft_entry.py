"""The driver's multichip gate: ``dryrun_multichip`` must self-provision.

Round 1's gate failed (MULTICHIP_r01.json ok:false) because the entrypoint
assumed the caller supplied >=8 devices and bound the TPU-tunnel backend.
This test reproduces the driver's invocation — a fresh interpreter with NO
cpu-forcing env — and fails if the self-provisioning regresses.
(SURVEY.md §5 simulated-mesh lesson.)
"""

import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_dryrun_multichip_self_provisions():
    # Scrub the cpu-forcing vars conftest set for THIS process so the
    # child sees what the driver's child would see.
    env = {k: v for k, v in os.environ.items()
           if k not in ("JAX_PLATFORMS", "XLA_FLAGS")}
    proc = subprocess.run(
        [sys.executable, "-c",
         "import __graft_entry__ as g; g.dryrun_multichip(8)"],
        env=env, cwd=REPO, capture_output=True, text=True, timeout=600)
    assert proc.returncode == 0, proc.stderr[-3000:]
    assert "dryrun_multichip(8): OK" in proc.stdout, proc.stdout
